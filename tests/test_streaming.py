"""Streaming execution mode (docs/streaming.md): spool codec, the
generalized ``(attempt_epoch, window_id)`` fence at every seam, the
StreamDriver lifecycle (cuts, exactly-once commits, backpressure, drain),
AM-crash resume with window-exact replay, and the window-commit ledger
rules journal_fsck enforces."""
import os
import time

import pytest

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.recovery import encode_journal_line
from tez_tpu.am.streaming import (StreamSpec, StreamSpoolError,
                                  decode_spool_record, encode_spool_record,
                                  read_spool)
from tez_tpu.common import config as C
from tez_tpu.common import epoch as epoch_registry
from tez_tpu.common.epoch import EpochFencedError, WindowFencedError
from tez_tpu.common.ids import DAGId, TaskAttemptId
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.tools import journal_fsck

APP = "app_1_stream"


def _template(name, parallelism=2):
    source = Vertex.create("source", ProcessorDescriptor.create(
        "tez_tpu.library.streaming:StreamWindowSourceProcessor"),
        parallelism)
    sink = Vertex.create("sink", ProcessorDescriptor.create(
        "tez_tpu.library.streaming:StreamWindowSinkProcessor"), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf))
    dag = DAG.create(name).add_vertex(source).add_vertex(sink)
    dag.add_edge(Edge.create(source, sink, prop))
    return dag.create_dag_plan()


def _conf(tmp_staging, **extra):
    base = {"tez.staging-dir": tmp_staging,
            "tez.am.local.num-containers": 4,
            "tez.am.session.max-concurrent-dags": 2,
            "tez.am.session.queue-size": 8}
    base.update(extra)
    return C.TezConfiguration(base)


def _spec(name, out_root, **conf):
    return StreamSpec(name=name, plan=_template(f"{name}-template"),
                      output_dir=os.path.join(out_root, name), conf=conf)


def _records(n, keys=3):
    return [{"k": f"key{i % keys}", "v": i + 1} for i in range(n)]


def _expected_windows(records, window_count):
    wins, cur = [], []
    for r in records:
        cur.append(r)
        if len(cur) >= window_count:
            wins.append(cur)
            cur = []
    if cur:
        wins.append(cur)
    return wins


def _expected_part(recs):
    totals = {}
    for r in recs:
        if isinstance(r, dict) and "k" in r:
            totals[r["k"]] = totals.get(r["k"], 0) + r.get("v", 1)
    return "\n".join(f"{k} {v}" for k, v in sorted(totals.items())) + "\n"


def _assert_windows(out_dir, records, window_count):
    wins = _expected_windows(records, window_count)
    for i, recs in enumerate(wins, start=1):
        path = os.path.join(out_dir, f"w{i:06d}.part0")
        assert os.path.exists(path), f"window {i} never published"
        with open(path) as fh:
            assert fh.read() == _expected_part(recs), f"window {i} diverged"
    extra = [n for n in os.listdir(out_dir)
             if not n.startswith(".") and
             int(n[1:7]) > len(wins)]
    assert not extra, f"unexpected windows published: {extra}"


def _wait_commits(am, n, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(am.logging_service.of_type(
                HistoryEventType.WINDOW_COMMIT_FINISHED)) >= n:
            return
        time.sleep(0.02)
    pytest.fail(f"{n} WINDOW_COMMIT_FINISHED never journaled")


# ----------------------------------------------------------- spool codec

def test_spool_codec_roundtrip_and_torn_tail(tmp_path):
    records = _records(5) + ["EOW"]
    path = str(tmp_path / "w000001.spool")
    with open(path, "w") as fh:
        for r in records:
            fh.write(encode_spool_record(r) + "\n")
    assert read_spool(path) == records
    # a torn FINAL line is the crash signature: dropped, not replayed
    with open(path, "a") as fh:
        fh.write(encode_spool_record({"k": "torn"})[:-4])
    assert read_spool(path) == records
    # the same damage mid-file is at-rest corruption: loud failure
    lines = [encode_spool_record(r) for r in records]
    lines[1] = lines[1][:8] + lines[1][8:].replace("key", "KEY")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    with pytest.raises(StreamSpoolError):
        read_spool(path)
    with pytest.raises(StreamSpoolError):
        decode_spool_record("nonsense")
    assert read_spool(str(tmp_path / "missing.spool")) == []


# ------------------------------------------------- the generalized fence

def test_window_registry_semantics():
    assert not epoch_registry.is_stale_window(APP, "s", 0)   # batch
    assert not epoch_registry.is_stale_window(APP, "", 5)    # no stream
    assert not epoch_registry.is_stale_window(APP, "s", 3)   # never reg'd
    assert epoch_registry.register_window(APP, "s", 3) == 3
    assert epoch_registry.is_stale_window(APP, "s", 2)
    assert not epoch_registry.is_stale_window(APP, "s", 3)
    assert not epoch_registry.is_stale_window(APP, "s", 4)
    # a replayed older window cannot roll the fence back
    assert epoch_registry.register_window(APP, "s", 2) == 3
    assert epoch_registry.current_window(APP, "s") == 3
    # the window coordinate is scoped per (app, stream)
    assert not epoch_registry.is_stale_window(APP, "other", 1)
    assert issubclass(WindowFencedError, EpochFencedError)


def test_umbilical_fences_stale_window(tmp_staging):
    """Heartbeat + commit arbitration: a straggler stamped with a sealed
    window is told to die / refused the commit, with the same typed
    ATTEMPT_FENCED journal record the epoch fence leaves."""
    from tez_tpu.am.task_comm import HeartbeatRequest
    am = DAGAppMaster(APP + "hb", _conf(tmp_staging), attempt=1)
    am.start()
    try:
        epoch_registry.register_window(APP + "hb", "s", 2)
        zombie = TaskAttemptId(DAGId(APP + "hb", 1).vertex(0).task(0), 0)
        resp = am.task_comm.heartbeat(HeartbeatRequest(
            attempt_id=zombie, events=[], epoch=1, window_id=1, stream="s"))
        assert resp.should_die
        assert not am.task_comm.can_commit(zombie, epoch=1, window_id=1,
                                           stream="s")
        fenced = am.logging_service.of_type(HistoryEventType.ATTEMPT_FENCED)
        assert fenced and fenced[0].data.get("reason") == "stale_window"
        # batch stamp (window 0) sails through the window fence
        resp = am.task_comm.heartbeat(HeartbeatRequest(
            attempt_id=zombie, events=[], epoch=1))
        assert not resp.should_die
    finally:
        am.stop()


def test_shuffle_and_store_seams_fence_stale_window():
    from tez_tpu.shuffle.service import ShuffleService
    from tez_tpu.store.buffer_store import ShuffleBufferStore
    epoch_registry.register_window(APP, "s", 5)
    svc = ShuffleService()
    with pytest.raises(WindowFencedError):
        svc.register("p", 0, run=None, app_id=APP, window_id=4, stream="s")
    with pytest.raises(WindowFencedError):
        svc.push_publish("p", 0, run=None, app_id=APP, window_id=4,
                         stream="s")
    with pytest.raises(WindowFencedError):
        svc.fetch_partition("p", 0, 0, app_id=APP, window_id=4, stream="s")
    store = ShuffleBufferStore()
    with pytest.raises(WindowFencedError):
        store.publish("p", 0, run=None, app_id=APP, window_id=4, stream="s")
    with pytest.raises(WindowFencedError):
        store.republish_lineage("lin", "p2", app_id=APP, window_id=4,
                                stream="s")
    # batch (window 0) is never window-fenced at any of these seams
    svc.register("batchp", 0, run=object(), app_id=APP, window_id=0,
                 stream="s")


# --------------------------------------------------- driver lifecycle e2e

def test_stream_driver_end_to_end(tmp_staging, tmp_path):
    records = _records(7)           # count=3: w1, w2 full + w3 cut by drain
    am = DAGAppMaster(APP + "e2e", _conf(tmp_staging), attempt=1)
    am.start()
    try:
        driver = am.open_stream(_spec(
            "clicks", str(tmp_path), **{
                "tez.runtime.stream.window.count": 3}))
        assert driver.ingest(records) == 3      # open window after 2 cuts
        final = driver.drain(timeout=60)
        assert final["committed"] == [1, 2, 3]
        assert final["retired"] and final["lag"] == 0
        assert final["aborted"] == [] and final["replayed"] == []
        _assert_windows(str(tmp_path / "clicks"), records, 3)
        # the ledger bracket is paired per window, in order
        started = am.logging_service.of_type(
            HistoryEventType.WINDOW_COMMIT_STARTED)
        finished = am.logging_service.of_type(
            HistoryEventType.WINDOW_COMMIT_FINISHED)
        assert [e.data["window_id"] for e in started] == [1, 2, 3]
        assert [e.data["window_id"] for e in finished] == [1, 2, 3]
        assert all(not e.data["replayed"] for e in finished)
        with pytest.raises(Exception):          # retired: ingest refused
            driver.ingest([{"k": "late"}])
    finally:
        am.stop()
    # the journal these runs leave satisfies fsck's window rules
    files = journal_fsck.discover_journals(
        os.path.join(tmp_staging, APP + "e2e", "recovery"))
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    assert report.streams["clicks"].inferred.startswith("RETIRED")
    assert report.streams["clicks"].committed == [1, 2, 3]


def test_punctuation_cuts_windows_early(tmp_staging, tmp_path):
    records = [{"k": "a", "v": 1}, {"k": "b", "v": 2}, "EOW",
               {"k": "a", "v": 10}, "EOW"]
    am = DAGAppMaster(APP + "punc", _conf(tmp_staging), attempt=1)
    am.start()
    try:
        driver = am.open_stream(_spec(
            "punct", str(tmp_path), **{
                "tez.runtime.stream.window.count": 100,
                "tez.runtime.stream.window.punctuation": "EOW"}))
        driver.ingest(records)
        final = driver.drain(timeout=60)
        assert final["committed"] == [1, 2]
    finally:
        am.stop()
    out = str(tmp_path / "punct")
    with open(os.path.join(out, "w000001.part0")) as fh:
        assert fh.read() == "a 1\nb 2\n"
    with open(os.path.join(out, "w000002.part0")) as fh:
        assert fh.read() == "a 10\n"


def test_backpressure_paces_source_and_journals_lagging(tmp_staging,
                                                        tmp_path):
    """max-lag=1 with 1-record windows: every second ingest must block
    until the previous window commits — bounded lag, nothing dropped, ONE
    typed WINDOW_LAGGING event per lag episode."""
    records = _records(4)
    am = DAGAppMaster(APP + "lag", _conf(tmp_staging), attempt=1)
    am.start()
    try:
        driver = am.open_stream(_spec(
            "paced", str(tmp_path), **{
                "tez.runtime.stream.window.count": 1,
                "tez.runtime.stream.max-lag": 1}))
        driver.ingest(records)
        final = driver.drain(timeout=120)
        assert final["committed"] == [1, 2, 3, 4]    # nothing dropped
        assert final["lag_episodes"] >= 1
        lagging = am.logging_service.of_type(HistoryEventType.WINDOW_LAGGING)
        assert len(lagging) == final["lag_episodes"]
        assert lagging[0].data["max_lag"] == 1
        assert lagging[0].data["stream"] == "paced"
    finally:
        am.stop()
    _assert_windows(str(tmp_path / "paced"), records, 1)


def test_publish_window_is_idempotent(tmp_staging, tmp_path):
    """The commit bracket's renames roll forward: a tmp whose final name
    already exists is dropped, never double-published — replaying an open
    bracket after a crash cannot corrupt a committed window."""
    class _NoAM:
        app_id = APP + "pub"

        def __init__(self, conf):
            self.conf = conf

    am = _NoAM(_conf(tmp_staging))
    from tez_tpu.am.streaming import StreamDriver
    driver = StreamDriver(am, _spec("pub", str(tmp_path)))   # never started
    out = str(tmp_path / "pub")
    with open(os.path.join(out, ".w000001.part0.tmp"), "w") as fh:
        fh.write("a 1\n")
    assert driver._publish_window(1) == 1
    with open(os.path.join(out, "w000001.part0")) as fh:
        assert fh.read() == "a 1\n"
    # a zombie re-creates the tmp with different bytes: the committed
    # final must win, the tmp must be swallowed
    with open(os.path.join(out, ".w000001.part0.tmp"), "w") as fh:
        fh.write("ZOMBIE\n")
    driver._publish_window(1)
    with open(os.path.join(out, "w000001.part0")) as fh:
        assert fh.read() == "a 1\n"
    assert not os.path.exists(os.path.join(out, ".w000001.part0.tmp"))


# ------------------------------------------------------ AM crash resume

def test_am_crash_mid_stream_resumes_window_exact(tmp_staging, tmp_path):
    """Crash with committed + sealed-uncommitted + open windows on disk:
    the successor must keep committed windows sealed, window-exact replay
    the uncommitted sealed one, and resume the open spool with its
    ingested records intact — exactly one WINDOW_COMMIT_FINISHED per
    window across both incarnations."""
    records = _records(9)
    conf = _conf(tmp_staging)
    app_id = APP + "crash"
    am1 = DAGAppMaster(app_id, conf, attempt=1)
    am1.start()
    am1.open_stream(_spec("surv", str(tmp_path), **{
        "tez.runtime.stream.window.count": 3}))
    am1.streams["surv"].ingest(records[:7])    # w1+w2 sealed, 1 open rec
    _wait_commits(am1, 1)
    am1.crash()
    epoch_registry.reset()

    am2 = DAGAppMaster(app_id, conf, attempt=2)
    am2.start()
    try:
        am2.recover_and_resume()
        assert "surv" in am2.streams, "stream not resumed from the ledger"
        driver = am2.streams["surv"]
        driver.ingest(records[7:])             # open window fills to 3
        final = driver.drain(timeout=60)
        assert final["committed"] == [1, 2, 3]
        # whatever was uncommitted-but-sealed at the crash replayed
        assert set(final["replayed"]) <= {1, 2}
    finally:
        am2.stop()
    _assert_windows(str(tmp_path / "surv"), records, 3)

    rec_dir = os.path.join(tmp_staging, app_id, "recovery")
    files = journal_fsck.discover_journals(rec_dir)
    assert len(files) == 2
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    assert report.streams["surv"].inferred.startswith("RETIRED")
    # exactly-once across incarnations: no window committed twice
    commits = {}
    for f in files:
        from tez_tpu.am.recovery import RecoveryParser, decode_journal_line
        with open(f, errors="replace") as fh:
            for line in fh:
                if not line.strip():
                    continue
                try:
                    ev = decode_journal_line(line.strip())
                except Exception:
                    continue
                if ev.event_type is HistoryEventType.WINDOW_COMMIT_FINISHED:
                    w = ev.data["window_id"]
                    commits[w] = commits.get(w, 0) + 1
    assert commits == {1: 1, 2: 1, 3: 1}


# --------------------------------------------------- fsck window rules

def _sev(event_type, stream="s", w=None, **extra):
    data = {"stream": stream}
    if w is not None:
        data["window_id"] = w
    data.update(extra)
    return HistoryEvent(event_type, data=data)


def _write(path, events):
    os.makedirs(os.path.dirname(str(path)), exist_ok=True)
    with open(str(path), "w") as fh:
        for ev in events:
            fh.write(encode_journal_line(ev) + "\n")
    return str(path)


OPENED = HistoryEventType.STREAM_OPENED
RETIRED = HistoryEventType.STREAM_RETIRED
W_START = HistoryEventType.WINDOW_COMMIT_STARTED
W_FIN = HistoryEventType.WINDOW_COMMIT_FINISHED
W_ABORT = HistoryEventType.WINDOW_COMMIT_ABORTED


def test_fsck_window_ledger_clean_and_violations(tmp_path):
    # clean: open, two paired brackets in order, retire
    p = _write(tmp_path / "ok.jsonl", [
        _sev(OPENED), _sev(W_START, w=1), _sev(W_FIN, w=1),
        _sev(W_START, w=2), _sev(W_FIN, w=2), _sev(RETIRED)])
    report = journal_fsck.fsck_files([p])
    assert report.ok, report.errors
    assert report.streams["s"].inferred.startswith("RETIRED")

    # duplicate FINISHED: the exactly-once violation
    p = _write(tmp_path / "dup.jsonl", [
        _sev(OPENED), _sev(W_START, w=1), _sev(W_FIN, w=1),
        _sev(W_FIN, w=1)])
    assert not journal_fsck.fsck_files([p]).ok

    # committed window ids must be strictly increasing
    p = _write(tmp_path / "order.jsonl", [
        _sev(OPENED), _sev(W_START, w=2), _sev(W_FIN, w=2),
        _sev(W_START, w=1), _sev(W_FIN, w=1)])
    assert not journal_fsck.fsck_files([p]).ok

    # FINISHED without a matching open STARTED
    p = _write(tmp_path / "orphan.jsonl", [_sev(OPENED), _sev(W_FIN, w=1)])
    assert not journal_fsck.fsck_files([p]).ok

    # nothing may follow STREAM_RETIRED
    p = _write(tmp_path / "late.jsonl", [
        _sev(OPENED), _sev(RETIRED), _sev(W_START, w=1)])
    assert not journal_fsck.fsck_files([p]).ok

    # events on a never-opened stream
    p = _write(tmp_path / "noopen.jsonl", [_sev(W_START, w=1)])
    assert not journal_fsck.fsck_files([p]).ok


def test_fsck_window_crash_rollforward_is_warning_not_error(tmp_path):
    """Attempt 1 dies inside w2's bracket; attempt 2 re-opens the SAME
    window's bracket (roll-forward) and finishes it — a warning-level
    crash signature, never an exactly-once error."""
    rec = tmp_path / "recovery"
    _write(rec / "1" / "journal.jsonl", [
        _sev(OPENED), _sev(W_START, w=1), _sev(W_FIN, w=1),
        _sev(W_START, w=2)])
    _write(rec / "2" / "journal.jsonl", [
        _sev(W_START, w=2), _sev(W_FIN, w=2), _sev(RETIRED)])
    files = journal_fsck.discover_journals(str(rec))
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    assert any("re-opened" in w for w in report.warnings)
    assert report.streams["s"].committed == [1, 2]
    # but a DIFFERENT window barging into an open bracket is an error
    p = _write(tmp_path / "barge.jsonl", [
        _sev(OPENED), _sev(W_START, w=1), _sev(W_START, w=2)])
    assert not journal_fsck.fsck_files([p]).ok
    # and a live stream's trailing open bracket is the recovery case
    p = _write(tmp_path / "open.jsonl", [_sev(OPENED), _sev(W_START, w=1)])
    report = journal_fsck.fsck_files([p])
    assert report.ok
    assert "IN-COMMIT" in report.streams["s"].inferred


# -------------------------------------------- observability integration

def test_stream_events_reach_parser_and_tools(tmp_staging, tmp_path):
    records = _records(4)
    am = DAGAppMaster(APP + "obs", _conf(tmp_staging), attempt=1)
    am.start()
    try:
        driver = am.open_stream(_spec(
            "obs", str(tmp_path), **{
                "tez.runtime.stream.window.count": 2}))
        driver.ingest(records)
        driver.drain(timeout=60)
    finally:
        am.stop()
    from tez_tpu.tools.counter_diff import stream_summary
    from tez_tpu.tools.doctor import diagnose_streams
    from tez_tpu.tools.history_parser import parse_jsonl_files
    journal = os.path.join(tmp_staging, APP + "obs", "recovery", "1",
                           "journal.jsonl")
    dags = parse_jsonl_files([journal])
    assert dags, "window DAGs missing from the history parse"
    evs = next(iter(dags.values())).stream_events
    kinds = [e["event"] for e in evs]
    assert kinds.count("COMMIT_FINISHED") == 2
    assert kinds[0] == "OPENED" and kinds[-1] == "RETIRED"
    summary = stream_summary(dags)
    assert summary["committed"] == 2 and summary["replayed"] == 0
    assert summary["p95_ms"] >= summary["p50_ms"] > 0
    rows = diagnose_streams(dags)
    assert rows and rows[0]["stream"] == "obs"
    assert rows[0]["committed"] == 2 and rows[0]["retired"]
    assert rows[0]["slowest"]["dominant_plane"] != ""
