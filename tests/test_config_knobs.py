"""Round-5 config-surface wiring: every new reference key must CHANGE real
behavior (reference: TezConfiguration.java / TezRuntimeConfiguration.java
constants; keys are padding unless a component reads them).
"""
from __future__ import annotations

import time

import numpy as np
import pytest

from tez_tpu.common import config as C
from tez_tpu.common.ids import DAGId


# --------------------------------------------------------------- speculation
class _FakeAttempt:
    def __init__(self, state, n_live=1, launch_time=0.0):
        from tez_tpu.am.task_impl import TaskAttemptState
        self.state = TaskAttemptState.RUNNING if state == "RUNNING" else state
        self._n_live = n_live
        self.launch_time = launch_time
        self.attempt_id = "att"
        self.progress = 0.1


class _FakeTask:
    def __init__(self, running=True, n_live=1, launch_time=0.0):
        from tez_tpu.am.task_impl import TaskState
        self.state = TaskState.RUNNING if running else TaskState.SUCCEEDED
        self._atts = [_FakeAttempt("RUNNING", launch_time=launch_time)
                      for _ in range(n_live)]
        self.task_id = "task"

    def live_attempts(self):
        return self._atts

    def successful_attempt_impl(self):
        return None


class _FakeVertex:
    def __init__(self, tasks):
        self.name = "v"
        self.tasks = {i: t for i, t in enumerate(tasks)}


class _FakeDag:
    def __init__(self, conf, vertices):
        self.conf = conf
        self.vertices = {f"v{i}": v for i, v in enumerate(vertices)}
        self.dag_id = "dag_1"
        self.state = "RUNNING"
        self.ctx = self

    dispatched: list = []

    def dispatch(self, ev):
        self.dispatched.append(ev)


def test_speculation_budget_caps_concurrent_speculations():
    from tez_tpu.am.speculation import Speculator
    conf = C.TezConfiguration({
        "tez.am.minimum.allowed.speculative.tasks": 2,
        "tez.am.proportion.total.tasks.speculatable": 0.01,
        "tez.am.proportion.running.tasks.speculatable": 0.1,
    })
    # 10 running tasks, 2 already speculating (2 live attempts)
    tasks = [_FakeTask(n_live=2), _FakeTask(n_live=2)] + \
        [_FakeTask() for _ in range(8)]
    dag = _FakeDag(conf, [_FakeVertex(tasks)])
    spec = Speculator(dag)
    # cap = max(2, 0.01*10=0, 0.1*10=1) = 2; 2 in flight -> budget 0
    assert spec._speculation_budget() == 0
    conf.set("tez.am.minimum.allowed.speculative.tasks", 5)
    spec2 = Speculator(dag)
    assert spec2._speculation_budget() == 3


def test_speculation_pacing_keys_read():
    from tez_tpu.am.speculation import Speculator
    conf = C.TezConfiguration({
        "tez.am.soonest.retry.after.no.speculate": 2000,
        "tez.am.soonest.retry.after.speculate": 30_000,
        "tez.am.legacy.speculative.single.task.vertex.timeout": 1500,
    })
    spec = Speculator(_FakeDag(conf, []))
    assert spec.retry_no_spec == 2.0
    assert spec.retry_spec == 30.0
    assert spec.single_task_timeout == 1.5
    # default: single-task vertices never speculate
    spec2 = Speculator(_FakeDag(C.TezConfiguration({}), []))
    assert spec2.single_task_timeout is None


def test_single_task_vertex_speculates_after_timeout():
    from tez_tpu.am.speculation import Speculator
    conf = C.TezConfiguration({
        "tez.am.legacy.speculative.single.task.vertex.timeout": 100})
    task = _FakeTask(launch_time=time.time() - 5.0)
    dag = _FakeDag(conf, [_FakeVertex([task])])
    dag.dispatched = []
    spec = Speculator(dag)
    assert spec._maybe_speculate_single_task(
        dag.vertices["v0"], time.time()) == 1
    assert len(dag.dispatched) == 1


# ------------------------------------------------------------------ counters
def test_counter_name_length_limits_configurable():
    from tez_tpu.common.counters import CounterGroup, Limits
    try:
        Limits.configure(C.TezConfiguration(
            {"tez.counters.counter-name.max-length": 8}))
        g = CounterGroup("g")
        c = g.find_counter("abcdefghijklmnop")
        assert c.name == "abcdefgh"
        # truncation collapses consistently to one counter
        assert g.find_counter("abcdefghZZZ") is c
    finally:
        Limits.configure(C.TezConfiguration({}))
        assert Limits.MAX_COUNTER_NAME_LEN == 64


# ------------------------------------------------------------- event backlog
class _PassThroughManager:
    """Minimal on-demand edge manager: event routes to every dest."""

    def route_data_movement_event_to_destination(self, src_task, src_idx,
                                                 dest_task):
        class _M:
            target_indices = [0]
        return _M()


def test_edge_event_pull_respects_max_events():
    from tez_tpu.am.edge import EdgeImpl
    from tez_tpu.api.events import DataMovementEvent
    edge = EdgeImpl.__new__(EdgeImpl)
    import threading
    edge._lock = threading.Lock()
    edge._events = [(i, 0, DataMovementEvent(source_index=0,
                                             user_payload=None,
                                             target_index=0))
                    for i in range(10)]
    edge.edge_manager = _PassThroughManager()
    out, seq = edge.get_events_for_task(0, 0, max_events=4)
    assert len(out) == 4 and seq == 4
    out2, seq2 = edge.get_events_for_task(0, seq, max_events=4)
    assert len(out2) == 4 and seq2 == 8
    out3, seq3 = edge.get_events_for_task(0, seq2)   # no cap: drain
    assert len(out3) == 2 and seq3 == 10


# ------------------------------------------------------------ memory scaling
def test_memory_reserve_fraction_and_uniform_allocator():
    from tez_tpu.runtime.memory import MemoryDistributor, parse_weight_ratios
    grants = {}
    md = MemoryDistributor(1000, reserve_fraction=0.5)
    md.request_memory(800, lambda g: grants.__setitem__("a", g), "a")
    md.make_initial_allocations()
    assert grants["a"] <= 500          # half the budget held back
    # weighted vs uniform: sorted output outweighs unsorted 3:1 by default
    def run(weighted):
        got = {}
        md = MemoryDistributor(600, reserve_fraction=0.0, weighted=weighted)
        md.request_memory(600, lambda g: got.__setitem__("s", g), "s",
                          component_type="PARTITIONED_SORTED_OUTPUT")
        md.request_memory(600, lambda g: got.__setitem__("u", g), "u",
                          component_type="PARTITIONED_UNSORTED_OUTPUT")
        md.make_initial_allocations()
        return got
    w = run(True)
    assert w["s"] > w["u"] * 2
    u = run(False)
    assert abs(u["s"] - u["u"]) <= 1   # uniform scaling
    # ratios spec parsing
    assert parse_weight_ratios("")[
        "PROCESSOR"] if False else True
    r = parse_weight_ratios("PROCESSOR=7,CUSTOM=2")
    assert r["PROCESSOR"] == 7 and r["CUSTOM"] == 2
    assert parse_weight_ratios("garbage") is None


# -------------------------------------------------------- preemption pacing
class _SchedCtx:
    def __init__(self, conf):
        self.conf = conf
        self.dispatched = []

    def ensure_runners(self, backlog):
        pass

    def dispatch(self, event):
        self.dispatched.append(event)


def _kills(ctx):
    return [e for e in ctx.dispatched
            if getattr(e, "event_type", None) is not None
            and e.event_type.name == "TA_KILL_REQUEST"]


def test_preemption_rounds_are_paced():
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    ctx = _SchedCtx(C.TezConfiguration({
        "tez.am.preemption.percentage": 50,   # limit = 1 victim per round
        "tez.am.preemption.heartbeats-between-preemptions": 40,  # 10 s
    }))
    sched = LocalTaskSchedulerService(ctx, num_slots=2)
    vid = DAGId("app_1_p", 1).vertex(0)
    sched.schedule(vid.task(0).attempt(0), "a", priority=20)
    sched.schedule(vid.task(1).attempt(0), "b", priority=20)
    assert sched.get_task("c0", timeout=0.1) == "a"
    assert sched.get_task("c1", timeout=0.1) == "b"
    high = DAGId("app_1_p", 1).vertex(1)
    sched.schedule(high.task(0).attempt(0), "h0", priority=5)
    assert len(_kills(ctx)) == 1       # first round fires immediately
    sched._preempting.clear()          # pretend the kill resolved
    sched.schedule(high.task(1).attempt(0), "h1", priority=5)
    assert len(_kills(ctx)) == 1       # second round suppressed by pacing


def test_preemption_max_wait_forces_round():
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    ctx = _SchedCtx(C.TezConfiguration({
        "tez.am.preemption.percentage": 100,
        "tez.am.preemption.heartbeats-between-preemptions": 40,
        "tez.am.preemption.max.wait-time-ms": 50,
    }))
    sched = LocalTaskSchedulerService(ctx, num_slots=1)
    vid = DAGId("app_1_p", 1).vertex(0)
    sched.schedule(vid.task(0).attempt(0), "a", priority=20)
    assert sched.get_task("c0", timeout=0.1) == "a"
    high = DAGId("app_1_p", 1).vertex(1)
    sched.schedule(high.task(0).attempt(0), "h0", priority=5)
    assert len(_kills(ctx)) == 1
    sched._preempting.clear()          # pretend the kill resolved
    sched._running[vid.task(1).attempt(0)] = "c0"
    time.sleep(0.08)                   # top request now waited > max-wait
    sched.schedule(high.task(1).attempt(0), "h1", priority=5)
    assert len(_kills(ctx)) >= 2       # pacing bypassed


def test_preemption_noop_after_shutdown():
    """A preemption retry Timer that fires after shutdown() must not kill
    anything: Timer.cancel cannot stop a callback already in flight, so
    _maybe_preempt itself has to early-return once the scheduler is down."""
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    ctx = _SchedCtx(C.TezConfiguration({
        "tez.am.preemption.percentage": 100,
        "tez.am.preemption.heartbeats-between-preemptions": 40,
        "tez.am.preemption.max.wait-time-ms": 50,
    }))
    sched = LocalTaskSchedulerService(ctx, num_slots=1)
    vid = DAGId("app_1_p", 1).vertex(0)
    sched.schedule(vid.task(0).attempt(0), "a", priority=20)
    assert sched.get_task("c0", timeout=0.1) == "a"
    high = DAGId("app_1_p", 1).vertex(1)
    sched.schedule(high.task(0).attempt(0), "h0", priority=5)
    assert len(_kills(ctx)) == 1
    # same arrangement that forces a round in the max-wait test above —
    # except the scheduler is shut down, so nothing may be preempted
    sched._preempting.clear()
    sched._running[vid.task(1).attempt(0)] = "c0"
    time.sleep(0.08)
    sched.shutdown()
    sched._maybe_preempt()             # the late Timer callback
    assert len(_kills(ctx)) == 1


def test_vertex_max_task_concurrency_caps_handout():
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    ctx = _SchedCtx(C.TezConfiguration(
        {"tez.am.vertex.max-task-concurrency": 1}))
    sched = LocalTaskSchedulerService(ctx, num_slots=4)
    va = DAGId("app_1_p", 1).vertex(0)
    vb = DAGId("app_1_p", 1).vertex(1)
    sched.schedule(va.task(0).attempt(0), "a0", priority=5)
    sched.schedule(va.task(1).attempt(0), "a1", priority=5)
    sched.schedule(vb.task(0).attempt(0), "b0", priority=20)
    assert sched.get_task("c0", timeout=0.1) == "a0"
    # a1 would exceed vertex-0 concurrency of 1: b0 goes out instead
    assert sched.get_task("c1", timeout=0.1) == "b0"
    assert sched.get_task("c2", timeout=0.05) is None   # a1 still capped
    assert sched.backlog() >= 1


# --------------------------------------------------- history logging switch
def test_history_logging_switches():
    from tez_tpu.am.history import (HistoryEvent, HistoryEventHandler,
                                    HistoryEventType,
                                    InMemoryHistoryLoggingService)
    svc = InMemoryHistoryLoggingService()
    h = HistoryEventHandler(svc, conf=C.TezConfiguration(
        {"tez.am.history.logging.enabled": False}))
    h.handle(HistoryEvent(HistoryEventType.AM_STARTED))
    assert len(svc.events) == 0
    svc2 = InMemoryHistoryLoggingService()
    h2 = HistoryEventHandler(svc2, conf=C.TezConfiguration({}))
    h2.set_dag_conf("dag_7", {"tez.dag.history.logging.enabled": False})
    h2.handle(HistoryEvent(HistoryEventType.AM_STARTED))
    h2.handle(HistoryEvent(HistoryEventType.DAG_SUBMITTED, dag_id="dag_7"))
    h2.handle(HistoryEvent(HistoryEventType.DAG_SUBMITTED, dag_id="dag_8"))
    assert len(svc2.events) == 2       # AM event + dag_8 only


def test_history_dag_switch_discarded_on_finish():
    """The per-DAG logging switch must be dropped at DAG_FINISHED even when
    the MASTER switch short-circuits handle() — a session AM running with
    am-logging off would otherwise leak one switch entry per suppressed
    DAG, forever."""
    from tez_tpu.am.history import (HistoryEvent, HistoryEventHandler,
                                    HistoryEventType,
                                    InMemoryHistoryLoggingService)
    for master in (True, False):
        svc = InMemoryHistoryLoggingService()
        h = HistoryEventHandler(svc, conf=C.TezConfiguration(
            {"tez.am.history.logging.enabled": master}))
        h.set_dag_conf("dag_9", {"tez.dag.history.logging.enabled": False})
        h.handle(HistoryEvent(HistoryEventType.DAG_STARTED, dag_id="dag_9"))
        assert "dag_9" in h._dag_logging_disabled
        h.handle(HistoryEvent(HistoryEventType.DAG_FINISHED,
                              dag_id="dag_9"))
        assert "dag_9" not in h._dag_logging_disabled, \
            f"switch leaked with am_logging_enabled={master}"
        assert len(svc.events) == 0    # dag_9 suppressed either way
