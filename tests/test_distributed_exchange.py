"""Multi-chip shuffle exchange on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import jax

from tez_tpu.parallel.exchange import (build_distributed_shuffle,
                                       distributed_shuffle_reference)
from tez_tpu.parallel.mesh import make_mesh, worker_sharding


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def test_distributed_shuffle_matches_host_golden(mesh8):
    W, N, L, CAP = 8, 64, 2, 64 * 8
    rng = np.random.default_rng(0)
    lanes = rng.integers(0, 1 << 20, (W * N, L)).astype(np.uint32)
    values = np.arange(W * N, dtype=np.uint32)
    valid = rng.random(W * N) < 0.9

    fn = build_distributed_shuffle(mesh8, L, N, CAP)
    out_lanes, out_vals, out_valid, dropped = jax.device_get(
        fn(lanes, values, valid.astype(bool)))
    assert int(dropped.sum()) == 0

    golden = distributed_shuffle_reference(lanes, values, valid, W)
    per = out_lanes.shape[0] // W
    for w in range(8):
        ol = out_lanes[w * per:(w + 1) * per]
        ov = out_vals[w * per:(w + 1) * per]
        om = out_valid[w * per:(w + 1) * per]
        got = [(tuple(ol[i].tolist()), int(ov[i]))
               for i in range(per) if om[i]]
        assert got == golden[w], f"worker {w}"


def test_distributed_shuffle_all_invalid(mesh8):
    W, N, L, CAP = 8, 16, 2, 16
    fn = build_distributed_shuffle(mesh8, L, N, CAP)
    lanes = np.zeros((W * N, L), dtype=np.uint32)
    values = np.zeros(W * N, dtype=np.uint32)
    valid = np.zeros(W * N, dtype=bool)
    _, _, out_valid, dropped = jax.device_get(fn(lanes, values, valid))
    assert not out_valid.any()
    assert int(dropped.sum()) == 0


def test_distributed_shuffle_overflow_is_reported(mesh8):
    """Rows beyond the per-pair capacity must be counted, never silently
    lost (the skew-handling layer re-runs with a bigger cap)."""
    W, N, L, CAP = 8, 16, 2, 4
    fn = build_distributed_shuffle(mesh8, L, N, CAP)
    lanes = np.zeros((W * N, L), dtype=np.uint32)   # all hash to one worker
    values = np.arange(W * N, dtype=np.uint32)
    valid = np.ones(W * N, dtype=bool)
    _, _, out_valid, dropped = jax.device_get(fn(lanes, values, valid))
    assert int(out_valid.sum()) + int(dropped.sum()) == W * N
    assert int(dropped.sum()) > 0


def test_ragged_exchange_matches_golden_or_skips(mesh8):
    """The ragged (zero-padding-on-wire) exchange; XLA:CPU lacks the
    ragged-all-to-all thunk, so this compiles+runs only on TPU."""
    W, N, L = 8, 32, 2
    fn = build_distributed_shuffle(mesh8, L, N, N, ragged=True)
    rng = np.random.default_rng(3)
    lanes = rng.integers(0, 1 << 18, (W * N, L)).astype(np.uint32)
    values = np.arange(W * N, dtype=np.uint32)
    valid = np.ones(W * N, dtype=bool)
    try:
        out_lanes, out_vals, out_valid, dropped = jax.device_get(
            fn(lanes, values, valid))
    except Exception as e:  # noqa: BLE001
        if "UNIMPLEMENTED" in str(e) or isinstance(e, NotImplementedError):
            pytest.skip(f"backend lacks ragged-all-to-all: {type(e).__name__}")
        raise
    assert int(dropped.sum()) == 0
    golden = distributed_shuffle_reference(lanes, values, valid, W)
    per = out_lanes.shape[0] // W
    for w in range(W):
        got = sorted((tuple(out_lanes[w * per + i].tolist()),
                      int(out_vals[w * per + i]))
                     for i in range(per) if out_valid[w * per + i])
        assert got == sorted(golden[w]), f"worker {w}"
