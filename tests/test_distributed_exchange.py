"""Multi-chip shuffle exchange on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import jax

from tez_tpu.parallel.exchange import (build_distributed_shuffle,
                                       distributed_shuffle_reference)
from tez_tpu.parallel.mesh import make_mesh, worker_sharding


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _inputs(W, N, L, V, seed=0, valid_frac=0.9, key_max_len=None):
    rng = np.random.default_rng(seed)
    lanes = rng.integers(0, 1 << 20, (W * N, L)).astype(np.uint32)
    key_max = key_max_len if key_max_len is not None else L * 4
    lengths = rng.integers(1, key_max + 1, W * N).astype(np.uint32)
    # zero the bytes beyond each key's length so lanes are canonical
    for i in range(L * 4):
        word, shift = divmod(i, 4)
        mask = ~(np.uint32(0xFF) << np.uint32(24 - 8 * (i % 4)))
        dead = lengths <= i
        lanes[dead, word] &= mask
    values = rng.integers(0, 1 << 30, (W * N, V)).astype(np.uint32)
    valid = rng.random(W * N) < valid_frac
    return lanes, lengths, values, valid


def _got(out_lanes, out_lens, out_vals, out_valid, W):
    per = out_lanes.shape[0] // W
    out = []
    for w in range(W):
        sl = slice(w * per, (w + 1) * per)
        ol, oln, ov, om = out_lanes[sl], out_lens[sl], out_vals[sl], \
            out_valid[sl]
        out.append([(tuple(ol[i].tolist()), int(oln[i]),
                     tuple(np.atleast_1d(ov[i]).tolist()))
                    for i in range(per) if om[i]])
    return out


def test_distributed_shuffle_matches_host_golden(mesh8):
    W, N, L, V, CAP = 8, 64, 2, 3, 64 * 8
    lanes, lengths, values, valid = _inputs(W, N, L, V)
    fn = build_distributed_shuffle(mesh8, L, N, CAP, value_words=V)
    out_lanes, out_lens, out_vals, out_valid, dropped = jax.device_get(
        fn(lanes, lengths, values, valid.astype(bool)))
    assert int(dropped.sum()) == 0
    golden = distributed_shuffle_reference(lanes, lengths, values, valid, W)
    got = _got(out_lanes, out_lens, out_vals, out_valid, W)
    for w in range(W):
        assert got[w] == golden[w], f"worker {w}"


def test_short_key_sorts_before_zero_padded_longer_key(mesh8):
    """Exactness of the length tie-break: key b"ad" must order before
    b"ad\\x00" even though their zero-padded lanes are identical.  The pair
    is chosen so BOTH keys hash to the same worker (FNV(b"ad") % 8 ==
    FNV(b"ad\\x00") % 8 == 0) — the tie-break is exercised by one worker's
    merge sort, not masked by worker routing."""
    from tez_tpu.parallel.exchange import fnv_bytes_host
    assert fnv_bytes_host(b"ad") % 8 == fnv_bytes_host(b"ad\x00") % 8

    W, N, L = 8, 8, 1
    fn = build_distributed_shuffle(mesh8, L, N, N * W, value_words=1)
    ad = int.from_bytes(b"ad\x00\x00", "big")
    lanes = np.zeros((W * N, L), np.uint32)
    lengths = np.zeros(W * N, np.uint32)
    values = np.zeros((W * N, 1), np.uint32)
    valid = np.zeros(W * N, bool)
    # two rows: same lanes, lengths 3 and 2 (deliberately reversed order)
    lanes[0, 0] = ad
    lengths[0] = 3
    values[0, 0] = 333
    lanes[1, 0] = ad
    lengths[1] = 2
    values[1, 0] = 222
    valid[:2] = True
    out_lanes, out_lens, out_vals, out_valid, dropped = jax.device_get(
        fn(lanes, lengths, values, valid))
    assert int(dropped.sum()) == 0
    rows = [(int(out_lens[i]), int(out_vals[i, 0]))
            for i in range(len(out_valid)) if out_valid[i]]
    assert rows == [(2, 222), (3, 333)]


def test_distributed_shuffle_all_invalid(mesh8):
    W, N, L = 8, 16, 2
    fn = build_distributed_shuffle(mesh8, L, N, 16, value_words=1)
    lanes = np.zeros((W * N, L), dtype=np.uint32)
    lengths = np.zeros(W * N, dtype=np.uint32)
    values = np.zeros((W * N, 1), dtype=np.uint32)
    valid = np.zeros(W * N, dtype=bool)
    _, _, _, out_valid, dropped = jax.device_get(
        fn(lanes, lengths, values, valid))
    assert not out_valid.any()
    assert int(dropped.sum()) == 0


def test_distributed_shuffle_overflow_is_reported(mesh8):
    """Rows beyond the per-pair capacity must be counted, never silently
    lost (the coordinator sizes CAP exactly; this guards the kernel)."""
    W, N, L, CAP = 8, 16, 2, 4
    fn = build_distributed_shuffle(mesh8, L, N, CAP, value_words=1)
    lanes = np.zeros((W * N, L), dtype=np.uint32)   # all hash to one worker
    lengths = np.full(W * N, 4, dtype=np.uint32)
    values = np.arange(W * N, dtype=np.uint32).reshape(-1, 1)
    valid = np.ones(W * N, dtype=bool)
    _, _, _, out_valid, dropped = jax.device_get(
        fn(lanes, lengths, values, valid))
    assert int(out_valid.sum()) + int(dropped.sum()) == W * N
    assert int(dropped.sum()) > 0


def test_ragged_exchange_matches_golden_or_skips(mesh8):
    """Ragged-engine parity on backends that have the thunk; elsewhere a
    SKIP carrying the probe's reason (never a silent pass — the probe
    re-raises anything that is not the known missing-thunk signature)."""
    from tez_tpu.parallel.exchange import probe_ragged_support
    ok, reason = probe_ragged_support(mesh8)
    if not ok:
        pytest.skip(reason)
    W, N, L, V = 8, 32, 2, 2
    fn = build_distributed_shuffle(mesh8, L, N, N, value_words=V,
                                   ragged=True)
    lanes, lengths, values, valid = _inputs(W, N, L, V, seed=3,
                                            valid_frac=1.0)
    out_lanes, out_lens, out_vals, out_valid, dropped = jax.device_get(
        fn(lanes, lengths, values, valid))
    assert int(dropped.sum()) == 0
    golden = distributed_shuffle_reference(lanes, lengths, values, valid, W)
    got = _got(out_lanes, out_lens, out_vals, out_valid, W)
    for w in range(W):
        assert sorted(got[w]) == sorted(golden[w]), f"worker {w}"


def test_probe_is_cached_and_resolver_maps_knob(mesh8):
    """The probe caches per (devices, platform); resolve_engine maps the
    knob onto what the backend can run — 'padded' is always honored,
    'auto'/'ragged' follow the probe, junk raises naming the knob."""
    from tez_tpu.parallel.exchange import (probe_ragged_support,
                                           resolve_engine)
    ok, reason = probe_ragged_support(mesh8)
    assert probe_ragged_support(mesh8) == (ok, reason)   # cached
    assert resolve_engine("padded", mesh8)[0] == "padded"
    eng_auto, why_auto = resolve_engine("auto", mesh8)
    eng_req, why_req = resolve_engine("ragged", mesh8)
    assert eng_auto == eng_req == ("ragged" if ok else "padded")
    if not ok:
        assert reason in why_req or "padded" in why_req
    with pytest.raises(ValueError, match="tez.runtime.mesh.exchange.engine"):
        resolve_engine("turbo", mesh8)


def test_explicit_dests_matching_hash_reproduces_golden(mesh8):
    """explicit_dests with the FNV route itself must be bit-identical to
    hash routing — the coordinator always sends explicit routes, so this
    is the bridge invariant between the two formulations."""
    from tez_tpu.ops.host_sort import fnv_rows_host
    from tez_tpu.ops.keycodec import lanes_to_matrix
    W, N, L, V, CAP = 8, 64, 2, 3, 64 * 8
    lanes, lengths, values, valid = _inputs(W, N, L, V, seed=5)
    dests = (fnv_rows_host(lanes_to_matrix(lanes),
                           lengths.astype(np.int64)) %
             np.uint32(W)).astype(np.uint32)
    fn = build_distributed_shuffle(mesh8, L, N, CAP, value_words=V,
                                   explicit_dests=True)
    out = jax.device_get(fn(lanes, lengths, values, valid.astype(bool),
                            dests))
    assert int(out[4].sum()) == 0
    golden = distributed_shuffle_reference(lanes, lengths, values, valid, W)
    got = _got(*out[:4], W)
    for w in range(W):
        assert got[w] == golden[w], f"worker {w}"


def test_explicit_dests_redirect_overrides_hash(mesh8):
    """Explicit routing WINS over the key hash: every valid row sent to
    worker 3 lands on worker 3, key-sorted, regardless of what the keys
    hash to (the splitter/coded seam)."""
    W, N, L = 8, 16, 2
    lanes, lengths, values, valid = _inputs(W, N, L, 1, seed=9)
    dests = np.full(W * N, 3, np.uint32)
    fn = build_distributed_shuffle(mesh8, L, N, W * N, value_words=1,
                                   explicit_dests=True)
    out_lanes, out_lens, out_vals, out_valid, dropped = jax.device_get(
        fn(lanes, lengths, values, valid.astype(bool), dests))
    assert int(dropped.sum()) == 0
    got = _got(out_lanes, out_lens, out_vals, out_valid, W)
    assert all(not got[w] for w in range(W) if w != 3)
    assert len(got[3]) == int(valid.sum())
    keys3 = [(g[0], g[1]) for g in got[3]]
    assert keys3 == sorted(keys3)
