"""Tiered shuffle buffer store tests (tez_tpu/store): lease pinning,
watermark demotion, byte-accounting invariants, epoch fencing, lineage
seal/republish, and session-mode cross-DAG output reuse end-to-end."""
from __future__ import annotations

import collections
import random
import threading

import numpy as np
import pytest

from tez_tpu.common import epoch as epoch_registry
from tez_tpu.common.epoch import EpochFencedError
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.store import ensure_store, local_buffer_store, reset_store
from tez_tpu.store.buffer_store import (DEVICE, DISK, HOST,
                                        ShuffleBufferStore, StoreKeyNotFound)


def _run(n: int = 64, parts: int = 2, seed: int = 0,
         dev_lanes: bool = False) -> Run:
    rng = random.Random(seed)
    pairs = [(b"k%06d" % rng.randrange(10_000), b"v%04d" % (i % 97))
             for i in range(n)]
    batch = KVBatch.from_pairs(sorted(pairs))
    if dev_lanes:
        # store accounting only needs .nbytes on each lane array, so plain
        # numpy arrays stand in for HBM buffers here
        batch.dev_keys = (np.zeros((n, 4), np.uint32),
                          np.zeros(n, np.int32), 0, n)
    bounds = np.linspace(0, n, parts + 1).astype(np.int64)
    return Run(batch, bounds)


def _pairs(batch: KVBatch):
    return list(batch.iter_pairs())


@pytest.fixture()
def store(tmp_path):
    s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 20,
                           disk_dir=str(tmp_path / "store"))
    yield s
    s.close()


# ------------------------------------------------------------- basic tiers

def test_publish_fetch_roundtrip(store):
    run = _run()
    store.publish("dag1/a0/cons", -1, run)
    for p in range(run.num_partitions):
        got = store.fetch_partition("dag1/a0/cons", -1, p)
        assert _pairs(got) == _pairs(run.partition(p))
    assert store.counters["store.hits"] == run.num_partitions
    assert store.tier_bytes(HOST) == run.nbytes
    with pytest.raises(StoreKeyNotFound):
        store.fetch_partition("dag1/a0/cons", 0, 0)
    assert store.counters["store.misses"] == 1
    assert store.unregister_prefix("dag1") == 1
    assert store.tier_bytes(HOST) == 0
    assert store.stats()["entries"] == 0


def test_device_tier_accounts_lane_bytes_and_demotes(tmp_path):
    s = ShuffleBufferStore(device_capacity=1 << 20, host_capacity=1 << 30,
                           disk_dir=str(tmp_path / "d"))
    try:
        run = _run(dev_lanes=True)
        lanes_nbytes = sum(a.nbytes for a in run.batch.dev_keys
                          if hasattr(a, "nbytes"))
        s.publish("dag1/a0/cons", -1, run)
        assert s.tier_bytes(DEVICE) == lanes_nbytes
        assert s.tier_bytes(HOST) == run.nbytes   # host arrays ride along
        freed = s.relieve_device_pressure(1 << 30)
        assert freed == lanes_nbytes
        assert s.tier_bytes(DEVICE) == 0
        assert s.counters["store.demotions.device_to_host"] == 1
        # demotion dropped the lanes but data stays fetchable bit-exact
        got = s.fetch_partition("dag1/a0/cons", -1, 0)
        assert got.dev_keys is None
        assert _pairs(got) == _pairs(run.partition(0))
    finally:
        s.close()


def test_no_device_capacity_drops_lanes_at_publish(store):
    store.publish("dag1/a0/cons", -1, _run(dev_lanes=True))
    assert store.tier_bytes(DEVICE) == 0
    assert store.get("dag1/a0/cons", -1).batch.dev_keys is None


# --------------------------------------------------- watermarks and leases

def test_watermark_demotion_cascade_host_to_disk(tmp_path):
    run0 = _run(seed=0)
    cap = run0.nbytes * 3
    s = ShuffleBufferStore(device_capacity=0, host_capacity=cap,
                           high_watermark=0.8, low_watermark=0.4,
                           disk_dir=str(tmp_path / "d"))
    try:
        runs = [_run(seed=i) for i in range(8)]
        for i, r in enumerate(runs):
            s.publish(f"dag1/a{i}/cons", -1, r)
        assert s.counters["store.demotions.host_to_disk"] >= 4
        assert s.tier_bytes(HOST) <= cap * 0.8
        assert s.tier_bytes(DISK) > 0
        # every run still fetchable bit-exact, whichever tier it landed in
        for i, r in enumerate(runs):
            for p in range(r.num_partitions):
                got = s.fetch_partition(f"dag1/a{i}/cons", -1, p)
                assert _pairs(got) == _pairs(r.partition(p))
    finally:
        s.close()


def test_lease_blocks_demotion_and_eviction(tmp_path):
    run = _run(seed=1)
    s = ShuffleBufferStore(device_capacity=0, host_capacity=run.nbytes * 2,
                           high_watermark=0.5, low_watermark=0.1,
                           disk_dir=str(tmp_path / "d"))
    try:
        s.publish("dag1/a0/cons", -1, run)
        with s.lease("dag1/a0/cons", -1) as leased:
            view = leased.partition(0)        # zero-copy view under lease
            # pressure that would otherwise demote everything: the leased
            # entry must be skipped even though it is the only candidate
            assert s.relieve_host_pressure(1 << 30) == 0
            s.publish("dag1/a1/cons", -1, _run(seed=2))   # watermark breach
            assert s.tier_bytes(HOST) >= run.nbytes       # still resident
            assert _pairs(view) == _pairs(run.partition(0))
        # lease released: the same pressure now demotes it
        assert s.relieve_host_pressure(1 << 30) > 0
        assert s.counters["store.demotions.host_to_disk"] >= 1
        got = s.fetch_partition("dag1/a0/cons", -1, 1)
        assert _pairs(got) == _pairs(run.partition(1))
    finally:
        s.close()


def test_leased_entry_survives_unregister(store):
    run = _run()
    store.publish("dag1/a0/cons", -1, run)
    with store.lease("dag1/a0/cons", -1) as leased:
        assert store.unregister_prefix("dag1") == 1
        # the alias is gone but the reader's run stays whole until release
        assert not store.contains("dag1/a0/cons", -1)
        assert _pairs(leased.partition(0)) == _pairs(run.partition(0))
    assert store.stats()["entries"] == 0
    assert store.tier_bytes(HOST) == 0


def test_disk_eviction_only_touches_sealed_lineage(tmp_path):
    run = _run(seed=3)
    s = ShuffleBufferStore(device_capacity=0, host_capacity=run.nbytes,
                           disk_capacity=run.nbytes * 2,
                           high_watermark=0.5, low_watermark=0.1,
                           disk_dir=str(tmp_path / "d"))
    try:
        # live DAG outputs demoted to disk are never evicted, no matter
        # how far over the disk watermark the tier goes
        for i in range(4):
            s.publish(f"dag1/a{i}/cons", -1, _run(seed=10 + i),
                      lineage=f"lin{i}/0/cons")
        assert s.tier_bytes(DISK) > s.disk_capacity * 0.5
        assert s.counters["store.evictions.disk"] == 0
        # sealed lineage-only entries ARE evictable once the DAG aliases go
        assert s.seal_lineage("dag1") == 4
        s.unregister_prefix("dag1")
        s.publish("dag2/a0/cons", -1, _run(seed=20))   # trigger enforcement
        assert s.counters["store.evictions.disk"] >= 1
    finally:
        s.close()


# ----------------------------------------------------------- byte accounting

def test_exact_byte_accounting_under_concurrency(tmp_path):
    run0 = _run(seed=0)
    s = ShuffleBufferStore(device_capacity=0, host_capacity=run0.nbytes * 4,
                           high_watermark=0.8, low_watermark=0.4,
                           disk_dir=str(tmp_path / "d"))
    errors = []

    def worker(w: int) -> None:
        try:
            for i in range(12):
                path = f"dag1/w{w}_{i}/cons"
                r = _run(seed=w * 100 + i)
                s.publish(path, -1, r)
                got = s.fetch_partition(path, -1, i % r.num_partitions)
                assert got.num_records >= 0
                if i % 3 == 0:
                    s.unregister_prefix(path)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors, errors
        # invariant: with no leases held, dropping every alias must return
        # every tier to EXACTLY zero — any drift means an accounting bug
        s.unregister_prefix("dag1")
        assert s.stats()["entries"] == 0
        assert s.tier_bytes(HOST) == 0
        assert s.tier_bytes(DEVICE) == 0
        assert s.tier_bytes(DISK) == 0
    finally:
        s.close()


# ------------------------------------------------------------ epoch fencing

def test_stale_epoch_publish_fenced(store):
    epoch_registry.register("app_x", 3)
    with pytest.raises(EpochFencedError):
        store.publish("dag1/a0/cons", -1, _run(), epoch=2, app_id="app_x")
    store.publish("dag1/a0/cons", -1, _run(), epoch=3, app_id="app_x")
    assert store.contains("dag1/a0/cons", -1)


def test_stale_epoch_sealed_lineage_misses(store):
    epoch_registry.register("app_y", 1)
    store.publish("dag1/a0/cons", -1, _run(), epoch=1, app_id="app_y",
                  lineage="lin1/0/cons")
    assert store.seal_lineage("dag1") == 1
    assert store.lineage_spills("lin1/0/cons") == [-1]
    # AM restarts: entries sealed by the superseded epoch are fenced out
    epoch_registry.register("app_y", 2)
    assert store.lineage_spills("lin1/0/cons") == []
    with pytest.raises(EpochFencedError):
        store.republish_lineage("lin1/0/cons", "dag2/a0/cons",
                                epoch=1, app_id="app_y")


# -------------------------------------------------------- lineage lifecycle

def test_seal_republish_roundtrip(store):
    run = _run(seed=5)
    store.publish("dag1/a0/cons", -1, run, lineage="linA/0/cons")
    store.publish("dag1/a0/cons", 0, _run(seed=6), lineage="linA/0/cons")
    store.publish("dag1/a1/cons", -1, _run(seed=7))      # untagged: no seal
    assert store.seal_lineage("dag1") == 2
    assert store.counters["store.lineage.sealed"] == 2
    # the DAG commits and its aliases drop; sealed entries survive
    store.unregister_prefix("dag1")
    assert store.lineage_spills("linA/0/cons") == [-1, 0]
    assert store.lineage_spills("nope") == []
    assert store.counters["store.lineage.misses"] == 1
    # a recurring DAG aliases them under its own path, zero copy
    assert store.republish_lineage("linA/0/cons",
                                   "dag2/a9/cons") == [-1, 0]
    got = store.fetch_partition("dag2/a9/cons", -1, 1)
    assert _pairs(got) == _pairs(run.partition(1))
    # dropping the new DAG still leaves the sealed copy for the next hit
    store.unregister_prefix("dag2")
    assert store.lineage_spills("linA/0/cons") == [-1, 0]


# ------------------------------------------------------- singleton lifecycle

def test_ensure_store_disabled_by_default():
    assert ensure_store({}) is None
    assert local_buffer_store() is None


def test_ensure_store_conf_knobs_and_reset(tmp_path):
    from tez_tpu.shuffle.service import local_shuffle_service
    conf = {"tez.runtime.store.enabled": "true",
            "tez.runtime.store.device.capacity-mb": 2,
            "tez.runtime.store.host.capacity-mb": 0.5,
            "tez.runtime.store.dir": str(tmp_path / "s")}
    s = ensure_store(conf)
    try:
        assert s is not None
        assert s is local_buffer_store()
        assert ensure_store(conf) is s                 # idempotent
        assert s.device_capacity == 2 << 20
        assert s.host_capacity == (1 << 20) // 2       # fractional MB
        assert local_shuffle_service().buffer_store() is s
        # registrations route through the store via the service seam
        run = _run()
        local_shuffle_service().register("dagZ/a0/cons", -1, run)
        assert s.contains("dagZ/a0/cons", -1)
        got = local_shuffle_service().fetch_partition("dagZ/a0/cons", -1, 0)
        assert _pairs(got) == _pairs(run.partition(0))
        assert s.counters["store.hits"] == 1
    finally:
        reset_store()
    assert local_buffer_store() is None
    assert local_shuffle_service().buffer_store() is None


def test_ensure_store_attaches_push_admission(tmp_path):
    from tez_tpu.shuffle.service import local_shuffle_service
    conf = {"tez.runtime.store.enabled": "true",
            "tez.runtime.store.dir": str(tmp_path / "s"),
            "tez.runtime.shuffle.push.enabled": "true",
            "tez.runtime.shuffle.push.source-quota-mb": 3}
    try:
        s = ensure_store(conf)
        assert s is not None
        adm = local_shuffle_service().push_admission()
        assert adm is not None
        assert adm.source_quota == 3 << 20
        assert ensure_store(conf) is s                 # idempotent
        assert local_shuffle_service().push_admission() is adm
    finally:
        reset_store()
    # reset detaches the landing zone along with the store
    assert local_shuffle_service().push_admission() is None


def test_ensure_store_push_off_no_admission(tmp_path):
    from tez_tpu.shuffle.service import local_shuffle_service
    conf = {"tez.runtime.store.enabled": "true",
            "tez.runtime.store.dir": str(tmp_path / "s")}
    try:
        assert ensure_store(conf) is not None
        assert local_shuffle_service().push_admission() is None
    finally:
        reset_store()


# --------------------------------------------- session-mode cross-DAG reuse

def _write_corpus(path, num_lines=200, seed=0):
    rng = random.Random(seed)
    words = [f"w{i:02d}" for i in range(25)]
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(words) for _ in range(6)]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def _read_out(out_dir):
    import os
    blobs = []
    for name in sorted(os.listdir(out_dir)):
        if not name.startswith("part-"):
            continue
        with open(os.path.join(out_dir, name), "rb") as fh:
            blobs.append(fh.read())
    assert blobs, f"no part- files in {out_dir}"
    return b"".join(blobs)


def test_session_cross_dag_output_reuse(tmp_path):
    """Two identical wordcount DAGs in one session: the second run's
    tokenizer/summation tasks must be served from sealed store lineage
    (processors skipped), the leaf sorter vertex must recompute (file
    outputs cannot reuse), and the outputs must be bit-exact."""
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount

    corpus = tmp_path / "in.txt"
    _write_corpus(str(corpus))
    conf = {"tez.staging-dir": str(tmp_path / "staging"),
            "tez.am.local.num-containers": 4,
            "tez.runtime.store.enabled": True,
            "tez.runtime.store.host.capacity-mb": 64}
    outs = []
    try:
        for i, name in enumerate(("sess_run1", "sess_run2")):
            out = str(tmp_path / f"out{i}")
            dag = ordered_wordcount.build_dag(
                [str(corpus)], out, tokenizer_parallelism=3,
                summation_parallelism=2, sorter_parallelism=1)
            with TezClient.create(name, conf) as client:
                status = client.submit_dag(dag).wait_for_completion(
                    timeout=90)
            assert status.state is DAGStatusState.SUCCEEDED
            outs.append(_read_out(out))
        store = local_buffer_store()
        assert store is not None
        c = store.stats()["counters"]
        # run 1 sealed its tokenizer+summation outputs (3 + 2 tasks); run 2
        # hit them — 5 probes (one per task) plus republishes count as hits
        assert c["store.lineage.sealed"] >= 5
        assert c["store.lineage.hits"] >= 5
        assert outs[0] == outs[1]
    finally:
        reset_store()


def test_lineage_hashes_stable_and_conf_sensitive(tmp_path):
    """vertex_lineage_hashes: identical plans hash identically; changing a
    vertex conf knob changes that vertex AND its downstream closure."""
    from tez_tpu.examples import ordered_wordcount
    from tez_tpu.store.lineage import task_lineage, vertex_lineage_hashes

    def plan(extra=None):
        dag = ordered_wordcount.build_dag(
            [str(tmp_path / "in.txt")], str(tmp_path / "out"),
            tokenizer_parallelism=2, summation_parallelism=2,
            sorter_parallelism=1)
        if extra:
            dag.vertices["summation"].set_conf("x.knob", extra)
        return dag.create_dag_plan()

    h1, h2 = vertex_lineage_hashes(plan()), vertex_lineage_hashes(plan())
    assert h1 == h2 and set(h1) == {"tokenizer", "summation", "sorter"}
    h3 = vertex_lineage_hashes(plan(extra="v2"))
    assert h3["tokenizer"] == h1["tokenizer"]       # upstream untouched
    assert h3["summation"] != h1["summation"]       # changed vertex
    assert h3["sorter"] != h1["sorter"]             # downstream closure
    assert task_lineage(h1["summation"], 1, "sorter") == \
        f"{h1['summation']}/1/sorter"
    assert task_lineage("", 1, "sorter") == ""      # lineage off


# ------------------------------------------------------------ chaos harness

def test_chaos_store_pressure_scenario(tmp_path):
    """The `--store-pressure` chaos scenario: a wide shuffle through tiny
    store tiers must demote/evict mid-merge and stay bit-exact."""
    from tez_tpu.tools import chaos
    ok, detail = chaos.run_store_pressure(0, str(tmp_path))
    assert ok, detail
    assert "churn=" in detail
