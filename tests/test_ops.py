"""Byte-exactness tests for the device data plane (sort/partition/merge/run
format) against numpy/pure-Python goldens — the TestIFile/TestPipelinedSorter
analog (SURVEY.md §4 tier 1 'real byte paths')."""
import os
import random

import numpy as np
import pytest

from tez_tpu.library.partitioners import HashPartitioner
from tez_tpu.ops import device
from tez_tpu.ops.keycodec import encode_keys, matrix_to_lanes, pad_to_matrix
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.ops.serde import VarLongSerde, get_serde
from tez_tpu.ops.sorter import (DeviceSorter, merge_sorted_runs,
                                sum_long_combiner)


def random_pairs(n, seed=0, max_key=12, max_val=8):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        k = bytes(rng.randrange(256) for _ in range(rng.randrange(1, max_key)))
        v = bytes(rng.randrange(256) for _ in range(rng.randrange(0, max_val)))
        out.append((k, v))
    return out


def golden_sorted(pairs, num_partitions):
    hp = HashPartitioner()
    decorated = [(hp.get_partition(k, v, num_partitions), k, i, v)
                 for i, (k, v) in enumerate(pairs)]
    decorated.sort(key=lambda t: (t[0], t[1], t[2]))  # stable by arrival
    return decorated


def test_kvbatch_roundtrip():
    pairs = random_pairs(100)
    b = KVBatch.from_pairs(pairs)
    assert list(b.iter_pairs()) == pairs
    assert b.num_records == 100
    perm = np.arange(99, -1, -1)
    rev = b.take(perm)
    assert list(rev.iter_pairs()) == pairs[::-1]


def test_pad_and_lanes_order_preserving():
    keys = [b"a", b"ab", b"b", b"", b"a\x00", b"\xff" * 20]
    b = KVBatch.from_pairs([(k, b"") for k in keys])
    mat, lengths = pad_to_matrix(b.key_bytes, b.key_offsets, 16)
    lanes = matrix_to_lanes(mat)
    order = sorted(range(len(keys)),
                   key=lambda i: tuple(lanes[i].tolist()) + (i,))
    golden = sorted(range(len(keys)), key=lambda i: (keys[i][:16], i))
    assert order == golden


def test_device_hash_matches_host_partitioner():
    pairs = random_pairs(500, seed=1, max_key=40)
    b = KVBatch.from_pairs(pairs)
    hp = HashPartitioner()
    golden = np.array([hp.get_partition(k, None, 7) for k, _ in pairs])
    klens = b.key_offsets[1:] - b.key_offsets[:-1]
    w = 1 << max(2, (int(klens.max()) - 1).bit_length())
    mat, lengths = pad_to_matrix(b.key_bytes, b.key_offsets, w)
    got = device.hash_partition(mat, lengths, 7)
    np.testing.assert_array_equal(got, golden)


@pytest.mark.parametrize("n,width", [(1000, 16), (1000, 4), (0, 16), (1, 16)])
def test_device_sorter_byte_exact(n, width):
    pairs = random_pairs(n, seed=2, max_key=24)  # keys can exceed width=4/16
    sorter = DeviceSorter(num_partitions=5, key_width=width)
    for k, v in pairs:
        sorter.write(k, v)
    run = sorter.flush()
    golden = golden_sorted(pairs, 5)
    got = list(run.batch.iter_pairs())
    assert got == [(k, v) for _, k, _, v in golden]
    # partition index correct
    for p in range(5):
        part = run.partition(p)
        expected = [(k, v) for pp, k, _, v in golden if pp == p]
        assert list(part.iter_pairs()) == expected


def test_sorter_multi_span_merge():
    pairs = random_pairs(3000, seed=3)
    sorter = DeviceSorter(num_partitions=3, key_width=16,
                          span_budget_bytes=4096)  # force many spans
    for k, v in pairs:
        sorter.write(k, v)
    run = sorter.flush()
    assert sorter.num_spills > 1
    golden = golden_sorted(pairs, 3)
    assert list(run.batch.iter_pairs()) == [(k, v) for _, k, _, v in golden]


def test_sorter_host_spill(tmp_path):
    pairs = random_pairs(2000, seed=4)
    sorter = DeviceSorter(num_partitions=2, span_budget_bytes=2048,
                          spill_dir=str(tmp_path), mem_budget_bytes=4096)
    for k, v in pairs:
        sorter.write(k, v)
    # spans over the mem budget spill as partition-indexed files
    assert any(f.endswith(".prun") for f in os.listdir(tmp_path))
    run = sorter.flush()
    golden = golden_sorted(pairs, 2)
    assert list(run.batch.iter_pairs()) == [(k, v) for _, k, _, v in golden]
    # flush consumed and removed the span spills (the final FileRun was
    # materialized and deleted by the flush() compat shim)
    assert not any(f.endswith(".prun") for f in os.listdir(tmp_path))


def test_run_save_load_checksum(tmp_path):
    pairs = random_pairs(50, seed=5)
    sorter = DeviceSorter(num_partitions=4)
    for k, v in pairs:
        sorter.write(k, v)
    run = sorter.flush()
    p = str(tmp_path / "x.run")
    run.save(p)
    run2 = Run.load(p)
    assert list(run2.batch.iter_pairs()) == list(run.batch.iter_pairs())
    np.testing.assert_array_equal(run2.row_index, run.row_index)
    # corrupt -> checksum failure
    data = bytearray(open(p, "rb").read())
    data[-1] ^= 0xFF
    open(p, "wb").write(bytes(data))
    with pytest.raises(IOError, match="checksum"):
        Run.load(p)


def test_merge_sorted_runs_equals_single_sort():
    pairs = random_pairs(900, seed=6)
    chunks = [pairs[:300], pairs[300:600], pairs[600:]]
    runs = []
    for c in chunks:
        s = DeviceSorter(num_partitions=4)
        for k, v in c:
            s.write(k, v)
        runs.append(s.flush())
    merged = merge_sorted_runs(runs, 4, 16)
    # golden: all pairs, arrival order = chunk order (stability contract)
    golden = golden_sorted(pairs, 4)
    assert list(merged.batch.iter_pairs()) == \
        [(k, v) for _, k, _, v in golden]


def test_pipelined_spills_emitted():
    pairs = random_pairs(1000, seed=7)
    sorter = DeviceSorter(num_partitions=2, span_budget_bytes=4096)
    spills = []
    sorter.on_spill = lambda run, sid: spills.append((sid, run))
    for k, v in pairs:
        sorter.write(k, v)
    assert sorter.flush() is None
    assert len(spills) >= 2
    total = sum(r.batch.num_records for _, r in spills)
    assert total == 1000


def test_sum_long_combiner():
    serde = VarLongSerde()
    words = [b"a", b"b", b"a", b"c", b"a", b"b"]
    sorter = DeviceSorter(num_partitions=2, combiner=sum_long_combiner)
    for w in words:
        sorter.write(w, serde.to_bytes(1))
    run = sorter.flush()
    got = {k: serde.from_bytes(v) for k, v in run.batch.iter_pairs()}
    assert got == {b"a": 3, b"b": 2, b"c": 1}


def test_varlong_serde_order_and_values():
    s = VarLongSerde()
    vals = [-(2**62), -5, -1, 0, 1, 7, 2**62]
    encs = [s.to_bytes(v) for v in vals]
    assert encs == sorted(encs)
    assert [s.from_bytes(e) for e in encs] == vals


def test_empty_partition_flags():
    sorter = DeviceSorter(num_partitions=8)
    sorter.write(b"onlykey", b"v")
    run = sorter.flush()
    flags = run.empty_partition_flags()
    assert flags.count(False) == 1 and flags.count(True) == 7


def test_split_boundary_no_lost_or_duplicated_lines(tmp_path):
    """Every line is read by exactly one split, including lines starting
    exactly at a split boundary (LineRecordReader semantics)."""
    from tez_tpu.io.text import FileSplit, _LineReader, compute_splits

    from tez_tpu.common.counters import TezCounters

    class _Ctx:
        counters = TezCounters()

        def notify_progress(self):
            pass

    p = tmp_path / "t.txt"
    lines = [f"line{i:04d}" for i in range(1000)]
    p.write_text("\n".join(lines) + "\n")
    size = p.stat().st_size
    # brute-force every 2-way split point, including line boundaries
    for cut in list(range(1, size, 97)) + [9, 10, 11, 18, 19, 20, 21]:
        splits = [FileSplit(str(p), 0, cut), FileSplit(str(p), cut, size - cut)]
        got = []
        for s in splits:
            got.extend(l.decode() for _, l in _LineReader([s], _Ctx()))
        assert got == lines, f"cut={cut}"


def test_custom_partitioner_spi():
    """Explicit per-record partitions (a custom Partitioner's output over
    logical keys) route records instead of the device hash."""
    from tez_tpu.ops.sorter import DeviceSorter

    sorter = DeviceSorter(num_partitions=3)
    pairs = [(bytes([i % 7]) + b"key", b"v") for i in range(60)]
    for k, v in pairs:
        sorter.write(k, v, partition=k[0] % 3)
    run = sorter.flush()
    total = 0
    for p in range(3):
        for k, _ in run.partition(p).iter_pairs():
            assert k[0] % 3 == p
            total += 1
    assert total == 60


def test_multi_pass_merge_factor():
    """More runs than io.sort.factor merge hierarchically with identical
    output (TezMerger computeBytesInMerges semantics)."""
    from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs
    pairs = random_pairs(600, seed=21)
    runs = []
    for i in range(0, 600, 60):   # 10 runs
        s = DeviceSorter(num_partitions=2)
        for k, v in pairs[i:i + 60]:
            s.write(k, v)
        runs.append(s.flush())
    one_pass = merge_sorted_runs(runs, 2, 16)
    multi = merge_sorted_runs(runs, 2, 16, merge_factor=3)
    assert list(one_pass.batch.iter_pairs()) == \
        list(multi.batch.iter_pairs())


def test_async_sortmaster_matches_sync():
    """Background span sorting produces the same result as inline."""
    from tez_tpu.ops.sorter import DeviceSorter
    pairs = random_pairs(2500, seed=22)
    outs = []
    for threads in (0, 2):
        s = DeviceSorter(num_partitions=3, span_budget_bytes=4096,
                         sort_threads=threads)
        for k, v in pairs:
            s.write(k, v)
        outs.append(s.flush())
    assert list(outs[0].batch.iter_pairs()) == \
        list(outs[1].batch.iter_pairs())


def test_pallas_fnv_matches_reference_kernel():
    """Pallas FNV hash (interpret mode on CPU) == the XLA kernel == the host
    partitioner."""
    from tez_tpu.ops.pallas_kernels import hash_partition_pallas
    pairs = random_pairs(700, seed=31, max_key=24)
    b = KVBatch.from_pairs(pairs)
    klens = b.key_offsets[1:] - b.key_offsets[:-1]
    w = 1 << max(2, (int(klens.max()) - 1).bit_length())
    mat, lengths = pad_to_matrix(b.key_bytes, b.key_offsets, w)
    golden = device.hash_partition(mat, lengths, 5)
    got = hash_partition_pallas(mat, lengths, 5, interpret=True)
    np.testing.assert_array_equal(got, golden)


def test_custom_comparator_sorter_and_merge():
    """Comparator-as-normalizer: ReverseByteKeyComparator sorts descending;
    merge honors the same order (reference: tez.runtime.key.comparator.class
    raw comparators, expressed as key normalization)."""
    from tez_tpu.library.comparators import ReverseByteKeyComparator
    from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs
    norm = ReverseByteKeyComparator().normalize
    keys = [b"aaaa", b"zzzz", b"mmmm", b"bbbb", b"yyyy"]
    s = DeviceSorter(num_partitions=1, key_normalizer=norm)
    for k in keys:
        s.write(k, b"v")
    run = s.flush()
    got = [k for k, _v in run.batch.iter_pairs()]
    assert got == sorted(keys, reverse=True)       # descending
    # merge two descending runs stays descending
    s2 = DeviceSorter(num_partitions=1, key_normalizer=norm)
    for k in (b"cccc", b"xxxx"):
        s2.write(k, b"v")
    merged = merge_sorted_runs([run, s2.flush()], 1, 16, key_normalizer=norm)
    got = [k for k, _v in merged.batch.iter_pairs()]
    assert got == sorted(keys + [b"cccc", b"xxxx"], reverse=True)


def test_custom_comparator_long_keys_tiebreak():
    """Keys longer than the device prefix width still order exactly under a
    normalizer (the host tie-break pass compares NORMALIZED keys)."""
    from tez_tpu.library.comparators import ReverseByteKeyComparator
    from tez_tpu.ops.sorter import DeviceSorter
    norm = ReverseByteKeyComparator().normalize
    base = b"p" * 20     # beyond the 16-byte prefix
    keys = [base + suf for suf in (b"a", b"c", b"b", b"e", b"d")]
    s = DeviceSorter(num_partitions=1, key_width=16, key_normalizer=norm)
    for k in keys:
        s.write(k, b"v")
    got = [k for k, _v in s.flush().batch.iter_pairs()]
    assert got == sorted(keys, reverse=True)


def test_custom_comparator_multi_span_flush():
    """Comparator order survives the span-spill + final-merge path (a tiny
    span budget forces multiple spans; regression: flush() once merged by
    raw bytes, undoing the comparator)."""
    from tez_tpu.library.comparators import ReverseByteKeyComparator
    from tez_tpu.ops.sorter import DeviceSorter
    norm = ReverseByteKeyComparator().normalize
    keys = [f"k{i:03d}".encode() for i in range(16)]
    s = DeviceSorter(num_partitions=1, key_normalizer=norm,
                     span_budget_bytes=64)   # ~3 records per span
    for k in keys:
        s.write(k, b"v")
    assert s.num_spills > 1, "test must exercise the multi-span merge"
    got = [k for k, _v in s.flush().batch.iter_pairs()]
    assert got == sorted(keys, reverse=True)


def _first_prun_blob(path):
    """First length-prefixed Run blob inside a partition-indexed spill file
    (container header, then [u64 len][blob]...)."""
    import struct
    from tez_tpu.ops.runformat import PR_MAGIC
    data = open(path, "rb").read()
    assert data.startswith(PR_MAGIC)
    off = len(PR_MAGIC)
    (blob_len,) = struct.unpack_from("<Q", data, off)
    return data[off + 8:off + 8 + blob_len]


def test_spill_compression_conf(tmp_path):
    """Compressed spills: Run blobs carry the codec flag; reads are
    transparent (self-describing header, reference: IFile codec)."""
    import os
    import struct
    from tez_tpu.ops.runformat import MAGIC, PR_MAGIC
    from tez_tpu.ops.sorter import DeviceSorter
    spill = str(tmp_path)
    s = DeviceSorter(num_partitions=2, span_budget_bytes=4096,
                     mem_budget_bytes=1, spill_dir=spill, spill_codec="zlib")
    for i in range(2000):
        s.write(f"key{i % 20:03d}".encode(), b"v" * 16)
    files = [f for f in os.listdir(spill) if f.endswith(".prun")]
    assert files, "nothing spilled"
    blob = _first_prun_blob(os.path.join(spill, files[0]))
    assert blob.startswith(MAGIC)
    assert blob[len(MAGIC)] == 1      # codec flag = compressed
    total = sum(os.path.getsize(os.path.join(spill, f)) for f in files)
    run = s.flush()
    assert run.batch.num_records == 2000
    # compressed spill should beat the raw size for this repetitive data
    raw = 2000 * (6 + 16)
    assert total < raw


def test_compress_conf_wired_end_to_end(tmp_path):
    """tez.runtime.compress travels through the edge payload into the sorter
    spill path (and an unsupported codec errors loudly)."""
    import collections
    from tez_tpu.examples import ordered_wordcount
    from tez_tpu.ops.runformat import MAGIC
    corpus = tmp_path / "in.txt"
    # unique words -> ~1.5MB of sorter payload, over the 1MiB span budget
    with open(corpus, "w") as fh:
        for i in range(60000):
            fh.write(f"uniqueword{i:06d} ")
    spill_dir = str(tmp_path / "spill")
    out = str(tmp_path / "out")
    from tez_tpu.client.tez_client import TezClient
    conf = {"tez.staging-dir": str(tmp_path / "s"),
            "tez.runtime.io.sort.mb": 1,
            "tez.runtime.compress": True,
            "tez.runtime.tpu.host.spill.dir": spill_dir}
    with TezClient.create("compress-e2e", conf) as client:
        dag = ordered_wordcount.build_dag([str(corpus)], out,
                                          tokenizer_parallelism=1)
        dag_client = client.submit_dag(dag)
        state = dag_client.wait_for_completion().state.name
        final = dag_client.get_dag_status(with_counters=True)
    assert state == "SUCCEEDED"
    # spill files are consumed (and removed) by the streaming final merge,
    # so compression is proven by the byte counters: actual disk writes
    # (compressed) must undercut the logical spilled KV payload
    tc = final.counters.to_dict().get("TaskCounter", {})
    spilled_records = tc.get("SPILLED_RECORDS", 0)
    host_spill = tc.get("HOST_SPILL_BYTES", 0)
    logical = tc.get("OUTPUT_BYTES", 0)
    assert spilled_records > 0, "span spill never engaged"
    assert host_spill > 0
    assert host_spill < logical, (host_spill, logical)


def test_codec_registry_zstd_roundtrip(tmp_path):
    """zstd codec: self-describing flag 2; roundtrip byte-identical;
    lz4 (absent in this image) errors loudly instead of silently
    uncompressing (reference: pluggable Hadoop codecs behind
    tez.runtime.compress.codec)."""
    import numpy as np
    import pytest
    pytest.importorskip("zstandard", reason="zstd wheel absent")
    from tez_tpu.ops.runformat import (KVBatch, MAGIC, Run, resolve_codec)
    batch = KVBatch.from_pairs(
        [(f"k{i % 7}".encode(), b"payload" * 8) for i in range(500)])
    run = Run(batch, np.array([0, 250, 500], dtype=np.int64))
    for codec, flag in ((None, 0), ("zlib", 1), ("zstd", 2)):
        blob = run.to_bytes(codec)
        assert blob[len(MAGIC)] == flag
        back = Run.from_bytes(blob)
        assert list(back.batch.iter_pairs()) == list(batch.iter_pairs())
        assert np.array_equal(back.row_index, run.row_index)
    assert len(run.to_bytes("zstd")) < len(run.to_bytes(None))
    with pytest.raises(ValueError, match="lz4"):
        run.to_bytes("lz4")
    with pytest.raises(ValueError, match="unsupported"):
        resolve_codec("snappy")


def test_zstd_conf_through_sorter(tmp_path):
    import os
    import pytest
    pytest.importorskip("zstandard", reason="zstd wheel absent")
    from tez_tpu.ops.runformat import MAGIC
    from tez_tpu.ops.sorter import DeviceSorter
    spill = str(tmp_path)
    s = DeviceSorter(num_partitions=2, span_budget_bytes=512,
                     mem_budget_bytes=1, spill_dir=spill, spill_codec="zstd")
    for i in range(200):
        s.write(f"key{i % 20:03d}".encode(), b"v" * 16)
    blob = _first_prun_blob(os.path.join(spill, os.listdir(spill)[0]))
    assert blob[len(MAGIC)] == 2      # zstd flag
    run = s.flush()
    assert run.batch.num_records == 200


def test_device_resident_span_and_merge():
    """Resident path (VERDICT r1 item 4): span sort keeps sorted key lanes
    on device, partition slicing preserves the view, and the consumer merge
    runs off those views without re-uploading — byte-identical to the host
    merge."""
    import numpy as np
    from tez_tpu.ops import device
    from tez_tpu.ops.runformat import KVBatch
    from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs

    rng = np.random.default_rng(42)
    num_partitions = 3
    producer_runs = []
    golden_rows = {p: [] for p in range(num_partitions)}
    for prod in range(3):
        s = DeviceSorter(num_partitions=num_partitions, key_width=16,
                         device_min_records=0)   # force the resident path
        pairs = []
        for i in range(400):
            k = f"k{rng.integers(0, 120):04d}".encode()   # <= 16B: resident
            v = f"v{prod}_{i}".encode()
            pairs.append((k, v))
            s.write(k, v)
        run = s.flush()
        assert run.batch.dev_keys is not None, "span sort not resident"
        producer_runs.append((run, pairs))
    # golden: per partition, concat producer-partition slices then stable
    # sort by key (equal keys keep producer order)
    from tez_tpu.library.partitioners import _stable_hash
    for run, pairs in producer_runs:
        per_part = {p: [] for p in range(num_partitions)}
        for k, v in pairs:
            per_part[_stable_hash(k) % num_partitions].append((k, v))
        for p in range(num_partitions):
            golden_rows[p].append(sorted(per_part[p], key=lambda kv: kv[0]))
    for p in range(num_partitions):
        slices = [run.partition(p) for run, _ in producer_runs]
        for sl in slices:
            assert sl.dev_keys is not None, "partition slice lost the view"
        from tez_tpu.ops.runformat import Run
        runs = [Run(sl, np.array([0, sl.num_records], np.int64))
                for sl in slices]
        merged = merge_sorted_runs(runs, 1, 16, engine="device")
        got = list(merged.batch.iter_pairs())
        expect = []
        rows = [list(r) for r in golden_rows[p]]
        import heapq
        expect = [kv for kv, _, _ in heapq.merge(
            *[[(kv, i, j) for j, kv in enumerate(r)]
              for i, r in enumerate(rows)],
            key=lambda t: (t[0][0], t[1], t[2]))]
        assert got == expect, f"partition {p} merge mismatch"


def test_resident_view_dropped_on_serialization():
    import numpy as np
    import pickle
    from tez_tpu.ops.runformat import KVBatch, Run
    from tez_tpu.ops.sorter import DeviceSorter
    s = DeviceSorter(num_partitions=2, device_min_records=0)
    for i in range(50):
        s.write(f"k{i:02d}".encode(), b"v")
    run = s.flush()
    assert run.batch.dev_keys is not None
    back = Run.from_bytes(run.to_bytes())
    assert back.batch.dev_keys is None
    assert pickle.loads(pickle.dumps(run.batch)).dev_keys is None
    assert list(back.batch.iter_pairs()) == list(run.batch.iter_pairs())


def test_long_keys_fall_back_to_exact_path():
    """Keys beyond the configured width take the matrix path with host
    tie-break — still byte-exact."""
    import numpy as np
    from tez_tpu.ops.sorter import DeviceSorter
    s = DeviceSorter(num_partitions=1, key_width=8)
    keys = [b"prefix__" + bytes([c]) * 4 for c in (3, 1, 2)] + [b"prefix__"]
    for k in keys:
        s.write(k, b"v")
    run = s.flush()
    assert run.batch.dev_keys is None   # not resident-eligible
    got = [k for k, _ in run.batch.iter_pairs()]
    assert got == sorted(keys)


def test_resident_merge_mixed_lane_widths():
    """Producers whose spans saw different max key lengths produce device
    views with different lane counts; the merge widens narrow views with
    zero lanes on device and stays byte-exact."""
    import numpy as np
    from tez_tpu.ops.runformat import Run
    from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs
    runs = []
    all_keys = []
    for prod, klen in enumerate((4, 12)):      # 1 lane vs 3 lanes
        s = DeviceSorter(num_partitions=1, key_width=16,
                         device_min_records=0)
        for i in range(120):
            k = f"{i % 37:0{klen}d}".encode()
            all_keys.append((k, prod, i))
            s.write(k, f"v{prod}".encode())
        run = s.flush()
        assert run.batch.dev_keys is not None
        runs.append(run)
    assert runs[0].batch.dev_keys[0].shape[1] != \
        runs[1].batch.dev_keys[0].shape[1]
    merged = merge_sorted_runs(runs, 1, 16, engine="device")
    got = [k for k, _ in merged.batch.iter_pairs()]
    assert got == sorted(got) and len(got) == 240
    assert sorted(got) == sorted(k for k, _, _ in all_keys)


def test_encode_keys_device_parity():
    """Device ragged->lanes encode == host encode (keycodec twins)."""
    import numpy as np
    from tez_tpu.ops.keycodec import encode_keys, encode_keys_device
    rng = np.random.default_rng(3)
    # lengths up to 40 so every width below has over-width keys (the
    # mask-at-width-vs-rounded-lanes distinction only shows then)
    rows = [rng.integers(97, 123, rng.integers(0, 41), dtype=np.int64)
            .astype(np.uint8) for _ in range(500)]
    kb = np.concatenate([r for r in rows if len(r)] or
                        [np.zeros(0, np.uint8)])
    ko = np.cumsum([0] + [len(r) for r in rows]).astype(np.int64)
    for width in (4, 16, 31):
        lanes_h, lens_h = encode_keys(kb, ko, width)
        lanes_d, lens_d = encode_keys_device(kb, ko, width)
        assert np.array_equal(lanes_h, np.asarray(lanes_d)), width
        assert np.array_equal(lens_h.astype(np.int64),
                              np.asarray(lens_d).astype(np.int64)), width


def test_native_wordcount_aggregator_matches_counter():
    """Fused native tokenize+count == collections.Counter over bytes.split()
    (the WordCount map task's whole data plane in one C pass)."""
    from collections import Counter
    from tez_tpu.ops.native import WordCountAggregator
    agg = WordCountAggregator.create()
    if agg is None:
        import pytest
        pytest.skip("native lib unavailable")
    chunks = [b"the cat\tsat  on\nthe mat\n", b"", b"mat cat mat\r\nthe\x0bend\n"]
    for c in chunks:
        agg.feed(c)
    kb, ko, counts = agg.emit()
    agg.close()
    got = {bytes(kb[ko[i]:ko[i + 1]]): int(counts[i])
           for i in range(len(counts))}
    assert got == dict(Counter(b"".join(chunks).split()))


def test_native_hash_sum_matches_python():
    import numpy as np
    from tez_tpu.ops.native import hash_sum_native
    rng = np.random.default_rng(5)
    keys = [f"k{rng.integers(0, 50)}".encode() for _ in range(3000)]
    vals = rng.integers(-100, 100, 3000).astype(np.int64)
    offsets = np.zeros(3001, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    kb = np.frombuffer(b"".join(keys), dtype=np.uint8)
    res = hash_sum_native(kb, offsets, vals)
    if res is None:
        import pytest
        pytest.skip("native lib unavailable")
    first_idx, sums = res
    golden: dict = {}
    order = []
    for k, v in zip(keys, vals.tolist()):
        if k not in golden:
            golden[k] = 0
            order.append(k)
        golden[k] += v
    assert [keys[i] for i in first_idx.tolist()] == order
    assert {keys[i]: int(s) for i, s in zip(first_idx, sums)} == golden


def test_presort_hash_combine_shrinks_sort_and_keeps_result():
    """With a sum combiner and long values, duplicate keys collapse BEFORE
    the device sort (COMBINE_* counters record it) and the run equals the
    post-sort-combine result."""
    from tez_tpu.common.counters import TaskCounter, TezCounters
    from tez_tpu.ops.serde import VarLongSerde
    serde = VarLongSerde()
    words = [f"w{i % 7}".encode() for i in range(5000)]
    counters = TezCounters()
    sorter = DeviceSorter(num_partitions=2, combiner=sum_long_combiner,
                          counters=counters)
    for w in words:
        sorter.write(w, serde.to_bytes(1))
    run = sorter.flush()
    got = {k: serde.from_bytes(v) for k, v in run.batch.iter_pairs()}
    from collections import Counter
    assert got == {k: c for k, c in Counter(words).items()}
    snap = counters.to_dict()
    combine_in = sum(g.get("COMBINE_INPUT_RECORDS", 0)
                     for g in snap.values())
    combine_out = sum(g.get("COMBINE_OUTPUT_RECORDS", 0)
                      for g in snap.values())
    assert combine_in == 5000 and combine_out == 7


def test_pre_combined_span_skips_hash_combine():
    """A span made of ONE pre_combined batch (the fused tokenize+count
    aggregator's promise: keys already unique) must skip the pre-sort hash
    pass entirely — COMBINE_INPUT_RECORDS stays 0 (ADVICE r3: the skip
    logic was dead because no emitter set the flag)."""
    import numpy as np

    from tez_tpu.common.counters import TezCounters
    from tez_tpu.ops.runformat import KVBatch
    from tez_tpu.ops.serde import VarLongSerde
    serde = VarLongSerde()
    keys = [f"w{i:04d}".encode() for i in range(512)]
    ko = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=ko[1:])
    kb = np.frombuffer(b"".join(keys), dtype=np.uint8).copy()
    vb = np.frombuffer(b"".join(serde.to_bytes(i + 1) for i in
                                range(len(keys))), dtype=np.uint8).copy()
    vo = np.arange(len(keys) + 1, dtype=np.int64) * 8
    counters = TezCounters()
    sorter = DeviceSorter(num_partitions=2, combiner=sum_long_combiner,
                          counters=counters)
    sorter.write_batch(KVBatch(kb, ko, vb, vo, pre_combined=True))
    run = sorter.flush()
    got = {k: serde.from_bytes(v) for k, v in run.batch.iter_pairs()}
    assert got == {k: i + 1 for i, k in enumerate(keys)}
    snap = counters.to_dict()
    assert sum(g.get("COMBINE_INPUT_RECORDS", 0)
               for g in snap.values()) == 0


def test_owc_reference_proxy_matches_golden():
    """The C++ OrderedWordCount reference-semantics proxy (the external
    E2E baseline, BASELINE.md protocol) produces the exact word->count
    map, count-sorted output."""
    import collections
    from tez_tpu.ops.native import owc_proxy
    text = (b"tick tock tick boom tick tock\n" * 3000 +
            b"quux tock\n" * 1500)
    res = owc_proxy(text, 4, 4)
    if res is None:
        import pytest as _pytest
        _pytest.skip("native lib unavailable")
    secs, out = res
    golden = collections.Counter(text.split())
    got = {}
    prev = -1
    for line in out.decode().splitlines():
        w, c = line.rsplit("\t", 1)
        got[w.encode()] = int(c)
        assert int(c) >= prev
        prev = int(c)
    assert got == dict(golden)
    assert secs > 0
