"""End-to-end scrape smoke (``make metrics-smoke``): boot a real session
AM with the web UI on, run one DAG, then validate every exposition
surface against its strict contract — /metrics through the golden
parser, /metrics.json structurally, /doctor/live through ``graft top``'s
pure renderer.  Fast and non-slow: this is the tier-1 guard that the
live ops plane actually serves.
"""
import json
import urllib.request

from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Vertex
from tez_tpu.obs.exposition import parse_exposition
from tez_tpu.tools import top


def _get(url):
    # generous: the AM web thread competes with the whole suite's
    # threads under full-suite load
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read().decode("utf-8")


def test_metrics_smoke(tmp_path):
    c = TezClient.create("metricsmoke", {
        "tez.staging-dir": str(tmp_path / "s"),
        "tez.am.web.enabled": True,
        # a sampler tick lands between submit and scrape without sleeps
        "tez.am.metrics.sample-period-ms": 25.0,
    }).start()
    try:
        dag = DAG.create("smokedag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 2))
        st = c.submit_dag(dag).wait_for_completion(timeout=180)
        assert st.state.name == "SUCCEEDED"
        am = c.framework_client.am
        url = am.web_ui.url

        # -- GET /metrics: strict Prometheus 0.0.4 ------------------------
        text = _get(url + "metrics")
        fams = parse_exposition(text)
        assert "tez_latency_am_heartbeat_rtt_ms" in fams
        assert any(info["type"] == "histogram" for info in fams.values())
        assert "tez_counter" in fams

        # -- GET /metrics.json: rows, windows, accounting -----------------
        # the 25ms sampler thread can be starved under full-suite load on
        # a small box: wait (bounded) for its first tick, then assert
        import time
        deadline = time.time() + 60
        body = json.loads(_get(url + "metrics.json?window=30"))
        while body["accounting"]["samples"] < 1 and time.time() < deadline:
            time.sleep(0.05)
            body = json.loads(_get(url + "metrics.json?window=30"))
        assert body["window_s"] == 30.0
        assert body["histograms"] and body["gauges"]
        series = {r["series"] for r in body["histograms"]}
        assert "am.heartbeat.rtt" in series
        acct = body["accounting"]
        assert acct["samples"] >= 1
        assert acct["scrape_errors"] == 0
        assert acct["collector_errors"] == 0
        # the sampler has ticked, so windowed aggregates are attached
        assert any("window" in r for r in body["histograms"])

        # -- drill-down: stream filter keeps only labeled series ----------
        empty = json.loads(_get(url + "metrics.json?stream=nosuch"))
        assert empty["histograms"] == [] and empty["gauges"] == []

        # -- GET /doctor/live + graft top ---------------------------------
        live = json.loads(_get(url + "doctor/live?window=30"))
        assert live["sampler"]["enabled"]
        assert live["sampler"]["ticks"] >= 1
        assert set(live["planes"]["busy_ms"]) >= {"admission", "store"}
        assert "queue_depth" in live
        frame = top.render(live)
        assert "graft top" in frame
        assert "rings:" in frame.splitlines()[-1]
        # the scraping path agrees with the pure renderer's input
        assert top.render(top.fetch(url, window_s=30)) .splitlines()[0] \
            == frame.splitlines()[0]
    finally:
        c.stop()
    # the scrapes themselves must not have dirtied scrape accounting
    from tez_tpu.obs import timeseries
    assert timeseries.registry().accounting()["scrape_errors"] == 0
