"""Async double-buffered device plane (ops/async_stage.py,
ops/device_pipeline.py, DeviceSorter pipeline integration).

The scheduler's contract is asserted against a FAKE clock and thread
events, never wall time: overlap (span k+1's encode starts before span k
completes), the dispatch-ahead depth bound, deterministic coalescing, and
out-of-order completion under the device.dispatch.delay fault point.
"""
import threading

import numpy as np
import pytest

from tez_tpu.common import faults
from tez_tpu.common.faults import parse_spec
from tez_tpu.ops.async_stage import AsyncSpanPipeline, overlap_pairs


class LogicalClock:
    """Thread-safe monotone counter: every _mark gets a unique tick, so
    event ordering is exact and wall-time free."""

    def __init__(self):
        self._t = 0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            self._t += 1
            return self._t


def test_overlap_witness_fake_clock():
    """span 1's encode must start while span 0 is still in flight: span 0's
    readback is held on an event that only span 1's encode sets."""
    span1_encoding = threading.Event()

    def encode(p):
        if p == 1:
            span1_encoding.set()
        return p

    def readback(inflight, ids):
        if ids == (0,):
            assert span1_encoding.wait(timeout=10.0), \
                "span 1 never started encoding while span 0 was in flight"
        return inflight

    pipe = AsyncSpanPipeline(
        dispatch_fn=lambda s: s, readback_fn=readback, encode_fn=encode,
        depth=2, readback_workers=2, clock=LogicalClock(), instrument=True)
    for i in range(3):
        pipe.submit(i, i)
    res = pipe.drain()
    assert res == {0: 0, 1: 1, 2: 2}
    pairs = overlap_pairs(pipe.events)
    assert ((0,), (1,)) in pairs, f"no overlap witnessed: {pipe.events}"
    assert pipe.stats.max_in_flight <= 2


def test_depth_bound_never_exceeded():
    """depth=1 serializes groups: in-flight never exceeds the bound and no
    encode starts while an earlier group is in flight."""
    release = threading.Event()
    seen = []

    def readback(inflight, ids):
        seen.append(ids)
        if len(seen) == 1:
            release.wait(timeout=10.0)
        return inflight

    pipe = AsyncSpanPipeline(
        dispatch_fn=lambda s: s, readback_fn=readback,
        depth=1, readback_workers=2, clock=LogicalClock(), instrument=True)
    for i in range(4):
        pipe.submit(i, i)
    release.set()
    pipe.drain()
    assert pipe.stats.max_in_flight == 1
    assert overlap_pairs(pipe.events) == []   # depth=1: no overlap possible


def test_paused_coalesce_deterministic():
    dispatched = []

    def dispatch(staged):
        dispatched.append(staged)
        return staged

    pipe = AsyncSpanPipeline(
        dispatch_fn=dispatch, readback_fn=lambda s, ids: sum(s),
        coalesce_fn=lambda staged: [x for s in staged for x in s],
        records_fn=len, coalesce_records=100, paused=True)
    for i in range(4):
        pipe.submit(i, [i] * 10, coalesce=True)
    pipe.resume()
    res = pipe.drain()
    assert len(dispatched) == 1          # every span in ONE dispatch
    assert pipe.stats.coalesced_groups == 1
    assert res == {i: sum([0] * 10 + [1] * 10 + [2] * 10 + [3] * 10)
                   for i in range(4)}


def test_coalesce_budget_respected():
    pipe = AsyncSpanPipeline(
        dispatch_fn=lambda s: s, readback_fn=lambda s, ids: len(ids),
        coalesce_fn=lambda staged: staged, records_fn=len,
        coalesce_records=20, paused=True)
    for i in range(4):
        pipe.submit(i, [i] * 10, coalesce=True)
    pipe.resume()
    pipe.drain()
    assert pipe.stats.dispatched == 2    # 4 x 10 records under a 20 budget
    assert pipe.stats.coalesced_groups == 2


def test_stage_error_propagates_and_poisons():
    def dispatch(staged):
        raise ValueError("boom at dispatch")

    pipe = AsyncSpanPipeline(dispatch_fn=dispatch,
                             readback_fn=lambda s, ids: s)
    pipe.submit(0, 0)
    with pytest.raises(ValueError, match="boom at dispatch"):
        pipe.drain()
    with pytest.raises(RuntimeError, match="pipeline failed"):
        pipe.submit(1, 1)


# -- device scheduler (needs jax; tier-1 runs with JAX_PLATFORMS=cpu) -------

def _mk_ragged(n, key_len, seed):
    rng = np.random.default_rng(seed)
    kb = rng.integers(0, 256, n * key_len, dtype=np.int64).astype(np.uint8)
    ko = np.arange(n + 1, dtype=np.int64) * key_len
    vb = rng.integers(0, 256, n * 8, dtype=np.int64).astype(np.uint8)
    return kb, ko, vb


def test_scheduler_matches_sync_kernel():
    """submit_ragged through the async plane == the sync device_shuffle_sort
    over the concatenated spans (stable concat-sort == merge of span sorts)."""
    from tez_tpu.ops.device_pipeline import (DeviceSpanScheduler,
                                             device_shuffle_sort)
    from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
    key_len, nspans, per = 8, 3, 400
    spans = [_mk_ragged(per, key_len, s) for s in range(nspans)]
    sched = DeviceSpanScheduler(num_partitions=3, key_width=key_len,
                                coalesce_records=nspans * per,
                                paused=True)
    for sid, (kb, ko, vb) in enumerate(spans):
        sched.submit_ragged(sid, kb, ko, vb, 8)
    sched.resume()
    res = sched.results()
    assert all(res[i] is res[0] for i in range(nspans))
    sp_a, lanes_a, vals_a, perm_a, counts_a, n_a = res[0]

    kb = np.concatenate([s[0] for s in spans])
    ko = np.arange(nspans * per + 1, dtype=np.int64) * key_len
    vb = np.concatenate([s[2] for s in spans])
    n = nspans * per
    mat, lengths = pad_to_matrix(kb, ko, key_len)
    lanes = matrix_to_lanes(mat)
    hash_w = 1 << max(2, (key_len - 1).bit_length())
    hmat, hlens = pad_to_matrix(kb, ko, hash_w)
    vals = np.ascontiguousarray(vb.reshape(n, 8)).view(np.uint32)
    out = device_shuffle_sort(lanes, lengths.astype(np.int64), vals, hmat,
                              hlens.astype(np.int32), 3)
    sp_s, lanes_s, vals_s, perm_s, counts_s = [np.asarray(x) for x in out]
    assert n_a == n
    np.testing.assert_array_equal(counts_a, counts_s)
    np.testing.assert_array_equal(perm_a[:n], perm_s[:n])
    np.testing.assert_array_equal(lanes_a[:n], lanes_s[:n])
    np.testing.assert_array_equal(vals_a[:n], vals_s[:n])


def test_recompile_count_bounded_within_bucket():
    """Varying span sizes inside one padding bucket must reuse ONE compiled
    program — the jit cache may grow by at most one entry."""
    from tez_tpu.ops.device_pipeline import (DeviceSpanScheduler,
                                             _fused_pipeline)
    key_len = 8

    def run(n, seed):
        kb, ko, vb = _mk_ragged(n, key_len, seed)
        sched = DeviceSpanScheduler(num_partitions=2, key_width=key_len)
        sched.submit_ragged(0, kb, ko, vb, 8)
        return sched.results()

    run(600, 0)                          # bucket warm (and maybe compile)
    cache0 = _fused_pipeline._cache_size()
    for i, n in enumerate((520, 700, 1000, 1024)):   # same padding bucket
        run(n, i + 1)
    assert _fused_pipeline._cache_size() - cache0 <= 1, \
        "same-bucket spans recompiled the fused pipeline"


def _mk_batch(n, seed):
    from tez_tpu.ops.runformat import KVBatch
    rng = np.random.default_rng(seed)
    keys = [b"k%08d" % i for i in rng.integers(0, 500, n)]
    vals = [b"v%06d" % i for i in rng.integers(0, 999999, n)]
    kb = np.frombuffer(b"".join(keys), dtype=np.uint8)
    ko = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
    vb = np.frombuffer(b"".join(vals), dtype=np.uint8)
    vo = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
    return KVBatch(kb, ko, vb, vo)


def _spill_sorter(depth):
    from tez_tpu.ops.sorter import DeviceSorter
    spills = {}
    s = DeviceSorter(num_partitions=4, engine="device",
                     device_min_records=0, key_width=16,
                     span_budget_bytes=20_000, pipeline_depth=depth)
    s.on_spill = lambda run, sid: spills.update(
        {sid: (run.batch.key_bytes.tobytes(), run.batch.val_bytes.tobytes(),
               run.row_index.tobytes())})
    return s, spills


def test_out_of_order_completion_spills_bit_exact():
    """device.dispatch.delay holds span 0's completion while later spans
    drain past it: completion is out of order, yet every spill carries its
    correct spill id and payload — bit-exact vs the fault-free sync engine."""
    sync, sync_spills = _spill_sorter(depth=0)
    for i in range(4):
        sync.write_batch(_mk_batch(1000, i))
    assert sync.flush_run() is None
    assert sorted(sync_spills) == [0, 1, 2, 3]

    faults.install("t", parse_spec(
        "device.dispatch.delay:delay:ms=400,n=1,match=span=0"))
    try:
        apipe, aspills = _spill_sorter(depth=2)
        for i in range(4):
            apipe.write_batch(_mk_batch(1000, i))
        assert apipe.flush_run() is None
        # on_spill fires in completion order; dict insertion order keeps it
        order = list(aspills)
    finally:
        faults.install("t", [])
    assert order[-1] == 0, f"span 0 was not delayed past the rest: {order}"
    assert aspills == sync_spills


def test_flush_reassembles_async_runs_in_spill_order():
    """Non-pipelined flush: runs complete out of order under the delay
    fault but the final merged output is bit-exact vs the sync engine."""
    from tez_tpu.ops.sorter import DeviceSorter

    def flush(depth, with_fault):
        if with_fault:
            faults.install("t", parse_spec(
                "device.dispatch.delay:delay:ms=400,n=1,match=span=0"))
        try:
            s = DeviceSorter(num_partitions=4, engine="device",
                             device_min_records=0, key_width=16,
                             span_budget_bytes=20_000, pipeline_depth=depth,
                             pipeline_coalesce_records=0)
            for i in range(4):
                s.write_batch(_mk_batch(1000, i))
            r = s.flush_run()
        finally:
            if with_fault:
                faults.install("t", [])
        return (r.batch.key_bytes.tobytes(), r.batch.val_bytes.tobytes(),
                r.row_index.tobytes())

    assert flush(2, True) == flush(0, False)


# -- failure containment: watchdog / failover / breaker / OOM ladder --------

import time  # noqa: E402

from tez_tpu.common.counters import TezCounters  # noqa: E402
from tez_tpu.ops.async_stage import (COUNTER_GROUP,  # noqa: E402
                                     CircuitBreaker)


class SettableClock:
    """Manually-advanced fake clock: watchdog deadlines are compared on the
    pipeline's injectable clock, so tests blow a deadline by advancing it —
    never by sleeping it out."""

    def __init__(self):
        self._t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self._t

    def advance(self, dt):
        with self._lock:
            self._t += dt


def test_failover_on_device_exception():
    """A device exception mid-dispatch re-routes JUST that group through
    failover_fn; the other spans stay on the device path and the pipeline
    never poisons."""
    def dispatch(staged):
        if staged == 1:
            raise ValueError("chip fault on span 1")
        return staged

    counters = TezCounters()
    pipe = AsyncSpanPipeline(
        dispatch_fn=dispatch, readback_fn=lambda s, ids: ("device", s),
        failover_fn=lambda ids, payloads: ("host", payloads[0]),
        breaker=CircuitBreaker(failures=100), counters=counters)
    for i in range(3):
        pipe.submit(i, i)
    res = pipe.drain()
    assert res == {0: ("device", 0), 1: ("host", 1), 2: ("device", 2)}
    assert pipe.stats.failovers == 1
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.failover.spans").value == 1
    assert fo.find_counter("device.failover.groups").value == 1


def test_watchdog_abandons_hung_readback_fake_clock():
    """A readback that never returns: the watchdog (deadline on the FAKE
    clock) abandons the attempt, fails the span over, and drain() returns
    in bounded wall time with every result present."""
    clock = SettableClock()
    hang = threading.Event()
    in_hang = threading.Event()
    failed_over = threading.Event()

    def readback(inflight, ids):
        if ids == (0,):
            in_hang.set()
            hang.wait(timeout=30.0)   # a hung D2H nobody will release
        return ("device", inflight)

    def failover(ids, payloads):
        failed_over.set()
        return ("host", payloads[0])

    pipe = AsyncSpanPipeline(
        dispatch_fn=lambda s: s, readback_fn=readback,
        failover_fn=failover, breaker=CircuitBreaker(failures=100),
        clock=clock, watchdog_readback_ms=1000)
    t_wall = time.monotonic()
    pipe.submit(0, 0)
    assert in_hang.wait(timeout=10.0)
    clock.advance(2.0)                # blow the 1000ms readback deadline
    assert failed_over.wait(timeout=10.0), "watchdog never fired"
    pipe.submit(1, 1)
    pipe.submit(2, 2)
    res = pipe.drain()
    wall = time.monotonic() - t_wall
    try:
        assert res == {0: ("host", 0), 1: ("device", 1), 2: ("device", 2)}
        assert pipe.stats.watchdog_fires == 1
        assert wall < 15.0, f"flush() not bounded by the watchdog: {wall:.1f}s"
    finally:
        hang.set()                    # release the abandoned daemon worker


def test_watchdog_abandons_hung_dispatch_and_drains_pending():
    """A dispatch that never returns wedges the staging thread itself: the
    watchdog must claim the hung group AND take over the queue, draining
    every not-yet-staged span through failover — drain() stays bounded."""
    clock = SettableClock()
    hang = threading.Event()
    in_hang = threading.Event()

    def dispatch(staged):
        if staged == 0:
            in_hang.set()
            hang.wait(timeout=30.0)   # staging thread stuck inside XLA
        return staged

    counters = TezCounters()
    pipe = AsyncSpanPipeline(
        dispatch_fn=dispatch, readback_fn=lambda s, ids: ("device", s),
        failover_fn=lambda ids, payloads: ("host", payloads[0]),
        breaker=CircuitBreaker(failures=100), counters=counters,
        clock=clock, watchdog_dispatch_ms=1000, paused=True)
    t_wall = time.monotonic()
    for i in range(4):
        pipe.submit(i, i)
    pipe.resume()
    assert in_hang.wait(timeout=10.0)
    clock.advance(2.0)                # blow the 1000ms dispatch deadline
    res = pipe.drain()
    wall = time.monotonic() - t_wall
    try:
        assert res == {i: ("host", i) for i in range(4)}
        assert pipe.stats.watchdog_fires == 1
        fo = counters.group(COUNTER_GROUP)
        assert fo.find_counter("device.watchdog.dispatch_fires").value == 1
        assert fo.find_counter("device.failover.drained").value == 3
        assert wall < 15.0, f"flush() not bounded when wedged: {wall:.1f}s"
    finally:
        hang.set()                    # release the abandoned staging thread


def test_breaker_trips_and_half_open_recovers_fake_clock():
    clock = SettableClock()
    br = CircuitBreaker(failures=2, cooldown_ms=1000, clock=clock)
    assert br.allow_device() and br.state == "closed"
    br.record_failure()
    assert br.state == "closed"       # below the consecutive threshold
    br.record_failure()
    assert br.state == "open" and br.trips == 1
    assert not br.allow_device()      # cooldown not elapsed
    clock.advance(1.1)
    assert br.allow_device()          # the half-open probe slot
    assert br.state == "half-open"
    assert not br.allow_device()      # only ONE probe at a time
    br.record_success()
    assert br.state == "closed" and br.recoveries == 1
    # a probe FAILURE re-opens immediately for another full cooldown
    br.record_failure()
    br.record_failure()
    assert br.state == "open" and br.trips == 2
    clock.advance(1.1)
    assert br.allow_device()
    br.record_failure()
    assert br.state == "open" and br.trips == 3
    assert not br.allow_device()


def test_breaker_open_short_circuits_before_device():
    """With the breaker open every group routes straight to the host
    engine — the dispatch fn (the chip) is never touched."""
    br = CircuitBreaker(failures=1, cooldown_ms=10_000,
                        clock=SettableClock())
    br.record_failure()               # open; fake clock never elapses it
    dispatched = []
    counters = TezCounters()
    pipe = AsyncSpanPipeline(
        dispatch_fn=lambda s: dispatched.append(s) or s,
        readback_fn=lambda s, ids: ("device", s),
        failover_fn=lambda ids, payloads: ("host", payloads[0]),
        breaker=br, counters=counters)
    for i in range(3):
        pipe.submit(i, i)
    res = pipe.drain()
    assert dispatched == []
    assert res == {i: ("host", i) for i in range(3)}
    assert counters.group(COUNTER_GROUP).find_counter(
        "device.breaker.short_circuits").value == 3


def test_oom_split_retry_before_host_failover():
    """RESOURCE_EXHAUSTED takes the split ladder FIRST: oom_retry_fn's
    (on-device) result completes the group, failover_fn is never called,
    and the split success re-arms the breaker."""
    failover_calls = []

    def dispatch(staged):
        if staged == 0:
            raise MemoryError("RESOURCE_EXHAUSTED: span too large")
        return staged

    br = CircuitBreaker(failures=2)
    counters = TezCounters()
    pipe = AsyncSpanPipeline(
        dispatch_fn=dispatch, readback_fn=lambda s, ids: ("device", s),
        failover_fn=lambda ids, payloads:
            failover_calls.append(ids) or ("host", payloads[0]),
        oom_retry_fn=lambda ids, payloads: ("split", payloads[0]),
        breaker=br, counters=counters)
    pipe.submit(0, 0)
    pipe.submit(1, 1)
    res = pipe.drain()
    assert res == {0: ("split", 0), 1: ("device", 1)}
    assert failover_calls == []       # the ladder stopped on-device
    assert pipe.stats.oom_splits == 1
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.oom.split_attempts").value == 1
    assert fo.find_counter("device.oom.split_success").value == 1
    assert br.state == "closed" and br.trips == 0


def test_oom_split_floor_falls_back_to_host():
    """When the split retry declines (floor reached — it raises), the
    group continues down the ladder to host failover."""
    def retry(ids, payloads):
        raise MemoryError("split floor reached")

    counters = TezCounters()
    pipe = AsyncSpanPipeline(
        dispatch_fn=lambda s: (_ for _ in ()).throw(
            MemoryError("RESOURCE_EXHAUSTED")),
        readback_fn=lambda s, ids: s,
        failover_fn=lambda ids, payloads: ("host", payloads[0]),
        oom_retry_fn=retry, breaker=CircuitBreaker(failures=100),
        counters=counters)
    pipe.submit(0, 0)
    res = pipe.drain()
    assert res == {0: ("host", 0)}
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.oom.split_attempts").value == 1
    assert fo.find_counter("device.oom.split_success").value == 0
    assert fo.find_counter("device.failover.spans").value == 1


def _flush_merged(depth, spec, **sorter_kw):
    """flush_run() a 4-span DeviceSorter under an optional fault spec;
    returns (merged-run bytes, counters)."""
    from tez_tpu.ops.sorter import DeviceSorter
    if spec:
        faults.install("t", parse_spec(spec))
    try:
        s = DeviceSorter(num_partitions=4, engine="device",
                         device_min_records=0, key_width=16,
                         span_budget_bytes=20_000, pipeline_depth=depth,
                         pipeline_coalesce_records=0, **sorter_kw)
        for i in range(4):
            s.write_batch(_mk_batch(1000, i))
        r = s.flush_run()
    finally:
        if spec:
            faults.install("t", [])
    return (r.batch.key_bytes.tobytes(), r.batch.val_bytes.tobytes(),
            r.row_index.tobytes()), s.counters


def test_sorter_oom_split_on_device_bit_exact():
    """One injected RESOURCE_EXHAUSTED dispatch (budget n=1): the span
    retries split in half ON DEVICE (the budget is spent, so the halves
    sort clean), the stable split-merge is bit-exact vs the fault-free
    sync engine, and host failover is never taken."""
    base, _ = _flush_merged(0, "")
    br = CircuitBreaker(failures=100)
    got, counters = _flush_merged(
        2, "device.dispatch.oom:fail:n=1,exc=runtime,match=span=0",
        split_min_bytes=1_000, breaker=br)
    assert got == base
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.oom.split_attempts").value == 1
    assert fo.find_counter("device.oom.split_success").value == 1
    assert fo.find_counter("device.failover.spans").value == 0
    assert br.trips == 0


def test_sorter_readback_failure_fails_over_bit_exact():
    """An injected readback crash re-sorts that span through the host
    engine; the merged flush stays bit-exact vs the sync engine."""
    base, _ = _flush_merged(0, "")
    br = CircuitBreaker(failures=100)
    got, counters = _flush_merged(
        2, "device.readback.fail:fail:n=1,exc=io,match=span=0", breaker=br)
    assert got == base
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.failover.spans").value == 1
    assert br.trips == 0


def test_engine_auto_width_routing():
    from tez_tpu.ops.sorter import _route_engine
    # narrow spans fall back to host ONLY when the caller opted in by
    # passing key bytes (auto engines)
    assert _route_engine("device", 10_000, 0, key_nbytes=100,
                         min_key_bytes=1 << 20) == "host"
    assert _route_engine("device", 10_000, 0, key_nbytes=1 << 21,
                         min_key_bytes=1 << 20) == "device"
    # explicit device engine never passes key_nbytes: no width rerouting
    assert _route_engine("device", 10_000, 0, key_nbytes=-1,
                         min_key_bytes=1 << 20) == "device"
    # record floor still applies first
    assert _route_engine("device", 10, 100, key_nbytes=1 << 21,
                         min_key_bytes=1 << 20) == "host"
    assert _route_engine("host", 10_000, 0) == "host"
