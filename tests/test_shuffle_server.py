"""DCN shuffle transport tests: server + fetcher + HMAC auth + security."""
import pytest

from tez_tpu.common.security import (ACLManager, DAGAccessControls,
                                     JobTokenSecretManager)
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.ops.sorter import DeviceSorter
from tez_tpu.shuffle.server import ShuffleFetcher, ShuffleServer
from tez_tpu.shuffle.service import ShuffleDataNotFound, ShuffleService


@pytest.fixture()
def served_run():
    service = ShuffleService()
    sorter = DeviceSorter(num_partitions=3)
    for i in range(100):
        sorter.write(f"k{i:03d}".encode(), f"v{i}".encode())
    run = sorter.flush()
    service.register("dagX/attempt_1/cons", -1, run)
    secrets = JobTokenSecretManager()
    server = ShuffleServer(secrets, service).start()
    yield server, secrets, run
    server.stop()


def test_fetch_roundtrip(served_run):
    server, secrets, run = served_run
    fetcher = ShuffleFetcher(secrets)
    for p in range(3):
        got = fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons",
                            -1, p)[0]
        assert list(got.iter_pairs()) == list(run.partition(p).iter_pairs())


def test_fetch_partition_range_keepalive(served_run):
    server, secrets, run = served_run
    fetcher = ShuffleFetcher(secrets)
    got = fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons",
                        -1, 0, 3)
    assert len(got) == 3
    total = sum(b.num_records for b in got)
    assert total == run.batch.num_records


def test_bad_hmac_rejected(served_run):
    server, secrets, _ = served_run
    wrong = JobTokenSecretManager(b"not-the-secret" * 2)
    fetcher = ShuffleFetcher(wrong, retries=1)
    with pytest.raises(PermissionError):
        fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons", -1, 0)
    assert server.auth_failures >= 1


def test_missing_data_not_found(served_run):
    server, secrets, _ = served_run
    fetcher = ShuffleFetcher(secrets)
    with pytest.raises(ShuffleDataNotFound):
        fetcher.fetch("127.0.0.1", server.port, "nope/nope", -1, 0)


def test_connection_refused_retries_then_raises():
    fetcher = ShuffleFetcher(JobTokenSecretManager(), retries=2,
                             backoff=0.01)
    with pytest.raises(ConnectionError, match="after 2 tries"):
        fetcher.fetch("127.0.0.1", 1, "x", -1, 0)  # port 1: refused


def test_acl_manager():
    acls = ACLManager("owner", DAGAccessControls(view_users=("alice",),
                                                 modify_users=()))
    assert acls.check_view_access("owner")
    assert acls.check_view_access("alice")
    assert not acls.check_view_access("mallory")
    assert not acls.check_modify_access("alice")
    open_acls = ACLManager("owner")
    assert open_acls.check_view_access("anyone")   # default view = '*'
    assert not open_acls.check_modify_access("anyone")


def test_token_hash_roundtrip():
    s = JobTokenSecretManager()
    h = s.compute_hash(b"msg")
    assert s.verify_hash(h, b"msg")
    assert not s.verify_hash(h, b"other")
    assert not JobTokenSecretManager().verify_hash(h, b"msg")
