"""DCN shuffle transport tests: server + fetcher + HMAC auth + security."""
import pytest

from tez_tpu.common.security import (ACLManager, DAGAccessControls,
                                     JobTokenSecretManager)
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.ops.sorter import DeviceSorter
from tez_tpu.shuffle.server import ShuffleFetcher, ShuffleServer
from tez_tpu.shuffle.service import ShuffleDataNotFound, ShuffleService


@pytest.fixture()
def served_run():
    service = ShuffleService()
    sorter = DeviceSorter(num_partitions=3)
    for i in range(100):
        sorter.write(f"k{i:03d}".encode(), f"v{i}".encode())
    run = sorter.flush()
    service.register("dagX/attempt_1/cons", -1, run)
    secrets = JobTokenSecretManager()
    server = ShuffleServer(secrets, service).start()
    yield server, secrets, run
    server.stop()


def test_fetch_roundtrip(served_run):
    server, secrets, run = served_run
    fetcher = ShuffleFetcher(secrets)
    for p in range(3):
        got = fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons",
                            -1, p)[0]
        assert list(got.iter_pairs()) == list(run.partition(p).iter_pairs())


def test_fetch_partition_range_keepalive(served_run):
    server, secrets, run = served_run
    fetcher = ShuffleFetcher(secrets)
    got = fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons",
                        -1, 0, 3)
    assert len(got) == 3
    total = sum(b.num_records for b in got)
    assert total == run.batch.num_records


def test_bad_hmac_rejected(served_run):
    server, secrets, _ = served_run
    wrong = JobTokenSecretManager(b"not-the-secret" * 2)
    fetcher = ShuffleFetcher(wrong, retries=1)
    with pytest.raises(PermissionError):
        fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons", -1, 0)
    assert server.auth_failures >= 1


def test_missing_data_not_found(served_run):
    server, secrets, _ = served_run
    fetcher = ShuffleFetcher(secrets)
    with pytest.raises(ShuffleDataNotFound):
        fetcher.fetch("127.0.0.1", server.port, "nope/nope", -1, 0)


def test_stale_epoch_fetch_fenced(served_run):
    """A fetch request stamped with a pre-restart AM epoch gets a 'fenced'
    reply (fatal, no retry); unstamped and current-epoch fetches still see
    the pre-crash data."""
    from tez_tpu.common import epoch as epoch_registry
    server, secrets, run = served_run
    epoch_registry.register("app_1_zfetch", 2)   # AM restarted: epoch 2 live
    stale = ShuffleFetcher(secrets, retries=1, epoch=1, app_id="app_1_zfetch")
    with pytest.raises(PermissionError, match="fenced"):
        stale.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons", -1, 0)
    # pre-crash shuffle data REMAINS fetchable by live/legacy readers
    for fetcher in (ShuffleFetcher(secrets),
                    ShuffleFetcher(secrets, epoch=2, app_id="app_1_zfetch")):
        got = fetcher.fetch("127.0.0.1", server.port, "dagX/attempt_1/cons",
                            -1, 0)[0]
        assert list(got.iter_pairs()) == list(run.partition(0).iter_pairs())


def test_connection_refused_retries_then_raises():
    fetcher = ShuffleFetcher(JobTokenSecretManager(), retries=2,
                             backoff=0.01)
    with pytest.raises(ConnectionError, match="after 2 tries"):
        fetcher.fetch("127.0.0.1", 1, "x", -1, 0)  # port 1: refused


def test_acl_manager():
    acls = ACLManager("owner", DAGAccessControls(view_users=("alice",),
                                                 modify_users=()))
    assert acls.check_view_access("owner")
    assert acls.check_view_access("alice")
    assert not acls.check_view_access("mallory")
    assert not acls.check_modify_access("alice")
    open_acls = ACLManager("owner")
    assert open_acls.check_view_access("anyone")   # default view = '*'
    assert not open_acls.check_modify_access("anyone")


def test_token_hash_roundtrip():
    s = JobTokenSecretManager()
    h = s.compute_hash(b"msg")
    assert s.verify_hash(h, b"msg")
    assert not s.verify_hash(h, b"other")
    assert not JobTokenSecretManager().verify_hash(h, b"msg")


def test_request_replay_on_new_connection_rejected(served_run):
    """A captured request (valid HMAC for its connection's nonce) must fail
    when replayed on a fresh connection: the new nonce changes the expected
    signature (SecureShuffleUtils full-request MAC + challenge binding)."""
    import json
    import socket
    import struct
    from tez_tpu.common.security import hash_from_request

    server, secrets, _ = served_run
    # capture leg: send a valid signed request and confirm it is accepted
    with socket.create_connection(("127.0.0.1", server.port)) as sk:
        fh = sk.makefile("rb")
        nonce1 = fh.read(16)
        captured = json.dumps({
            "path": "dagX/attempt_1/cons", "spill": -1,
            "partition_lo": 0, "partition_hi": 1,
            "hmac": hash_from_request(secrets, "dagX/attempt_1/cons", -1,
                                      0, 1, nonce1).hex(),
        }).encode()
        sk.sendall(struct.pack("<I", len(captured)) + captured)
        (hdr_len,) = struct.unpack("<I", fh.read(4))
        assert json.loads(fh.read(hdr_len))["status"] == "ok"
    # replay leg: same bytes on a NEW connection -> forbidden
    with socket.create_connection(("127.0.0.1", server.port)) as sk:
        fh = sk.makefile("rb")
        assert len(fh.read(16)) == 16          # fresh nonce
        sk.sendall(struct.pack("<I", len(captured)) + captured)
        (hdr_len,) = struct.unpack("<I", fh.read(4))
        header = json.loads(fh.read(hdr_len))
    assert header["status"] == "forbidden"
    assert server.auth_failures >= 1


def test_hmac_covers_partition_range(served_run):
    """Tampering partition_hi after signing must be rejected (the reference
    MACs the entire request URL; partial coverage let a 1-partition grant
    fetch the whole spill)."""
    import json
    import socket
    import struct
    from tez_tpu.common.security import hash_from_request

    server, secrets, _ = served_run
    with socket.create_connection(("127.0.0.1", server.port)) as sk:
        fh = sk.makefile("rb")
        nonce = fh.read(16)
        req = json.dumps({
            "path": "dagX/attempt_1/cons", "spill": -1,
            "partition_lo": 0, "partition_hi": 3,   # widened after signing
            "hmac": hash_from_request(secrets, "dagX/attempt_1/cons", -1,
                                      0, 1, nonce).hex(),
        }).encode()
        sk.sendall(struct.pack("<I", len(req)) + req)
        (hdr_len,) = struct.unpack("<I", fh.read(4))
        header = json.loads(fh.read(hdr_len))
    assert header["status"] == "forbidden"


def test_umbilical_handshake_not_replayable():
    """The raw handshake is challenge-response: a client that writes a
    fixed 32-byte signature (the pre-nonce protocol / a replayed capture)
    must be rejected."""
    from tez_tpu.am.umbilical_server import (authenticate_stream,
                                             client_handshake)

    secrets = JobTokenSecretManager()

    # legit handshake: run server and client against in-memory pipes
    import threading
    s2c_r, s2c_w = _pipe_pair()
    c2s_r, c2s_w = _pipe_pair()
    ok = {}
    t = threading.Thread(target=lambda: ok.__setitem__(
        "server", authenticate_stream(c2s_r, s2c_w, secrets, b"umbilical-hello")))
    t.start()
    client_handshake(s2c_r, c2s_w, secrets, b"umbilical-hello")
    t.join()
    assert ok["server"] is True

    # replay: feed the captured client reply to a NEW server handshake
    captured = bytes(c2s_w.captured)
    s2c_r2, s2c_w2 = _pipe_pair()
    c2s_r2, c2s_w2 = _pipe_pair()
    c2s_w2.write(captured[:32])   # replayed signature, ignores new nonce
    assert authenticate_stream(c2s_r2, s2c_w2, secrets,
                               b"umbilical-hello") is False


def _pipe_pair():
    """A blocking in-memory byte pipe exposing (reader, writer) file-likes;
    the writer also records everything written (for capture tests)."""
    import threading

    class _Chan:
        def __init__(self):
            self.buf = bytearray()
            self.captured = bytearray()
            self.cond = threading.Condition()

        def read(self, n):
            with self.cond:
                while len(self.buf) < n:
                    if not self.cond.wait(5.0):
                        return bytes(self.buf)   # timeout: short read
                out = bytes(self.buf[:n])
                del self.buf[:n]
                return out

        def write(self, b):
            with self.cond:
                self.buf.extend(b)
                self.captured.extend(b)
                self.cond.notify_all()

        def flush(self):
            pass

    ch = _Chan()
    return ch, ch


def test_fetch_session_many_fetches_one_connection(served_run):
    """FetchSession: one TCP connect + one nonce handshake serves many
    requests (the coalescing transport the fetch scheduler batches onto);
    a definitive miss leaves the connection usable."""
    from tez_tpu.shuffle.server import FetchSession
    from tez_tpu.shuffle.service import ShuffleDataNotFound
    server, secrets, run = served_run
    s = FetchSession(secrets, "127.0.0.1", server.port)
    try:
        for p in range(3):
            got = s.fetch("dagX/attempt_1/cons", -1, p)
            assert list(got.iter_pairs()) == \
                list(run.partition(p).iter_pairs())
        with pytest.raises(ShuffleDataNotFound):
            s.fetch("no/such/output", -1, 0)
        # connection still serves after the miss
        got = s.fetch("dagX/attempt_1/cons", -1, 1)
        assert got.num_records == run.partition(1).num_records
    finally:
        s.close()
