"""Test harness config: force an 8-device virtual CPU mesh so multi-chip
sharding paths compile and execute without TPU hardware (the driver's
dryrun_multichip does the same)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The ambient sitecustomize may have registered the real-TPU backend and
# pinned jax_platforms before this file runs; the config update (which
# outranks the env var) forces tests onto the virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _lockorder_witness_session():
    """Arm the runtime lock-order witness for the whole suite (graftlint's
    dynamic half — docs/static_analysis.md): every lock the tests create
    inside tez_tpu is wrapped, nested acquisitions are recorded, and the
    session fails if any order inversion was observed or if a witnessed
    edge is missing from the static lock graph.  TEZ_LOCKORDER_WITNESS=0
    opts out (e.g. when bisecting an unrelated failure)."""
    if os.environ.get("TEZ_LOCKORDER_WITNESS", "1") == "0":
        yield
        return
    from tez_tpu.common import lockorder
    lockorder.arm("pytest-session")
    yield
    lockorder.disarm("pytest-session")
    from tez_tpu.analysis import lockorder as static_lockorder
    from tez_tpu.analysis.core import Context
    import tez_tpu
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(tez_tpu.__file__)))
    edges, locks = static_lockorder.build_graph(Context(root))
    problems = lockorder.check(set(edges), locks)
    assert not problems, \
        "lock-order witness: " + "\n".join(problems)


@pytest.fixture()
def tmp_staging(tmp_path):
    return str(tmp_path / "staging")


@pytest.fixture(autouse=True)
def _disarm_fault_plane():
    """The fault plane is process-global; a test that leaks armed rules
    would poison every later test in the session."""
    yield
    from tez_tpu.common import faults
    faults.clear_all()


@pytest.fixture(autouse=True)
def _disarm_trace_plane():
    """The tracing plane and metrics registry are process-global; spans or
    gauges leaked by one test must not bleed into the next one's exports."""
    yield
    from tez_tpu.common import metrics, tracing
    tracing.clear_all()
    metrics.registry().reset()


@pytest.fixture(autouse=True)
def _reset_timeseries_plane():
    """The live time-series registry is process-global like the metrics
    registry; sampled rings and registered collectors leaked by one
    test's AM must not feed the next test's windows."""
    yield
    from tez_tpu.obs import timeseries
    reg = timeseries.registry()
    reg.reset()
    for name in reg.collectors():
        reg.unregister_collector(name)
    reg.capacity = timeseries.DEFAULT_CAPACITY


@pytest.fixture(autouse=True)
def _reset_device_breaker():
    """The device circuit breaker is a sticky process singleton; a test
    that tripped it (injected device faults) must not leave the device
    engine short-circuited to host for every later test."""
    yield
    from tez_tpu.ops.async_stage import reset_process_breaker
    reset_process_breaker()


@pytest.fixture(autouse=True)
def _reset_epoch_registry():
    """The AM-epoch registry is process-global; a test that restarted an AM
    (attempt 2+) would otherwise fence the next test's attempt-1 AMs if an
    app_id collided."""
    yield
    from tez_tpu.common import epoch
    epoch.reset()


@pytest.fixture(autouse=True)
def _reset_buffer_store():
    """The tiered buffer store is a process singleton attached to the
    shuffle service; a test that enabled it (store conf knobs) must not
    leave its tiny tiers — or its sealed lineage cache — behind for
    later tests."""
    yield
    from tez_tpu.store import reset_store
    reset_store()
