"""Local-mode orchestrator integration tests.

Mirrors the reference's TestLocalMode / TestFaultTolerance style: whole DAGs
through TezClient with fault-injectable components (SURVEY.md §4).
"""
import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)


def sleep_vertex(name, parallelism, sleep_ms=1, payload=None):
    p = dict(payload or {})
    p.setdefault("sleep_ms", sleep_ms)
    return Vertex.create(name, ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor", payload=p), parallelism)


def make_test_vertex(name, parallelism, payload=None):
    return Vertex.create(name, ProcessorDescriptor.create(
        "tez_tpu.library.test_components:TestProcessor", payload=payload or {}),
        parallelism)


def tedge(a, b, movement=DataMovementType.SCATTER_GATHER):
    return Edge.create(a, b, EdgeProperty.create(
        movement, DataSourceType.PERSISTED, SchedulingType.SEQUENTIAL,
        OutputDescriptor.create("tez_tpu.library.test_components:TestOutput"),
        InputDescriptor.create("tez_tpu.library.test_components:TestInput")))


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("test", {"tez.staging-dir": tmp_staging,
                                  "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


def test_single_vertex_dag(client):
    dag = DAG.create("single").add_vertex(sleep_vertex("v", 3))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.SUCCEEDED
    assert status.vertex_status["v"].progress.succeeded_task_count == 3


def test_two_vertex_scatter_gather(client):
    a, b = make_test_vertex("a", 3), make_test_vertex("b", 2)
    dag = DAG.create("sg").add_vertex(a).add_vertex(b).add_edge(tedge(a, b))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.SUCCEEDED
    assert status.vertex_status["a"].progress.succeeded_task_count == 3
    assert status.vertex_status["b"].progress.succeeded_task_count == 2


def test_diamond_dag(client):
    a, b, c, d = (make_test_vertex(n, 2) for n in "abcd")
    dag = DAG.create("diamond")
    for v in (a, b, c, d):
        dag.add_vertex(v)
    dag.add_edge(tedge(a, b)).add_edge(tedge(a, c))
    dag.add_edge(tedge(b, d, DataMovementType.BROADCAST))
    dag.add_edge(tedge(c, d))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.SUCCEEDED


def test_one_to_one_edge(client):
    a, b = make_test_vertex("a", 3), make_test_vertex("b", 3)
    dag = DAG.create("o2o").add_vertex(a).add_vertex(b).add_edge(
        tedge(a, b, DataMovementType.ONE_TO_ONE))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.SUCCEEDED


def test_failing_task_retries_then_succeeds(client):
    # fails attempts 0 and 1, succeeds from attempt 2
    v = make_test_vertex("v", 2, payload={
        "do_fail": True, "failing_task_indices": [1],
        "failing_upto_attempt": 1})
    dag = DAG.create("retry").add_vertex(v)
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.SUCCEEDED


def test_task_fails_all_attempts_fails_dag(client):
    v = make_test_vertex("v", 2, payload={
        "do_fail": True, "failing_task_indices": [0]})
    dag = DAG.create("perma-fail").add_vertex(v)
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.FAILED
    assert any("failed" in d for d in status.diagnostics)


def test_downstream_vertex_failure_fails_dag(client):
    a = make_test_vertex("a", 2)
    b = make_test_vertex("b", 2, payload={"do_fail": True,
                                     "failing_task_indices": [-1]})
    dag = DAG.create("down-fail").add_vertex(a).add_vertex(b).add_edge(
        tedge(a, b))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.FAILED


def test_fatal_failure_no_retry(client):
    v = make_test_vertex("v", 1, payload={
        "do_fail": True, "failing_task_indices": [-1], "fatal": True})
    dag = DAG.create("fatal").add_vertex(v)
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.FAILED


def test_kill_dag(client):
    v = sleep_vertex("v", 2, sleep_ms=10_000)
    dag = DAG.create("kill").add_vertex(v)
    dc = client.submit_dag(dag)
    import time
    time.sleep(0.3)
    dc.try_kill_dag()
    status = dc.wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.KILLED


def test_session_runs_multiple_dags(client):
    for i in range(3):
        dag = DAG.create(f"d{i}").add_vertex(sleep_vertex("v", 2))
        status = client.submit_dag(dag).wait_for_completion(timeout=30)
        assert status.state is DAGStatusState.SUCCEEDED


def test_three_stage_mrr_shape(client):
    """map -> reduce -> reduce chained scatter-gathers (MRR, SURVEY §6)."""
    a, b, c = make_test_vertex("m", 4), make_test_vertex("r1", 3), make_test_vertex("r2", 2)
    dag = DAG.create("mrr").add_vertex(a).add_vertex(b).add_vertex(c)
    dag.add_edge(tedge(a, b)).add_edge(tedge(b, c))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.SUCCEEDED


def test_counters_aggregate_to_dag(client):
    dag = DAG.create("counters").add_vertex(sleep_vertex("v", 2))
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.counters is not None
    d = status.counters.to_dict()
    assert d.get("TaskCounter", {}).get("WALL_CLOCK_MILLISECONDS", 0) >= 0


def test_history_events_emitted(client, tmp_staging):
    dag = DAG.create("hist").add_vertex(sleep_vertex("v", 1))
    client.submit_dag(dag).wait_for_completion(timeout=30)
    svc = client.framework_client.am.logging_service
    from tez_tpu.am.history import HistoryEventType
    types = {e.event_type for e in svc.events}
    for t in (HistoryEventType.DAG_SUBMITTED, HistoryEventType.DAG_STARTED,
              HistoryEventType.VERTEX_STARTED, HistoryEventType.TASK_STARTED,
              HistoryEventType.TASK_ATTEMPT_STARTED,
              HistoryEventType.DAG_FINISHED):
        assert t in types, f"missing {t}"


def test_exception_propagation_to_diagnostics(client):
    """Task exception text reaches DAGStatus diagnostics (reference:
    TestExceptionPropagation.java:100)."""
    v = make_test_vertex("v", 1, payload={"do_fail": True,
                                          "failing_task_indices": [-1]})
    status = client.submit_dag(
        DAG.create("diag").add_vertex(v)).wait_for_completion(timeout=30)
    assert status.state is DAGStatusState.FAILED
    text = " ".join(status.diagnostics) + " ".join(
        status.vertex_status["v"].diagnostics)
    assert "TestProcessor failing" in text
    assert "RuntimeError" in text


def test_concurrent_dispatcher_mode(tmp_staging):
    """The sharded AM dispatcher runs whole DAGs correctly (per-entity
    ordering preserved across shards)."""
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.concurrent.dispatcher.shards": 4,
                               "tez.am.local.num-containers": 4}).start()
    try:
        a, b = make_test_vertex("a", 4), make_test_vertex("b", 3)
        dag = DAG.create("sharded").add_vertex(a).add_vertex(b).add_edge(
            tedge(a, b))
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
        assert status.state is DAGStatusState.SUCCEEDED
        assert status.vertex_status["b"].progress.succeeded_task_count == 3
    finally:
        c.stop()


def test_scale_500_tasks(tmp_staging):
    """AM event-path scale smoke (SURVEY.md §7 event-storm concern): a
    500-task vertex completes promptly; every transition flows through the
    dispatcher (~6 events/task like the reference)."""
    import time
    c = TezClient.create("scale", {"tez.staging-dir": tmp_staging,
                                   "tez.am.local.num-containers": 8}).start()
    try:
        dag = DAG.create("scale500").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 0}), 500))
        t0 = time.time()
        st = c.submit_dag(dag).wait_for_completion(timeout=120)
        assert st.state is DAGStatusState.SUCCEEDED
        assert st.vertex_status["v"].progress.succeeded_task_count == 500
        assert time.time() - t0 < 60   # generous: ~0.5s typical
    finally:
        c.stop()


def test_event_storm_100x100_scatter_gather(tmp_staging):
    """SURVEY §7 event-storm concern at the EDGE level (Edge.java:151
    lesson): a 100x100 SCATTER_GATHER DAG — 10,000 logical edge routes —
    through the sharded dispatcher and on-demand composite-event routing.
    Asserts wall-clock and that the event queues stayed bounded (composite
    events expand per-consumer on demand, not 10k-at-once into the AM
    queue)."""
    import time
    from tez_tpu.library.conf import OrderedPartitionedKVEdgeConfig

    c = TezClient.create("storm", {"tez.staging-dir": tmp_staging,
                                   "tez.am.local.num-containers": 8}).start()
    try:
        producer = Vertex.create("p", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SleepProcessor",
            payload={"sleep_ms": 0}), 100)
        consumer = Vertex.create("q", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SleepProcessor",
            payload={"sleep_ms": 0}), 100)
        edge = OrderedPartitionedKVEdgeConfig.new_builder(
            "bytes", "bytes").build()
        dag = DAG.create("storm100").add_vertex(producer)\
            .add_vertex(consumer)
        dag.add_edge(Edge.create(producer, consumer,
                                 edge.create_default_edge_property()))
        t0 = time.time()
        st = c.submit_dag(dag).wait_for_completion(timeout=300)
        wall = time.time() - t0
        assert st.state is DAGStatusState.SUCCEEDED
        assert st.vertex_status["p"].progress.succeeded_task_count == 100
        assert st.vertex_status["q"].progress.succeeded_task_count == 100
        assert wall < 120, f"event storm took {wall:.0f}s"
        am = c.framework_client.am
        peaks = am.dispatcher.peak_depths() \
            if hasattr(am.dispatcher, "peak_depths") \
            else [am.dispatcher.peak_in_flight]
        # 100 producers x 100-consumer composite events route on demand:
        # the AM queues must never hold anywhere near the 10k expansion
        assert max(peaks) < 2500, peaks
    finally:
        c.stop()


# slow tier: one million routes run 2-4 minutes on a 1-core box — that
# never fit the tier-1 wall budget (it flaked on wall, not correctness,
# since PR 18).  The 100x100 storm above keeps the routing-blowup
# regression guard in tier-1; this scale runs with `-m slow`.
@pytest.mark.slow
def test_event_storm_1k_x_1k_stretch(tmp_staging):
    """Stretch storm (SURVEY §7): 1000x1000 SCATTER_GATHER — one MILLION
    logical edge routes — completes promptly with bounded AM queues."""
    import time
    from tez_tpu.library.conf import OrderedPartitionedKVEdgeConfig

    c = TezClient.create("storm1k", {"tez.staging-dir": tmp_staging,
                                     "tez.am.local.num-containers": 8}).start()
    try:
        p = Vertex.create("p", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SleepProcessor",
            payload={"sleep_ms": 0}), 1000)
        q = Vertex.create("q", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SleepProcessor",
            payload={"sleep_ms": 0}), 1000)
        edge = OrderedPartitionedKVEdgeConfig.new_builder(
            "bytes", "bytes").build()
        dag = DAG.create("storm1m").add_vertex(p).add_vertex(q)
        dag.add_edge(Edge.create(p, q, edge.create_default_edge_property()))
        # load-scaled budget: the 180s floor guards the routing-blowup
        # regression on an idle box; on a box already oversubscribed by
        # co-tenant work the budget grows with the oversubscription
        # factor instead of flaking (this test measures OUR scaling, not
        # the neighbors' CPU appetite)
        import os
        ncpu = os.cpu_count() or 1
        load0 = os.getloadavg()[0]
        t0 = time.time()
        # completion timeout is a pure correctness guard — generous,
        # because loadavg sampled *before* the run can't see contention
        # that ramps up while the storm is in flight
        st = c.submit_dag(dag).wait_for_completion(timeout=1800.0)
        wall = time.time() - t0
        assert st.state is DAGStatusState.SUCCEEDED
        assert st.vertex_status["q"].progress.succeeded_task_count == 1000
        # re-sample after the run: the 1-minute loadavg now reflects any
        # co-tenant work that arrived mid-storm, so the budget scales
        # with the oversubscription we actually ran under
        load = max(load0, os.getloadavg()[0])
        budget = 180.0 * max(1.0, load / ncpu)
        assert wall < budget, (f"1M-route storm took {wall:.0f}s "
                               f"(budget {budget:.0f}s at load "
                               f"{load:.1f}/{ncpu} cpus)")
        am = c.framework_client.am
        peaks = am.dispatcher.peak_depths() \
            if hasattr(am.dispatcher, "peak_depths") \
            else [am.dispatcher.peak_in_flight]
        # composite routing expands on demand: queues must stay far below
        # the 1M logical expansion
        assert max(peaks) < 50_000, peaks
    finally:
        c.stop()
