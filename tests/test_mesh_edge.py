"""The ICI exchange as a real DAG edge: SCATTER_GATHER through
parallel/exchange.py inside framework execution (VERDICT round-1 item 2).

OrderedWordCount runs with its tokenizer->summation edge on the mesh
(MeshOrderedPartitionedKVEdgeConfig) over the virtual 8-device CPU mesh and
must produce byte-identical output to the host-shuffle run."""
import collections
import os
import random

import numpy as np
import pytest

import jax

from tez_tpu.ops.runformat import KVBatch
from tez_tpu.parallel.coordinator import (MeshCapacityError,
                                          MeshExchangeCoordinator,
                                          mesh_coordinator,
                                          reset_coordinator)


@pytest.fixture(autouse=True)
def fresh_coordinator():
    reset_coordinator()
    yield
    reset_coordinator()


def make_batch(pairs):
    return KVBatch.from_pairs([(k.encode(), v.encode()) for k, v in pairs])


def reference_route(pairs, num_workers):
    from tez_tpu.parallel.exchange import fnv_bytes_host
    out = [[] for _ in range(num_workers)]
    for k, v in pairs:
        out[fnv_bytes_host(k.encode()) % num_workers].append(
            (k.encode(), v.encode()))
    for part in out:
        part.sort(key=lambda kv: kv[0])
    return out


def test_coordinator_exchange_matches_host_routing():
    coord = MeshExchangeCoordinator()
    rng = random.Random(5)
    pairs = [(f"key{rng.randrange(500):05d}", f"val{i:06d}")
             for i in range(3000)]
    thirds = [pairs[0::3], pairs[1::3], pairs[2::3]]
    for idx, chunk in enumerate(thirds):
        coord.register_producer("e1", idx, 3, 4, make_batch(chunk),
                                key_width=16, value_width=12)
    golden = reference_route(pairs, 4)
    for w in range(4):
        got = coord.wait_consumer("e1", w, 3, 4, timeout=30)
        got_pairs = list(got.iter_pairs())
        assert [k for k, _ in got_pairs] == [k for k, _ in golden[w]]
        # every (k, v) multiset must survive exactly
        assert sorted(got_pairs) == sorted(golden[w])
    assert coord.exchanges_run == 1
    assert coord.rows_exchanged == 3000


def test_coordinator_multi_round_on_skew():
    """A hot key bigger than the per-round budget forces a multi-round
    exchange; output must still be complete and sorted."""
    coord = MeshExchangeCoordinator(max_rows_per_round=256)
    hot = [("hotkey", f"v{i:07d}") for i in range(900)]
    cold = [(f"cold{i:04d}", "x") for i in range(300)]
    coord.register_producer("e2", 0, 2, 3, make_batch(hot),
                            key_width=12, value_width=8)
    coord.register_producer("e2", 1, 2, 3, make_batch(cold),
                            key_width=12, value_width=8)
    golden = reference_route(hot + cold, 3)
    total_got = 0
    for w in range(3):
        got = list(coord.wait_consumer("e2", w, 2, 3, timeout=60).iter_pairs())
        total_got += len(got)
        assert [k for k, _ in got] == [k for k, _ in golden[w]]
        assert sorted(got) == sorted(golden[w])
    assert total_got == 1200
    assert coord.exchanges_run == 1


def test_oversized_key_rejected_loudly():
    """Keys beyond the HARD cap (not the slot hint — widths auto-widen)
    still error actionably: one huge record would tax every row's HBM
    slot, so it belongs on the host shuffle edge."""
    coord = MeshExchangeCoordinator()
    with pytest.raises(MeshCapacityError, match="max.key.bytes"):
        coord.register_producer(
            "e3", 0, 1, 2, make_batch([("x" * 300, "v")]),
            key_width=16, value_width=8)
    with pytest.raises(MeshCapacityError, match="max.value.bytes"):
        coord.register_producer(
            "e3v", 0, 1, 2, make_batch([("k", "v" * 2000)]),
            key_width=16, value_width=8)


def test_mesh_edge_wordcount_byte_identical(tmp_path):
    """The flagship: OrderedWordCount through the mesh exchange inside a
    real DAG, byte-identical to the host-shuffle run."""
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    from tez_tpu.examples import ordered_wordcount

    rng = random.Random(17)
    words = [f"word{rng.randrange(400):04d}" for _ in range(30_000)]
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(" ".join(words))
    golden = collections.Counter(words)

    outs = {}
    for exchange in ("host", "mesh"):
        out_dir = str(tmp_path / f"out_{exchange}")
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf={"tez.staging-dir": str(tmp_path / f"stg_{exchange}")},
            tokenizer_parallelism=3, summation_parallelism=2,
            sorter_parallelism=1, exchange=exchange)
        assert state == "SUCCEEDED", exchange
        lines = []
        for name in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, name)) as fh:
                lines.extend(fh.read().splitlines())
        counts = dict(line.rsplit(None, 1) for line in lines if line.strip())
        assert {k: int(v) for k, v in counts.items()} == dict(golden), \
            exchange
        outs[exchange] = lines
    assert outs["host"] == outs["mesh"]
    assert mesh_coordinator().exchanges_run >= 1


def test_producer_reregistration_reruns_exchange():
    """A producer re-running after the exchange (output loss recovery) must
    invalidate and re-run the exchange with the replacement span — not
    permanently fail the edge."""
    coord = MeshExchangeCoordinator()
    a = make_batch([("k1", "old")])
    b = make_batch([("k2", "vb")])
    coord.register_producer("er", 0, 2, 2, a, key_width=8, value_width=8)
    coord.register_producer("er", 1, 2, 2, b, key_width=8, value_width=8)
    first = {w: list(coord.wait_consumer("er", w, 2, 2,
                                         timeout=30).iter_pairs())
             for w in range(2)}
    assert sorted(sum(first.values(), [])) == \
        sorted([(b"k1", b"old"), (b"k2", b"vb")])
    # producer 0 re-runs with different data
    coord.register_producer("er", 0, 2, 2, make_batch([("k1", "new")]),
                            key_width=8, value_width=8)
    second = {w: list(coord.wait_consumer("er", w, 2, 2,
                                          timeout=30).iter_pairs())
              for w in range(2)}
    assert sorted(sum(second.values(), [])) == \
        sorted([(b"k1", b"new"), (b"k2", b"vb")])
    assert coord.exchanges_run == 2


def test_mesh_edge_skew_multi_round_inside_dag(tmp_path, monkeypatch):
    """VERDICT r1 weak #7: the skew story end to end INSIDE a DAG — a hot
    key whose partition exceeds the per-round device budget drives the
    multi-round rank-sliced exchange during real edge execution, and the
    output stays exactly correct.  (Persistent skew beyond the mesh
    entirely is the host fair-shuffle path —
    test_custom_edges.py::test_fair_shuffle_e2e_splits_hot_partition.)"""
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    from tez_tpu.examples import ordered_wordcount
    from tez_tpu.parallel import coordinator as coord_mod

    coord_mod.reset_coordinator()
    try:
        rng = random.Random(23)
        # one hot word dominates: its partition alone exceeds 512 rows
        words = ["hotword"] * 4000 + \
            [f"cold{rng.randrange(300):04d}" for _ in range(2000)]
        rng.shuffle(words)
        corpus = tmp_path / "skew.txt"
        corpus.write_text(" ".join(words))
        golden = collections.Counter(words)

        out_dir = str(tmp_path / "out")
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf={"tez.staging-dir": str(tmp_path / "stg"),
                  "tez.runtime.tpu.mesh.max-rows-per-round": 512},
            tokenizer_parallelism=3, summation_parallelism=2,
            sorter_parallelism=1, exchange="mesh")
        assert state == "SUCCEEDED"
        coord = coord_mod.mesh_coordinator()
        assert coord.multi_round_exchanges >= 1, \
            "skew did not engage the multi-round exchange"
        lines = []
        for name in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, name)) as fh:
                lines.extend(fh.read().splitlines())
        counts = dict(line.rsplit(None, 1) for line in lines if line.strip())
        assert {k: int(v) for k, v in counts.items()} == dict(golden)
    finally:
        coord_mod.reset_coordinator()


def test_mesh_edge_keys_beyond_slot_hint_auto_widen(tmp_path):
    """Keys wider than the configured slot hint AUTO-WIDEN (VERDICT r2
    item 5: the reference carries arbitrary KV, IFile.java:67) — the DAG
    succeeds and the counts are exact."""
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "long.txt"
    corpus.write_text("averyveryverylongword " * 200)
    out_dir = str(tmp_path / "out")
    state = ordered_wordcount.run(
        [str(corpus)], out_dir,
        conf={"tez.staging-dir": str(tmp_path / "stg"),
              "tez.runtime.tpu.key.width.bytes": 8,
              "tez.am.task.max.failed.attempts": 2},
        tokenizer_parallelism=2, summation_parallelism=2,
        sorter_parallelism=1, exchange="mesh")
    assert state == "SUCCEEDED"
    got = {}
    for name in sorted(os.listdir(out_dir)):
        with open(os.path.join(out_dir, name)) as fh:
            for line in fh.read().splitlines():
                if line.strip():
                    w, c = line.rsplit(None, 1)
                    got[w] = int(c)
    assert got == {"averyveryverylongword": 200}


def test_mesh_edge_capacity_error_fails_dag_actionably(tmp_path):
    """A mesh edge that CANNOT carry the data (key beyond the hard cap)
    must fail the DAG with the actionable use-the-host-edge diagnostic —
    attempts retry and exhaust, never hang."""
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "long.txt"
    corpus.write_text(("x" * 300 + " ") * 50)
    state = ordered_wordcount.run(
        [str(corpus)], str(tmp_path / "out"),
        conf={"tez.staging-dir": str(tmp_path / "stg"),
              "tez.am.task.max.failed.attempts": 2},
        tokenizer_parallelism=2, summation_parallelism=2,
        sorter_parallelism=1, exchange="mesh")
    assert state == "FAILED"


def test_wide_kv_64b_keys_256b_values():
    """VERDICT r2 item 5: 64 B keys and 256 B values ride the mesh edge
    (slot widths auto-widen to the data; producers with different widths
    harmonize at exchange time)."""
    coord = MeshExchangeCoordinator()
    rng = random.Random(11)
    pairs = [(f"{rng.randrange(200):05d}".ljust(64, "k"),
              f"v{i:06d}".ljust(256, "p")) for i in range(800)]
    halves = [pairs[0::2], pairs[1::2]]
    # producer 0 ships narrow records too — mixed widths in one edge
    halves[0] = halves[0] + [("tiny", "v")]
    for idx, chunk in enumerate(halves):
        coord.register_producer("wide", idx, 2, 2, make_batch(chunk),
                                key_width=16, value_width=16)
    golden = reference_route(halves[0] + halves[1], 2)
    for w in range(2):
        got = list(coord.wait_consumer("wide", w, 2, 2,
                                       timeout=60).iter_pairs())
        assert [k for k, _ in got] == [k for k, _ in golden[w]]
        assert sorted(got) == sorted(golden[w])


def test_consumers_exceed_device_count():
    """VERDICT r2 item 5: consumer parallelism = 2x the device count —
    the exchange routes over the largest dividing device count and splits
    each device's sorted output into its consumer partitions."""
    n_dev = len(jax.devices())
    if n_dev < 2:
        pytest.skip("needs multiple virtual devices")
    W = n_dev * 2
    coord = MeshExchangeCoordinator()
    rng = random.Random(23)
    pairs = [(f"key{rng.randrange(997):05d}", f"val{i:06d}")
             for i in range(4000)]
    thirds = [pairs[0::3], pairs[1::3], pairs[2::3]]
    for idx, chunk in enumerate(thirds):
        coord.register_producer("many", idx, 3, W, make_batch(chunk),
                                key_width=16, value_width=12)
    golden = reference_route(pairs, W)
    total = 0
    for w in range(W):
        got = list(coord.wait_consumer("many", w, 3, W,
                                       timeout=60).iter_pairs())
        total += len(got)
        assert [k for k, _ in got] == [k for k, _ in golden[w]], f"part {w}"
        assert sorted(got) == sorted(golden[w])
    assert total == 4000


def test_consumers_exceed_devices_e2e_wordcount(tmp_path):
    """Full-DAG proof: summation parallelism 2x the mesh device count,
    byte-identical to the host-shuffle run."""
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple virtual devices")
    from tez_tpu.examples import ordered_wordcount
    rng = random.Random(31)
    words = [f"word{rng.randrange(300):04d}" for _ in range(20_000)]
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(" ".join(words))
    outs = {}
    W = len(jax.devices()) * 2
    for exchange in ("host", "mesh"):
        out_dir = str(tmp_path / f"out_{exchange}")
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf={"tez.staging-dir": str(tmp_path / f"stg_{exchange}")},
            tokenizer_parallelism=3, summation_parallelism=W,
            sorter_parallelism=1, exchange=exchange)
        assert state == "SUCCEEDED", exchange
        lines = []
        for name in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, name)) as fh:
                lines.extend(fh.read().splitlines())
        outs[exchange] = lines
    assert outs["host"] == outs["mesh"]


def test_barrier_timeout_poisons_edge_and_late_producer_heals():
    """Straggler defense (VERDICT r3 item 7): a producer that never
    registers must not stall consumers forever — the first consumer to hit
    its deadline poisons the edge (naming the missing producers) so
    siblings fail FAST; a late registration heals the edge for retries."""
    import threading
    import time

    coord = MeshExchangeCoordinator()
    coord.register_producer("dag0/e1", 0, num_producers=2, num_consumers=2,
                            batch=make_batch([("a", "1")]), key_width=8,
                            value_width=8)
    # producer 1 hangs: consumer 0 times out and the error names it
    with pytest.raises(TimeoutError, match=r"missing producer task "
                                           r"indices \[1\]"):
        coord.wait_consumer("dag0/e1", 0, num_producers=2, num_consumers=2,
                            timeout=0.6)
    # sibling consumers fail FAST off the poisoned edge (no own deadline)
    t0 = time.time()
    with pytest.raises(RuntimeError, match="failed"):
        coord.wait_consumer("dag0/e1", 1, num_producers=2, num_consumers=2,
                            timeout=30.0)
    assert time.time() - t0 < 5.0
    # the straggler finally arrives: edge heals, retries succeed
    coord.register_producer("dag0/e1", 1, num_producers=2, num_consumers=2,
                            batch=make_batch([("b", "2")]), key_width=8,
                            value_width=8)
    got = [coord.wait_consumer("dag0/e1", c, num_producers=2,
                               num_consumers=2, timeout=30.0)
           for c in range(2)]
    all_pairs = sorted(kv for b in got for kv in b.iter_pairs())
    assert all_pairs == [(b"a", b"1"), (b"b", b"2")]


def test_barrier_deadline_conf_fails_dag_actionably(tmp_path):
    """E2E: a DAG whose mesh-edge producer hangs fails within the
    configured deadline with the missing producer named (instead of
    hanging the DAG forever)."""
    import time

    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount

    corpus = tmp_path / "in.txt"
    corpus.write_text("alpha beta alpha\n" * 200)

    # hang exactly one tokenizer attempt ONCE via the fault-injection seam
    from tez_tpu.examples.ordered_wordcount import VectorTokenProcessor
    orig_run = VectorTokenProcessor.run
    hung = {"done": False}

    def hanging_run(self, inputs, outputs):
        if self.context.task_index == 1 and not hung["done"]:
            hung["done"] = True
            time.sleep(30)   # well past the edge deadline
        return orig_run(self, inputs, outputs)

    VectorTokenProcessor.run = hanging_run
    try:
        conf = {"tez.staging-dir": str(tmp_path / "stg"),
                "tez.runtime.tpu.mesh.exchange.deadline.secs": 2.0,
                "tez.am.task.max.failed.attempts": 1,
                "tez.am.max.allowed.time-sec.for-read-error": 1}
        t0 = time.time()
        with TezClient.create("barrier-timeout", conf) as client:
            dag = ordered_wordcount.build_dag(
                [str(corpus)], str(tmp_path / "out"),
                tokenizer_parallelism=2, summation_parallelism=2,
                sorter_parallelism=1, exchange="mesh",
                tokenizer_mode="vector")
            status = client.submit_dag(dag).wait_for_completion()
        wall = time.time() - t0
        # consumers must not have waited for the full 30s hang
        assert wall < 25, f"barrier deadline did not engage ({wall:.0f}s)"
        diags = str(status.vertex_status)
        assert status.state.name in ("FAILED", "SUCCEEDED"), diags
        if status.state.name == "FAILED":
            assert "missing producer" in diags or "mesh" in diags, diags
    finally:
        VectorTokenProcessor.run = orig_run
