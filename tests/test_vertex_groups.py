"""Vertex-group E2E: two producer vertices' outputs merge into one consumer
through a GroupInputEdge + ConcatenatedMergedKVInput (reference:
TestGroupedEdges style)."""
import os

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (EntityDescriptor, OutputCommitterDescriptor,
                                    OutputDescriptor, ProcessorDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, GroupInputEdge, Vertex)
from tez_tpu.library.conf import UnorderedPartitionedKVEdgeConfig
from tez_tpu.library.processors import SimpleProcessor


class EmitTagged(SimpleProcessor):
    def run(self, inputs, outputs):
        writer = outputs["collector"].get_writer()
        tag = self.context.vertex_name
        for i in range(10):
            writer.write(f"{tag}-{self.context.task_index}-{i}".encode(), b"1")


class CollectGroup(SimpleProcessor):
    def run(self, inputs, outputs):
        # the group input is presented under the GROUP name; constituents
        # are hidden from the processor
        assert "g" in inputs, list(inputs)
        assert "p1" not in inputs and "p2" not in inputs
        writer = outputs["output"].get_writer()
        for k, v in inputs["g"].get_reader():
            writer.write(k, v)


def test_vertex_group_merged_input(tmp_staging, tmp_path):
    client = TezClient.create("t", {"tez.staging-dir": tmp_staging}).start()
    try:
        p1 = Vertex.create("p1", ProcessorDescriptor.create(EmitTagged), 2)
        p2 = Vertex.create("p2", ProcessorDescriptor.create(EmitTagged), 2)
        collector = Vertex.create("collector", ProcessorDescriptor.create(
            CollectGroup), 2)
        out_dir = str(tmp_path / "out")
        collector.add_data_sink("output", DataSinkDescriptor.create(
            OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                    payload={"path": out_dir,
                                             "key_serde": "text",
                                             "value_serde": "text"}),
            OutputCommitterDescriptor.create(
                "tez_tpu.io.file_output:FileOutputCommitter",
                payload={"path": out_dir})))
        dag = DAG.create("group")
        for v in (p1, p2, collector):
            dag.add_vertex(v)
        group = dag.create_vertex_group("g", [p1, p2])
        edge_conf = UnorderedPartitionedKVEdgeConfig.new_builder(
            "bytes", "bytes").build()
        dag.add_group_edge(GroupInputEdge.create(
            group, collector, edge_conf.create_default_edge_property(),
            EntityDescriptor.create(
                "tez_tpu.library.inputs:ConcatenatedMergedKVInput")))
        status = client.submit_dag(dag).wait_for_completion(timeout=60)
        assert status.state is DAGStatusState.SUCCEEDED
        keys = set()
        for f in os.listdir(out_dir):
            if f.startswith("part-"):
                for line in open(os.path.join(out_dir, f), "rb"):
                    keys.add(line.split(b"\t")[0].decode())
        expected = {f"{v}-{t}-{i}" for v in ("p1", "p2")
                    for t in range(2) for i in range(10)}
        assert keys == expected
    finally:
        client.stop()
