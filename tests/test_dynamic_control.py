"""Phase-5 tests: slow-start, auto-parallelism shrink, speculation."""
import collections
import os
import random
import time

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor,
                                    VertexManagerPluginDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.examples import ordered_wordcount


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


def write_corpus(path, num_lines=400, seed=0):
    rng = random.Random(seed)
    words = [f"w{i:02d}" for i in range(30)]
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(words) for _ in range(6)]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def test_auto_parallelism_shrinks_summation(client, tmp_path):
    """Summation declared with 8 tasks shrinks to fewer when the measured
    output is tiny (reference: ShuffleVertexManager auto-parallelism)."""
    corpus = tmp_path / "in.txt"
    golden = write_corpus(str(corpus))
    out = str(tmp_path / "out")
    dag = ordered_wordcount.build_dag([str(corpus)], out,
                                      tokenizer_parallelism=3,
                                      summation_parallelism=8)
    # switch summation's manager to auto-parallel with a large desired input
    summation = dag.vertices["summation"]
    summation.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.vertex_managers:ShuffleVertexManager",
        payload={"auto_parallel": True,
                 "desired_task_input_size": 1 << 30,
                 "min_task_parallelism": 1,
                 "min_fraction": 0.5, "max_fraction": 0.75}))
    dc = client.submit_dag(dag)
    status = dc.wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    # shrank all the way to 1 task
    assert status.vertex_status["summation"].progress.total_task_count == 1
    # output still exactly correct through the range edge manager
    rows = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, c = line.rstrip(b"\n").split(b"\t")
                rows[w.decode()] = int(c)
    assert rows == dict(golden)


from tez_tpu.library.processors import SimpleProcessor


class StragglerProcessor(SimpleProcessor):
    """Task 1 attempt 0 stalls (cooperatively, checking for kill); all other
    attempts finish fast."""

    def run(self, inputs, outputs):
        if self.context.task_index == 1 and \
                self.context.task_attempt_number == 0:
            deadline = time.time() + 60
            while time.time() < deadline:
                time.sleep(0.05)
                self.context.notify_progress()
        else:
            time.sleep(0.05)


def test_speculation_rescues_straggler(client, tmp_path):
    """A task whose first attempt stalls gets a speculative attempt that
    finishes (reference: LegacySpeculator)."""
    v = Vertex.create("v", ProcessorDescriptor.create(StragglerProcessor), 3)
    dag = DAG.create("spec").add_vertex(v)
    dag.set_conf("tez.am.speculation.enabled", True)
    dag.set_conf("tez.am.legacy.speculative.slowtask.threshold", 1.0)
    dc = client.submit_dag(dag)
    status = dc.wait_for_completion(timeout=45)
    assert status.state is DAGStatusState.SUCCEEDED
    am = client.framework_client.am
    d = am.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("NUM_SPECULATIONS", 0) >= 1
