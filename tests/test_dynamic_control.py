"""Phase-5 tests: slow-start, auto-parallelism shrink, speculation."""
import collections
import os
import random
import time

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor,
                                    VertexManagerPluginDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.examples import ordered_wordcount


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


def write_corpus(path, num_lines=400, seed=0):
    rng = random.Random(seed)
    words = [f"w{i:02d}" for i in range(30)]
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(words) for _ in range(6)]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def test_auto_parallelism_shrinks_summation(client, tmp_path):
    """Summation declared with 8 tasks shrinks to fewer when the measured
    output is tiny (reference: ShuffleVertexManager auto-parallelism)."""
    corpus = tmp_path / "in.txt"
    golden = write_corpus(str(corpus))
    out = str(tmp_path / "out")
    dag = ordered_wordcount.build_dag([str(corpus)], out,
                                      tokenizer_parallelism=3,
                                      summation_parallelism=8)
    # switch summation's manager to auto-parallel with a large desired input
    summation = dag.vertices["summation"]
    summation.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.vertex_managers:ShuffleVertexManager",
        payload={"auto_parallel": True,
                 "desired_task_input_size": 1 << 30,
                 "min_task_parallelism": 1,
                 "min_fraction": 0.5, "max_fraction": 0.75}))
    dc = client.submit_dag(dag)
    status = dc.wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    # shrank all the way to 1 task
    assert status.vertex_status["summation"].progress.total_task_count == 1
    # output still exactly correct through the range edge manager
    rows = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, c = line.rstrip(b"\n").split(b"\t")
                rows[w.decode()] = int(c)
    assert rows == dict(golden)


from tez_tpu.library.processors import SimpleProcessor


class StragglerProcessor(SimpleProcessor):
    """Task 1 attempt 0 stalls (cooperatively, checking for kill); all other
    attempts finish fast."""

    def run(self, inputs, outputs):
        if self.context.task_index == 1 and \
                self.context.task_attempt_number == 0:
            deadline = time.time() + 60
            while time.time() < deadline:
                time.sleep(0.05)
                self.context.notify_progress()
        else:
            time.sleep(0.05)


def test_speculation_rescues_straggler(client, tmp_path):
    """A task whose first attempt stalls gets a speculative attempt that
    finishes (reference: LegacySpeculator)."""
    v = Vertex.create("v", ProcessorDescriptor.create(StragglerProcessor), 3)
    dag = DAG.create("spec").add_vertex(v)
    dag.set_conf("tez.am.speculation.enabled", True)
    dag.set_conf("tez.am.legacy.speculative.slowtask.threshold", 1.0)
    dc = client.submit_dag(dag)
    status = dc.wait_for_completion(timeout=45)
    assert status.state is DAGStatusState.SUCCEEDED
    am = client.framework_client.am
    d = am.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("NUM_SPECULATIONS", 0) >= 1


class FailingCommitter:
    """OutputCommitter whose commit always throws (module-level for
    descriptor resolution)."""

    def __init__(self, context):
        self.context = context

    def initialize(self):
        pass

    def setup_output(self):
        pass

    def commit_output(self):
        raise RuntimeError("commit boom")

    def abort_output(self, state):
        pass


def test_per_vertex_commit_mode(client, tmp_path):
    """tez.am.commit-all-outputs-on-dag-success=False: each vertex commits
    its own outputs at VERTEX success — the producer's _SUCCESS marker lands
    while the gated consumer vertex is still running (reference: per-vertex
    commit mode in DAGImpl/VertexImpl)."""
    import time
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    corpus.write_text("a b a c\n" * 100)
    out = str(tmp_path / "out")
    dag = ordered_wordcount.build_dag([str(corpus)], out,
                                      tokenizer_parallelism=2)
    dag.set_conf("tez.am.commit-all-outputs-on-dag-success", False)
    dc = client.submit_dag(dag)
    status = dc.wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    # the journal shows the per-vertex commit record
    events = client.framework_client.am.logging_service.events
    kinds = [e.event_type.name for e in events]
    assert "VERTEX_COMMIT_STARTED" in kinds


def test_per_vertex_commit_failure_fails_vertex(client, tmp_path):
    """A vertex whose committer throws FAILS (and the DAG with it) in
    per-vertex commit mode."""
    from tez_tpu.common.payload import (OutputCommitterDescriptor,
                                        OutputDescriptor)
    from tez_tpu.dag.dag import DataSinkDescriptor
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 2)
    v.add_data_sink("sink", DataSinkDescriptor(
        OutputDescriptor.create("tez_tpu.library.unordered:UnorderedKVOutput",
                                payload={}),
        OutputCommitterDescriptor.create(
            "tests.test_dynamic_control:FailingCommitter")))
    dag = DAG.create("commitfail").add_vertex(v)
    dag.set_conf("tez.am.commit-all-outputs-on-dag-success", False)
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.FAILED
    assert any("commit" in d
               for d in status.vertex_status["v"].diagnostics), \
        status.vertex_status["v"].diagnostics


def test_per_vertex_commit_does_not_poison_recovery(tmp_staging, tmp_path):
    """A vertex that committed (per-vertex mode) and FINISHED long before an
    AM crash must not be treated as a commit-in-flight on recovery — the DAG
    resubmits instead of failing."""
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.am.dag_impl import DAGState
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    import tez_tpu.common.config as C2
    conf = C2.TezConfiguration({"tez.staging-dir": tmp_staging})
    am1 = DAGAppMaster("app_1_pvc", conf)
    am1.start()
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    plan = DAG.create("pvc").add_vertex(v).create_dag_plan()
    # forge the journal shape: submitted, vertex commit started AND the
    # vertex finished, DAG still running at crash
    am1.history(HistoryEvent(
        HistoryEventType.DAG_SUBMITTED, dag_id="dag_1_pvc_1",
        data={"dag_name": plan.name, "plan": plan.serialize().hex()}))
    am1.history(HistoryEvent(
        HistoryEventType.VERTEX_COMMIT_STARTED, dag_id="dag_1_pvc_1",
        vertex_id="vertex_1_pvc_1_00", data={"vertex_name": "v"}))
    am1.history(HistoryEvent(
        HistoryEventType.VERTEX_FINISHED, dag_id="dag_1_pvc_1",
        vertex_id="vertex_1_pvc_1_00",
        data={"vertex_name": "v", "state": "SUCCEEDED", "num_tasks": 1}))
    am1.stop()
    am2 = DAGAppMaster("app_1_pvc", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    # resubmitted, NOT failed-for-commit-in-flight
    assert am2.completed_dags.get("dag_1_pvc_1") is not DAGState.FAILED
    assert am2.wait_for_dag(recovered, timeout=30) is DAGState.SUCCEEDED
    am2.stop()


def test_per_vertex_commit_rejects_group_shared_sink(client, tmp_path):
    """Group-shared sinks are incompatible with commit-on-vertex-success
    (first member would commit an output siblings still write)."""
    from tez_tpu.common.payload import OutputDescriptor
    from tez_tpu.dag.dag import DataSinkDescriptor
    a = Vertex.create("a", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor", payload={}), 1)
    b = Vertex.create("b", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor", payload={}), 1)
    dag = DAG.create("groupsink").add_vertex(a).add_vertex(b)
    from tez_tpu.dag.dag import Edge
    from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                           EdgeProperty, SchedulingType)
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "bytes"}
    dag.add_edge(Edge.create(a, b, EdgeProperty.create(
        DataMovementType.ONE_TO_ONE, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVOutput", payload=kv),
        InputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVInput", payload=kv))))
    g = dag.create_vertex_group("g", [a, b])
    g.add_data_sink("shared", DataSinkDescriptor(
        OutputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVOutput", payload={})))
    dag.set_conf("tez.am.commit-all-outputs-on-dag-success", False)
    status = client.submit_dag(dag).wait_for_completion(timeout=30)
    assert status.state.name in ("ERROR", "FAILED")
    assert any("group-shared sinks" in d for d in status.diagnostics), \
        status.diagnostics


def test_recovery_restores_committed_vertex_state(tmp_staging):
    """A vertex whose per-vertex commit landed pre-crash must NOT re-run
    commit_output() after recovery — proven with a committer that would
    throw if invoked again."""
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.am.dag_impl import DAGState
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    from tez_tpu.common.payload import (OutputCommitterDescriptor,
                                        OutputDescriptor)
    from tez_tpu.dag.dag import DataSinkDescriptor
    import tez_tpu.common.config as C2
    conf = C2.TezConfiguration({"tez.staging-dir": tmp_staging})
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    v.add_data_sink("sink", DataSinkDescriptor(
        OutputDescriptor.create("tez_tpu.library.unordered:UnorderedKVOutput",
                                payload={}),
        OutputCommitterDescriptor.create(
            "tests.test_dynamic_control:FailingCommitter")))
    dag = DAG.create("pvc2").add_vertex(v)
    dag.set_conf("tez.am.commit-all-outputs-on-dag-success", False)
    plan = dag.create_dag_plan()
    am1 = DAGAppMaster("app_1_pvc2", conf)
    am1.start()
    vid = "vertex_1_pvc2_1_00"
    am1.history(HistoryEvent(
        HistoryEventType.DAG_SUBMITTED, dag_id="dag_1_pvc2_1",
        data={"dag_name": plan.name, "plan": plan.serialize().hex()}))
    am1.history(HistoryEvent(
        HistoryEventType.VERTEX_COMMIT_STARTED, dag_id="dag_1_pvc2_1",
        vertex_id=vid, data={"vertex_name": "v"}))
    am1.history(HistoryEvent(
        HistoryEventType.VERTEX_FINISHED, dag_id="dag_1_pvc2_1",
        vertex_id=vid,
        data={"vertex_name": "v", "state": "SUCCEEDED", "num_tasks": 1}))
    am1.stop()
    am2 = DAGAppMaster("app_1_pvc2", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    # tasks re-run (no task journal), but the commit is NOT re-invoked —
    # FailingCommitter.commit_output would fail the DAG if it were
    assert am2.wait_for_dag(recovered, timeout=30) is DAGState.SUCCEEDED
    am2.stop()


def test_controlled_dag_scheduler_holds_downstream(client, tmp_path):
    """DAGSchedulerNaturalOrderControlled: a downstream vertex with an
    eager (ImmediateStart) manager is HELD until its source has scheduled
    every task — under the default scheduler it would schedule at DAG start
    (reference: DAGSchedulerNaturalOrderControlled)."""
    from tez_tpu.am.history import HistoryEventType
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "long"}

    def build(scheduler):
        a = Vertex.create("a", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SleepProcessor",
            payload={"sleep_ms": 400}), 2)
        b = Vertex.create("b", ProcessorDescriptor.create(
            SkewedEmitterForSched), 2)
        c2 = Vertex.create("c", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SimpleProcessor"), 1)
        from tez_tpu.dag.edge_property import (DataMovementType,
                                               DataSourceType, EdgeProperty,
                                               SchedulingType)
        sg = lambda s, d, out_name: Edge.create(s, d, EdgeProperty.create(  # noqa: E731
            DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
            SchedulingType.SEQUENTIAL,
            OutputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedPartitionedKVOutput",
                payload=kv),
            InputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVInput",
                payload=kv)))
        dag = DAG.create("ctrl").add_vertex(a).add_vertex(b).add_vertex(c2)
        # b slow-starts on a's completion; c is EAGER
        dag.add_edge(sg(a, b, "b")).add_edge(sg(b, c2, "c"))
        b.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
            "tez_tpu.library.vertex_managers:ShuffleVertexManager",
            payload={"min_fraction": 1.0, "max_fraction": 1.0}))
        c2.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
            "tez_tpu.library.vertex_managers:ImmediateStartVertexManager"))
        dag.set_conf("tez.am.dag.scheduler.class", scheduler)
        return dag

    dag = build("tez_tpu.am.dag_scheduler:DAGSchedulerNaturalOrderControlled")
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    events = client.framework_client.am.logging_service.events
    started = {}
    for e in events:
        if e.event_type is HistoryEventType.TASK_STARTED:
            started.setdefault(e.data.get("vertex_name"), e.timestamp)
    # eager c was held until b scheduled (b itself waits for a's completion,
    # ~400ms) — with the uncontrolled scheduler c starts at t=0
    assert started["c"] >= started["b"], started
    assert started["c"] - started["a"] > 0.3, started


class SkewedEmitterForSched(SimpleProcessor):
    def run(self, inputs, outputs):
        w = outputs["c"].get_writer()
        w.write(b"k", 1)


class EmptyInitializer:
    """Initializer resolving a root vertex to ZERO tasks (an empty data
    source — module-level for descriptor resolution)."""

    def __init__(self, context=None):
        self.context = context

    def initialize(self):
        from tez_tpu.api.initializer import InputConfigureVertexTasksEvent
        return [InputConfigureVertexTasksEvent(num_tasks=0)]

    def handle_input_initializer_event(self, events):
        pass


@pytest.mark.parametrize("sched", [
    "tez_tpu.am.dag_scheduler:DAGSchedulerNaturalOrder",
    "tez_tpu.am.dag_scheduler:DAGSchedulerNaturalOrderControlled"])
def test_runtime_empty_source_vertex(client, sched):
    """A root vertex whose initializer resolves to 0 tasks completes
    immediately and must not wedge its consumer under either DAG scheduler
    (regression: 0-task SUCCEEDED transition missing from the vertex state
    table; controlled gate waiting forever on a source that never
    schedules)."""
    from tez_tpu.common.payload import InputInitializerDescriptor
    from tez_tpu.dag.dag import DataSourceDescriptor
    from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                           EdgeProperty, SchedulingType)
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "long"}
    empty = Vertex.create("empty", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor", payload={}), -1)
    empty.add_data_source("src", DataSourceDescriptor.create(
        InputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVInput", payload=kv),
        initializer=InputInitializerDescriptor.create(
            "tests.test_dynamic_control:EmptyInitializer")))
    down = Vertex.create("down", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SimpleProcessor"), 2)
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedPartitionedKVOutput",
            payload=kv),
        InputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVInput", payload=kv))
    dag = DAG.create("emptysrc").add_vertex(empty).add_vertex(down)
    dag.add_edge(Edge.create(empty, down, prop))
    dag.set_conf("tez.am.dag.scheduler.class", sched)
    st = client.submit_dag(dag).wait_for_completion(timeout=45)
    assert st.state is DAGStatusState.SUCCEEDED, st.diagnostics
