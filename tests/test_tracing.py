"""Tracing-plane tests: span runtime, carrier propagation, Perfetto export,
latency histograms, /metrics surface, and the span critical-path analyzer."""
import json
import threading
import typing
import urllib.request

import pytest

from tests.trace_schema import check_trace
from tez_tpu.common import metrics, tracing
from tez_tpu.common.counters import TezCounters


# ------------------------------------------------------------- span runtime

def test_disarmed_is_noop():
    """The disarmed fast path: no spans, no allocations, NOOP singleton."""
    assert not tracing.armed()
    s = tracing.span("anything", cat="x", k=1)
    assert s is tracing.NOOP_SPAN
    with s as inner:
        inner.annotate(a=1)
        inner.event("e")
    tracing.event("standalone")
    assert tracing.snapshot() == []
    assert tracing.current_span() is None
    assert tracing.current_carrier() == ""


def test_armed_records_nested_spans():
    tracing.arm(scope="t")
    with tracing.span("outer", cat="task", vertex="v1") as outer:
        assert tracing.current_span() is outer
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            inner.event("tick", n=1)
    spans = tracing.snapshot()
    assert [s.name for s in spans] == ["inner", "outer"]  # finish order
    assert all(s.end is not None and s.end >= s.start for s in spans)
    assert spans[0].events and spans[0].events[0][1] == "tick"


def test_span_error_capture():
    tracing.arm(scope="t")
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("no")
    (sp,) = tracing.snapshot()
    assert sp.args.get("error", "").startswith("ValueError")


def test_carrier_round_trip_and_attach():
    tracing.arm(scope="t")
    with tracing.span("root") as root:
        carrier = tracing.current_carrier()
    ctx = tracing.parse_carrier(carrier)
    assert ctx == (root.trace_id, root.span_id)
    assert tracing.parse_carrier("") is None
    assert tracing.parse_carrier("00-zz-xx-01") is None
    # a "remote" worker attaches the carrier and parents off it
    with tracing.attached(carrier):
        with tracing.span("remote") as rm:
            assert rm.trace_id == root.trace_id
            assert rm.parent_id == root.span_id


def test_cross_thread_explicit_parent():
    """Fetch-style spans: parent captured on one thread, span on another."""
    tracing.arm(scope="t")
    captured = {}
    with tracing.span("attempt") as att:
        captured["ctx"] = tracing.current_context()

    def fetcher():
        with tracing.span("shuffle.fetch", parent=captured["ctx"]) as f:
            captured["fetch"] = (f.trace_id, f.parent_id)

    th = threading.Thread(target=fetcher)
    th.start()
    th.join()
    assert captured["fetch"] == (att.trace_id, att.span_id)


def test_buffer_survives_disarm_and_is_bounded():
    tracing.arm(scope="t", capacity=8)
    for i in range(20):
        with tracing.span(f"s{i}"):
            pass
    assert len(tracing.snapshot()) == 8              # ring buffer bound
    tracing.clear("t")
    assert not tracing.armed()
    assert len(tracing.snapshot()) == 8              # survives disarm
    assert tracing.span("late") is tracing.NOOP_SPAN  # but records nothing
    tracing.clear_all()
    assert tracing.snapshot() == []


def test_install_from_conf_refcounted():
    from tez_tpu.common import config as C
    conf = C.TezConfiguration({"tez.trace.enabled": True})
    assert tracing.install_from_conf(conf, scope="dag1")
    assert tracing.install_from_conf(conf, scope="dag2")
    tracing.clear("dag1")
    assert tracing.armed()                            # dag2 still holds it
    tracing.clear("dag2")
    assert not tracing.armed()
    off = C.TezConfiguration({})
    assert not tracing.install_from_conf(off, scope="dag3")
    assert not tracing.armed()


# ---------------------------------------------------------- perfetto export

def test_spans_export_valid_trace_event_json():
    from tez_tpu.tools import trace_export
    tracing.arm(scope="t")
    with tracing.span("outer", cat="task", vertex="v"):
        with tracing.span("inner"):
            pass
        tracing.event("fence.stale_epoch", seam="umbilical")
    trace = trace_export.spans_to_trace(tracing.snapshot())
    n = check_trace(json.loads(json.dumps(trace)))
    assert n >= 4  # 2 X spans + 1 instant + >=1 thread_name metadata
    names = [e["name"] for e in trace["traceEvents"]]
    assert {"outer", "inner", "fence.stale_epoch", "thread_name"} <= set(names)
    x = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert all(e["args"]["trace_id"] for e in x)


def test_critical_path_picks_dominant():
    from tez_tpu.tools.trace_export import (critical_path,
                                            critical_path_report)
    tracing.arm(scope="t")
    with tracing.span("dag", cat="dag") as root:
        with tracing.span("fast", vertex="a"):
            pass
        with tracing.span("slow", vertex="b") as slow:
            slow.start -= 0.5                          # fake 500ms of work
    spans = tracing.snapshot()
    path = critical_path(spans)
    assert [s.name for s in path] == ["dag", "slow"]
    assert path[0].trace_id == root.trace_id
    rep = critical_path_report(spans)
    assert rep["dominant"]["name"] == "slow"
    assert rep["dominant"]["vertex"] == "b"
    assert rep["chain"][0]["name"] == "dag"


# ------------------------------------------------------- latency histograms

def test_histogram_buckets_and_quantiles():
    h = metrics.Histogram("x")
    for ms in (0.5, 3, 3, 700, 1e9):
        h.observe(ms)
    d = h.to_dict()
    assert d["count"] == 5
    assert sum(d["counts"]) == 5
    assert d["counts"][-1] == 1                       # 1e9 ms -> overflow
    assert metrics.bucket_index(0.5) == 0
    assert metrics.bucket_index(1.0) == 0
    assert metrics.bucket_index(1.5) == 1
    assert metrics.bucket_index(65536.0) == 16
    assert metrics.bucket_index(65537.0) == 17
    assert 0 < h.quantile(0.5) <= 4.0
    assert h.quantile(0.95) >= 512.0


def test_observe_mirrors_into_counters_and_aggregates():
    """Bucket counters roll up task->vertex->DAG through plain aggregate()."""
    c1, c2 = TezCounters(), TezCounters()
    metrics.observe("shuffle.fetch.rtt", 3.0, counters=c1)
    metrics.observe("shuffle.fetch.rtt", 100.0, counters=c2)
    agg = TezCounters()
    agg.aggregate(c1)
    agg.aggregate(c2)
    hists = metrics.histograms_from_counters(agg.to_dict())
    h = hists["shuffle.fetch.rtt"]
    assert h["count"] == 2
    assert h["sum_us"] == 103000
    assert h["max_ms"] == 128.0


def test_prometheus_render_is_well_formed():
    metrics.observe("spill.write", 12.0)
    metrics.set_gauge("running_tasks", 3)
    text = metrics.render_prometheus(metrics.registry().histograms(),
                                     metrics.registry().gauges())
    lines = text.splitlines()
    assert text.endswith("\n")
    hist = [ln for ln in lines if ln.startswith("tez_latency_spill_write_ms")]
    assert any('le="+Inf"' in ln for ln in hist)
    assert any(ln.startswith("tez_latency_spill_write_ms_sum") for ln in hist)
    assert any(ln.startswith("tez_latency_spill_write_ms_count 1") for ln in hist)
    # cumulative buckets never decrease
    vals = [int(ln.rsplit(" ", 1)[1]) for ln in hist if "_bucket" in ln]
    assert vals == sorted(vals)
    assert "tez_running_tasks 3" in text
    # every sample line is "name{labels} value" or "name value"
    for ln in lines:
        if ln.startswith("#") or not ln:
            continue
        assert len(ln.rsplit(" ", 1)) == 2, ln


def test_counter_diff_histogram_regression():
    from tez_tpu.tools.counter_diff import diff_histograms, flatten
    a, b = TezCounters(), TezCounters()
    for _ in range(20):
        metrics.observe("shuffle.fetch.rtt", 10.0, counters=a)
        metrics.observe("shuffle.fetch.rtt", 300.0, counters=b)
    rows = diff_histograms(a.to_dict(), b.to_dict())
    (name, sa, sb, regressed) = rows[0]
    assert name == "shuffle.fetch.rtt" and regressed
    assert sb["p95"] > sa["p95"]
    # same distribution -> no regression flag
    rows = diff_histograms(a.to_dict(), a.to_dict())
    assert not rows[0][3]
    # histogram groups are kept out of the plain counter diff
    assert flatten(a.to_dict()) == {}


def test_limits_configure_annotations_resolve():
    """Regression: Limits.configure used 'Any' without importing it, which
    blew up only when annotations were evaluated."""
    from tez_tpu.common import counters as counters_mod
    hints = typing.get_type_hints(counters_mod.Limits.configure.__func__,
                                  vars(counters_mod))
    assert hints["conf"] is typing.Any


# --------------------------------------------------- swimlane / history r-t

def test_swimlane_history_round_trip(tmp_path):
    """History JSONL -> DagInfo -> swimlane SVG: lane count matches the
    containers used, every attempt renders one bar, bar geometry is
    monotonic with attempt duration."""
    import re
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    from tez_tpu.tools.history_parser import parse_jsonl_files
    from tez_tpu.tools.swimlane import LEFT, render_svg
    hist = str(tmp_path / "hist")
    c = TezClient.create("lane", {
        "tez.staging-dir": str(tmp_path / "s"),
        "tez.history.logging.service.class":
            "tez_tpu.am.history:JsonlHistoryLoggingService",
        "tez.history.logging.log-dir": hist}).start()
    try:
        dag = DAG.create("lanedag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 5}), 3))
        st = c.submit_dag(dag).wait_for_completion(timeout=30)
        assert st.state.name == "SUCCEEDED"
    finally:
        c.stop()
    dag_info = list(parse_jsonl_files([hist]).values())[0]
    attempts = [a for a in dag_info.all_attempts() if a.start_time]
    assert len(attempts) == 3
    containers = {a.container_id for a in attempts}
    svg = render_svg(dag_info)
    bars = re.findall(r'<rect x="([\d.]+)" y="\d+" width="([\d.]+)"[^>]*>'
                      r'<title>(attempt_\S+)', svg)
    assert len(bars) == len(attempts)                 # one bar per attempt
    assert len(re.findall(r'<text x="4" y="\d+">', svg)) - 1 \
        == len(containers)                            # one label per lane
    by_id = {a.attempt_id: a for a in attempts}
    for x, w, aid in bars:
        a = by_id[aid]
        assert float(x) >= LEFT                        # bars start in-lane
        assert float(w) >= 2.0                         # min visible width
        # longer attempts never render narrower than much-shorter ones
    durs = sorted((by_id[aid].duration, float(w)) for x, w, aid in bars)
    for (d0, w0), (d1, w1) in zip(durs, durs[1:]):
        if d1 - d0 > 0.05:                             # beyond min-width blur
            assert w1 >= w0


# ----------------------------------------------------------- e2e trace plane

def test_e2e_trace_and_metrics(tmp_path):
    """A real DAG with tez.trace.enabled: one trace id links dag, attempt,
    and shuffle spans; /metrics and /trace serve from the same run; the
    span critical-path analyzer names the dominant vertex."""
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.tools import trace_export
    from tez_tpu.tools.analyzers import SpanCriticalPathAnalyzer
    from tez_tpu.tools.chaos import _build_dag
    result = str(tmp_path / "result.txt")
    c = TezClient.create("traced", {
        "tez.staging-dir": str(tmp_path / "s"),
        "tez.am.web.enabled": True}).start()
    try:
        dag = _build_dag("traced", result, trace=True)
        st = c.submit_dag(dag).wait_for_completion(timeout=60)
        assert st.state.name == "SUCCEEDED"
        url = c.framework_client.am.web_ui.url
        prom = urllib.request.urlopen(url + "metrics").read().decode()
        trace_json = json.loads(
            urllib.request.urlopen(url + "trace").read())
        dag_impl = c.framework_client.am.current_dag
    finally:
        c.stop()

    spans = tracing.snapshot()
    assert spans, "no spans recorded with tez.trace.enabled"
    by_cat = {}
    for s in spans:
        by_cat.setdefault(s.cat, []).append(s)
    (dag_span,) = by_cat["dag"]
    assert dag_span.end is not None                    # finished on dag end
    attempts = by_cat["task"]
    assert any(s.name.startswith("attempt:") for s in attempts)
    fetches = [s for s in by_cat.get("shuffle", [])
               if s.name == "shuffle.fetch"]
    assert fetches, "no shuffle.fetch spans"
    # causality: every attempt and fetch span shares the DAG's trace id
    for s in attempts + fetches:
        assert s.trace_id == dag_span.trace_id, s.name

    # exported trace validates against the trace_event schema
    check_trace(trace_export.spans_to_trace(spans))
    assert trace_json["traceEvents"], "GET /trace returned an empty trace"
    check_trace(trace_json)

    # /metrics: valid-ish prometheus with the two acceptance histograms
    assert "# TYPE tez_latency_shuffle_fetch_rtt_ms histogram" in prom
    assert "# TYPE tez_latency_spill_write_ms histogram" in prom
    assert "tez_running_tasks" in prom
    assert "tez_am_epoch" in prom

    # analyzer names the dominant vertex of the scatter-gather DAG
    res = SpanCriticalPathAnalyzer().analyze(dag_impl)
    assert "dominant vertex:" in res.headline, res.headline
    assert ("producer" in res.headline) or ("consumer" in res.headline), \
        res.headline
