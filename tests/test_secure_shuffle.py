"""Encrypted shuffle + umbilical (TestSecureShuffle.java:70 analog).

A self-signed CA + endpoint cert generated per test session; the shuffle
server/fetcher and the AM umbilical run mutual TLS, HMAC handshakes run
inside the encrypted channel, plaintext clients are rejected, and a full
subprocess-runner DAG (cross-process umbilical + TCP shuffle) completes
over TLS end to end.
"""
import datetime
import os
import socket
import ssl

import numpy as np
import pytest

from tez_tpu.common.security import JobTokenSecretManager
from tez_tpu.common.tls import client_context, server_context, tls_config
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.shuffle.server import FetchSession, ShuffleServer
from tez_tpu.shuffle.service import ShuffleService


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed CA signing one endpoint cert (mutual TLS: every
    endpoint presents the same identity, verified against the CA).
    Skips when the environment can't generate fixtures (no cryptography
    wheel) — the TLS plane itself is stdlib-ssl only."""
    pytest.importorskip(
        "cryptography", reason="cert-fixture generation needs cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    d = tmp_path_factory.mktemp("certs")

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def _write_key(key, path):
        path.write_bytes(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))

    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = _key()
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                            "tez-test-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=1))
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))
    node_key = _key()
    node_cert = (x509.CertificateBuilder()
                 .subject_name(x509.Name([x509.NameAttribute(
                     NameOID.COMMON_NAME, "tez-node")]))
                 .issuer_name(ca_name)
                 .public_key(node_key.public_key())
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(now - datetime.timedelta(minutes=5))
                 .not_valid_after(now + datetime.timedelta(days=1))
                 .add_extension(x509.SubjectAlternativeName(
                     [x509.DNSName("localhost"),
                      x509.IPAddress(__import__("ipaddress")
                                     .ip_address("127.0.0.1"))]),
                     critical=False)
                 .sign(ca_key, hashes.SHA256()))
    (d / "ca.pem").write_bytes(ca_cert.public_bytes(
        serialization.Encoding.PEM))
    (d / "node.pem").write_bytes(node_cert.public_bytes(
        serialization.Encoding.PEM))
    _write_key(node_key, d / "node.key")
    return {"ca": str(d / "ca.pem"), "cert": str(d / "node.pem"),
            "key": str(d / "node.key")}


def _tls_conf(certs, extra=None):
    conf = {"tez.runtime.shuffle.ssl.enable": True,
            "tez.shuffle.ssl.cert.path": certs["cert"],
            "tez.shuffle.ssl.key.path": certs["key"],
            "tez.shuffle.ssl.ca.path": certs["ca"]}
    conf.update(extra or {})
    return conf


def _sample_run():
    batch = KVBatch.from_pairs([(f"k{i:03d}".encode(), b"v" * 8)
                                for i in range(50)])
    return Run(batch, np.array([0, 25, 50], dtype=np.int64))


def test_secure_fetch_roundtrip_and_plaintext_rejected(certs):
    """Fetches succeed over mutual TLS; a plaintext client cannot speak to
    the TLS server (no silent downgrade)."""
    conf = _tls_conf(certs)
    secrets = JobTokenSecretManager(b"tok" * 8)
    service = ShuffleService()
    run = _sample_run()
    service.register("attempt_x", -1, run)
    server = ShuffleServer(secrets, service,
                           ssl_context=server_context(conf)).start()
    try:
        session = FetchSession(secrets, "127.0.0.1", server.port,
                               ssl_context=client_context(conf))
        got = session.fetch("attempt_x", -1, 0)
        session.close()
        assert list(got.iter_pairs()) == list(run.partition(0).iter_pairs())
        # plaintext client: the TLS accept fails the connection — the
        # 16-byte nonce greeting never arrives in cleartext
        with pytest.raises((ConnectionError, OSError)):
            FetchSession(secrets, "127.0.0.1", server.port)
    finally:
        server.stop()


def test_tls_client_rejects_untrusted_server(certs, tmp_path):
    """A server whose cert is NOT signed by the client's CA is refused
    (fetcher-side verification — the SSLFactory truststore role)."""
    import subprocess
    import sys
    other = tmp_path / "other"
    other.mkdir()
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(other / "k.pem"), "-out", str(other / "c.pem"),
         "-days", "1", "-subj", "/CN=rogue"],
        check=True, capture_output=True)
    rogue_conf = _tls_conf(certs, {
        "tez.shuffle.ssl.cert.path": str(other / "c.pem"),
        "tez.shuffle.ssl.key.path": str(other / "k.pem")})
    secrets = JobTokenSecretManager(b"tok" * 8)
    server = ShuffleServer(secrets, ShuffleService(),
                           ssl_context=server_context(rogue_conf)).start()
    try:
        with pytest.raises((ssl.SSLError, ConnectionError, OSError)):
            FetchSession(secrets, "127.0.0.1", server.port,
                         ssl_context=client_context(_tls_conf(certs)))
    finally:
        server.stop()


def test_tls_config_validation(certs):
    assert tls_config({}) is None
    assert tls_config({"tez.runtime.shuffle.ssl.enable": False}) is None
    with pytest.raises(ValueError, match="not configured"):
        tls_config({"tez.runtime.shuffle.ssl.enable": True})
    with pytest.raises(ValueError, match="not found"):
        tls_config(_tls_conf(certs,
                             {"tez.shuffle.ssl.ca.path": "/nope.pem"}))


def test_secure_shuffle_dag_e2e(certs, tmp_path):
    """TestSecureShuffle analog: a subprocess-runner wordcount — runner
    processes dial the AM umbilical and each other's shuffle servers over
    mutual TLS — produces correct, verified output."""
    import collections
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount

    corpus = tmp_path / "in.txt"
    golden = collections.Counter()
    import random
    rng = random.Random(3)
    with open(corpus, "w") as fh:
        for _ in range(2000):
            w = f"w{rng.randint(0, 99):02d}"
            golden[w] += 1
            fh.write(w + " ")
    out = str(tmp_path / "out")
    conf = _tls_conf(certs, {
        "tez.staging-dir": str(tmp_path / "stg"),
        "tez.runner.mode": "subprocess",
        "tez.am.local.num-containers": 2,
        "tez.am.runner.env": {"JAX_PLATFORMS": "cpu"}})
    with TezClient.create("secure-wc", conf) as c:
        dag = ordered_wordcount.build_dag(
            [str(corpus)], out, tokenizer_parallelism=2,
            summation_parallelism=2, sorter_parallelism=1)
        status = c.submit_dag(dag).wait_for_completion(timeout=120)
        assert status.state.name == "SUCCEEDED", status
        # the umbilical server really is TLS: a plaintext umbilical
        # greets with a 16-byte nonce IMMEDIATELY on connect; a TLS
        # server sends nothing until a ClientHello, then answers a
        # plaintext frame with a TLS alert (0x15) or a hard close
        port = c.framework_client.am.umbilical_server.port
        raw = socket.create_connection(("127.0.0.1", port), timeout=5)
        try:
            raw.settimeout(2)
            try:
                greeting = raw.recv(16)
            except (TimeoutError, OSError):
                greeting = b""
            assert greeting == b"", \
                "umbilical sent a plaintext greeting — TLS is off"
            raw.sendall(b"\x00\x00\x00\x02{}")
            try:
                data = raw.recv(64)
            except (ConnectionError, TimeoutError, OSError):
                data = b""
            assert data == b"" or data[:1] == b"\x15", data
        finally:
            raw.close()
    rows = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, cnt = line.rstrip(b"\n").split(b"\t")
                rows[w.decode()] = int(cnt)
    assert rows == dict(golden)
