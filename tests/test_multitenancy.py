"""Multi-tenant session AM tests (docs/multitenancy.md).

Covers the admission controller's three verdicts (ACCEPT / QUEUE / SHED)
and the lossless-admission ledger a killed queue consumer leaves behind;
deficit-round-robin tenant fair-share in the task scheduler; per-tenant
store byte quotas and the governed result cache (TTL, admission policy,
per-tenant cap); and whole-session integration — concurrent DAGs through
one resident AM, typed shed + jittered resubmit, and zero epoch fences
with two live DAGs.
"""
from __future__ import annotations

import collections
import itertools
import pickle
import random
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from tez_tpu.am.admission import AdmissionController
from tez_tpu.am.history import HistoryEventType
from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.errors import DAGRejectedError
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import config as C
from tez_tpu.common import faults, metrics
from tez_tpu.common.ids import DAGId
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.dag.plan import DAGPlan
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.store.buffer_store import (DISK, HOST, ShuffleBufferStore,
                                        StoreKeyNotFound, StoreQuotaExceeded)


def _wait_until(pred, timeout: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def sleep_vertex(name, parallelism, sleep_ms=1):
    return Vertex.create(name, ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": sleep_ms}), parallelism)


def make_test_vertex(name, parallelism):
    return Vertex.create(name, ProcessorDescriptor.create(
        "tez_tpu.library.test_components:TestProcessor"), parallelism)


def tedge(a, b, movement=DataMovementType.SCATTER_GATHER):
    return Edge.create(a, b, EdgeProperty.create(
        movement, DataSourceType.PERSISTED, SchedulingType.SEQUENTIAL,
        OutputDescriptor.create("tez_tpu.library.test_components:TestOutput"),
        InputDescriptor.create("tez_tpu.library.test_components:TestInput")))


def _plan(name: str, tenant: str = "", sleep_ms: int = 1):
    dag = DAG.create(name).add_vertex(sleep_vertex("v", 1, sleep_ms))
    if tenant:
        dag.set_conf("tez.dag.tenant", tenant)
    return dag.create_dag_plan({})


# ------------------------------------------------ admission verdicts (unit)

class _StubAM:
    """Just enough DAGAppMaster surface for AdmissionController: conf,
    app_id, the history sink, and a _start_dag that mints fresh ids."""

    def __init__(self, conf=None):
        self.conf = C.TezConfiguration(conf or {})
        self.app_id = "app_admit_1"
        self.events = []
        self.start_exc = None
        self._seq = itertools.count(1)

    def history(self, ev):
        self.events.append(ev)

    def _start_dag(self, plan, recovery_data, tenant, sub_id=None):
        if self.start_exc is not None:
            raise self.start_exc
        return f"dag_{next(self._seq)}"

    def of(self, t):
        return [e for e in self.events if e.event_type is t]


@pytest.fixture()
def admit2():
    am = _StubAM({"tez.am.session.max-concurrent-dags": 1,
                  "tez.am.session.queue-size": 4,
                  "tez.am.session.shed.retry-after-ms": 250})
    ac = AdmissionController(am)
    yield am, ac
    ac.stop()


def test_admission_accept_immediate(admit2):
    am, ac = admit2
    assert ac.submit(_plan("d1", tenant="acme")) == "dag_1"
    st = ac.status()
    assert st["running"] == 1 and st["queue_depth"] == 0
    assert st["consumer_alive"]
    assert st["tenants"]["acme"] == {
        "running": 1, "queued": 0, "accepted": 1, "shed": 0,
        "completed": 0, "failed": 0}


def test_admission_queue_journals_then_promotes(admit2):
    am, ac = admit2
    ac.submit(_plan("d1", tenant="acme"))
    got = {}

    def second():
        got["dag_id"] = ac.submit(_plan("d2", tenant="acme"))

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert _wait_until(lambda: ac.status()["queue_depth"] == 1)
    # the lossless-admission contract: the parked plan is journaled
    # BEFORE the submitter blocks, and it round-trips byte-exact
    queued = am.of(HistoryEventType.DAG_QUEUED)
    assert len(queued) == 1
    plan = DAGPlan.deserialize(bytes.fromhex(queued[0].data["plan"]))
    assert plan.name == "d2" and queued[0].data["tenant"] == "acme"
    # free the slot -> the consumer promotes the parked submission
    ac.on_dag_finished("acme", "SUCCEEDED", 5.0)
    t.join(timeout=10)
    assert got.get("dag_id") == "dag_2"
    st = ac.status()
    assert st["queue_depth"] == 0 and st["running"] == 1
    assert st["tenants"]["acme"]["completed"] == 1
    h = metrics.registry().histograms().get("am.admit.queue_wait")
    assert h is not None and h.count >= 1


def test_admission_shed_queue_full():
    am = _StubAM({"tez.am.session.max-concurrent-dags": 1,
                  "tez.am.session.queue-size": 0,
                  "tez.am.session.shed.retry-after-ms": 250})
    ac = AdmissionController(am)
    try:
        ac.submit(_plan("d1", tenant="acme"))
        with pytest.raises(DAGRejectedError) as ei:
            ac.submit(_plan("d2", tenant="acme"))
        e = ei.value
        assert "queue full" in e.reason
        assert e.retry_after_s == pytest.approx(0.25)
        assert e.tenant == "acme" and e.queue_depth == 0
        shed = am.of(HistoryEventType.DAG_ADMISSION_SHED)
        assert len(shed) == 1 and shed[0].data["dag_name"] == "d2"
        assert shed[0].data["retry_after_ms"] == pytest.approx(250.0)
        st = ac.status()
        assert st["tenants"]["acme"]["shed"] == 1
        # shed contract: nothing server-side remembers the submission
        assert ac.unresolved() == []
    finally:
        ac.stop()


def test_admission_shed_tenant_inflight_cap():
    am = _StubAM({"tez.am.session.max-concurrent-dags": 4,
                  "tez.am.session.tenant.max-inflight": 1})
    ac = AdmissionController(am)
    try:
        ac.submit(_plan("a1", tenant="acme"))
        with pytest.raises(DAGRejectedError) as ei:
            ac.submit(_plan("a2", tenant="acme"))
        assert "max-inflight" in ei.value.reason
        assert ei.value.tenant_inflight == 1
        # another tenant is not collateral damage
        ac.submit(_plan("b1", tenant="beta"))
        st = ac.status()
        assert st["tenants"]["acme"]["shed"] == 1
        assert st["tenants"]["beta"]["accepted"] == 1
    finally:
        ac.stop()


def test_admission_fault_forced_shed(admit2):
    am, ac = admit2
    faults.install("mt-shed", faults.parse_spec("am.admit.shed:fail:n=1"))
    with pytest.raises(DAGRejectedError) as ei:
        ac.submit(_plan("d1", tenant="acme"))
    assert "fault-injected shed" in ei.value.reason
    assert ac.submit(_plan("d2", tenant="acme")) == "dag_1"


def test_admission_rollback_on_start_failure(admit2):
    am, ac = admit2
    am.start_exc = RuntimeError("container pool exploded")
    with pytest.raises(RuntimeError, match="container pool exploded"):
        ac.submit(_plan("d1", tenant="acme"))
    st = ac.status()
    assert st["running"] == 0
    assert st["tenants"]["acme"]["failed"] == 1
    # the slot is actually free again, not leaked
    am.start_exc = None
    assert ac.submit(_plan("d2", tenant="acme")) == "dag_1"


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_queue_consumer_kill_leaves_lossless_ledger(admit2):
    """Regression for the lossless-admission contract: kill the queue
    consumer mid-drain (am.queue.delay:fail fires after the pop, before
    _start_dag) — the DAG_QUEUED ledger record and unresolved() must
    still account for the submission; nothing is silently dropped."""
    am, ac = admit2
    ac.submit(_plan("d1", tenant="acme"))
    faults.install("mt-kill", faults.parse_spec("am.queue.delay:fail:n=1"))
    t = threading.Thread(
        target=lambda: ac.submit(_plan("d2q", tenant="acme")), daemon=True)
    t.start()
    assert _wait_until(lambda: ac.status()["queue_depth"] == 1)
    ac.on_dag_finished("acme", "SUCCEEDED", 5.0)   # consumer pops -> dies
    assert _wait_until(lambda: not ac.consumer_alive())
    queued = am.of(HistoryEventType.DAG_QUEUED)
    assert len(queued) == 1
    sub_id = queued[0].dag_id
    # the popped-but-never-started submission is still visible ...
    assert ac.unresolved() == [sub_id]
    # ... and its full plan survives in the ledger for replay on restart
    plan = DAGPlan.deserialize(bytes.fromhex(queued[0].data["plan"]))
    assert plan.name == "d2q"
    assert t.is_alive(), "submitter must still be blocked, not dropped"
    # unblock the submitter the way an AM restart would (resolve with error)
    ac._draining.error = RuntimeError("AM restarting; replay from ledger")
    ac._draining.done.set()
    t.join(timeout=10)


def test_rejected_error_pickles_with_hint():
    e = DAGRejectedError("queue full", retry_after_s=0.75, tenant="acme",
                         queue_depth=3, tenant_inflight=2)
    e2 = pickle.loads(pickle.dumps(e))
    assert isinstance(e2, DAGRejectedError)
    assert (e2.reason, e2.retry_after_s, e2.tenant, e2.queue_depth,
            e2.tenant_inflight) == ("queue full", 0.75, "acme", 3, 2)
    assert "RETRY-AFTER 0.750s" in str(e2)


# ------------------------------------------------ DRR fair-share (unit)

def _drr_sched(weights: str, fair_share: bool = True, slots: int = 1):
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    ctx = SimpleNamespace(
        conf=C.TezConfiguration({
            "tez.am.session.fair-share": fair_share,
            "tez.am.session.tenant.weights": weights}),
        ensure_runners=lambda backlog: None, dispatch=lambda e: None)
    return LocalTaskSchedulerService(ctx, num_slots=slots)


def _drr_handouts(sched, per_tenant, n):
    va = DAGId("app_drr_p", 1).vertex(0)
    vb = DAGId("app_drr_p", 2).vertex(0)
    for i in range(per_tenant):
        sched.schedule(va.task(i).attempt(0),
                       SimpleNamespace(tenant="A"), priority=5)
        sched.schedule(vb.task(i).attempt(0),
                       SimpleNamespace(tenant="B"), priority=5)
    return "".join(
        sched.get_task(f"c{i}", timeout=0.2).tenant for i in range(n))


def test_drr_honors_weights_2_to_1():
    order = _drr_handouts(_drr_sched("A=2,B=1"), per_tenant=12, n=12)
    counts = collections.Counter(order)
    assert counts["A"] == 8 and counts["B"] == 4, order
    # interleaved, not front-loaded: A never gets more than its burst
    assert "AAA" not in order and "BB" not in order, order


def test_drr_equal_weights_alternate():
    order = _drr_handouts(_drr_sched("A=1,B=1"), per_tenant=8, n=12)
    counts = collections.Counter(order)
    assert counts["A"] == 6 and counts["B"] == 6, order
    assert "AA" not in order and "BB" not in order, order


def test_drr_fractional_weight_still_served():
    # w < 1 accumulates credit across rotations instead of starving
    order = _drr_handouts(_drr_sched("A=0.5,B=1"), per_tenant=12, n=12)
    counts = collections.Counter(order)
    assert counts["A"] == 4 and counts["B"] == 8, order


def test_drr_work_conserving_when_tenant_drains():
    sched = _drr_sched("A=2,B=1")
    va = DAGId("app_drr_p", 1).vertex(0)
    vb = DAGId("app_drr_p", 2).vertex(0)
    for i in range(2):
        sched.schedule(va.task(i).attempt(0),
                       SimpleNamespace(tenant="A"), priority=5)
    for i in range(6):
        sched.schedule(vb.task(i).attempt(0),
                       SimpleNamespace(tenant="B"), priority=5)
    out = [sched.get_task(f"c{i}", timeout=0.2) for i in range(8)]
    assert all(s is not None for s in out), "idle slots with queued work"
    assert collections.Counter(s.tenant for s in out) == {"A": 2, "B": 6}


def test_drr_off_falls_back_to_priority():
    sched = _drr_sched("A=8,B=1", fair_share=False)
    va = DAGId("app_drr_p", 1).vertex(0)
    vb = DAGId("app_drr_p", 2).vertex(0)
    sched.schedule(va.task(0).attempt(0),
                   SimpleNamespace(tenant="A"), priority=20)
    sched.schedule(vb.task(0).attempt(0),
                   SimpleNamespace(tenant="B"), priority=5)
    # plain priority order: B's high-priority task first despite A's weight
    assert sched.get_task("c0", timeout=0.2).tenant == "B"
    assert sched.get_task("c1", timeout=0.2).tenant == "A"


# ------------------------------------------------ store quotas + result cache

def _run(n: int = 64, parts: int = 2, seed: int = 0) -> Run:
    rng = random.Random(seed)
    pairs = [(b"k%06d" % rng.randrange(10_000), b"v%04d" % (i % 97))
             for i in range(n)]
    batch = KVBatch.from_pairs(sorted(pairs))
    bounds = np.linspace(0, n, parts + 1).astype(np.int64)
    return Run(batch, bounds)


def test_tenant_host_quota_rejects_and_isolates(tmp_path):
    run = _run()
    s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 30,
                           disk_dir=str(tmp_path / "q"),
                           tenant_host_quota=int(run.nbytes))
    try:
        s.publish("dagA/a0/c", -1, run, tenant="acme")
        with pytest.raises(StoreQuotaExceeded) as ei:
            s.publish("dagA/a1/c", -1, _run(seed=1), tenant="acme")
        assert ei.value.tenant == "acme" and ei.value.tier == HOST
        assert s.counters["store.quota.rejected.host"] == 1
        # the quota is per tenant, not global: beta still publishes
        s.publish("dagB/b0/c", -1, _run(seed=2), tenant="beta")
        tb = s.tenant_bytes()
        assert set(tb) == {"acme", "beta"}
        assert tb["acme"][HOST] == run.nbytes
    finally:
        s.close()


def test_result_cache_ttl_expires_sealed_entries(tmp_path):
    now = [1000.0]
    s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 30,
                           disk_dir=str(tmp_path / "t"),
                           clock=lambda: now[0], result_cache_ttl=10.0)
    try:
        s.publish("dagA/a0/c", 0, _run(), lineage="L1", tenant="acme")
        assert s.seal_lineage("dagA") == 1
        assert s.lineage_spills("L1") == [0]
        now[0] += 11.0
        assert s.lineage_spills("L1") == []
        assert s.counters["store.result_cache.expired"] == 1
    finally:
        s.close()


def test_result_cache_second_use_admission(tmp_path):
    s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 30,
                           disk_dir=str(tmp_path / "s"),
                           result_cache_admit="second-use")
    try:
        s.publish("dagA/a0/c", 0, _run(), lineage="L2", tenant="acme")
        # first seal defers: the tag has never been probed (scan resistance)
        assert s.seal_lineage("dagA") == 0
        assert s.counters["store.result_cache.deferred"] == 1
        assert s.lineage_spills("L2") == []       # miss records the tag
        assert s.seal_lineage("dagA") == 1        # second use admits
        assert s.lineage_spills("L2") == [0]
    finally:
        s.close()


def test_result_cache_tenant_cap_evicts_lru(tmp_path):
    run = _run()
    s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 30,
                           disk_dir=str(tmp_path / "c"),
                           result_cache_bytes=int(run.nbytes))
    try:
        s.publish("dagA/x/c", 0, run, lineage="La", tenant="acme")
        s.publish("dagA/y/c", 0, _run(seed=1), lineage="Lb", tenant="acme")
        assert s.seal_lineage("dagA") == 2
        # only one seal fits under the per-tenant cap: the LRU one (La,
        # sealed first, never hit) was evicted to admit Lb
        assert s.counters["store.result_cache.evicted"] == 1
        assert s.lineage_spills("La") == []
        assert s.lineage_spills("Lb") == [0]
    finally:
        s.close()


def test_concurrent_dag_finish_races_seal_and_unregister(tmp_path):
    """Two DAGs commit at once: each seals its lineage then drops its DAG
    aliases (the AM's SUCCEEDED path) while readers fetch — byte
    accounting and tenant attribution must come out exact."""
    s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 30,
                           disk_dir=str(tmp_path / "r"))
    spills, errs = 8, []
    runs = {t: [_run(seed=10 * i + hash(t) % 7) for i in range(spills)]
            for t in ("acme", "beta")}
    try:
        for tenant, rs in runs.items():
            for i, r in enumerate(rs):
                s.publish(f"dag-{tenant}/a{i}/c", 0, r,
                          lineage=f"{tenant}-L{i}", tenant=tenant)
        start = threading.Barrier(3)

        def commit(tenant):
            try:
                start.wait(timeout=10)
                assert s.seal_lineage(f"dag-{tenant}") == spills
                s.unregister_prefix(f"dag-{tenant}")
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        def read():
            try:
                start.wait(timeout=10)
                for i in range(spills):
                    try:
                        s.fetch_partition("dag-acme/a%d/c" % i, 0, 0)
                    except StoreKeyNotFound:
                        pass          # unregistered mid-read: a clean miss
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=commit, args=(t,), daemon=True)
              for t in runs] + [threading.Thread(target=read, daemon=True)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not errs, errs
        # every surviving entry is a sealed lineage alias; bytes and
        # tenant attribution both still balance exactly
        st = s.stats()
        assert st["entries"] == 2 * spills
        want = {t: sum(r.nbytes for r in rs) for t, rs in runs.items()}
        tb = s.tenant_bytes()
        assert {t: tb[t][HOST] for t in runs} == want
        assert st["bytes"][HOST] == sum(want.values())
        for tenant in runs:
            for i in range(spills):
                assert s.lineage_spills(f"{tenant}-L{i}") == [0]
    finally:
        s.close()


# ------------------------------------------------ session integration

def test_session_concurrent_dags_one_am(tmp_staging):
    conf = {"tez.staging-dir": tmp_staging,
            "tez.am.local.num-containers": 4,
            "tez.am.session.max-concurrent-dags": 2,
            "tez.am.session.queue-size": 4}
    client = TezClient.create("mt-sess", conf, session=True).start()
    states, errs = {}, []
    try:
        start = threading.Barrier(3)

        def one(i):
            try:
                dag = DAG.create(f"mt{i}").add_vertex(
                    sleep_vertex("v", 2, sleep_ms=50))
                dag.set_conf("tez.dag.tenant", f"t{i % 2}")
                start.wait(timeout=10)
                dc = client.submit_dag(dag)
                states[i] = dc.wait_for_completion(timeout=60).state
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=one, args=(i,), daemon=True)
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=90)
        assert not errs, errs
        assert all(states[i] is DAGStatusState.SUCCEEDED for i in range(3))
        qs = client.queue_status()
        assert qs["running"] == 0 and qs["queue_depth"] == 0
        assert qs["consumer_alive"] and qs["live_dags"] == {}
        assert sum(t["completed"] for t in qs["tenants"].values()) == 3
    finally:
        client.stop()


def test_session_shed_typed_error_then_retry_succeeds(tmp_staging):
    from tez_tpu.utils.backoff import ExponentialBackoff
    conf = {"tez.staging-dir": tmp_staging,
            "tez.am.local.num-containers": 2,
            "tez.am.session.max-concurrent-dags": 1,
            "tez.am.session.queue-size": 2,
            "tez.am.session.shed.retry-after-ms": 20}
    client = TezClient.create("mt-shed", conf, session=True).start()
    try:
        faults.install("mt-shed-it",
                       faults.parse_spec("am.admit.shed:fail:n=2"))
        dag1 = DAG.create("shed1").add_vertex(sleep_vertex("v", 1))
        with pytest.raises(DAGRejectedError) as ei:
            client.submit_dag(dag1)
        assert ei.value.retry_after_s == pytest.approx(0.02)
        # the retry helper eats the second forced shed and then lands
        dag2 = DAG.create("shed2").add_vertex(sleep_vertex("v", 1))
        dc = client.submit_dag_with_retry(
            dag2, retries=5,
            backoff=ExponentialBackoff(base=0.01, cap=0.05, jitter=True,
                                       rng=random.Random(0)))
        assert dc.wait_for_completion(timeout=60).state is \
            DAGStatusState.SUCCEEDED
        qs = client.queue_status()
        assert sum(t["shed"] for t in qs["tenants"].values()) == 2
    finally:
        client.stop()


def test_two_live_dags_zero_epoch_fences(tmp_staging):
    """Two traced shuffle DAGs running concurrently in one AM must never
    trip the epoch fence — per-DAG registration prefixes and the shared
    epoch registry stay disjoint."""
    from tez_tpu.common import tracing
    conf = {"tez.staging-dir": tmp_staging,
            "tez.am.local.num-containers": 4,
            "tez.am.session.max-concurrent-dags": 2}
    client = TezClient.create("mt-fence", conf, session=True).start()
    states, errs = {}, []
    try:
        start = threading.Barrier(2)

        def one(i):
            try:
                a, b = make_test_vertex("a", 2), make_test_vertex("b", 2)
                dag = DAG.create(f"fence{i}").add_vertex(a).add_vertex(b)
                dag.add_edge(tedge(a, b))
                dag.set_conf("tez.trace.enabled", True)
                start.wait(timeout=10)
                states[i] = client.submit_dag(dag).wait_for_completion(
                    timeout=60).state
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=one, args=(i,), daemon=True)
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=90)
        assert not errs, errs
        assert all(s is DAGStatusState.SUCCEEDED for s in states.values())
        spans = tracing.snapshot()
        fences = [s for s in spans if s.name == "fence.stale_epoch"] + \
            [n for s in spans for _, n, _ in s.events
             if n == "fence.stale_epoch"]
        assert not fences, f"epoch fences tripped: {fences}"
    finally:
        client.stop()
