"""Skew/straggler exchange plane: round planning, the folded-in
splitter, engine resolution, and coded r2 — all bit-exact against the
legacy padded formulation (itself kernel-verified against the host
reference in test_distributed_exchange.py)."""
import numpy as np
import pytest

import jax

from tez_tpu.common import faults
from tez_tpu.ops.runformat import KVBatch
from tez_tpu.parallel.coordinator import (MeshExchangeCoordinator,
                                          plan_rounds)

KEY_BYTES = 6
VAL_BYTES = 5


@pytest.fixture(scope="module", autouse=True)
def _devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _no_faults():
    yield
    faults.install("test", [])


def _corpus(rows, producers, consumers, hot_frac, hot_part, seed=0):
    """Producer spans with ``hot_frac`` of rows in consumer partition
    ``hot_part`` — classified by the real FNV partitioner, so the skew is
    exact by construction."""
    from tez_tpu.ops.host_sort import fnv_rows_host
    rng = np.random.default_rng(seed)
    pool = rng.integers(0, 256, size=(4096, KEY_BYTES), dtype=np.uint8)
    part = fnv_rows_host(pool, np.full(pool.shape[0], KEY_BYTES,
                                       dtype=np.int64)) % consumers
    hot, cold = pool[part == hot_part], pool[part != hot_part]
    n_hot = int(rows * hot_frac)
    keys = np.concatenate([
        hot[rng.integers(0, hot.shape[0], n_hot)],
        cold[rng.integers(0, cold.shape[0], rows - n_hot)]])
    keys = keys[rng.permutation(rows)]
    vals = rng.integers(0, 256, size=(rows, VAL_BYTES), dtype=np.uint8)
    spans = []
    for i in range(producers):
        k, v = keys[i::producers], vals[i::producers]
        n = k.shape[0]
        spans.append(KVBatch(
            k.reshape(-1), np.arange(n + 1, dtype=np.int64) * KEY_BYTES,
            v.reshape(-1), np.arange(n + 1, dtype=np.int64) * VAL_BYTES))
    return spans


def _run(coord, spans, edge, consumers, **kw):
    for i, b in enumerate(spans):
        coord.register_producer(edge, i, len(spans), consumers, b,
                                KEY_BYTES, VAL_BYTES, **kw)
    return [coord.wait_consumer(edge, c, len(spans), consumers, timeout=120)
            for c in range(consumers)]


def _sig(res):
    return [(np.asarray(b.key_bytes).tobytes(),
             np.asarray(b.val_bytes).tobytes()) for b in res]


def _golden(spans, consumers):
    out = _run(MeshExchangeCoordinator(legacy_sizing=True), spans,
               "golden/a->b", consumers, engine="padded")
    return _sig(out)


# ---------------------------------------------------------------- planning

def test_plan_rounds_budget_invariants():
    """Every round's quota fits the device budget, quotas sum exactly to
    the histogram, and the balanced cap never exceeds per_round."""
    rng = np.random.default_rng(7)
    for _ in range(50):
        D = int(rng.integers(1, 9))
        per_round = int(rng.integers(1, 200))
        counts = rng.integers(0, per_round * 4, D).astype(np.int64)
        for legacy in (False, True):
            plan = plan_rounds(counts, per_round, D, legacy=legacy)
            total = np.zeros(D, dtype=np.int64)
            for quota, cap in plan:
                assert quota.max() <= per_round
                assert 1 <= cap <= per_round
                assert quota.sum() > 0          # no empty rounds
                total += quota
            np.testing.assert_array_equal(total, counts)
    assert plan_rounds(np.zeros(4, dtype=np.int64), 16, 4) == []


def test_plan_rounds_balanced_cap_beats_legacy():
    """One hot destination: legacy pads every pair to the hot partition,
    balanced splits its quota over D senders — a D-fold smaller cap."""
    counts = np.array([1000, 10, 10, 10], dtype=np.int64)
    [(_, legacy_cap)] = plan_rounds(counts, 1 << 20, 4, legacy=True)
    [(_, cap)] = plan_rounds(counts, 1 << 20, 4, legacy=False)
    assert legacy_cap >= 1000
    assert cap < legacy_cap
    assert cap >= -(-1000 // 4)      # still holds the hot dest's chunks


# ------------------------------------------------------- property matrix

@pytest.mark.parametrize("consumers", [8, 16])
@pytest.mark.parametrize("hot_frac", [0.0, 0.45])
@pytest.mark.parametrize("coded", ["off", "r2"])
def test_exchange_matrix_bit_exact(consumers, hot_frac, coded):
    """(W, skew, engine=auto, coded) matrix: every cell bit-identical to
    the legacy padded run of the same corpus — including W=16 on 8
    devices (two consumer partitions per device, host recombine)."""
    spans = _corpus(6_000, 4, consumers, hot_frac, hot_part=1,
                    seed=consumers * 10 + int(hot_frac * 100))
    golden = _golden(spans, consumers)
    coord = MeshExchangeCoordinator(max_rows_per_round=2_000, split_after=1)
    out = _run(coord, spans, f"cell-{coded}/a->b", consumers,
               engine="auto", coded=coded)
    assert _sig(out) == golden
    if hot_frac > 0.0:
        # 45% in one of >=8 partitions always busts the 2k budget
        assert coord.partition_splits >= 1
        assert coord.multi_round_exchanges == 0
    from tez_tpu.parallel.exchange import probe_ragged_support
    ok, _ = probe_ragged_support(coord.mesh_for(coord.devices_for(consumers)))
    assert coord.last_engine == ("ragged" if ok else "padded")


def test_splitter_recombine_preserves_key_order():
    """Equal hot keys split across sub-partitions must recombine in their
    original arrival order — values of one repeated key come back exactly
    as the no-split exchange delivers them."""
    consumers = 8
    spans = _corpus(4_000, 4, consumers, hot_frac=0.5, hot_part=3, seed=2)
    golden = _golden(spans, consumers)
    coord = MeshExchangeCoordinator(max_rows_per_round=600, split_after=1)
    out = _run(coord, spans, "recombine/a->b", consumers, engine="auto")
    assert coord.partition_splits >= 1
    assert _sig(out) == golden      # byte-exact => value order preserved


def test_splitter_disabled_falls_back_to_rounds():
    """split_after=0 turns the splitter off: the same hot corpus instead
    pays extra rounds, and stays bit-exact."""
    consumers = 8
    spans = _corpus(4_000, 4, consumers, hot_frac=0.5, hot_part=3, seed=2)
    golden = _golden(spans, consumers)
    coord = MeshExchangeCoordinator(max_rows_per_round=600, split_after=0)
    out = _run(coord, spans, "nosplit/a->b", consumers, engine="auto")
    assert coord.partition_splits == 0
    assert coord.multi_round_exchanges >= 1
    assert _sig(out) == golden


# ------------------------------------------------------------------ coded

def test_coded_r2_masks_delayed_chip():
    """With one chip's readback delayed, the coded exchange returns from
    the buddy copy without waiting out the delay — and stays bit-exact."""
    consumers = 8
    spans = _corpus(3_000, 4, consumers, hot_frac=0.0, hot_part=0, seed=4)
    golden = _golden(spans, consumers)
    coord = MeshExchangeCoordinator()
    # warm run compiles the coded program fault-free
    _run(coord, spans, "warm-coded/a->b", consumers, coded="r2")
    faults.install("test", faults.parse_spec(
        "mesh.exchange.delay:delay:ms=1500,n=1,match=device=5"))
    import time
    t0 = time.perf_counter()
    out = _run(coord, spans, "delayed-coded/a->b", consumers, coded="r2")
    wall = time.perf_counter() - t0
    assert _sig(out) == golden
    assert coord.coded_buddy_wins >= 1
    assert wall < 1.5, f"coded exchange waited out the delay ({wall:.2f}s)"


def test_coded_r2_both_copies_failed_raises():
    """fail-mode on BOTH holders of one partition (primary chip and its
    buddy) must surface an error, not silently drop the partition."""
    consumers = 8
    spans = _corpus(2_000, 4, consumers, hot_frac=0.0, hot_part=0, seed=6)
    coord = MeshExchangeCoordinator()
    _run(coord, spans, "warm-fail/a->b", consumers, coded="r2")
    # partition 2's primary is device 2; its buddy copy lives on device 3
    # ((2+1) % 8) — failing both readbacks kills every recovery path
    faults.install("test", faults.parse_spec(
        "mesh.exchange.delay:fail:n=1,match=device=2;"
        "mesh.exchange.delay:fail:n=1,match=device=3"))
    with pytest.raises(Exception, match="copies"):
        _run(coord, spans, "bothfail/a->b", consumers, coded="r2")
