"""Unit tests for the deterministic fault plane (tez_tpu/common/faults.py)."""
import pytest

from tez_tpu.common import faults
from tez_tpu.common.faults import format_spec, parse_spec


def test_parse_spec_roundtrip():
    spec = ("shuffle.fetch.read:fail:n=2,exc=io;"
            "task.run:delay:ms=300,match=_00_000000_0;"
            "spill.read:corrupt:n=1;"
            "am.heartbeat:pfail:p=0.25,n=5")
    rules = parse_spec(spec)
    assert [r.point for r in rules] == [
        "shuffle.fetch.read", "task.run", "spill.read", "am.heartbeat"]
    assert rules[0].times == 2 and rules[0].exc == "io"
    assert rules[1].delay_ms == 300 and rules[1].match == "_00_000000_0"
    assert rules[3].prob == 0.25
    assert format_spec(parse_spec(format_spec(rules))) == format_spec(rules)


@pytest.mark.parametrize("bad", [
    "task.run",                      # no mode
    "task.run:explode",              # unknown mode
    "task.run:fail:exc=nuclear",     # unknown exc
    "task.run:fail:volume=11",       # unknown param
    "task.run:fail:n=0",             # never fires
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_fail_n_times_budget():
    faults.install("t", parse_spec("p.x:fail:n=2,exc=io"), seed=1)
    for _ in range(2):
        with pytest.raises(IOError):
            faults.fire("p.x")
    faults.fire("p.x")   # budget exhausted: no-op
    assert [a for (_, _, a) in faults.plane().journal] == ["fail", "fail"]


def test_exc_kinds():
    faults.install("t", parse_spec(
        "a:fail:exc=conn,n=1;b:fail:exc=timeout,n=1;c:fail:exc=perm,n=1"))
    with pytest.raises(ConnectionError):
        faults.fire("a")
    with pytest.raises(TimeoutError):
        faults.fire("b")
    with pytest.raises(PermissionError):
        faults.fire("c")


def test_match_filters_on_detail():
    faults.install("t", parse_spec("task.run:fail:match=_00_000000_0,n=1"))
    faults.fire("task.run", "attempt_1_x_1_00_000001_0")   # no match: no-op
    with pytest.raises(ConnectionError):
        faults.fire("task.run", "attempt_1_x_1_00_000000_0")


def test_pfail_deterministic_across_installs():
    def draw(seed):
        faults.clear_all()
        faults.install("t", parse_spec("p:pfail:p=0.5"), seed=seed)
        out = []
        for _ in range(20):
            try:
                faults.fire("p")
                out.append(0)
            except ConnectionError:
                out.append(1)
        return out

    a, b, c = draw(7), draw(7), draw(8)
    assert a == b                      # same (spec, seed): same schedule
    assert a != c                      # different seed: different schedule
    assert 0 < sum(a) < 20             # actually probabilistic


def test_delay_sleeps(monkeypatch):
    slept = []
    import tez_tpu.common.faults as F
    monkeypatch.setattr(F.time, "sleep", lambda s: slept.append(s))
    faults.install("t", parse_spec("p:delay:ms=250,n=1"))
    faults.fire("p")
    assert slept == [0.25]
    faults.fire("p")       # budget spent
    assert slept == [0.25]


def test_corrupt_bytes_flips_exactly_one_byte_below_lo_respected():
    faults.install("t", parse_spec("p:corrupt:n=1"), seed=3)
    data = bytes(range(100))
    out = faults.corrupt_bytes("p", "d", data, lo=19)
    diff = [i for i in range(100) if out[i] != data[i]]
    assert len(diff) == 1 and diff[0] >= 19
    # budget spent: second call is a no-op
    assert faults.corrupt_bytes("p", "d", data, lo=19) is data


def test_corrupt_position_deterministic():
    def flip(seed):
        faults.clear_all()
        faults.install("t", parse_spec("p:corrupt"), seed=seed)
        data = bytes(100)
        out = faults.corrupt_bytes("p", "d", data)
        return next(i for i in range(100) if out[i] != data[i])

    assert flip(11) == flip(11)


def test_scope_isolation_and_disarm():
    faults.install("dag1", parse_spec("p:fail"))
    faults.install("dag2", parse_spec("q:fail"))
    assert faults.armed()
    faults.clear("dag1")
    assert faults.armed()              # dag2 still holds rules
    with pytest.raises(ConnectionError):
        faults.fire("q")
    faults.fire("p")                   # dag1's rule is gone
    faults.clear("dag2")
    assert not faults.armed()          # fast path restored


def test_disarmed_is_free():
    faults.clear_all()
    faults.fire("anything", "detail")          # all no-ops
    assert not faults.should_corrupt("x")
    data = b"abc"
    assert faults.corrupt_bytes("x", "d", data) is data


def test_install_from_conf():
    from tez_tpu.common import config as C
    conf = C.TezConfiguration({
        "tez.test.fault.spec": "p:fail:n=1", "tez.test.fault.seed": 9})
    assert faults.install_from_conf(conf, scope="dag_x")
    with pytest.raises(ConnectionError):
        faults.fire("p")
    empty = C.TezConfiguration({})
    assert not faults.install_from_conf(empty, scope="dag_y")
