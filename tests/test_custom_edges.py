"""Phase-8 tests: cartesian product + fair-shuffle skew splitting."""
import collections
import os

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor,
                                    VertexManagerPluginDescriptor,
                                    EdgeManagerPluginDescriptor,
                                    OutputCommitterDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, Edge, Vertex)
from tez_tpu.dag.edge_property import (DataSourceType, EdgeProperty,
                                       SchedulingType)
from tez_tpu.library.cartesian_product import CartesianProductCombination
from tez_tpu.library.fair_shuffle import compute_fair_mappings
from tez_tpu.library.processors import SimpleProcessor


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


# ---------------------------------------------------------------------------
# cartesian product
# ---------------------------------------------------------------------------
class EmitIndexProcessor(SimpleProcessor):
    """Each task emits one record carrying its task index."""

    def run(self, inputs, outputs):
        for out in outputs.values():
            out.get_writer().write(
                f"t{self.context.task_index}".encode(), b"x")


class PairCollector(SimpleProcessor):
    """Reads one record from each side, records the combination."""

    def run(self, inputs, outputs):
        left = [k for k, _ in inputs["a"].get_reader()]
        right = [k for k, _ in inputs["b"].get_reader()]
        writer = outputs["output"].get_writer()
        for l in left:
            for r in right:
                writer.write(l + b"|" + r, b"1")


def test_combination_math():
    c = CartesianProductCombination([2, 3])
    assert c.total == 6
    combos = {(c.coordinate(d, 0), c.coordinate(d, 1)) for d in range(6)}
    assert combos == {(i, j) for i in range(2) for j in range(3)}
    assert c.dests_for(0, 1) == [3, 4, 5]


def test_cartesian_product_e2e(client, tmp_path):
    a = Vertex.create("a", ProcessorDescriptor.create(EmitIndexProcessor), 2)
    b = Vertex.create("b", ProcessorDescriptor.create(EmitIndexProcessor), 3)
    joiner = Vertex.create("joiner", ProcessorDescriptor.create(
        PairCollector), 6)
    out_dir = str(tmp_path / "out")
    joiner.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": out_dir,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": out_dir})))
    joiner.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.cartesian_product:CartesianProductVertexManager",
        payload={"sources": ["a", "b"]}))

    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "bytes"}
    def cp_edge(src):
        desc = EdgeManagerPluginDescriptor.create(
            "tez_tpu.library.cartesian_product:CartesianProductEdgeManager",
            payload={})
        return EdgeProperty.create_custom(
            desc, DataSourceType.PERSISTED,
            OutputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVOutput", payload=conf),
            InputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVInput", payload=conf))

    dag = DAG.create("cp")
    for v in (a, b, joiner):
        dag.add_vertex(v)
    dag.add_edge(Edge.create(a, joiner, cp_edge("a")))
    dag.add_edge(Edge.create(b, joiner, cp_edge("b")))
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    pairs = set()
    for f in os.listdir(out_dir):
        if f.startswith("part-"):
            for line in open(os.path.join(out_dir, f), "rb"):
                pairs.add(line.split(b"\t")[0])
    assert pairs == {f"t{i}|t{j}".encode()
                     for i in range(2) for j in range(3)}


# ---------------------------------------------------------------------------
# fair shuffle
# ---------------------------------------------------------------------------
def test_fair_mapping_splits_skew():
    # partition 1 is 10x oversized -> split across sources
    totals = [100, 1000, 50]
    mappings = compute_fair_mappings(totals, num_sources=4,
                                     desired_task_input_size=300,
                                     max_tasks=0)
    parts = collections.Counter(p for p, _, _ in mappings)
    assert parts[0] == 1 and parts[2] == 1
    assert parts[1] == 4  # ceil(1000/300)=4 slices
    # slices of partition 1 tile the source range exactly
    slices = sorted((lo, hi) for p, lo, hi in mappings if p == 1)
    assert slices[0][0] == 0 and slices[-1][1] == 4
    assert all(s[1] == t[0] for s, t in zip(slices, slices[1:]))


def test_fair_shuffle_e2e_splits_hot_partition(client, tmp_path):
    """One hot key dominates; FairShuffleVertexManager splits its partition
    across source ranges and the aggregation still sums correctly."""
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    with open(corpus, "w") as fh:
        for i in range(3000):
            fh.write("hotkey filler%d\n" % (i % 7))
    out = str(tmp_path / "out")
    dag = ordered_wordcount.build_dag([str(corpus)], out,
                                      tokenizer_parallelism=4,
                                      summation_parallelism=4,
                                      combine=False)
    dag.vertices["summation"].set_vertex_manager_plugin(
        VertexManagerPluginDescriptor.create(
            "tez_tpu.library.fair_shuffle:FairShuffleVertexManager",
            payload={"desired_task_input_size": 4096,
                     "min_fraction": 0.9, "max_fraction": 0.9}))
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, c = line.rstrip(b"\n").split(b"\t")
                got[w.decode()] = got.get(w.decode(), 0) + int(c)
    golden = collections.Counter(
        w for l in open(corpus) for w in l.split())
    assert got == dict(golden)
    # the hot partition really was split: more tasks than declared
    assert status.vertex_status["summation"].progress.total_task_count > 4
