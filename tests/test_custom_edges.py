"""Phase-8 tests: cartesian product + fair-shuffle skew splitting."""
import collections
import os

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor,
                                    VertexManagerPluginDescriptor,
                                    EdgeManagerPluginDescriptor,
                                    OutputCommitterDescriptor)
from tez_tpu.dag.dag import (DAG, DataSinkDescriptor, Edge, Vertex)
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.library.cartesian_product import CartesianProductCombination
from tez_tpu.library.fair_shuffle import compute_fair_mappings
from tez_tpu.library.processors import SimpleProcessor


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


# ---------------------------------------------------------------------------
# cartesian product
# ---------------------------------------------------------------------------
class EmitIndexProcessor(SimpleProcessor):
    """Each task emits one record carrying its task index."""

    def run(self, inputs, outputs):
        for out in outputs.values():
            out.get_writer().write(
                f"t{self.context.task_index}".encode(), b"x")


class PairCollector(SimpleProcessor):
    """Reads one record from each side, records the combination."""

    def run(self, inputs, outputs):
        left = [k for k, _ in inputs["a"].get_reader()]
        right = [k for k, _ in inputs["b"].get_reader()]
        writer = outputs["output"].get_writer()
        for l in left:
            for r in right:
                writer.write(l + b"|" + r, b"1")


def test_combination_math():
    c = CartesianProductCombination([2, 3])
    assert c.total == 6
    combos = {(c.coordinate(d, 0), c.coordinate(d, 1)) for d in range(6)}
    assert combos == {(i, j) for i in range(2) for j in range(3)}
    assert c.dests_for(0, 1) == [3, 4, 5]


def test_cartesian_product_e2e(client, tmp_path):
    a = Vertex.create("a", ProcessorDescriptor.create(EmitIndexProcessor), 2)
    b = Vertex.create("b", ProcessorDescriptor.create(EmitIndexProcessor), 3)
    joiner = Vertex.create("joiner", ProcessorDescriptor.create(
        PairCollector), 6)
    out_dir = str(tmp_path / "out")
    joiner.add_data_sink("output", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": out_dir,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": out_dir})))
    joiner.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.cartesian_product:CartesianProductVertexManager",
        payload={"sources": ["a", "b"]}))

    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "bytes"}
    def cp_edge(src):
        desc = EdgeManagerPluginDescriptor.create(
            "tez_tpu.library.cartesian_product:CartesianProductEdgeManager",
            payload={})
        return EdgeProperty.create_custom(
            desc, DataSourceType.PERSISTED,
            OutputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVOutput", payload=conf),
            InputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVInput", payload=conf))

    dag = DAG.create("cp")
    for v in (a, b, joiner):
        dag.add_vertex(v)
    dag.add_edge(Edge.create(a, joiner, cp_edge("a")))
    dag.add_edge(Edge.create(b, joiner, cp_edge("b")))
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    pairs = set()
    for f in os.listdir(out_dir):
        if f.startswith("part-"):
            for line in open(os.path.join(out_dir, f), "rb"):
                pairs.add(line.split(b"\t")[0])
    assert pairs == {f"t{i}|t{j}".encode()
                     for i in range(2) for j in range(3)}


# ---------------------------------------------------------------------------
# fair shuffle
# ---------------------------------------------------------------------------
def test_fair_mapping_splits_skew():
    # partition 1 is 10x oversized -> split across sources
    totals = [100, 1000, 50]
    mappings = compute_fair_mappings(totals, num_sources=4,
                                     desired_task_input_size=300,
                                     max_tasks=0)
    parts = collections.Counter(p for p, _, _ in mappings)
    assert parts[0] == 1 and parts[2] == 1
    assert parts[1] == 4  # ceil(1000/300)=4 slices
    # slices of partition 1 tile the source range exactly
    slices = sorted((lo, hi) for p, lo, hi in mappings if p == 1)
    assert slices[0][0] == 0 and slices[-1][1] == 4
    assert all(s[1] == t[0] for s, t in zip(slices, slices[1:]))


class SkewedEmitter(SimpleProcessor):
    """Emits one hot key heavily plus a few cold keys (payload: n_hot)."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        w = outputs["sum"].get_writer()
        for _ in range(payload.get("n_hot", 200)):
            w.write(b"hotkey", 1)
        for i in range(10):
            w.write(f"cold{i}".encode(), 1)


class TwoInputSummer(SimpleProcessor):
    """Sums grouped counts from BOTH source edges into a per-task file."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        totals = collections.Counter()
        for name in ("a", "b"):
            for k, vs in inputs[name].get_reader():
                totals[k] += sum(vs)
        path = os.path.join(payload["out_dir"],
                            f"part-{self.context.task_index}")
        with open(path, "w") as fh:
            for k, v in totals.items():
                fh.write(f"{k.decode()}\t{v}\n")


class _FakeVMContext:
    """Minimal VertexManagerPluginContext stub for decision-logic tests."""

    def __init__(self, payload, in_edges, num_tasks):
        from tez_tpu.common.payload import UserPayload
        self._payload = UserPayload.of(payload)
        self._in_edges = in_edges              # name -> EdgeProperty
        self._num_tasks = dict(num_tasks)      # vertex name -> parallelism
        self.vertex_name_ = "consumer"
        self.scheduled = []
        self.reconfigured = None               # (parallelism, edge props)

    @property
    def vertex_name(self):
        return self.vertex_name_

    @property
    def user_payload(self):
        return self._payload

    def get_vertex_num_tasks(self, name):
        return self._num_tasks[name]

    def get_input_vertex_edge_properties(self):
        return dict(self._in_edges)

    def get_output_vertex_edge_properties(self):
        return {}

    def get_input_vertex_groups(self):
        return {}

    def schedule_tasks(self, requests):
        self.scheduled.extend(r.task_index for r in requests)

    def reconfigure_vertex(self, parallelism, source_edge_properties=None,
                           **_kw):
        self.reconfigured = (parallelism, source_edge_properties)
        self._num_tasks[self.vertex_name_] = parallelism

    def vertex_reconfiguration_planned(self):
        pass

    def vertex_reconfiguration_restored(self):
        return False

    def done_reconfiguring_vertex(self):
        pass

    def register_for_vertex_state_updates(self, vertex_name, states):
        pass


def _sg_prop():
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "long"}
    return EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput", payload=kv),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=kv))


def _bc_prop():
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "bytes"}
    return EdgeProperty.create(
        DataMovementType.BROADCAST, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVOutput", payload=kv),
        InputDescriptor.create(
            "tez_tpu.library.unordered:UnorderedKVInput", payload=kv))


def _vm_event(vec, vertex, task):
    from tez_tpu.api.events import VertexManagerEvent

    class _Att:
        class task_id:
            id = task
        vertex_id = vertex
    ev = VertexManagerEvent("consumer", {"partition_sizes": vec,
                                         "output_size": sum(vec)})
    ev.producer_attempt = _Att()
    ev.producer_vertex_name = vertex
    return ev


def test_fair_shuffle_broadcast_does_not_inflate_fraction():
    """A finished BROADCAST side-input must not count toward the shuffle
    completion fraction, which gates the (irreversible) split decision."""
    from tez_tpu.api.vertex_manager import TaskAttemptIdentifier
    from tez_tpu.library.fair_shuffle import FairShuffleVertexManager
    ctx = _FakeVMContext(
        {"desired_task_input_size": 100, "min_fraction": 1.0,
         "max_fraction": 1.0},
        {"sg": _sg_prop(), "bc": _bc_prop()},
        {"sg": 4, "bc": 4, "consumer": 2})
    vm = FairShuffleVertexManager(ctx)
    vm.initialize()
    vm.on_vertex_started([])
    # the whole broadcast source finishes first, no SG stats yet
    for i in range(4):
        vm.on_source_task_completed(TaskAttemptIdentifier("bc", i, 0))
    assert not vm._parallelism_determined, \
        "broadcast completions finalized the split decision prematurely"
    # now the skewed SG source reports and completes -> split happens
    for i in range(4):
        vm.on_vertex_manager_event_received(_vm_event([400, 10], "sg", i))
        vm.on_source_task_completed(TaskAttemptIdentifier("sg", i, 0))
    assert ctx.reconfigured is not None
    assert ctx.reconfigured[0] > 2     # hot partition split


def test_fair_shuffle_projects_unreported_source():
    """An SG source vertex with no stats yet is projected at the observed
    per-task average, not counted as zero (which would hide its skew)."""
    from tez_tpu.api.vertex_manager import TaskAttemptIdentifier
    from tez_tpu.library.fair_shuffle import FairShuffleVertexManager
    # a: 3 tasks reporting [400, 10]; b: 2 tasks, silent.
    # a-only projection: partition0 = 1200 < 1500 -> no split.
    # with b projected at avg: 1200 + 2*400 = 2000 >= 1500 -> split.
    ctx = _FakeVMContext(
        {"desired_task_input_size": 1500, "min_fraction": 0.5,
         "max_fraction": 0.5},
        {"a": _sg_prop(), "b": _sg_prop()},
        {"a": 3, "b": 2, "consumer": 2})
    vm = FairShuffleVertexManager(ctx)
    vm.initialize()
    vm.on_vertex_started([])
    for i in range(3):
        vm.on_vertex_manager_event_received(_vm_event([400, 10], "a", i))
        vm.on_source_task_completed(TaskAttemptIdentifier("a", i, 0))
    # fraction = 3/5 >= 0.5 -> decision ran with b unreported
    assert vm._parallelism_determined
    assert ctx.reconfigured is not None, \
        "unreported source counted as zero; skew split skipped"
    assert ctx.reconfigured[0] > 2
    # every slice carries per-edge ranges for BOTH edges
    assert set(ctx.reconfigured[1]) == {"a", "b"}


def test_auto_parallel_ignores_broadcast_output_stats():
    """A BROADCAST side-input's tiny output reports must not drag down the
    per-task average and over-shrink the consumer (auto-parallelism must
    average SHUFFLE source stats only)."""
    from tez_tpu.api.vertex_manager import TaskAttemptIdentifier
    from tez_tpu.library.vertex_managers import ShuffleVertexManager
    ctx = _FakeVMContext(
        {"auto_parallel": True, "desired_task_input_size": 1000,
         "min_fraction": 1.0, "max_fraction": 1.0},
        {"sg": _sg_prop(), "bc": _bc_prop()},
        {"sg": 4, "bc": 4, "consumer": 4})
    vm = ShuffleVertexManager(ctx)
    vm.initialize()
    vm.on_vertex_started([])
    for i in range(4):   # broadcast side-input: 4 x 10 bytes
        vm.on_vertex_manager_event_received(_vm_event([10], "bc", i))
    for i in range(4):   # shuffle source: 4 x 1000 bytes
        vm.on_vertex_manager_event_received(_vm_event([1000], "sg", i))
        vm.on_source_task_completed(TaskAttemptIdentifier("sg", i, 0))
    assert vm._parallelism_determined
    # clean average = 1000 -> expected 4000 -> desired 4 == current: no
    # shrink.  (Polluted average 505 would wrongly shrink to 3.)
    assert ctx.reconfigured is None


def test_fair_shuffle_multi_source(client, tmp_path):
    """Two scatter-gather sources with different parallelism feed one fair-
    shuffle consumer: the hot partition is split with per-edge source ranges
    (reference: FairShuffleVertexManager over multiple edges) and global
    sums stay correct."""
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "long"}
    a = Vertex.create("a", ProcessorDescriptor.create(
        SkewedEmitter, payload={"n_hot": 300}), 3)
    b = Vertex.create("b", ProcessorDescriptor.create(
        SkewedEmitter, payload={"n_hot": 200}), 2)
    consumer = Vertex.create("sum", ProcessorDescriptor.create(
        TwoInputSummer, payload={"out_dir": out_dir}), 2)
    consumer.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.fair_shuffle:FairShuffleVertexManager",
        payload={"desired_task_input_size": 512,
                 "min_fraction": 1.0, "max_fraction": 1.0}))

    def sg_edge(src):
        return Edge.create(src, consumer, EdgeProperty.create(
            DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
            SchedulingType.SEQUENTIAL,
            OutputDescriptor.create(
                "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
                payload=kv),
            InputDescriptor.create(
                "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=kv)))

    dag = DAG.create("fair_multi").add_vertex(a).add_vertex(b) \
        .add_vertex(consumer)
    dag.add_edge(sg_edge(a)).add_edge(sg_edge(b))
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = collections.Counter()
    for f in os.listdir(out_dir):
        for line in open(os.path.join(out_dir, f)):
            k, v = line.rstrip("\n").split("\t")
            got[k] += int(v)
    expected = collections.Counter({"hotkey": 3 * 300 + 2 * 200})
    for i in range(10):
        expected[f"cold{i}"] = 5   # 3 a-tasks + 2 b-tasks, 1 each
    assert got == dict(expected)
    # the hot partition was split across source ranges on BOTH edges
    assert status.vertex_status["sum"].progress.total_task_count > 2


def test_fair_shuffle_e2e_splits_hot_partition(client, tmp_path):
    """One hot key dominates; FairShuffleVertexManager splits its partition
    across source ranges and the aggregation still sums correctly."""
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    with open(corpus, "w") as fh:
        for i in range(3000):
            fh.write("hotkey filler%d\n" % (i % 7))
    out = str(tmp_path / "out")
    dag = ordered_wordcount.build_dag([str(corpus)], out,
                                      tokenizer_parallelism=4,
                                      summation_parallelism=4,
                                      combine=False)
    dag.vertices["summation"].set_vertex_manager_plugin(
        VertexManagerPluginDescriptor.create(
            "tez_tpu.library.fair_shuffle:FairShuffleVertexManager",
            payload={"desired_task_input_size": 4096,
                     "min_fraction": 0.9, "max_fraction": 0.9}))
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, c = line.rstrip(b"\n").split(b"\t")
                got[w.decode()] = got.get(w.decode(), 0) + int(c)
    golden = collections.Counter(
        w for l in open(corpus) for w in l.split())
    assert got == dict(golden)
    # the hot partition really was split: more tasks than declared
    assert status.vertex_status["summation"].progress.total_task_count > 4
