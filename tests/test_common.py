"""Tests for counters, dispatcher, state machine, config."""
import enum

import pytest

from tez_tpu.common import config as C
from tez_tpu.common.counters import (CounterLimitExceeded, DAGCounter, Limits,
                                     TaskCounter, TezCounters)
from tez_tpu.common.dispatcher import DrainDispatcher, Dispatcher, Event
from tez_tpu.common.ids import DAGId, new_app_id
from tez_tpu.common.statemachine import (InvalidStateTransition,
                                         StateMachineFactory)


class Color(enum.Enum):
    PING = 1
    PONG = 2


class Ev(Event):
    def __init__(self, t):
        super().__init__(t)


def test_dispatcher_routes_by_enum_class():
    d = DrainDispatcher()
    got = []
    d.register(Color, lambda e: got.append(e.event_type))
    d.dispatch(Ev(Color.PING))
    d.dispatch(Ev(Color.PONG))
    assert d.drain() == 2
    assert got == [Color.PING, Color.PONG]


def test_dispatcher_handler_enqueues_more():
    d = DrainDispatcher()
    got = []

    def handler(e):
        got.append(e.event_type)
        if e.event_type is Color.PING:
            d.dispatch(Ev(Color.PONG))

    d.register(Color, handler)
    d.dispatch(Ev(Color.PING))
    d.drain()
    assert got == [Color.PING, Color.PONG]


def test_threaded_dispatcher_drains():
    d = Dispatcher()
    got = []
    d.register(Color, lambda e: got.append(1))
    d.start()
    for _ in range(100):
        d.dispatch(Ev(Color.PING))
    assert d.await_drained(5)
    d.stop()
    assert len(got) == 100


def test_multi_handler_fanout():
    d = DrainDispatcher()
    a, b = [], []
    d.register(Color, lambda e: a.append(1))
    d.register(Color, lambda e: b.append(1))
    d.dispatch(Ev(Color.PING))
    d.drain()
    assert a == [1] and b == [1]


class TState(enum.Enum):
    NEW = 1
    RUNNING = 2
    DONE = 3
    FAILED = 4


class TEvent(enum.Enum):
    START = 1
    FINISH = 2
    CRASH = 3


def test_state_machine_transitions():
    f = StateMachineFactory(TState.NEW)
    f.add(TState.NEW, TState.RUNNING, TEvent.START)
    f.add_multi(TState.RUNNING, (TState.DONE, TState.FAILED), TEvent.FINISH,
                lambda entity, ev: TState.DONE if ev.ok else TState.FAILED)

    class E:
        pass

    class FinishEv:
        event_type = TEvent.FINISH

        def __init__(self, ok):
            self.ok = ok

    class StartEv:
        event_type = TEvent.START

    sm = f.make(E())
    assert sm.state is TState.NEW
    sm.handle(StartEv())
    assert sm.state is TState.RUNNING
    sm.handle(FinishEv(ok=False))
    assert sm.state is TState.FAILED
    with pytest.raises(InvalidStateTransition):
        sm.handle(StartEv())


def test_state_change_callback():
    f = StateMachineFactory(TState.NEW)
    f.add(TState.NEW, TState.RUNNING, TEvent.START)
    changes = []

    class StartEv:
        event_type = TEvent.START

    sm = f.make(object(), on_state_change=lambda e, o, n: changes.append((o, n)))
    sm.handle(StartEv())
    assert changes == [(TState.NEW, TState.RUNNING)]


def test_counters_aggregate():
    t1, t2, v = TezCounters(), TezCounters(), TezCounters()
    t1.increment(TaskCounter.OUTPUT_RECORDS, 10)
    t2.increment(TaskCounter.OUTPUT_RECORDS, 5)
    t2.increment(DAGCounter.NUM_SUCCEEDED_TASKS)
    v.aggregate(t1)
    v.aggregate(t2)
    assert v.find_counter(TaskCounter.OUTPUT_RECORDS).value == 15
    assert v.find_counter(DAGCounter.NUM_SUCCEEDED_TASKS).value == 1
    d = v.to_dict()
    assert d["TaskCounter"]["OUTPUT_RECORDS"] == 15
    assert TezCounters.from_dict(d).find_counter(
        TaskCounter.OUTPUT_RECORDS).value == 15


def test_counter_group_limit():
    c = TezCounters()
    g = c.group("g")
    for i in range(Limits.MAX_COUNTERS):
        g.find_counter(f"c{i}")
    with pytest.raises(CounterLimitExceeded):
        g.find_counter("one-too-many")


def test_config_keys_and_scopes():
    conf = C.TezConfiguration()
    assert conf.get(C.IO_SORT_MB) == 256
    conf.set(C.IO_SORT_MB, 64)
    assert conf.get(C.IO_SORT_MB) == 64
    assert C.IO_SORT_MB.scope is C.Scope.VERTEX
    sub = C.runtime_conf_subset(
        {"tez.runtime.io.sort.mb": 1, "tez.am.foo": 2})
    assert sub == {"tez.runtime.io.sort.mb": 1}
    merged = conf.merged({"x": 1})
    assert merged["x"] == 1 and merged.get(C.IO_SORT_MB) == 64


def test_ids_format():
    app = new_app_id(123)
    d = DAGId(app, 1)
    v = d.vertex(2)
    t = v.task(3)
    a = t.attempt(0)
    assert str(v).startswith("vertex_")
    assert str(a).startswith("attempt_")
    assert a.dag_id is d
    assert sorted([t.attempt(1), a]) == [a, t.attempt(1)]


def test_sharded_dispatcher_preserves_per_entity_order():
    from tez_tpu.common.dispatcher import ShardedDispatcher

    class KeyedEv(Event):
        def __init__(self, t, vertex_id, seq):
            super().__init__(t)
            self.vertex_id = vertex_id
            self.seq = seq

    d = ShardedDispatcher(num_shards=4)
    got = {}
    d.register(Color, lambda e: got.setdefault(e.vertex_id, []).append(e.seq))
    d.start()
    for seq in range(200):
        for vid in ("a", "b", "c", "d", "e"):
            d.dispatch(KeyedEv(Color.PING, vid, seq))
    assert d.await_drained(10)
    d.stop()
    for vid, seqs in got.items():
        assert seqs == list(range(200)), vid
    assert len(got) == 5


def test_ndc_context_and_propagation():
    """NDC stack tags log records and survives executor handoff
    (CallableWithNdc semantics)."""
    import concurrent.futures
    import logging
    from tez_tpu.common import ndc

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = logging.getLogger("test.ndc")
    h = Capture()
    h.addFilter(ndc.NdcFilter())
    logger.addHandler(h)
    try:
        logger.warning("outside")
        with ndc.context("attempt_1"):
            with ndc.context("input_a"):
                logger.warning("inside")
                wrapped = ndc.with_current_ndc(
                    lambda: ndc.current())
        assert records[0].ndc == ""
        assert records[1].ndc == "attempt_1:input_a"
        # captured stack re-applies on a foreign thread, then unwinds
        with concurrent.futures.ThreadPoolExecutor(1) as ex:
            assert ex.submit(wrapped).result() == "attempt_1:input_a"
            assert ex.submit(ndc.current).result() == ""
    finally:
        logger.removeHandler(h)
