"""Canned DAG topology builders (tez-tests dag shapes analog)."""
import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.models import shapes


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("shapes", {"tez.staging-dir": tmp_staging,
                                    "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


def test_shapes_verify():
    for build in (shapes.simple_dag, shapes.simple_dag_3_vertices,
                  shapes.simple_v_dag, shapes.simple_reverse_v_dag,
                  shapes.two_levels_failing_dag,
                  shapes.three_levels_failing_dag):
        dag = build()
        dag.create_dag_plan()   # runs verify()


def test_three_levels_shape_runs(client):
    status = client.submit_dag(
        shapes.three_levels_failing_dag(payload={})) \
        .wait_for_completion(timeout=120)
    assert status.state is DAGStatusState.SUCCEEDED


def test_multi_attempt_dag_retries_then_succeeds(client):
    status = client.submit_dag(
        shapes.multi_attempt_dag(failing_upto_attempt=1)) \
        .wait_for_completion(timeout=120)
    assert status.state is DAGStatusState.SUCCEEDED
    am = client.framework_client.am
    d = am.dag_counters.to_dict().get("DAGCounter", {})
    # 3 vertices x (1 failed attempt + 1 success) at minimum
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) >= 6


def test_failing_shape_fails(client):
    dag = shapes.simple_dag(payload={"do_fail": True,
                                     "failing_task_indices": [-1]})
    status = client.submit_dag(dag).wait_for_completion(timeout=120)
    assert status.state is DAGStatusState.FAILED
