"""Estimator SPI unit tests (reference: SimpleExponentialTaskRuntimeEstimator
vs LegacyTaskRuntimeEstimator — the two must disagree exactly where the
exponential smoothing is the point)."""
from __future__ import annotations

import math

import pytest

from tez_tpu.am.estimators import (
    DataStatistics,
    LegacyRuntimeEstimator,
    SimpleExponentialRuntimeEstimator,
    create_estimator,
)
from tez_tpu.common import config as C


def _conf(**over):
    base = {
        "tez.am.legacy.speculative.exponential.smooth.lambda-millis": 2_000,
        "tez.am.legacy.speculative.exponential.stagnated.millis": 5_000,
        "tez.am.legacy.speculative.exponential.skip.initials": 3,
    }
    base.update(over)
    return C.TezConfiguration(base)


def test_data_statistics():
    s = DataStatistics()
    for x in (2.0, 4.0, 6.0):
        s.add(x)
    assert s.mean() == pytest.approx(4.0)
    assert s.std() == pytest.approx(math.sqrt(8 / 3))
    assert s.outlier(1.0) == pytest.approx(4.0 + math.sqrt(8 / 3))


def _feed(est, attempt, points):
    """points: (timestamp, progress) pairs."""
    est.enroll(attempt, points[0][0])
    for t, p in points:
        est.update_attempt(attempt, p, t)


def test_exponential_forgives_slow_start():
    """A task that crawled early but is moving fast NOW: the legacy
    lifetime-average estimator condemns it; the smoothed estimator sees the
    recent rate and predicts a short remaining time (the reason
    SimpleExponentialTaskRuntimeEstimator exists)."""
    # 0..20s: progress crawls to 0.1; 20..30s: sprints to 0.8
    points = [(float(t), 0.005 * t) for t in range(0, 21)]
    points += [(20.0 + t, 0.1 + 0.07 * t) for t in range(1, 11)]
    now = 30.0

    legacy = LegacyRuntimeEstimator()
    legacy.contextualize(_conf(), "v")
    legacy.attempt_succeeded(10.0)
    _feed(legacy, "a0", points)
    legacy_total = legacy.estimated_runtime("a0", now)

    exp = SimpleExponentialRuntimeEstimator()
    exp.contextualize(_conf(), "v")
    exp.attempt_succeeded(10.0)
    _feed(exp, "a0", points)
    exp_total = exp.estimated_runtime("a0", now)

    # legacy: 30s elapsed / 0.8 progress = 37.5s total -> straggler vs the
    # 10s mean.  exponential: recent rate ~0.07/s -> ~33s total... still
    # above mean, but the *relative* judgment flips at the decision gate:
    assert legacy_total == pytest.approx(37.5, rel=0.01)
    assert exp_total < legacy_total  # smoothing credits the recent sprint
    # with a harsher slow start the gap is decisive
    points2 = [(float(t), 0.001 * t) for t in range(0, 21)]
    points2 += [(20.0 + t, 0.02 + 0.095 * t) for t in range(1, 11)]
    legacy2 = LegacyRuntimeEstimator()
    legacy2.contextualize(_conf(), "v")
    _feed(legacy2, "a1", points2)
    exp2 = SimpleExponentialRuntimeEstimator()
    exp2.contextualize(_conf(), "v")
    _feed(exp2, "a1", points2)
    l2 = legacy2.estimated_runtime("a1", now)
    e2 = exp2.estimated_runtime("a1", now)
    # mean 10s, threshold 1.0 -> gate at 20s: legacy says ~31s (speculate),
    # exponential says ~30.3s elapsed+remaining/0.095 ~ 30.3 < ... both
    # above 20 in absolute terms, but exp2 must be well below l2 and close
    # to the true finish (progress 0.97 at t=30, ~0.3s left).
    assert e2 < 31.0 < l2 - 0.001 or e2 < l2 * 0.99
    assert e2 == pytest.approx(30.0 + (1 - 0.97) / 0.095, rel=0.2)


def test_exponential_catches_stagnation_legacy_does_not():
    """A task that reached 0.9 quickly then froze: lifetime average says
    'nearly done, fast' (legacy estimate ~ elapsed/0.9 — no speculation);
    the smoothed estimator detects stagnation and returns infinity."""
    points = [(float(t), 0.09 * t) for t in range(0, 11)]   # 0.9 @ t=10
    points += [(10.0 + 2 * t, 0.9) for t in range(1, 6)]    # frozen to t=20
    now = 20.0

    legacy = LegacyRuntimeEstimator()
    legacy.contextualize(_conf(), "v")
    _feed(legacy, "a0", points)
    assert legacy.estimated_runtime("a0", now) == pytest.approx(20 / 0.9,
                                                                rel=0.01)
    exp = SimpleExponentialRuntimeEstimator()
    exp.contextualize(_conf(), "v")   # stagnation window 5s
    _feed(exp, "a0", points)
    assert exp.has_stagnated("a0", now)
    assert exp.estimated_runtime("a0", now) == math.inf


def test_skip_initials_withholds_estimate():
    exp = SimpleExponentialRuntimeEstimator()
    exp.contextualize(_conf(), "v")   # skip.initials = 3
    exp.enroll("a0", 0.0)
    exp.update_attempt("a0", 0.1, 0.0)
    exp.update_attempt("a0", 0.2, 1.0)    # 1 rate sample
    assert exp.estimated_runtime("a0", 2.0) is None
    exp.update_attempt("a0", 0.3, 2.0)
    exp.update_attempt("a0", 0.4, 3.0)    # 3 samples -> estimate appears
    est = exp.estimated_runtime("a0", 3.0)
    assert est == pytest.approx(3.0 + 0.6 / 0.1, rel=0.05)


def test_registry_and_custom_class():
    conf = _conf(**{"tez.am.legacy.speculative.estimator.class": "legacy"})
    assert isinstance(create_estimator(conf, "v"), LegacyRuntimeEstimator)
    conf2 = _conf(**{
        "tez.am.legacy.speculative.estimator.class":
            "tez_tpu.am.estimators:SimpleExponentialRuntimeEstimator"})
    assert isinstance(create_estimator(conf2, "v"),
                      SimpleExponentialRuntimeEstimator)


def test_new_attempt_runtime_is_mean_of_completions():
    exp = SimpleExponentialRuntimeEstimator()
    exp.contextualize(_conf(), "v")
    assert exp.estimated_new_attempt_runtime() is None
    exp.attempt_succeeded(4.0)
    exp.attempt_succeeded(6.0)
    assert exp.estimated_new_attempt_runtime() == pytest.approx(5.0)
    assert exp.threshold_runtime(1.0) == pytest.approx(6.0)


def test_speculation_race_injected_slow_attempt(tmp_path):
    """E2E race via the fault plane's delay mode: task 0's first attempt is
    held in an injected 4s stall (no cooperative progress reporting, unlike
    StragglerProcessor — the delay happens *before* the processor runs), the
    speculator launches a copy, and the copy wins: the DAG finishes well
    under the injected delay (VERDICT item 6)."""
    import time as _time

    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex

    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 50}), 4)
    dag = DAG.create("specrace").add_vertex(v)
    dag.set_conf("tez.am.speculation.enabled", True)
    dag.set_conf("tez.am.legacy.speculative.slowtask.threshold", 1.0)
    dag.set_conf("tez.am.soonest.retry.after.no.speculate", 200)
    dag.set_conf("tez.test.fault.spec",
                 "task.run:delay:ms=4000,n=1,match=_00_000000_0")
    dag.set_conf("tez.test.fault.seed", 6)

    client = TezClient.create("specrace", {
        "tez.staging-dir": str(tmp_path / "staging"),
        "tez.am.local.num-containers": 5}).start()
    try:
        t0 = _time.monotonic()
        status = client.submit_dag(dag).wait_for_completion(timeout=30)
        elapsed = _time.monotonic() - t0
        assert status.state is DAGStatusState.SUCCEEDED
        # the speculative copy overtook the stalled original: the DAG beat
        # the injected delay with margin
        assert elapsed < 3.5, f"DAG waited out the stall ({elapsed:.1f}s)"
        am = client.framework_client.am
        d = am.dag_counters.to_dict().get("DAGCounter", {})
        assert d.get("NUM_SPECULATIONS", 0) >= 1
        from tez_tpu.am.history import HistoryEventType
        finished = {
            e.attempt_id: e.data.get("state", "")
            for e in am.logging_service.of_type(
                HistoryEventType.TASK_ATTEMPT_FINISHED)
            if e.attempt_id and "_00_000000_" in e.attempt_id}
        # the speculative sibling (attempt #1) is the one that succeeded
        assert any(a.endswith("_1") and s == "SUCCEEDED"
                   for a, s in finished.items()), finished
    finally:
        client.stop()
