"""Packaging layer: version, dist assemblies, example driver.

Reference role: tez-dist assemblies
(tez-dist/src/main/assembly/{tez-dist,tez-dist-minimal}.xml) and
ExampleDriver (tez-examples/.../ExampleDriver.java:33).
"""
import sys
import tarfile

import tez_tpu
from tez_tpu.examples import driver
from tez_tpu.tools import dist


def test_version_exported():
    assert tez_tpu.__version__.count(".") == 2


def test_dist_full_and_minimal(tmp_path):
    full = dist.build(minimal=False, out_dir=str(tmp_path))
    minimal = dist.build(minimal=True, out_dir=str(tmp_path))
    with tarfile.open(full) as tf:
        names = tf.getnames()
    root = names[0].split("/")[0]
    assert any(n.endswith("tez_tpu/examples/driver.py") for n in names)
    assert any(n.endswith("/bench.py") for n in names)
    assert any(n.endswith("native/ragged.cpp") for n in names)
    # every source the Makefile needs must ship, or make -C native fails
    assert any(n.endswith("native/shuffle_server.cpp") for n in names)
    assert any(n.endswith("native/Makefile") for n in names)
    assert f"{root}/MANIFEST" in names
    with tarfile.open(minimal) as tf:
        min_names = tf.getnames()
    assert not any("/examples/" in n or "/models/" in n for n in min_names)
    # tools stay in minimal (AM web imports them at request time)
    assert any("/tools/analyzers.py" in n for n in min_names)
    assert any(n.endswith("tez_tpu/am/app_master.py") for n in min_names)
    assert any(n.endswith("native/ragged.cpp") for n in min_names)
    assert any(n.endswith("native/shuffle_server.cpp") for n in min_names)
    assert len(min_names) < len(names)


def test_example_driver_usage(capsys, monkeypatch):
    monkeypatch.setattr(sys, "argv", ["tez-examples"])
    assert driver.main() == 2
    out = capsys.readouterr().out
    for name in ("wordcount", "orderedwordcount", "mrr", "sortmergejoin",
                 "hashjoin"):
        assert name in out


def test_example_driver_runs_wordcount(tmp_path, capsys, monkeypatch):
    corpus = tmp_path / "in.txt"
    corpus.write_text("a b a c a b\n")
    out_dir = str(tmp_path / "out")
    monkeypatch.setattr(
        sys, "argv", ["tez-examples", "wordcount", str(corpus), out_dir])
    assert driver.main() == 0
    assert "SUCCEEDED" in capsys.readouterr().out


def test_cartesian_product_example(tmp_path, capsys, monkeypatch):
    left = tmp_path / "l.txt"; left.write_text("a b\n")
    right = tmp_path / "r.txt"; right.write_text("x y z\n")
    out = str(tmp_path / "out")
    monkeypatch.setattr(sys, "argv", ["tez-examples", "cartesianproduct",
                                      str(left), str(right), out])
    assert driver.main() == 0
    import os
    pairs = set()
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                pairs.add(line.split("\t")[0])
    assert pairs == {f"{a}|{b}" for a in "ab" for b in "xyz"}


def test_simple_session_example(tmp_path, capsys, monkeypatch):
    files = []
    for i in range(2):
        p = tmp_path / f"in{i}.txt"
        p.write_text(f"w{i} w{i} other\n")
        files.append(str(p))
    out = str(tmp_path / "out")
    monkeypatch.setattr(sys, "argv", ["tez-examples", "simplesessionexample",
                                      *files, out])
    assert driver.main() == 0
    import os
    assert sorted(os.listdir(out)) == ["dag0", "dag1"]
