"""Relational query engine tests (tez_tpu/query/, docs/query.md).

Four layers, cheapest first:

- logical-plan unit tests: fingerprint stability, schema propagation;
- planner unit tests: content-addressed vertex names, operator tags,
  strategy decision records (estimate / forced / pinned / required);
- PlanFeedback unit tests: observed-build strategy flips, skew-driven
  reducer bumps, plane blame from histogram deltas;
- end-to-end: every tools/query_corpus.py query bit-exact vs its numpy
  oracle under the auto planner, under BOTH forced join strategies, with
  sealed-lineage reuse on, under a seeded kill storm, and through the
  skewed-corpus replan path (run 1 repartition by estimate, run 2
  broadcast by observation, QUERY_REPLANNED journaled).
"""
import os
from types import SimpleNamespace

import pytest

from tez_tpu.am.history import HistoryEventType
from tez_tpu.query import PlanFeedback, QuerySession, Table, plan_query
from tez_tpu.query.feedback import blame_from_histograms
from tez_tpu.tools.query_corpus import CORPUS_QUERIES, generate

CONF_BASE = {"tez.am.local.num-containers": 4}


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    return generate(str(tmp_path_factory.mktemp("qcorpus")),
                    scale=0.25, skew=0.0, seed=3)


@pytest.fixture(scope="module")
def zipf_corpus(tmp_path_factory):
    return generate(str(tmp_path_factory.mktemp("qcorpus_zipf")),
                    scale=0.25, skew=1.2, seed=5)


def _session(tmp_path, name, extra=None):
    conf = dict(CONF_BASE)
    conf["tez.staging-dir"] = str(tmp_path / name / "staging")
    conf.update(extra or {})
    return QuerySession(name, conf)


def _events(session, event_type):
    am = session._am
    return [ev for ev in am.logging_service.events
            if ev.event_type is event_type]


# ------------------------------------------------------------- logical plan

def _tiny_scan(tmp_path, name="t", rows=("a|1", "b|2")):
    p = tmp_path / f"{name}.tbl"
    p.write_text("\n".join(rows) + "\n")
    return Table.scan(name, [str(p)], ["k", "v"])


def test_fingerprints_stable_and_structural(tmp_path):
    t1 = _tiny_scan(tmp_path).filter("v", "ge", "1", numeric=True)
    t2 = _tiny_scan(tmp_path).filter("v", "ge", "1", numeric=True)
    assert t1.plan.fingerprint == t2.plan.fingerprint
    t3 = _tiny_scan(tmp_path).filter("v", "ge", "2", numeric=True)
    assert t1.plan.fingerprint != t3.plan.fingerprint


def test_schema_propagation(tmp_path):
    left = _tiny_scan(tmp_path, "l")
    right = Table.scan("r", [str(tmp_path / "r.tbl")], ["k", "w"])
    inner = left.join(right, "k")
    assert inner.plan.schema == ("k", "v", "w")
    assert left.join(right, "k", how="semi").plan.schema == ("k", "v")
    assert left.join(right, "k",
                     how="semi_distinct").plan.schema == ("k",)
    agg = inner.aggregate(["k"], [("n", "count", "k"),
                                  ("s", "sum", "v")])
    assert agg.plan.schema == ("k", "n", "s")
    win = inner.window("k", "v", func="row_number", out_col="rk")
    assert win.plan.schema == ("k", "v", "w", "rk")
    assert inner.limit(5, ["k"]).plan.schema == ("k", "v", "w")


# ----------------------------------------------------------------- planner

def test_planner_content_addressed_and_tagged(tmp_path, corpus):
    q = next(c for c in CORPUS_QUERIES if c.name == "nation_revenue")
    conf = {"tez.staging-dir": str(tmp_path / "staging")}
    p1 = plan_query(q.build(corpus), conf, str(tmp_path / "o1"))
    p2 = plan_query(q.build(corpus), conf, str(tmp_path / "o2"))
    # identical subplans lower to identical vertex names: that identity
    # IS the sealed-lineage cache key (docs/query.md, docs/store.md)
    assert set(p1.operators) == set(p2.operators)
    assert p1.operators == p2.operators
    for vname, tag in p1.operators.items():
        assert vname.startswith("q_")
        assert "@" in tag            # "<op chain>@<fingerprint>"
    strategies = [d for d in p1.decisions if d["kind"] == "join_strategy"]
    assert strategies and strategies[0]["basis"] == "estimate"


def test_planner_strategy_bases(tmp_path):
    left = _tiny_scan(tmp_path, "l")
    right = Table.scan("r", [str(tmp_path / "l.tbl")], ["k", "w"])
    conf = {"tez.query.scan.splits": 1}

    def strategy_decision(table, extra=None):
        p = plan_query(table.plan, {**conf, **(extra or {})},
                       str(tmp_path / "out"))
        return next(d for d in p.decisions
                    if d["kind"] == "join_strategy")

    d = strategy_decision(left.join(right, "k"),
                          {"tez.query.join.strategy": "repartition"})
    assert (d["choice"], d["basis"]) == ("repartition", "forced")
    d = strategy_decision(left.hash_join(right, "k"))
    assert (d["choice"], d["basis"]) == ("broadcast", "pinned")
    d = strategy_decision(left.sort_merge_join(right, "k"))
    assert (d["choice"], d["basis"]) == ("repartition", "pinned")
    d = strategy_decision(left.join(right, "k", how="semi_distinct"),
                          {"tez.query.join.strategy": "broadcast"})
    # distinct-on-key needs the key-partitioned exchange: required
    # outranks even the forced knob
    assert (d["choice"], d["basis"]) == ("repartition", "required")


# ---------------------------------------------------------------- feedback

def _feedback(**over):
    conf = {"tez.query.replan.enabled": True,
            "tez.query.replan.skew-factor": 4.0,
            "tez.query.replan.max-reducers": 8}
    conf.update(over)
    return PlanFeedback(conf)


def _strategy_run(fb, fp, strategy, build_bytes, blamed="exchange"):
    fb.record_run(
        [{"node": fp, "operator": "join", "kind": "join_strategy",
          "choice": strategy, "basis": "estimate", "detail": ""}],
        {(fp, "build"): {"bytes": build_bytes, "partitions": [build_bytes]}},
        blamed, 1.0)


def test_feedback_strategy_flips():
    fb = _feedback()
    assert fb.advise_strategy("fp", 1.0) is None   # nothing observed yet
    _strategy_run(fb, "fp", "repartition", 1024)   # 1KB observed build
    strat, detail, extras = fb.advise_strategy("fp", 1.0)
    assert strat == "broadcast" and extras["from"] == "repartition"
    # outgrown broadcast flips back
    _strategy_run(fb, "fp2", "broadcast", 8 << 20)
    strat, _, extras = fb.advise_strategy("fp2", 1.0)
    assert strat == "repartition" and extras["to"] == "repartition"
    # observed-good strategy is pinned (no flip-flop on estimates)
    _strategy_run(fb, "fp3", "broadcast", 1024)
    strat, _, extras = fb.advise_strategy("fp3", 1.0)
    assert strat == "broadcast" and extras["from"] == extras["to"]


def test_feedback_reducer_bump_on_skew():
    fb = _feedback()
    fb.record_run(
        [{"node": "fp", "operator": "agg", "kind": "parallelism",
          "choice": 2, "basis": "default", "detail": ""}],
        {("fp", "group"): {"bytes": 1100, "partitions": [1000, 100]}},
        "exchange", 1.0)
    n, _, extras = fb.advise_reducers("fp", 2)
    assert n == 4 and extras == {"from": 2, "to": 4, "role": "group",
                                 "peak_bytes": 1000, "rest_bytes": 100.0}
    # the bump is sticky once the skew is fixed, and capped at max
    fb.record_run(
        [{"node": "fp", "operator": "agg", "kind": "parallelism",
          "choice": 4, "basis": "replan", "detail": ""}],
        {("fp", "group"): {"bytes": 1200,
                           "partitions": [300, 300, 300, 300]}},
        "exchange", 1.0)
    n, _, _ = fb.advise_reducers("fp", 2)
    assert n == 4
    fb.max_reducers = 4
    fb.record_run([], {("fp", "group"): {"bytes": 1100,
                                         "partitions": [1000, 50, 25, 25]}},
                  "exchange", 1.0)
    assert fb.advise_reducers("fp", 2)[0] == 4


def test_feedback_disabled_gives_no_opinion():
    fb = _feedback(**{"tez.query.replan.enabled": False})
    _strategy_run(fb, "fp", "repartition", 1024)
    assert fb.advise_strategy("fp", 1.0) is None
    assert fb.advise_reducers("fp", 2) is None


def test_blame_from_histograms():
    h = lambda ms: SimpleNamespace(sum_ms=ms)  # noqa: E731
    before = {"shuffle.fetch.wait_ms": h(10.0)}
    after = {"shuffle.fetch.wait_ms": h(510.0),
             "device.dispatch_ms": h(20.0),
             "unrelated.metric_ms": h(9999.0)}
    plane, busy = blame_from_histograms(before, after)
    assert plane == "transport" and busy == 500.0
    assert blame_from_histograms(after, after) == ("", 0.0)


# -------------------------------------------------------------- end to end

def test_corpus_bit_exact_auto(tmp_path, corpus):
    with _session(tmp_path, "auto") as s:
        for q in CORPUS_QUERIES:
            r = s.run(q.build(corpus), str(tmp_path / f"out_{q.name}"),
                      query_name=q.name, sink=q.sink)
            assert r.state == "SUCCEEDED"
            assert r.read_output() == q.oracle(corpus), q.name
        submitted = _events(s, HistoryEventType.QUERY_SUBMITTED)
    assert len(submitted) == len(CORPUS_QUERIES)
    by_name = {ev.data["query"]: ev.data for ev in submitted}
    assert set(by_name) == {q.name for q in CORPUS_QUERIES}
    for data in by_name.values():
        assert data["operators"] and data["wall_s"] > 0


def test_corpus_bit_exact_both_strategies_forced(tmp_path, corpus,
                                                 zipf_corpus):
    """Physical join strategy must never change results — every join
    query bit-exact under both forced strategies, on the uniform AND the
    Zipf-skewed corpus."""
    joiny = [q for q in CORPUS_QUERIES
             if any(n.op == "join" for n in q.build(corpus).plan.walk())]
    assert len(joiny) >= 4
    for label, c in (("uni", corpus), ("zipf", zipf_corpus)):
        for strategy in ("broadcast", "repartition"):
            with _session(tmp_path, f"forced_{label}_{strategy}",
                          {"tez.query.join.strategy": strategy}) as s:
                for q in joiny:
                    r = s.run(q.build(c),
                              str(tmp_path /
                                  f"o_{label}_{strategy}_{q.name}"),
                              query_name=q.name, sink=q.sink)
                    assert r.state == "SUCCEEDED"
                    assert r.read_output() == q.oracle(c), \
                        (label, strategy, q.name)


def test_session_lineage_reuse(tmp_path, corpus):
    """Identical rerun in one session is served from the sealed-lineage
    store (PR-7) through the governed result cache (PR-11), bit-exact."""
    q = next(c for c in CORPUS_QUERIES if c.name == "nation_revenue")
    with _session(tmp_path, "reuse",
                  {"tez.runtime.store.enabled": True,
                   "tez.query.replan.enabled": False}) as s:
        r1 = s.run(q.build(corpus), str(tmp_path / "reuse1"),
                   query_name=q.name, sink=q.sink)
        r2 = s.run(q.build(corpus), str(tmp_path / "reuse2"),
                   query_name=q.name, sink=q.sink)
        submitted = _events(s, HistoryEventType.QUERY_SUBMITTED)
    want = q.oracle(corpus)
    assert r1.read_output() == want and r2.read_output() == want
    assert r1.cache_hits == 0 and r2.cache_hits > 0
    assert submitted[-1].data["cache_hits"] == r2.cache_hits


def test_replan_flips_exchange_bound_join(tmp_path, zipf_corpus):
    """The adaptive loop on the seeded skewed corpus: run 1 repartitions
    by (file-size) estimate; the observed post-filter build side fits the
    broadcast threshold, so run 2 is replanned to broadcast — journaled
    as a typed QUERY_REPLANNED summary event BEFORE the DAG submits —
    and stays bit-exact."""
    c = zipf_corpus

    def build():
        # selective filter on the build side: the estimator can't see
        # through it (estimated_bytes is file size), the observation can
        small = c.scan("orders").filter("o_total", "ge", "95000",
                                        numeric=True)
        return (c.scan("lineitem")
                .join(small, "l_orderkey", "o_orderkey")
                .aggregate(["l_flag"], [("n", "count", "l_flag"),
                                        ("rev", "sum", "l_price")]))

    conf = {"tez.query.broadcast.max-mb": 0.004}
    with _session(tmp_path, "replan", conf) as s:
        r1 = s.run(build(), str(tmp_path / "rp1"), query_name="rp")
        r2 = s.run(build(), str(tmp_path / "rp2"), query_name="rp")
    d1 = next(d for d in r1.decisions if d["kind"] == "join_strategy")
    d2 = next(d for d in r2.decisions if d["kind"] == "join_strategy")
    assert (d1["choice"], d1["basis"]) == ("repartition", "estimate")
    assert (d2["choice"], d2["basis"]) == ("broadcast", "replan")
    assert r1.replans == [] and len(r2.replans) >= 1
    flip = next(p for p in r2.replans if p["kind"] == "join_strategy")
    assert (flip["from"], flip["to"]) == ("repartition", "broadcast")
    assert r1.read_output() == r2.read_output() != []


def test_query_kill_storm_inline(tmp_path, corpus):
    """Tier-1 sliver of chaos --query-storm: two corpus queries under
    seeded recoverable task kills with the result cache on — retries may
    cost time, never rows."""
    queries = [q for q in CORPUS_QUERIES
               if q.name in ("nation_revenue", "supply_chain")]
    with _session(tmp_path, "storm",
                  {"tez.runtime.store.enabled": True,
                   "tez.am.task.max.failed.attempts": 4}) as s:
        for i, q in enumerate(queries):
            r = s.run(q.build(corpus), str(tmp_path / f"storm_{q.name}"),
                      query_name=q.name, sink=q.sink,
                      dag_conf={"tez.test.fault.spec":
                                "task.run:fail:n=2,exc=runtime",
                                "tez.test.fault.seed": i,
                                "tez.dag.tenant": f"tenant{i % 2}"})
            assert r.state == "SUCCEEDED"
            assert r.read_output() == q.oracle(corpus), q.name
        finished = _events(s, HistoryEventType.TASK_ATTEMPT_FINISHED)
        killed = sum(1 for ev in finished
                     if (ev.data or {}).get("state") == "FAILED")
    assert killed >= 2


@pytest.mark.slow
def test_query_storm_chaos_harness(tmp_path):
    """The full chaos leg: both corpus flavors (seed parity picks
    uniform vs Zipf), the whole suite twice per seed, kills + cache."""
    from tez_tpu.tools import chaos
    for seed in (0, 1):
        ok, detail = chaos.run_query_storm(seed, str(tmp_path),
                                           timeout=120.0)
        assert ok, detail
