"""Timeline-cache analog: cached DagInfo reads over JSONL history dirs.

Reference role: tez-yarn-timeline-cache-plugin (per-DAG entity-group cache
for the history read path).
"""
import os
import time

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.models import shapes
from tez_tpu.tools.history_cache import DagInfoCache


@pytest.fixture()
def history_dir(tmp_path, tmp_staging):
    log_dir = str(tmp_path / "hist")
    conf = {"tez.staging-dir": tmp_staging,
            "tez.history.logging.service.class":
                "tez_tpu.am.history:JsonlHistoryLoggingService",
            "tez.history.logging.log-dir": log_dir}
    with TezClient.create("hc", conf) as client:
        st = client.submit_dag(shapes.simple_dag(payload={})) \
            .wait_for_completion(timeout=60)
        assert st.state is DAGStatusState.SUCCEEDED
    return log_dir


def test_cache_parses_and_caches(history_dir):
    cache = DagInfoCache(history_dir)
    ids = cache.dag_ids()
    assert len(ids) == 1
    dag = cache.get(ids[0])
    assert dag is not None and dag.state == "SUCCEEDED"
    assert dag.vertex("v1") is not None
    # second read: no file changed -> no re-parse, hit counted
    files_before = dict(cache._fingerprints)
    assert cache.get(ids[0]) is dag
    assert cache.hits >= 1
    assert cache._fingerprints == files_before


def test_cache_invalidates_on_append(history_dir):
    cache = DagInfoCache(history_dir)
    ids = cache.dag_ids()
    first = cache.get(ids[0])
    # append a new DAG's history into a NEW file in the same dir
    path = os.path.join(history_dir, "extra.jsonl")
    from tez_tpu.am.history import scan_history_store
    src = scan_history_store(history_dir)[0]
    import re
    with open(os.path.join(history_dir, src)) as fh:
        body = re.sub(r"dag_(\d)", r"dagX_\1", fh.read())
    with open(path, "w") as fh:
        fh.write(body)
    ids2 = cache.dag_ids()
    assert len(ids2) == 2
    # original entry survived unchanged (entity-group isolation)
    assert cache.get(ids[0]) is first


def test_cache_lru_eviction(tmp_path):
    log_dir = str(tmp_path)
    # synthesize 3 single-line dag files via a real one is heavy; use dag
    # submitted/finished pairs
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    for i in range(3):
        with open(os.path.join(log_dir, f"h{i}.jsonl"), "w") as fh:
            for kind, data in ((HistoryEventType.DAG_SUBMITTED,
                                {"dag_name": f"d{i}"}),
                               (HistoryEventType.DAG_FINISHED,
                                {"state": "SUCCEEDED"})):
                fh.write(HistoryEvent(kind, dag_id=f"dag_{i}",
                                      timestamp=time.time(),
                                      data=data).to_json() + "\n")
    cache = DagInfoCache(log_dir, max_dags=2)
    assert len(cache.dag_ids()) == 2  # oldest evicted


def test_cache_evicted_dag_still_readable(tmp_path):
    """A miss for an LRU-evicted DAG triggers a bypass re-parse (the files
    are unchanged, so refresh alone would never restore it)."""
    import json, os, time
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    log_dir = str(tmp_path)
    for i in range(3):
        with open(os.path.join(log_dir, f"h{i}.jsonl"), "w") as fh:
            for kind, data in ((HistoryEventType.DAG_SUBMITTED,
                                {"dag_name": f"d{i}"}),
                               (HistoryEventType.DAG_FINISHED,
                                {"state": "SUCCEEDED"})):
                fh.write(HistoryEvent(kind, dag_id=f"dag_{i}",
                                      timestamp=time.time(),
                                      data=data).to_json() + "\n")
    cache = DagInfoCache(log_dir, max_dags=2)
    present = set(cache.dag_ids())
    evicted = ({"dag_0", "dag_1", "dag_2"} - present).pop()
    info = cache.get(evicted)
    assert info is not None and info.state == "SUCCEEDED"


def test_store_layout_is_date_partitioned(history_dir):
    """The docstring's promise is now true: journals land under
    date=YYYY-MM-DD/app_<id>_<pid>.jsonl (ProtoHistoryLoggingService's
    date-partitioned layout)."""
    import os
    import re
    entries = sorted(os.listdir(history_dir))
    assert entries and all(re.fullmatch(r"date=\d{4}-\d{2}-\d{2}", e)
                           for e in entries), entries
    files = os.listdir(os.path.join(history_dir, entries[0]))
    assert files and all(re.fullmatch(r"app_.+_\d+\.jsonl", f)
                         for f in files), files


def _fake_day(tmp_path, day, app, events):
    import os
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    d = tmp_path / f"date={day}"
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"app_{app}_1.jsonl", "a") as fh:
        for dag_id, etype, data in events:
            fh.write(HistoryEvent(HistoryEventType[etype], dag_id=dag_id,
                                  data=data).to_json() + "\n")


def test_manifest_scan_multi_day_and_date_bounds(tmp_path):
    """scan_history_store walks the partitions (+ legacy flat files) and
    honors inclusive date bounds."""
    from tez_tpu.am.history import scan_history_store
    _fake_day(tmp_path, "2026-07-27", "a1",
              [("dag_1", "DAG_SUBMITTED", {"dag_name": "d1"})])
    _fake_day(tmp_path, "2026-07-28", "a2",
              [("dag_2", "DAG_SUBMITTED", {"dag_name": "d2"})])
    _fake_day(tmp_path, "2026-07-29", "a3",
              [("dag_3", "DAG_SUBMITTED", {"dag_name": "d3"})])
    (tmp_path / "legacy.jsonl").write_text("")
    got = scan_history_store(str(tmp_path))
    assert len(got) == 4 and got[-1].endswith("legacy.jsonl")
    got = scan_history_store(str(tmp_path), date_from="2026-07-28")
    assert [p for p in got if "date=" in p] == \
        [p for p in got] and len(got) == 2
    got = scan_history_store(str(tmp_path), date_from="2026-07-28",
                             date_to="2026-07-28")
    assert len(got) == 1 and "date=2026-07-28" in got[0]


def test_cache_and_parser_over_multi_day_store(tmp_path):
    """DagInfoCache + history parser read a store whose DAGs span several
    date partitions (a DAG finishing after midnight has events in two)."""
    from tez_tpu.tools.history_parser import parse_jsonl_files
    _fake_day(tmp_path, "2026-07-28", "am1", [
        ("dag_x", "DAG_SUBMITTED", {"dag_name": "overnight"}),
        ("dag_x", "DAG_STARTED", {}),
    ])
    _fake_day(tmp_path, "2026-07-29", "am1", [
        ("dag_x", "DAG_FINISHED", {"state": "SUCCEEDED"}),
        ("dag_y", "DAG_SUBMITTED", {"dag_name": "fresh"}),
        ("dag_y", "DAG_FINISHED", {"state": "FAILED"}),
    ])
    cache = DagInfoCache(str(tmp_path))
    ids = set(cache.dag_ids())
    assert ids == {"dag_x", "dag_y"}
    assert cache.get("dag_x").state == "SUCCEEDED"
    assert cache.get("dag_y").state == "FAILED"
    # the parser CLI path: a bare directory argument manifest-scans it
    dags = parse_jsonl_files([str(tmp_path)])
    assert set(dags) == {"dag_x", "dag_y"}
    assert dags["dag_x"].name == "overnight"
