"""Timeline-cache analog: cached DagInfo reads over JSONL history dirs.

Reference role: tez-yarn-timeline-cache-plugin (per-DAG entity-group cache
for the history read path).
"""
import os
import time

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.models import shapes
from tez_tpu.tools.history_cache import DagInfoCache


@pytest.fixture()
def history_dir(tmp_path, tmp_staging):
    log_dir = str(tmp_path / "hist")
    conf = {"tez.staging-dir": tmp_staging,
            "tez.history.logging.service.class":
                "tez_tpu.am.history:JsonlHistoryLoggingService",
            "tez.history.logging.log-dir": log_dir}
    with TezClient.create("hc", conf) as client:
        st = client.submit_dag(shapes.simple_dag(payload={})) \
            .wait_for_completion(timeout=60)
        assert st.state is DAGStatusState.SUCCEEDED
    return log_dir


def test_cache_parses_and_caches(history_dir):
    cache = DagInfoCache(history_dir)
    ids = cache.dag_ids()
    assert len(ids) == 1
    dag = cache.get(ids[0])
    assert dag is not None and dag.state == "SUCCEEDED"
    assert dag.vertex("v1") is not None
    # second read: no file changed -> no re-parse, hit counted
    files_before = dict(cache._fingerprints)
    assert cache.get(ids[0]) is dag
    assert cache.hits >= 1
    assert cache._fingerprints == files_before


def test_cache_invalidates_on_append(history_dir):
    cache = DagInfoCache(history_dir)
    ids = cache.dag_ids()
    first = cache.get(ids[0])
    # append a new DAG's history into a NEW file in the same dir
    path = os.path.join(history_dir, "extra.jsonl")
    src = [f for f in os.listdir(history_dir) if f != "extra.jsonl"][0]
    import re
    with open(os.path.join(history_dir, src)) as fh:
        body = re.sub(r"dag_(\d)", r"dagX_\1", fh.read())
    with open(path, "w") as fh:
        fh.write(body)
    ids2 = cache.dag_ids()
    assert len(ids2) == 2
    # original entry survived unchanged (entity-group isolation)
    assert cache.get(ids[0]) is first


def test_cache_lru_eviction(tmp_path):
    log_dir = str(tmp_path)
    # synthesize 3 single-line dag files via a real one is heavy; use dag
    # submitted/finished pairs
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    for i in range(3):
        with open(os.path.join(log_dir, f"h{i}.jsonl"), "w") as fh:
            for kind, data in ((HistoryEventType.DAG_SUBMITTED,
                                {"dag_name": f"d{i}"}),
                               (HistoryEventType.DAG_FINISHED,
                                {"state": "SUCCEEDED"})):
                fh.write(HistoryEvent(kind, dag_id=f"dag_{i}",
                                      timestamp=time.time(),
                                      data=data).to_json() + "\n")
    cache = DagInfoCache(log_dir, max_dags=2)
    assert len(cache.dag_ids()) == 2  # oldest evicted


def test_cache_evicted_dag_still_readable(tmp_path):
    """A miss for an LRU-evicted DAG triggers a bypass re-parse (the files
    are unchanged, so refresh alone would never restore it)."""
    import json, os, time
    from tez_tpu.am.history import HistoryEvent, HistoryEventType
    log_dir = str(tmp_path)
    for i in range(3):
        with open(os.path.join(log_dir, f"h{i}.jsonl"), "w") as fh:
            for kind, data in ((HistoryEventType.DAG_SUBMITTED,
                                {"dag_name": f"d{i}"}),
                               (HistoryEventType.DAG_FINISHED,
                                {"state": "SUCCEEDED"})):
                fh.write(HistoryEvent(kind, dag_id=f"dag_{i}",
                                      timestamp=time.time(),
                                      data=data).to_json() + "\n")
    cache = DagInfoCache(log_dir, max_dags=2)
    present = set(cache.dag_ids())
    evicted = ({"dag_0", "dag_1", "dag_2"} - present).pop()
    info = cache.get(evicted)
    assert info is not None and info.state == "SUCCEEDED"
