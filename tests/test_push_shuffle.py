"""Push-based pipelined shuffle: admission, transport, fencing, backstop.

The contract under test (docs/push_shuffle.md): every spill is registered
for pull BEFORE its push is queued, so no push failure — rejection storm,
dead pusher, bad auth, stale epoch — may ever lose data; pushes that do
land are zero-copy aliases of the pull-registered run (same host) or
per-partition runs under ``push_key`` (remote).
"""
import threading
import time

import pytest

from tez_tpu.common import epoch as epoch_registry
from tez_tpu.common import faults
from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.common.epoch import EpochFencedError
from tez_tpu.common.faults import parse_spec
from tez_tpu.common.security import JobTokenSecretManager
from tez_tpu.ops.sorter import DeviceSorter
from tez_tpu.shuffle.push import (PushAdmissionController, PushRejected,
                                  SpillPusher, push_key)
from tez_tpu.shuffle.server import ShuffleServer
from tez_tpu.shuffle.service import ShuffleDataNotFound, ShuffleService
from tez_tpu.store.buffer_store import HOST, ShuffleBufferStore


def _make_run(partitions=3, records=60, tag="k"):
    sorter = DeviceSorter(num_partitions=partitions)
    for i in range(records):
        sorter.write(f"{tag}{i:04d}".encode(), f"v{i}".encode())
    return sorter.flush()


@pytest.fixture()
def landing(tmp_path):
    """Consumer-side landing zone: service + buffer store + admission."""
    service = ShuffleService()
    store = ShuffleBufferStore(device_capacity=0, host_capacity=8 << 20,
                               disk_dir=str(tmp_path / "spill"))
    service.attach_buffer_store(store)
    admission = PushAdmissionController(lambda: store,
                                        source_quota_bytes=4 << 20,
                                        retry_after_ms=1.0)
    service.attach_push_admission(admission)
    return service, store, admission


# ---------------------------------------------------------------- admission

def test_admission_watermark_rejects_above_host_capacity(tmp_path):
    store = ShuffleBufferStore(device_capacity=0, host_capacity=1000,
                               disk_dir=str(tmp_path))
    adm = PushAdmissionController(lambda: store, admit_watermark=0.5,
                                  retry_after_ms=7.0)
    adm.admit("src_a", 100)
    with pytest.raises(PushRejected) as ei:
        adm.admit("src_a", 600)     # 0 in tier yet, but 600 > 1000 * 0.5
    assert ei.value.retry_after_ms == 7.0
    assert "watermark" in ei.value.reason
    assert adm.admitted == 1 and adm.rejected == 1


def test_admission_source_quota_first_oversize_admitted():
    adm = PushAdmissionController(lambda: None, source_quota_bytes=100)
    # no store: everything is rejected outright (push has no landing zone)
    with pytest.raises(PushRejected) as ei:
        adm.admit("src", 10)
    assert "landing zone" in ei.value.reason

    store_holder = []
    adm2 = PushAdmissionController(lambda: store_holder[0],
                                   source_quota_bytes=100)

    class _FakeStore:
        host_capacity = 0           # watermark rule off

        def tier_bytes(self, tier):
            return 0

    store_holder.append(_FakeStore())
    adm2.admit("hot", 5000)         # oversize while holding nothing: allowed
    assert adm2.held("hot") == 5000
    with pytest.raises(PushRejected):
        adm2.admit("hot", 1)        # quota exhausted once holding
    adm2.admit("cold", 50)
    adm2.admit("cold", 50)
    with pytest.raises(PushRejected):
        adm2.admit("cold", 1)
    assert adm2.release_prefix("hot") == 5000
    adm2.admit("hot", 60)           # quota returned
    assert adm2.held("hot") == 60


def test_admission_fault_point_turns_decision_into_rejection():
    class _FakeStore:
        host_capacity = 0

        def tier_bytes(self, tier):
            return 0

    adm = PushAdmissionController(lambda: _FakeStore())
    faults.install("t", parse_spec("shuffle.push.admit:fail:n=1,exc=io"),
                   seed=1)
    try:
        with pytest.raises(PushRejected) as ei:
            adm.admit("src", 10)
        assert "fault-injected" in ei.value.reason
        adm.admit("src", 10)        # n=1: the next decision is clean
    finally:
        faults.clear_all()
    assert adm.rejected == 1 and adm.admitted == 1


# ----------------------------------------------------- same-host push publish

def test_push_publish_same_host_is_zero_copy_alias(landing):
    service, store, _ = landing
    run = _make_run()
    service.register("dagP/a_1/c", 0, run, use_store=False)   # pull backstop
    service.push_publish("dagP/a_1/c", 0, run)
    # the store entry IS the registered run object — no copy, no
    # double-count between the pull registry and the push landing zone
    assert store.get("dagP/a_1/c", 0) is run
    got = service.fetch_partition("dagP/a_1/c", 0, 1)
    assert list(got.iter_pairs()) == list(run.partition(1).iter_pairs())


def test_push_publish_stale_epoch_fenced(landing):
    service, store, _ = landing
    epoch_registry.register("app_push", 3)
    run = _make_run()
    with pytest.raises(EpochFencedError):
        service.push_publish("dagF/a_1/c", 0, run, epoch=2, app_id="app_push")
    assert store.get("dagF/a_1/c", 0) is None
    # the live epoch pushes fine
    service.push_publish("dagF/a_1/c", 0, run, epoch=3, app_id="app_push")
    assert store.get("dagF/a_1/c", 0) is run


def test_push_publish_without_admission_rejects(tmp_path):
    service = ShuffleService()
    store = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 20,
                               disk_dir=str(tmp_path))
    service.attach_buffer_store(store)      # store but NO admission
    with pytest.raises(PushRejected):
        service.push_publish("dagN/a_1/c", 0, _make_run())


def test_push_listener_notified_and_errors_swallowed(landing):
    service, _, _ = landing
    seen = []

    def broken(path, spill):
        raise RuntimeError("consumer merge-wake exploded")

    service.add_push_listener(broken)
    service.add_push_listener(lambda path, spill: seen.append((path, spill)))
    run = _make_run()
    service.push_publish("dagL/a_1/c", 2, run)   # broken listener: no raise
    assert seen == [("dagL/a_1/c", 2)]
    service.remove_push_listener(broken)


def test_unregister_prefix_sweeps_pushed_keys_and_quota(landing):
    service, store, admission = landing
    run = _make_run()
    service.register("dagU/a_1/c", 0, run, use_store=False)
    service.push_publish("dagU/a_1/c", 0, run)
    # a remotely-landed partition under the same DAG prefix
    service.push_publish("dagU/a_1/c", 1, _make_run(partitions=1),
                         partition=2)
    assert admission.held("dagU/a_1/c") > 0
    service.unregister_prefix("dagU")
    assert store.get("dagU/a_1/c", 0) is None
    assert store.get(push_key("dagU/a_1/c", 2), 1) is None
    assert admission.held("dagU/a_1/c") == 0
    with pytest.raises(ShuffleDataNotFound):
        service.fetch_partition("dagU/a_1/c", 0, 0)


# ------------------------------------------------------------- SpillPusher

def test_spill_pusher_local_counters_and_close_drain(landing):
    service, store, _ = landing
    counters = TezCounters()
    pusher = SpillPusher(service, threads=2, counters=counters)
    runs = [_make_run(tag=f"t{i}") for i in range(4)]
    for i, run in enumerate(runs):
        service.register("dagS/a_1/c", i, run, use_store=False)
        assert pusher.submit("dagS/a_1/c", i, run)
    pusher.close()                  # close drains: counters are settled
    assert pusher.pushes_sent == 4 and pusher.pushes_rejected == 0
    assert counters.find_counter(TaskCounter.SHUFFLE_PUSH_BYTES).value == \
        sum(r.nbytes for r in runs)
    assert not pusher.submit("dagS/a_1/c", 99, runs[0])   # closed
    for i, run in enumerate(runs):
        assert store.get("dagS/a_1/c", i) is run


def test_spill_pusher_send_fault_storm_pull_backstop(landing):
    service, store, _ = landing
    counters = TezCounters()
    run = _make_run()
    service.register("dagK/a_1/c", 0, run, use_store=False)
    faults.install("t", parse_spec("shuffle.push.send:fail:exc=io"), seed=1)
    try:
        pusher = SpillPusher(service, retries=2, counters=counters,
                             backoff_base=0.001)
        pusher.submit("dagK/a_1/c", 0, run)
        pusher.close()
    finally:
        faults.clear_all()
    assert pusher.pushes_rejected == 1 and pusher.pushes_sent == 0
    assert counters.find_counter(TaskCounter.SHUFFLE_PUSH_REJECTED).value == 1
    assert store.get("dagK/a_1/c", 0) is None         # push never landed
    got = service.fetch_partition("dagK/a_1/c", 0, 0)  # pull backstop serves
    assert list(got.iter_pairs()) == list(run.partition(0).iter_pairs())


def test_spill_pusher_admission_storm_retries_then_abandons(landing):
    service, store, admission = landing
    run = _make_run()
    service.register("dagR/a_1/c", 0, run, use_store=False)
    faults.install("t", parse_spec("shuffle.push.admit:fail:exc=io"), seed=1)
    try:
        pusher = SpillPusher(service, retries=3, backoff_base=0.001)
        t0 = time.perf_counter()
        pusher.submit("dagR/a_1/c", 0, run)
        pusher.close()
        waited = time.perf_counter() - t0
    finally:
        faults.clear_all()
    assert pusher.pushes_rejected == 1
    assert admission.rejected == 3            # one per retry attempt
    # each rejection honored the RETRY-AFTER hint (1 ms x 3) before retrying
    assert waited >= 0.003
    assert store.get("dagR/a_1/c", 0) is None


def test_spill_pusher_inflight_cap_blocks_then_releases(landing):
    service, _, _ = landing
    run = _make_run(records=200)
    limit = run.nbytes + 1          # second submit must wait for the first
    order = []
    orig = service.push_publish

    def slow_publish(path, spill_id, r, **kw):
        order.append(("start", spill_id))
        time.sleep(0.05)
        orig(path, spill_id, r, **kw)
        order.append(("done", spill_id))

    service.push_publish = slow_publish
    pusher = SpillPusher(service, threads=2, inflight_limit_bytes=limit)
    for i in range(3):
        service.register("dagI/a_1/c", i, run, use_store=False)
        assert pusher.submit("dagI/a_1/c", i, run)
    pusher.close()
    assert pusher.pushes_sent == 3
    # the cap serialized the pushes: no spill started before the previous
    # one finished, despite the 2-thread pool
    for i in range(len(order) - 1):
        if order[i][0] == "start":
            assert order[i + 1] == ("done", order[i][1])


# ------------------------------------------------------------- remote push

@pytest.fixture()
def remote_landing(landing):
    service, store, admission = landing
    secrets = JobTokenSecretManager()
    server = ShuffleServer(secrets, service).start()
    yield server, secrets, service, store
    server.stop()


def test_remote_push_roundtrip(remote_landing):
    server, secrets, service, store = remote_landing
    producer_service = ShuffleService()      # mapper host: no store at all
    counters = TezCounters()
    run = _make_run()
    pusher = SpillPusher(producer_service, counters=counters,
                         secrets=secrets)
    assert pusher.submit("dagW/a_1/c", 0, run,
                         host="127.0.0.1", port=server.port)
    pusher.close()
    assert pusher.pushes_sent == 1
    assert counters.find_counter(TaskCounter.SHUFFLE_PUSH_BYTES).value == \
        run.nbytes
    # landed per-partition under push_key; the consumer-side service probe
    # (plain key -> bare registry -> push key) serves them transparently
    for p in range(3):
        assert store.get(push_key("dagW/a_1/c", p), 0) is not None
        got = service.fetch_partition("dagW/a_1/c", 0, p)
        assert list(got.iter_pairs()) == list(run.partition(p).iter_pairs())


def test_remote_push_bad_hmac_fatal_no_retry(remote_landing):
    server, _, _, store = remote_landing
    wrong = JobTokenSecretManager(b"not-the-secret" * 2)
    counters = TezCounters()
    pusher = SpillPusher(ShuffleService(), retries=3, counters=counters,
                         secrets=wrong, backoff_base=0.001)
    run = _make_run()
    pusher.submit("dagH/a_1/c", 0, run, host="127.0.0.1", port=server.port)
    pusher.close()
    assert pusher.pushes_rejected == 1
    assert counters.find_counter(
        TaskCounter.SHUFFLE_PUSH_REJECTED).value == 1
    # PermissionError is fatal: exactly ONE wire attempt, not three
    assert server.auth_failures == 1
    assert store.get(push_key("dagH/a_1/c", 0), 0) is None


def test_remote_push_stale_epoch_fenced_no_retry(remote_landing):
    server, secrets, _, store = remote_landing
    epoch_registry.register("app_rp", 5)
    pusher = SpillPusher(ShuffleService(), retries=3, secrets=secrets,
                         epoch=4, app_id="app_rp", backoff_base=0.001)
    run = _make_run()
    pusher.submit("dagZ/a_1/c", 0, run, host="127.0.0.1", port=server.port)
    pusher.close()
    assert pusher.pushes_rejected == 1
    assert store.get(push_key("dagZ/a_1/c", 0), 0) is None


def test_remote_push_admission_retry_then_success(remote_landing):
    """A RETRY-AFTER reply is retryable: the first attempt is rejected by
    an injected admission fault, the retry lands."""
    server, secrets, service, store = remote_landing
    faults.install("t", parse_spec("shuffle.push.admit:fail:n=1,exc=io"),
                   seed=1)
    try:
        pusher = SpillPusher(ShuffleService(), retries=3, secrets=secrets,
                             backoff_base=0.001)
        run = _make_run(partitions=1)
        pusher.submit("dagA/a_1/c", 0, run,
                      host="127.0.0.1", port=server.port)
        pusher.close()
    finally:
        faults.clear_all()
    assert pusher.pushes_sent == 1
    assert store.get(push_key("dagA/a_1/c", 0), 0) is not None
