"""Blockwise vectorized k-way merge (ops/block_merge.py) + streaming
grouped-block reader: the round-4 spill-cliff machinery.

Reference semantics under test: TezMerger.java:76 MergeQueue — equal keys
across runs emerge in run (source) order, ALL of an earlier run's equal keys
before any later run's, even when the equal-key run spans a source's block
boundary; within a run producer order is preserved exactly.
"""
import heapq
import itertools

import numpy as np
import pytest

from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.ops.block_merge import iter_merged_blocks
from tez_tpu.ops.runformat import KVBatch


def batch_of(pairs):
    return KVBatch.from_pairs([(k.encode() if isinstance(k, str) else k,
                                v.encode() if isinstance(v, str) else v)
                               for k, v in pairs])


def blocks_of(pairs, block):
    """Split a sorted pair list into KVBatch blocks of `block` rows."""
    return [batch_of(pairs[i:i + block]) for i in range(0, len(pairs), block)]


def heap_golden(sources):
    """The replaced per-record heapq semantics (source order on ties)."""
    its = [iter(sorted(src, key=lambda kv: kv[0])) for src in sources]
    return list(heapq.merge(*[iter(src) for src in sources],
                            key=lambda kv: kv[0]))


def collect(sources, block, **kw):
    out = []
    for b in iter_merged_blocks(
            [iter(blocks_of(src, block)) for src in sources],
            key_width=16, engine="host", **kw):
        out.extend((k, v) for k, v in b.iter_pairs())
    return out


def test_merge_matches_heapq_random():
    rng = np.random.default_rng(0)
    sources = []
    for s in range(5):
        n = int(rng.integers(50, 400))
        keys = sorted(f"k{rng.integers(0, 120):04d}" for _ in range(n))
        sources.append([(k, f"s{s}r{i}") for i, k in enumerate(keys)])
    got = collect([[(k.encode(), v.encode()) for k, v in s]
                   for s in sources], block=37)
    want = heap_golden([[(k.encode(), v.encode()) for k, v in s]
                        for s in sources])
    assert got == want


def test_tie_run_spanning_block_boundary_keeps_source_order():
    # source 0's run of 'kEQ' spans three 4-row blocks; heapq semantics
    # demand ALL of source 0's kEQ rows before source 1's
    s0 = [("kAA", f"a{i}") for i in range(3)] + \
         [("kEQ", f"x{i}") for i in range(10)]
    s1 = [("kEQ", f"y{i}") for i in range(4)] + [("kZZ", "z")]
    srcs = [[(k.encode(), v.encode()) for k, v in s] for s in (s0, s1)]
    got = collect(srcs, block=4)
    want = heap_golden(srcs)
    assert got == want
    eq_vals = [v for k, v in got if k == b"kEQ"]
    assert eq_vals == [f"x{i}".encode() for i in range(10)] + \
                      [f"y{i}".encode() for i in range(4)]


def test_single_source_passthrough():
    src = [(f"k{i:03d}".encode(), b"v") for i in range(100)]
    assert collect([src], block=7) == src


def test_empty_and_tiny_sources():
    assert collect([], block=4) == []
    assert collect([[], [(b"a", b"1")], []], block=4) == [(b"a", b"1")]


def test_merge_with_normalizer_ties():
    # case-insensitive comparator: 'A' and 'a' are one sort key; source
    # order must hold for the tie
    norm = bytes.upper
    s0 = [(b"A", b"s0")]
    s1 = [(b"a", b"s1"), (b"b", b"s1b")]
    got = collect([s0, s1], block=2, key_normalizer=norm)
    assert got == [(b"A", b"s0"), (b"a", b"s1"), (b"b", b"s1b")]


class _Ctx:
    def __init__(self):
        self.counters = TezCounters()

    def notify_progress(self):
        pass


class _Plan:
    def __init__(self, blocks):
        self.blocks = blocks

    def iter_batches(self):
        return iter(self.blocks)


def _grouped(blocks, normalizer=None):
    from tez_tpu.library.inputs import StreamingGroupedKVReader
    from tez_tpu.ops.serde import BytesSerde
    ctx = _Ctx()
    r = StreamingGroupedKVReader(_Plan(blocks), BytesSerde(), BytesSerde(),
                                 ctx, key_normalizer=normalizer)
    out = [(b.key(0), [(b.key(int(s)), int(e - s))
                       for s, e in zip(starts,
                                       np.append(starts, b.num_records))])
           for b, starts in r.grouped_blocks()]
    return out, ctx


def test_grouped_blocks_group_spans_many_blocks():
    # one giant group across 4 blocks + neighbors; every yielded block must
    # contain only complete groups
    pairs = [(b"a", b"1")] + [(b"big", str(i).encode()) for i in range(17)] \
        + [(b"z", b"9")]
    blocks = blocks_of(pairs, 5)
    from tez_tpu.library.inputs import StreamingGroupedKVReader
    from tez_tpu.ops.serde import BytesSerde
    ctx = _Ctx()
    r = StreamingGroupedKVReader(_Plan(blocks), BytesSerde(), BytesSerde(),
                                 ctx)
    seen = []
    for batch, starts in r.grouped_blocks():
        bounds = np.append(starts, batch.num_records)
        for s, e in zip(bounds[:-1], bounds[1:]):
            key = batch.key(int(s))
            vals = [batch.value(i) for i in range(int(s), int(e))]
            # complete-group invariant: a key never repeats across yields
            assert not seen or seen[-1][0] != key
            seen.append((key, vals))
    assert [k for k, _ in seen] == [b"a", b"big", b"z"]
    assert seen[1][1] == [str(i).encode() for i in range(17)]
    assert ctx.counters.find_counter(TaskCounter.REDUCE_INPUT_GROUPS)\
        .value == 3
    assert ctx.counters.find_counter(TaskCounter.REDUCE_INPUT_RECORDS)\
        .value == 19


def test_grouped_blocks_iter_matches_groupby():
    rng = np.random.default_rng(3)
    keys = sorted(f"k{rng.integers(0, 40):03d}".encode() for _ in range(500))
    pairs = [(k, str(i).encode()) for i, k in enumerate(keys)]
    blocks = blocks_of(pairs, 23)
    from tez_tpu.library.inputs import StreamingGroupedKVReader
    from tez_tpu.ops.serde import BytesSerde
    r = StreamingGroupedKVReader(_Plan(blocks), BytesSerde(), BytesSerde(),
                                 _Ctx())
    got = [(k, list(vs)) for k, vs in r]
    want = [(k, [v for _, v in grp])
            for k, grp in itertools.groupby(pairs, key=lambda kv: kv[0])]
    assert got == want
