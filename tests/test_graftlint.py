"""graftlint static-analysis suite tests: per-checker fixture snippets
(positive / negative / suppression), the CLI exit-code contract and
baseline flow, a self-run over the real tree asserting the committed
baseline is clean, and the runtime lock-order witness — including a
deliberately provoked A->B/B->A inversion and the static/dynamic
cross-validation contract."""
from __future__ import annotations

import json
import os
import textwrap

import pytest

import tez_tpu
from tez_tpu.analysis import all_checkers
from tez_tpu.analysis.core import (Context, load_baseline,
                                   partition_by_baseline, run_checkers)
from tez_tpu.analysis import (faultpoints, jax_hazards, knobs, lockorder,
                              metric_names)
from tez_tpu.common import lockorder as witness
from tez_tpu.tools import graftlint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(
    tez_tpu.__file__)))


def _ctx(tmp_path, files, docs=None):
    """Materialize a fixture package tree under tmp_path/tez_tpu."""
    pkg = tmp_path / "tez_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, text in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        if p.parent != pkg and not (p.parent / "__init__.py").exists():
            (p.parent / "__init__.py").write_text("")
        p.write_text(textwrap.dedent(text))
    if docs:
        d = tmp_path / "docs"
        d.mkdir(exist_ok=True)
        for name, text in docs.items():
            (d / name).write_text(textwrap.dedent(text))
    return Context(str(tmp_path))


def _codes(findings):
    return sorted(f.code for f in findings)


def _symbols(findings, code):
    return sorted(f.symbol for f in findings if f.code == code)


# ------------------------------------------------------------ lockorder

_CYCLE_MODULE = """
    import threading
    LA = threading.Lock()
    LB = threading.Lock()

    def f1():
        with LA:
            with LB:
                pass

    def f2():
        with LB:
            with LA:
                pass
"""


def test_lockorder_reports_inverse_nesting_cycle(tmp_path):
    ctx = _ctx(tmp_path, {"pair.py": _CYCLE_MODULE})
    found = lockorder.run(ctx)
    assert _codes(found) == ["lock-cycle"]
    assert found[0].symbol == "pair.LA<->pair.LB"


def test_lockorder_consistent_order_is_clean(tmp_path):
    ctx = _ctx(tmp_path, {"pair.py": """
        import threading
        LA = threading.Lock()
        LB = threading.Lock()

        def f1():
            with LA:
                with LB:
                    pass

        def f2():
            with LA:
                with LB:
                    pass
    """})
    assert lockorder.run(ctx) == []


def test_lockorder_cycle_through_call_edges(tmp_path):
    # no direct inverse nesting anywhere: the cycle only exists through
    # the call edges (hold own lock, call the other side)
    ctx = _ctx(tmp_path, {
        "m1.py": """
            import threading

            class Holder:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold_and_poke(self, other):
                    with self._lock:
                        other.do_work()
        """,
        "m2.py": """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()

                def do_work(self):
                    with self._lock:
                        return 1

                def rev(self, holder):
                    with self._lock:
                        holder.hold_and_poke(None)
        """,
    })
    found = lockorder.run(ctx)
    assert _codes(found) == ["lock-cycle"]
    assert found[0].symbol == "m1.Holder._lock<->m2.Worker._lock"


def test_lockorder_resolves_stored_callbacks(tmp_path):
    # the pipeline invokes a constructor-injected callback while holding
    # its own lock; the callback's acquisitions must land in the graph
    ctx = _ctx(tmp_path, {
        "pipe.py": """
            import threading

            class Pipe:
                def __init__(self, on_complete=None):
                    self._lock = threading.Lock()
                    self._on_complete = on_complete

                def complete(self, result):
                    with self._lock:
                        self._on_complete(result)
        """,
        "owner.py": """
            import threading
            from tez_tpu.pipe import Pipe

            class Owner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pipe = Pipe(on_complete=self._fold)

                def _fold(self, result):
                    with self._lock:
                        return result
        """,
    })
    edges, _locks = lockorder.build_graph(ctx)
    assert ("pipe.Pipe._lock", "owner.Owner._lock") in edges


def test_lockorder_condition_aliases_to_wrapped_lock(tmp_path):
    # Condition(self._lock) is the SAME lock: nesting them is not an edge
    ctx = _ctx(tmp_path, {"cv.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def use(self):
                with self._lock:
                    with self._cv:
                        pass
    """})
    edges, locks = lockorder.build_graph(ctx)
    assert edges == {}
    assert locks == {"cv.Box._lock"}


def test_lock_graph_exports_expected_real_edges():
    """The static graph over the real tree must contain the nesting the
    runtime witness demonstrably exercises (subset contract anchors)."""
    edges, locks = lockorder.build_graph(Context(REPO_ROOT))
    assert "shuffle.scheduler.FetchScheduler.lock" in locks
    assert ("store.buffer_store.ShuffleBufferStore._lock",
            "common.metrics.MetricsRegistry._lock") in edges
    assert ("shuffle.scheduler.FetchScheduler.lock",
            "common.metrics.MetricsRegistry._lock") in edges


# ------------------------------------------------------------ knobs

_KNOB_CONFIG = """
    def _key(name, default=None, scope=None, doc=""):
        return name

    GOOD = _key("tez.good.knob", 1)
    DEAD = _key("tez.dead.knob", 1)
"""


def test_knob_drift_codes(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/config.py": _KNOB_CONFIG,
        "user.py": """
            def read(conf):
                return (conf.get("tez.good.knob"),
                        conf.get("tez.rogue.knob"))
        """,
    }, docs={"configuration.md": "| `tez.good.knob` |\n| `tez.dead.knob` |\n"})
    found = knobs.run(ctx)
    assert _codes(found) == ["knob-unread", "knob-unregistered"]
    assert _symbols(found, "knob-unregistered") == ["tez.rogue.knob"]
    assert _symbols(found, "knob-unread") == ["tez.dead.knob"]


def test_knob_undocumented_when_docs_stale(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/config.py": _KNOB_CONFIG,
        "user.py": "def read(c):\n    return c.get('tez.good.knob')\n",
    }, docs={"configuration.md": "| `tez.good.knob` |\n"})
    found = knobs.run(ctx)
    # tez.dead.knob is both unread and missing from the generated doc
    assert _codes(found) == ["knob-undocumented", "knob-unread"]
    assert _symbols(found, "knob-undocumented") == ["tez.dead.knob"]


def test_inline_suppression_silences_finding(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/config.py": _KNOB_CONFIG,
        "user.py": """
            def read(conf):
                conf.get("tez.good.knob")
                return conf.get("tez.rogue.knob")  # graftlint: disable=knob-unregistered
        """,
    }, docs={"configuration.md": "| `tez.good.knob` |\n| `tez.dead.knob` |\n"})
    found = run_checkers(ctx, [knobs.CHECKER])
    assert _codes(found) == ["knob-unread"]


def test_suppression_on_line_above_and_all(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/config.py": _KNOB_CONFIG,
        "user.py": """
            def read(conf):
                conf.get("tez.good.knob")
                # graftlint: disable=all
                return conf.get("tez.rogue.knob")
        """,
    }, docs={"configuration.md": "| `tez.good.knob` |\n| `tez.dead.knob` |\n"})
    found = run_checkers(ctx, [knobs.CHECKER])
    assert _codes(found) == ["knob-unread"]


# ------------------------------------------------------------ faultpoints

def test_fault_point_drift_codes(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/faults.py": """
            KNOWN_POINTS = {
                "used.point": "seam a",
                "dead.point": "seam b",
                "undoc.point": "seam c",
            }

            def fire(point, detail=""):
                pass
        """,
        "seams.py": """
            from tez_tpu.common import faults

            def go():
                faults.fire("used.point")
                faults.fire("rogue.point")
                faults.fire("undoc.point")
        """,
    }, docs={"fault_injection.md": """
        | point | seam |
        |---|---|
        | `used.point` | a |
        | `dead.point` | b |
        | `stale.point` | gone |
    """})
    found = faultpoints.run(ctx)
    assert _codes(found) == ["fault-doc-stale", "fault-undocumented",
                             "fault-unfired", "fault-unregistered"]
    assert _symbols(found, "fault-unregistered") == ["rogue.point"]
    assert _symbols(found, "fault-unfired") == ["dead.point"]
    assert _symbols(found, "fault-undocumented") == ["undoc.point"]
    assert _symbols(found, "fault-doc-stale") == ["stale.point"]


# ------------------------------------------------------------ metric_names

def test_metric_drift_codes(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/metrics.py": """
            WELL_KNOWN_HISTOGRAMS = ("a.hist", "b.unused")
        """,
        "instr.py": """
            from tez_tpu.common import metrics

            def go(m):
                metrics.observe("a.hist", 1.0)
                metrics.observe("rogue.hist", 2.0)
                m.set_gauge("queue.depth", 3)
        """,
    }, docs={"observability.md": "`a.hist` and `b.unused` histograms\n"})
    found = metric_names.run(ctx)
    assert _codes(found) == ["gauge-undocumented", "hist-unregistered",
                             "hist-unused"]
    assert _symbols(found, "hist-unregistered") == ["rogue.hist"]
    assert _symbols(found, "hist-unused") == ["b.unused"]
    assert _symbols(found, "gauge-undocumented") == ["queue.depth"]


def test_counter_diff_section_cross_checked(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/metrics.py": """
            WELL_KNOWN_HISTOGRAMS = ("a.hist",)
        """,
        "tools/counter_diff.py": """
            DEVICE_STAGE_HISTS = ("a.hist", "ghost.hist")
        """,
        "instr.py": """
            from tez_tpu.common import metrics

            def go():
                metrics.observe("a.hist", 1.0)
        """,
    }, docs={"observability.md": "`a.hist`\n"})
    found = metric_names.run(ctx)
    assert _codes(found) == ["diff-stale-hist"]
    assert found[0].symbol == "ghost.hist"


# ------------------------------------------------------------ jax_hazards

def test_jax_hazard_codes(tmp_path):
    ctx = _ctx(tmp_path, {
        "hazards.py": """
            import threading
            import jax

            def bad_loop(xs):
                out = []
                for x in xs:
                    f = jax.jit(lambda v: v)
                    out.append(f(x))
                return out

            def bad_immediate(x):
                return jax.jit(lambda v: v)(x)

            def bad_thread(fn):
                threading.Thread(target=fn).start()

            def good_thread(fn):
                threading.Thread(target=fn, daemon=True).start()

            def bad_acquire(my_lock):
                my_lock.acquire()
        """,
        "ops/device.py": """
            def sync(x):
                return x.item()
        """,
    })
    found = jax_hazards.run(ctx)
    assert _codes(found) == ["bare-acquire", "host-sync", "jit-immediate",
                             "jit-in-loop", "thread-nondaemon"]


def test_jax_builder_patterns_allowed(tmp_path):
    ctx = _ctx(tmp_path, {
        "ok.py": """
            import functools
            import jax

            TOP = jax.jit(lambda v: v)

            @functools.lru_cache(maxsize=1)
            def build():
                return jax.jit(lambda v: v + 1)

            def item_off_hot_path(x):
                return x.item()
        """,
    })
    assert jax_hazards.run(ctx) == []


# ------------------------------------------------------------ CLI / baseline

def test_cli_exit_codes_and_baseline_flow(tmp_path):
    _ctx(tmp_path, {
        "common/config.py": _KNOB_CONFIG,
        "user.py": """
            def read(conf):
                conf.get("tez.good.knob")
                conf.get("tez.dead.knob")
                return conf.get("tez.rogue.knob")
        """,
    }, docs={"configuration.md": "| `tez.good.knob` |\n| `tez.dead.knob` |\n"})
    bl = str(tmp_path / "baseline.json")
    argv = ["--root", str(tmp_path), "--baseline", bl]
    assert graftlint.main(argv) == 1                 # new finding
    assert graftlint.main(argv + ["--update-baseline"]) == 0
    data = json.load(open(bl))
    assert data["findings"] == [
        "knobs:knob-unregistered:tez_tpu/user.py:tez.rogue.knob"]
    assert graftlint.main(argv) == 0                 # baselined now
    assert graftlint.main(["--checker", "no-such-checker"]) == 2


def test_cli_detects_seeded_lock_cycle(tmp_path):
    _ctx(tmp_path, {"pair.py": _CYCLE_MODULE})
    assert graftlint.main(["--root", str(tmp_path),
                           "--baseline", str(tmp_path / "bl.json")]) == 1


def test_self_run_matches_committed_baseline():
    """`make lint` contract: the committed tree is clean against the
    committed baseline (no new findings, no stale entries)."""
    findings = run_checkers(Context(REPO_ROOT), all_checkers())
    new, _known, stale = partition_by_baseline(
        findings, load_baseline(graftlint._DEFAULT_BASELINE))
    assert [f.render() for f in new] == []
    assert stale == []


def test_finding_identity_is_line_stable(tmp_path):
    ctx = _ctx(tmp_path, {
        "common/config.py": _KNOB_CONFIG,
        "user.py": "def f(c):\n    return c.get('tez.rogue.knob')\n",
    })
    ctx2 = _ctx(tmp_path / "shifted", {
        "common/config.py": _KNOB_CONFIG,
        "user.py": "# pushed\n# down\ndef f(c):\n"
                   "    return c.get('tez.rogue.knob')\n",
    })
    (i1,) = [f.identity for f in knobs.run(ctx)
             if f.code == "knob-unregistered"]
    (i2,) = [f.identity for f in knobs.run(ctx2)
             if f.code == "knob-unregistered"]
    assert i1.split(":", 1)[1].endswith("user.py:tez.rogue.knob")
    assert i1 == i2


# ------------------------------------------------------------ runtime witness

def test_witness_detects_provoked_inversion():
    """Deliberate A->B then B->A on a PRIVATE witness instance (the
    process-global record the session finalizer asserts on stays
    pristine)."""
    w = witness.LockWitness()
    a = w.wrap(witness._ORIG_LOCK(), "fixture.A")
    b = w.wrap(witness._ORIG_LOCK(), "fixture.B")
    witness.arm("test-inversion")       # refcounted; recording gate on
    try:
        with a:
            with b:
                pass
        assert w.violations() == []
        assert w.edges() == {("fixture.A", "fixture.B")}
        with b:
            with a:
                pass
    finally:
        witness.disarm("test-inversion")
    (v,) = w.violations()
    assert (v.held, v.acquired) == ("fixture.B", "fixture.A")
    assert "test_graftlint.py" in v.where
    assert "prior observations order fixture.A before fixture.B" \
        in v.render()


def test_witness_reentrant_rlock_is_not_an_edge():
    w = witness.LockWitness()
    r = w.wrap(witness._ORIG_RLOCK(), "fixture.R")
    witness.arm("test-reentrant")
    try:
        with r:
            with r:
                pass
    finally:
        witness.disarm("test-reentrant")
    assert w.edges() == set()
    assert w.violations() == []


def test_witness_names_and_validates_package_locks(tmp_path):
    """Locks created inside tez_tpu while armed get static-analyzer
    names, real nesting is recorded, and the observed edges validate
    against the static graph (the acceptance-criteria subset check)."""
    from tez_tpu.ops.runformat import KVBatch, Run
    from tez_tpu.store.buffer_store import ShuffleBufferStore
    import numpy as np

    witness.arm("test-naming")
    try:
        s = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 20,
                               disk_dir=str(tmp_path / "store"))
        assert getattr(s._lock, "_witness_name", None) == \
            "store.buffer_store.ShuffleBufferStore._lock"
        pairs = sorted((b"k%04d" % i, b"v%04d" % i) for i in range(32))
        run = Run(KVBatch.from_pairs(pairs),
                  np.array([0, 16, 32], dtype=np.int64))
        s.publish("dag1/a0/cons", -1, run)
        s.fetch_partition("dag1/a0/cons", -1, 0)
        s.close()
    finally:
        witness.disarm("test-naming")
    from tez_tpu.common import metrics
    if getattr(metrics.registry()._lock, "_witness_name", None) is not None:
        # the registry singleton was born inside an armed window, so the
        # publish path must have recorded the store->metrics nesting;
        # when it predates arming it is invisible BY DESIGN (the subset
        # property) and there is no edge to assert on
        edge = ("store.buffer_store.ShuffleBufferStore._lock",
                "common.metrics.MetricsRegistry._lock")
        assert edge in witness.witness().edges()
    static_edges, static_locks = lockorder.build_graph(Context(REPO_ROOT))
    assert witness.check(set(static_edges), static_locks) == []


def test_witness_scope_refcounting():
    """disarm() of one scope must not unpatch while another (e.g. the
    session fixture's) is still armed."""
    was_armed = witness.armed()
    witness.arm("test-scope-a")
    witness.arm("test-scope-b")
    witness.disarm("test-scope-a")
    assert witness.armed()
    witness.disarm("test-scope-b")
    assert witness.armed() == was_armed


def test_witness_install_from_conf():
    from tez_tpu.common import config as C
    conf = C.TezConfiguration({C.DEBUG_LOCKORDER.name: True})
    assert witness.install_from_conf(conf, "test-conf-scope")
    assert witness.armed()
    witness.disarm("test-conf-scope")
    off = C.TezConfiguration({})
    assert not witness.install_from_conf(off, "test-conf-scope2")


def test_witness_condition_wait_keeps_stack_exact():
    """Condition.wait releases and reacquires through the wrapper's
    _release_save/_acquire_restore; the held stack must stay balanced
    (an unbalanced stack would fabricate phantom edges afterwards)."""
    import threading as th
    w = witness.LockWitness()
    inner = w.wrap(witness._ORIG_LOCK(), "fixture.CVL")
    cv = witness._ORIG_CONDITION(inner)
    other = w.wrap(witness._ORIG_LOCK(), "fixture.OTHER")
    witness.arm("test-cv")
    try:
        def waker():
            with cv:
                cv.notify_all()
        t = th.Thread(target=waker, daemon=True)
        with cv:
            t.start()
            cv.wait(timeout=2.0)
        with other:
            pass                      # held stack must be empty again
    finally:
        witness.disarm("test-cv")
    assert all(e[0] != "fixture.CVL" for e in w.edges()), w.edges()
    assert w.violations() == []
