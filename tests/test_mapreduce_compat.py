"""MR-compat tests: user map/reduce functions on the DAG engine, plus the
3-stage MRR chain (benchmark workload 4 shape)."""
import collections
import os

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.io.mapreduce import simple_mr_dag


def wc_map(offset, line):
    for word in line.split():
        yield word, b"\x00" * 7 + b"\x01"  # not used; see long variant below


def wc_map_long(offset, line):
    from tez_tpu.ops.serde import VarLongSerde
    one = VarLongSerde().to_bytes(1)
    for word in line.split():
        yield word, one


def wc_reduce(word, values):
    from tez_tpu.ops.serde import VarLongSerde
    s = VarLongSerde()
    yield word, str(sum(s.from_bytes(v) for v in values)).encode()


def test_simple_mr_wordcount(tmp_path):
    corpus = tmp_path / "in.txt"
    corpus.write_text("x y z x y x\n" * 100)
    out = str(tmp_path / "out")
    dag = simple_mr_dag("mr-wc", [str(corpus)], out,
                        map_fn="tests.test_mapreduce_compat:wc_map_long",
                        reduce_fn="tests.test_mapreduce_compat:wc_reduce",
                        num_mappers=2, num_reducers=2,
                        key_serde="text", value_serde="text")
    with TezClient.create("mr", {"tez.staging-dir":
                                 str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                k, v = line.split("\t")
                got[k] = int(v)
    assert got == {"x": 300, "y": 200, "z": 100}


def fixed_map(key, value):
    """key: 8B id, value: 8B big-endian count — emit (bucket, count)."""
    import struct
    (count,) = struct.unpack(">q", value)
    yield key[:2], str(count).encode()


def fixed_reduce(bucket, values):
    yield bucket, str(sum(int(v) for v in values)).encode()


def test_fixed_width_binary_format_e2e(tmp_path):
    """Second stock InputFormat (VERDICT r1 item 9): fixed-width binary KV
    records through simple_mr_dag, record-aligned splits, exact sums."""
    import struct
    data = tmp_path / "in.bin"
    golden = collections.Counter()
    with open(data, "wb") as fh:
        for i in range(5000):
            key = f"b{i % 7}_{i:04d}".encode()[:8].ljust(8, b"\x00")
            count = i % 13
            golden[key[:2]] += count
            fh.write(key + struct.pack(">q", count))
    out = str(tmp_path / "out")
    dag = simple_mr_dag(
        "mr-fixed", [str(data)], out,
        map_fn="tests.test_mapreduce_compat:fixed_map",
        reduce_fn="tests.test_mapreduce_compat:fixed_reduce",
        num_mappers=3, num_reducers=2,
        key_serde="text", value_serde="text",
        input_format="fixed",
        format_params={"key_bytes": 8, "value_bytes": 8})
    with TezClient.create("mrf", {"tez.staging-dir":
                                  str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                k, v = line.rstrip(b"\n").split(b"\t")
                got[k] = int(v)
    assert got == {k: v for k, v in golden.items()}


def test_fixed_width_splits_are_record_aligned(tmp_path):
    from tez_tpu.io.formats import FixedWidthKVFormat
    data = tmp_path / "a.bin"
    rec = 12
    data.write_bytes(b"x" * (rec * 1000 + 5))   # trailing partial record
    fmt = FixedWidthKVFormat({"key_bytes": 4, "value_bytes": 8})
    splits = fmt.compute_splits([str(data)], 4, min_split_bytes=64)
    assert splits, "no splits"
    covered = 0
    for s in splits:
        assert s.start % rec == 0 and s.length % rec == 0, s
        covered += s.length
    assert covered == rec * 1000    # partial tail record dropped
    # splits are disjoint and ordered
    ends = 0
    for s in sorted(splits, key=lambda s: s.start):
        assert s.start == ends
        ends = s.start + s.length


def test_multi_mr_input_one_reader_per_split(tmp_path):
    """MultiMRInput analog: get_key_value_readers() exposes split
    boundaries (reference: MultiMRInput.java)."""
    from tez_tpu.io.formats import MultiMRInput
    from tez_tpu.io.text import FileSplit
    from tez_tpu.common.counters import TezCounters

    f1 = tmp_path / "a.txt"
    f1.write_text("a1\na2\n")
    f2 = tmp_path / "b.txt"
    f2.write_text("b1\n")

    class _Payload:
        def load(self):
            return {"format": "text",
                    "static_splits": [
                        FileSplit(str(f1), 0, f1.stat().st_size),
                        FileSplit(str(f2), 0, f2.stat().st_size)]}

    class _Ctx:
        user_payload = _Payload()
        counters = TezCounters()

        def notify_progress(self):
            pass

    inp = MultiMRInput.__new__(MultiMRInput)
    inp.context = _Ctx()
    inp.initialize()
    readers = inp.get_key_value_readers()
    assert len(readers) == 2
    assert [line for _, line in readers[0]] == [b"a1", b"a2"]
    assert [line for _, line in readers[1]] == [b"b1"]
    # the fused reader chains them in split order
    inp2 = MultiMRInput.__new__(MultiMRInput)
    inp2.context = _Ctx()
    inp2.initialize()
    assert [line for _, line in inp2.get_reader()] == [b"a1", b"a2", b"b1"]


def test_split_wave_grouping_keys(tmp_path):
    """tez.grouping.split-waves/min-size/max-size drive group count when
    vertex parallelism is unbound (TezSplitGrouper semantics)."""
    from tez_tpu.io.formats import MRSplitGenerator
    from tez_tpu.common.payload import UserPayload

    data = tmp_path / "in.txt"
    data.write_bytes(b"x" * (1 << 20))   # 1 MiB

    class Ctx:
        num_tasks = -1
        def __init__(self, payload):
            self.user_payload = UserPayload.of(payload)
        def get_total_available_resource(self):
            return 4

    class Gen(MRSplitGenerator):
        def __init__(self, payload):
            self.context = Ctx(payload)

    def group_count(payload):
        events = Gen(payload).initialize()
        return events[0].num_tasks

    base = {"paths": [str(data)], "min_split_bytes": 1024}
    # min-size dominates: 1 MiB total / 50 MiB min => 1 group
    assert group_count(dict(base)) == 1
    # tiny min-size: waves x slots = 6 groups (1.7 * 4 -> 6)
    assert group_count({**base, "tez.grouping.min-size": 1024}) == 6
    # waves honored
    assert group_count({**base, "tez.grouping.min-size": 1024,
                        "tez.grouping.split-waves": 1.0}) == 4
    # max-size forces MORE groups than waves would pick
    assert group_count({**base, "tez.grouping.min-size": 1,
                        "tez.grouping.max-size": 64 * 1024}) == 16


def test_counter_limits_configurable():
    from tez_tpu.common.counters import Limits
    before = (Limits.MAX_COUNTERS, Limits.MAX_GROUPS)
    try:
        Limits.configure({"tez.counters.max": 7, "tez.counters.max.groups": 3})
        assert (Limits.MAX_COUNTERS, Limits.MAX_GROUPS) == (7, 3)
    finally:
        Limits.MAX_COUNTERS, Limits.MAX_GROUPS = before


def test_svm_descriptor_from_conf():
    from tez_tpu.library.vertex_managers import ShuffleVertexManager
    d = ShuffleVertexManager.create_descriptor(
        {"tez.shuffle-vertex-manager.min-src-fraction": 0.5,
         "tez.shuffle-vertex-manager.enable.auto-parallel": True},
        min_task_parallelism=2)
    p = d.payload.load()
    assert p["min_fraction"] == 0.5 and p["auto_parallel"] is True
    assert p["max_fraction"] == 0.75 and p["min_task_parallelism"] == 2
    assert "ShuffleVertexManager" in d.class_name


def count_pass_reduce(word, values):
    from tez_tpu.ops.serde import VarLongSerde
    s = VarLongSerde()
    # (word, ones...) -> (word, total) still VarLong encoded for stage 2
    yield word, s.to_bytes(sum(s.from_bytes(v) for v in values))


def fold_first_letter_reduce(word, values):
    from tez_tpu.ops.serde import VarLongSerde
    s = VarLongSerde()
    yield word[:1], s.to_bytes(sum(s.from_bytes(v) for v in values))


def total_reduce(letter, values):
    from tez_tpu.ops.serde import VarLongSerde
    s = VarLongSerde()
    yield letter, str(sum(s.from_bytes(v) for v in values)).encode()


def test_mr_chain_dag_three_stages(tmp_path):
    """YARNRunner-style chained-job translation: map -> reduce1 (word
    totals) -> reduce2 (fold to first letter) -> reduce3 (letter totals),
    one DAG, byte-verified (TestOrderedWordCount / MRR shape)."""
    from tez_tpu.io.mapreduce import mr_chain_dag
    corpus = tmp_path / "in.txt"
    corpus.write_text("apple ant bee bear apple cat\n" * 50)
    out = str(tmp_path / "out")
    dag = mr_chain_dag(
        "mrr", [str(corpus)], out,
        map_fn="tests.test_mapreduce_compat:wc_map_long",
        reduce_fns=[
            "tests.test_mapreduce_compat:count_pass_reduce",
            "tests.test_mapreduce_compat:fold_first_letter_reduce",
            "tests.test_mapreduce_compat:total_reduce"],
        num_mappers=2, num_reducers=[2, 2, 1],
        key_serde="text", value_serde="text")
    assert len(dag.vertices) == 4
    with TezClient.create("mrr", {"tez.staging-dir":
                                  str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=90)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                k, v = line.split("\t")
                got[k] = int(v.strip())
    # a: apple*2 + ant = 150, b: bee + bear = 100, c: cat = 50
    assert got == {"a": 150, "b": 100, "c": 50}


def ident_map(offset, line):
    for w in line.split():
        yield w, b"1"


def test_mr_job_conf_translation_e2e(tmp_path):
    """VERDICT r3 item 8: a conf-DEFINED job (Hadoop mapreduce.* keys,
    Writable class names) translates through mr_job_to_dag and runs E2E —
    the YARNRunner seam."""
    from tez_tpu.io.mapreduce import mr_job_to_dag
    corpus = tmp_path / "in.txt"
    corpus.write_text("x y z x y x\n" * 100)
    out = str(tmp_path / "out")
    conf = {
        "mapreduce.job.name": "conf-wc",
        "mapreduce.job.map.class":
            "tests.test_mapreduce_compat:wc_map_long",
        "mapreduce.job.reduce.class":
            "tests.test_mapreduce_compat:wc_reduce",
        "mapreduce.job.maps": 2,
        "mapreduce.job.reduces": 2,
        "mapreduce.input.fileinputformat.inputdir": str(corpus),
        "mapreduce.output.fileoutputformat.outputdir": out,
        "mapreduce.job.inputformat.class":
            "org.apache.hadoop.mapreduce.lib.input.TextInputFormat",
        "mapreduce.map.output.key.class": "org.apache.hadoop.io.Text",
        "mapreduce.map.output.value.class":
            "org.apache.hadoop.io.BytesWritable",
        "mapreduce.job.output.key.class": "org.apache.hadoop.io.Text",
        "mapreduce.job.output.value.class": "org.apache.hadoop.io.Text",
    }
    dag = mr_job_to_dag(conf)
    assert dag.name == "conf-wc"
    with TezClient.create("mrconf", {"tez.staging-dir":
                                     str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                k, v = line.split("\t")
                got[k] = int(v)
    assert got == {"x": 300, "y": 200, "z": 100}


def test_mr_job_conf_legacy_aliases_and_map_only(tmp_path):
    """mapred.* legacy keys work (new keys win on conflict); reduces=0
    builds the map-only DAG committing straight to the sink."""
    from tez_tpu.io.mapreduce import mr_job_to_dag
    corpus = tmp_path / "in.txt"
    corpus.write_text("a b\nc d\n")
    out = str(tmp_path / "out")
    conf = {
        "mapred.job.name": "legacy-ignored",
        "mapreduce.job.name": "maponly",       # new key wins
        "mapred.mapper.class": "tests.test_mapreduce_compat:ident_map",
        "mapred.reduce.tasks": 0,
        "mapred.input.dir": str(corpus),
        "mapred.output.dir": out,
        "mapred.output.key.class": "org.apache.hadoop.io.Text",
        "mapred.output.value.class": "org.apache.hadoop.io.Text",
    }
    dag = mr_job_to_dag(conf)
    assert dag.name == "maponly"
    assert len(dag.vertices) == 1            # truly map-only
    with TezClient.create("mrlegacy", {"tez.staging-dir":
                                       str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    words = []
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                words.append(line.split("\t")[0])
    assert sorted(words) == ["a", "b", "c", "d"]


def test_mr_job_conf_validation():
    from tez_tpu.io.mapreduce import mr_job_to_dag
    import pytest as _pytest
    with _pytest.raises(ValueError, match="no mapper"):
        mr_job_to_dag({"mapreduce.job.reduces": 1})
    with _pytest.raises(ValueError, match="input dir"):
        mr_job_to_dag({"mapreduce.job.map.class": "m:f"})
    with _pytest.raises(ValueError, match="no reducer"):
        mr_job_to_dag({"mapreduce.job.map.class": "m:f",
                       "mapreduce.input.fileinputformat.inputdir": "/x",
                       "mapreduce.output.fileoutputformat.outputdir": "/y",
                       "mapreduce.job.reduces": 2})
