"""MR-compat tests: user map/reduce functions on the DAG engine, plus the
3-stage MRR chain (benchmark workload 4 shape)."""
import collections
import os

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.io.mapreduce import simple_mr_dag


def wc_map(offset, line):
    for word in line.split():
        yield word, b"\x00" * 7 + b"\x01"  # not used; see long variant below


def wc_map_long(offset, line):
    from tez_tpu.ops.serde import VarLongSerde
    one = VarLongSerde().to_bytes(1)
    for word in line.split():
        yield word, one


def wc_reduce(word, values):
    from tez_tpu.ops.serde import VarLongSerde
    s = VarLongSerde()
    yield word, str(sum(s.from_bytes(v) for v in values)).encode()


def test_simple_mr_wordcount(tmp_path):
    corpus = tmp_path / "in.txt"
    corpus.write_text("x y z x y x\n" * 100)
    out = str(tmp_path / "out")
    dag = simple_mr_dag("mr-wc", [str(corpus)], out,
                        map_fn="tests.test_mapreduce_compat:wc_map_long",
                        reduce_fn="tests.test_mapreduce_compat:wc_reduce",
                        num_mappers=2, num_reducers=2,
                        key_serde="text", value_serde="text")
    with TezClient.create("mr", {"tez.staging-dir":
                                 str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for f in os.listdir(out):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                k, v = line.split("\t")
                got[k] = int(v)
    assert got == {"x": 300, "y": 200, "z": 100}
