"""Bounded-memory consumer merge (MergeManager.java:83 analog) tests:
admission + stall, mem->disk trigger, disk cascade, streaming final merge,
poisoning on post-merge slot reset, and an E2E run with budget << data."""
import os

import numpy as np
import pytest

from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.library.merge_manager import ShuffleMergeManager
from tez_tpu.ops.runformat import KVBatch


def sorted_batch(seed: int, n: int, vlen: int = 32) -> KVBatch:
    rng = np.random.default_rng(seed)
    keys = sorted(f"k{rng.integers(0, 50_000):08d}".encode()
                  for _ in range(n))
    vals = [rng.integers(0, 256, vlen, dtype=np.uint8).tobytes()
            for _ in range(n)]
    return KVBatch.from_pairs(list(zip(keys, vals)))


def reference_merge(batches):
    """Golden: global stable sort by key over (slot-ordered) concatenation."""
    pairs = []
    for b in batches:
        pairs.extend(b.iter_pairs())
    pairs.sort(key=lambda kv: kv[0])
    return pairs


def drain(mm):
    result = mm.finish()
    if result.is_streaming:
        return [(k, v) for _, k, v in result.stream.iter_records()]
    return list(result.batch.iter_pairs())


def test_unbounded_budget_passthrough(tmp_path):
    counters = TezCounters()
    mm = ShuffleMergeManager(counters, 0, str(tmp_path), engine="host")
    batches = [sorted_batch(i, 500) for i in range(4)]
    for slot, b in enumerate(batches):
        mm.commit(slot, b)
    assert drain(mm) == reference_merge(batches)
    assert mm._mem_to_disk == 0


def test_budget_forces_disk_merges_and_bounds_memory(tmp_path):
    counters = TezCounters()
    batches = [sorted_batch(i, 2000) for i in range(8)]
    total = sum(b.nbytes for b in batches)
    budget = total // 5
    mm = ShuffleMergeManager(counters, budget, str(tmp_path), engine="host",
                             merge_threshold=0.5, max_single_fraction=2.0,
                             block_records=256)
    for slot, b in enumerate(batches):
        mm.commit(slot, b)
    got = drain(mm)
    assert got == reference_merge(batches)
    assert mm.peak_mem_bytes <= budget
    assert mm._mem_to_disk >= 1
    assert counters.find_counter(TaskCounter.NUM_MEM_TO_DISK_MERGES)\
        .value >= 1
    assert counters.find_counter(TaskCounter.SHUFFLE_BYTES_TO_MEM).value > 0


def test_oversized_batch_goes_straight_to_disk(tmp_path):
    counters = TezCounters()
    big = sorted_batch(1, 4000)
    mm = ShuffleMergeManager(counters, big.nbytes * 2, str(tmp_path),
                             engine="host", max_single_fraction=0.25,
                             block_records=512)
    mm.commit(0, big)
    assert counters.find_counter(TaskCounter.SHUFFLE_BYTES_TO_DISK)\
        .value == big.nbytes
    assert drain(mm) == reference_merge([big])


def test_disk_to_disk_cascade(tmp_path):
    counters = TezCounters()
    batches = [sorted_batch(i, 800) for i in range(6)]
    mm = ShuffleMergeManager(counters, 10 * 1024 * 1024, str(tmp_path),
                             engine="host", merge_factor=2,
                             max_single_fraction=0.0001,  # everything DISK
                             block_records=128)
    for slot, b in enumerate(batches):
        mm.commit(slot, b)
    # CV wait, not a sleep-poll: quiesce() returns once the cascade has
    # drained (6 runs -> 1 is several disk-to-disk folds), however long
    # the background merger is starved under full-suite load
    assert mm.quiesce(timeout=120), "background merger never quiesced"
    assert mm._disk_to_disk >= 1
    assert counters.find_counter(TaskCounter.NUM_DISK_TO_DISK_MERGES)\
        .value >= 1
    assert drain(mm) == reference_merge(batches)


def test_slot_reset_in_memory_discards(tmp_path):
    counters = TezCounters()
    keep = sorted_batch(0, 300)
    drop = sorted_batch(1, 300)
    mm = ShuffleMergeManager(counters, 0, str(tmp_path), engine="host")
    mm.commit(0, keep)
    mm.commit(1, drop)
    dropped = mm.on_slot_reset(1)
    assert dropped and dropped[0] is drop
    assert drain(mm) == reference_merge([keep])


def test_slot_reset_after_disk_merge_poisons(tmp_path):
    counters = TezCounters()
    big = sorted_batch(0, 2000)
    mm = ShuffleMergeManager(counters, big.nbytes * 2, str(tmp_path),
                             engine="host", max_single_fraction=0.1)
    mm.commit(3, big)          # oversized -> disk
    mm.on_slot_reset(3)        # data already on disk: unrecoverable
    with pytest.raises(RuntimeError, match="merge state lost"):
        mm.commit(0, sorted_batch(1, 10))
    mm.cleanup()


def test_streaming_plan_is_reiterable(tmp_path):
    counters = TezCounters()
    batches = [sorted_batch(i, 1000) for i in range(4)]
    mm = ShuffleMergeManager(counters, 10 * 1024 * 1024, str(tmp_path),
                             engine="host", max_single_fraction=0.0001,
                             block_records=128)
    for slot, b in enumerate(batches):
        mm.commit(slot, b)
    result = mm.finish()
    assert result.is_streaming
    first = [(k, v) for _, k, v in result.stream.iter_records()]
    second = [(k, v) for _, k, v in result.stream.iter_records()]
    assert first == second == reference_merge(batches)


def test_e2e_wordcount_with_tiny_merge_budget(tmp_path):
    """Framework-level: OrderedWordCount with a consumer merge budget far
    below the shuffled data size must spill, stream, and still produce
    output identical to the unbounded run."""
    import collections
    import random
    from tez_tpu.examples import ordered_wordcount

    rng = random.Random(11)
    words = [f"w{rng.randrange(300):05d}" for _ in range(250_000)]
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(" ".join(words))
    golden = collections.Counter(words)

    outs = {}
    for label, budget_mb in (("unbounded", 0), ("tiny", 1)):
        out_dir = str(tmp_path / f"out_{label}")
        conf = {"tez.staging-dir": str(tmp_path / f"stg_{label}"),
                "tez.runtime.io.sort.mb": 1}
        if budget_mb:
            conf["tez.runtime.shuffle.merge.budget.mb"] = budget_mb
            conf["tez.runtime.shuffle.merge.percent"] = 0.4
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf=conf, tokenizer_parallelism=3, summation_parallelism=2,
            sorter_parallelism=1)
        assert state == "SUCCEEDED"
        lines = []
        for name in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, name)) as fh:
                lines.extend(fh.read().splitlines())
        outs[label] = lines
        counts = dict(line.rsplit(None, 1) for line in lines if line.strip())
        assert {k: int(v) for k, v in counts.items()} == dict(golden)
    assert outs["unbounded"] == outs["tiny"]


def test_commit_below_threshold_does_not_deadlock(tmp_path):
    """A batch that doesn't fit the remaining budget while committed memory
    sits BELOW the merge threshold must not stall forever: a stalled
    fetcher forces the merger to free memory early."""
    counters = TezCounters()
    b0 = sorted_batch(0, 900)
    budget = int(b0.nbytes * 1.25)
    mm = ShuffleMergeManager(counters, budget, str(tmp_path), engine="host",
                             merge_threshold=0.9, max_single_fraction=0.5,
                             block_records=128)
    mm.commit(0, b0)                       # ~80% of budget: below threshold
    big2 = sorted_batch(1, 500)            # doesn't fit; < max_single
    import threading
    done = threading.Event()
    t = threading.Thread(target=lambda: (mm.commit(1, big2), done.set()),
                         daemon=True)
    t.start()
    assert done.wait(20), "commit deadlocked below merge threshold"
    assert drain(mm) == reference_merge([b0, big2])


def test_stale_generation_commit_dropped(tmp_path):
    """A fetch that started before a slot reset must not displace (or join)
    the new attempt's data, even when it commits after the reset."""
    counters = TezCounters()
    mm = ShuffleMergeManager(counters, 0, str(tmp_path), engine="host")
    stale = sorted_batch(0, 200)
    fresh = sorted_batch(1, 200)
    gen = mm.slot_generation(2)
    mm.on_slot_reset(2)                      # producer re-ran mid-fetch
    assert mm.commit(2, fresh, mm.slot_generation(2)) is True
    assert mm.commit(2, stale, gen) is False   # late stale commit dropped
    assert drain(mm) == reference_merge([fresh])


def _file_source_run(tmp_path, name, batches):
    """Write batches as one partition-indexed file (each batch = one
    partition), return its path."""
    from tez_tpu.ops.runformat import PartitionedRunWriter
    path = os.path.join(str(tmp_path), name)
    w = PartitionedRunWriter(path, len(batches), block_records=64)
    for p, b in enumerate(batches):
        w.append(b, p)
    return w.close()


def test_disk_direct_sources_stream_without_copy(tmp_path):
    """Disk-direct sources (producer-owned partition-indexed files) merge
    correctly with mem batches and cost neither memory budget nor consumer
    spill files (LocalDiskFetchedInput analog)."""
    counters = TezCounters()
    spill = tmp_path / "consumer"
    spill.mkdir()
    mm = ShuffleMergeManager(counters, 1, str(spill), engine="host",
                             merge_threshold=1.0, block_records=64)
    p0 = _file_source_run(tmp_path, "prod0.prun",
                          [sorted_batch(0, 700), sorted_batch(1, 10)])
    p1 = _file_source_run(tmp_path, "prod1.prun",
                          [sorted_batch(2, 650), sorted_batch(3, 10)])
    from tez_tpu.ops.runformat import FileRun
    assert mm.commit_local_file(0, p0, 0, FileRun(p0).partition_nbytes(0))
    assert mm.commit_local_file(1, p1, 0, FileRun(p1).partition_nbytes(0))
    golden = reference_merge([sorted_batch(0, 700), sorted_batch(2, 650)])
    result = mm.finish()
    assert result.is_streaming
    got = [(k, v) for _, k, v in result.stream.iter_records()]
    assert got == golden
    # re-iterable, and no consumer-side spill files were written
    assert [(k, v) for _, k, v in result.stream.iter_records()] == golden
    assert not any(f.endswith((".crun",)) for f in os.listdir(spill))
    mm.cleanup()
    # producer files must survive consumer cleanup (producer-owned)
    assert os.path.exists(p0) and os.path.exists(p1)


def test_disk_direct_small_inputs_materialize(tmp_path):
    """Small disk-direct inputs fold into the in-RAM merged batch (no
    streaming plan) when they fit the memory budget."""
    counters = TezCounters()
    mm = ShuffleMergeManager(counters, 64 << 20, str(tmp_path), engine="host")
    path = _file_source_run(tmp_path, "prod.prun", [sorted_batch(5, 300)])
    from tez_tpu.ops.runformat import FileRun
    mem = sorted_batch(6, 300)
    mm.commit(1, mem)
    assert mm.commit_local_file(0, path, 0, FileRun(path).partition_nbytes(0))
    result = mm.finish()
    assert not result.is_streaming
    assert list(result.batch.iter_pairs()) == \
        reference_merge([sorted_batch(5, 300), mem])


def test_disk_direct_slot_reset_drops_source(tmp_path):
    """A producer re-run drops its disk-direct source cleanly (no poison:
    the source was never folded into shared merge state)."""
    counters = TezCounters()
    mm = ShuffleMergeManager(counters, 0, str(tmp_path), engine="host")
    stale = _file_source_run(tmp_path, "stale.prun", [sorted_batch(7, 100)])
    fresh = sorted_batch(8, 100)
    gen = mm.slot_generation(0)
    assert mm.commit_local_file(0, stale, 0, 4096, gen)
    mm.on_slot_reset(0)
    assert mm.commit_local_file(0, stale, 0, 4096, gen) is False  # stale gen
    mm.commit(0, fresh, mm.slot_generation(0))
    assert drain(mm) == reference_merge([fresh])
