"""journal_fsck: CRC validation, ledger pairing, ordering, terminal-state
inference — plus an end-to-end check against a journal a real AM wrote."""
import os

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.am.dag_impl import DAGState
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.recovery import encode_journal_line
from tez_tpu.common import config as C
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Vertex
from tez_tpu.tools import journal_fsck


def _write_journal(path, events, tail=""):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        for ev in events:
            fh.write(encode_journal_line(ev) + "\n")
        if tail:
            fh.write(tail)
    return path


def _ledger_events(dag_id, *types):
    evs = [HistoryEvent(HistoryEventType.DAG_SUBMITTED, dag_id=dag_id)]
    evs += [HistoryEvent(t, dag_id=dag_id) for t in types]
    return evs


def test_fsck_clean_commit_cycle(tmp_path):
    p = _write_journal(str(tmp_path / "journal.jsonl"), _ledger_events(
        "dag_1_a_1",
        HistoryEventType.DAG_COMMIT_STARTED,
        HistoryEventType.DAG_COMMIT_FINISHED) + [
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_a_1",
                     data={"state": "SUCCEEDED"})])
    report = journal_fsck.fsck_files([p])
    assert report.ok and not report.torn_tail
    assert report.dags["dag_1_a_1"].inferred_terminal == "SUCCEEDED"
    assert journal_fsck.main([p]) == 0


def test_fsck_torn_tail_tolerated_midstream_not(tmp_path):
    evs = _ledger_events("dag_1_b_1")
    # torn last record (the AM died mid-append): tolerated, still clean
    p = _write_journal(str(tmp_path / "torn.jsonl"), evs,
                       tail="deadbeef {truncat")
    report = journal_fsck.fsck_files([p])
    assert report.ok and report.torn_tail
    # the same damage mid-stream is NOT the crash signature: error
    with open(p, "a") as fh:
        fh.write("\n" + encode_journal_line(evs[0]) + "\n")
    report = journal_fsck.fsck_files([p])
    assert not report.ok
    assert journal_fsck.main([p]) == 1


def test_fsck_ledger_pairing_violations(tmp_path):
    # FINISHED without an open STARTED
    p1 = _write_journal(str(tmp_path / "j1.jsonl"), _ledger_events(
        "dag_1_c_1", HistoryEventType.DAG_COMMIT_FINISHED))
    assert not journal_fsck.fsck_files([p1]).ok
    # SUCCEEDED with the ledger still open
    p2 = _write_journal(str(tmp_path / "j2.jsonl"), _ledger_events(
        "dag_1_c_2", HistoryEventType.DAG_COMMIT_STARTED) + [
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_c_2",
                     data={"state": "SUCCEEDED"})])
    assert not journal_fsck.fsck_files([p2]).ok
    # open ledger with no terminal record: legal (that's the recovery case)
    p3 = _write_journal(str(tmp_path / "j3.jsonl"), _ledger_events(
        "dag_1_c_3", HistoryEventType.DAG_COMMIT_STARTED))
    report = journal_fsck.fsck_files([p3])
    assert report.ok
    assert "IN-COMMIT" in report.dags["dag_1_c_3"].inferred_terminal


def test_fsck_ledger_threads_across_attempts(tmp_path):
    """The resumed commit's FINISHED lands in attempt 2's journal; fsck in
    attempt order must pair it with attempt 1's STARTED."""
    rec = tmp_path / "recovery"
    _write_journal(str(rec / "1" / "journal.jsonl"), _ledger_events(
        "dag_1_d_1", HistoryEventType.DAG_COMMIT_STARTED))
    _write_journal(str(rec / "2" / "journal.jsonl"), [
        HistoryEvent(HistoryEventType.DAG_COMMIT_FINISHED,
                     dag_id="dag_1_d_1"),
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_d_1",
                     data={"state": "SUCCEEDED"})])
    files = journal_fsck.discover_journals(str(rec))
    assert [os.path.basename(os.path.dirname(f)) for f in files] == ["1", "2"]
    report = journal_fsck.fsck_files(files)
    assert report.ok
    assert report.dags["dag_1_d_1"].inferred_terminal == "SUCCEEDED"


def test_fsck_missing_target():
    assert journal_fsck.main([os.path.join("/nonexistent", "x")]) == 2


def test_fsck_real_am_journal(tmp_staging):
    """A journal written by an actual AM run passes fsck CLEAN with the
    right terminal state."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging})
    am = DAGAppMaster("app_1_fsck", conf, attempt=1)
    am.start()
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    dag_id = am.submit_dag(DAG.create("fsck").add_vertex(v).create_dag_plan())
    assert am.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am.stop()
    rec = os.path.join(tmp_staging, "app_1_fsck", "recovery")
    files = journal_fsck.discover_journals(rec)
    assert files
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    assert report.dags[str(dag_id)].inferred_terminal == "SUCCEEDED"
    assert journal_fsck.main(["--staging", tmp_staging,
                              "--app", "app_1_fsck"]) == 0


# ---------------------------------------------------- admission-queue pairing

def _mini_plan_hex(name="qd"):
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    return DAG.create(name).add_vertex(v).create_dag_plan().serialize().hex()


def _queued(sub_id, plan_hex, name="qd"):
    return HistoryEvent(HistoryEventType.DAG_QUEUED, dag_id=sub_id,
                        data={"dag_name": name, "tenant": "tA",
                              "plan": plan_hex})


def _requeued(sub_id, plan_hex, name="qd"):
    return HistoryEvent(HistoryEventType.DAG_REQUEUED_ON_RECOVERY,
                        dag_id=sub_id,
                        data={"dag_name": name, "tenant": "tA",
                              "plan": plan_hex, "attempt": 2})


def _promoted(sub_id, dag_id="dag_1_q_1"):
    return HistoryEvent(HistoryEventType.DAG_SUBMITTED, dag_id=dag_id,
                        data={"dag_name": "qd", "sub_id": sub_id})


def test_fsck_admission_clean_pair_and_unresolved(tmp_path):
    hexp = _mini_plan_hex()
    # queued -> promoted: clean, and the sub never materializes a DAG ledger
    p = _write_journal(str(tmp_path / "j.jsonl"), [
        _queued("app-sub1", hexp), _promoted("app-sub1"),
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_q_1",
                     data={"state": "SUCCEEDED"})])
    report = journal_fsck.fsck_files([p])
    assert report.ok, report.errors
    assert report.subs["app-sub1"].inferred == "PROMOTED"
    assert "app-sub1" not in report.dags     # sub_id is not a DAG
    # queued with no promotion: legal — that's exactly the replay case
    p2 = _write_journal(str(tmp_path / "j2.jsonl"),
                        [_queued("app-sub2", hexp)])
    report = journal_fsck.fsck_files([p2])
    assert report.ok
    assert "UNRESOLVED" in report.subs["app-sub2"].inferred


def test_fsck_admission_requeue_threads_across_attempts(tmp_path):
    """Attempt 1 queues, attempt 2 requeues and promotes: one ledger."""
    hexp = _mini_plan_hex()
    rec = tmp_path / "recovery"
    _write_journal(str(rec / "1" / "journal.jsonl"),
                   [_queued("app-sub1", hexp)])
    _write_journal(str(rec / "2" / "journal.jsonl"), [
        _requeued("app-sub1", hexp), _promoted("app-sub1")])
    files = journal_fsck.discover_journals(str(rec))
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    led = report.subs["app-sub1"]
    assert led.queued == 1 and led.requeued == 1 and led.promoted


def test_fsck_admission_pairing_violations(tmp_path):
    hexp = _mini_plan_hex()
    # duplicate DAG_QUEUED for one sub_id
    p = _write_journal(str(tmp_path / "a.jsonl"),
                       [_queued("s1", hexp), _queued("s1", hexp)])
    report = journal_fsck.fsck_files([p])
    assert any("duplicate DAG_QUEUED" in e for e in report.errors)
    # requeue for a submission never queued
    p = _write_journal(str(tmp_path / "b.jsonl"), [_requeued("s2", hexp)])
    report = journal_fsck.fsck_files([p])
    assert any("never DAG_QUEUED" in e for e in report.errors)
    # DAG_QUEUED arriving after a requeue: attempt order violated
    p = _write_journal(str(tmp_path / "c.jsonl"),
                       [_queued("s3", hexp), _requeued("s3", hexp),
                        _queued("s3", hexp)])
    report = journal_fsck.fsck_files([p])
    assert any("attempt order violated" in e for e in report.errors)
    # queue record after its promotion
    p = _write_journal(str(tmp_path / "d.jsonl"),
                       [_queued("s4", hexp), _promoted("s4"),
                        _requeued("s4", hexp)])
    report = journal_fsck.fsck_files([p])
    assert any("after its promotion" in e for e in report.errors)
    # promotion of a sub_id the journal never queued
    p = _write_journal(str(tmp_path / "e.jsonl"), [_promoted("ghost")])
    report = journal_fsck.fsck_files([p])
    assert any("never DAG_QUEUED" in e for e in report.errors)
    # duplicate promotion
    p = _write_journal(str(tmp_path / "f.jsonl"),
                       [_queued("s5", hexp), _promoted("s5"),
                        _promoted("s5", dag_id="dag_1_q_2")])
    report = journal_fsck.fsck_files([p])
    assert any("duplicate promotion" in e for e in report.errors)
    # queue record with no sub_id at all
    p = _write_journal(str(tmp_path / "g.jsonl"), [
        HistoryEvent(HistoryEventType.DAG_QUEUED, dag_id=None,
                     data={"dag_name": "x"})])
    report = journal_fsck.fsck_files([p])
    assert any("without a sub_id" in e for e in report.errors)


def test_fsck_admission_undecodable_plan(tmp_path):
    # unresolved + undecodable: lost work, an error
    p = _write_journal(str(tmp_path / "u.jsonl"), [
        HistoryEvent(HistoryEventType.DAG_QUEUED, dag_id="s6",
                     data={"dag_name": "broken", "plan": "deadbeef"})])
    report = journal_fsck.fsck_files([p])
    assert any("replay impossible" in e for e in report.errors)
    assert "LOST" in report.subs["s6"].inferred
    assert journal_fsck.main([p]) == 1
    # promoted + undecodable: the live object made it through — warning only
    p = _write_journal(str(tmp_path / "v.jsonl"), [
        HistoryEvent(HistoryEventType.DAG_QUEUED, dag_id="s7",
                     data={"dag_name": "odd", "plan": "deadbeef"}),
        _promoted("s7")])
    report = journal_fsck.fsck_files([p])
    assert report.ok
    assert any("promoted anyway" in w for w in report.warnings)


def test_fsck_real_crashed_session_journal(tmp_staging):
    """A journal pair written by a real crash + replay passes fsck CLEAN
    with the parked submission reported PROMOTED."""
    import threading
    import time as _time
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.max.app.attempts": 3,
                               "tez.am.local.num-containers": 2,
                               "tez.am.session.max-concurrent-dags": 1,
                               "tez.am.session.queue-size": 4})
    am1 = DAGAppMaster("app_1_fsckha", conf, attempt=1)
    am1.start()
    hold = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 20_000}), 1)
    first = am1.submit_dag(
        DAG.create("hold").add_vertex(hold).create_dag_plan())
    quick = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    parked_plan = DAG.create("parked").add_vertex(quick).create_dag_plan()
    t = threading.Thread(target=lambda: _try_submit(am1, parked_plan),
                         daemon=True)
    t.start()
    deadline = _time.time() + 20
    while not am1.logging_service.of_type(HistoryEventType.DAG_QUEUED):
        assert _time.time() < deadline, "submission never journaled"
        _time.sleep(0.02)
    am1.crash()
    t.join(timeout=10)

    am2 = DAGAppMaster("app_1_fsckha", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    am2.kill_dag(recovered)
    am2.wait_for_dag(recovered, timeout=30)
    deadline = _time.time() + 30
    while am2.find_dag_id_by_name("parked") is None:
        assert _time.time() < deadline, "parked DAG never promoted"
        _time.sleep(0.05)
    dag_id = am2.find_dag_id_by_name("parked")
    assert am2.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am2.stop()

    files = journal_fsck.discover_journals(
        os.path.join(tmp_staging, "app_1_fsckha", "recovery"))
    assert len(files) == 2
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    [sub_id] = report.sub_order
    led = report.subs[sub_id]
    assert led.queued == 1 and led.requeued == 1 and led.promoted
    assert led.inferred == "PROMOTED"
    assert journal_fsck.main(["--staging", tmp_staging,
                              "--app", "app_1_fsckha"]) == 0


def _try_submit(am, plan):
    try:
        am.submit_dag(plan)
    except Exception:   # noqa: BLE001 — AMCrashedError expected on crash
        pass
