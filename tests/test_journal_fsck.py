"""journal_fsck: CRC validation, ledger pairing, ordering, terminal-state
inference — plus an end-to-end check against a journal a real AM wrote."""
import os

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.am.dag_impl import DAGState
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.recovery import encode_journal_line
from tez_tpu.common import config as C
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Vertex
from tez_tpu.tools import journal_fsck


def _write_journal(path, events, tail=""):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        for ev in events:
            fh.write(encode_journal_line(ev) + "\n")
        if tail:
            fh.write(tail)
    return path


def _ledger_events(dag_id, *types):
    evs = [HistoryEvent(HistoryEventType.DAG_SUBMITTED, dag_id=dag_id)]
    evs += [HistoryEvent(t, dag_id=dag_id) for t in types]
    return evs


def test_fsck_clean_commit_cycle(tmp_path):
    p = _write_journal(str(tmp_path / "journal.jsonl"), _ledger_events(
        "dag_1_a_1",
        HistoryEventType.DAG_COMMIT_STARTED,
        HistoryEventType.DAG_COMMIT_FINISHED) + [
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_a_1",
                     data={"state": "SUCCEEDED"})])
    report = journal_fsck.fsck_files([p])
    assert report.ok and not report.torn_tail
    assert report.dags["dag_1_a_1"].inferred_terminal == "SUCCEEDED"
    assert journal_fsck.main([p]) == 0


def test_fsck_torn_tail_tolerated_midstream_not(tmp_path):
    evs = _ledger_events("dag_1_b_1")
    # torn last record (the AM died mid-append): tolerated, still clean
    p = _write_journal(str(tmp_path / "torn.jsonl"), evs,
                       tail="deadbeef {truncat")
    report = journal_fsck.fsck_files([p])
    assert report.ok and report.torn_tail
    # the same damage mid-stream is NOT the crash signature: error
    with open(p, "a") as fh:
        fh.write("\n" + encode_journal_line(evs[0]) + "\n")
    report = journal_fsck.fsck_files([p])
    assert not report.ok
    assert journal_fsck.main([p]) == 1


def test_fsck_ledger_pairing_violations(tmp_path):
    # FINISHED without an open STARTED
    p1 = _write_journal(str(tmp_path / "j1.jsonl"), _ledger_events(
        "dag_1_c_1", HistoryEventType.DAG_COMMIT_FINISHED))
    assert not journal_fsck.fsck_files([p1]).ok
    # SUCCEEDED with the ledger still open
    p2 = _write_journal(str(tmp_path / "j2.jsonl"), _ledger_events(
        "dag_1_c_2", HistoryEventType.DAG_COMMIT_STARTED) + [
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_c_2",
                     data={"state": "SUCCEEDED"})])
    assert not journal_fsck.fsck_files([p2]).ok
    # open ledger with no terminal record: legal (that's the recovery case)
    p3 = _write_journal(str(tmp_path / "j3.jsonl"), _ledger_events(
        "dag_1_c_3", HistoryEventType.DAG_COMMIT_STARTED))
    report = journal_fsck.fsck_files([p3])
    assert report.ok
    assert "IN-COMMIT" in report.dags["dag_1_c_3"].inferred_terminal


def test_fsck_ledger_threads_across_attempts(tmp_path):
    """The resumed commit's FINISHED lands in attempt 2's journal; fsck in
    attempt order must pair it with attempt 1's STARTED."""
    rec = tmp_path / "recovery"
    _write_journal(str(rec / "1" / "journal.jsonl"), _ledger_events(
        "dag_1_d_1", HistoryEventType.DAG_COMMIT_STARTED))
    _write_journal(str(rec / "2" / "journal.jsonl"), [
        HistoryEvent(HistoryEventType.DAG_COMMIT_FINISHED,
                     dag_id="dag_1_d_1"),
        HistoryEvent(HistoryEventType.DAG_FINISHED, dag_id="dag_1_d_1",
                     data={"state": "SUCCEEDED"})])
    files = journal_fsck.discover_journals(str(rec))
    assert [os.path.basename(os.path.dirname(f)) for f in files] == ["1", "2"]
    report = journal_fsck.fsck_files(files)
    assert report.ok
    assert report.dags["dag_1_d_1"].inferred_terminal == "SUCCEEDED"


def test_fsck_missing_target():
    assert journal_fsck.main([os.path.join("/nonexistent", "x")]) == 2


def test_fsck_real_am_journal(tmp_staging):
    """A journal written by an actual AM run passes fsck CLEAN with the
    right terminal state."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging})
    am = DAGAppMaster("app_1_fsck", conf, attempt=1)
    am.start()
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    dag_id = am.submit_dag(DAG.create("fsck").add_vertex(v).create_dag_plan())
    assert am.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am.stop()
    rec = os.path.join(tmp_staging, "app_1_fsck", "recovery")
    files = journal_fsck.discover_journals(rec)
    assert files
    report = journal_fsck.fsck_files(files)
    assert report.ok, report.errors
    assert report.dags[str(dag_id)].inferred_terminal == "SUCCEEDED"
    assert journal_fsck.main(["--staging", tmp_staging,
                              "--app", "app_1_fsck"]) == 0
