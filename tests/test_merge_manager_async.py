"""Async reduce-side merge plane (tez.runtime.merge.async.depth > 0):
background merges submit through an AsyncSpanPipeline merge lane instead of
running inline on the merger thread.

Contracts under test:
- drained output is BYTE-identical to the synchronous merger (depth=0) for
  identical commit sequences — mem->disk merges, disk cascades, and the
  streaming final merge included;
- the overlap witness: merge k's chunked-run disk write (readback stage)
  runs while merge k+1's dispatch is in flight (overlap_pairs over the
  instrumented event stream, gated on thread events — no wall-clock);
- PR-5 containment covers merge dispatches: injected device.dispatch.oom
  and device.dispatch.hang faults recover through the split/failover ladder
  (watchdog + breaker) with bit-exact output.
"""
import threading
import time

import numpy as np
import pytest

from tez_tpu.common import faults
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.faults import parse_spec
from tez_tpu.library.merge_manager import ShuffleMergeManager
from tez_tpu.ops.async_stage import (COUNTER_GROUP, CircuitBreaker,
                                     overlap_pairs)

from test_merge_manager import drain, reference_merge, sorted_batch


def _wait_for(pred, what, timeout=20.0):
    """Deadline-poll an internal progress predicate: the merger thread runs
    asynchronously, so tests that need 'merge k submitted before commit
    k+1' must observe it rather than race it."""
    deadline = time.time() + timeout
    while not pred():
        assert time.time() < deadline, what
        time.sleep(0.005)


def _run_manager(tmp_path, batches, tag, async_depth, engine="host",
                 mm_cls=ShuffleMergeManager, budget=None,
                 merge_threshold=0.5, **kw):
    spill = tmp_path / f"spill_{tag}"
    spill.mkdir()
    counters = TezCounters()
    total = sum(b.nbytes for b in batches)
    mm = mm_cls(counters, total // 4 if budget is None else budget,
                str(spill), engine=engine,
                merge_threshold=merge_threshold, max_single_fraction=2.0,
                block_records=256, async_depth=async_depth,
                device_min_records=0, **kw)
    for slot, b in enumerate(batches):
        mm.commit(slot, b)
    return mm, drain(mm), counters


@pytest.mark.parametrize("engine", ["host", "device"])
def test_async_matches_sync_bit_exact(tmp_path, engine):
    batches = [sorted_batch(i, 1500) for i in range(8)]
    _, sync, _ = _run_manager(tmp_path, batches, f"sync_{engine}", 0,
                              engine=engine)
    mm, got, _ = _run_manager(tmp_path, batches, f"async_{engine}", 2,
                              engine=engine)
    assert mm._mem_to_disk >= 1        # the async lane actually merged
    assert got == sync == reference_merge(batches)


def test_async_disk_cascade_matches_sync(tmp_path):
    """Everything lands on disk (tiny max_single): the async lane runs
    disk->disk cascades through the pipeline; age order (and therefore the
    equal-key tie order of the final streaming merge) must match the
    synchronous merger exactly."""
    batches = [sorted_batch(i, 600) for i in range(6)]

    def run(tag, depth):
        spill = tmp_path / f"spill_{tag}"
        spill.mkdir()
        counters = TezCounters()
        mm = ShuffleMergeManager(counters, 10 << 20, str(spill),
                                 engine="host", merge_factor=2,
                                 max_single_fraction=0.0001,
                                 block_records=128, async_depth=depth)
        for slot, b in enumerate(batches):
            mm.commit(slot, b)
        # finish() closes the merger loop, which checks _closed BEFORE
        # looking for runnable disk work — observe the cascade rather than
        # race it (6 runs with merge_factor=2 make one inevitable)
        _wait_for(lambda: mm._disk_to_disk >= 1,
                  f"{tag}: disk cascade never ran")
        out = drain(mm)
        return mm, out

    _, sync = run("sync", 0)
    mm, got = run("async", 2)
    assert mm._disk_to_disk >= 1
    assert got == sync == reference_merge(batches)


class _GatedManager(ShuffleMergeManager):
    """Holds merge 0's disk write (readback stage) until a LATER merge's
    dispatch has started — the deterministic overlap handshake."""

    def __init__(self, *a, **kw):
        self.later_dispatched = threading.Event()
        self.dispatch_count = 0
        super().__init__(*a, **kw)

    def _pipe_dispatch(self, payload):
        out = super()._pipe_dispatch(payload)
        self.dispatch_count += 1
        if self.dispatch_count >= 2:
            self.later_dispatched.set()
        return out

    def _pipe_readback(self, inflight, ids):
        if ids == (0,):
            assert self.later_dispatched.wait(timeout=30.0), \
                "merge 1 never dispatched while merge 0's write was held"
        return super()._pipe_readback(inflight, ids)


def test_async_overlap_witness(tmp_path):
    """Instrument-mode proof that the merge lane overlaps: a later merge's
    pipeline entry (encode mark) starts before merge 0's readback (the
    chunked-run write) ends."""
    batches = [sorted_batch(i, 1200) for i in range(10)]
    # a budget far above the data keeps commits from stalling on the held
    # disk write; the tiny threshold keeps the merger claiming eagerly.
    # Commits land in two waves with an observed dispatch between them:
    # without the poll the merger thread can lose the race to finish() and
    # fold everything in-RAM without ever submitting to the pipeline.
    total = sum(b.nbytes for b in batches)
    spill = tmp_path / "spill_overlap"
    spill.mkdir()
    counters = TezCounters()
    mm = _GatedManager(counters, total * 4, str(spill), engine="host",
                       merge_threshold=0.02, max_single_fraction=2.0,
                       block_records=256, async_depth=2,
                       device_min_records=0, instrument=True)
    for slot in range(5):
        mm.commit(slot, batches[slot])
    _wait_for(lambda: mm.dispatch_count >= 1, "merge 0 never dispatched")
    for slot in range(5, 10):
        mm.commit(slot, batches[slot])
    _wait_for(lambda: mm.dispatch_count >= 2, "merge 1 never dispatched")
    got = drain(mm)
    assert mm.dispatch_count >= 2
    assert got == reference_merge(batches)
    pairs = overlap_pairs(mm.pipeline_events())
    assert any(a == (0,) for a, _b in pairs), \
        f"no overlap witnessed: {mm.pipeline_events()}"


def _chaos_run(tmp_path, batches, tag, depth, spec, budget_div=4, **kw):
    if spec:
        faults.install("t", parse_spec(spec))
    try:
        spill = tmp_path / f"spill_{tag}"
        spill.mkdir()
        counters = TezCounters()
        total = sum(b.nbytes for b in batches)
        mm = ShuffleMergeManager(counters, total // budget_div, str(spill),
                                 engine="device", device_min_records=0,
                                 merge_threshold=0.5, max_single_fraction=2.0,
                                 block_records=256, async_depth=depth, **kw)
        for slot, b in enumerate(batches):
            mm.commit(slot, b)
        return drain(mm), counters
    finally:
        if spec:
            faults.install("t", [])


def test_async_oom_split_ladder_bit_exact(tmp_path):
    """An injected RESOURCE_EXHAUSTED on the first merge dispatch drives
    the OOM ladder: the run set halves and re-merges on device (composed
    merge bit-identical); no host failover, breaker untouched."""
    batches = [sorted_batch(i, 1500) for i in range(8)]
    # budget_div=2 with threshold 0.5 puts the merge trigger at TWO
    # batches: every claim holds >= 2 live runs, so the OOM split retry
    # always has a halving point (never declines to the failover floor)
    sync, _ = _chaos_run(tmp_path, batches, "sync", 0, "", budget_div=2)
    br = CircuitBreaker(failures=100)
    got, counters = _chaos_run(
        tmp_path, batches, "oom", 2,
        "device.dispatch.oom:fail:n=1,exc=runtime,match=span=0",
        budget_div=2, breaker=br)
    assert got == sync == reference_merge(batches)
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.oom.split_attempts").value == 1
    assert fo.find_counter("device.oom.split_success").value == 1
    assert br.trips == 0


def test_async_hang_watchdog_failover_bit_exact(tmp_path):
    """An injected hung merge dispatch (well past the watchdog deadline):
    the watchdog abandons the attempt, the merge fails over to the host
    engine from its raw payload, and the drained output stays bit-exact."""
    batches = [sorted_batch(i, 1500) for i in range(8)]
    sync, _ = _chaos_run(tmp_path, batches, "sync_h", 0, "")
    br = CircuitBreaker(failures=100)
    got, counters = _chaos_run(
        tmp_path, batches, "hang", 2,
        "device.dispatch.hang:delay:ms=1500,n=1,match=span=0",
        breaker=br, watchdog_dispatch_ms=200, watchdog_readback_ms=200)
    assert got == sync == reference_merge(batches)
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.watchdog.fires").value >= 1
    assert fo.find_counter("device.failover.spans").value >= 1
    assert br.trips == 0


def test_async_breaker_short_circuit_bit_exact(tmp_path):
    """A storm of merge-dispatch OOMs trips the breaker; later merges
    short-circuit straight to the host engine without touching the device —
    drained output still bit-exact.

    Commits are paced one batch per merge claim: a single-run claim makes
    the OOM split retry decline (no halving point), so the failure falls
    through to host failover and the breaker STAYS open — a multi-run claim
    would split successfully on device and close the breaker again."""
    batches = [sorted_batch(i, 900) for i in range(4)]
    sync, _ = _chaos_run(tmp_path, batches, "sync_b", 0, "")
    br = CircuitBreaker(failures=1, cooldown_ms=60_000)
    faults.install("t", parse_spec("device.dispatch.oom:fail:n=99,exc=runtime"))
    try:
        spill = tmp_path / "spill_storm"
        spill.mkdir()
        counters = TezCounters()
        total = sum(b.nbytes for b in batches)
        mm = ShuffleMergeManager(counters, total * 4, str(spill),
                                 engine="device", device_min_records=0,
                                 merge_threshold=0.02,
                                 max_single_fraction=2.0, block_records=256,
                                 async_depth=2, breaker=br)
        for slot, b in enumerate(batches):
            mm.commit(slot, b)
            _wait_for(lambda: mm._pipe_seq >= slot + 1,
                      f"merge {slot} never claimed")
        got = drain(mm)
    finally:
        faults.install("t", [])
    assert got == sync == reference_merge(batches)
    assert br.trips >= 1
    fo = counters.group(COUNTER_GROUP)
    assert fo.find_counter("device.breaker.short_circuits").value >= 1
    assert fo.find_counter("device.failover.spans").value >= 2


def test_async_depth_zero_has_no_pipeline(tmp_path):
    counters = TezCounters()
    mm = ShuffleMergeManager(counters, 1 << 20, str(tmp_path),
                             engine="host", async_depth=0)
    assert mm._pipeline is None
    assert mm.pipeline_events() == []
    mm.commit(0, sorted_batch(0, 50))
    assert drain(mm) == reference_merge([sorted_batch(0, 50)])
