"""End-to-end ordered-shuffle tests: OrderedWordCount through the full stack
(the phase-3 gate from SURVEY.md §7: E2E with correct, deterministically
ordered reducer input)."""
import collections
import os
import random

import pytest

from tez_tpu.examples import ordered_wordcount


WORDS = ["apple", "banana", "cherry", "date", "elderberry", "fig", "grape",
         "kiwi", "lemon", "mango", "nectarine", "orange", "papaya", "quince"]


def write_corpus(path, num_lines=500, seed=0):
    rng = random.Random(seed)
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(WORDS) for _ in range(rng.randrange(1, 12))]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def read_output(out_dir):
    rows = []
    for f in sorted(os.listdir(out_dir)):
        if not f.startswith("part-"):
            continue
        with open(os.path.join(out_dir, f), "rb") as fh:
            for line in fh:
                word, count = line.rstrip(b"\n").split(b"\t")
                rows.append((word.decode(), int(count)))
    return rows


@pytest.mark.parametrize("combine,pipelined", [(True, False), (False, False),
                                               (True, True)])
def test_ordered_wordcount_e2e(tmp_path, combine, pipelined):
    corpus = tmp_path / "in.txt"
    golden = write_corpus(str(corpus), num_lines=300)
    out_dir = str(tmp_path / "out")
    state = ordered_wordcount.run(
        [str(corpus)], out_dir,
        conf={"tez.staging-dir": str(tmp_path / "stg")},
        tokenizer_parallelism=3, summation_parallelism=2,
        sorter_parallelism=1, combine=combine, pipelined=pipelined)
    assert state == "SUCCEEDED"
    assert os.path.exists(os.path.join(out_dir, "_SUCCESS"))
    rows = read_output(out_dir)
    # counts correct
    assert {w: c for w, c in rows} == dict(golden)
    # globally ordered by count ascending (big-endian long key order)
    counts = [c for _, c in rows]
    assert counts == sorted(counts)


def test_ordered_wordcount_multifile_splits(tmp_path):
    goldens = collections.Counter()
    ins = []
    for i in range(3):
        p = tmp_path / f"in{i}.txt"
        goldens.update(write_corpus(str(p), num_lines=100, seed=i))
        ins.append(str(p))
    out_dir = str(tmp_path / "out")
    state = ordered_wordcount.run(
        ins, out_dir, conf={"tez.staging-dir": str(tmp_path / "stg")},
        tokenizer_parallelism=4)
    assert state == "SUCCEEDED"
    assert {w: c for w, c in read_output(out_dir)} == dict(goldens)


def test_determinism_two_runs_byte_identical(tmp_path):
    """Byte-identical output across runs (the reference north-star's
    byte-exactness requirement applied to our own framework)."""
    corpus = tmp_path / "in.txt"
    write_corpus(str(corpus), num_lines=200, seed=42)
    outs = []
    for run_i in range(2):
        out_dir = str(tmp_path / f"out{run_i}")
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf={"tez.staging-dir": str(tmp_path / f"stg{run_i}")},
            tokenizer_parallelism=3, summation_parallelism=3)
        assert state == "SUCCEEDED"
        parts = b""
        for f in sorted(os.listdir(out_dir)):
            if f.startswith("part-"):
                parts += open(os.path.join(out_dir, f), "rb").read()
        outs.append(parts)
    assert outs[0] == outs[1]


from tez_tpu.library.processors import SimpleProcessor  # noqa: E402


class MixedCaseEmitter(SimpleProcessor):
    def run(self, inputs, outputs):
        w = outputs["sum"].get_writer()
        for word in ("Apple", "banana", "APPLE", "Banana", "apple",
                     "cherry"):
            w.write(word.encode(), 1)


class GroupRecorder(SimpleProcessor):
    def run(self, inputs, outputs):
        payload = self.context.user_payload.load()
        rows = [(k.decode(), sum(vs))
                for k, vs in inputs["emit"].get_reader()]
        with open(os.path.join(payload["out"],
                               f"part-{self.context.task_index}"),
                  "w") as fh:
            for k, v in rows:
                fh.write(f"{k}\t{v}\n")


def test_case_insensitive_comparator_e2e(tmp_path):
    """tez.runtime.key.comparator.class end to end: 'Foo' and 'foo' sort
    together and the consumer groups them into ONE comparator-equal group
    (raw-comparator grouping semantics)."""
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                        ProcessorDescriptor)
    from tez_tpu.dag.dag import DAG, Edge, Vertex
    from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                           EdgeProperty, SchedulingType)

    out = str(tmp_path / "res")
    os.makedirs(out)
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "long",
          "tez.runtime.key.comparator.class":
              "tez_tpu.library.comparators:CaseInsensitiveKeyComparator"}
    c = TezClient.create("cmp", {"tez.staging-dir": str(tmp_path / "s"),
                                 "tez.am.local.num-containers": 3}).start()
    try:
        emit = Vertex.create("emit", ProcessorDescriptor.create(
            MixedCaseEmitter), 2)
        summ = Vertex.create("sum", ProcessorDescriptor.create(
            GroupRecorder, payload={"out": out}), 1)
        prop = EdgeProperty.create(
            DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
            SchedulingType.SEQUENTIAL,
            OutputDescriptor.create(
                "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
                payload=kv),
            InputDescriptor.create(
                "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=kv))
        dag = DAG.create("cmpdag").add_vertex(emit).add_vertex(summ)
        dag.add_edge(Edge.create(emit, summ, prop))
        st = c.submit_dag(dag).wait_for_completion(timeout=60)
        assert st.state is DAGStatusState.SUCCEEDED
    finally:
        c.stop()
    rows = [line.rstrip("\n").split("\t")
            for line in open(os.path.join(out, "part-0"))]
    # one group per case-insensitive word, counts summed across cases+tasks
    assert [(k.lower(), int(v)) for k, v in rows] == \
        [("apple", 6), ("banana", 4), ("cherry", 2)]


def test_vector_tokenizer_matches_simple(tmp_path):
    """Batch-first tokenizer (iter_chunks + write_batch) must produce
    identical output to the per-record path, across both exchanges."""
    import collections
    import random
    from tez_tpu.examples import ordered_wordcount

    rng = random.Random(23)
    corpus = tmp_path / "c.txt"
    with open(corpus, "w") as fh:
        for _ in range(5000):
            fh.write(f"v{rng.randrange(200):03d}")
            fh.write(rng.choice([" ", " ", "  ", "\n", "\r\n", "\t", "\x0b", "\x0c"]))
    outs = {}
    for mode in ("simple", "vector"):
        out_dir = str(tmp_path / f"out_{mode}")
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf={"tez.staging-dir": str(tmp_path / f"stg_{mode}")},
            tokenizer_parallelism=3, summation_parallelism=2,
            sorter_parallelism=1, tokenizer_mode=mode)
        assert state == "SUCCEEDED", mode
        lines = []
        for name in sorted(os.listdir(out_dir)):
            with open(os.path.join(out_dir, name)) as fh:
                lines.extend(fh.read().splitlines())
        outs[mode] = lines
    assert outs["simple"] == outs["vector"]
    assert len(outs["simple"]) == 200


def test_spill_scale_e2e_counters(tmp_path, tmp_staging):
    """Framework-level spill proof (100 GB protocol stage 1, small scale):
    data >> span budget forces producer disk spills and the consumer merge
    cascade, SPILLED_RECORDS / ADDITIONAL_SPILLS_BYTES_* counters record
    it, and the output still matches the golden (reference:
    PipelinedSorter.java:559, MergeManager.java:387)."""
    from tez_tpu.tools import spill_bench
    rec = spill_bench.run(target_mb=6, vocab=60_000, sort_mb=1,
                          engine="host", parallelism=2)
    c = rec["counters"]
    assert c.get("SPILLED_RECORDS", 0) > 0
    assert c.get("ADDITIONAL_SPILLS_BYTES_WRITTEN", 0) > 0
    assert c.get("ADDITIONAL_SPILLS_BYTES_READ", 0) > 0
    assert rec["distinct_words"] > 0
