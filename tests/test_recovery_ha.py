"""AM crash survival (docs/recovery.md): admission-queue replay across
incarnations, client re-attach, zombie fencing, and coded push replicas.

The contract under test: a SIGKILLed session AM loses NOTHING that was
journaled — parked submissions replay under the successor incarnation with
their original sub_id/tenant/arrival order, live handles re-bind, stale
heartbeats are fenced, and a pushed spill whose primary store copy dies is
reconstructed from its coded buddy without re-running the producer."""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.am.dag_impl import DAGState
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.errors import AMCrashedError, DAGLostError
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import config as C
from tez_tpu.common import faults
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Vertex


def _plan(name, sleep_ms=1, tasks=2):
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": sleep_ms}), tasks)
    return DAG.create(name).add_vertex(v).create_dag_plan()


def _session_conf(tmp_staging, **extra):
    base = {"tez.staging-dir": tmp_staging,
            "tez.am.local.num-containers": 2,
            "tez.am.max.app.attempts": 3,
            "tez.am.session.max-concurrent-dags": 1,
            "tez.am.session.queue-size": 4}
    base.update(extra)
    return C.TezConfiguration(base)


def _park(am, plan, errors, crashed):
    """Submit on a thread; the parked submitter must observe a typed
    AMCrashedError when the AM dies under it."""

    def run():
        try:
            am.submit_dag(plan)
            errors.append(f"{plan.name}: promoted instead of crashed")
        except AMCrashedError as e:
            crashed.append(e)
        except BaseException as e:  # noqa: BLE001 — typed verdicts only
            errors.append(f"{plan.name}: {type(e).__name__}: {e}")

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _wait_journaled(am, n, timeout=20.0):
    deadline = time.time() + timeout
    while len(am.logging_service.of_type(HistoryEventType.DAG_QUEUED)) < n:
        if time.time() > deadline:
            pytest.fail(f"{n} DAG_QUEUED records never journaled")
        time.sleep(0.02)


def _wait_name(am, name, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        dag_id = am.find_dag_id_by_name(name)
        if dag_id is not None:
            return dag_id
        time.sleep(0.05)
    pytest.fail(f"DAG {name} never promoted on the successor AM")


def test_crash_replays_parked_admission_queue(tmp_staging):
    """Parked submissions die with AMCrashedError; the successor incarnation
    rebuilds the queue from unresolved DAG_QUEUED records — original sub_id,
    tenant, and arrival order — under DAG_REQUEUED_ON_RECOVERY events."""
    conf = _session_conf(tmp_staging)
    am1 = DAGAppMaster("app_1_qrep", conf, attempt=1)
    am1.start()
    am1.submit_dag(_plan("qa", sleep_ms=20_000))   # holds the only slot
    errors, crashed = [], []
    # serialize the parks: two racing submitters can journal their
    # DAG_QUEUED records in the opposite order of sub-id assignment,
    # and this test asserts on arrival ORDER
    t_b = _park(am1, _plan("qb"), errors, crashed)
    _wait_journaled(am1, 1)
    t_c = _park(am1, _plan("qc"), errors, crashed)
    _wait_journaled(am1, 2)
    am1.crash()
    t_b.join(timeout=10)
    t_c.join(timeout=10)
    assert not errors, errors
    assert len(crashed) == 2
    queued_ids = [e.dag_id for e in
                  am1.logging_service.of_type(HistoryEventType.DAG_QUEUED)]

    am2 = DAGAppMaster("app_1_qrep", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None          # the mid-run qa resubmitted
    requeued = am2.logging_service.of_type(
        HistoryEventType.DAG_REQUEUED_ON_RECOVERY)
    # original sub_ids, original arrival order, replay attempt stamped
    assert [e.dag_id for e in requeued] == queued_ids
    assert all(e.data["attempt"] == 2 for e in requeued)
    assert [e.data["dag_name"] for e in requeued] == ["qb", "qc"]
    # qa still sleeps 20s; kill it to free the slot, then the replayed
    # queue drains in order
    am2.kill_dag(recovered)
    assert am2.wait_for_dag(recovered, timeout=30) is DAGState.KILLED
    for name in ("qb", "qc"):
        dag_id = _wait_name(am2, name)
        assert am2.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    # promotions resolved the replayed records: a third incarnation would
    # have nothing left to replay
    from tez_tpu.am.recovery import RecoveryParser
    parser = RecoveryParser(tmp_staging, "app_1_qrep")
    assert parser.queued_submissions() == []
    am2.stop()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_popped_but_unstarted_submission_replays(tmp_staging):
    """The am.queue.delay window: the consumer pops a submission and dies
    before _start_dag.  Its DAG_QUEUED record is the only surviving trace —
    the successor incarnation must still replay it."""
    conf = _session_conf(tmp_staging)
    am1 = DAGAppMaster("app_1_qpop", conf, attempt=1)
    am1.start()
    first = am1.submit_dag(_plan("pa", sleep_ms=20_000))
    errors, crashed = [], []
    t_b = _park(am1, _plan("pb"), errors, crashed)
    _wait_journaled(am1, 1)
    faults.install("t", faults.parse_spec("am.queue.delay:fail:n=1"), seed=1)
    try:
        # freeing the slot makes the consumer pop pb — and die mid-drain
        am1.kill_dag(first)
        assert am1.wait_for_dag(first, timeout=30) is DAGState.KILLED
        deadline = time.time() + 10
        while am1.admission.consumer_alive():
            if time.time() > deadline:
                pytest.fail("consumer survived the am.queue.delay fault")
            time.sleep(0.02)
        # popped-but-unstarted: still visible as unresolved
        assert len(am1.admission.unresolved()) == 1
        am1.crash()
    finally:
        faults.clear_all()
    t_b.join(timeout=10)
    assert not errors, errors
    assert len(crashed) == 1

    am2 = DAGAppMaster("app_1_qpop", conf, attempt=2)
    am2.start()
    am2.recover_and_resume()
    requeued = am2.logging_service.of_type(
        HistoryEventType.DAG_REQUEUED_ON_RECOVERY)
    assert [e.data["dag_name"] for e in requeued] == ["pb"]
    assert requeued[0].dag_id == crashed[0].sub_id   # original sub_id kept
    dag_id = _wait_name(am2, "pb")
    assert am2.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am2.stop()


def test_client_reattach_rebinds_live_handles(tmp_staging):
    """TezClient.reattach(): the successor AM replays the journal, live
    DAGClient handles re-bind by dag_id (finished DAGs included — their
    journaled verdict survives the restart), and attach_dag recovers the
    handle for a submission whose submitter observed AMCrashedError."""
    c = TezClient.create("ha", {"tez.staging-dir": tmp_staging,
                                "tez.am.local.num-containers": 2,
                                "tez.am.max.app.attempts": 3,
                                "tez.am.session.max-concurrent-dags": 1,
                                "tez.am.session.queue-size": 4},
                         session=True).start()
    try:
        done = c.submit_dag(DAG.create("done").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 2)))
        assert done.wait_for_completion(
            timeout=30).state is DAGStatusState.SUCCEEDED
        live = c.submit_dag(DAG.create("live").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 3000}), 2)))
        errors, crashed = [], []
        am1 = c.framework_client.am
        t = _park(am1, _plan("parked"), errors, crashed)
        _wait_journaled(am1, 1)
        am1.crash()
        t.join(timeout=10)
        assert not errors and len(crashed) == 1

        c.reattach()
        am2 = c.framework_client.am
        assert am2 is not am1 and am2.attempt == am1.attempt + 1
        # the mid-run handle re-bound transparently: same object, new AM
        assert live._am is am2
        assert live.wait_for_completion(
            timeout=60).state is DAGStatusState.SUCCEEDED
        # the finished handle answers from the rolled-forward registry
        assert done.get_dag_status().state is DAGStatusState.SUCCEEDED
        # the parked submission replays; attach_dag recovers its handle
        parked = c.attach_dag("parked", timeout=30)
        assert parked.wait_for_completion(
            timeout=30).state is DAGStatusState.SUCCEEDED
        # a name the journal never saw is typed lost, not a timeout
        with pytest.raises(DAGLostError):
            c.attach_dag("never-submitted", timeout=5)
    finally:
        c.stop()


def test_zombie_fence_counts_journals_and_flight_marks(tmp_staging):
    """A heartbeat stamped with the dead incarnation's epoch is ordered to
    die, counted, journaled as ATTEMPT_FENCED, and visible in the flight
    recorder (the chaos --am-kill acceptance surface)."""
    from tez_tpu.am.task_comm import HeartbeatRequest
    from tez_tpu.common.ids import DAGId
    from tez_tpu.obs import flight
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.max.app.attempts": 3})
    flight.install("t")
    am1 = DAGAppMaster("app_1_zfen", conf, attempt=1)
    am2 = DAGAppMaster("app_1_zfen", conf, attempt=2)   # supersedes am1
    try:
        am2.start()
        zombie = DAGId("app_1_zfen", 1).vertex(0).task(0).attempt(0)
        resp = am2.task_comm.heartbeat(
            HeartbeatRequest(zombie, [], epoch=1))
        assert resp.should_die
        assert am2.task_comm.fenced_count == 1
        fenced = am2.logging_service.of_type(HistoryEventType.ATTEMPT_FENCED)
        assert len(fenced) == 1
        assert fenced[0].data["msg_epoch"] == 1
        assert fenced[0].data["am_epoch"] == 2
        marks = [e for e in flight.snapshot().events
                 if e.name == "fence.stale_epoch"]
        assert marks, "fence left no flight-recorder mark"
    finally:
        am2.stop()
        am1.stop()
        flight.clear_all()


def test_queued_plan_roundtrip_across_process_boundary(tmp_staging):
    """The journaled DAG_QUEUED plan must replay in a FRESH interpreter —
    the successor AM is a different process in production.  A subprocess
    parses the journal with RecoveryParser and deserializes the plan; the
    round-tripped bytes must match bit-exact."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.max.app.attempts": 3})
    plan = _plan("xproc", tasks=3)
    am1 = DAGAppMaster("app_1_xproc", conf, attempt=1)
    am1.start()
    am1.history(HistoryEvent(
        HistoryEventType.DAG_QUEUED, dag_id="app_1_xproc-sub1",
        data={"dag_name": plan.name, "tenant": "tA",
              "plan": plan.serialize().hex()}))
    am1.crash()

    script = (
        "import json, sys\n"
        "from tez_tpu.am.recovery import RecoveryParser\n"
        "from tez_tpu.dag.plan import DAGPlan\n"
        "staging, app_id = sys.argv[1], sys.argv[2]\n"
        "recs = RecoveryParser(staging, app_id).queued_submissions()\n"
        "[rec] = recs\n"
        "plan = DAGPlan.deserialize(bytes.fromhex(rec['plan']))\n"
        "print(json.dumps({\n"
        "    'sub_id': rec['sub_id'], 'tenant': rec['tenant'],\n"
        "    'decode_error': rec['decode_error'], 'name': plan.name,\n"
        "    'vertices': [v.name for v in plan.vertices],\n"
        "    'num_tasks': [v.parallelism for v in plan.vertices],\n"
        "    'reserialized': plan.serialize().hex()}))\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", script, tmp_staging, "app_1_xproc"],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["sub_id"] == "app_1_xproc-sub1"
    assert out["tenant"] == "tA"
    assert out["decode_error"] == ""
    assert out["name"] == "xproc"
    assert out["vertices"] == ["v"] and out["num_tasks"] == [3]
    assert out["reserialized"] == plan.serialize().hex()


def test_push_replica_failover_serves_without_producer(tmp_path):
    """replicas=2 lands a coded buddy copy; when the primary store entry
    and the producer's registration both die (store.replica.lost), the
    fetch chain reconstructs from the replica and accounts the failover."""
    from tez_tpu.common.counters import TezCounters
    from tez_tpu.ops.sorter import DeviceSorter
    from tez_tpu.shuffle.push import PushAdmissionController
    from tez_tpu.shuffle.service import ShuffleDataNotFound, ShuffleService
    from tez_tpu.store.buffer_store import ShuffleBufferStore

    def make_service(subdir):
        service = ShuffleService()
        store = ShuffleBufferStore(device_capacity=0, host_capacity=8 << 20,
                                   disk_dir=str(tmp_path / subdir))
        service.attach_buffer_store(store)
        service.attach_push_admission(PushAdmissionController(
            lambda: store, source_quota_bytes=4 << 20))
        return service

    sorter = DeviceSorter(num_partitions=3)
    for i in range(60):
        sorter.write(f"k{i:04d}".encode(), f"v{i}".encode())
    run = sorter.flush()

    service = make_service("repl")
    counters = TezCounters()
    service.register("dagHA/a_1/c", 0, run, use_store=False)
    service.push_publish("dagHA/a_1/c", 0, run, replicas=2, counters=counters)
    group = counters.to_dict().get("ShuffleStore", {})
    assert group.get("store.replica.bytes", 0) >= run.nbytes

    faults.install("t", faults.parse_spec("store.replica.lost:fail:n=1"),
                   seed=1)
    try:
        got = service.fetch_partition("dagHA/a_1/c", 0, 1, counters=counters)
    finally:
        faults.clear_all()
    assert list(got.iter_pairs()) == list(run.partition(1).iter_pairs())
    group = counters.to_dict().get("ShuffleStore", {})
    assert group.get("store.replica.failover", 0) == 1

    # contrast: without the replica the same loss is fatal — the replica
    # is what stands between a dead store and a producer re-run
    bare = make_service("bare")
    bare.register("dagHA/a_1/c", 0, run, use_store=False)
    bare.push_publish("dagHA/a_1/c", 0, run)   # replicas=1 (default)
    faults.install("t", faults.parse_spec("store.replica.lost:fail:n=1"),
                   seed=1)
    try:
        with pytest.raises(ShuffleDataNotFound):
            bare.fetch_partition("dagHA/a_1/c", 0, 1)
    finally:
        faults.clear_all()
