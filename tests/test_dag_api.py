"""DAG DSL tests mirroring tez-api's TestDAGVerify / TestDAGPlan."""
import pickle

import pytest

from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor, UserPayload)
from tez_tpu.dag.dag import DAG, Edge, TezUncheckedException, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.dag.plan import DAGPlan


def proc(name="p"):
    return ProcessorDescriptor.create("tez_tpu.library.processors:SimpleProcessor")


def sg_edge(a, b):
    return Edge.create(a, b, EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create("x:O"), InputDescriptor.create("x:I")))


def test_linear_dag_verifies():
    a, b, c = Vertex.create("a", proc(), 2), Vertex.create("b", proc(), 2), \
        Vertex.create("c", proc(), 1)
    dag = DAG.create("d").add_vertex(a).add_vertex(b).add_vertex(c)
    dag.add_edge(sg_edge(a, b)).add_edge(sg_edge(b, c))
    assert dag.verify() == ["a", "b", "c"]


def test_cycle_rejected():
    a, b = Vertex.create("a", proc(), 1), Vertex.create("b", proc(), 1)
    dag = DAG.create("d").add_vertex(a).add_vertex(b)
    dag.add_edge(sg_edge(a, b)).add_edge(sg_edge(b, a))
    with pytest.raises(TezUncheckedException, match="cycle"):
        dag.verify()


def test_self_edge_rejected():
    a = Vertex.create("a", proc(), 1)
    dag = DAG.create("d").add_vertex(a)
    dag.add_edge(sg_edge(a, a))
    with pytest.raises(TezUncheckedException, match="self-edge"):
        dag.verify()


def test_duplicate_vertex_rejected():
    dag = DAG.create("d").add_vertex(Vertex.create("a", proc(), 1))
    with pytest.raises(TezUncheckedException, match="duplicate"):
        dag.add_vertex(Vertex.create("a", proc(), 1))


def test_edge_with_foreign_vertex_rejected():
    a = Vertex.create("a", proc(), 1)
    b = Vertex.create("b", proc(), 1)
    dag = DAG.create("d").add_vertex(a)
    with pytest.raises(TezUncheckedException, match="not part of DAG"):
        dag.add_edge(sg_edge(a, b))


def test_disconnected_allowed_with_warning(caplog):
    """Reference parity: DAG.java:574 verify() only rejects cycles/dups —
    disconnected component sets (e.g. tez-tests TwoLevelsFailingDAG) run
    as one DAG.  We warn instead of rejecting."""
    a, b, c, d = (Vertex.create(n, proc(), 1) for n in "abcd")
    dag = DAG.create("d")
    for v in (a, b, c, d):
        dag.add_vertex(v)
    dag.add_edge(sg_edge(a, b)).add_edge(sg_edge(c, d))
    import logging
    with caplog.at_level(logging.WARNING, logger="tez_tpu.dag.dag"):
        order = dag.verify()
    assert len(order) == 4
    assert any("disconnected" in r.message for r in caplog.records)


def test_one_to_one_parallelism_mismatch_rejected():
    a, b = Vertex.create("a", proc(), 2), Vertex.create("b", proc(), 3)
    e = Edge.create(a, b, EdgeProperty.create(
        DataMovementType.ONE_TO_ONE, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create("x:O"), InputDescriptor.create("x:I")))
    dag = DAG.create("d").add_vertex(a).add_vertex(b).add_edge(e)
    with pytest.raises(TezUncheckedException, match="ONE_TO_ONE"):
        dag.verify()


def test_bad_parallelism_rejected():
    with pytest.raises(TezUncheckedException):
        Vertex.create("a", proc(), 0)
    with pytest.raises(TezUncheckedException):
        Vertex.create("a", proc(), -2)


def test_plan_roundtrip():
    a, b = Vertex.create("a", proc(), 2), Vertex.create("b", proc(), 4)
    a.set_conf("tez.runtime.io.sort.mb", 64)
    dag = DAG.create("d").add_vertex(a).add_vertex(b).add_edge(sg_edge(a, b))
    plan = dag.create_dag_plan({"k": "v"})
    plan2 = DAGPlan.deserialize(plan.serialize())
    assert plan2.name == "d"
    assert [v.name for v in plan2.vertices] == ["a", "b"]
    assert plan2.vertex("a").out_edge_ids == ("a->b",)
    assert plan2.vertex("b").in_edge_ids == ("a->b",)
    assert plan2.vertex("a").conf["tez.runtime.io.sort.mb"] == 64
    assert plan2.dag_conf["k"] == "v"
    assert plan2.edge("a->b").edge_property.data_movement_type is \
        DataMovementType.SCATTER_GATHER


def test_vertex_group_plan():
    a, b, c = (Vertex.create(n, proc(), 2) for n in "abc")
    dag = DAG.create("d")
    for v in (a, b, c):
        dag.add_vertex(v)
    g = dag.create_vertex_group("g", [a, b])
    from tez_tpu.dag.dag import GroupInputEdge
    from tez_tpu.common.payload import EntityDescriptor
    ep = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create("x:O"), InputDescriptor.create("x:I"))
    dag.add_group_edge(GroupInputEdge.create(
        g, c, ep, EntityDescriptor.create("x:Merged")))
    plan = dag.create_dag_plan()
    assert len(plan.group_edges) == 1
    # group edge expands to one member edge each
    member_edges = [e for e in plan.edges if "#group:" in e.id]
    assert {e.input_vertex for e in member_edges} == {"a", "b"}
    assert plan.vertex("c").in_edge_ids == tuple(e.id for e in member_edges)


def test_user_payload_roundtrip():
    p = UserPayload.of({"a": 1})
    assert p.load() == {"a": 1}
    assert UserPayload.of(b"raw").load() == b"raw"
    assert UserPayload.of(None).load() is None
    assert pickle.loads(pickle.dumps(p)).load() == {"a": 1}
