"""Native sendfile shuffle server: protocol compatibility with the Python
client, auth, ranges, deletion, and an E2E DAG run over subprocess runners.

Reference role: tez-plugins/tez-aux-services ShuffleHandler.java:159 (native
data server + job-token HMAC + zero-copy file regions + keep-alive).
"""
import os

import numpy as np
import pytest

from tez_tpu.common.security import JobTokenSecretManager
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.shuffle import native_server
from tez_tpu.shuffle.server import FetchSession, ShuffleFetcher
from tez_tpu.shuffle.service import ShuffleDataNotFound

pytestmark = pytest.mark.skipif(
    not native_server.native_available(),
    reason="libtezhost.so unavailable (no C++ toolchain)")


def _make_run(num_partitions=3, rows_per=4, seed=0):
    rng = np.random.default_rng(seed)
    n = num_partitions * rows_per
    keys = [f"k{seed}_{i:03d}".encode() for i in range(n)]
    vals = [rng.integers(0, 256, 8, dtype=np.int64).astype(np.uint8)
            .tobytes() for i in range(n)]
    kb = np.frombuffer(b"".join(keys), dtype=np.uint8)
    ko = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
    vb = np.frombuffer(b"".join(vals), dtype=np.uint8)
    vo = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
    row_index = (np.arange(num_partitions + 1) * rows_per).astype(np.int64)
    return Run(KVBatch(kb, ko, vb, vo), row_index)


@pytest.fixture()
def server(tmp_path):
    secrets = JobTokenSecretManager()
    store = native_server.FileShuffleStore(str(tmp_path / "store"))
    srv = native_server.NativeShuffleServer(secrets, str(tmp_path / "store"))
    yield secrets, store, srv
    srv.stop()


def _batches_equal(a: KVBatch, b: KVBatch) -> bool:
    return (a.num_records == b.num_records and
            np.array_equal(a.key_bytes, b.key_bytes) and
            np.array_equal(a.key_offsets, b.key_offsets) and
            np.array_equal(a.val_bytes, b.val_bytes) and
            np.array_equal(a.val_offsets, b.val_offsets))


def test_fetch_parity_with_python_client(server):
    secrets, store, srv = server
    run = _make_run()
    store.register("v/task0/out", 0, run)
    fetcher = ShuffleFetcher(secrets)
    for p in range(3):
        got = fetcher.fetch("127.0.0.1", srv.port, "v/task0/out", 0, p)
        assert len(got) == 1
        assert _batches_equal(got[0], run.partition(p))
    assert srv.bytes_served > 0


def test_range_fetch_and_keepalive(server):
    secrets, store, srv = server
    store.register("v/t/out", 2, _make_run(seed=1))
    sess = FetchSession(secrets, "127.0.0.1", srv.port)
    try:
        got = sess.fetch_range("v/t/out", 2, 0, 3)   # one request, 3 blobs
        assert [b.num_records for b in got] == [4, 4, 4]
        # keep-alive: same connection serves another fetch
        again = sess.fetch_range("v/t/out", 2, 1, 2)
        assert _batches_equal(again[0], got[1])
    finally:
        sess.close()


def test_auth_rejected(server):
    secrets, store, srv = server
    store.register("v/x/out", 0, _make_run(seed=2))
    wrong = ShuffleFetcher(JobTokenSecretManager())   # different token
    with pytest.raises(PermissionError):
        wrong.fetch("127.0.0.1", srv.port, "v/x/out", 0, 0)
    assert srv.auth_failures >= 1


def test_missing_and_out_of_range(server):
    secrets, store, srv = server
    store.register("v/y/out", 0, _make_run(seed=3))
    fetcher = ShuffleFetcher(secrets)
    with pytest.raises(ShuffleDataNotFound):
        fetcher.fetch("127.0.0.1", srv.port, "v/NOPE/out", 0, 0)
    with pytest.raises(ShuffleDataNotFound):
        fetcher.fetch("127.0.0.1", srv.port, "v/y/out", 0, 7)


def test_store_deletion_tracker(tmp_path):
    store = native_server.FileShuffleStore(str(tmp_path))
    store.register("dagA/v1/t0", 0, _make_run())
    store.register("dagA/v2/t0", 0, _make_run())
    store.register("dagB/v1/t0", 0, _make_run())
    assert store.unregister_prefix("dagA/") == 2
    names = os.listdir(str(tmp_path))
    assert len([n for n in names if n.endswith(".data")]) == 1


def test_e2e_dag_over_native_shuffle(tmp_path, tmp_staging):
    """OrderedWordCount through subprocess runners serving via the native
    server (TEZ_TPU_NATIVE_SHUFFLE_DIR), output verified."""
    import collections
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount

    words = [f"w{i % 40:02d}" for i in range(4000)]
    corpus = tmp_path / "c.txt"
    corpus.write_text(" ".join(words))
    out_dir = str(tmp_path / "out")
    conf = {
        "tez.staging-dir": tmp_staging,
        "tez.runner.mode": "subprocess",
        "tez.am.local.num-containers": 2,
        "tez.am.runner.env": {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "TEZ_TPU_NATIVE_SHUFFLE_DIR": str(tmp_path / "native"),
        },
    }
    with TezClient.create("native-e2e", conf) as client:
        dag = ordered_wordcount.build_dag(
            [str(corpus)], out_dir, tokenizer_parallelism=2,
            summation_parallelism=2, sorter_parallelism=1)
        status = client.submit_dag(dag).wait_for_completion(timeout=120)
        assert status.state is DAGStatusState.SUCCEEDED
    got = {}
    for name in sorted(os.listdir(out_dir)):
        with open(os.path.join(out_dir, name)) as fh:
            for line in fh.read().splitlines():
                if line.strip():
                    w, c = line.rsplit(None, 1)
                    got[w] = int(c)
    assert got == dict(collections.Counter(words))
    # the native store actually served: data files were written
    native_files = []
    for root, _dirs, files in os.walk(str(tmp_path / "native")):
        native_files += [f for f in files if f.endswith(".data")]
    assert native_files, "native store was never written"
