"""Phase-4 tests: unordered shuffle path, broadcast edges, join examples."""
import collections
import os
import random

import numpy as np
import pytest

from tez_tpu.examples import hash_join, sort_merge_join, wordcount
from tez_tpu.library.unordered import UnorderedPartitionedWriter
from tez_tpu.common.counters import TezCounters
from tez_tpu.library.partitioners import HashPartitioner


def test_unordered_writer_partitions_correctly():
    writer = UnorderedPartitionedWriter(4, 1 << 20, TezCounters())
    pairs = [(f"k{i}".encode(), f"v{i}".encode()) for i in range(500)]
    for k, v in pairs:
        writer.write(k, v)
    run = writer.flush()
    hp = HashPartitioner()
    golden = collections.defaultdict(list)
    for k, v in pairs:
        golden[hp.get_partition(k, v, 4)].append((k, v))
    for p in range(4):
        got = list(run.partition(p).iter_pairs())
        assert got == golden.get(p, []), f"partition {p}"


def test_unordered_writer_multi_span_concat():
    writer = UnorderedPartitionedWriter(2, 2048, TezCounters())
    pairs = [(os.urandom(8), os.urandom(6)) for _ in range(800)]
    for k, v in pairs:
        writer.write(k, v)
    run = writer.flush()
    assert writer.num_spills > 1
    total = sum(run.partition_row_count(p) for p in range(2))
    assert total == 800
    # within a partition, spill order then arrival order is preserved
    hp = HashPartitioner()
    for p in range(2):
        got = set(run.partition(p).iter_pairs())
        want = {(k, v) for k, v in pairs if hp.get_partition(k, v, 2) == p}
        assert got == want


def write_corpus(path, num_lines=200, seed=0):
    rng = random.Random(seed)
    words = [f"w{i:02d}" for i in range(40)]
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(words) for _ in range(rng.randrange(1, 8))]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def read_kv_output(out_dir):
    rows = []
    for f in sorted(os.listdir(out_dir)):
        if f.startswith("part-"):
            for line in open(os.path.join(out_dir, f), "rb"):
                k, v = line.rstrip(b"\n").split(b"\t")
                rows.append((k.decode(), v.decode()))
    return rows


def test_wordcount_unordered_e2e(tmp_path):
    corpus = tmp_path / "in.txt"
    golden = write_corpus(str(corpus))
    out = str(tmp_path / "out")
    state = wordcount.run([str(corpus)], out,
                          conf={"tez.staging-dir": str(tmp_path / "s")},
                          tokenizer_parallelism=3, summation_parallelism=2)
    assert state == "SUCCEEDED"
    got = {k: int(v) for k, v in read_kv_output(out)}
    assert got == dict(golden)


def test_hash_join_e2e(tmp_path):
    stream = tmp_path / "stream.txt"
    hashf = tmp_path / "hash.txt"
    stream.write_text("\n".join(f"item{i:03d}" for i in range(300)) + "\n")
    hashf.write_text("\n".join(f"item{i:03d}" for i in range(0, 300, 7)) + "\n")
    out = str(tmp_path / "out")
    state = hash_join.run([str(stream)], [str(hashf)], out,
                          conf={"tez.staging-dir": str(tmp_path / "s")},
                          num_joiners=2)
    assert state == "SUCCEEDED"
    got = sorted(k for k, _ in read_kv_output(out))
    assert got == sorted(f"item{i:03d}" for i in range(0, 300, 7))


def test_sort_merge_join_e2e(tmp_path):
    left = tmp_path / "l.txt"
    right = tmp_path / "r.txt"
    left.write_text("\n".join(f"k{i:03d}" for i in range(0, 200, 2)) + "\n")
    right.write_text("\n".join(f"k{i:03d}" for i in range(0, 200, 3)) + "\n")
    out = str(tmp_path / "out")
    state = sort_merge_join.run([str(left)], [str(right)], out,
                                conf={"tez.staging-dir": str(tmp_path / "s")},
                                num_joiners=2, side_parallelism=2)
    assert state == "SUCCEEDED"
    got = sorted(k for k, _ in read_kv_output(out))
    want = sorted(f"k{i:03d}" for i in range(0, 200, 6))
    assert got == want


def test_unordered_pipelined_no_final_merge(tmp_path):
    """Unordered output with final merge disabled ships per-spill events
    and the streaming consumer still sees every record."""
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.client.dag_client import DAGStatusState
    corpus = tmp_path / "in.txt"
    golden = write_corpus(str(corpus), num_lines=300)
    out = str(tmp_path / "out")
    from tez_tpu.examples import wordcount as wc
    dag = wc.build_dag([str(corpus)], out, tokenizer_parallelism=2,
                       summation_parallelism=2)
    # force tiny buffers + no final merge => many per-spill shipments
    for v in dag.vertices.values():
        v.set_conf("tez.runtime.enable.final-merge.in.output", False)
        v.set_conf("tez.runtime.unordered.output.buffer.size-mb", 1)
    with TezClient.create("t", {"tez.staging-dir":
                                str(tmp_path / "s")}) as c:
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    got = {k: int(v) for k, v in read_kv_output(out)}
    assert got == dict(golden)


def test_filesystem_counters_populated(tmp_path):
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    corpus.write_text("a b c\n" * 100)
    with TezClient.create("t", {"tez.staging-dir":
                                str(tmp_path / "s")}) as c:
        dag = ordered_wordcount.build_dag([str(corpus)],
                                          str(tmp_path / "out"),
                                          tokenizer_parallelism=2)
        status = c.submit_dag(dag).wait_for_completion(timeout=60)
    fs = status.counters.to_dict().get("FileSystemCounter", {})
    assert fs.get("FILE_BYTES_READ", 0) >= 600
    assert fs.get("FILE_BYTES_WRITTEN", 0) > 0
