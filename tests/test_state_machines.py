"""State-machine unit tests driving DAG/Vertex/Task/Attempt directly with a
DrainDispatcher (the reference's TestDAGImpl/TestVertexImpl/TestTaskAttempt
style — no runners, injected events only)."""
import enum
import os
from typing import Any

import pytest

from tez_tpu.am.dag_impl import DAGImpl, DAGState
from tez_tpu.am.events import (DAGEvent, DAGEventType, SchedulerEventType,
                               TaskAttemptEvent, TaskAttemptEventType,
                               TaskEvent, TaskEventType, VertexEvent,
                               VertexEventType)
from tez_tpu.am.history import HistoryEvent, InMemoryHistoryLoggingService
from tez_tpu.am.task_impl import TaskAttemptState, TaskState
from tez_tpu.am.vertex_impl import VertexState
from tez_tpu.common import config as C
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.dispatcher import DrainDispatcher
from tez_tpu.common.ids import DAGId
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.common.payload import InputDescriptor, OutputDescriptor


class FakeAM:
    """Minimal AMContext: everything flows through a DrainDispatcher; no
    runners exist, so attempts only move when the test injects events."""

    def __init__(self):
        self.dispatcher = DrainDispatcher()
        self.conf = C.TezConfiguration({"tez.am.task.max.failed.attempts": 3})
        self.dag_counters = TezCounters()
        self.logging_service = InMemoryHistoryLoggingService()
        self.current_dag = None
        self.finished = []
        self.launch_requests = []
        from tez_tpu.am.events import (DAGEventType, SchedulerEventType,
                                       TaskAttemptEventType, TaskEventType,
                                       VertexEventType)
        d = self.dispatcher
        d.register(DAGEventType, lambda e: self.current_dag.handle(e))
        d.register(VertexEventType, self._vertex)
        d.register(TaskEventType, self._task)
        d.register(TaskAttemptEventType, self._attempt)
        d.register(SchedulerEventType, self._scheduler)

    # handlers
    def _vertex(self, e):
        v = self.current_dag.vertex_by_id(e.vertex_id)
        if v:
            v.handle(e)

    def _task(self, e):
        v = self.current_dag.vertex_by_id(e.task_id.vertex_id)
        t = v.tasks.get(e.task_id.id) if v else None
        if t:
            t.handle(e)

    def _attempt(self, e):
        v = self.current_dag.vertex_by_id(e.attempt_id.vertex_id)
        t = v.tasks.get(e.attempt_id.task_id.id) if v else None
        a = t.attempt(e.attempt_id) if t else None
        if a:
            a.handle(e)

    def _scheduler(self, e):
        if e.event_type is SchedulerEventType.S_TA_LAUNCH_REQUEST:
            self.launch_requests.append(e.attempt_id)

    # AMContext surface
    def dispatch(self, e):
        self.dispatcher.dispatch(e)

    def history(self, e: HistoryEvent):
        self.logging_service.handle(e)

    def history_vertex_configured(self, v):
        pass

    def submit_to_executor(self, fn):
        fn()   # synchronous: commits/initializers run inline

    def total_slots(self):
        return 4

    def ensure_runners(self, backlog):
        pass

    def kill_attempt_in_runner(self, attempt_id):
        pass

    def deliver_processor_events(self, v, events, idx):
        pass

    def on_dag_finished(self, dag, final):
        self.finished.append(final)


def build_dag(am: FakeAM, vertices=(("a", 2), ("b", 2)), edges=(("a", "b"),)):
    dag = DAG.create("t")
    vs = {}
    for name, par in vertices:
        vs[name] = Vertex.create(name, ProcessorDescriptor.create(
            "tez_tpu.library.processors:SimpleProcessor"), par)
        dag.add_vertex(vs[name])
    for s, d in edges:
        dag.add_edge(Edge.create(vs[s], vs[d], EdgeProperty.create(
            DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
            SchedulingType.SEQUENTIAL, OutputDescriptor.create("x:O"),
            InputDescriptor.create("x:I"))))
    plan = dag.create_dag_plan()
    impl = DAGImpl(DAGId("app_0_t", 1), plan, am)
    am.current_dag = impl
    return impl


def start_dag(am, impl):
    am.dispatch(DAGEvent(DAGEventType.DAG_INIT, impl.dag_id))
    am.dispatch(DAGEvent(DAGEventType.DAG_START, impl.dag_id))
    am.dispatcher.drain()


def finish_attempt(am, attempt_id, state="done"):
    am.dispatch(TaskAttemptEvent(
        TaskAttemptEventType.TA_STARTED_REMOTELY, attempt_id,
        container_id="c0"))
    am.dispatcher.drain()
    t = {"done": TaskAttemptEventType.TA_DONE,
         "failed": TaskAttemptEventType.TA_FAILED}[state]
    am.dispatch(TaskAttemptEvent(t, attempt_id, diagnostics="injected"))
    am.dispatcher.drain()


def test_happy_path_to_succeeded():
    am = FakeAM()
    impl = build_dag(am)
    start_dag(am, impl)
    assert impl.state is DAGState.RUNNING
    a = impl.vertex_by_name("a")
    assert a.state is VertexState.RUNNING
    # ImmediateStart on source vertex 'a' launches both tasks
    assert len(am.launch_requests) == 2
    for att in list(am.launch_requests):
        finish_attempt(am, att)
    assert a.state is VertexState.SUCCEEDED
    # slow-start released consumer tasks once sources completed
    b = impl.vertex_by_name("b")
    assert b.state is VertexState.RUNNING
    b_attempts = am.launch_requests[2:]
    assert len(b_attempts) == 2
    for att in b_attempts:
        finish_attempt(am, att)
    assert impl.state is DAGState.SUCCEEDED
    assert am.finished == [DAGState.SUCCEEDED]


def test_task_retries_until_limit_then_fails_dag():
    am = FakeAM()
    impl = build_dag(am, vertices=(("a", 1),), edges=())
    start_dag(am, impl)
    att = am.launch_requests[0]
    task = impl.vertex_by_name("a").tasks[0]
    # max.failed.attempts = 3: two retries after the first failure
    for i in range(3):
        finish_attempt(am, am.launch_requests[-1], state="failed")
        if i < 2:
            assert task.state is TaskState.RUNNING
            assert len(am.launch_requests) == i + 2  # replacement spawned
    assert task.state is TaskState.FAILED
    assert impl.vertex_by_name("a").state is VertexState.FAILED
    assert impl.state is DAGState.FAILED


def test_output_loss_reruns_succeeded_task():
    am = FakeAM()
    impl = build_dag(am)
    start_dag(am, impl)
    for att in list(am.launch_requests):
        finish_attempt(am, att)
    a = impl.vertex_by_name("a")
    assert a.state is VertexState.SUCCEEDED
    # consumer reports the producer's output as lost (local fetch error)
    lost = a.tasks[0].successful_attempt
    am.dispatch(TaskAttemptEvent(
        TaskAttemptEventType.TA_OUTPUT_FAILED, lost,
        consumer_task_index=0, is_local_fetch=True, diagnostics="lost"))
    am.dispatcher.drain()
    assert a.tasks[0].state is TaskState.RUNNING     # re-running
    assert a.state is VertexState.RUNNING            # vertex pulled back
    # the rerun completes; vertex succeeds again
    finish_attempt(am, am.launch_requests[-1])
    assert a.state is VertexState.SUCCEEDED


def test_kill_running_dag():
    am = FakeAM()
    impl = build_dag(am, vertices=(("a", 2),), edges=())
    start_dag(am, impl)
    am.dispatch(DAGEvent(DAGEventType.DAG_KILL, impl.dag_id,
                         diagnostics="test kill"))
    am.dispatcher.drain()
    # attempts were told to die; inject their kill confirmations
    a = impl.vertex_by_name("a")
    for t in a.tasks.values():
        for att in t.live_attempts():
            am.dispatch(TaskAttemptEvent(
                TaskAttemptEventType.TA_KILL_REQUEST, att.attempt_id,
                diagnostics="killed"))
    am.dispatcher.drain()
    assert impl.state is DAGState.KILLED
    assert am.finished == [DAGState.KILLED]


def test_vertex_manager_error_fails_dag():
    am = FakeAM()
    impl = build_dag(am, vertices=(("a", 1),), edges=())
    start_dag(am, impl)
    am.dispatch(VertexEvent(
        VertexEventType.V_MANAGER_USER_CODE_ERROR,
        impl.vertex_by_name("a").vertex_id, diagnostics="boom"))
    am.dispatcher.drain()
    # terminate in-flight attempts
    for t in impl.vertex_by_name("a").tasks.values():
        for att in t.live_attempts():
            am.dispatch(TaskAttemptEvent(
                TaskAttemptEventType.TA_KILL_REQUEST, att.attempt_id))
    am.dispatcher.drain()
    assert impl.vertex_by_name("a").state is VertexState.FAILED
    assert impl.state is DAGState.FAILED
    assert any("boom" in d for d in impl.vertex_by_name("a").diagnostics)


def test_speculative_attempt_loser_killed():
    am = FakeAM()
    impl = build_dag(am, vertices=(("a", 1),), edges=())
    start_dag(am, impl)
    task = impl.vertex_by_name("a").tasks[0]
    first = am.launch_requests[0]
    am.dispatch(TaskAttemptEvent(TaskAttemptEventType.TA_STARTED_REMOTELY,
                                 first, container_id="c0"))
    am.dispatcher.drain()
    am.dispatch(TaskEvent(TaskEventType.T_ADD_SPEC_ATTEMPT, task.task_id))
    am.dispatcher.drain()
    assert len(am.launch_requests) == 2
    second = am.launch_requests[1]
    am.dispatch(TaskAttemptEvent(TaskAttemptEventType.TA_STARTED_REMOTELY,
                                 second, container_id="c1"))
    am.dispatcher.drain()
    # the speculative attempt wins
    am.dispatch(TaskAttemptEvent(TaskAttemptEventType.TA_DONE, second))
    am.dispatcher.drain()
    assert task.state is TaskState.SUCCEEDED
    assert task.successful_attempt == second
    loser = task.attempt(first)
    assert loser.state is TaskAttemptState.KILLED


def test_container_blacklisted_after_repeated_failures():
    """A container accumulating failures stops receiving work (AMNode
    blacklisting analog)."""
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService

    class Ctx:
        def ensure_runners(self, backlog):
            pass

    sched = LocalTaskSchedulerService(Ctx(), 2)
    from tez_tpu.common.ids import DAGId
    vid = DAGId("app_0_bl", 1).vertex(0)
    cid = "container-x"
    for i in range(3):
        att = vid.task(i).attempt(0)
        sched.schedule(att, object(), priority=1)
        got = sched.get_task(cid, timeout=0.1)
        assert got is not None
        sched.deallocate(att, failed=True)
    assert sched.is_blacklisted(cid)
    # further pulls from the bad container are refused...
    att = vid.task(9).attempt(0)
    sched.schedule(att, object(), priority=1)
    assert sched.get_task(cid, timeout=0.1) is None
    # ...but a healthy container still gets the work
    assert sched.get_task("container-y", timeout=0.1) is not None
