"""Service-plugin SPI tests: a custom TaskSchedulerService slots in behind
the same seam a TPU-pod/GKE executor would use (the tez-ext-service-tests
analog, SURVEY.md §4 tier 5), plus memory distributor + prewarm + MRR."""
import collections

import pytest

from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Vertex
from tez_tpu.runtime.memory import MemoryDistributor


class RecordingScheduler(LocalTaskSchedulerService):
    """External-service-style scheduler: observes every allocation."""

    def __init__(self, ctx, num_slots):
        super().__init__(ctx, num_slots)
        self.scheduled = []
        self.deallocated = []

    def schedule(self, attempt_id, task_spec, priority):
        self.scheduled.append((str(attempt_id), priority))
        super().schedule(attempt_id, task_spec, priority)

    def deallocate(self, attempt_id, failed=False):
        self.deallocated.append(str(attempt_id))
        super().deallocate(attempt_id, failed=failed)


def test_custom_task_scheduler_plugin(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging}).start()
    try:
        am = c.framework_client.am
        # swap the scheduler behind the SPI seam before any DAG runs
        rec = RecordingScheduler(am, am.task_scheduler.num_slots)
        am.task_scheduler = rec
        am.scheduler_manager.scheduler = rec
        dag = DAG.create("d").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 3))
        status = c.submit_dag(dag).wait_for_completion(timeout=30)
        assert status.state is DAGStatusState.SUCCEEDED
        assert len(rec.scheduled) == 3
        assert len(rec.deallocated) == 3
        # priorities follow the DAG scheduler's band assignment
        assert all(p == 3 for _, p in rec.scheduled)
    finally:
        c.stop()


def test_prewarm_spins_runners(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 3}).start()
    try:
        c.pre_warm()
        import time
        deadline = time.time() + 5
        while time.time() < deadline and \
                c.framework_client.am.runner_pool.live_count() < 3:
            time.sleep(0.05)
        assert c.framework_client.am.runner_pool.live_count() == 3
    finally:
        c.stop()


def test_weighted_memory_scaling():
    grants = {}
    md = MemoryDistributor(budget_bytes=1000)
    md.budget = 1000  # exact budget for the math below
    md.request_memory(1000, lambda g: grants.__setitem__("sorted", g),
                      component_type="PARTITIONED_SORTED_OUTPUT")
    md.request_memory(1000, lambda g: grants.__setitem__("unsorted", g),
                      component_type="PARTITIONED_UNSORTED_OUTPUT")
    md.make_initial_allocations()
    # 3:1 weights -> sorted gets 750, unsorted 250
    assert grants["sorted"] == 750
    assert grants["unsorted"] == 250
    # under-subscribed: full grants
    md2 = MemoryDistributor(budget_bytes=10_000)
    md2.request_memory(100, lambda g: grants.__setitem__("a", g))
    md2.make_initial_allocations()
    assert grants["a"] == 100


def test_mrr_three_stage(tmp_path, tmp_staging):
    from tez_tpu.examples import mrr
    data = tmp_path / "in.txt"
    rows = {f"k{i:03d}": "v" * (i % 17 + 1) for i in range(120)}
    data.write_text("".join(f"{k}\t{v}\n" for k, v in rows.items()))
    out = str(tmp_path / "out")
    state = mrr.run([str(data)], out,
                    conf={"tez.staging-dir": tmp_staging},
                    map_parallelism=2, r1_parallelism=2, r2_parallelism=1)
    assert state == "SUCCEEDED"
    import os
    got = {}
    order_ok = True
    prev = -1
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f)):
                k, total = line.rstrip("\n").split("\t")
                got[k] = int(total)
                order_ok = order_ok and int(total) >= prev
                prev = int(total)
    assert got == {k: len(v) for k, v in rows.items()}
    assert order_ok


class ExplodingScheduler(LocalTaskSchedulerService):
    """schedule() throws — the *WithErrors service-plugin tier (reference:
    TestExternalTezServicesErrors): plugin errors must fail the DAG, not
    crash the AM process."""

    def schedule(self, attempt_id, task_spec, priority):
        raise RuntimeError("scheduler plugin exploded")


def test_scheduler_plugin_error_contained(tmp_staging):
    from tez_tpu.am.app_master import DAGAppMaster
    from tez_tpu.am.dag_impl import DAGState
    from tez_tpu.common import config as C
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 2})
    am = DAGAppMaster("app_1_boom", conf)
    am.task_scheduler = ExplodingScheduler(am, 2)
    am.scheduler_manager.scheduler = am.task_scheduler
    am.start()
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor", payload={}), 1)
    dag_id = am.submit_dag(DAG.create("boom").add_vertex(v).create_dag_plan())
    final = am.wait_for_dag(dag_id, timeout=30)
    assert final in (DAGState.ERROR, DAGState.FAILED)
    # AM survives: a follow-up healthy submission would still be accepted
    assert am.dispatcher is not None
    am.stop()


def test_session_min_held_containers(tmp_staging):
    """Session mode holds warm runners across DAGs (reference:
    tez.am.session.min.held-containers)."""
    import time
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    c = TezClient.create("held", {
        "tez.staging-dir": tmp_staging,
        "tez.am.local.num-containers": 3,
        "tez.am.session.min.held-containers": 2,
        "tez.am.container.idle.release-timeout-min.millis": 200}).start()
    try:
        am = c.framework_client.am
        dag = DAG.create("d1").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 3))
        assert c.submit_dag(dag).wait_for_completion(
            timeout=30).state is DAGStatusState.SUCCEEDED
        time.sleep(1.2)          # several idle timeouts pass
        held = am.runner_pool.live_count()
        assert held == 2, f"expected 2 held runners, found {held}"
    finally:
        c.stop()


def test_pod_pool_two_host_dag(tmp_staging, tmp_path):
    """External cluster binding (YarnTaskSchedulerService/NMClient analog):
    the AM ACQUIRES runner pods from the pod driver — two pods with
    DISTINCT stable node ids (process-per-host harness on the real plugin
    seam), cross-pod shuffle over TCP, correct output."""
    import collections
    import os
    import random
    from tez_tpu.examples import ordered_wordcount

    corpus = tmp_path / "in.txt"
    rng = random.Random(7)
    golden = collections.Counter()
    with open(corpus, "w") as fh:
        for _ in range(3000):
            w = f"w{rng.randint(0, 200):03d}"
            golden[w] += 1
            fh.write(w + " ")
    out = str(tmp_path / "out")
    conf = {"tez.staging-dir": tmp_staging,
            "tez.runner.mode": "pods",
            "tez.am.pod-pool.max-pods": 2,
            "tez.am.local.num-containers": 2,
            "tez.am.runner.env": {"JAX_PLATFORMS": "cpu"}}
    with TezClient.create("podpool", conf) as c:
        dag = ordered_wordcount.build_dag(
            [str(corpus)], out, tokenizer_parallelism=2,
            summation_parallelism=2, sorter_parallelism=1)
        status = c.submit_dag(dag).wait_for_completion(timeout=120)
        assert status.state is DAGStatusState.SUCCEEDED
        am = c.framework_client.am
        from tez_tpu.am.cluster_binding import (PodPoolRunnerPool,
                                                ProcessPodDriver)
        assert isinstance(am.runner_pool, PodPoolRunnerPool)
        assert isinstance(am.runner_pool.driver, ProcessPodDriver)
        # two distinct simulated hosts did the work
        nodes = {str(a.node_id) for v in am.current_dag.vertices.values()
                 for t in v.tasks.values()
                 for a in t.attempts.values() if a.node_id}
        assert nodes == {"pod-0", "pod-1"}, nodes
    rows = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, cnt = line.rstrip(b"\n").split(b"\t")
                rows[w.decode()] = int(cnt)
    assert rows == dict(golden)


def test_kubernetes_driver_gated_loudly():
    from tez_tpu.am.cluster_binding import KubernetesPodDriver
    with pytest.raises(RuntimeError, match="kubernetes"):
        KubernetesPodDriver()


class _FakePodApiError(Exception):
    """ApiException-shaped (the driver's contract is `.status`)."""

    def __init__(self, status):
        super().__init__(f"fake api error {status}")
        self.status = status


class FakeCoreV1Api:
    """A CoreV1Api-shaped fake whose 'kubelet' EXECUTES the pod manifest's
    container command as a local process — the manifest is validated by
    running it, not by eyeballing.  Records every API call; pod phase
    follows the real process (Pending->Running->Succeeded/Failed)."""

    def __init__(self):
        import threading
        self.calls = []
        self.manifests = {}
        self._procs = {}
        self._lock = threading.Lock()

    def create_namespaced_pod(self, namespace, manifest):
        import os
        import subprocess
        import sys
        name = manifest["metadata"]["name"]
        with self._lock:
            self.calls.append(("create", namespace, name))
            if name in self._procs:
                raise _FakePodApiError(409)
            self.manifests[name] = manifest
            spec = manifest["spec"]["containers"][0]
            cmd = list(spec["command"])
            cmd[0] = sys.executable          # "python" -> this interpreter
            # the downward-API POD_IP substitution a real kubelet performs
            cmd = ["127.0.0.1" if a == "$(POD_IP)" else a for a in cmd]
            env = dict(os.environ)
            for e in spec.get("env", []):
                if "value" in e:
                    env[e["name"]] = e["value"]
            env["POD_IP"] = "127.0.0.1"
            repo_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env["PYTHONPATH"] = repo_root + os.pathsep + \
                env.get("PYTHONPATH", "")
            self._procs[name] = subprocess.Popen(cmd, env=env)

    def read_namespaced_pod(self, name, namespace):
        import types
        with self._lock:
            self.calls.append(("read", namespace, name))
            proc = self._procs.get(name)
        if proc is None:
            raise _FakePodApiError(404)
        rc = proc.poll()
        phase = "Running" if rc is None else \
            ("Succeeded" if rc == 0 else "Failed")
        return types.SimpleNamespace(
            status=types.SimpleNamespace(phase=phase))

    def delete_namespaced_pod(self, name, namespace):
        with self._lock:
            self.calls.append(("delete", namespace, name))
            proc = self._procs.pop(name, None)
        if proc is None:
            raise _FakePodApiError(404)
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except Exception:  # noqa: BLE001
            proc.kill()


_FAKE_K8S_API = FakeCoreV1Api()


class FakeK8sBackedDriver:
    """Zero-arg factory for tez.am.pod-pool.driver.class: the REAL
    KubernetesPodDriver wired to the module's fake API server."""

    def __new__(cls):
        from tez_tpu.am.cluster_binding import KubernetesPodDriver
        return KubernetesPodDriver(namespace="tez-test",
                                   image="tez-tpu-runner:test",
                                   core_api=_FAKE_K8S_API)


def test_kubernetes_driver_two_pod_dag(tmp_staging, tmp_path):
    """VERDICT r3 item 6: the REAL KubernetesPodDriver (manifest build,
    create/read/delete API protocol, phase handling) drives a 2-pod DAG
    end to end against a fake API whose kubelet runs the manifests."""
    import collections
    import os
    import random
    from tez_tpu.examples import ordered_wordcount

    _FAKE_K8S_API.calls.clear()
    _FAKE_K8S_API.manifests.clear()
    corpus = tmp_path / "in.txt"
    rng = random.Random(11)
    golden = collections.Counter()
    with open(corpus, "w") as fh:
        for _ in range(2500):
            w = f"w{rng.randint(0, 150):03d}"
            golden[w] += 1
            fh.write(w + " ")
    out = str(tmp_path / "out")
    conf = {"tez.staging-dir": tmp_staging,
            "tez.runner.mode": "pods",
            "tez.am.pod-pool.driver.class":
                "test_service_plugins:FakeK8sBackedDriver",
            "tez.am.pod-pool.max-pods": 2,
            "tez.am.local.num-containers": 2,
            "tez.am.runner.env": {"JAX_PLATFORMS": "cpu"}}
    with TezClient.create("k8spool", conf) as c:
        dag = ordered_wordcount.build_dag(
            [str(corpus)], out, tokenizer_parallelism=2,
            summation_parallelism=2, sorter_parallelism=1)
        status = c.submit_dag(dag).wait_for_completion(timeout=120)
        assert status.state is DAGStatusState.SUCCEEDED
        from tez_tpu.am.cluster_binding import KubernetesPodDriver
        assert isinstance(c.framework_client.am.runner_pool.driver,
                          KubernetesPodDriver)
    rows = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, cnt = line.rstrip(b"\n").split(b"\t")
                rows[w.decode()] = int(cnt)
    assert rows == dict(golden)
    # the driver spoke the full API protocol to the fake server
    kinds = [k for k, *_ in _FAKE_K8S_API.calls]
    assert kinds.count("create") == 2
    assert "read" in kinds and "delete" in kinds
    assert all(ns == "tez-test" for _, ns, _ in _FAKE_K8S_API.calls)
    # manifests carried the deployment contract the driver promises
    for m in _FAKE_K8S_API.manifests.values():
        spec = m["spec"]["containers"][0]
        assert spec["image"] == "tez-tpu-runner:test"
        assert "--node-id" in spec["command"]
        env_names = {e["name"] for e in spec["env"]}
        assert "TEZ_TPU_JOB_TOKEN" in env_names and "POD_IP" in env_names


def test_kubernetes_driver_poll_phases_and_404(tmp_path):
    """Phase mapping + 404-reap + transient-fault tolerance of poll()."""
    import types
    from tez_tpu.am.cluster_binding import KubernetesPodDriver

    class _Api:
        def __init__(self):
            self.phase = "Pending"
            self.fail = None

        def read_namespaced_pod(self, name, ns):
            if self.fail is not None:
                raise self.fail
            return types.SimpleNamespace(
                status=types.SimpleNamespace(phase=self.phase))

    api = _Api()
    d = KubernetesPodDriver(core_api=api)
    assert d.poll("p") is None            # Pending: still coming up
    api.phase = "Running"
    assert d.poll("p") is None
    api.phase = "Succeeded"
    assert d.poll("p") == 0
    api.phase = "Failed"
    assert d.poll("p") == 1
    api.fail = _FakePodApiError(404)      # evicted outside the pool
    assert d.poll("p") == 1
    api.fail = _FakePodApiError(500)      # transient API fault: keep pod
    assert d.poll("p") is None
