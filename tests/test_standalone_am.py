"""Standalone AM + remote client: the full cross-process control plane
(client -> AM over DAGClientServer, AM -> runners over the umbilical)."""
import os
import subprocess
import sys
import time

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.common.security import JobTokenSecretManager
from tez_tpu.dag.dag import DAG, Vertex


def spawn_am(tmp_path, *extra_args):
    """Launch a standalone AM process; returns (proc, port, token)."""
    token = JobTokenSecretManager().secret.hex()
    env = dict(os.environ)
    env["TEZ_TPU_JOB_TOKEN"] = token
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tez_tpu.am.client_server",
         "--staging-dir", str(tmp_path / "stg"), *extra_args],
        env=env, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline().strip()
    assert line.startswith("READY "), line
    return proc, int(line.split()[1]), token


@pytest.fixture()
def standalone_am(tmp_path):
    proc, port, token = spawn_am(tmp_path, "--num-containers", "2")
    yield port, token
    proc.terminate()
    proc.wait(timeout=10)


def test_remote_client_runs_dag_on_standalone_am(standalone_am):
    port, token = standalone_am
    client = TezClient.create("remote", {
        "tez.framework.mode": "remote",
        "tez.am.address": f"127.0.0.1:{port}",
        "tez.job.token": token,
    }).start()
    try:
        dag = DAG.create("remote-dag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 3))
        status = client.submit_dag(dag).wait_for_completion(timeout=60)
        assert status.state is DAGStatusState.SUCCEEDED
        assert status.vertex_status["v"].progress.succeeded_task_count == 3
    finally:
        client.stop()


def test_minicluster_full_stack(tmp_path):
    """MiniTezCluster analog (SURVEY.md §4 tier 3): standalone AM process,
    runner PROCESSES under it (socket umbilical), per-runner TCP shuffle
    servers with HMAC auth, remote client over the DAGClientServer — a real
    ordered-shuffle wordcount through the full multi-process stack, output
    validated against a host golden."""
    import collections
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    corpus.write_text("apple banana cherry apple banana apple\n" * 120)
    out = str(tmp_path / "out")

    proc, port, token = spawn_am(tmp_path, "--runner-mode", "subprocess",
                                 "--num-containers", "3")
    try:
        client = TezClient.create("mini", {
            "tez.framework.mode": "remote",
            "tez.am.address": f"127.0.0.1:{port}",
            "tez.job.token": token,
        }).start()
        try:
            dag = ordered_wordcount.build_dag(
                [str(corpus)], out, tokenizer_parallelism=2,
                summation_parallelism=2)
            status = client.submit_dag(dag).wait_for_completion(timeout=120)
            assert status.state is DAGStatusState.SUCCEEDED
        finally:
            client.stop()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    got = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, c = line.rstrip(b"\n").rsplit(b"\t", 1)
                got[w.decode()] = int(c)
    golden = collections.Counter(
        w for l in open(corpus) for w in l.split())
    assert got == dict(golden)


def test_remote_client_bad_token_rejected(standalone_am):
    port, _ = standalone_am
    bad = TezClient.create("bad", {
        "tez.framework.mode": "remote",
        "tez.am.address": f"127.0.0.1:{port}",
        "tez.job.token": JobTokenSecretManager().secret.hex(),
    })
    with pytest.raises(PermissionError):
        bad.start()


def test_remote_kill(standalone_am):
    port, token = standalone_am
    client = TezClient.create("remote", {
        "tez.framework.mode": "remote",
        "tez.am.address": f"127.0.0.1:{port}",
        "tez.job.token": token,
    }).start()
    try:
        dag = DAG.create("tokill").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 30_000}), 2))
        dc = client.submit_dag(dag)
        time.sleep(0.5)
        dc.try_kill_dag("remote kill")
        status = dc.wait_for_completion(timeout=30)
        assert status.state is DAGStatusState.KILLED
    finally:
        client.stop()


def test_remote_stop_synchronous_reaches_close():
    """Synchronous stop() (tez.client.asynchronous-stop=False) must poll
    the (host, port) captured at start() — not re-read tez.am.address,
    which may be cleared or portless by then — and must reach am.close()
    even when no address is available at all."""
    import socket

    from tez_tpu.client.remote import RemoteFrameworkClient
    from tez_tpu.common import config as C

    class FakeAM:
        def __init__(self):
            self.closed = False
            self.shutdowns = 0

        def shutdown_session(self):
            self.shutdowns += 1

        def close(self):
            self.closed = True

    # grab a port with nothing listening: the liveness poll must exit on
    # the first refused connect, not wait out the 15s default
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    conf = C.TezConfiguration({
        "tez.session.mode": True,
        "tez.client.asynchronous-stop": False,
        "tez.am.address": "cleared-no-port",   # unparseable at stop time
    })
    c = RemoteFrameworkClient(conf)
    am = FakeAM()
    c.am = am
    c._am_addr = ("127.0.0.1", port)   # as captured by start()
    t0 = time.time()
    c.stop()
    assert time.time() - t0 < 5.0
    assert am.shutdowns == 1 and am.closed and c.am is None

    # never start()ed AND the conf address is portless: the guarded
    # re-parse degrades to skipping the poll — close() still runs
    c2 = RemoteFrameworkClient(conf)
    am2 = FakeAM()
    c2.am = am2
    c2.stop()
    assert am2.shutdowns == 1 and am2.closed and c2.am is None
