"""Standalone AM + remote client: the full cross-process control plane
(client -> AM over DAGClientServer, AM -> runners over the umbilical)."""
import os
import subprocess
import sys
import time

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.common.security import JobTokenSecretManager
from tez_tpu.dag.dag import DAG, Vertex


@pytest.fixture()
def standalone_am(tmp_path):
    token = JobTokenSecretManager().secret.hex()
    env = dict(os.environ)
    env["TEZ_TPU_JOB_TOKEN"] = token
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tez_tpu.am.client_server",
         "--staging-dir", str(tmp_path / "stg"),
         "--num-containers", "2"],
        env=env, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline().strip()
    assert line.startswith("READY "), line
    port = int(line.split()[1])
    yield port, token
    proc.terminate()
    proc.wait(timeout=10)


def test_remote_client_runs_dag_on_standalone_am(standalone_am):
    port, token = standalone_am
    client = TezClient.create("remote", {
        "tez.framework.mode": "remote",
        "tez.am.address": f"127.0.0.1:{port}",
        "tez.job.token": token,
    }).start()
    try:
        dag = DAG.create("remote-dag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 3))
        status = client.submit_dag(dag).wait_for_completion(timeout=60)
        assert status.state is DAGStatusState.SUCCEEDED
        assert status.vertex_status["v"].progress.succeeded_task_count == 3
    finally:
        client.stop()


def test_remote_client_bad_token_rejected(standalone_am):
    port, _ = standalone_am
    bad = TezClient.create("bad", {
        "tez.framework.mode": "remote",
        "tez.am.address": f"127.0.0.1:{port}",
        "tez.job.token": JobTokenSecretManager().secret.hex(),
    })
    with pytest.raises(PermissionError):
        bad.start()


def test_remote_kill(standalone_am):
    port, token = standalone_am
    client = TezClient.create("remote", {
        "tez.framework.mode": "remote",
        "tez.am.address": f"127.0.0.1:{port}",
        "tez.job.token": token,
    }).start()
    try:
        dag = DAG.create("tokill").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 30_000}), 2))
        dc = client.submit_dag(dag)
        time.sleep(0.5)
        dc.try_kill_dag("remote kill")
        status = dc.wait_for_completion(timeout=30)
        assert status.state is DAGStatusState.KILLED
    finally:
        client.stop()
