"""Phase-6 tests: fetch-failure -> producer rerun, AM recovery, heartbeat
liveness, deletion tracking (TestFaultTolerance / TestAMRecovery analogs)."""
import os
import time

import pytest

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.am.dag_impl import DAGState
from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import config as C
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.library.processors import SimpleProcessor
from tez_tpu.ops.serde import VarLongSerde


@pytest.fixture()
def client(tmp_staging):
    c = TezClient.create("t", {"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 4}).start()
    yield c
    c.stop()


class EmitProcessor(SimpleProcessor):
    """Writes (word, 1) records downstream."""

    def run(self, inputs, outputs):
        writer = outputs["consumer"].get_writer()
        for i in range(50):
            writer.write(f"key{i:03d}".encode(), 1)


class CountProcessor(SimpleProcessor):
    """Counts groups from the sorted input; records total in registry."""

    def run(self, inputs, outputs):
        reader = inputs["producer"].get_reader()
        total = 0
        for _k, vs in reader:
            total += sum(vs)
        self.context.object_registry.add("session", "observed_total", total)


def test_fetch_failure_reruns_producer(client):
    """InputReadErrorEvent fails the producer attempt; the task re-runs and
    the consumer completes with correct data (SURVEY.md §3.5)."""
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        EmitProcessor), 2)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        CountProcessor), 1)
    conf = {"tez.runtime.key.class": "bytes",
            "tez.runtime.value.class": "long"}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf),
        InputDescriptor.create(
            "tez_tpu.library.test_components:FlakyFetchOrderedInput",
            payload={**conf, "failing_fetch_task_indices": [0]}))
    dag = DAG.create("fetchfail").add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    status = client.submit_dag(dag).wait_for_completion(timeout=60)
    assert status.state is DAGStatusState.SUCCEEDED
    am = client.framework_client.am
    # a producer task must have run 2 attempts (one failed for output loss)
    d = am.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) >= 4  # 2 producers + rerun + consumer


def _mini_plan(name="recov", sleep_ms=1):
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": sleep_ms}), 2)
    return DAG.create(name).add_vertex(v).create_dag_plan()


def test_am_recovery_resubmits_inflight_dag(tmp_staging):
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 2})
    am1 = DAGAppMaster("app_1_recov", conf, attempt=1)
    am1.start()
    dag_id = am1.submit_dag(_mini_plan(sleep_ms=20_000))
    time.sleep(0.5)          # DAG running, tasks sleeping
    am1.stop()               # "crash": journal survives, work incomplete

    am2 = DAGAppMaster("app_1_recov", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    assert str(recovered) == str(dag_id)
    # the recovered DAG re-runs; make it finish fast by killing the sleepers?
    # no — plan had 20s sleeps; instead just verify it is RUNNING again
    status = am2.dag_status(recovered)
    assert status["state"] in ("RUNNING", "INITED", "NEW")
    am2.kill_dag(recovered)
    assert am2.wait_for_dag(recovered, timeout=30) is DAGState.KILLED
    am2.stop()


class GatedCountProcessor(SimpleProcessor):
    """Blocks until a sentinel file appears, then counts the sorted input and
    writes the total to a result file (payload: gate_path, result_path)."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        while not os.path.exists(payload["gate_path"]):
            time.sleep(0.05)
        reader = inputs["producer"].get_reader()
        total = sum(sum(vs) for _k, vs in reader)
        with open(payload["result_path"], "w") as fh:
            fh.write(str(total))


def test_am_recovery_short_circuits_succeeded_tasks(tmp_staging, tmp_path):
    """Producer vertex completes before the AM crash; after recovery its
    tasks are restored from the journal (not re-run) and their replayed
    DataMovementEvents feed the consumer, which produces correct data
    (reference: RecoveryParser completed-work short-circuit, SURVEY.md §5.4)."""
    gate = str(tmp_path / "gate")
    result = str(tmp_path / "result")
    conf_kv = {"tez.runtime.key.class": "bytes",
               "tez.runtime.value.class": "long"}
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        EmitProcessor), 2)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        GatedCountProcessor,
        payload={"gate_path": gate, "result_path": result}), 1)
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf_kv),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf_kv))
    dag = DAG.create("recov_sc").add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    plan = dag.create_dag_plan()

    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 3})
    am1 = DAGAppMaster("app_1_recsc", conf, attempt=1)
    am1.start()
    am1.submit_dag(plan)
    deadline = time.time() + 60
    while time.time() < deadline:
        st = am1.current_dag.status_dict()
        if st["vertices"].get("producer", {}).get("state") == "SUCCEEDED":
            break
        time.sleep(0.1)
    else:
        pytest.fail("producer vertex never finished")
    am1.stop()            # crash while the consumer is gated

    am2 = DAGAppMaster("app_1_recsc", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    open(gate, "w").close()          # release the consumer
    assert am2.wait_for_dag(recovered, timeout=60) is DAGState.SUCCEEDED
    with open(result) as fh:
        assert int(fh.read()) == 100  # 2 producers x 50 records x value 1
    # Producer tasks were restored, not re-launched: only the consumer ran.
    d = am2.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) == 1
    assert d.get("NUM_SUCCEEDED_TASKS", 0) == 3
    am2.stop()


def test_am_recovery_finished_dag_untouched(tmp_staging):
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging})
    am1 = DAGAppMaster("app_1_fin", conf, attempt=1)
    am1.start()
    dag_id = am1.submit_dag(_mini_plan())
    assert am1.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am1.stop()
    am2 = DAGAppMaster("app_1_fin", conf, attempt=2)
    am2.start()
    assert am2.recover_and_resume() is None
    am2.stop()


def test_am_recovery_commit_in_flight_fails_dag(tmp_staging):
    """Commit started but no completion record, under the strict
    policy="fail": DAG FAILED on recovery (reference: RecoveryParser commit
    rules, SURVEY.md §5.4; the default "resume" policy instead re-runs the
    idempotent committers — see the resume tests below)."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.commit.recovery.policy": "fail"})
    am1 = DAGAppMaster("app_1_cif", conf, attempt=1)
    am1.start()
    plan = _mini_plan()
    # forge a journal: DAG submitted + commit started, then crash
    am1.history(HistoryEvent(
        HistoryEventType.DAG_SUBMITTED, dag_id="dag_1_cif_7",
        data={"dag_name": plan.name, "plan": plan.serialize().hex()}))
    am1.history(HistoryEvent(
        HistoryEventType.DAG_COMMIT_STARTED, dag_id="dag_1_cif_7"))
    am1.stop()
    am2 = DAGAppMaster("app_1_cif", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    assert am2.completed_dags["dag_1_cif_7"] is DAGState.FAILED
    # the rollback decision itself is journaled (ledger ABORTED record)
    assert am2.logging_service.of_type(HistoryEventType.DAG_COMMIT_ABORTED)
    am2.stop()


def _forge_commit_journal(am, plan, dag_id_str, *ledger_events):
    """DAG_SUBMITTED (with serialized plan) + the given ledger records."""
    am.history(HistoryEvent(
        HistoryEventType.DAG_SUBMITTED, dag_id=dag_id_str,
        data={"dag_name": plan.name, "plan": plan.serialize().hex()}))
    for ev in ledger_events:
        am.history(HistoryEvent(ev, dag_id=dag_id_str))


def _sink_plan(name, out_dir):
    """Single-vertex plan with a FileOutput data sink (so recovery has a
    real committer to re-instantiate)."""
    from tez_tpu.common.payload import OutputCommitterDescriptor
    from tez_tpu.dag.dag import DataSinkDescriptor
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 1)
    v.add_data_sink("sink", DataSinkDescriptor.create(
        OutputDescriptor.create("tez_tpu.io.file_output:FileOutput",
                                payload={"path": out_dir,
                                         "key_serde": "text",
                                         "value_serde": "text"}),
        OutputCommitterDescriptor.create(
            "tez_tpu.io.file_output:FileOutputCommitter",
            payload={"path": out_dir})))
    return DAG.create(name).add_vertex(v).create_dag_plan()


def test_am_recovery_commit_finished_rolls_forward(tmp_staging):
    """A journaled DAG_COMMIT_FINISHED means every committer ran to
    completion before the crash: recovery rolls the DAG forward to
    SUCCEEDED without touching the committers again."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging})
    am1 = DAGAppMaster("app_1_cfin", conf, attempt=1)
    am1.start()
    _forge_commit_journal(am1, _mini_plan(), "dag_1_cfin_3",
                          HistoryEventType.DAG_COMMIT_STARTED,
                          HistoryEventType.DAG_COMMIT_FINISHED)
    am1.stop()
    am2 = DAGAppMaster("app_1_cfin", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    assert am2.completed_dags["dag_1_cfin_3"] is DAGState.SUCCEEDED
    am2.stop()


def test_am_recovery_commit_aborted_fails_dag(tmp_staging, tmp_path):
    """A journaled DAG_COMMIT_ABORTED is a recorded rollback decision:
    recovery re-runs the idempotent aborts and lands on FAILED, un-publishing
    anything a partial commit left behind."""
    out_dir = str(tmp_path / "out")
    plan = _sink_plan("cabort", out_dir)
    # a partially-committed output: one published part file, manifest inside
    # the tmp tree recording it, staged file still waiting
    os.makedirs(os.path.join(out_dir, "_temporary", "committed"))
    with open(os.path.join(out_dir, "part-00000"), "w") as fh:
        fh.write("published-by-crashed-attempt")
    with open(os.path.join(out_dir, "_temporary", "_publish_manifest"),
              "w") as fh:
        fh.write("part-00000\n")
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging})
    am1 = DAGAppMaster("app_1_cab", conf, attempt=1)
    am1.start()
    _forge_commit_journal(am1, plan, "dag_1_cab_4",
                          HistoryEventType.DAG_COMMIT_STARTED,
                          HistoryEventType.DAG_COMMIT_ABORTED)
    am1.stop()
    am2 = DAGAppMaster("app_1_cab", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    assert am2.completed_dags["dag_1_cab_4"] is DAGState.FAILED
    am2.stop()
    # rollback un-published the partial commit and removed the tmp tree
    assert not os.path.exists(os.path.join(out_dir, "part-00000"))
    assert not os.path.exists(os.path.join(out_dir, "_temporary"))
    assert not os.path.exists(os.path.join(out_dir, "_SUCCESS"))


def test_am_recovery_commit_in_flight_resumes(tmp_staging, tmp_path):
    """Default policy="resume": an open ledger (COMMIT_STARTED, no
    completion record) re-runs ONLY the idempotent committers — staged files
    are published, the DAG rolls forward to SUCCEEDED, and the resumed
    commit closes the ledger with DAG_COMMIT_FINISHED."""
    out_dir = str(tmp_path / "out")
    plan = _sink_plan("cres", out_dir)
    # crash state: one part published (and in the manifest), one still staged
    committed = os.path.join(out_dir, "_temporary", "committed")
    os.makedirs(committed)
    with open(os.path.join(out_dir, "part-00000"), "w") as fh:
        fh.write("already-published\n")
    with open(os.path.join(out_dir, "_temporary", "_publish_manifest"),
              "w") as fh:
        fh.write("part-00000\n")
    with open(os.path.join(committed, "part-00001"), "w") as fh:
        fh.write("still-staged\n")
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging})
    am1 = DAGAppMaster("app_1_cres", conf, attempt=1)
    am1.start()
    _forge_commit_journal(am1, plan, "dag_1_cres_5",
                          HistoryEventType.DAG_COMMIT_STARTED)
    am1.stop()
    am2 = DAGAppMaster("app_1_cres", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    assert am2.completed_dags["dag_1_cres_5"] is DAGState.SUCCEEDED
    assert am2.logging_service.of_type(HistoryEventType.DAG_COMMIT_FINISHED)
    am2.stop()
    # both parts published exactly once, marker written, tmp tree gone
    with open(os.path.join(out_dir, "part-00000")) as fh:
        assert fh.read() == "already-published\n"
    with open(os.path.join(out_dir, "part-00001")) as fh:
        assert fh.read() == "still-staged\n"
    assert os.path.exists(os.path.join(out_dir, "_SUCCESS"))
    assert not os.path.exists(os.path.join(out_dir, "_temporary"))


def test_stale_epoch_fenced_at_am_seams(tmp_staging):
    """Zombie fencing: once attempt 2 registers its epoch, messages carrying
    attempt 1's epoch are rejected at the umbilical seams — can_commit
    arbitration denies, heartbeat orders the runner to die — and attempt
    1's own communicator self-fences."""
    from tez_tpu.am.task_comm import HeartbeatRequest
    from tez_tpu.common.ids import DAGId
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.max.app.attempts": 3})
    am1 = DAGAppMaster("app_1_fence", conf, attempt=1)
    am2 = DAGAppMaster("app_1_fence", conf, attempt=2)   # supersedes am1
    attempt_id = DAGId("app_1_fence", 1).vertex(0).task(0).attempt(0)
    # a delayed pre-restart can_commit reaching the NEW AM: epoch 1 < 2.
    # (fencing short-circuits before any DAG lookup — am2 runs no DAG.)
    assert am2.task_comm.can_commit(attempt_id, epoch=1) is False
    resp = am2.task_comm.heartbeat(HeartbeatRequest(attempt_id, [], epoch=1))
    assert resp.should_die
    # the OLD AM's communicator knows it was superseded and refuses
    # arbitration for everyone, current-epoch callers included
    assert am1.task_comm.can_commit(attempt_id, epoch=1) is False
    resp = am1.task_comm.heartbeat(HeartbeatRequest(attempt_id, [], epoch=1))
    assert resp.should_die
    am1.stop()
    am2.stop()


def test_stale_epoch_fenced_at_shuffle_register(tmp_staging):
    """A zombie producer task (spec stamped with the pre-crash epoch) must
    not register shuffle output after the AM restarts."""
    from tez_tpu.common.epoch import EpochFencedError
    from tez_tpu.library.outputs import _empty_run
    from tez_tpu.shuffle.service import local_shuffle_service
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.max.app.attempts": 3})
    am1 = DAGAppMaster("app_1_shf", conf, attempt=1)
    service = local_shuffle_service()
    run = _empty_run(1)
    # current epoch registers fine (and pre-crash data stays fetchable)
    service.register("dag_1_shf_1/zombie_probe/a", -1, run,
                     epoch=1, app_id="app_1_shf")
    am2 = DAGAppMaster("app_1_shf", conf, attempt=2)
    with pytest.raises(EpochFencedError):
        service.register("dag_1_shf_1/zombie_probe/b", -1, run,
                         epoch=1, app_id="app_1_shf")
    # unstamped (legacy) and current-epoch registrations still work
    service.register("dag_1_shf_1/zombie_probe/c", -1, run)
    service.register("dag_1_shf_1/zombie_probe/d", -1, run,
                     epoch=2, app_id="app_1_shf")
    service.unregister_prefix("dag_1_shf_1/")
    am1.stop()
    am2.stop()


def test_heartbeat_timeout_fails_attempt(tmp_staging):
    """An attempt whose heartbeats stop is timed out and retried."""
    conf = C.TezConfiguration({
        "tez.staging-dir": tmp_staging,
        "tez.task.heartbeat.timeout-ms": 300,
        "tez.am.local.num-containers": 2})
    am = DAGAppMaster("app_1_hb", conf)
    am.heartbeat_monitor.check_interval = 0.1
    am.start()
    # a "task" session that never heartbeats: forge one via the umbilical
    from tez_tpu.am.task_comm import _AttemptSession
    plan = _mini_plan(sleep_ms=1)
    dag_id = am.submit_dag(plan)
    assert am.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am.stop()


def test_shuffle_data_released_after_dag(client, tmp_path):
    from tez_tpu.shuffle.service import local_shuffle_service
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    corpus.write_text("a b c\n" * 50)
    state = ordered_wordcount.run(
        [str(corpus)], str(tmp_path / "out"),
        conf={"tez.staging-dir": str(tmp_path / "s")},
        tokenizer_parallelism=2)
    assert state == "SUCCEEDED"
    count, nbytes = local_shuffle_service().stats()
    assert count == 0, f"{count} shuffle outputs leaked"


def test_node_tracker_blacklist_and_ignore_threshold():
    """AMNodeImpl semantics: per-node failure accumulation blacklists; when
    too much of the fleet is blacklisted, blacklists are ignored."""
    from tez_tpu.am.node_map import AMNodeTracker, NodeState
    conf = C.TezConfiguration({"tez.am.maxtaskfailures.per.node": 2})
    t = AMNodeTracker(conf)
    for n in ("n0", "n1", "n2", "n3"):
        t.node_seen(n)
    t.on_attempt_failed("n0")
    assert t.is_usable("n0")                    # below threshold
    t.on_attempt_failed("n0")
    assert not t.is_usable("n0")                # blacklisted (1/4 <= 33%)
    assert t.state("n0") is NodeState.BLACKLISTED
    t.on_attempt_failed("n1")
    t.on_attempt_failed("n1")
    # 2/4 = 50% > 33%: blacklisting ignored, both FORCED_ACTIVE
    assert t.is_usable("n0") and t.is_usable("n1")
    assert t.state("n0") is NodeState.FORCED_ACTIVE
    assert t.snapshot()["n1"]["failures"] == 2


def test_blacklisted_node_starved_but_single_node_survives(tmp_staging):
    """A single-node app whose node crosses the failure threshold keeps
    running via the ignore threshold (1/1 blacklisted > 33%) — blacklisting
    must never deadlock the app against itself."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.maxtaskfailures.per.node": 2,
                               "tez.am.task.max.failed.attempts": 4,
                               "tez.am.local.num-containers": 2})
    am = DAGAppMaster("app_1_node", conf)
    am.start()
    flaky = Vertex.create("flaky", ProcessorDescriptor.create(
        "tez_tpu.library.test_components:TestProcessor",
        payload={"do_fail": True, "failing_task_indices": [0],
                 "failing_upto_attempt": 2}), 1)
    plan = DAG.create("noded").add_vertex(flaky).create_dag_plan()
    dag_id = am.submit_dag(plan)
    assert am.wait_for_dag(dag_id, timeout=60) is DAGState.SUCCEEDED
    # 3 failures on the only node: it WAS blacklisted, then forced active
    from tez_tpu.am.node_map import NodeState
    assert am.node_tracker.state("local-0") is NodeState.FORCED_ACTIVE
    am.stop()


def test_scheduler_preempts_lower_priority(tmp_staging):
    """Slots full of low-priority work + a high-priority request waiting ->
    the lowest-priority running attempt is killed (YarnTaskSchedulerService
    preemption semantics; killed attempts respawn)."""
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    from tez_tpu.common.ids import DAGId

    class _Ctx:
        conf = C.TezConfiguration({})
        dispatched = []

        def ensure_runners(self, backlog):
            pass

        def dispatch(self, event):
            self.dispatched.append(event)

    ctx = _Ctx()
    sched = LocalTaskSchedulerService(ctx, num_slots=2)
    vid = DAGId("app_1_p", 1).vertex(0)
    low_a, low_b = vid.task(0).attempt(0), vid.task(1).attempt(0)
    high = DAGId("app_1_p", 1).vertex(1).task(0).attempt(0)
    sched.schedule(low_a, "spec-a", priority=20)
    sched.schedule(low_b, "spec-b", priority=20)
    assert sched.get_task("c0", timeout=0.1) == "spec-a"
    assert sched.get_task("c1", timeout=0.1) == "spec-b"
    assert not ctx.dispatched        # nothing waiting: no preemption
    sched.schedule(high, "spec-high", priority=5)
    kills = [e for e in ctx.dispatched
             if getattr(e, "event_type", None) is not None
             and e.event_type.name == "TA_KILL_REQUEST"]
    assert len(kills) == 1           # capped at 10% of 2 slots -> 1
    assert kills[0].attempt_id in (low_a, low_b)
    assert "preempted" in kills[0].diagnostics


def test_preemption_breaks_priority_inversion_deadlock(tmp_staging):
    """All slots held by consumers blocked on data a failed producer must
    re-create: without preemption this deadlocks; with it, one consumer is
    preempted, the producer re-runs, and the DAG completes correctly."""
    c = TezClient.create("pre", {"tez.staging-dir": tmp_staging,
                                 "tez.am.local.num-containers": 2}).start()
    try:
        producer = Vertex.create("producer", ProcessorDescriptor.create(
            EmitProcessor), 1)
        consumer = Vertex.create("consumer", ProcessorDescriptor.create(
            CountProcessor), 2)
        conf = {"tez.runtime.key.class": "bytes",
                "tez.runtime.value.class": "long"}
        prop = EdgeProperty.create(
            DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
            SchedulingType.SEQUENTIAL,
            OutputDescriptor.create(
                "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
                payload=conf),
            InputDescriptor.create(
                "tez_tpu.library.test_components:FlakyFetchOrderedInput",
                # BOTH consumers lose their fetch (so neither can finish
                # without the producer re-running) and hold the report back
                # until both occupy the two slots -> the producer rerun has
                # no slot and the schedule deadlocks without preemption
                payload={**conf, "failing_fetch_task_indices": [0, 1],
                         "inject_delay_ms": 1500}))
        dag = DAG.create("inversion").add_vertex(producer).add_vertex(consumer)
        dag.add_edge(Edge.create(producer, consumer, prop))
        status = c.submit_dag(dag).wait_for_completion(timeout=90)
        assert status.state is DAGStatusState.SUCCEEDED
        am = c.framework_client.am
        d = am.dag_counters.to_dict().get("DAGCounter", {})
        # producer + its rerun + 2 consumers + the preempted consumer's
        # respawn (a preempted ATTEMPT respawns; the task is never KILLED)
        assert d.get("TOTAL_LAUNCHED_TASKS", 0) >= 5
    finally:
        c.stop()


def test_am_recovery_idempotent_across_three_attempts(tmp_staging, tmp_path):
    """Crash -> recover -> crash AGAIN -> recover: attempt 3 still
    short-circuits the producers because the recovered attempt re-journals
    its TASK_FINISHED + generated events (recovery is idempotent)."""
    gate = str(tmp_path / "gate")
    result = str(tmp_path / "result")
    conf_kv = {"tez.runtime.key.class": "bytes",
               "tez.runtime.value.class": "long"}
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        EmitProcessor), 2)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        GatedCountProcessor,
        payload={"gate_path": gate, "result_path": result}), 1)
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf_kv),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf_kv))
    dag = DAG.create("recov3").add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    plan = dag.create_dag_plan()
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               # three attempts on purpose: raise the
                               # restart budget above the default of 2
                               "tez.am.max.app.attempts": 3,
                               "tez.am.local.num-containers": 3})

    am1 = DAGAppMaster("app_1_r3", conf, attempt=1)
    am1.start()
    am1.submit_dag(plan)
    deadline = time.time() + 60
    while time.time() < deadline:
        st = am1.current_dag.status_dict()
        if st["vertices"].get("producer", {}).get("state") == "SUCCEEDED":
            break
        time.sleep(0.1)
    else:
        pytest.fail("producer never finished in attempt 1")
    am1.stop()

    am2 = DAGAppMaster("app_1_r3", conf, attempt=2)
    am2.start()
    assert am2.recover_and_resume() is not None
    deadline = time.time() + 30
    while time.time() < deadline:   # wait for producers to be restored
        st = am2.current_dag.status_dict()
        if st["vertices"].get("producer", {}).get("state") == "SUCCEEDED":
            break
        time.sleep(0.1)
    am2.stop()                       # crash again, consumer still gated

    am3 = DAGAppMaster("app_1_r3", conf, attempt=3)
    am3.start()
    recovered = am3.recover_and_resume()
    assert recovered is not None
    open(gate, "w").close()
    assert am3.wait_for_dag(recovered, timeout=60) is DAGState.SUCCEEDED
    assert int(open(result).read()) == 100
    d = am3.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) == 1   # consumer only
    am3.stop()


def test_recovery_journal_pickle_gate():
    """Pickle-encoded journal payloads are rejected during replay unless
    tez.dag.recovery.trusted-staging opts in (the journal lives in a shared
    staging dir; unpickling it is code execution)."""
    import pytest as _pytest
    from tez_tpu.am.recovery import (UntrustedJournalPayload, event_from_wire,
                                     event_to_wire)

    wire = event_to_wire(_CarrierEvent())
    assert wire["t"] == "pickle"
    with _pytest.raises(UntrustedJournalPayload):
        event_from_wire(wire)
    assert isinstance(event_from_wire(wire, allow_pickle=True),
                      _CarrierEvent)

    from tez_tpu.api.events import DataMovementEvent
    dme_wire = event_to_wire(DataMovementEvent(source_index=1,
                                               user_payload=b"x", version=0))
    ev = event_from_wire(dme_wire)     # typed kinds replay without opt-in
    assert ev.source_index == 1 and ev.user_payload == b"x"


class _CarrierEvent:
    """Not a DME/CDME: forces the pickle wire kind (module-level so the
    allow_pickle=True leg can actually unpickle it)."""


def test_am_recovery_restores_reconfigured_vertex(tmp_staging, tmp_path):
    """A consumer shrunk by auto-parallelism before the crash keeps its
    shrunk parallelism after recovery (the journaled reconfiguration is
    re-applied, the manager does not re-decide) and the producer's completed
    tasks are restored, not re-run (reference: RecoveryParser.java:658
    restoring VertexConfigurationDoneEvent)."""
    from tez_tpu.common.payload import VertexManagerPluginDescriptor
    gate = str(tmp_path / "gate")
    result = str(tmp_path / "result")
    conf_kv = {"tez.runtime.key.class": "bytes",
               "tez.runtime.value.class": "long"}
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        EmitProcessor), 2)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        GatedCountProcessor,
        payload={"gate_path": gate, "result_path": result}), 6)
    consumer.set_vertex_manager_plugin(VertexManagerPluginDescriptor.create(
        "tez_tpu.library.vertex_managers:ShuffleVertexManager",
        payload={"auto_parallel": True,
                 "desired_task_input_size": 1 << 30,
                 "min_task_parallelism": 1,
                 "min_fraction": 1.0, "max_fraction": 1.0}))
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=conf_kv),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=conf_kv))
    dag = DAG.create("recov_reconf").add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    plan = dag.create_dag_plan()

    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 3})
    am1 = DAGAppMaster("app_1_reconf", conf, attempt=1)
    am1.start()
    am1.submit_dag(plan)
    deadline = time.time() + 60
    while time.time() < deadline:
        st = am1.current_dag.status_dict()
        cons = st["vertices"].get("consumer", {})
        if st["vertices"].get("producer", {}).get("state") == "SUCCEEDED" \
                and cons.get("total_tasks") == 1:
            break
        time.sleep(0.1)
    else:
        pytest.fail("producer never finished / consumer never shrank: "
                    f"{am1.current_dag.status_dict()}")
    am1.stop()            # crash: consumer reconfigured 6->1, gated

    am2 = DAGAppMaster("app_1_reconf", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    # the reconfiguration was RESTORED, not re-decided: 1 task as soon as
    # the vertex exists (restore happens inside vertex init, before any
    # source-completion stats could drive a fresh decision)
    deadline = time.time() + 30
    while time.time() < deadline:
        st = am2.current_dag.status_dict()
        cons = st["vertices"].get("consumer")
        if cons is not None and cons.get("state") not in ("NEW",):
            break
        time.sleep(0.05)
    assert st["vertices"]["consumer"]["total_tasks"] == 1, st["vertices"]
    open(gate, "w").close()
    assert am2.wait_for_dag(recovered, timeout=60) is DAGState.SUCCEEDED
    with open(result) as fh:
        assert int(fh.read()) == 100  # 2 producers x 50 records x value 1
    d = am2.dag_counters.to_dict().get("DAGCounter", {})
    # producers restored from the journal; only the consumer launched
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) == 1
    assert d.get("NUM_SUCCEEDED_TASKS", 0) == 3
    am2.stop()


def test_dag_aware_preemption_spares_unrelated_branches(tmp_staging):
    """DagAwareYarnTaskScheduler analog: preemption victims must be
    DESCENDANTS of the vertices whose requests are blocked — unrelated
    branch work keeps running, and with no descendant running there is no
    preemption at all (killing unrelated work cannot unblock the waiting
    request)."""
    from tez_tpu.am.task_scheduler import DagAwareTaskSchedulerService
    from tez_tpu.common.ids import DAGId

    class _V:
        def __init__(self, name, dests):
            self.name = name
            self.out_edges = {
                d: type("E", (), {"destination_vertex":
                                  type("V", (), {"name": d})()})()
                for d in dests}

    class _Dag:
        dag_id = "dag_x"

        def __init__(self):
            # A -> B; C independent; D -> E
            self.vertices = {"A": _V("A", ["B"]), "B": _V("B", []),
                             "C": _V("C", []),
                             "D": _V("D", ["E"]), "E": _V("E", [])}
            self._by_index = {0: self.vertices["A"],
                              1: self.vertices["B"],
                              2: self.vertices["C"],
                              3: self.vertices["D"],
                              4: self.vertices["E"]}

        def vertex_by_id(self, vid):
            return self._by_index.get(vid.id)

    class _Ctx:
        conf = C.TezConfiguration({})

        def __init__(self):
            self.dispatched = []
            self.current_dag = _Dag()

        def ensure_runners(self, backlog):
            pass

        def dispatch(self, event):
            self.dispatched.append(event)

    did = DAGId("app_1_da", 1)
    a_att = did.vertex(0).task(0).attempt(0)
    b_att = did.vertex(1).task(0).attempt(0)
    c_att = did.vertex(2).task(0).attempt(0)

    def kills(ctx):
        return [e for e in ctx.dispatched
                if getattr(e, "event_type", None) is not None
                and e.event_type.name == "TA_KILL_REQUEST"]

    # case 1: B (descendant) and C (unrelated) fill the slots; A waits ->
    # only B is preempted
    ctx = _Ctx()
    sched = DagAwareTaskSchedulerService(ctx, num_slots=2)
    sched.schedule(b_att, "spec-b", priority=20)
    sched.schedule(c_att, "spec-c", priority=20)
    assert sched.get_task("c0", timeout=0.1) is not None
    assert sched.get_task("c1", timeout=0.1) is not None
    sched.schedule(a_att, "spec-a", priority=5)
    got = kills(ctx)
    assert len(got) == 1 and got[0].attempt_id == b_att, got

    # case 2: only unrelated C work runs -> no preemption at all
    ctx2 = _Ctx()
    sched2 = DagAwareTaskSchedulerService(ctx2, num_slots=2)
    c2 = did.vertex(2).task(1).attempt(0)
    sched2.schedule(c_att, "spec-c", priority=20)
    sched2.schedule(c2, "spec-c2", priority=20)
    assert sched2.get_task("c0", timeout=0.1) is not None
    assert sched2.get_task("c1", timeout=0.1) is not None
    sched2.schedule(a_att, "spec-a", priority=5)
    assert not kills(ctx2), kills(ctx2)

    # case 3: the blocked set covers descendants of EVERY waiting vertex,
    # not just the best priority: A (prio 5, no descendants running) and D
    # (prio 10) both wait; D's descendant E runs -> E is preempted
    ctx4 = _Ctx()
    sched4 = DagAwareTaskSchedulerService(ctx4, num_slots=2)
    d_att = did.vertex(3).task(0).attempt(0)
    e_att = did.vertex(4).task(0).attempt(0)
    sched4.schedule(e_att, "spec-e", priority=20)
    sched4.schedule(c_att, "spec-c", priority=20)
    assert sched4.get_task("c0", timeout=0.1) is not None
    assert sched4.get_task("c1", timeout=0.1) is not None
    sched4.schedule(a_att, "spec-a", priority=5)
    sched4.schedule(d_att, "spec-d", priority=10)
    got4 = kills(ctx4)
    assert len(got4) == 1 and got4[0].attempt_id == e_att, got4

    # the stock scheduler WOULD have preempted in case 2 (contrast)
    from tez_tpu.am.task_scheduler import LocalTaskSchedulerService
    ctx3 = _Ctx()
    sched3 = LocalTaskSchedulerService(ctx3, num_slots=2)
    sched3.schedule(c_att, "spec-c", priority=20)
    sched3.schedule(c2, "spec-c2", priority=20)
    assert sched3.get_task("c0", timeout=0.1) is not None
    assert sched3.get_task("c1", timeout=0.1) is not None
    sched3.schedule(a_att, "spec-a", priority=5)
    assert len(kills(ctx3)) == 1


def test_dag_aware_scheduler_conf_seam(tmp_staging):
    """tez.am.task.scheduler.class selects the scheduler; a full DAG runs
    through the DAG-aware one."""
    from tez_tpu.am.task_scheduler import DagAwareTaskSchedulerService
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.task.scheduler.class": "dag-aware",
                               "tez.am.local.num-containers": 2})
    am = DAGAppMaster("app_1_das", conf)
    am.start()
    assert isinstance(am.task_scheduler, DagAwareTaskSchedulerService)
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.processors:SleepProcessor",
        payload={"sleep_ms": 1}), 3)
    plan = DAG.create("das").add_vertex(v).create_dag_plan()
    dag_id = am.submit_dag(plan)
    assert am.wait_for_dag(dag_id, timeout=30) is DAGState.SUCCEEDED
    am.stop()


_STUCK_ONCE = {"done": False}


class StuckOnceProcessor:
    """Heartbeats keep flowing (the runner's heartbeat thread) but the
    processor makes no progress on its first attempt."""

    def __init__(self, context):
        self.context = context

    def initialize(self):
        pass

    def run(self, inputs, outputs):
        import time
        if not _STUCK_ONCE["done"]:
            _STUCK_ONCE["done"] = True
            time.sleep(30)    # way past the stuck interval

    def close(self):
        pass

    def handle_events(self, events):
        pass


def test_progress_stuck_attempt_killed_and_retried(tmp_staging):
    """tez.task.progress.stuck.interval-ms (TaskHeartbeatHandler progress
    check): an attempt that heartbeats but makes NO progress is timed out
    and the retry completes the task."""
    import time
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex

    _STUCK_ONCE["done"] = False
    conf = {"tez.staging-dir": tmp_staging,
            "tez.task.progress.stuck.interval-ms": 800,
            "tez.am.local.num-containers": 2}
    c = TezClient.create("stuck", conf).start()
    try:
        c.framework_client.am.heartbeat_monitor.check_interval = 0.1
        dag = DAG.create("stuckdag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tests.test_resilience:StuckOnceProcessor"), 1))
        t0 = time.time()
        st = c.submit_dag(dag).wait_for_completion(timeout=60)
        wall = time.time() - t0
        assert st.state is DAGStatusState.SUCCEEDED
        assert wall < 25, f"stuck attempt not killed promptly ({wall:.0f}s)"
        # the hung first attempt was killed for no progress
        am = c.framework_client.am
        diags = [
            d for v in am.current_dag.vertices.values()
            for t in v.tasks.values() for a in t.attempts.values()
            for d in a.diagnostics]
        assert any("no progress" in d for d in diags), diags
    finally:
        c.stop()


def test_container_reuse_disabled_one_task_per_container(tmp_staging):
    """tez.am.container.reuse.enabled=False: every task runs in a fresh
    container (no reuse counter, fresh registries)."""
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex

    conf = {"tez.staging-dir": tmp_staging,
            "tez.am.container.reuse.enabled": False,
            "tez.am.local.num-containers": 2}
    c = TezClient.create("noreuse", conf).start()
    try:
        dag = DAG.create("noreuse").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 0}), 6))
        st = c.submit_dag(dag).wait_for_completion(timeout=60)
        assert st.state is DAGStatusState.SUCCEEDED
        am = c.framework_client.am
        reuse = am.dag_counters.to_dict().get("DAGCounter", {}).get(
            "TOTAL_CONTAINER_REUSE_COUNT", 0)
        assert reuse == 0, f"containers were reused {reuse}x with reuse off"
        # and with reuse ON (default) the same DAG does reuse containers
    finally:
        c.stop()


def test_max_app_attempts_refuses_restart(tmp_staging):
    """tez.am.max.app.attempts: the AM restart budget — a supervisor
    looping restarts of a persistently-crashing app is refused loudly."""
    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.max.app.attempts": 2})
    DAGAppMaster("app_1_maxatt", conf, attempt=2).stop()  # at the budget: ok
    with pytest.raises(RuntimeError, match="max.app.attempts"):
        DAGAppMaster("app_1_maxatt", conf, attempt=3)


def test_debug_artifacts_written_on_submit(tmp_staging):
    """tez.generate.debug.artifacts: the submitted plan lands in the AM
    work dir for postmortems."""
    import glob as globlib
    import os
    from tez_tpu.client.dag_client import DAGStatusState
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex

    conf = {"tez.staging-dir": tmp_staging,
            "tez.generate.debug.artifacts": True}
    with TezClient.create("dbg", conf) as c:
        dag = DAG.create("dbgdag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 0}), 1))
        st = c.submit_dag(dag).wait_for_completion(timeout=30)
        assert st.state is DAGStatusState.SUCCEEDED
        work = c.framework_client.am.work_dir
    arts = globlib.glob(os.path.join(work, "*-plan-debug.json"))
    assert arts, f"no debug artifact in {work}"
    import json as _json
    body = _json.load(open(arts[0]))
    assert body["name"] == "dbgdag" and body["vertices"] == ["v"]
