"""Phase-9 tests: history parser, analyzers, swimlane over a real run."""
import os

import pytest

from tez_tpu.examples import ordered_wordcount
from tez_tpu.tools.analyzers import (ALL_ANALYZERS, analyze_dag,
                                     CriticalPathAnalyzer)
from tez_tpu.tools.history_parser import parse_jsonl_files
from tez_tpu.tools.swimlane import render_svg


@pytest.fixture(scope="module")
def history_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hist")
    corpus = tmp / "in.txt"
    corpus.write_text("alpha beta gamma alpha\n" * 200)
    hist = str(tmp / "history")
    state = ordered_wordcount.run(
        [str(corpus)], str(tmp / "out"),
        conf={"tez.staging-dir": str(tmp / "s"),
              "tez.history.logging.service.class":
                  "tez_tpu.am.history:JsonlHistoryLoggingService",
              "tez.history.logging.log-dir": hist},
        tokenizer_parallelism=2)
    assert state == "SUCCEEDED"
    return hist


def test_parse_history(history_dir):
    dags = parse_jsonl_files([history_dir])
    assert len(dags) == 1
    dag = list(dags.values())[0]
    assert dag.name == "OrderedWordCount"
    assert dag.state == "SUCCEEDED"
    assert {v.name for v in dag.vertices.values()} == \
        {"tokenizer", "summation", "sorter"}
    assert dag.duration > 0
    tok = dag.vertex("tokenizer")
    assert tok.num_tasks == 2 and len(tok.tasks) == 2
    for t in tok.tasks.values():
        att = t.successful_attempt
        assert att is not None and att.container_id
        assert att.counters  # per-attempt counters recorded


def test_analyzers_produce_results(history_dir):
    dags = parse_jsonl_files([history_dir])
    dag = list(dags.values())[0]
    results = analyze_dag(dag)
    assert len(results) == len(ALL_ANALYZERS)
    by_name = {r.analyzer: r for r in results}
    assert "tokenizer" in str(by_name["critical_path"].rows)
    shuffled = by_name["shuffle_time"].rows
    assert any(r["shuffle_bytes"] > 0 for r in shuffled)
    assert by_name["hung_tasks"].rows == []
    reuse = by_name["container_reuse"]
    assert sum(r.get("tasks_run", 0) for r in reuse.rows) >= 5
    # full reference plugin set
    overview = by_name["dag_overview"]
    assert {r["vertex"] for r in overview.rows} == \
        {"tokenizer", "summation", "sorter"}
    assert all(r["task_states"].get("SUCCEEDED") for r in overview.rows)
    assert by_name["input_read_errors"].rows == []
    loc = by_name["locality"].rows
    assert loc and all(r["local_fraction"] == 1.0 for r in loc)  # single host
    crit = by_name["vertex_critical_path"]
    assert [r["vertex"] for r in crit.rows] == \
        ["tokenizer", "summation", "sorter"]
    assert by_name["task_assignment"].rows
    assert by_name["attempt_result_stats"].rows
    assert by_name["slow_nodes"].rows
    assert by_name["one_on_one_edges"].rows == []  # no 1-1 edges in this DAG


from tez_tpu.library.processors import SimpleProcessor  # noqa: E402


class OneToOneEmitter(SimpleProcessor):
    """Module-level so descriptors can resolve tests.test_tools:OneToOneEmitter."""

    def run(self, inputs, outputs):
        outputs["b"].get_writer().write(b"k", b"v")


class OneToOneReader(SimpleProcessor):
    def run(self, inputs, outputs):
        list(inputs["a"].get_reader())


def test_one_on_one_edge_analyzer(tmp_path):
    """ONE_TO_ONE edge placement analysis over a real 1-1 DAG run."""
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                        ProcessorDescriptor)
    from tez_tpu.dag.dag import DAG, Edge, Vertex
    from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                           EdgeProperty, SchedulingType)
    from tez_tpu.tools.analyzers import OneOnOneEdgeAnalyzer
    hist = str(tmp_path / "hist")
    c = TezClient.create("oo", {
        "tez.staging-dir": str(tmp_path / "s"),
        "tez.history.logging.service.class":
            "tez_tpu.am.history:JsonlHistoryLoggingService",
        "tez.history.logging.log-dir": hist}).start()
    try:
        kv = {"tez.runtime.key.class": "bytes",
              "tez.runtime.value.class": "bytes"}
        a = Vertex.create("a", ProcessorDescriptor.create(OneToOneEmitter), 2)
        b = Vertex.create("b", ProcessorDescriptor.create(OneToOneReader), 2)
        prop = EdgeProperty.create(
            DataMovementType.ONE_TO_ONE, DataSourceType.PERSISTED,
            SchedulingType.SEQUENTIAL,
            OutputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVOutput", payload=kv),
            InputDescriptor.create(
                "tez_tpu.library.unordered:UnorderedKVInput", payload=kv))
        dag = DAG.create("oodag").add_vertex(a).add_vertex(b)
        dag.add_edge(Edge.create(a, b, prop))
        st = c.submit_dag(dag).wait_for_completion(timeout=30)
        assert st.state.name == "SUCCEEDED"
    finally:
        c.stop()
    dags = parse_jsonl_files([hist])
    dag_info = list(dags.values())[0]
    assert dag_info.edges and dag_info.edges[0]["movement"] == "ONE_TO_ONE"
    res = OneOnOneEdgeAnalyzer().analyze(dag_info)
    assert res.rows == [{"edge": "a->b", "pairs": 2, "colocated": 2}]


def test_swimlane_svg(history_dir):
    dags = parse_jsonl_files([history_dir])
    dag = list(dags.values())[0]
    svg = render_svg(dag)
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    assert "tokenizer" in svg and "attempt_" in svg


def test_analyzer_cli(history_dir, capsys):
    import sys
    from tez_tpu.tools import analyzers
    old = sys.argv
    try:
        sys.argv = ["analyzers", history_dir]
        assert analyzers.main() == 0
    finally:
        sys.argv = old
    out = capsys.readouterr().out
    assert "critical_path" in out and "OrderedWordCount" in out


def test_native_gather_matches_numpy():
    """native/ragged.cpp gather == numpy fallback (skips if no toolchain)."""
    import numpy as np
    from tez_tpu.ops.native import gather_ragged_native, native_available
    if not native_available():
        import pytest
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(1)
    n = 5000
    lens = rng.integers(0, 30, n)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    data = rng.integers(0, 256, int(offsets[-1])).astype(np.uint8)
    perm = rng.permutation(n)
    out, oo = gather_ragged_native(data, offsets, perm)
    # golden via pure-numpy path
    from tez_tpu.ops.runformat import _ranges
    nl = lens[perm]
    golden_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(nl, out=golden_off[1:])
    idx = np.repeat(offsets[:-1][perm], nl) + _ranges(nl)
    assert np.array_equal(out, data[idx])
    assert np.array_equal(oo, golden_off)


def test_am_web_endpoint(tmp_path):
    """AM web UI serves live status (AMWebController analog)."""
    import json
    import urllib.request
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    c = TezClient.create("web", {"tez.staging-dir": str(tmp_path / "s"),
                                 "tez.fake.access.token": "hunter2",
                                 "tez.am.web.enabled": True}).start()
    try:
        dag = DAG.create("webdag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 1}), 2))
        c.submit_dag(dag).wait_for_completion(timeout=30)
        url = c.framework_client.am.web_ui.url
        status = json.loads(urllib.request.urlopen(url + "status").read())
        assert status["name"] == "webdag"
        assert status["state"] == "SUCCEEDED"
        assert status["vertices"]["v"]["succeeded"] == 2
        counters = json.loads(urllib.request.urlopen(
            url + "counters").read())
        assert "TaskCounter" in counters
        page = urllib.request.urlopen(url).read()
        assert b"<html" in page
        # SPA REST surface (tez-ui feature set)
        graph = json.loads(urllib.request.urlopen(url + "graph").read())
        assert [v["name"] for v in graph["vertices"]] == ["v"]
        assert graph["vertices"][0]["state"] == "SUCCEEDED"
        tasks = json.loads(urllib.request.urlopen(
            url + "tasks?vertex=v").read())
        assert len(tasks) == 2
        assert all(t["attempts"][0]["state"] == "SUCCEEDED" for t in tasks)
        dags = json.loads(urllib.request.urlopen(url + "dags").read())
        assert any(d["state"] == "SUCCEEDED" for d in dags)
        res = json.loads(urllib.request.urlopen(url + "analyzers").read())
        assert {"critical_path", "dag_overview"} <= \
            {r["analyzer"] for r in res}
        # attempt drill-down: counters + diagnostics + timing per attempt
        aid = tasks[0]["attempts"][0]["id"]
        att = json.loads(urllib.request.urlopen(
            url + "attempt?id=" + urllib.parse.quote(aid)).read())
        assert att["state"] == "SUCCEEDED" and att["vertex"] == "v"
        assert "TaskCounter" in att["counters"]
        assert json.loads(urllib.request.urlopen(
            url + "attempt?id=bogus").read())["error"]
        # per-vertex counter aggregation
        vc = json.loads(urllib.request.urlopen(
            url + "counters?vertex=v").read())
        assert "TaskCounter" in vc
        # effective conf with secrets redacted
        conf = json.loads(urllib.request.urlopen(url + "conf").read())
        assert conf.get("tez.am.web.enabled") in (True, "True")
        assert conf["tez.fake.access.token"] == "<redacted>"
        assert "hunter2" not in json.dumps(conf)
    finally:
        c.stop()


def test_host_sorter_engine_byte_exact():
    """'host' sorter engine (np.lexsort) output == device engine output."""
    import random
    from tez_tpu.ops.sorter import DeviceSorter
    rng = random.Random(11)
    pairs = [(bytes(rng.randrange(256) for _ in range(rng.randrange(1, 20))),
              bytes(rng.randrange(256) for _ in range(4)))
             for _ in range(800)]
    runs = []
    for engine in ("device", "host"):
        s = DeviceSorter(num_partitions=3, engine=engine)
        for k, v in pairs:
            s.write(k, v)
        runs.append(s.flush())
    assert list(runs[0].batch.iter_pairs()) == list(runs[1].batch.iter_pairs())
    import numpy as np
    np.testing.assert_array_equal(runs[0].row_index, runs[1].row_index)


def test_thread_dump_and_stats():
    from io import StringIO
    from tez_tpu.runtime.diagnostics import (RuntimeStatsUpdater,
                                             dump_thread_stacks)
    from tez_tpu.common.counters import TaskCounter, TezCounters
    text = dump_thread_stacks()
    assert "MainThread" in text
    c = TezCounters()
    u = RuntimeStatsUpdater(c)
    sum(i * i for i in range(100000))
    u.update()
    assert c.find_counter(TaskCounter.CPU_MILLISECONDS).value >= 0
    assert c.find_counter(TaskCounter.PHYSICAL_MEMORY_BYTES).value > 0


def test_counter_diff_cli(history_dir, capsys):
    import sys
    from tez_tpu.tools import counter_diff
    import glob as g
    from tez_tpu.am.history import scan_history_store
    f = scan_history_store(history_dir)[0]
    old = sys.argv
    try:
        sys.argv = ["counter_diff", f, f]
        assert counter_diff.main() == 0
    finally:
        sys.argv = old
    out = capsys.readouterr().out
    assert "wall delta" in out


def test_bench_diff_gate(tmp_path, capsys):
    """bench_diff matches metrics by their pre-paren prefix, skips 0.0
    sentinels, and exits nonzero only on a >threshold drop."""
    import json

    from tez_tpu.tools import bench_diff

    def write(name, values):
        lines = [json.dumps({
            "metric": f"{m} (qualifiers change {name})", "value": v,
            "unit": "MB/s", "vs_baseline": 1.0})
            for m, v in values.items()]
        p = tmp_path / name
        p.write_text(json.dumps({"tail": "\n".join(lines), "rc": 0}))
        return str(p)

    old = write("old.json", {"sort": 100.0, "e2e": 50.0, "stalled": 0.0})
    ok = write("ok.json", {"sort": 85.0, "e2e": 60.0, "stalled": 0.0})
    bad = write("bad.json", {"sort": 70.0, "e2e": 60.0, "stalled": 0.0})
    assert bench_diff.diff(old, ok) == 0       # -15% is inside the gate
    assert bench_diff.diff(old, bad) == 1      # -30% regresses
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "unavailable sentinel" in out
    # raw-stdout input (no wrapper) parses too
    raw = tmp_path / "raw.txt"
    raw.write_text("noise\n" + json.dumps(
        {"metric": "sort (raw)", "value": 99.0, "unit": "MB/s"}) + "\n")
    assert bench_diff.diff(old, str(raw)) == 0


def test_log_split(tmp_path):
    """tez-log-split analog: interleaved attempt logs carve into per-attempt
    files, continuation lines follow their record."""
    from tez_tpu.tools.log_split import split_log
    a1 = "attempt_1785290000_0001_1_00_000000_0"
    a2 = "attempt_1785290000_0001_1_00_000001_0"
    combined = [
        "2026-07-29 01:00:00 INFO am: dag submitted\n",
        f"2026-07-29 01:00:01 INFO [{a1}] task: starting\n",
        f"2026-07-29 01:00:01 ERROR [{a2}] task: boom\n",
        "Traceback (most recent call last):\n",
        "  File \"x.py\", line 1\n",
        f"2026-07-29 01:00:02 INFO [{a1}] task: done\n",
        "2026-07-29 01:00:03 INFO am: dag finished\n",
    ]
    out = str(tmp_path / "split")
    counts = split_log(combined, out)
    assert counts == {"main.log": 2, f"{a1}.log": 2, f"{a2}.log": 3}
    body = open(os.path.join(out, f"{a2}.log")).read()
    assert "Traceback" in body and "File" in body   # continuation followed


def test_client_session_expiry(tmp_path):
    """Standalone session AM shuts down when the client stops talking
    (reference: tez.am.client.heartbeat.timeout.secs)."""
    import time as _time
    from tests.test_standalone_am import spawn_am
    from tez_tpu.client.tez_client import TezClient
    proc, port, token = spawn_am(
        tmp_path, "--num-containers", "1",
        "--client-heartbeat-timeout-secs", "1.5")
    try:
        c = TezClient.create("exp", {
            "tez.framework.mode": "remote",
            "tez.am.address": f"127.0.0.1:{port}",
            "tez.job.token": token,
            "tez.client.am.heartbeat.interval.secs": 0.5}).start()
        _time.sleep(4)               # idle but alive: keepalive holds the
        assert proc.poll() is None   # session open past the 1.5s timeout
        c.stop()                     # client goes away without shutdown
        deadline = _time.time() + 15
        while proc.poll() is None and _time.time() < deadline:
            _time.sleep(0.2)
        assert proc.poll() is not None, "session AM outlived its client"
    finally:
        if proc.poll() is None:
            proc.terminate()
        proc.wait(timeout=10)


def test_task_jax_profile_trace(tmp_path):
    """tez.task.jax-profile.dir writes a per-attempt XLA profiler trace —
    the TPU-native per-kernel tracing story (SURVEY.md §5.1)."""
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    prof = str(tmp_path / "prof")
    c = TezClient.create("prof", {"tez.staging-dir": str(tmp_path / "s"),
                                  "tez.task.jax-profile.dir": prof}).start()
    try:

        dag = DAG.create("profdag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(ComputeProcessor), 1))
        # generous: the XLA profiler's first start in a loaded process
        # can pay tens of seconds of one-time setup (observed flaking
        # at 60s under full-suite load)
        st = c.submit_dag(dag).wait_for_completion(timeout=300)
        assert st.state.name == "SUCCEEDED"
    finally:
        c.stop()
    # one trace dir per attempt, containing xplane protobufs
    entries = os.listdir(prof)
    assert any(e.startswith("attempt_") for e in entries), entries
    found = []
    for root, _dirs, files in os.walk(prof):
        found.extend(f for f in files if f.endswith(".xplane.pb"))
    assert found, "no xplane trace written"


class ComputeProcessor(SimpleProcessor):
    def run(self, inputs, outputs):
        import jax.numpy as jnp
        import jax
        x = jnp.arange(1024, dtype=jnp.float32)
        jax.block_until_ready(jnp.dot(x, x))
