"""Phase-9 tests: history parser, analyzers, swimlane over a real run."""
import os

import pytest

from tez_tpu.examples import ordered_wordcount
from tez_tpu.tools.analyzers import (ALL_ANALYZERS, analyze_dag,
                                     CriticalPathAnalyzer)
from tez_tpu.tools.history_parser import parse_jsonl_files
from tez_tpu.tools.swimlane import render_svg


@pytest.fixture(scope="module")
def history_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hist")
    corpus = tmp / "in.txt"
    corpus.write_text("alpha beta gamma alpha\n" * 200)
    hist = str(tmp / "history")
    state = ordered_wordcount.run(
        [str(corpus)], str(tmp / "out"),
        conf={"tez.staging-dir": str(tmp / "s"),
              "tez.history.logging.service.class":
                  "tez_tpu.am.history:JsonlHistoryLoggingService",
              "tez.history.logging.log-dir": hist},
        tokenizer_parallelism=2)
    assert state == "SUCCEEDED"
    return hist


def test_parse_history(history_dir):
    dags = parse_jsonl_files([os.path.join(history_dir, "*.jsonl")])
    assert len(dags) == 1
    dag = list(dags.values())[0]
    assert dag.name == "OrderedWordCount"
    assert dag.state == "SUCCEEDED"
    assert {v.name for v in dag.vertices.values()} == \
        {"tokenizer", "summation", "sorter"}
    assert dag.duration > 0
    tok = dag.vertex("tokenizer")
    assert tok.num_tasks == 2 and len(tok.tasks) == 2
    for t in tok.tasks.values():
        att = t.successful_attempt
        assert att is not None and att.container_id
        assert att.counters  # per-attempt counters recorded


def test_analyzers_produce_results(history_dir):
    dags = parse_jsonl_files([os.path.join(history_dir, "*.jsonl")])
    dag = list(dags.values())[0]
    results = analyze_dag(dag)
    assert len(results) == len(ALL_ANALYZERS)
    by_name = {r.analyzer: r for r in results}
    assert "tokenizer" in str(by_name["critical_path"].rows)
    shuffled = by_name["shuffle_time"].rows
    assert any(r["shuffle_bytes"] > 0 for r in shuffled)
    assert by_name["hung_tasks"].rows == []
    reuse = by_name["container_reuse"]
    assert sum(r.get("tasks_run", 0) for r in reuse.rows) >= 5


def test_swimlane_svg(history_dir):
    dags = parse_jsonl_files([os.path.join(history_dir, "*.jsonl")])
    dag = list(dags.values())[0]
    svg = render_svg(dag)
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    assert "tokenizer" in svg and "attempt_" in svg


def test_analyzer_cli(history_dir, capsys):
    import sys
    from tez_tpu.tools import analyzers
    old = sys.argv
    try:
        sys.argv = ["analyzers", os.path.join(history_dir, "*.jsonl")]
        assert analyzers.main() == 0
    finally:
        sys.argv = old
    out = capsys.readouterr().out
    assert "critical_path" in out and "OrderedWordCount" in out
