"""Label-aware exposition: name->label splitting, byte-for-byte golden
text rendering, drill-down filters, the structured JSON surface, and the
strict parser's invariant checks.

The golden fixture (tests/golden/metrics_exposition.txt) pins the wire
format: rendering is deterministic by construction (sorted families,
sorted label sets, no timestamps), so any diff against the golden file
is a real format change and must be reviewed as one.
"""
import os

import pytest

from tez_tpu.common.metrics import Histogram
from tez_tpu.obs.exposition import (parse_exposition, render_json,
                                    render_text, split_labels)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "metrics_exposition.txt")


def _fixture():
    """A small, fully deterministic exposition covering every label
    family: the stream aggregate, a per-stream series, a per-tenant
    series, a lane gauge, a plain gauge, and a plain counter group."""
    agg = Histogram("stream.window.latency")
    for v in (100.0, 300.0):
        agg.observe(v)
    s1 = Histogram("stream.s1.window.latency")
    s1.observe(100.0)
    ten = Histogram("tenant.acme.dag.latency")
    ten.observe(5000.0)
    hists = {h.name: h for h in (agg, s1, ten)}
    gauges = {"slo.burn.active": 1.0,
              "mesh.lane.0.occupancy": 0.5,
              "tenant.acme.store.bytes": 4096.0}
    counters = {"TaskCounter": {"SPILLED_RECORDS": 3, "INPUT_RECORDS": 12},
                "LatencyHistogram.x": {"COUNT": 9}}  # skipped: hist-backed
    return hists, gauges, counters


def test_split_labels():
    assert split_labels("stream.window.latency") == \
        ("stream.window.latency", {})          # the session aggregate
    assert split_labels("stream.s1.window.latency") == \
        ("stream.window.latency", {"stream": "s1"})
    assert split_labels("tenant.acme.dag.latency") == \
        ("tenant.dag.latency", {"tenant": "acme"})
    assert split_labels("mesh.lane.3.occupancy") == \
        ("mesh.lane.occupancy", {"lane": "3"})
    assert split_labels("am.admit.queue_wait") == \
        ("am.admit.queue_wait", {})


def test_render_text_matches_golden():
    hists, gauges, counters = _fixture()
    text = render_text(hists, gauges, counters)
    with open(GOLDEN, encoding="utf-8") as fh:
        golden = fh.read()
    assert text == golden, (
        "exposition text drifted from tests/golden/metrics_exposition.txt"
        " — if the format change is intentional, regenerate the golden"
        " file from render_text() and review the diff")


def test_golden_passes_the_strict_parser():
    with open(GOLDEN, encoding="utf-8") as fh:
        fams = parse_exposition(fh.read())
    hist = fams["tez_latency_stream_window_latency_ms"]
    assert hist["type"] == "histogram"
    # aggregate (no labels) and the s1 drill-down share one family
    label_sets = {tuple(sorted(lb.items()))
                  for _, lb, _ in hist["samples"]}
    assert ("stream", "s1") in {
        kv for ls in label_sets for kv in ls}
    counts = [(n, lb, v) for n, lb, v in hist["samples"]
              if n.endswith("_count")]
    assert ({}, 2.0) in [(lb, v) for _, lb, v in counts]
    assert ({"stream": "s1"}, 1.0) in [(lb, v) for _, lb, v in counts]
    gauge = fams["tez_tenant_store_bytes"]
    assert gauge["samples"] == [
        ("tez_tenant_store_bytes", {"tenant": "acme"}, 4096.0)]
    counter = fams["tez_counter"]
    assert ("tez_counter", {"group": "TaskCounter",
                            "name": "SPILLED_RECORDS"}, 3.0) \
        in counter["samples"]
    # the LatencyHistogram.* counter group is rendered as a histogram
    # family above, never duplicated as tez_counter rows
    assert not any(lb.get("group", "").startswith("LatencyHistogram")
                   for _, lb, _ in counter["samples"])


def test_drilldown_filters():
    hists, gauges, counters = _fixture()
    t = render_text(hists, gauges, counters, tenant="acme")
    fams = parse_exposition(t)
    assert set(fams) == {"tez_latency_tenant_dag_latency_ms",
                         "tez_tenant_store_bytes"}
    s = render_text(hists, gauges, counters, stream="s1")
    fams = parse_exposition(s)
    assert set(fams) == {"tez_latency_stream_window_latency_ms"}
    assert all(lb.get("stream") == "s1"
               for _, lb, _ in fams[
                   "tez_latency_stream_window_latency_ms"]["samples"])
    # filtering drops the unlabeled counter block entirely
    assert "tez_counter" not in parse_exposition(t)


def test_label_escaping_round_trips():
    weird = 'we"ird\\ten\nant'
    h = Histogram(f"tenant.{weird}.dag.latency")
    h.observe(10.0)
    text = render_text({h.name: h}, {})
    fams = parse_exposition(text)
    labels = [lb for _, lb, _ in
              fams["tez_latency_tenant_dag_latency_ms"]["samples"]]
    assert all(lb["tenant"] == weird for lb in labels)


def test_render_json_rows_windows_accounting():
    hists, gauges, _ = _fixture()
    windows = {"stream.s1.window.latency": {"count": 1, "p95": 128.0}}
    acct = {"series": 6, "evicted": 0, "scrape_errors": 0}
    out = render_json(hists, gauges, windows=windows, accounting=acct,
                      window_s=10.0)
    assert out["window_s"] == 10.0
    assert out["accounting"] == acct
    by_series = {r["series"]: r for r in out["histograms"]}
    row = by_series["stream.s1.window.latency"]
    assert row["name"] == "stream.window.latency"
    assert row["labels"] == {"stream": "s1"}
    assert row["count"] == 1
    assert row["window"] == windows["stream.s1.window.latency"]
    assert 64.0 < row["p95"] <= 128.0
    assert by_series["stream.window.latency"]["labels"] == {}
    # tenant drill-down filters JSON rows the same way as text
    only = render_json(hists, gauges, tenant="acme")
    assert {r["series"] for r in only["histograms"]} == \
        {"tenant.acme.dag.latency"}
    assert {r["series"] for r in only["gauges"]} == \
        {"tenant.acme.store.bytes"}


def test_parser_rejects_untyped_samples():
    with pytest.raises(ValueError, match="no TYPE"):
        parse_exposition("tez_mystery 1\n")


def test_parser_rejects_non_cumulative_buckets():
    bad = ("# TYPE tez_latency_x_ms histogram\n"
           'tez_latency_x_ms_bucket{le="1"} 5\n'
           'tez_latency_x_ms_bucket{le="2"} 3\n'
           'tez_latency_x_ms_bucket{le="+Inf"} 5\n'
           "tez_latency_x_ms_sum 9\n"
           "tez_latency_x_ms_count 5\n")
    with pytest.raises(ValueError, match="not cumulative"):
        parse_exposition(bad)


def test_parser_rejects_count_bucket_mismatch():
    bad = ("# TYPE tez_latency_x_ms histogram\n"
           'tez_latency_x_ms_bucket{le="+Inf"} 5\n'
           "tez_latency_x_ms_sum 9\n"
           "tez_latency_x_ms_count 4\n")
    with pytest.raises(ValueError, match="_count"):
        parse_exposition(bad)


def test_parser_rejects_missing_inf_bucket():
    bad = ("# TYPE tez_latency_x_ms histogram\n"
           'tez_latency_x_ms_bucket{le="1"} 5\n'
           "tez_latency_x_ms_sum 9\n"
           "tez_latency_x_ms_count 5\n")
    with pytest.raises(ValueError, match=r"\+Inf"):
        parse_exposition(bad)
