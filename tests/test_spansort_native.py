"""Differential tests for the v2 native span sort / fused merge
(tez_tpu/native/spansort.cpp).

Reference semantics: stable (partition, full key bytes) order, byte-identical
materialization — PipelinedSorter.java:75 (span sort) and TezMerger.java:76
(MergeQueue run-age tie order).  Every case checks the native output against
an independent numpy/python golden, across the paths that branch inside the
native code: dedup-rank vs direct (duplication gate), fixed-width vs ragged
rows, derived vs given vs absent partitions.
"""
from __future__ import annotations

import numpy as np
import pytest

from tez_tpu.ops.native import (merge_emit_native, native_available,
                                span_sort_emit_native)
from tez_tpu.ops.runformat import KVBatch
from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native lib unavailable")


def _fnv32_parts(keys: list, num_partitions: int) -> np.ndarray:
    out = np.empty(len(keys), dtype=np.int32)
    for i, k in enumerate(keys):
        h = 2166136261
        for b in k:
            h = ((h ^ b) * 16777619) & 0xFFFFFFFF
        out[i] = h % num_partitions
    return out


def _golden_sort(keys: list, vals: list, parts: np.ndarray):
    """Stable (partition, key bytes) order via python sort (stable)."""
    order = sorted(range(len(keys)), key=lambda i: (int(parts[i]), keys[i]))
    return [keys[i] for i in order], [vals[i] for i in order], \
        [int(parts[i]) for i in order]


def _ragged(rows: list):
    data = np.frombuffer(b"".join(rows), dtype=np.uint8)
    offsets = np.zeros(len(rows) + 1, dtype=np.int64)
    np.cumsum([len(r) for r in rows], out=offsets[1:])
    return data, offsets


def _rows(data: np.ndarray, offsets: np.ndarray) -> list:
    b = data.tobytes()
    return [b[offsets[i]:offsets[i + 1]] for i in range(len(offsets) - 1)]


def _make(rng, n, vocab, fixed_key, fixed_val):
    """Synthetic span: `vocab` distinct keys (None = all unique)."""
    keys = []
    for i in range(n):
        wid = int(rng.integers(0, vocab)) if vocab else i
        if fixed_key:
            keys.append(b"k%07d" % wid)
        else:
            keys.append(b"k%d" % wid + b"x" * int(rng.integers(0, 9)))
    if fixed_val:
        vals = [bytes([int(rng.integers(0, 256))] * 8) for _ in range(n)]
    else:
        vals = [bytes([i % 256] * int(rng.integers(0, 13))) for i in range(n)]
    return keys, vals


@pytest.mark.parametrize("vocab", [64, None])        # dedup path vs direct
@pytest.mark.parametrize("fixed_key", [True, False])
@pytest.mark.parametrize("fixed_val", [True, False])
@pytest.mark.parametrize("parts_mode", ["derive", "given", "none"])
def test_span_sort_emit_matches_golden(vocab, fixed_key, fixed_val,
                                       parts_mode):
    rng = np.random.default_rng(42)
    n, p = 6000, 5
    keys, vals = _make(rng, n, vocab, fixed_key, fixed_val)
    kb, ko = _ragged(keys)
    vb, vo = _ragged(vals)
    if parts_mode == "derive":
        res = span_sort_emit_native(kb, ko, vb, vo, p, None, True)
        parts = _fnv32_parts(keys, p)
    elif parts_mode == "given":
        parts = np.asarray(rng.integers(0, p, n), dtype=np.int32)
        res = span_sort_emit_native(kb, ko, vb, vo, p, parts, False)
    else:
        res = span_sort_emit_native(kb, ko, vb, vo, p, None, False)
        parts = np.zeros(n, dtype=np.int32)
    assert res is not None
    out_kb, out_ko, out_vb, out_vo, row_index = res
    gk, gv, gp = _golden_sort(keys, vals, parts)
    assert _rows(out_kb, out_ko) == gk
    assert _rows(out_vb, out_vo) == gv
    counts = np.bincount(parts, minlength=p)
    assert np.array_equal(np.diff(row_index), counts)


def test_span_sort_emit_rejects_out_of_range_partitions():
    # regression: an out-of-range custom partition id must degrade to the
    # safe fallback (clean python error), never scribble past the
    # num_partitions-sized native buffers
    n = 8192
    keys = [b"k%07d" % (i % 50) for i in range(n)]
    vals = [b"\0" * 8] * n
    kb, ko = _ragged(keys)
    vb, vo = _ragged(vals)
    bad = np.full(n, 7, dtype=np.int32)
    assert span_sort_emit_native(kb, ko, vb, vo, 4, bad, False) is None
    assert span_sort_emit_native(kb, ko, vb, vo, 4,
                                 np.full(n, -1, dtype=np.int32),
                                 False) is None
    # and through the public sorter API it raises instead of crashing —
    # for BOTH internal routes: dedup-rank (heavy duplication) and direct
    # (near-unique keys, where the counting sort indexes by partition id)
    s = DeviceSorter(num_partitions=4, engine="host", key_width=8)
    with pytest.raises(ValueError):
        s.sort_batch(KVBatch(kb, ko, vb, vo), custom_partitions=bad)
    ukeys = [b"u%07d" % i for i in range(n)]          # all unique: direct
    ukb, uko = _ragged(ukeys)
    for badval in (7, -1):
        with pytest.raises(ValueError):
            s.sort_batch(KVBatch(ukb, uko, vb, vo),
                         custom_partitions=np.full(n, badval,
                                                   dtype=np.int32))
    with pytest.raises(ValueError):                   # short array
        s.sort_batch(KVBatch(ukb, uko, vb, vo),
                     custom_partitions=np.zeros(n - 1, dtype=np.int32))


@pytest.mark.parametrize("vocab", [48, None])
@pytest.mark.parametrize("fixed", [True, False])
@pytest.mark.parametrize("num_runs", [5, 9])   # 9 exercises the head heap
def test_merge_emit_matches_concat_stable_sort(vocab, fixed, num_runs):
    """The fused merge must equal a stable sort of the runs' concatenation
    (equal (partition, key) rows keep run order = MergeQueue age order)."""
    rng = np.random.default_rng(7)
    p = 4
    runs, all_keys, all_vals, all_parts = [], [], [], []
    for _ in range(num_runs):
        n = int(rng.integers(500, 3000))
        keys, vals = _make(rng, n, vocab, fixed, fixed)
        parts = _fnv32_parts(keys, p)
        sk, sv, sp = _golden_sort(keys, vals, parts)
        kb, ko = _ragged(sk)
        vb, vo = _ragged(sv)
        row_index = np.zeros(p + 1, dtype=np.int64)
        np.cumsum(np.bincount(sp, minlength=p), out=row_index[1:])
        runs.append((kb, ko, vb, vo, row_index))
        all_keys.extend(sk)
        all_vals.extend(sv)
        all_parts.extend(sp)
    res = merge_emit_native(runs, p)
    assert res is not None
    out_kb, out_ko, out_vb, out_vo, row_index = res
    gk, gv, _ = _golden_sort(all_keys, all_vals,
                             np.asarray(all_parts, dtype=np.int32))
    assert _rows(out_kb, out_ko) == gk
    assert _rows(out_vb, out_vo) == gv
    assert row_index[-1] == len(gk)


def test_merge_sorted_runs_host_uses_fused_path_and_verifies():
    """End-to-end through the public API: producer sorts + host merge give
    byte-identical results to a python golden, zipfian duplication."""
    rng = np.random.default_rng(3)
    p = 4
    runs, all_keys, all_vals = [], [], []
    for _ in range(3):
        n = 5000
        wid = rng.zipf(1.4, n).astype(np.int64) % 300
        keys = [b"w%09d" % w for w in wid]
        vals = [bytes(rng.integers(0, 256, 8, dtype=np.int64)
                      .astype(np.uint8)) for _ in range(n)]
        kb, ko = _ragged(keys)
        vb, vo = _ragged(vals)
        s = DeviceSorter(num_partitions=p, engine="host", key_width=12)
        s.write_batch(KVBatch(kb, ko, vb, vo))
        run = s.flush()
        runs.append(run)
        # stable producer sort: golden concat order is the run's own order
        all_keys.extend(_rows(run.batch.key_bytes, run.batch.key_offsets))
        all_vals.extend(_rows(run.batch.val_bytes, run.batch.val_offsets))
    merged = merge_sorted_runs(runs, p, 12, engine="host")
    parts = _fnv32_parts(all_keys, p)
    gk, gv, _ = _golden_sort(all_keys, all_vals, parts)
    assert _rows(merged.batch.key_bytes, merged.batch.key_offsets) == gk
    assert _rows(merged.batch.val_bytes, merged.batch.val_offsets) == gv


def test_emit_rejects_nonpositive_partitions():
    """tz_span_sort_emit must reject num_partitions <= 0 with rc -1 (the
    partition-count pass would otherwise index an empty/negative
    part_counts array); the python wrapper surfaces the rejection as None
    so callers take the host fallback."""
    import ctypes

    from tez_tpu.ops import native
    lib = native._load()
    kb, ko = _ragged([b"a", b"bb"])
    vb, vo = _ragged([b"x", b"yy"])
    out_kb = np.empty(int(ko[-1]), dtype=np.uint8)
    out_ko = np.empty(3, dtype=np.int64)
    out_vb = np.empty(int(vo[-1]), dtype=np.uint8)
    out_vo = np.empty(3, dtype=np.int64)
    part_counts = np.empty(1, dtype=np.int64)

    def rc_for(p):
        return lib.tz_span_sort_emit(
            kb.ctypes.data_as(ctypes.c_void_p),
            ko.ctypes.data_as(ctypes.c_void_p),
            vb.ctypes.data_as(ctypes.c_void_p),
            vo.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(2), ctypes.c_int32(p), None,
            ctypes.c_int32(1),
            out_kb.ctypes.data_as(ctypes.c_void_p),
            out_ko.ctypes.data_as(ctypes.c_void_p),
            out_vb.ctypes.data_as(ctypes.c_void_p),
            out_vo.ctypes.data_as(ctypes.c_void_p),
            None, part_counts.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(1))

    assert rc_for(0) == -1
    assert rc_for(-4) == -1
    assert rc_for(1) == 0              # the guard is exact, not off-by-one
    assert span_sort_emit_native(kb, ko, vb, vo, 0, None, True) is None
