"""Fetch-scheduler tests (reference: ShuffleScheduler.java:91 per-host
queues, :179 penalty box + Referee, :295 bounded fetcher pool; injectable
fetchers mirror FetcherWithInjectableErrors)."""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import pytest

from tez_tpu.shuffle.scheduler import FetchRequest, FetchScheduler
from tez_tpu.shuffle.service import ShuffleDataNotFound


class FakeSession:
    def __init__(self, hub: "FakeHub", host: str, port: int):
        self.hub = hub
        self.host = host
        self.port = port
        self.closed = False

    def fetch(self, path: str, spill: int, partition: int):
        return self.hub.serve(self, path, spill, partition)

    def close(self) -> None:
        self.closed = True


class FakeHub:
    """Injectable fetcher backend: scripted failures per host."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.sessions: List[FakeSession] = []
        self.fetches: List[Tuple[str, str, int, int]] = []
        self.fail_hosts: Dict[str, int] = {}   # host -> remaining failures
        self.hang_hosts: set = set()
        self.concurrent = 0
        self.max_concurrent = 0
        self.per_session_fetches: Dict[int, int] = {}

    def factory(self, host: str, port: int):
        s = FakeSession(self, host, port)
        with self.lock:
            self.sessions.append(s)
            self.concurrent += 1
            self.max_concurrent = max(self.max_concurrent, self.concurrent)
        real_close = s.close

        def close():
            with self.lock:
                self.concurrent -= 1
            real_close()
        s.close = close
        return s

    def serve(self, session: FakeSession, path: str, spill: int,
              partition: int):
        with self.lock:
            self.fetches.append((session.host, path, spill, partition))
            self.per_session_fetches[id(session)] = \
                self.per_session_fetches.get(id(session), 0) + 1
            if self.fail_hosts.get(session.host, 0) > 0:
                self.fail_hosts[session.host] -= 1
                raise ConnectionError(f"scripted failure on {session.host}")
        if session.host in self.hang_hosts:
            time.sleep(10.0)
        return f"data:{path}:{spill}:{partition}"


class Collector:
    def __init__(self) -> None:
        self.lock = threading.Condition()
        self.ok: List[Tuple] = []
        self.errors: List[Tuple] = []

    def deliver(self, req: FetchRequest, batch, error: Optional[Exception]):
        with self.lock:
            if error is None:
                self.ok.append((req.key, batch))
            else:
                self.errors.append((req.key, error))
            self.lock.notify_all()

    def wait(self, n: int, timeout: float = 10.0) -> None:
        with self.lock:
            assert self.lock.wait_for(
                lambda: len(self.ok) + len(self.errors) >= n, timeout), \
                (self.ok, self.errors)


def _mk(hub, collector, **kw) -> FetchScheduler:
    defaults = dict(num_fetchers=4, max_per_fetch=8, penalty_base=0.05,
                    penalty_cap=0.4, max_attempts=3, stall_timeout=30.0)
    defaults.update(kw)
    return FetchScheduler(collector.deliver, hub.factory, **defaults)


def test_coalesces_one_host_into_few_sessions():
    """16 outputs on one host, max_per_fetch=8: at most a couple of
    connections, many fetches per connection (keep-alive batching)."""
    hub, col = FakeHub(), Collector()
    sched = _mk(hub, col)
    try:
        for i in range(16):
            sched.enqueue(FetchRequest("h1", 1, f"out{i}", -1, 0))
        col.wait(16)
        assert len(col.ok) == 16 and not col.errors
        assert len(hub.sessions) <= 4
        assert max(hub.per_session_fetches.values()) >= 4
    finally:
        sched.stop()


def test_bounded_fetcher_pool():
    """32 outputs across 16 hosts, 3 fetchers: concurrency never exceeds
    the pool size (ShuffleScheduler numFetchers bound)."""
    hub, col = FakeHub(), Collector()
    sched = _mk(hub, col, num_fetchers=3)
    try:
        for i in range(32):
            sched.enqueue(FetchRequest(f"h{i % 16}", 1, f"out{i}", -1, 0))
        col.wait(32)
        assert len(col.ok) == 32
        assert hub.max_concurrent <= 3
    finally:
        sched.stop()


def test_penalty_box_backoff_then_recovery():
    """A host failing twice lands in the penalty box with growing holds,
    then recovers and serves its whole queue."""
    hub, col = FakeHub(), Collector()
    hub.fail_hosts["bad"] = 2

    class MaxDraw:           # pin full jitter at its envelope so the
        @staticmethod        # elapsed-time floor below stays deterministic
        def uniform(a, b):
            return b

    sched = _mk(hub, col, num_fetchers=2, penalty_rng=MaxDraw())
    try:
        t0 = time.time()
        for i in range(4):
            sched.enqueue(FetchRequest("bad", 1, f"out{i}", -1, 0))
        col.wait(4, timeout=15)
        elapsed = time.time() - t0
        assert len(col.ok) == 4 and not col.errors
        # two penalties: 0.05 + 0.1 — must actually have waited
        assert elapsed >= 0.1
        host = sched.hosts[("bad", 1)]
        assert host.failures == 0   # reset on success
    finally:
        sched.stop()


def test_retry_budget_exhaustion_delivers_error():
    hub, col = FakeHub(), Collector()
    hub.fail_hosts["dead"] = 10_000
    sched = _mk(hub, col, max_attempts=3)
    try:
        sched.enqueue(FetchRequest("dead", 1, "out0", -1, 0))
        col.wait(1, timeout=15)
        assert not col.ok and len(col.errors) == 1
        key, err = col.errors[0]
        assert key == ("out0", -1, 0)
        assert isinstance(err, ConnectionError)
    finally:
        sched.stop()


def test_definitive_miss_no_retry():
    """ShuffleDataNotFound is delivered immediately; the connection is NOT
    penalized (the host is healthy, the data is gone)."""
    hub, col = FakeHub(), Collector()

    class MissSession(FakeSession):
        def fetch(self, path, spill, partition):
            self.hub.fetches.append((self.host, path, spill, partition))
            raise ShuffleDataNotFound(path)

    sched = FetchScheduler(col.deliver,
                           lambda h, p: MissSession(hub, h, p),
                           num_fetchers=1, penalty_base=0.05,
                           max_attempts=3)
    try:
        sched.enqueue(FetchRequest("h", 1, "gone", -1, 0))
        col.wait(1)
        assert len(col.errors) == 1
        assert isinstance(col.errors[0][1], ShuffleDataNotFound)
        assert len(hub.fetches) == 1           # no retry
        assert not sched.penalties             # no penalty box entry
    finally:
        sched.stop()


def test_speculative_refetch_rescues_stalled_connection():
    """A hung connection older than the stall timeout gets a duplicate on a
    fresh session; the duplicate's result is delivered, the stalled one is
    dropped by the first-wins gate."""
    hub, col = FakeHub(), Collector()
    hub.hang_hosts.add("slow")
    sched = _mk(hub, col, num_fetchers=2, stall_timeout=0.3)
    try:
        sched.enqueue(FetchRequest("slow", 1, "out0", -1, 0))
        time.sleep(0.5)            # let the first fetch hang past the stall
        hub.hang_hosts.discard("slow")   # new connections are fast
        col.wait(1, timeout=10)
        assert len(col.ok) == 1 and not col.errors
        assert len(hub.sessions) >= 2   # a second connection was opened
    finally:
        sched.stop()


def test_duplicate_enqueue_same_key_delivered_once():
    hub, col = FakeHub(), Collector()
    sched = _mk(hub, col)
    try:
        sched.enqueue(FetchRequest("h", 1, "o", 0, 2))
        col.wait(1)
        sched.enqueue(FetchRequest("h", 1, "o", 0, 2))
        time.sleep(0.2)
        assert len(col.ok) == 1
    finally:
        sched.stop()


def test_table_injectable_fetcher_conf_seam():
    """The tez.runtime.shuffle.fetcher.class seam: a ShuffleFetchTable with
    remote payloads routes fetches through the injected session class,
    retries scripted failures via the penalty box, and completes."""
    import numpy as np
    from tez_tpu.api.events import ShufflePayload
    from tez_tpu.common.counters import TaskCounter, TezCounters
    from tez_tpu.library.inputs import ShuffleFetchTable
    from tez_tpu.library.test_components import ScriptedFetchSession
    from tez_tpu.ops.runformat import KVBatch, Run
    from tez_tpu.shuffle.service import local_shuffle_service

    class _Payload:
        def load(self):
            return {}

    class _Ctx:
        def __init__(self):
            self.counters = TezCounters()
            self.conf = {
                "tez.runtime.shuffle.fetcher.class":
                    "tez_tpu.library.test_components:ScriptedFetchSession",
                "tez.runtime.shuffle.host.penalty.base-ms": 20,
                "tez.runtime.shuffle.fetch.attempts": 5,
            }
            self.user_payload = _Payload()
            self.events = []

        def get_service_provider_metadata(self, name):
            return {"host": "local", "port": 0, "secret": b"s"}

        def send_events(self, evs):
            self.events.extend(evs)

        def notify_progress(self):
            pass

    svc = local_shuffle_service()
    golden = []
    for i in range(4):
        batch = KVBatch.from_pairs([(f"k{i}{j}".encode(), b"v")
                                    for j in range(5)])
        golden.append(list(batch.iter_pairs()))
        svc.register(f"prod{i}", -1,
                     Run(batch, np.array([0, 5], dtype=np.int64)))
    ScriptedFetchSession.reset(fail_remaining=2)
    ctx = _Ctx()
    table = ShuffleFetchTable(ctx, num_slots=4, my_partition=0)
    try:
        for i in range(4):
            table.on_payload(i, 0, ShufflePayload(
                host="far-host", port=9, path_component=f"prod{i}"))
        batches = table.wait_all(timeout=20)
        got = sorted(p for b in batches for p in b.iter_pairs())
        assert got == sorted(p for g in golden for p in g)
        # every fetch went through the injected class, with retries
        assert len(ScriptedFetchSession.fetch_log) >= 4 + 2
        assert ctx.counters.to_dict()["TaskCounter"][
            "NUM_SHUFFLED_INPUTS"] == 4
    finally:
        table.shutdown()
        for i in range(4):
            svc.unregister_prefix(f"prod{i}")


# ---------------------------------------------------------------- TTL cache


class FakeClock:
    """Injectable scheduler clock: every TTL/penalty/stall decision steps
    only when the test says so."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _wait_cached(sched, n_sessions=None, timeout=5.0):
    """Spin until the scheduler has stashed a keep-alive session (and,
    optionally, until the hub saw n_sessions connections)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        with sched.lock:
            if sched._session_cache:
                return
        time.sleep(0.005)
    raise AssertionError("session never cached")


def test_stale_cached_session_discarded_at_checkout():
    """A keep-alive session idle past session_ttl must NOT be reused: the
    server may have half-closed it.  Checkout validates the TTL itself
    (not just the referee sweep) and the open-session slot accounting
    nets zero across the close + fresh connect."""
    hub, col = FakeHub(), Collector()
    clk = FakeClock()
    sched = _mk(hub, col, session_ttl=5.0, clock=clk, stall_timeout=1e9)
    try:
        sched.enqueue(FetchRequest("h1", 1, "a", -1, 0))
        col.wait(1)
        _wait_cached(sched)
        clk.advance(10.0)              # idle past TTL
        sched.enqueue(FetchRequest("h1", 1, "b", -1, 0))
        col.wait(2)
        assert len(col.ok) == 2 and not col.errors
        # a second, fresh connection served "b"; the stale one was closed
        assert len(hub.sessions) == 2
        assert hub.sessions[0].closed
        _wait_cached(sched)
        assert not hub.sessions[1].closed
        with sched.lock:
            assert sched._open_sessions == 1   # close+reconnect netted zero
    finally:
        sched.stop()


class GateHub(FakeHub):
    """Serve blocks on path "slow" until the test releases the gate."""

    def __init__(self) -> None:
        super().__init__()
        self.gate = threading.Event()
        self.entered = threading.Event()

    def serve(self, session, path, spill, partition):
        if path == "slow":
            self.entered.set()
            assert self.gate.wait(10)
        return super().serve(session, path, spill, partition)


def test_ttl_sweep_never_closes_checked_out_session():
    """Regression: the referee's TTL sweep once raced a fetcher that had
    just reused a cached session — the sweep closed the socket mid-fetch.
    Checkout now POPS the cache entry, so a checked-out session is
    invisible to the sweep no matter how far the clock jumps."""
    hub, col = GateHub(), Collector()
    clk = FakeClock()
    sched = _mk(hub, col, session_ttl=5.0, clock=clk, stall_timeout=1e9)
    try:
        sched.enqueue(FetchRequest("h1", 1, "fast", -1, 0))
        col.wait(1)
        _wait_cached(sched)                    # keep-alive session stashed
        sched.enqueue(FetchRequest("h1", 1, "slow", -1, 0))
        assert hub.entered.wait(5)             # reused session, mid-fetch
        assert len(hub.sessions) == 1          # reuse, not a new connect
        clk.advance(50.0)                      # way past session_ttl
        with sched.lock:
            sched.lock.notify_all()            # wake the referee sweep
        time.sleep(0.1)
        assert not hub.sessions[0].closed      # sweep spared the session
        hub.gate.set()
        col.wait(2)
        assert len(col.ok) == 2 and not col.errors
        assert len(hub.sessions) == 1          # whole run on one socket
    finally:
        sched.stop()


# ----------------------------------------------------- store short-circuit


def test_local_probe_short_circuits_store_hits():
    """Requests the local store can serve never open a connection; the
    rest of the batch still coalesces onto one session."""
    hub, col = FakeHub(), Collector()

    def probe(path, spill, partition):
        if path.startswith("here"):
            return f"store:{path}:{spill}:{partition}"
        return None

    sched = _mk(hub, col, local_probe=probe)
    try:
        for i in range(4):
            sched.enqueue(FetchRequest("h1", 1, f"here{i}", -1, 0))
            sched.enqueue(FetchRequest("h1", 1, f"far{i}", -1, 0))
        col.wait(8)
        assert len(col.ok) == 8 and not col.errors
        store_served = {k[0] for k, b in col.ok
                        if str(b).startswith("store:")}
        assert store_served == {f"here{i}" for i in range(4)}
        # probed keys never reached the wire
        assert all(p.startswith("far") for (_, p, _, _) in hub.fetches)
        assert len(hub.fetches) == 4
    finally:
        sched.stop()


def test_local_probe_all_hits_opens_no_connection():
    hub, col = FakeHub(), Collector()
    sched = _mk(hub, col,
                local_probe=lambda p, s, pt: f"store:{p}:{s}:{pt}")
    try:
        for i in range(6):
            sched.enqueue(FetchRequest("h1", 1, f"here{i}", -1, i))
        col.wait(6)
        assert len(col.ok) == 6 and not col.errors
        assert hub.sessions == [] and hub.fetches == []
    finally:
        sched.stop()
