"""Byte-exactness of the reduce-side merge-path kernel (ops/device.py
merge_path_runs / merge_resident_slices kernel="merge_path") against the
host merge engine and the concatenate+re-sort device kernel.

The contract under test is the TezMerger MergeQueue one: merged output is
(partition, key)-sorted with equal (partition, key) groups emitting in run
arrival order — keys AND values byte-identical across engines, across the
property matrix (random widths past the lane cap, duplicate-heavy keys,
empty runs, single runs, > merge_factor cascades).
"""
import random

import jax.numpy as jnp
import numpy as np
import pytest

from tez_tpu.ops import device
from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.ops.sorter import merge_sorted_runs

from test_ops import golden_sorted, random_pairs


def _partition_sorted_run(pairs, num_partitions):
    golden = golden_sorted(pairs, num_partitions)
    batch = KVBatch.from_pairs([(k, v) for _, k, _, v in golden])
    counts = np.bincount([p for p, *_ in golden], minlength=num_partitions)
    row_index = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(counts, out=row_index[1:])
    return Run(batch, row_index)


def _merge_both_engines(chunks, num_partitions, key_width, merge_factor=0):
    """Merge the same pre-sorted runs through the device merge-path tail
    and the host engine; return both pair lists."""
    runs_d = [_partition_sorted_run(c, num_partitions) for c in chunks]
    runs_h = [_partition_sorted_run(c, num_partitions) for c in chunks]
    dev = merge_sorted_runs(runs_d, num_partitions, key_width,
                            engine="device", merge_factor=merge_factor,
                            device_min_records=0)
    host = merge_sorted_runs(runs_h, num_partitions, key_width,
                             engine="host", merge_factor=merge_factor)
    return dev, host


@pytest.mark.parametrize("seed", range(6))
def test_merge_path_matches_host_engine_property_matrix(seed):
    rng = random.Random(seed)
    num_partitions = rng.choice([1, 4, 7])
    key_width = rng.choice([4, 12, 16])
    # max_key beyond key_width exercises the beyond-cap host tie-break;
    # small alphabets force duplicate keys across and within runs
    max_key = rng.choice([3, key_width, key_width + 9])
    k = rng.randrange(2, 7)
    chunks = []
    for i in range(k):
        n = rng.choice([0, 1, rng.randrange(2, 400)])
        chunks.append([(bytes(rng.randrange(4) for _ in
                        range(rng.randrange(1, max_key + 1))),
                        bytes([i, j % 256])) for j in range(n)])
    dev, host = _merge_both_engines(chunks, num_partitions, key_width)
    assert list(dev.batch.iter_pairs()) == list(host.batch.iter_pairs())
    np.testing.assert_array_equal(dev.row_index, host.row_index)


def test_merge_path_equal_keys_keep_run_arrival_order():
    # every run holds the SAME keys; values carry (run, row) so any tie
    # mis-order is visible in the value column
    keys = [b"a", b"a", b"b", b"zz"]
    chunks = [[(k, bytes([r, j])) for j, k in enumerate(keys)]
              for r in range(5)]
    dev, host = _merge_both_engines(chunks, 2, 8)
    got = list(dev.batch.iter_pairs())
    assert got == list(host.batch.iter_pairs())
    for key in set(keys):
        runs_seen = [v[0] for kk, v in got if kk == key]
        assert runs_seen == sorted(runs_seen)


def test_merge_path_single_run_and_all_empty():
    pairs = random_pairs(200, seed=9)
    dev, host = _merge_both_engines([pairs], 3, 16)
    assert list(dev.batch.iter_pairs()) == list(host.batch.iter_pairs())
    dev, host = _merge_both_engines([[], [], []], 3, 16)
    assert dev.batch.num_records == 0
    assert list(dev.batch.iter_pairs()) == list(host.batch.iter_pairs())


def test_merge_path_cascade_beyond_merge_factor():
    pairs = random_pairs(700, seed=10, max_key=6)   # duplicate-heavy
    chunks = [pairs[i::7] for i in range(7)]
    dev, host = _merge_both_engines(chunks, 4, 16, merge_factor=3)
    one_pass, _ = _merge_both_engines(chunks, 4, 16)
    assert list(dev.batch.iter_pairs()) == list(host.batch.iter_pairs())
    assert list(dev.batch.iter_pairs()) == list(one_pass.batch.iter_pairs())


def _resident_view(keys, key_width):
    """Device-resident (lanes, lengths, lo, hi) view of an already-sorted
    key list — the dev_keys shape producers hand to the resident merge."""
    b = KVBatch.from_pairs([(k, b"") for k in keys])
    mat, lengths = pad_to_matrix(b.key_bytes, b.key_offsets, key_width)
    lanes = matrix_to_lanes(mat)
    return (jnp.asarray(lanes), jnp.asarray(lengths.astype(np.int32)),
            0, len(keys))


@pytest.mark.parametrize("seed", range(4))
def test_merge_resident_kernels_agree(seed):
    rng = random.Random(100 + seed)
    key_width = rng.choice([4, 8])
    views, all_keys = [], []
    for _ in range(rng.randrange(2, 6)):
        n = rng.choice([1, rng.randrange(1, 300)])
        keys = sorted(bytes(rng.randrange(5) for _ in
                            range(rng.randrange(1, key_width + 1)))
                      for _ in range(n))
        views.append(_resident_view(keys, key_width))
        all_keys.extend(keys)
    perm_mp = device.merge_resident_slices(views, kernel="merge_path")
    perm_sort = device.merge_resident_slices(views, kernel="sort")
    np.testing.assert_array_equal(perm_mp, perm_sort)
    merged = [all_keys[i] for i in perm_mp]
    assert merged == sorted(all_keys)   # ties resolved by run order = concat
    np.testing.assert_array_equal(np.sort(perm_mp), np.arange(len(all_keys)))


def test_merge_rank_pallas_interpret_parity():
    from tez_tpu.ops.pallas_kernels import MERGE_ROW_BLOCK, merge_rank_pallas
    rng = np.random.default_rng(7)
    n, m, w = 173, 2 * MERGE_ROW_BLOCK, 3   # m a block multiple: grid path
    run_lanes = np.sort(rng.integers(0, 4, (n, w)).astype(np.uint32), axis=0)
    run_lens = rng.integers(1, 9, n).astype(np.uint32)
    q_lanes = rng.integers(0, 4, (m, w)).astype(np.uint32)
    q_lens = rng.integers(1, 9, m).astype(np.uint32)
    # the run must be sorted under the composite comparator (lanes
    # most-significant-first, then length): np.lexsort keys go least
    # significant first
    order = np.lexsort((run_lens,) + tuple(
        run_lanes[:, i] for i in range(w - 1, -1, -1)))
    run_lanes, run_lens = run_lanes[order], run_lens[order]
    for count_equal in (False, True):
        golden = device._rank_search(
            jnp.asarray(run_lanes), jnp.asarray(run_lens),
            jnp.asarray(q_lanes), jnp.asarray(q_lens), count_equal)
        got = merge_rank_pallas(
            jnp.asarray(run_lanes), jnp.asarray(run_lens),
            jnp.asarray(q_lanes), jnp.asarray(q_lens),
            count_equal=count_equal, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(golden))
