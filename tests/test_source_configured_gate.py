"""Regression tests for the unconfigured-source scheduling gate.

A consumer vertex must not release tasks while any source vertex's
parallelism is still unresolved (num_tasks == -1, e.g. an InputInitializer
racing the consumer's init).  Before the gate, the ShuffleVertexManager
clamped the unknown source total to 0, read the completed fraction as 1.0,
and released every consumer task at vertex start; the task specs snapshot
physical_input_count=-1, wait_all returned instantly, and the consumer
SUCCEEDED empty — silent total data loss on every DAG after the first in
a warm process (run 1 wins the race because the initializer finishes
before spec build).
"""
import collections
import os
import random
import threading

import pytest

from tez_tpu.api.vertex_manager import VertexStateUpdate
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.common.payload import InputDescriptor, OutputDescriptor, UserPayload
from tez_tpu.library.vertex_managers import ShuffleVertexManager


def _sg_prop():
    kv = {"tez.runtime.key.class": "bytes", "tez.runtime.value.class": "long"}
    return EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput", payload=kv),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput", payload=kv))


class _GateVMContext:
    """Fake VertexManagerPluginContext with a mutable source parallelism."""

    def __init__(self, payload, in_edges, num_tasks):
        self._payload = UserPayload.of(payload)
        self._in_edges = in_edges
        self.num_tasks = dict(num_tasks)
        self.scheduled = []
        self.state_registrations = []

    @property
    def vertex_name(self):
        return "consumer"

    @property
    def user_payload(self):
        return self._payload

    def get_vertex_num_tasks(self, name):
        return self.num_tasks[name]

    def get_input_vertex_edge_properties(self):
        return dict(self._in_edges)

    def get_output_vertex_edge_properties(self):
        return {}

    def schedule_tasks(self, requests):
        self.scheduled.extend(r.task_index for r in requests)

    def vertex_reconfiguration_restored(self):
        return False

    def register_for_vertex_state_updates(self, vertex_name, states):
        self.state_registrations.append((vertex_name, tuple(states)))


def test_svm_holds_until_source_configured():
    """No release while a shuffle source's parallelism is unresolved; the
    CONFIGURED state update unblocks scheduling."""
    ctx = _GateVMContext(
        {"min_fraction": 0.0, "max_fraction": 0.0},
        {"src": _sg_prop()},
        {"src": -1, "consumer": 2})
    vm = ShuffleVertexManager(ctx)
    vm.initialize()
    vm.on_vertex_started([])
    assert ctx.scheduled == [], \
        "consumer released against an unconfigured source"
    # the source initializer resolves parallelism -> CONFIGURED fires
    ctx.num_tasks["src"] = 3
    vm.on_vertex_state_updated(VertexStateUpdate("src", "CONFIGURED"))
    assert sorted(ctx.scheduled) == [0, 1]


def test_svm_registers_for_source_state_updates():
    ctx = _GateVMContext({}, {"src": _sg_prop()}, {"src": -1, "consumer": 2})
    vm = ShuffleVertexManager(ctx)
    vm.initialize()
    assert ("src", ("CONFIGURED",)) in ctx.state_registrations


def test_svm_auto_parallel_waits_for_source_configured():
    """Unknown source total must not finalize the (irreversible) auto-
    parallelism decision as if there were zero sources."""
    ctx = _GateVMContext(
        {"auto_parallel": True, "min_fraction": 0.0, "max_fraction": 0.0},
        {"src": _sg_prop()},
        {"src": -1, "consumer": 4})
    vm = ShuffleVertexManager(ctx)
    vm.initialize()
    vm.on_vertex_started([])
    assert not vm._parallelism_determined
    assert ctx.scheduled == []


def test_fetch_table_rejects_negative_slot_count():
    """Defense in depth: a spec built against an unconfigured source must
    fail the attempt loudly, never succeed empty."""
    from tez_tpu.library.inputs import ShuffleFetchTable
    with pytest.raises(ValueError, match="unresolved physical input count"):
        ShuffleFetchTable(None, -1, 0)


def _write_corpus(path, num_lines, seed):
    words = ["apple", "banana", "cherry", "date", "fig", "grape", "kiwi"]
    rng = random.Random(seed)
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(words) for _ in range(rng.randrange(1, 10))]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def test_pipelined_wordcount_repeated_same_process(tmp_path):
    """The original failure mode: with auto source parallelism (initializer
    driven) and pipelined shuffle, run 2+ in a warm process lost ALL data
    because consumers were scheduled before the tokenizer configured."""
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    golden = _write_corpus(str(corpus), num_lines=200, seed=3)
    for run in (1, 2):
        out_dir = str(tmp_path / f"out{run}")
        state = ordered_wordcount.run(
            [str(corpus)], out_dir,
            conf={"tez.staging-dir": str(tmp_path / f"stg{run}"),
                  "tez.runtime.pipelined-shuffle.enabled": True})
        assert state == "SUCCEEDED"
        rows = {}
        with open(os.path.join(out_dir, "part-00000"), "rb") as fh:
            for line in fh:
                word, count = line.rstrip(b"\n").split(b"\t")
                rows[word.decode()] = int(count)
        assert rows == dict(golden), f"run {run} lost data"


def test_pipelined_wordcount_interleaved_dags_bit_exact(tmp_path):
    """Two pipelined wordcount DAGs interleaved in the same process
    (distinct corpora, distinct staging dirs, barrier-synced start) must
    each stay bit-exact: the process-global shuffle plane keys every
    registration by DAG path, so concurrent pipelined spills from one DAG
    must never satisfy — or corrupt — the other's fetches."""
    from tez_tpu.examples import ordered_wordcount
    corpora, goldens = {}, {}
    for run_id, seed in (("a", 7), ("b", 11)):
        path = tmp_path / f"in-{run_id}.txt"
        goldens[run_id] = _write_corpus(str(path), num_lines=150, seed=seed)
        corpora[run_id] = str(path)
    errs, start = [], threading.Barrier(2)

    def drive(run_id):
        try:
            start.wait(timeout=30)
            out_dir = str(tmp_path / f"out-{run_id}")
            state = ordered_wordcount.run(
                [corpora[run_id]], out_dir,
                conf={"tez.staging-dir": str(tmp_path / f"stg-{run_id}"),
                      "tez.runtime.pipelined-shuffle.enabled": True})
            assert state == "SUCCEEDED"
            rows = {}
            with open(os.path.join(out_dir, "part-00000"), "rb") as fh:
                for line in fh:
                    word, count = line.rstrip(b"\n").split(b"\t")
                    rows[word.decode()] = int(count)
            assert rows == dict(goldens[run_id]), \
                f"dag {run_id} lost or cross-mixed data"
        except BaseException as e:  # noqa: BLE001 — surface on main thread
            errs.append((run_id, e))

    threads = [threading.Thread(target=drive, args=(r,), daemon=True)
               for r in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
