"""Out-of-process runner tests: the multi-process execution mode with the
socket umbilical and cross-process shuffle (the MiniCluster-style tier:
real processes, real sockets — SURVEY.md §4 tier 3)."""
import collections
import os
import random

import pytest

from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient


@pytest.fixture()
def proc_client(tmp_staging):
    c = TezClient.create("proc", {
        "tez.staging-dir": tmp_staging,
        "tez.runner.mode": "subprocess",
        "tez.am.local.num-containers": 2,
        # force runner processes onto CPU (tests must not touch real TPU)
        "tez.am.runner.env": {"JAX_PLATFORMS": "cpu",
                              "PALLAS_AXON_POOL_IPS": ""},
    }).start()
    yield c
    c.stop()


def write_corpus(path, num_lines=300, seed=0):
    rng = random.Random(seed)
    words = [f"w{i:02d}" for i in range(25)]
    counts = collections.Counter()
    with open(path, "w") as fh:
        for _ in range(num_lines):
            line = [rng.choice(words) for _ in range(6)]
            counts.update(line)
            fh.write(" ".join(line) + "\n")
    return counts


def test_ordered_wordcount_across_processes(proc_client, tmp_path):
    """Full OrderedWordCount with producer and consumer tasks in SEPARATE
    runner processes: task specs over the socket umbilical, shuffle data
    over the TCP shuffle servers with HMAC auth."""
    from tez_tpu.examples import ordered_wordcount
    corpus = tmp_path / "in.txt"
    golden = write_corpus(str(corpus))
    out = str(tmp_path / "out")
    dag = ordered_wordcount.build_dag([str(corpus)], out,
                                      tokenizer_parallelism=2,
                                      summation_parallelism=2)
    status = proc_client.submit_dag(dag).wait_for_completion(timeout=120)
    assert status.state is DAGStatusState.SUCCEEDED
    rows = {}
    for f in sorted(os.listdir(out)):
        if f.startswith("part-"):
            for line in open(os.path.join(out, f), "rb"):
                w, c = line.rstrip(b"\n").split(b"\t")
                rows[w.decode()] = int(c)
    assert rows == dict(golden)
    # cross-process fetches actually happened (DCN counter nonzero) unless
    # both vertices landed in one runner — with 2 runners and 4+ tasks at
    # least some fetches cross processes
    counters = status.counters.to_dict().get("TaskCounter", {})
    assert counters.get("SHUFFLE_BYTES", 0) > 0


def test_failing_task_retries_across_processes(proc_client):
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    v = Vertex.create("v", ProcessorDescriptor.create(
        "tez_tpu.library.test_components:TestProcessor",
        payload={"do_fail": True, "failing_task_indices": [0],
                 "failing_upto_attempt": 0}), 2)
    status = proc_client.submit_dag(
        DAG.create("retry").add_vertex(v)).wait_for_completion(timeout=120)
    assert status.state is DAGStatusState.SUCCEEDED


def test_runner_process_killed_midtask_recovers(tmp_staging):
    """SIGKILL a runner process while its task runs: the heartbeat monitor
    times the attempt out, the pool respawns a runner, the task retries and
    the DAG completes (container-loss recovery, reference:
    ContainerHeartbeatHandler + container reallocation)."""
    import signal
    import time
    from tez_tpu.common.payload import ProcessorDescriptor
    from tez_tpu.dag.dag import DAG, Vertex
    c = TezClient.create("killer", {
        "tez.staging-dir": tmp_staging,
        "tez.runner.mode": "subprocess",
        "tez.am.local.num-containers": 2,
        "tez.task.heartbeat.timeout-ms": 1000,
        "tez.am.runner.env": {"JAX_PLATFORMS": "cpu",
                              "PALLAS_AXON_POOL_IPS": ""},
    }).start()
    try:
        am = c.framework_client.am
        am.heartbeat_monitor.check_interval = 0.2
        dag = DAG.create("killdag").add_vertex(Vertex.create(
            "v", ProcessorDescriptor.create(
                "tez_tpu.library.processors:SleepProcessor",
                payload={"sleep_ms": 4000}), 2))
        dc = c.submit_dag(dag)
        # Deterministic victim selection: wait until some attempt is
        # actually RUNNING in a live runner process, then kill THAT
        # process (a fixed sleep races child startup on a loaded box).
        from tez_tpu.am.task_impl import TaskAttemptState
        deadline = time.time() + 30
        victim = None
        while time.time() < deadline and victim is None:
            running_cids = set()
            d = am.current_dag
            for v in (d.vertices.values() if d else ()):
                for t in v.tasks.values():
                    for a in t.attempts.values():
                        if a.state is TaskAttemptState.RUNNING and \
                                a.container_id is not None:
                            running_cids.add(str(a.container_id))
            if running_cids:
                with am.runner_pool._lock:
                    for p, cid in am.runner_pool._procs.values():
                        if str(cid) in running_cids and p.poll() is None:
                            victim = p
                            break
            if victim is None:
                time.sleep(0.1)
        assert victim is not None, "no attempt started in a runner process"
        os.kill(victim.pid, signal.SIGKILL)
        status = dc.wait_for_completion(timeout=60)
        assert status.state is DAGStatusState.SUCCEEDED
        d = am.dag_counters.to_dict().get("DAGCounter", {})
        # 2 original tasks + at least one retry after the kill
        assert d.get("TOTAL_LAUNCHED_TASKS", 0) >= 3
    finally:
        c.stop()
