"""Chaos soak scenarios: compound fault storms driven by the deterministic
fault plane, each checked bit-exact against a fault-free run.

Three compound scenarios (fetch+blacklist+speculation, AM-kill+recovery
replay, corrupt-spill+CRC-quarantine+rerun) plus a fixed-seed tier-1 smoke
of the `python -m tez_tpu.tools.chaos` harness and a multi-seed slow soak.
"""
import os
import time

import pytest

from tez_tpu.am.app_master import DAGAppMaster
from tez_tpu.am.dag_impl import DAGState
from tez_tpu.am.history import HistoryEventType
from tez_tpu.client.dag_client import DAGStatusState
from tez_tpu.client.tez_client import TezClient
from tez_tpu.common import config as C
from tez_tpu.common import faults
from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                    ProcessorDescriptor)
from tez_tpu.dag.dag import DAG, Edge, Vertex
from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                       EdgeProperty, SchedulingType)
from tez_tpu.library.processors import SimpleProcessor
from tez_tpu.tools import chaos

CONF_KV = {"tez.runtime.key.class": "bytes",
           "tez.runtime.value.class": "long"}


def _sg_edge(producer, consumer):
    return Edge.create(producer, consumer, EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=CONF_KV),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput",
            payload=CONF_KV)))


def _emit_count_dag(name, result_path, consumer_cls=None, payload=None):
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        chaos.ChaosEmitProcessor), 2)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        consumer_cls or chaos.ChaosCountProcessor,
        payload=payload or {"result_path": result_path}), 1)
    dag = DAG.create(name).add_vertex(producer).add_vertex(consumer)
    dag.add_edge(_sg_edge(producer, consumer))
    return dag


def _read(path):
    with open(path, "rb") as fh:
        return fh.read()


def _run_one(tmp_path, name, dag, extra_conf=None, timeout=90):
    """Fresh client per run so counters/history are per-scenario. Returns
    (state, am) — am outlives the stopped client for forensics."""
    client = TezClient.create(name, {
        "tez.staging-dir": str(tmp_path / name / "staging"),
        "tez.am.local.num-containers": 4,
        **(extra_conf or {})}).start()
    try:
        status = client.submit_dag(dag).wait_for_completion(timeout=timeout)
        return status.state, client.framework_client.am
    finally:
        client.stop()


# ---------------------------------------------------------------- tier-1

def test_storm_generation_deterministic():
    for seed in (0, 7, 1234):
        assert chaos.make_storm(seed) == chaos.make_storm(seed)
        for rule in chaos.make_storm(seed).split(";"):
            assert rule in chaos.STORM_MENU
    assert chaos.make_storm(0) != chaos.make_storm(1)


def test_chaos_smoke_fixed_seed(tmp_path):
    """Fast fixed-seed run of the chaos harness: one baseline DAG + one
    storm DAG, bit-exact (the CLI equivalent:
    `python -m tez_tpu.tools.chaos --seed 1234`)."""
    ok, spec, detail = chaos.run_trial(1234, str(tmp_path))
    assert ok, f"storm [{spec}] diverged: {detail}"


def test_scenario_fetch_blacklist_speculation(tmp_path):
    """Compound storm: a producer attempt is delayed into straggling (bait
    for the speculator), two injected task failures blacklist the only
    local node (which must then be force-activated), and a fetch read
    fails once — the DAG still succeeds with bit-exact output."""
    base_state, base_am = _run_one(
        tmp_path, "base1", _emit_count_dag(
            "base1", str(tmp_path / "base1.txt")))
    assert base_state is DAGStatusState.SUCCEEDED
    baseline = _read(str(tmp_path / "base1.txt"))

    result = str(tmp_path / "storm1.txt")
    dag = _emit_count_dag("storm1", result)
    dag.set_conf("tez.am.speculation.enabled", True)
    dag.set_conf("tez.am.legacy.speculative.slowtask.threshold", 1.0)
    dag.set_conf("tez.am.soonest.retry.after.no.speculate", 200)
    # order matters: the delay rule (scoped to producer task 0 attempt 0 by
    # the match filter) must claim before the broad fail rule
    dag.set_conf("tez.test.fault.spec",
                 "task.run:delay:ms=3000,n=1,match=_00_000000_0;"
                 "task.run:fail:n=2,exc=runtime;"
                 "shuffle.fetch.read:fail:n=1,exc=io")
    dag.set_conf("tez.test.fault.seed", 1)
    state, am = _run_one(tmp_path, "storm1", dag, extra_conf={
        "tez.am.maxtaskfailures.per.node": 2,
        "tez.am.task.max.failed.attempts": 4})
    assert state is DAGStatusState.SUCCEEDED
    assert _read(result) == baseline
    # both node-health transitions made it into the history stream
    assert am.logging_service.of_type(HistoryEventType.NODE_BLACKLISTED)
    assert am.logging_service.of_type(HistoryEventType.NODE_FORCED_ACTIVE)
    d = am.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("NUM_SPECULATIONS", 0) >= 1


class GatedChaosCountProcessor(SimpleProcessor):
    """ChaosCountProcessor behind a sentinel-file gate (payload: gate_path,
    result_path) — lets the test crash the AM while the consumer holds."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        while not os.path.exists(payload["gate_path"]):
            time.sleep(0.05)
        reader = inputs["producer"].get_reader()
        totals = {k: sum(vs) for k, vs in reader}
        lines = [f"{k.decode()} {v}" for k, v in sorted(totals.items())]
        with open(payload["result_path"], "w") as fh:
            fh.write("\n".join(lines) + "\n")


def test_scenario_am_kill_recovery_replay(tmp_staging, tmp_path):
    """Compound storm: journal appends/fsyncs are slowed and a task attempt
    is failed while the AM is killed mid-DAG; the successor AM replays the
    journal, short-circuits the finished producer, and the released
    consumer produces bit-exact output."""
    # fault-free baseline (gate pre-opened)
    base_gate = str(tmp_path / "base_gate")
    open(base_gate, "w").close()
    base_result = str(tmp_path / "base2.txt")
    base_state, _ = _run_one(tmp_path, "base2", _emit_count_dag(
        "base2", base_result, consumer_cls=GatedChaosCountProcessor,
        payload={"gate_path": base_gate, "result_path": base_result}))
    assert base_state is DAGStatusState.SUCCEEDED
    baseline = _read(base_result)

    gate = str(tmp_path / "gate")
    result = str(tmp_path / "storm2.txt")
    dag = _emit_count_dag("storm2", result,
                          consumer_cls=GatedChaosCountProcessor,
                          payload={"gate_path": gate, "result_path": result})
    dag.set_conf("tez.test.fault.spec",
                 "am.recovery.append:delay:ms=10,n=5;"
                 "am.recovery.fsync:delay:ms=10,n=5;"
                 "task.run:fail:n=1,exc=runtime,match=_00_000")
    dag.set_conf("tez.test.fault.seed", 2)
    plan = dag.create_dag_plan()

    conf = C.TezConfiguration({"tez.staging-dir": tmp_staging,
                               "tez.am.local.num-containers": 3,
                               "tez.am.task.max.failed.attempts": 4})
    am1 = DAGAppMaster("app_1_chaos", conf, attempt=1)
    am1.start()
    am1.submit_dag(plan)
    deadline = time.time() + 60
    while time.time() < deadline:
        st = am1.current_dag.status_dict()
        if st["vertices"].get("producer", {}).get("state") == "SUCCEEDED":
            break
        time.sleep(0.1)
    else:
        pytest.fail("producer vertex never finished under storm")
    am1.stop()               # crash while the consumer is gated

    am2 = DAGAppMaster("app_1_chaos", conf, attempt=2)
    am2.start()
    recovered = am2.recover_and_resume()
    assert recovered is not None
    open(gate, "w").close()
    assert am2.wait_for_dag(recovered, timeout=60) is DAGState.SUCCEEDED
    assert _read(result) == baseline
    # producer restored from the journal, not re-run
    d = am2.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) == 1
    am2.stop()


def test_scenario_commit_storm_exactly_once(tmp_path):
    """The exactly-once commit scenario: the AM is killed between the
    ledger's DAG_COMMIT_STARTED and DAG_COMMIT_FINISHED records (a
    commit.publish delay fault parks the publisher in that window), the
    successor attempt resumes the commit from the ledger, and the published
    output is bit-exact vs a fault-free run — _SUCCESS present, no orphaned
    _temporary tree, no double-published part file.  The parked publisher
    wakes as a zombie from the superseded epoch and must be fenced.
    CLI equivalent: `python -m tez_tpu.tools.chaos --commit-storm`."""
    ok, detail = chaos.run_commit_storm(str(tmp_path))
    assert ok, detail


def test_scenario_corrupt_spill_quarantine_rerun(tmp_path):
    """Compound storm: a fetched shuffle payload is corrupted in flight;
    the CRC check rejects it, the consumer quarantines the source and the
    producer re-runs — output stays bit-exact."""
    base_state, _ = _run_one(tmp_path, "base3", _emit_count_dag(
        "base3", str(tmp_path / "base3.txt")))
    assert base_state is DAGStatusState.SUCCEEDED
    baseline = _read(str(tmp_path / "base3.txt"))

    result = str(tmp_path / "storm3.txt")
    dag = _emit_count_dag("storm3", result)
    dag.set_conf("tez.test.fault.spec", "shuffle.data:corrupt:n=1")
    dag.set_conf("tez.test.fault.seed", 3)
    state, am = _run_one(tmp_path, "storm3", dag)
    assert state is DAGStatusState.SUCCEEDED
    assert _read(result) == baseline
    # the corruption really fired ...
    assert any(p == "shuffle.data" and a == "corrupt"
               for (p, _d, a) in faults.plane().journal)
    # ... and forced a producer re-run beyond the fault-free 3 tasks
    d = am.dag_counters.to_dict().get("DAGCounter", {})
    assert d.get("TOTAL_LAUNCHED_TASKS", 0) >= 4


def test_scenario_device_hang():
    """Device-plane hang scenario: one span's XLA dispatch hangs for longer
    than the whole test budget; the dispatch watchdog abandons it, the span
    is re-sorted through the host engine, flush() returns in bounded time,
    and every spill is bit-exact vs the synchronous run.  CLI equivalent:
    `python -m tez_tpu.tools.chaos --device-hang`."""
    ok, detail = chaos.run_device_hang(0)
    assert ok, detail


def test_scenario_device_oom_storm():
    """Device-plane OOM storm: repeated RESOURCE_EXHAUSTED dispatches drive
    the containment ladder end to end — split retry on device first, host
    failover at the floor, breaker trip after the configured consecutive
    failures, short-circuit of the remaining spans, then half-open probe
    recovery after the cooldown — with both the storm run and the recovery
    run bit-exact.  CLI equivalent:
    `python -m tez_tpu.tools.chaos --device-oom-storm`."""
    ok, detail = chaos.run_device_oom_storm(0)
    assert ok, detail


@pytest.mark.slow
def test_chaos_soak_multi_seed(tmp_path):
    """Soak: consecutive seeded storms, all bit-exact vs one baseline."""
    state, baseline = chaos._run_dag(str(tmp_path), "baseline")
    assert state == DAGStatusState.SUCCEEDED.name and baseline
    failures = []
    for seed in range(10):
        ok, spec, detail = chaos.run_trial(seed, str(tmp_path),
                                           baseline=baseline)
        if not ok:
            failures.append((seed, spec, detail))
    assert not failures, (
        f"{failures}; repro: python -m tez_tpu.tools.chaos "
        f"--seed {failures[0][0]}")
