"""Partition-indexed disk runs (PartitionedRunWriter/FileRun) and the
streaming producer final merge (DeviceSorter.flush_run spill path).

Reference semantics: the final IFile + TezSpillRecord a producer task
publishes (PipelinedSorter.java:559 final merge, TezMerger.java:76 bounded
merge, TezSpillRecord.java partition index) — here one partition-indexed
file streamed blockwise with bounded memory.
"""
import os

import numpy as np
import pytest

from tez_tpu.common.counters import TaskCounter, TezCounters
from tez_tpu.ops.runformat import (FileRun, KVBatch, PartitionedRunWriter,
                                   Run, save_run_partitioned)
from tez_tpu.ops.sorter import DeviceSorter, sum_long_combiner

from test_ops import golden_sorted, random_pairs


def _partition_sorted_run(pairs, num_partitions):
    golden = golden_sorted(pairs, num_partitions)
    batch = KVBatch.from_pairs([(k, v) for _, k, _, v in golden])
    counts = np.bincount([p for p, *_ in golden], minlength=num_partitions)
    row_index = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(counts, out=row_index[1:])
    return Run(batch, row_index), golden


def test_partitioned_run_roundtrip(tmp_path):
    pairs = random_pairs(1500, seed=11)
    run, golden = _partition_sorted_run(pairs, 5)
    path = str(tmp_path / "r.prun")
    save_run_partitioned(run, path, block_records=100)
    fr = FileRun(path)
    assert fr.num_partitions == 5
    assert fr.nbytes == sum(len(k) + len(v) for _, k, _, v in golden)
    for p in range(5):
        expected = [(k, v) for pp, k, _, v in golden if pp == p]
        assert fr.partition_row_count(p) == len(expected)
        assert fr.partition_nbytes(p) == sum(
            len(k) + len(v) for k, v in expected)
        assert list(fr.partition(p).iter_pairs()) == expected
        # block streaming is bounded and ordered
        blocks = list(fr.iter_partition_blocks(p))
        assert all(b.num_records <= 100 for b in blocks)
        flat = [kv for b in blocks for kv in b.iter_pairs()]
        assert flat == expected
    back = fr.to_run()
    assert list(back.batch.iter_pairs()) == list(run.batch.iter_pairs())
    assert np.array_equal(back.row_index, run.row_index)


def test_partitioned_run_empty_partitions(tmp_path):
    batch = KVBatch.from_pairs([(b"k1", b"v1"), (b"k2", b"v2")])
    run = Run(batch, np.array([0, 0, 2, 2, 2], dtype=np.int64))
    path = str(tmp_path / "e.prun")
    save_run_partitioned(run, path)
    fr = FileRun(path)
    assert fr.empty_partition_flags() == [True, False, True, True]
    assert fr.partition(0).num_records == 0
    assert list(fr.partition(1).iter_pairs()) == [(b"k1", b"v1"),
                                                  (b"k2", b"v2")]
    assert fr.partition(3).num_records == 0


def test_partitioned_run_codec(tmp_path):
    pytest.importorskip("zstandard", reason="zstd wheel absent")
    pairs = [(f"dup{i % 9}".encode(), b"x" * 64) for i in range(3000)]
    run, _ = _partition_sorted_run(pairs, 3)
    raw = str(tmp_path / "raw.prun")
    comp = str(tmp_path / "z.prun")
    save_run_partitioned(run, raw)
    save_run_partitioned(run, comp, codec="zstd")
    assert os.path.getsize(comp) < os.path.getsize(raw)
    assert list(FileRun(comp).to_run().batch.iter_pairs()) == \
        list(run.batch.iter_pairs())


def test_partition_major_order_enforced(tmp_path):
    w = PartitionedRunWriter(str(tmp_path / "o.prun"), 3)
    w.append(KVBatch.from_pairs([(b"a", b"1")]), 2)
    with pytest.raises(ValueError, match="partition-major"):
        w.append(KVBatch.from_pairs([(b"b", b"2")]), 1)


def test_flush_run_streams_spilled_spans(tmp_path):
    """Spilled spans merge blockwise into a disk-backed FileRun — no second
    full sort, bounded memory — and the result is byte-identical to the
    in-RAM merge."""
    pairs = random_pairs(4000, seed=12)
    ctr = TezCounters()
    s = DeviceSorter(num_partitions=3, span_budget_bytes=4096,
                     spill_dir=str(tmp_path), mem_budget_bytes=8192,
                     counters=ctr)
    for k, v in pairs:
        s.write(k, v)
    result = s.flush_run()
    assert isinstance(result, FileRun), "spill-scale flush must stay on disk"
    golden = golden_sorted(pairs, 3)
    got = []
    for p in range(3):
        got.extend(result.partition(p).iter_pairs())
    assert got == [(k, v) for _, k, _, v in golden]
    snap = ctr.to_dict().get("TaskCounter", {})
    assert snap.get("ADDITIONAL_SPILLS_BYTES_READ", 0) > 0
    assert snap.get("ADDITIONAL_SPILLS_BYTES_WRITTEN", 0) > 0
    # span spill files were consumed and removed; only the final file stays
    left = [f for f in os.listdir(tmp_path) if f.endswith(".prun")]
    assert left == [os.path.basename(result.path)]
    result.delete()
    assert not os.path.exists(result.path)


def test_flush_run_streaming_combiner(tmp_path):
    """Block-local combine during the streaming merge preserves totals (sum
    combiner is associative; duplicates split across block edges re-unify at
    the consumer's grouped reader)."""
    from tez_tpu.ops.serde import VarLongSerde
    serde = VarLongSerde()
    words = [f"w{i % 50:03d}".encode() for i in range(6000)]
    ctr = TezCounters()
    s = DeviceSorter(num_partitions=2, span_budget_bytes=4096,
                     spill_dir=str(tmp_path), mem_budget_bytes=8192,
                     counters=ctr, combiner=sum_long_combiner)
    for w in words:
        s.write(w, serde.to_bytes(1))
    result = s.flush_run()
    totals = {}
    for p in range(2):
        for k, v in result.partition(p).iter_pairs():
            totals[k] = totals.get(k, 0) + serde.from_bytes(v)
    assert totals == {w: 120 for w in set(words)}
    if isinstance(result, FileRun):
        result.delete()
