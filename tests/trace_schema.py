"""Minimal Chrome/Perfetto ``trace_event`` JSON schema checker.

Not a full validator — just the invariants the Perfetto UI and
chrome://tracing actually require to load a "JSON Array Format" trace:
a ``traceEvents`` list whose members carry the right fields per phase.
Raises AssertionError with a pointed message on the first violation so a
failing test names the bad event.
"""
from typing import Any, Dict

# phases we emit; "X"=complete, "i"=instant, "M"=metadata
_KNOWN_PHASES = {"X", "i", "M", "B", "E"}


def check_event(ev: Dict[str, Any], idx: int) -> None:
    assert isinstance(ev, dict), f"event[{idx}] is not an object: {ev!r}"
    ph = ev.get("ph")
    assert ph in _KNOWN_PHASES, f"event[{idx}] bad phase {ph!r}"
    assert isinstance(ev.get("name"), str) and ev["name"], \
        f"event[{idx}] missing name"
    assert isinstance(ev.get("pid"), int), f"event[{idx}] missing int pid"
    assert isinstance(ev.get("tid"), int), f"event[{idx}] missing int tid"
    if ph == "M":
        assert ev["name"] in ("thread_name", "process_name"), \
            f"event[{idx}] unknown metadata {ev['name']!r}"
        assert isinstance(ev.get("args", {}).get("name"), str), \
            f"event[{idx}] metadata without args.name"
        return
    ts = ev.get("ts")
    assert isinstance(ts, int) and ts >= 0, \
        f"event[{idx}] ts must be a non-negative int (µs), got {ts!r}"
    if ph == "X":
        dur = ev.get("dur")
        assert isinstance(dur, int) and dur > 0, \
            f"event[{idx}] complete event needs positive int dur, got {dur!r}"
    if ph == "i":
        assert ev.get("s", "t") in ("t", "p", "g"), \
            f"event[{idx}] bad instant scope {ev.get('s')!r}"
    args = ev.get("args", {})
    assert isinstance(args, dict), f"event[{idx}] args not an object"


def check_trace(trace: Dict[str, Any]) -> int:
    """Validate a trace dict; returns the number of events checked."""
    assert isinstance(trace, dict), "trace root must be an object"
    events = trace.get("traceEvents")
    assert isinstance(events, list), "traceEvents must be a list"
    for i, ev in enumerate(events):
        check_event(ev, i)
    return len(events)
