"""Time-series registry: ring eviction accounting, deterministic
windowed aggregation, histogram snapshot/merge determinism, collector
write-through and failure accounting, per-plane busy attribution.

Every test drives :class:`TimeSeriesRegistry` with explicit ``now_ns``
values, so the expected windows are exact — no sleeps, no wall clock.
"""
import pytest

from tez_tpu.common import metrics
from tez_tpu.obs import timeseries
from tez_tpu.obs.timeseries import Series, TimeSeriesRegistry

S = 1_000_000_000  # ns


def _hist(name, values):
    h = metrics.registry().histogram(name)
    for v in values:
        h.observe(v)
    return h


def test_ring_eviction_is_counted_never_silent():
    reg = TimeSeriesRegistry(capacity=4)
    for i in range(10):
        metrics.set_gauge("tsr.g", float(i))
        reg.sample(now_ns=i * S)
    s = reg._series["tsr.g"]
    assert len(s.points) == 4
    assert s.evicted == 6
    # the newest samples survive; the oldest were the ones evicted
    assert [p[1] for p in s.points] == [6.0, 7.0, 8.0, 9.0]
    acct = reg.accounting()
    assert acct["evicted"] >= 6
    assert acct["samples"] == 10
    assert acct["series"] >= 1


def test_series_capacity_floor_is_two():
    s = Series("x", "gauge", 0)
    assert s.capacity == 2


def test_hist_window_delta_is_exact_and_repeatable():
    reg = TimeSeriesRegistry()
    h = _hist("tsw.lat", [])
    reg.sample(now_ns=0)                   # zero baseline
    h.observe(100.0)
    h.observe(200.0)
    reg.sample(now_ns=1 * S)
    h.observe(300.0)
    reg.sample(now_ns=2 * S)

    # wide window: delta against the zero baseline == everything
    wide = reg.window("tsw.lat", 10.0, now_ns=2 * S)
    assert wide["count"] == 3
    assert wide["sum_ms"] == 600.0
    assert wide["rate_per_s"] == 1.5      # 3 obs over exactly 2 s

    # narrow window: base is the newest sample at/before now-1s, so
    # only the 300 ms observation is inside
    narrow = reg.window("tsw.lat", 1.0, now_ns=2 * S)
    assert narrow["count"] == 1
    assert narrow["sum_ms"] == 300.0
    assert 256.0 < narrow["p95"] <= 512.0

    # pure function of ring contents: identical on every call
    assert reg.window("tsw.lat", 1.0, now_ns=2 * S) == narrow
    assert reg.window("tsw.lat", 10.0, now_ns=2 * S) == wide


def test_hist_window_quantiles_match_bucket_math():
    reg = TimeSeriesRegistry()
    values = [10.0, 20.0, 40.0, 80.0, 700.0]
    h = _hist("tsq.lat", [])
    reg.sample(now_ns=0)
    for v in values:
        h.observe(v)
    reg.sample(now_ns=1 * S)
    w = reg.window("tsq.lat", 5.0, now_ns=1 * S)
    counts = [0] * metrics.NUM_BUCKETS
    for v in values:
        counts[metrics.bucket_index(v)] += 1
    for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
        assert w[key] == round(metrics.quantile_from_buckets(counts, q), 4)


def test_hist_window_clamps_negative_deltas():
    # a registry reset between samples makes cumulative counts go DOWN;
    # the windowed delta must clamp at zero, not report garbage
    pts = [(0, (5, 0), 5, 500.0), (1 * S, (1, 0), 1, 80.0)]
    w = timeseries._hist_window(pts, 10 * S, 1 * S)
    assert w["count"] == 0
    assert w["sum_ms"] == 0.0
    assert w["p95"] == 0.0


def test_hist_window_covered_reports_truncation():
    reg = TimeSeriesRegistry()
    _hist("tsc.lat", [50.0])
    reg.sample(now_ns=10 * S)
    reg.sample(now_ns=11 * S)
    # asked for 60 s but the ring only spans 1 s
    w = reg.window("tsc.lat", 60.0, now_ns=11 * S)
    assert w["covered_s"] == 1.0


def test_gauge_window_stats_and_strict_start():
    reg = TimeSeriesRegistry()
    for i, v in enumerate([1.0, 3.0, 5.0, 7.0]):
        metrics.set_gauge("tsg.depth", v)
        reg.sample(now_ns=i * S)
    w = reg.window("tsg.depth", 2.0, now_ns=3 * S)
    # start = 1 s, and the cut is strict: only t=2s and t=3s qualify
    assert w == {"n": 2, "last": 7.0, "min": 5.0, "max": 7.0,
                 "mean": 6.0, "kind": "gauge"}
    # empty window falls back to the last known value with n=0
    stale = reg.window("tsg.depth", 1.0, now_ns=30 * S)
    assert stale["n"] == 0 and stale["last"] == 7.0


def test_window_of_unknown_series_is_none():
    reg = TimeSeriesRegistry()
    assert reg.window("never.sampled", 5.0, now_ns=0) is None


def test_hist_snapshot_and_merge_are_order_independent():
    values = [3.0, 17.0, 90.0, 2048.0, 70000.0, 90.0]
    a = metrics.Histogram("a")
    b = metrics.Histogram("b")
    for v in values:
        a.observe(v)
    for v in reversed(values):
        b.observe(v)
    sa, sb = a.snapshot(), b.snapshot()
    assert sa.counts == sb.counts
    assert sa.count == sb.count == len(values)
    assert sa.sum_ms == pytest.approx(sb.sum_ms)
    # merging two snapshots == observing the union, in any order
    merged = [x + y for x, y in zip(sa.counts, sb.counts)]
    union = metrics.Histogram("u")
    for v in values * 2:
        union.observe(v)
    assert merged == union.counts
    for q in (0.5, 0.95, 0.99):
        assert metrics.quantile_from_buckets(merged, q) == \
            union.quantile(q)


def test_sampled_rings_reproduce_identically():
    # two registries fed the same snapshots at the same timestamps agree
    # on every windowed aggregate — the determinism the golden surfaces
    # (burn alerts, /metrics.json windows) are built on
    r1, r2 = TimeSeriesRegistry(), TimeSeriesRegistry()
    h = _hist("tsd.lat", [])
    for t, obs in ((0, []), (1, [40.0]), (2, [600.0, 70.0]), (3, [9.0])):
        for v in obs:
            h.observe(v)
        r1.sample(now_ns=t * S)
        r2.sample(now_ns=t * S)
    for win in (1.0, 2.0, 10.0):
        assert r1.window("tsd.lat", win, now_ns=3 * S) == \
            r2.window("tsd.lat", win, now_ns=3 * S)


def test_collector_write_through_and_error_accounting():
    reg = TimeSeriesRegistry()
    reg.register_collector("lanes", lambda: {"mesh.lane.0.occupancy": 0.75})
    reg.register_collector("sick", lambda: 1 / 0)
    reg.sample(now_ns=1 * S)
    # collector gauges ride the rings AND write through to the
    # point-in-time gauge surface GET /metrics renders
    assert reg.window("mesh.lane.0.occupancy", 5.0, 1 * S)["last"] == 0.75
    assert metrics.registry().gauges()["mesh.lane.0.occupancy"] == 0.75
    assert reg.accounting()["collector_errors"] == 1
    reg.unregister_collector("sick")
    reg.sample(now_ns=2 * S)
    assert reg.accounting()["collector_errors"] == 1


def test_plane_busy_attribution_uses_shared_mapping():
    reg = TimeSeriesRegistry()
    reg.sample(now_ns=0)                  # zero baseline for well-knowns
    _hist("store.publish", [100.0])
    _hist("mesh.exchange.round", [40.0, 60.0])
    _hist("obs.flight.dump", [999.0])     # mapped to None: never blamed
    reg.sample(now_ns=1 * S)
    busy = reg.plane_busy_ms(10.0, now_ns=1 * S)
    assert busy["store"] == 100.0
    assert busy["exchange"] == 100.0
    assert set(busy) == set(timeseries.PLANES)
    assert sum(busy.values()) == 200.0


def test_reset_drops_data_keeps_collectors():
    reg = TimeSeriesRegistry()
    reg.register_collector("keep", lambda: {"k.g": 1.0})
    reg.sample(now_ns=0)
    reg.note_scrape_error()
    reg.reset()
    acct = reg.accounting()
    assert acct["series"] == 0 and acct["samples"] == 0
    assert acct["scrape_errors"] == 0
    assert reg.collectors() == ["keep"]
