"""Flight-recorder, SLO-watchdog, and doctor tests (docs/doctor.md).

Ring semantics first (bounded overwrite order, disarm-mid-write safety,
snapshot-while-appending consistency, dump budget + round-trip), then the
auto-dump triggers (breaker-open, admission shed — the shed path must
also latch an SLO breach that lands in the dump, the history journal,
and GET /slo), the scrape-race regression (/metrics vs a concurrently
retiring DAG), and the doctor's golden waterfall on a seeded two-vertex
DAG where every plane percentage is known by construction.
"""
from __future__ import annotations

import itertools
import json
import threading
from types import SimpleNamespace

import pytest

from tez_tpu.am.admission import AdmissionController
from tez_tpu.am.history import HistoryEventType
from tez_tpu.am.web import _Handler
from tez_tpu.client.errors import DAGRejectedError
from tez_tpu.common import config as C
from tez_tpu.common import metrics
from tez_tpu.common.counters import TezCounters
from tez_tpu.common.payload import ProcessorDescriptor
from tez_tpu.dag.dag import DAG, Vertex
from tez_tpu.obs import flight, slo
from tez_tpu.tools import doctor
from tez_tpu.tools.history_parser import (AttemptInfo, DagInfo, TaskInfo,
                                          VertexInfo)
from tests.trace_schema import check_trace


@pytest.fixture(autouse=True)
def _flight_clean():
    """conftest resets the fault/trace/metrics planes but not this one."""
    flight.clear_all()
    yield
    flight.clear_all()


# ------------------------------------------------------------ ring semantics

def test_disarmed_record_is_noop():
    assert not flight.armed()
    flight.record(flight.MARK, "nobody-home")
    snap = flight.snapshot()
    assert snap.events == [] and snap.dropped_before == 0


def test_ring_bounded_overwrite_keeps_newest_in_order():
    flight.install("t", capacity=16)
    for i in range(50):
        flight.record(flight.MARK, f"e{i}")
    snap = flight.snapshot()
    seqs = [e.seq for e in snap.events]
    assert 0 < len(seqs) <= 16
    # bounded-journal contract: the survivors are exactly the newest
    # records, in append order, and the drop count is honest
    assert seqs == list(range(51 - len(seqs), 51))
    assert snap.dropped_before == seqs[0] - 1
    assert snap.events[-1].name == "e49"
    assert [e.name for e in snap.events] == \
        [f"e{s - 1}" for s in seqs]


def test_ring_capacity_floor():
    flight.install("t", capacity=1)          # floored to 16
    for i in range(16):
        flight.record(flight.MARK, f"e{i}")
    assert len(flight.snapshot().events) == 16


def test_ring_survives_scope_clear_until_clear_all():
    flight.install("a")
    for i in range(3):
        flight.record(flight.MARK, f"e{i}")
    flight.clear("a")
    assert not flight.armed()
    flight.record(flight.MARK, "after-disarm")     # module fn: gated out
    assert len(flight.snapshot().events) == 3      # ring retained
    flight.clear_all()
    assert flight.snapshot().events == []


def test_snapshot_while_appending_is_consistent():
    flight.install("t", capacity=64)
    stop = threading.Event()
    errors = []

    def writer():
        try:
            i = 0
            while not stop.is_set() and i < 20000:
                flight.plane().record(flight.MARK, f"w{i % 100}", a=i)
                i += 1
        except Exception as e:  # noqa: BLE001 — the test IS the catch
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(50):
            snap = flight.snapshot()
            seqs = [e.seq for e in snap.events]
            assert len(seqs) <= 64
            assert seqs == sorted(set(seqs))       # unique, ascending
            for e in snap.events:
                # every name id in the copied bytes must resolve to the
                # string that was interned for it — never garbage
                assert e.name.startswith("w") and e.a % 100 == int(
                    e.name[1:])
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors


def test_disarm_mid_write_is_safe():
    errors = []
    stop = threading.Event()

    def writer():
        try:
            while not stop.is_set():
                # raw plane path: the one that races a concurrent
                # clear_all swapping the ring/name table out
                flight.plane().record(flight.MARK, "racer", a=1)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(30):
            flight.install("x", capacity=32)
            flight.snapshot()
            flight.clear_all()
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors
    assert not flight.armed()


def test_dump_budget_and_roundtrip(tmp_path):
    flight.install("t", dump_dir=str(tmp_path), max_dumps=2)
    flight.record(flight.MARK, "payload", scope="s1", a=7, b=9)
    p1 = flight.auto_dump("unit.reason", scope="s1")
    p2 = flight.auto_dump("unit.reason")
    assert p1 is not None and p2 is not None
    # budget spent for this arm cycle
    assert flight.auto_dump("unit.reason") is None
    with open(p1) as fh:
        payload = json.load(fh)
    assert payload["reason"] == "unit.reason" and payload["scope"] == "s1"
    snap = flight.load_dump(p1)
    ev = next(e for e in snap.events if e.name == "payload")
    assert (ev.kind, ev.scope, ev.a, ev.b) == (flight.MARK, "s1", 7, 9)
    assert snap.anchor == pytest.approx(flight.snapshot().anchor)
    # re-arming resets the budget
    flight.install("t", dump_dir=str(tmp_path), max_dumps=2)
    assert flight.auto_dump("unit.reason") is not None


def test_dump_without_dir_returns_none():
    flight.install("t")
    assert flight.auto_dump("no.dir") is None


def test_install_from_conf():
    assert not flight.install_from_conf(C.TezConfiguration({}), "s")
    assert not flight.armed()
    conf = C.TezConfiguration({C.OBS_FLIGHT_ENABLED.name: True,
                               C.OBS_FLIGHT_BUFFER_EVENTS.name: 32})
    assert flight.install_from_conf(conf, "s")
    assert flight.armed() and "s" in flight.plane().scopes


def test_metrics_observe_feeds_ring():
    flight.install("t")
    metrics.observe("spill.write", 2.5)          # ms
    evs = [e for e in flight.snapshot().events if e.name == "spill.write"]
    assert len(evs) == 1
    assert evs[0].kind == flight.COUNTER and evs[0].a == 2500  # µs


def test_span_edge_feeds_ring_and_maps_to_plane():
    from tez_tpu.common import clock
    flight.install("t")
    wall0, _ = clock.anchor()
    flight.span_edge("fetch.block", wall0 + 1.0, 0.01, cat="fetch")
    snap = flight.snapshot()
    ivs = doctor.intervals_from_flight([snap])
    assert len(ivs) == 1
    s, e, plane, label = ivs[0]
    assert plane == "transport" and label == "fetch.block"
    assert e - s == pytest.approx(0.01, rel=1e-6)
    assert s == pytest.approx(wall0 + 1.0, abs=1e-6)


# ------------------------------------------------------- auto-dump triggers

def test_breaker_open_auto_dumps(tmp_path):
    from tez_tpu.ops.async_stage import CircuitBreaker
    flight.install("t", dump_dir=str(tmp_path))
    br = CircuitBreaker(failures=1, cooldown_ms=60_000.0)
    br.record_failure()
    files = sorted(tmp_path.glob("flight_device.breaker.open_*.json"))
    assert len(files) == 1
    snap = flight.load_dump(str(files[0]))
    opens = [e for e in snap.events
             if e.kind == flight.BREAKER and e.name == "open"]
    assert len(opens) == 1 and opens[0].a == 1


class _StubAM:
    """Minimal DAGAppMaster surface for AdmissionController (the same
    shape test_multitenancy uses): conf, app_id, history sink, and a
    _start_dag that mints fresh ids."""

    def __init__(self, conf=None):
        self.conf = C.TezConfiguration(conf or {})
        self.app_id = "app_flight_1"
        self.events = []
        self._seq = itertools.count(1)
        self.slo_watchdog = None

    def history(self, ev):
        self.events.append(ev)

    def _start_dag(self, plan, recovery_data, tenant):
        return f"dag_{next(self._seq)}"

    def of(self, t):
        return [e for e in self.events if e.event_type is t]


def _plan(name, tenant=""):
    dag = DAG.create(name).add_vertex(Vertex.create(
        "v", ProcessorDescriptor.create(
            "tez_tpu.library.processors:SleepProcessor",
            payload={"sleep_ms": 1}), 1))
    if tenant:
        dag.set_conf("tez.dag.tenant", tenant)
    return dag.create_dag_plan({})


def test_shed_auto_dumps_with_latched_slo_breach(tmp_path):
    """The acceptance chain: a forced shed must surface the SLO breach in
    the flight dump, the history journal, and GET /slo — and the latch
    must hold one typed event per episode, not one per shed."""
    am = _StubAM({"tez.am.session.max-concurrent-dags": 1,
                  "tez.am.session.queue-size": 0,
                  "tez.am.session.shed.retry-after-ms": 100,
                  C.AM_SLO_SHED_RATE.name: 0.01,
                  C.AM_SLO_MIN_COUNT.name: 1})
    am.slo_watchdog = slo.from_conf(am.conf, journal=am.history)
    assert am.slo_watchdog is not None
    flight.install("t", dump_dir=str(tmp_path))
    ac = AdmissionController(am)
    try:
        ac.submit(_plan("d1", tenant="acme"))
        with pytest.raises(DAGRejectedError):
            ac.submit(_plan("d2", tenant="acme"))
        with pytest.raises(DAGRejectedError):
            ac.submit(_plan("d3", tenant="acme"))
    finally:
        ac.stop()

    # the flight dump: one per shed, each containing the ADMIT verdict
    # and (shed #1) the SLO record written BEFORE the dump was cut
    dumps = sorted(tmp_path.glob("flight_am.admit.shed_*.json"))
    assert len(dumps) == 2
    snap = flight.load_dump(str(dumps[0]))
    sheds = [e for e in snap.events
             if e.kind == flight.ADMIT and e.name == "shed"]
    assert sheds and sheds[0].scope == "acme"
    slos = [e for e in snap.events if e.kind == flight.SLO]
    assert len(slos) == 1
    assert slos[0].name == "slo.breach.shed_rate"
    assert slos[0].scope == "acme"
    assert slos[0].a == 5000 and slos[0].b == 100   # 0.5 / 0.01 in bp

    # the history journal: exactly one typed breach (latched across the
    # second shed, whose rate stays over target)
    breaches = am.of(HistoryEventType.TENANT_SLO_BREACH)
    assert len(breaches) == 1
    assert breaches[0].data["tenant"] == "acme"
    assert breaches[0].data["kind"] == slo.KIND_SHED_RATE
    assert breaches[0].data["observed"] == pytest.approx(0.5)

    # GET /slo: the breach is live in the watchdog surface
    status = _Handler._slo(am)
    assert status["enabled"] and status["total_breaches"] == 1
    active = {(b["tenant"], b["kind"]) for b in status["active"]}
    assert ("acme", slo.KIND_SHED_RATE) in active
    assert metrics.registry().gauges()["slo.breach.total"] == 1.0


def test_slo_surface_disabled_shape():
    am = _StubAM()
    status = _Handler._slo(am)
    assert status == {"enabled": False, "targets": {}, "active": [],
                      "total_breaches": 0, "log": []}


# ------------------------------------------------------------ SLO watchdogs

def test_slo_shed_rate_breach_then_clear():
    journal = []
    wd = slo.SloWatchdog(C.TezConfiguration({C.AM_SLO_SHED_RATE.name: 0.5,
                                             C.AM_SLO_MIN_COUNT.name: 1}),
                         journal=journal.append)
    new = wd.evaluate({"t": {"accepted": 1, "shed": 3}})   # 0.75 > 0.5
    assert len(new) == 1 and new[0]["tenant"] == "t"
    assert wd.evaluate({"t": {"accepted": 1, "shed": 3}}) == []  # latched
    assert wd.evaluate({"t": {"accepted": 9, "shed": 1}}) == []  # clears
    st = wd.status()
    assert st["active"] == [] and st["total_breaches"] == 1
    assert [e["event"] for e in st["log"]] == ["breach", "clear"]
    assert len(journal) == 1      # one typed event per episode


def test_slo_queue_wait_is_session_wide():
    wd = slo.SloWatchdog(C.TezConfiguration(
        {C.AM_SLO_QUEUE_WAIT_P95_MS.name: 1.0,
         C.AM_SLO_MIN_COUNT.name: 3}))
    for _ in range(3):
        metrics.observe("am.admit.queue_wait", 50.0)
    new = wd.evaluate({})
    assert len(new) == 1
    assert new[0]["tenant"] == "*"
    assert new[0]["kind"] == slo.KIND_QUEUE_WAIT
    assert new[0]["observed"] > 1.0


def test_slo_min_count_guards_single_observation():
    wd = slo.SloWatchdog(C.TezConfiguration({C.AM_SLO_SHED_RATE.name: 0.01,
                                             C.AM_SLO_MIN_COUNT.name: 4}))
    assert wd.evaluate({"t": {"accepted": 1, "shed": 2}}) == []


def test_slo_from_conf_none_when_no_target():
    assert slo.from_conf(C.TezConfiguration({})) is None


# --------------------------------------------------- /metrics scrape race

def test_metrics_scrape_never_drops_a_retiring_dag():
    """Regression for the scrape race: a DAG moving live->retired between
    the two registry reads used to vanish from BOTH maps mid-scrape.
    _metrics now snapshots under the AM's _dag_done lock, so a mover
    thread toggling the DAG between the maps (under that lock, like the
    real retire path) must never produce a scrape without its counters."""
    counters = TezCounters()
    counters.find_counter("shuffle", "FLIGHT_SCRAPE_RACE_BYTES").increment(7)
    dag = SimpleNamespace(dag_id="dag_r", counters=counters, vertices={})
    am = SimpleNamespace(_dag_done=threading.Condition(),
                         live_dags={"dag_r": dag}, retired_dags={},
                         current_dag=None, attempt=1, conf=None)
    stop = threading.Event()

    def mover():
        while not stop.is_set():
            with am._dag_done:                    # the retire path
                am.retired_dags["dag_r"] = am.live_dags.pop("dag_r")
            with am._dag_done:                    # and back again
                am.live_dags["dag_r"] = am.retired_dags.pop("dag_r")

    t = threading.Thread(target=mover)
    t.start()
    try:
        for _ in range(300):
            assert "FLIGHT_SCRAPE_RACE_BYTES" in _Handler._metrics(am)
    finally:
        stop.set()
        t.join(timeout=10)


# ------------------------------------------------------ doctor golden path

def _golden_dag():
    """Two-vertex DAG with every boundary seeded: admission holds it
    [1000.0, 1000.2], two map attempts run [1000.2, 1000.5] and
    [1000.2, 1000.4], nothing is instrumented over [1000.5, 1000.6],
    and the single reduce attempt runs [1000.6, 1001.0]."""
    m = VertexInfo("v_m", name="map")
    m.tasks["t0"] = TaskInfo("t0", "map", attempts={
        "a0": AttemptInfo("attempt_m0", "t0", "map", start_time=1000.2,
                          finish_time=1000.5, state="SUCCEEDED")})
    m.tasks["t1"] = TaskInfo("t1", "map", attempts={
        "a1": AttemptInfo("attempt_m1", "t1", "map", start_time=1000.2,
                          finish_time=1000.4, state="SUCCEEDED")})
    r = VertexInfo("v_r", name="reduce")
    r.tasks["t2"] = TaskInfo("t2", "reduce", attempts={
        "a2": AttemptInfo("attempt_r0", "t2", "reduce", start_time=1000.6,
                          finish_time=1001.0, state="SUCCEEDED")})
    return DagInfo("dag_golden", name="golden", tenant="acme",
                   submit_time=1000.0, start_time=1000.2,
                   finish_time=1001.0, state="SUCCEEDED",
                   vertices={"v_m": m, "v_r": r})


def test_doctor_golden_waterfall_history_only():
    rep = doctor.diagnose(_golden_dag(), [], [])
    assert rep["wall_s"] == pytest.approx(1.0)
    assert rep["planes"]["admission"]["pct"] == pytest.approx(20.0)
    assert rep["planes"]["compute"]["pct"] == pytest.approx(70.0)
    assert rep["planes"]["control"]["pct"] == pytest.approx(10.0)
    for p in ("exchange", "device", "store", "transport"):
        assert rep["planes"][p]["pct"] == 0.0
    # the acceptance criterion: the sweep partitions the window
    assert rep["pct_total"] == pytest.approx(100.0, abs=0.01)
    assert [(s["offset_s"], s["plane"]) for s in rep["waterfall"]] == [
        (0.0, "admission"), (pytest.approx(0.2), "compute"),
        (pytest.approx(0.5), "control"), (pytest.approx(0.6), "compute")]
    assert rep["split"]["queue_wait_pct"] == pytest.approx(22.22, abs=0.01)
    assert rep["split"]["compute_pct"] == pytest.approx(77.78, abs=0.01)
    text = doctor.render_text(rep)
    assert "plane blame" in text and "admission" in text


def test_doctor_flight_intervals_fill_uncovered_gap():
    """A store COUNTER observation covering exactly the control gap must
    re-blame it: flight data is what turns 'uncovered' into a plane."""
    snap = flight.FlightSnapshot(
        events=[flight.FlightEvent(1, int(0.6e9), flight.COUNTER,
                                   "store.fetch.wait", "", 100_000, 0)],
        anchor=(1000.0, 0), dropped_before=0)
    rep = doctor.diagnose(_golden_dag(), [snap], [])
    assert rep["planes"]["store"]["pct"] == pytest.approx(10.0)
    assert rep["planes"]["control"]["pct"] == pytest.approx(0.0)
    assert rep["pct_total"] == pytest.approx(100.0, abs=0.01)
    assert rep["sources"]["flight_events"] == 1


def test_doctor_straggler_uses_fleet_median_for_thin_vertices():
    dag = _golden_dag()
    # in-DAG the single reduce attempt is its own median (1.0x) ...
    solo = doctor.straggler_attempts(dag)
    assert all(r["slowdown"] < 2.0 for r in solo)
    # ... but against the fleet baseline it is named as the straggler
    rows = doctor.straggler_attempts(dag, fleet={"reduce": 0.1})
    assert rows[0]["attempt_id"] == "attempt_r0"
    assert rows[0]["slowdown"] == pytest.approx(4.0)
    rep = doctor.diagnose(dag, [], [], fleet={"reduce": 0.1})
    assert "straggler attempt_r0" in rep["verdict"]


def test_doctor_slo_breaches_reach_report_and_text():
    breach = {"tenant": "acme", "kind": "shed_rate",
              "observed": 0.5, "target": 0.01}
    rep = doctor.diagnose(_golden_dag(), [], [breach])
    assert rep["slo_breaches"] == [breach]
    assert "1 SLO breach(es)" in rep["verdict"]
    assert "tenant=acme shed_rate" in doctor.render_text(rep)


def _uniform_dag(dag_id, t0, wall, n=3, dur=0.1, name="w"):
    v = VertexInfo("v", name=name)
    for i in range(n):
        v.tasks[f"t{i}"] = TaskInfo(f"t{i}", name, attempts={
            "a": AttemptInfo(f"{dag_id}_a{i}", f"t{i}", name,
                             start_time=t0 + 0.1,
                             finish_time=t0 + 0.1 + dur,
                             state="SUCCEEDED")})
    return DagInfo(dag_id, submit_time=t0, start_time=t0 + 0.1,
                   finish_time=t0 + wall, state="SUCCEEDED",
                   vertices={"v": v})


def test_doctor_triage_prefers_failed_then_skew_then_wall():
    failed = _uniform_dag("dag_f", 3000.0, 0.3)
    failed.state = "FAILED"
    dags = {"dag_a": _uniform_dag("dag_a", 1000.0, 1.5),
            "dag_b": _uniform_dag("dag_b", 2000.0, 0.6, n=1, dur=0.5),
            "dag_f": failed}
    assert doctor._triage_pick(dags) == "dag_f"
    # no failures: dag_b's lone 0.5 s attempt is 5x the fleet median for
    # vertex "w", which outranks dag_a's longer but uniform wall
    del dags["dag_f"]
    assert doctor._triage_pick(dags) == "dag_b"
    # no skew at all: longest wall wins
    dags["dag_b"] = _uniform_dag("dag_b", 2000.0, 0.6)
    assert doctor._triage_pick(dags) == "dag_a"


def test_trace_export_flight_tracks_are_valid_perfetto():
    from tez_tpu.tools import trace_export
    snap = flight.FlightSnapshot(
        events=[
            flight.FlightEvent(1, int(1.0e9), flight.SPAN, "fetch.block",
                               "fetch", int(0.9e9), int(0.1e9)),
            flight.FlightEvent(2, int(1.2e9), flight.COUNTER,
                               "store.publish", "", 2500, 0),
            flight.FlightEvent(3, int(1.3e9), flight.ADMIT, "shed",
                               "acme", 1, 0),
        ],
        anchor=(1000.0, 0), dropped_before=0)
    trace = trace_export.flight_to_trace(snap)
    assert check_trace(trace) >= 6      # 3 events + their lane metadata
    by_name = {e["name"]: e for e in trace["traceEvents"]
               if e["ph"] != "M"}
    assert by_name["store.publish"]["dur"] == 2500
    assert by_name["shed"]["ph"] == "i"
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M"}
    assert {"flight:span:fetch", "flight:counter:store.publish",
            "flight:admit"} <= lanes
