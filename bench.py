"""Benchmark: OrderedWordCount-style shuffle+sort core on one TPU chip.

Measures the partitioned sort + k-way merge data path (the part of the
reference that PipelinedSorter/TezMerger implement — SURVEY.md §2.5 /
BASELINE.md north star) on synthetic records: P producer tasks each
partition+sort their span on device; C consumer tasks merge their partition's
slices.  Baseline is a strong HOST implementation of the same semantics
(vectorized numpy FNV hash + lexsort + stable merge) on this machine —
record-at-a-time JVM-style sorting is far slower than this baseline, so
vs_baseline understates the advantage over the reference.

Prints ONE JSON line:
  {"metric": ..., "value": MB/s/chip, "unit": "MB/s", "vs_baseline": x}
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def make_records(num_records: int, key_len: int = 12, seed: int = 0):
    """Synthetic word-count-ish records: zipfian keys, 8-byte long values."""
    rng = np.random.default_rng(seed)
    vocab = 50_000
    word_ids = rng.zipf(1.3, num_records).astype(np.int64) % vocab
    # fixed-width keys: "w%010d" style bytes
    digits = np.zeros((num_records, key_len), dtype=np.uint8)
    digits[:, 0] = ord("w")
    ids = word_ids.copy()
    for i in range(key_len - 1, 0, -1):
        digits[:, i] = ord("0") + (ids % 10)
        ids //= 10
    key_bytes = digits.reshape(-1)
    key_offsets = np.arange(num_records + 1, dtype=np.int64) * key_len
    val_bytes = rng.integers(0, 256, num_records * 8, dtype=np.int64)\
        .astype(np.uint8)
    val_offsets = np.arange(num_records + 1, dtype=np.int64) * 8
    return key_bytes, key_offsets, val_bytes, val_offsets


def host_baseline(key_bytes, key_offsets, val_bytes, val_offsets,
                  num_producers: int, num_partitions: int, key_len: int):
    """Vectorized host implementation of the same partition+sort+merge."""
    n = len(key_offsets) - 1
    keys = key_bytes.reshape(n, key_len)
    # FNV-1a per row (vectorized over rows, loop over key bytes)
    h = np.full(n, 2166136261, dtype=np.uint64)
    for j in range(key_len):
        h = ((h ^ keys[:, j].astype(np.uint64)) * np.uint64(16777619)) \
            & np.uint64(0xFFFFFFFF)
    part = (h % np.uint64(num_partitions)).astype(np.int64)
    per = n // num_producers
    producer_runs = []
    for p in range(num_producers):
        sl = slice(p * per, (p + 1) * per if p < num_producers - 1 else n)
        cols = [keys[sl, j] for j in range(key_len - 1, -1, -1)]
        order = np.lexsort(cols + [part[sl]])
        producer_runs.append((part[sl][order], keys[sl][order]))
    # consumer merge: for each partition, concat producer slices + stable sort
    out = []
    for c in range(num_partitions):
        segs = []
        for parts, ks in producer_runs:
            lo = np.searchsorted(parts, c, "left")
            hi = np.searchsorted(parts, c, "right")
            segs.append(ks[lo:hi])
        allk = np.concatenate(segs) if segs else np.zeros((0, key_len),
                                                          np.uint8)
        cols = [allk[:, j] for j in range(key_len - 1, -1, -1)]
        out.append(allk[np.lexsort(cols)])
    return out


def prepare_device_inputs(key_bytes, key_offsets, val_bytes, val_offsets,
                          key_len: int):
    """Normalize + upload ONCE (the data plane is HBM-resident: records are
    produced on device and stay there; host<->device DMA is not part of the
    shuffle+sort path being measured)."""
    import jax
    import jax.numpy as jnp
    from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
    n = len(key_offsets) - 1
    mat, lengths = pad_to_matrix(key_bytes, key_offsets, key_len)
    lanes = matrix_to_lanes(mat)
    hash_w = 1 << max(2, (key_len - 1).bit_length())
    hmat, hlens = pad_to_matrix(key_bytes, key_offsets, hash_w)
    vals = np.ascontiguousarray(val_bytes.reshape(n, 8)).view(np.uint32)
    from tez_tpu.ops.device import uniform_clamped_lengths
    uniform, _ = uniform_clamped_lengths(lengths, lanes.shape[1] * 4 + 1)
    dev = [jnp.asarray(x) for x in (lanes, lengths.astype(np.int64), vals,
                                    hmat, hlens.astype(np.int32))]
    jax.block_until_ready(dev)
    return dev + [uniform]


def tpu_path(dev_inputs, num_partitions: int):
    """The measured region: hash-partition + global (partition, key) sort +
    payload gather + partition index, all device-resident — the single-chip
    equivalent of producer sort + exchange + consumer merge (on one chip the
    exchange is an HBM-resident buffer handoff).

    Timing honesty: through the axon relay, block_until_ready can return
    before remote execution finishes, so completion is forced by fetching a
    scalar that depends on the whole pipeline (the tiny counts vector)."""
    from tez_tpu.ops.device_pipeline import device_shuffle_sort
    lanes, lengths, vals, hmat, hlens, uniform = dev_inputs
    out = device_shuffle_sort(lanes, lengths, vals, hmat, hlens,
                              num_partitions, uniform_length=uniform)
    _ = np.asarray(out[4])   # counts: forces full execution, ~P ints D2H
    return out


_bench_done = None   # signalled when timing completed
_warm_done = None    # signalled once the device finished ONE full pipeline


def _arm_watchdog(total_mb: float) -> None:
    """The axon relay can stall compiles indefinitely.  Two-stage response
    instead of hanging the harness: after a grace period, re-run the whole
    bench in a clean CPU subprocess (honest fallback number, labeled); if
    even that fails, emit a labeled zero at TEZ_BENCH_TIMEOUT seconds."""
    global _bench_done, _warm_done
    import os
    import threading
    _bench_done = threading.Event()
    _warm_done = threading.Event()
    budget = float(os.environ.get("TEZ_BENCH_TIMEOUT", "480"))

    def _zero() -> None:
        if _bench_done.is_set():
            return
        print(json.dumps({
            "metric": "ordered-shuffle-sort throughput "
                      "(WATCHDOG: device stalled before completing)",
            "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}), flush=True)
        os._exit(0)

    fallback_delay = min(150.0, budget * 0.5)

    def _fallback() -> None:
        if _bench_done.is_set() or _warm_done.is_set() or \
                os.environ.get("TEZ_BENCH_FALLBACK") == "1":
            # a device that completed one full pipeline is WORKING, just
            # slow/large — never misreport it as a relay stall
            return
        import subprocess
        env = dict(os.environ)
        env["TEZ_BENCH_FALLBACK"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        # drop the axon sitecustomize: it pins the TPU platform in
        # jax.config, which outranks JAX_PLATFORMS
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p)
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
                env=env, capture_output=True, text=True,
                # child deadline must sit INSIDE the zero watchdog's:
                # fallback_delay + timeout + margin <= budget, whatever the
                # budget (no fixed floor that could breach it)
                timeout=max(15.0, budget - fallback_delay - 30))
            # the device may have woken up while the child ran: the real
            # result wins, and two JSON lines must never print
            if _bench_done.is_set() or _warm_done.is_set():
                return
            for line in reversed(out.stdout.strip().splitlines()):
                if line.startswith("{"):
                    print(line, flush=True)
                    os._exit(0)
        except Exception:  # noqa: BLE001 — the zero timer is still armed
            pass

    for delay, fn in ((fallback_delay, _fallback), (budget, _zero)):
        t = threading.Timer(delay, fn)
        t.daemon = True
        t.start()


def main() -> int:
    import os
    cpu_fallback = os.environ.get("TEZ_BENCH_FALLBACK") == "1"
    if cpu_fallback:
        import jax
        jax.config.update("jax_platforms", "cpu")
    num_records = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    key_len = 12
    num_producers, num_partitions = 4, 4
    kb, ko, vb, vo = make_records(num_records, key_len)
    total_mb = (kb.nbytes + vb.nbytes) / 1e6
    _arm_watchdog(total_mb)

    dev = prepare_device_inputs(kb, ko, vb, vo, key_len)
    # warm up (compile; persisted across runs via the jit cache)
    tpu_path(dev, num_partitions)
    if _warm_done is not None:
        _warm_done.set()   # device is alive: disarm the CPU fallback

    t0 = time.time()
    reps = 3
    for _ in range(reps):
        tpu_out = tpu_path(dev, num_partitions)
    tpu_s = (time.time() - t0) / reps

    t0 = time.time()
    host_out = host_baseline(kb, ko, vb, vo, num_producers, num_partitions,
                             key_len)
    host_s = time.time() - t0

    # sanity: same keys per partition in same order
    sorted_parts, out_lanes, out_vals, perm, counts = \
        [np.asarray(x) for x in tpu_out]
    n = num_records
    sorted_keys = kb.reshape(n, key_len)[perm[:n]]
    bounds = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(counts, out=bounds[1:])
    for c in range(num_partitions):
        got = sorted_keys[bounds[c]:bounds[c + 1]]
        assert got.shape == host_out[c].shape, \
            f"partition {c}: {got.shape} vs {host_out[c].shape}"
        assert np.array_equal(got, host_out[c]), f"partition {c} mismatch"

    mbps = total_mb / tpu_s
    if _bench_done is not None:
        _bench_done.set()
    label = (f"ordered-shuffle-sort throughput ({num_records} recs, "
             f"{num_partitions} partitions, HBM-resident)")
    if cpu_fallback:
        label += " [CPU FALLBACK: TPU relay stalled]"
    print(json.dumps({
        "metric": label,
        "value": round(mbps, 2),
        "unit": "MB/s",
        "vs_baseline": round(host_s / tpu_s, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
