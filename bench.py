"""Benchmark: OrderedWordCount shuffle+sort on one TPU chip.

Two measurements, two JSON lines (driver parses the LAST line as the
headline; VERDICT round-1 items 1+5):

1. FRAMEWORK line (printed first): OrderedWordCount end-to-end through the
   full stack — DAG submission, vectorized tokenizer, device sorter,
   shuffle service, consumer merge, committed file output — following
   BASELINE.md's protocol (input MB/s, SHUFFLE_BYTES / SPILLED_RECORDS
   counters, output verified against a host golden).  vs_baseline is
   EXTERNAL: proxy_wall / framework_wall against the C++ reference-
   semantics OrderedWordCount proxy (native/baseline_proxy.cpp owc_proxy)
   on the identical corpus, proxy output verified against the same
   golden; the old device-vs-host-engine ratio ships as
   host_engine_wall_ratio.
2. KERNEL line (printed last, the headline): the partitioned sort + k-way
   merge core (PipelinedSorter/TezMerger semantics, SURVEY.md §2.5) on
   synthetic records, device-resident, vs a strong vectorized numpy host
   baseline.

Device liveness is PROBED FIRST in disposable subprocesses (the axon
relay can stall `jax.devices()` indefinitely; the parent never imports
jax until a child proved the backend responds, and each probe attempt
also seeds the persistent compile cache).  Only after the probe fails
for the whole warm budget does the bench re-run everything in a clean
CPU subprocess (honest, labeled fallback).

The kernel headline's vs_baseline follows BASELINE.md's protocol: the
reference's own sorter semantics, measured on this host.  No JVM exists
in this image, so the baseline is the C++ PipelinedSorter/TezMerger
proxy (tez_tpu/native/baseline_proxy.cpp, clearly labeled); the numpy
host engine comparison is printed as a separate info line.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def make_records(num_records: int, key_len: int = 12, seed: int = 0):
    """Synthetic word-count-ish records: zipfian keys, 8-byte long values."""
    rng = np.random.default_rng(seed)
    vocab = 50_000
    word_ids = rng.zipf(1.3, num_records).astype(np.int64) % vocab
    digits = np.zeros((num_records, key_len), dtype=np.uint8)
    digits[:, 0] = ord("w")
    ids = word_ids.copy()
    for i in range(key_len - 1, 0, -1):
        digits[:, i] = ord("0") + (ids % 10)
        ids //= 10
    key_bytes = digits.reshape(-1)
    key_offsets = np.arange(num_records + 1, dtype=np.int64) * key_len
    val_bytes = rng.integers(0, 256, num_records * 8, dtype=np.int64)\
        .astype(np.uint8)
    val_offsets = np.arange(num_records + 1, dtype=np.int64) * 8
    return key_bytes, key_offsets, val_bytes, val_offsets


def host_baseline(key_bytes, key_offsets, val_bytes, val_offsets,
                  num_producers: int, num_partitions: int, key_len: int):
    """Vectorized host implementation of the same partition+sort+merge."""
    n = len(key_offsets) - 1
    keys = key_bytes.reshape(n, key_len)
    h = np.full(n, 2166136261, dtype=np.uint64)
    for j in range(key_len):
        h = ((h ^ keys[:, j].astype(np.uint64)) * np.uint64(16777619)) \
            & np.uint64(0xFFFFFFFF)
    part = (h % np.uint64(num_partitions)).astype(np.int64)
    per = n // num_producers
    producer_runs = []
    for p in range(num_producers):
        sl = slice(p * per, (p + 1) * per if p < num_producers - 1 else n)
        cols = [keys[sl, j] for j in range(key_len - 1, -1, -1)]
        order = np.lexsort(cols + [part[sl]])
        producer_runs.append((part[sl][order], keys[sl][order]))
    out = []
    for c in range(num_partitions):
        segs = []
        for parts, ks in producer_runs:
            lo = np.searchsorted(parts, c, "left")
            hi = np.searchsorted(parts, c, "right")
            segs.append(ks[lo:hi])
        allk = np.concatenate(segs) if segs else np.zeros((0, key_len),
                                                          np.uint8)
        cols = [allk[:, j] for j in range(key_len - 1, -1, -1)]
        out.append(allk[np.lexsort(cols)])
    return out


def prepare_device_inputs(key_bytes, key_offsets, val_bytes, val_offsets,
                          key_len: int):
    """Normalize + upload ONCE (the data plane is HBM-resident: records are
    produced on device and stay there; host<->device DMA is not part of the
    shuffle+sort path being measured)."""
    import jax
    import jax.numpy as jnp
    from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
    n = len(key_offsets) - 1
    mat, lengths = pad_to_matrix(key_bytes, key_offsets, key_len)
    lanes = matrix_to_lanes(mat)
    hash_w = 1 << max(2, (key_len - 1).bit_length())
    hmat, hlens = pad_to_matrix(key_bytes, key_offsets, hash_w)
    vals = np.ascontiguousarray(val_bytes.reshape(n, 8)).view(np.uint32)
    from tez_tpu.ops.device import uniform_clamped_lengths
    uniform, _ = uniform_clamped_lengths(lengths, lanes.shape[1] * 4 + 1)
    dev = [jnp.asarray(x) for x in (lanes, lengths.astype(np.int64), vals,
                                    hmat, hlens.astype(np.int32))]
    jax.block_until_ready(dev)
    return dev + [uniform]


def tpu_path(dev_inputs, num_partitions: int):
    """The measured region: hash-partition + global (partition, key) sort +
    payload gather + partition index, all device-resident — the single-chip
    equivalent of producer sort + exchange + consumer merge (on one chip the
    exchange is an HBM-resident buffer handoff).

    Timing honesty: through the axon relay, block_until_ready can return
    before remote execution finishes, so completion is forced by fetching a
    scalar that depends on the whole pipeline (the tiny counts vector)."""
    from tez_tpu.ops.device_pipeline import device_shuffle_sort
    lanes, lengths, vals, hmat, hlens, uniform = dev_inputs
    out = device_shuffle_sort(lanes, lengths, vals, hmat, hlens,
                              num_partitions, uniform_length=uniform)
    _ = np.asarray(out[4])   # counts: forces full execution, ~P ints D2H
    return out


def make_spans(key_bytes, val_bytes, key_len: int, num_records: int,
               num_spans: int):
    """Slice the record stream into producer spans of RAW host bytes —
    encode/H2D happen inside the pipeline's staging thread, where the async
    plane overlaps them with in-flight dispatches."""
    spans = []
    per = num_records // num_spans
    for p in range(num_spans):
        lo = p * per
        hi = (p + 1) * per if p < num_spans - 1 else num_records
        m = hi - lo
        spans.append((key_bytes[lo * key_len:hi * key_len],
                      np.arange(m + 1, dtype=np.int64) * key_len,
                      val_bytes[lo * 8:hi * 8]))
    return spans


def pipeline_path(spans, num_partitions: int, key_len: int):
    """The measured region for the async device plane (ops/async_stage.py):
    submit every span's raw bytes, drain.  Spans below the coalesce budget
    merge into ONE bucketed dispatch — a stable sort of the concatenation is
    bit-identical to merging the individually-sorted spans — so the result
    is the same global partition-major order the sync path produces.
    paused=True defers the staging thread until all spans are queued,
    making the coalesce grouping deterministic."""
    from tez_tpu.ops.device_pipeline import DeviceSpanScheduler
    total = sum(len(ko) - 1 for _, ko, _ in spans)
    sched = DeviceSpanScheduler(num_partitions, depth=2,
                                coalesce_records=total, key_width=key_len,
                                paused=True)
    for sid, (kb, ko, vb) in enumerate(spans):
        sched.submit_ragged(sid, kb, ko, vb, 8)
    sched.resume()
    return sched.results()


def bench_merge(num_records: int, key_len: int, cpu_fallback: bool) -> dict:
    """Reduce-side merge micro-bench (info line): two pre-sorted
    HBM-resident runs — the merge ladder's pairwise rung — merged by the
    O(N) merge-path rank kernel vs concatenating and re-sorting the same
    views.  The perm is bit-verified across kernels; vs_baseline =
    re-sort wall / merge-path wall, and min_vs_baseline is the ratio
    floor bench_diff enforces (the merge-path kernel must keep beating
    concatenate+re-sort)."""
    import jax.numpy as jnp
    from tez_tpu.ops import device
    from tez_tpu.ops.keycodec import matrix_to_lanes, pad_to_matrix
    n = min(num_records, 1_000_000)
    num_runs = 2
    kb, _, _, _ = make_records(n, key_len, seed=3)
    keys = kb.reshape(n, key_len)
    per = n // num_runs
    views, total_bytes = [], 0
    for r in range(num_runs):
        lo, hi = r * per, ((r + 1) * per if r < num_runs - 1 else n)
        sub = keys[lo:hi]
        order = np.lexsort([sub[:, j] for j in range(key_len - 1, -1, -1)])
        flat = np.ascontiguousarray(sub[order]).reshape(-1)
        offs = np.arange(hi - lo + 1, dtype=np.int64) * key_len
        mat, lengths = pad_to_matrix(flat, offs, key_len)
        views.append((jnp.asarray(matrix_to_lanes(mat)),
                      jnp.asarray(lengths.astype(np.int32)), 0, hi - lo))
        total_bytes += flat.nbytes

    def once(kernel):
        return np.asarray(device.merge_resident_slices(views, kernel=kernel))

    p_mp, p_sort = once("merge_path"), once("sort")   # warm both programs
    assert np.array_equal(p_mp, p_sort), \
        "merge-path perm diverges from concat+re-sort"
    reps = 3
    t0 = time.time()
    for _ in range(reps):
        once("merge_path")
    mp_s = (time.time() - t0) / reps
    t0 = time.time()
    for _ in range(reps):
        once("sort")
    sort_s = (time.time() - t0) / reps
    suffix = " [CPU FALLBACK: TPU relay stalled]" if cpu_fallback else ""
    return {
        "metric": (f"reduce-side merge-path vs concat+re-sort (info line; "
                   f"{num_runs} pre-sorted runs x {per} recs, HBM-resident, "
                   f"perm bit-verified across kernels){suffix}"),
        "value": round(total_bytes / 1e6 / mp_s, 2), "unit": "MB/s",
        "vs_baseline": round(sort_s / mp_s, 3),
        "min_vs_baseline": 1.3,
    }


def bench_store(num_records: int, key_len: int, cpu_fallback: bool) -> dict:
    """Tiered buffer-store short-circuit vs loopback TCP fetch (info line).

    The same registered spills are fetched two ways: (A) over the
    keep-alive DCN shuffle socket on loopback — connect + HMAC handshake
    paid once, then per-partition request/serialize/copy per fetch — and
    (B) through ShuffleBufferStore.fetch_partition, the leased zero-copy
    view the fetch scheduler's local_probe takes for same-host producers.
    vs_baseline = TCP wall / store wall; min_vs_baseline is the ratio
    floor bench_diff enforces (the short-circuit losing its edge over the
    wire means the lease path grew a copy).  The metric text also reports
    the session-mode leg: spills sealed under lineage keys, republished
    to a second DAG's path, and re-fetched bit-exact as cache hits."""
    from tez_tpu.common.security import JobTokenSecretManager
    from tez_tpu.ops.runformat import KVBatch, Run
    from tez_tpu.shuffle.server import FetchSession, ShuffleServer
    from tez_tpu.shuffle.service import ShuffleService
    from tez_tpu.store.buffer_store import ShuffleBufferStore

    n = min(num_records, 400_000)
    num_spills, num_partitions = 4, 4
    per = n // num_spills
    service = ShuffleService()
    store = ShuffleBufferStore(device_capacity=0, host_capacity=1 << 30)
    service.attach_buffer_store(store)
    paths = []
    for s in range(num_spills):
        kb, ko, vb, vo = make_records(per, key_len, seed=100 + s)
        bounds = np.linspace(0, per, num_partitions + 1).astype(np.int64)
        path = f"bench_dag/attempt_{s}/cons"
        service.register(path, -1, Run(KVBatch(kb, ko, vb, vo), bounds),
                         lineage=f"benchlin{s}/0/cons")
        paths.append(path)

    reps = 3
    secrets = JobTokenSecretManager()
    server = ShuffleServer(secrets, service).start()
    try:
        sess = FetchSession(secrets, "127.0.0.1", server.port)
        try:
            tcp_probe = sess.fetch(paths[0], -1, 1)        # warm + verify
            for path in paths:
                sess.fetch_range(path, -1, 0, num_partitions)
            t0 = time.time()
            for _ in range(reps):
                for path in paths:
                    sess.fetch_range(path, -1, 0, num_partitions)
            tcp_s = (time.time() - t0) / reps
        finally:
            sess.close()
    finally:
        server.stop()

    bytes_per_pass = 0
    for path in paths:                                      # warm
        for p in range(num_partitions):
            bytes_per_pass += store.fetch_partition(path, -1, p).nbytes
    store_probe = store.fetch_partition(paths[0], -1, 1)
    assert np.array_equal(tcp_probe.key_bytes, store_probe.key_bytes) and \
        np.array_equal(tcp_probe.val_bytes, store_probe.val_bytes), \
        "TCP and store short-circuit served different partition bytes"
    t0 = time.time()
    for _ in range(reps):
        for path in paths:
            for p in range(num_partitions):
                store.fetch_partition(path, -1, p)
    store_s = (time.time() - t0) / reps

    # session-mode leg: DAG commits -> seal, DAG aliases drop, a recurring
    # DAG republishes the sealed entries under its own path and re-fetches
    sealed = store.seal_lineage("bench_dag")
    service.unregister_prefix("bench_dag")
    hits = 0
    for s in range(num_spills):
        new_path = f"bench_dag2/attempt_{s}/cons"
        hits += len(store.republish_lineage(f"benchlin{s}/0/cons", new_path))
        reused = store.fetch_partition(new_path, -1, 1)
        if s == 0:
            assert np.array_equal(reused.key_bytes, store_probe.key_bytes), \
                "lineage-republished partition diverges from the original"
    store.close()

    suffix = " [CPU FALLBACK: TPU relay stalled]" if cpu_fallback else ""
    return {
        "metric": (f"store short-circuit vs loopback TCP fetch (info line; "
                   f"{num_spills} spills x {num_partitions} partitions, "
                   f"{bytes_per_pass / 1e6:.1f} MB/pass, keep-alive TCP "
                   f"session {bytes_per_pass / 1e6 / tcp_s:.0f} MB/s; "
                   f"session leg: {sealed} sealed, {hits} lineage hits "
                   f"republished + re-fetched bit-exact){suffix}"),
        "value": round(bytes_per_pass / 1e6 / store_s, 2), "unit": "MB/s",
        "vs_baseline": round(tcp_s / store_s, 3),
        "min_vs_baseline": 1.5,
    }


_DEVICE_STAGES = (("encode", "device.encode"), ("h2d", "device.h2d"),
                  ("dispatch_wait", "device.dispatch_wait"),
                  ("d2h", "device.d2h"))


def device_stage_ms():
    """Cumulative wall ms per async-plane stage, from the in-process
    metrics histograms the pipeline feeds (docs/device_pipeline.md)."""
    from tez_tpu.common import metrics
    hs = metrics.registry().histograms()
    return {short: round(float(hs[name].sum_ms), 1) if name in hs else 0.0
            for short, name in _DEVICE_STAGES}


# ---------------------------------------------------------------------------
# watchdog (axon relay can stall backend init / compile indefinitely)
# ---------------------------------------------------------------------------
_bench_done = None   # signalled when timing completed
_warm_done = None    # signalled once the device finished ONE full pipeline
_phase = ["init"]    # what the bench was doing when a watchdog fired
_kernel_line = [None]   # completed kernel measurement — the watchdog prints
                        # it instead of zero if a LATER stage (E2E) stalls


def _arm_watchdog() -> None:
    global _bench_done, _warm_done
    import threading
    _bench_done = threading.Event()
    _warm_done = threading.Event()
    budget = float(os.environ.get("TEZ_BENCH_TIMEOUT", "480"))

    def _zero() -> None:
        if _bench_done.is_set():
            return
        if os.environ.get("TEZ_BENCH_E2E_ONLY") == "1":
            print(json.dumps({
                "metric": f"OrderedWordCount E2E WATCHDOG: stalled during "
                          f"{_phase[0]}",
                "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}),
                flush=True)
            os._exit(0)
        if _kernel_line[0] is not None:
            # the kernel measurement completed and verified; only a later
            # stage (framework E2E) stalled — report the real number
            print(json.dumps({
                "metric": f"OrderedWordCount E2E WATCHDOG: stalled during "
                          f"{_phase[0]}",
                "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}),
                flush=True)
            print(json.dumps(_kernel_line[0]), flush=True)
            os._exit(0)
        print(json.dumps({
            "metric": f"ordered-shuffle-sort throughput (WATCHDOG: device "
                      f"stalled during {_phase[0]})",
            "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}), flush=True)
        os._exit(0)

    fallback_delay = min(150.0, budget * 0.5)

    def _fallback() -> None:
        if _bench_done.is_set() or _warm_done.is_set() or \
                os.environ.get("TEZ_BENCH_FALLBACK") == "1":
            # a device that completed one full pipeline is WORKING, just
            # slow/large — never misreport it as a relay stall
            return
        import subprocess
        env = dict(os.environ)
        env["TEZ_BENCH_FALLBACK"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        # drop the axon sitecustomize: it pins the TPU platform in
        # jax.config, which outranks JAX_PLATFORMS
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p)
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
                env=env, capture_output=True, text=True,
                timeout=max(15.0, budget - fallback_delay - 30))
            if _bench_done.is_set() or _warm_done.is_set():
                return   # device woke up while the child ran: real result wins
            printed = False
            for line in out.stdout.strip().splitlines():
                if line.startswith("{"):
                    print(line, flush=True)
                    printed = True
            if printed:
                os._exit(0)
        except Exception:  # noqa: BLE001 — the zero timer is still armed
            pass

    timers = [(budget, _zero)]
    if os.environ.get("TEZ_BENCH_E2E_ONLY") != "1":
        # The CPU-fallback timer exists to catch a relay that stalls during
        # backend init/compile.  The E2E-only child is only ever spawned
        # AFTER the kernel stage proved the device alive, and its runs are
        # legitimately minutes long — arming the 150 s fallback there would
        # kill a healthy measurement and mislabel it a stall.
        timers.insert(0, (fallback_delay, _fallback))
    for delay, fn in timers:
        t = threading.Timer(delay, fn)
        t.daemon = True
        t.start()


# ---------------------------------------------------------------------------
# framework E2E (BASELINE.md protocol: full stack, counters, verified output)
# ---------------------------------------------------------------------------
def _make_corpus(path: str, target_mb: int, seed: int = 0):
    """Zipfian word corpus; returns (bytes_written, golden Counter-dict)."""
    rng = np.random.default_rng(seed)
    vocab = 20_000
    words = np.array([f"w{i:06d}" for i in range(vocab)])
    total = 0
    counts = np.zeros(vocab, dtype=np.int64)
    chunk_words = 1 << 20
    words_per_line = 8192   # ~64 KB lines: text splits stay balanced
    # (multi-MB lines would skew line-aligned splits across tokenizers)
    with open(path, "w") as fh:
        while total < target_mb << 20:
            ids = rng.zipf(1.3, chunk_words).astype(np.int64) % vocab
            counts += np.bincount(ids, minlength=vocab)
            chunk = words[ids]
            for s in range(0, len(chunk), words_per_line):
                text = " ".join(chunk[s:s + words_per_line])
                fh.write(text)
                fh.write("\n")
                total += len(text) + 1
    golden = {words[i]: int(counts[i]) for i in np.flatnonzero(counts)}
    return total, golden


def _run_wordcount(corpus: str, out_dir: str, staging: str,
                   engine: str) -> dict:
    from tez_tpu.client.tez_client import TezClient
    from tez_tpu.examples import ordered_wordcount
    conf = {"tez.staging-dir": staging,
            "tez.runtime.sorter.class": engine,
            "tez.runtime.io.sort.mb": 512}
    with TezClient.create("bench-owc", conf) as client:
        dag = ordered_wordcount.build_dag(
            [corpus], out_dir, tokenizer_parallelism=4,
            summation_parallelism=4, sorter_parallelism=1,
            combine=True, tokenizer_mode="vector")
        dag_client = client.submit_dag(dag)
        status = dag_client.wait_for_completion()
        final = dag_client.get_dag_status(with_counters=True)
    counters = {}
    if final.counters is not None:
        d = final.counters.to_dict()
        for group in d.values():
            for name in ("SHUFFLE_BYTES", "SPILLED_RECORDS",
                         "OUTPUT_RECORDS", "REDUCE_INPUT_RECORDS"):
                if name in group:
                    counters[name] = counters.get(name, 0) + group[name]
    return {"state": status.state.name, "counters": counters}


def _verify_output(out_dir: str, golden: dict) -> None:
    got = {}
    for name in sorted(os.listdir(out_dir)):
        with open(os.path.join(out_dir, name)) as fh:
            for line in fh.read().splitlines():
                if line.strip():
                    w, c = line.rsplit(None, 1)
                    got[w] = int(c)
    assert got == golden, (
        f"framework output mismatch: {len(got)} words vs {len(golden)}")


def bench_framework(cpu_fallback: bool) -> dict:
    """OrderedWordCount through the full stack; returns the JSON record."""
    import shutil
    import tempfile
    default_mb = 32 if cpu_fallback else 96
    target_mb = int(os.environ.get("TEZ_BENCH_E2E_MB", str(default_mb)))
    td = tempfile.mkdtemp(prefix="tez_bench_")
    try:
        _phase[0] = "e2e corpus generation"
        corpus = os.path.join(td, "corpus.txt")
        nbytes, golden = _make_corpus(corpus, target_mb)

        # BASELINE.md protocol: 3 runs per engine, median wall-clock (the
        # first device run additionally pays trace/compile warmup; the
        # median reports steady state for BOTH engines identically)
        reps = max(1, int(os.environ.get("TEZ_BENCH_E2E_REPS", "3")))
        runs = {}
        for engine in ("device", "host"):
            walls = []
            counters = {}
            for rep in range(reps):
                _phase[0] = f"e2e wordcount ({engine} engine, run {rep + 1})"
                out_dir = os.path.join(td, f"out_{engine}_{rep}")
                t0 = time.time()
                r = _run_wordcount(corpus, out_dir, os.path.join(td, "stg"),
                                   engine)
                walls.append(time.time() - t0)
                assert r["state"] == "SUCCEEDED", r
                _verify_output(out_dir, golden)
                counters = r["counters"]
                import shutil as _sh
                _sh.rmtree(out_dir, ignore_errors=True)
            walls.sort()
            runs[engine] = (walls[len(walls) // 2], counters)

        dev_wall, counters = runs["device"]
        host_wall, _ = runs["host"]

        # EXTERNAL baseline (BASELINE.md protocol): the reference-semantics
        # C++ OrderedWordCount proxy over the IDENTICAL corpus — tokenize,
        # span sort + combine, per-partition heap merge + sum, count-keyed
        # second sort, merged output — output verified against the same
        # golden.  vs_baseline = proxy wall / framework wall ( >1 means the
        # framework beats reference semantics at equal work on this host).
        _phase[0] = "e2e reference-proxy baseline"
        proxy_wall = None
        res = None
        try:
            from tez_tpu.ops.native import owc_proxy_counts
            pw = []
            for _ in range(reps):
                res = owc_proxy_counts(corpus, 4, 4)
                if res is None:
                    break
                secs, got = res
                pw.append(secs)
        except (ImportError, OSError) as e:   # AVAILABILITY miss only:
            # a wrong/corrupt baseline must raise, not be relabeled
            print(f"# owc_proxy baseline unavailable: {e}",
                  file=sys.stderr)
            res = None
        if res is not None and pw:
            if got != golden:
                # a WRONG baseline is a bug, never "unavailable"
                raise RuntimeError(
                    f"owc_proxy output mismatch: {len(got)} words vs "
                    f"golden {len(golden)}")
            pw.sort()
            proxy_wall = pw[len(pw) // 2]
        vs = round(proxy_wall / dev_wall, 3) if proxy_wall else 0.0
        base_note = (f"C++ OrderedWordCount reference-semantics proxy "
                     f"{proxy_wall:.2f}s on the same corpus"
                     if proxy_wall else "proxy unavailable")
        return {
            "metric": (f"OrderedWordCount E2E through full framework "
                       f"({target_mb} MB input, 4x4x1 tasks, device sorter, "
                       f"median of {reps}, verified vs host golden; "
                       f"SHUFFLE_BYTES={counters.get('SHUFFLE_BYTES', 0)}, "
                       f"SPILLED_RECORDS="
                       f"{counters.get('SPILLED_RECORDS', 0)}; "
                       f"baseline={base_note})"
                       + (" [CPU FALLBACK: TPU relay stalled]"
                          if cpu_fallback else "")),
            "value": round(nbytes / 1e6 / dev_wall, 2),
            "unit": "MB/s",
            "vs_baseline": vs,
            "host_engine_wall_ratio": round(host_wall / dev_wall, 3),
        }
    finally:
        shutil.rmtree(td, ignore_errors=True)


def _bench_framework_subprocess(cpu_fallback: bool) -> dict:
    """Run the E2E stage in a FRESH process: the kernel bench leaves 2M-record
    buffers + executables on the (relay-backed) device, and measuring the
    framework in that polluted state under-reports it.  Falls back to
    in-process on any subprocess failure."""
    import subprocess
    env = dict(os.environ)
    env["TEZ_BENCH_E2E_ONLY"] = "1"
    budget = float(os.environ.get("TEZ_BENCH_TIMEOUT", "480"))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=budget)
        for line in reversed(out.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(
            f"no JSON from E2E subprocess: {out.stderr[-300:]!r}")
    except Exception as e:  # noqa: BLE001 — degrade to in-process
        sys.stderr.write(f"e2e subprocess failed ({e!r:.200}); "
                         "running in-process\n")
        return bench_framework(cpu_fallback)


_PROBE_SRC = """
import jax
import jax.numpy as jnp
import numpy as np
ds = jax.devices()
x = jnp.asarray(np.arange(4096, dtype=np.int32)[::-1].copy())
y = jax.jit(jax.lax.sort)(x)
assert int(np.asarray(y)[0]) == 0
print("PROBE_OK", ds[0].platform, flush=True)
"""


def probe_device() -> bool:
    """Prove the backend answers WITHOUT importing jax in this process.

    Each attempt is a disposable subprocess (a stalled axon claim hangs
    `jax.devices()` forever — only a child can be abandoned); a success
    also warms the relay + persistent compile cache for the parent.
    Attempts continue until TEZ_BENCH_WARM_BUDGET seconds (default 240)
    elapse."""
    if os.environ.get("TEZ_BENCH_FALLBACK") == "1":
        return True   # CPU child: nothing to probe
    budget = float(os.environ.get("TEZ_BENCH_WARM_BUDGET", "240"))
    per_try = float(os.environ.get("TEZ_BENCH_PROBE_TIMEOUT", "120"))
    import subprocess
    deadline = time.time() + budget
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        left = max(10.0, deadline - time.time())
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True,
                timeout=min(per_try, left))
            if "PROBE_OK" in out.stdout:
                sys.stderr.write(f"device probe ok (attempt {attempt})\n")
                return True
            sys.stderr.write(
                f"probe attempt {attempt} failed: {out.stderr[-200:]!r}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"probe attempt {attempt} timed out\n")
        except Exception as e:  # noqa: BLE001 — keep probing until budget
            sys.stderr.write(f"probe attempt {attempt} error: {e!r:.150}\n")
    return False


def rerun_on_cpu() -> int:
    """The staged last resort: every probe failed, so the whole bench
    re-runs in a clean CPU child (honest '[CPU FALLBACK]' labels)."""
    import subprocess
    env = dict(os.environ)
    env["TEZ_BENCH_FALLBACK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    # drop the axon sitecustomize: it pins the TPU platform in jax.config,
    # which outranks JAX_PLATFORMS
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p)
    budget = float(os.environ.get("TEZ_BENCH_TIMEOUT", "480"))
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env, capture_output=True, text=True, timeout=budget)
        printed = False
        for line in out.stdout.strip().splitlines():
            if line.startswith("{"):
                print(line, flush=True)
                printed = True
        if printed:
            return 0
        sys.stderr.write(out.stderr[-500:] + "\n")
    except Exception as e:  # noqa: BLE001 — report rather than hang
        sys.stderr.write(f"cpu fallback failed: {e!r:.200}\n")
    print(json.dumps({
        "metric": "ordered-shuffle-sort throughput "
                  "(UNAVAILABLE: device stalled AND cpu fallback failed)",
        "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}), flush=True)
    return 0


def main() -> int:
    cpu_fallback = os.environ.get("TEZ_BENCH_FALLBACK") == "1"
    if cpu_fallback:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if os.environ.get("TEZ_BENCH_E2E_ONLY") == "1":
        _arm_watchdog()
        line = bench_framework(cpu_fallback)
        if _bench_done is not None:
            _bench_done.set()
        print(json.dumps(line), flush=True)
        return 0
    if os.environ.get("TEZ_BENCH_STORE_ONLY") == "1":
        # make bench-store: the buffer-store short-circuit info line —
        # pure host path, no device probe needed
        num_records = int(sys.argv[1]) if len(sys.argv) > 1 else 400_000
        print(json.dumps(bench_store(num_records, 12, cpu_fallback)),
              flush=True)
        return 0
    if os.environ.get("TEZ_BENCH_SORT_ONLY") == "1":
        # make bench-sort: the external-sort push-vs-pull scale leg through
        # the full framework — pure host path, no device probe needed
        from tez_tpu.tools.sort_bench import bench_sort
        print(json.dumps(bench_sort(cpu_fallback)), flush=True)
        return 0
    if os.environ.get("TEZ_BENCH_EXCHANGE_ONLY") == "1":
        # make bench-exchange: the MULTICHIP skewed-key corpus through the
        # mesh exchange plane — padded baseline vs ragged/skew-aware/coded
        # legs, one metric line each (the skew-aware line carries the
        # bench_diff min_vs_baseline floor)
        from tez_tpu.tools.exchange_bench import bench_exchange
        for rec in bench_exchange(cpu_fallback):
            print(json.dumps(rec), flush=True)
        return 0
    if os.environ.get("TEZ_BENCH_QUERY_ONLY") == "1":
        # make bench-query: broadcast-vs-repartition info lines on the
        # uniform and zipf corpora + the adaptive-replan headline whose
        # min_vs_baseline floor bench-diff enforces (run 2, replanned
        # from observed stats, must beat the naive run 1)
        from tez_tpu.tools.query_bench import bench_query
        for rec in bench_query(cpu_fallback):
            print(json.dumps(rec), flush=True)
        return 0
    if os.environ.get("TEZ_BENCH_MERGE_ONLY") == "1":
        # make bench-merge: just the reduce-side merge-path info line
        num_records = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
        print(json.dumps(bench_merge(num_records, 12, cpu_fallback)),
              flush=True)
        return 0
    # -- stage 0: prove the device answers before touching jax here; a
    # failed probe degrades to the labeled CPU re-run (VERDICT r2 item 1:
    # warm the backend before arming timers, fallback only as last resort)
    if not cpu_fallback and not probe_device():
        return rerun_on_cpu()
    num_records = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    key_len = 12
    num_producers, num_partitions = 4, 4
    _arm_watchdog()

    # -- stage 1: tiny-shape pipeline seeds the jit cache path
    _phase[0] = "device warmup (tiny shape)"
    kb0, ko0, vb0, vo0 = make_records(65_536, key_len, seed=7)
    tpu_path(prepare_device_inputs(kb0, ko0, vb0, vo0, key_len),
             num_partitions)
    if _warm_done is not None:
        _warm_done.set()   # device is alive: disarm the CPU fallback

    # -- stage 2: kernel bench at full size
    _phase[0] = "kernel compile (full shape)"
    kb, ko, vb, vo = make_records(num_records, key_len)
    total_mb = (kb.nbytes + vb.nbytes) / 1e6
    dev = prepare_device_inputs(kb, ko, vb, vo, key_len)
    tpu_path(dev, num_partitions)      # warm the full-size program
    del dev

    # -- the measured region is the ASYNC device plane: raw producer spans
    # submitted to DeviceSpanScheduler (staging-thread encode + H2D +
    # coalesced dispatch + worker readback), drained to host arrays.  The
    # warm above compiled the same _fused_pipeline program/shape.
    _phase[0] = "device pipeline warm"
    spans = make_spans(kb, vb, key_len, num_records, num_producers)
    res = pipeline_path(spans, num_partitions, key_len)
    assert all(res[i] is res[0] for i in range(num_producers)), \
        "spans did not coalesce into one dispatch"

    _phase[0] = "kernel timed runs"
    stage_before = device_stage_ms()
    t0 = time.time()
    reps = 3
    for _ in range(reps):
        res = pipeline_path(spans, num_partitions, key_len)
    tpu_s = (time.time() - t0) / reps
    stage_after = device_stage_ms()
    stage_ms = {k: round((stage_after[k] - stage_before[k]) / reps, 1)
                for k in stage_after}
    # the satellite breakdown wants sort wall: in-flight time minus D2H
    stage_ms["sort"] = round(
        max(0.0, stage_ms.pop("dispatch_wait") - stage_ms["d2h"]), 1)
    tpu_out = res[0]

    t0 = time.time()
    host_out = host_baseline(kb, ko, vb, vo, num_producers, num_partitions,
                             key_len)
    host_s = time.time() - t0

    # reference baseline: PipelinedSorter/TezMerger semantics in C++
    # (BASELINE.md — no JVM in this image, proxy clearly labeled)
    from tez_tpu.ops.native import pipelined_sorter_proxy
    n = num_records
    proxy = pipelined_sorter_proxy(kb.reshape(n, key_len),
                                   vb.reshape(n, 8),
                                   num_producers, num_partitions)
    proxy_s = proxy[0] if proxy is not None else None

    # byte-identity: device keys AND values vs the host golden.  The spans
    # are adjacent slices submitted in order, so the coalesced concat
    # preserves global record order and perm indexes kb directly.
    sorted_parts, out_lanes, out_vals, perm, counts, _nreal = \
        [np.asarray(x) for x in tpu_out]
    sorted_keys = kb.reshape(n, key_len)[perm[:n]]
    bounds = np.zeros(num_partitions + 1, dtype=np.int64)
    np.cumsum(counts, out=bounds[1:])
    for c in range(num_partitions):
        got = sorted_keys[bounds[c]:bounds[c + 1]]
        assert got.shape == host_out[c].shape, \
            f"partition {c}: {got.shape} vs {host_out[c].shape}"
        assert np.array_equal(got, host_out[c]), f"partition {c} mismatch"
    if proxy is not None:
        _, proxy_keys, proxy_vals, proxy_counts = proxy
        assert np.array_equal(proxy_counts, counts[:num_partitions]), \
            "proxy/device partition counts diverge"
        assert np.array_equal(sorted_keys, proxy_keys), \
            "proxy/device key order diverges"
        # values from the DEVICE output (not reconstructed via perm):
        # byte-identical payloads are the reducer-output contract
        dev_vals = out_vals[:n].copy().view(np.uint8).reshape(n, 8)
        assert np.array_equal(dev_vals, proxy_vals), \
            "device values diverge from baseline"

    # the kernel line is safe from here on: a later stall reports it
    mbps = total_mb / tpu_s
    suffix = " [CPU FALLBACK: TPU relay stalled]" if cpu_fallback else ""
    print(json.dumps({
        "metric": (f"ordered-shuffle-sort vs numpy-lexsort host engine "
                   f"(info line; async device pipeline, {num_producers} "
                   f"spans coalesced; same {num_records} recs){suffix}"),
        "value": round(mbps, 2), "unit": "MB/s",
        "vs_baseline": round(host_s / tpu_s, 3),
        "stage_ms": stage_ms}), flush=True)
    sys.stderr.write(
        "device-pipeline stages (wall ms/rep): " +
        " ".join(f"{k}={v}" for k, v in stage_ms.items()) + "\n")

    # -- stage 2.5: reduce-side merge-path micro-bench (info line; the
    # bench_diff gate enforces its min_vs_baseline ratio floor)
    _phase[0] = "merge-path micro-bench"
    try:
        print(json.dumps(bench_merge(num_records, key_len, cpu_fallback)),
              flush=True)
    except BaseException as e:  # noqa: BLE001 — degrade, never hide the
        # headline behind a broken info stage
        print(json.dumps({
            "metric": f"reduce-side merge-path FAILED: {e!r:.200}",
            "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}), flush=True)

    native_s = None
    if cpu_fallback:
        # what engine=auto actually RUNS on a chipless backend: the native
        # host span sort + merge through the real sorter machinery.  On
        # fallback this becomes the headline (measuring the XLA:CPU device
        # pipeline as the headline would measure a path auto never picks);
        # the device-pipeline number stays above as an info line.
        _phase[0] = "native host engine timed runs"
        from tez_tpu.ops.runformat import KVBatch
        from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs

        def native_once():
            runs = []
            per = num_records // num_producers
            for p in range(num_producers):
                lo = p * per
                hi = (p + 1) * per if p < num_producers - 1 else num_records
                s = DeviceSorter(num_partitions=num_partitions,
                                 engine="host", key_width=key_len)
                m = hi - lo
                s.write_batch(KVBatch(
                    kb[lo * key_len:hi * key_len],
                    np.arange(m + 1, dtype=np.int64) * key_len,
                    vb[lo * 8:hi * 8],
                    np.arange(m + 1, dtype=np.int64) * 8))
                runs.append(s.flush())
            return merge_sorted_runs(runs, num_partitions, key_len,
                                     engine="host")
        native_once()   # warm (native lib load, allocator)
        t0 = time.time()
        for _ in range(reps):
            merged = native_once()
        native_s = (time.time() - t0) / reps
        mk = merged.batch.key_bytes.reshape(-1, key_len)
        if proxy is not None:
            assert np.array_equal(mk, proxy[1]), \
                "host engine keys diverge from baseline"
    if proxy_s is not None:
        vs = round(proxy_s / tpu_s, 3)
        base_note = (f"baseline=PipelinedSorter-semantics C++ proxy "
                     f"{proxy_s:.2f}s (no JVM in image; BASELINE.md)")
    else:
        vs = round(host_s / tpu_s, 3)
        base_note = "baseline=numpy host engine (native proxy unavailable)"
    if native_s is not None:
        # CPU fallback headline: the engine auto actually picks there —
        # native host span sort + merge.  Verification and the ratio are
        # only claimed when the proxy actually ran (no proxy = no "byte-
        # verified" in the label and an honest numpy-ratio fallback, never
        # the stalled-run 0.0 sentinel).
        if proxy_s is not None:
            verify_note = "keys byte-verified vs baseline"
            vs_native = round(proxy_s / native_s, 3)
        else:
            verify_note = "proxy unavailable: UNVERIFIED, ratio vs numpy"
            vs_native = round(host_s / native_s, 3)
        _kernel_line[0] = {
            "metric": (f"ordered-shuffle-sort throughput ({num_records} "
                       f"recs, {num_partitions} partitions, engine=auto->"
                       f"host native span sort+merge, {verify_note}; "
                       f"{base_note}; device-pipeline info "
                       f"line above)" + suffix),
            "value": round(total_mb / native_s, 2),
            "unit": "MB/s",
            "vs_baseline": vs_native,
        }
    else:
        _kernel_line[0] = {
            "metric": (f"ordered-shuffle-sort throughput ({num_records} "
                       f"recs, {num_partitions} partitions, HBM-resident, "
                       f"keys+values byte-verified; {base_note})" + suffix),
            "value": round(mbps, 2),
            "unit": "MB/s",
            "vs_baseline": vs,
        }

    # -- stage 3: framework E2E (second metric; BASELINE.md protocol)
    fw_line = None
    if os.environ.get("TEZ_BENCH_SKIP_E2E") != "1":
        try:
            if cpu_fallback:
                fw_line = bench_framework(cpu_fallback)
            else:
                fw_line = _bench_framework_subprocess(cpu_fallback)
        except BaseException as e:  # noqa: BLE001 — the kernel line must
            # still print: a broken E2E stage degrades, never hides
            fw_line = {"metric": f"OrderedWordCount E2E FAILED: {e!r:.200}",
                       "value": 0.0, "unit": "MB/s", "vs_baseline": 0.0}

    if _bench_done is not None:
        _bench_done.set()
    if fw_line is not None:
        print(json.dumps(fw_line), flush=True)
    print(json.dumps(_kernel_line[0]), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
