# Developer entry points.  Everything runs on XLA:CPU unless a TPU is
# attached; bench.py probes the device itself and falls back with honest
# labels.

PY ?= python
OLD ?= BENCH_r05.json
NEW ?= /tmp/bench_new.json

.PHONY: test lint bench bench-new bench-diff bench-merge bench-store bench-sort bench-exchange bench-query chaos chaos-query-storm chaos-device-ooo chaos-device chaos-merge chaos-store chaos-push chaos-exchange chaos-ha chaos-stream chaos-slo-burn soak docs doctor top metrics-smoke

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# static analysis gate (docs/static_analysis.md): exit 0 clean,
# 1 = findings outside tez_tpu/tools/graftlint_baseline.json, 2 = error
lint:
	$(PY) -m tez_tpu.tools.graftlint

bench:
	$(PY) bench.py

# capture a fresh bench run in the same shape the driver archives
bench-new:
	$(PY) bench.py | tee /tmp/bench_stdout.txt
	$(PY) -c "import json; print(json.dumps({'tail': open('/tmp/bench_stdout.txt').read()}))" > $(NEW)

# gate: nonzero exit when NEW drops >20% below OLD on any shared metric
bench-diff:
	$(PY) -m tez_tpu.tools.bench_diff $(OLD) $(NEW)

# reduce-side merge-path micro-bench only: prints the info-line JSON with
# the min_vs_baseline ratio floor bench-diff enforces
bench-merge:
	JAX_PLATFORMS=cpu TEZ_BENCH_MERGE_ONLY=1 $(PY) bench.py

# buffer-store short-circuit micro-bench only: store leased zero-copy fetch
# vs loopback TCP, plus the lineage seal/republish session leg
bench-store:
	JAX_PLATFORMS=cpu TEZ_BENCH_STORE_ONLY=1 $(PY) bench.py

# external-sort scale leg: the same spill-heavy sort DAG end-to-end with
# pull-based vs push-based shuffle; bench-diff enforces the ratio floor
bench-sort:
	JAX_PLATFORMS=cpu TEZ_BENCH_SORT_ONLY=1 $(PY) bench.py

# MULTICHIP skewed-key exchange legs (8 virtual devices on CPU): padded
# baseline vs ragged/skew-aware/coded, bit-identical outputs; bench-diff
# enforces the skew-aware leg's min_vs_baseline >= 1.3 floor
bench-exchange:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 TEZ_BENCH_EXCHANGE_ONLY=1 $(PY) bench.py

# query plane (docs/query.md): broadcast-vs-repartition legs on the
# uniform + zipf corpora, then the adaptive-replan headline — run 1
# repartitions by estimate, run 2 is replanned to broadcast from the
# observed stats and must beat run 1 (bench-diff enforces the
# min_vs_baseline >= 1.0 floor); the QUERY_REPLANNED event is asserted
# in the JSONL journal and in doctor's rendering
bench-query:
	JAX_PLATFORMS=cpu TEZ_BENCH_QUERY_ONLY=1 $(PY) bench.py

chaos:
	$(PY) -m tez_tpu.tools.chaos --trials 3

chaos-device-ooo:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --device-ooo --trials 3

# failure-containment soak: hung dispatch + OOM storm + reorder, all bit-exact
chaos-device:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --device-ooo --device-hang --device-oom-storm --trials 3

# reduce-side merge-lane containment: OOM storm on async merge dispatches,
# breaker trip + short-circuit + half-open recovery, drained output bit-exact
chaos-merge:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --merge-storm --trials 3

# buffer-store eviction storm: wide shuffle through deliberately tiny store
# tiers forces demotion/eviction mid-merge, output bit-exact vs store-off
chaos-store:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --store-pressure --trials 3

# push-transport kill storm: eager pushes die mid-map-wave (seeded
# shuffle.push.send faults); the pull backstop must keep the output
# bit-exact vs a fault-free pull-only baseline
chaos-push:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --push-storm --trials 3

# AM crash survival: SIGKILL the session AM with one DAG mid-run and two
# parked in the admission queue, reattach, replay — every DAG bit-exact,
# parked losses typed, zombies fenced; plus the coded push-replica
# failover leg (store.replica.lost, zero producer re-execution)
chaos-ha:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --am-kill --trials 3

# streaming crash survival: 3 resident streams on one session AM under
# seeded mid-window task kills, then an AM crash mid-stream with sealed
# uncommitted windows + a half-filled open spool on disk; the successor
# window-exact replays from the commit ledger — committed windows
# bit-exact vs a fault-free feed, zero duplicate commits, bounded lag
chaos-stream:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --stream-kill --trials 3

# burn-before-breach SLO alerting: one resident stream ramping toward a
# window-p95 target; the telemetry sampler's multi-window burn evaluation
# must journal SLO_BURN_ALERT strictly before TENANT_SLO_BREACH, fsck's
# SLO ledger and the doctor's alert->breach join must both agree
chaos-slo-burn:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --slo-burn --trials 3

# multi-tenant session soak: one resident session AM under barrier-synced
# recurring DAGs from 3 tenants, forced am.admit.shed / am.queue.delay
# faults plus seeded task faults — every accepted DAG bit-exact, shed
# submissions the only (typed) losses, store bytes tenant-attributed,
# zero epoch fences, per-tenant p95 bounded
soak:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --tenant-storm --trials 3

# query kill storm: the whole deterministic corpus suite twice per trial
# (seed parity picks uniform vs zipf) under seeded task/fetch kills with
# the result cache on — every run bit-exact vs the numpy oracle, kills
# confirmed in the journal, round 2 must serve lineage cache hits
chaos-query-storm:
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --query-storm --trials 3

# skewed hot-key exchange with one delayed chip (mesh.exchange.delay):
# the splitter must hold the round count down and coded r2 must mask the
# straggler, output bit-exact vs the fault-free padded baseline
chaos-exchange:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 $(PY) -m tez_tpu.tools.chaos --exchange-skew --trials 3

# live terminal view of one AM's GET /doctor/live (docs/telemetry.md);
# the AM must run with tez.am.web.enabled=true (make soak does)
URL ?= http://127.0.0.1:8080
top:
	$(PY) -m tez_tpu.tools.top $(URL)

# tier-1 scrape smoke: boot an AM with the web UI on, then validate
# /metrics via the strict golden parser, /metrics.json structurally,
# and /doctor/live through graft top's renderer
metrics-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_metrics_smoke.py -q

docs:
	$(PY) -m tez_tpu.tools.gen_config_docs > docs/configuration.md

# causal auto-triage (docs/doctor.md): one flight-armed tenant-storm run,
# then the doctor's cross-plane blame waterfall over its history journals
# + flight dumps.  DOCTOR_DIR is kept so the artifacts can be re-examined
# (doctor runs on the storm session's journals; the tsbase* warmup
# baselines would otherwise dominate the straggler ranking).
DOCTOR_DIR ?= /tmp/tez-doctor
doctor:
	rm -rf $(DOCTOR_DIR) && mkdir -p $(DOCTOR_DIR)
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.chaos --tenant-storm --trials 1 --dump-flight --workdir $(DOCTOR_DIR)
	JAX_PLATFORMS=cpu $(PY) -m tez_tpu.tools.doctor $(DOCTOR_DIR)/tenantstorm0 $(DOCTOR_DIR)/flight_*.json
