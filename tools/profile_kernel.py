"""Stage-by-stage wall profile of the CPU-fallback kernel headline path.

Round-5 target: kernel vs_baseline >= 3.0 against the PipelinedSorter-
semantics C++ proxy (BASELINE.json).  This breaks the native host engine's
2M-record run into its stages so optimization goes where the time is.
Run alone on the single bench core (memory: never two benches at once).
"""
from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    from bench import make_records
    from tez_tpu.ops.native import (fnv32_partition_native,
                                    sort_partition_keys_native,
                                    merge_runs_native,
                                    pipelined_sorter_proxy)
    from tez_tpu.ops.runformat import KVBatch
    from tez_tpu.ops.sorter import DeviceSorter, merge_sorted_runs

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    key_len = 12
    num_producers, num_partitions = 4, 4
    kb, ko, vb, vo = make_records(n, key_len)
    total_mb = (kb.nbytes + vb.nbytes) / 1e6
    uniq = len(np.unique(kb.reshape(n, key_len), axis=0))
    print(f"n={n} total={total_mb:.1f}MB unique_keys={uniq}")

    def t(label, fn, reps=3):
        fn()  # warm
        t0 = time.time()
        for _ in range(reps):
            out = fn()
        dt = (time.time() - t0) / reps
        print(f"{label:38s} {dt*1000:8.1f} ms")
        return out, dt

    per = n // num_producers
    kbp = kb[: per * key_len]
    kop = np.arange(per + 1, dtype=np.int64) * key_len
    vbp = vb[: per * 8]
    vop = np.arange(per + 1, dtype=np.int64) * 8

    parts, dt_part = t("fnv32_partition (1 producer span)",
                       lambda: fnv32_partition_native(kbp, kop,
                                                      num_partitions))
    perm, dt_sort = t("tz_sort_partition_keys (1 span)",
                      lambda: sort_partition_keys_native(kbp, kop, parts))

    batch = KVBatch(kbp, kop, vbp, vop)
    _, dt_take = t("batch.take(perm) (1 span)", lambda: batch.take(perm))

    def one_producer():
        s = DeviceSorter(num_partitions=num_partitions, engine="host",
                         key_width=key_len)
        s.write_batch(KVBatch(kbp, kop, vbp, vop))
        return s.flush()
    run1, dt_prod = t("DeviceSorter full producer (1 span)", one_producer)

    def all_runs():
        runs = []
        for p in range(num_producers):
            lo = p * per
            hi = (p + 1) * per if p < num_producers - 1 else n
            m = hi - lo
            s = DeviceSorter(num_partitions=num_partitions, engine="host",
                             key_width=key_len)
            s.write_batch(KVBatch(
                kb[lo * key_len:hi * key_len],
                np.arange(m + 1, dtype=np.int64) * key_len,
                vb[lo * 8:hi * 8],
                np.arange(m + 1, dtype=np.int64) * 8))
            runs.append(s.flush())
        return runs
    runs, dt_runs = t("all 4 producers", all_runs, reps=1)

    _, dt_merge = t("merge_sorted_runs (4 runs)",
                    lambda: merge_sorted_runs(runs, num_partitions, key_len,
                                              engine="host"), reps=1)

    # merge internals
    batch_c = KVBatch.concat([r.batch for r in runs])
    partitions = np.concatenate([
        np.repeat(np.arange(r.num_partitions, dtype=np.int32),
                  np.diff(r.row_index)) for r in runs])
    run_bounds = np.zeros(len(runs) + 1, dtype=np.int64)
    np.cumsum([r.batch.num_records for r in runs], out=run_bounds[1:])
    _, dt_concat = t("  merge: KVBatch.concat",
                     lambda: KVBatch.concat([r.batch for r in runs]))
    permm, dt_mr = t("  merge: tz_merge_runs",
                     lambda: merge_runs_native(batch_c.key_bytes,
                                               batch_c.key_offsets,
                                               partitions, run_bounds))
    _, dt_take2 = t("  merge: take(perm) 2M",
                    lambda: batch_c.take(permm))

    def full():
        return merge_sorted_runs(all_runs(), num_partitions, key_len,
                                 engine="host")
    _, dt_full = t("FULL native_once (sorts + merge)", full, reps=3)

    res = pipelined_sorter_proxy(kb.reshape(n, key_len), vb.reshape(n, 8),
                                 num_producers, num_partitions)
    if res is None:
        print("C++ proxy unavailable (native lib missing); no ratio")
        return
    print(f"{'C++ proxy (baseline)':38s} {res[0]*1000:8.1f} ms")
    print(f"native/proxy ratio: {res[0]/dt_full:.3f}x  "
          f"({total_mb/dt_full:.1f} MB/s vs {total_mb/res[0]:.1f} MB/s)")


if __name__ == "__main__":
    main()
