"""Background TPU probe: retry jax.devices() all session, log diagnostics.

Round-3 verdict item 1: the TPU relay stalls (`jax.devices()` hangs >90 s).
This probe runs as a detached background process for the whole round, retrying
device initialisation with a hard per-attempt timeout (via a child process so a
hung libtpu cannot wedge the prober itself), and appends one JSON line per
attempt to TPU_PROBE.jsonl.  The moment an attempt succeeds it writes
TPU_READY.json with the device inventory and keeps the probe alive so bench.py
can check freshness.

Usage:  python tools/tpu_probe.py [--interval 60] [--attempt-timeout 300]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE.jsonl")
READY = os.path.join(REPO, "TPU_READY.json")

CHILD_SRC = r"""
import faulthandler, json, os, sys, time
t0 = time.time()
# The env's platform is `axon` (TPU relay tunnel): do NOT override
# JAX_PLATFORMS — forcing `tpu` attempts a local libtpu init with no local
# chip and hangs unconditionally.  On timeout the parent gets this stack
# dump on stderr (diagnostic artifact: where initialization died).
faulthandler.dump_traceback_later(float(sys.argv[1]) - 5, exit=True)
try:
    import jax
    devs = jax.devices()
    out = {
        "ok": True,
        "platform": devs[0].platform if devs else None,
        "n_devices": len(devs),
        "kinds": sorted({getattr(d, "device_kind", "?") for d in devs}),
        "init_s": round(time.time() - t0, 2),
        "jax_version": jax.__version__,
    }
except Exception as e:  # noqa: BLE001
    out = {"ok": False, "error": f"{type(e).__name__}: {e}",
           "init_s": round(time.time() - t0, 2)}
print(json.dumps(out))
"""


def attempt(timeout: float) -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", CHILD_SRC, str(timeout)],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
        wall = round(time.time() - t0, 2)
        line = (proc.stdout or "").strip().splitlines()
        if line:
            try:
                res = json.loads(line[-1])
                res["wall_s"] = wall
                return res
            except json.JSONDecodeError:
                pass
        return {"ok": False, "error": "no-json-output", "wall_s": wall,
                "rc": proc.returncode,
                "stderr_tail": (proc.stderr or "")[-3000:]}
    except subprocess.TimeoutExpired as e:
        stderr = e.stderr
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
        return {"ok": False, "error": f"timeout>{timeout}s",
                "wall_s": round(time.time() - t0, 2),
                "stderr_tail": (stderr or "")[-3000:]}


def relay_port_probe(port: int = 8083, timeout: float = 3.0
                     ) -> "tuple[bool, str, float]":
    """Fast liveness pre-check: the axon plugin's stateless RPC port
    (jax.devices() path — see TPU_DIAGNOSTIC.md).  Returns (up, error
    detail, measured wall) — refused vs timed-out are DIFFERENT relay
    failure modes and the log must say which."""
    import socket
    t0 = time.time()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True, "", time.time() - t0
    except ConnectionRefusedError:
        return False, f"relay-port-{port}-refused", time.time() - t0
    except (socket.timeout, TimeoutError):
        return False, f"relay-port-{port}-connect-timeout", time.time() - t0
    except OSError as e:
        return False, f"relay-port-{port}-{type(e).__name__}", \
            time.time() - t0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=60.0)
    ap.add_argument("--attempt-timeout", type=float, default=300.0)
    ap.add_argument("--max-attempts", type=int, default=0, help="0 = forever")
    args = ap.parse_args()

    n = 0
    while True:
        n += 1
        # cheap socket probe gates the expensive jax attempt; every 20th
        # round goes the full way regardless (the port contract could
        # change under us)
        up, err, wall = relay_port_probe()
        if not up and n % 20 != 0:
            res = {"ok": False, "error": err, "wall_s": round(wall, 3)}
        else:
            res = attempt(args.attempt_timeout)
        res["attempt"] = n
        res["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open(LOG, "a") as f:
            f.write(json.dumps(res) + "\n")
        if res.get("ok"):
            with open(READY, "w") as f:
                json.dump(res, f, indent=1)
            # Re-probe occasionally to keep READY fresh, but back off.
            time.sleep(max(args.interval, 300))
        else:
            if args.max_attempts and n >= args.max_attempts:
                return
            time.sleep(args.interval)


if __name__ == "__main__":
    main()
