"""Fetch scheduling for the DCN shuffle path.

Reference parity: tez-runtime-library/.../shuffle/orderedgrouped/
ShuffleScheduler.java:91 — per-host queues (MapHost), a bounded fetcher
pool (:295), multi-output coalescing per connection (keep-alive batching),
a penalty DelayQueue with backoff Referee (:179-180), per-input retry
accounting, and speculative refetch of stalled connections.

TPU-first deltas: this scheduler only runs for inter-host (DCN) fetches —
same-host handoffs short-circuit through tez_tpu.shuffle.service and
intra-slice scatter-gather rides the ICI mesh exchange instead
(parallel/coordinator.py), so the pool is sized for cross-slice stragglers,
not the common path.
"""
from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from tez_tpu.common import metrics, tracing
from tez_tpu.shuffle.service import ShuffleDataNotFound
from tez_tpu.utils.backoff import ExponentialBackoff

log = logging.getLogger(__name__)

HostKey = Tuple[str, int]


@dataclass
class FetchRequest:
    """One (source output, partition) to pull from one host."""
    host: str
    port: int
    path: str
    spill: int
    partition: int
    #: opaque caller cookie handed back on delivery
    cookie: Any = None
    attempts: int = 0
    speculative: bool = False
    #: caller's trace context (tracing.TraceContext | None): fetch spans,
    #: penalty-box holds and retry events parent under the consuming task
    trace: Any = None
    #: measured wire RTT of the successful fetch, stamped before delivery
    rtt_ms: float = 0.0

    @property
    def key(self) -> Tuple[str, int, int]:
        return (self.path, self.spill, self.partition)


class _Host:
    """MapHost analog: pending queue + penalty/busy state.  ``active`` is a
    count, not a flag: a speculative refetch legitimately opens a second
    concurrent connection, and serialization must resume only when BOTH are
    done."""
    __slots__ = ("key", "pending", "active", "penalized", "failures")

    def __init__(self, key: HostKey) -> None:
        self.key = key
        self.pending: deque = deque()
        self.active = 0
        self.penalized = False
        self.failures = 0


class _Inflight:
    __slots__ = ("host_key", "requests", "started")

    def __init__(self, host_key: HostKey, requests: List[FetchRequest],
                 started: float):
        self.host_key = host_key
        self.requests = requests
        self.started = started


def TcpFetchSession(secrets: Any, host: str, port: int,
                    connect_timeout: float = 5.0, ssl_context: Any = None,
                    read_timeout: float = 30.0, epoch: int = 0,
                    app_id: str = ""):
    """Real transport session: ONE TCP connect + nonce handshake, many
    fetches (shuffle/server.py FetchSession — the server's handler loops
    per connection).  epoch/app_id stamp each request so the server can
    fence consumers from a superseded AM incarnation."""
    from tez_tpu.shuffle.server import FetchSession
    return FetchSession(secrets, host, port, connect_timeout,
                        ssl_context=ssl_context, read_timeout=read_timeout,
                        epoch=epoch, app_id=app_id)


class FetchScheduler:
    """Bounded fetcher pool over per-host queues with penalty-box backoff.

    ``deliver(request, batch, error)`` is invoked exactly once per enqueued
    request key — batch on success (or ``None`` for a speculative duplicate
    that lost the race... those are swallowed, not delivered), error after
    the retry budget or on a definitive miss.
    """

    def __init__(self, deliver: Callable[[FetchRequest, Any, Optional[Exception]], None],
                 session_factory: Callable[[str, int], Any],
                 num_fetchers: int = 8,
                 max_per_fetch: int = 20,
                 penalty_base: float = 0.25,
                 penalty_cap: float = 10.0,
                 max_attempts: int = 4,
                 stall_timeout: float = 15.0,
                 name: str = "shuffle",
                 penalty_rng: Optional[random.Random] = None,
                 session_ttl: float = 30.0,
                 clock: Callable[[], float] = time.time,
                 local_probe: Optional[Callable[[str, int, int], Any]] = None):
        self.deliver = deliver
        self.session_factory = session_factory
        # injectable clock drives every TTL/penalty/stall decision so tests
        # can step time deterministically (never touches perf_counter RTTs)
        self._clock = clock
        # store short-circuit: a probe that returns the batch when this
        # host already holds the data (same-host buffer store) — probed
        # requests never open a connection
        self.local_probe = local_probe
        self.num_fetchers = max(1, num_fetchers)
        self.max_per_fetch = max(1, max_per_fetch)
        self.penalty_base = penalty_base
        self.penalty_cap = penalty_cap
        # full jitter so fetchers penalized by the same bad host don't
        # reconnect in lockstep when the box opens; penalty_rng pins the
        # draw for deterministic tests
        self._penalty = ExponentialBackoff(penalty_base, penalty_cap,
                                           jitter=True, rng=penalty_rng)
        self.max_attempts = max_attempts
        self.stall_timeout = stall_timeout
        self.session_ttl = session_ttl

        self.lock = threading.Condition()
        # per-host keep-alive cache: a healthy session is checked back in
        # after its batch instead of closed, so the next batch to the same
        # host skips the TCP connect + nonce handshake.  Bounded: OPEN
        # sessions (cached + checked out) never exceed num_fetchers — the
        # cache yields (oldest idle first) before a new connect.  The
        # referee closes entries idle past session_ttl.
        self._session_cache: Dict[HostKey, Tuple[Any, float]] = {}
        self._open_sessions = 0
        self.hosts: Dict[HostKey, _Host] = {}
        self.ready: deque = deque()            # host keys with runnable work
        self.penalties: List[Tuple[float, HostKey]] = []   # heap
        self.inflight: Dict[int, _Inflight] = {}           # worker id -> batch
        self.done_keys: Set[Tuple[str, int, int]] = set()  # delivered once
        self.speculated: Set[Tuple[str, int, int]] = set()
        self._outstanding = 0      # enqueued keys not yet delivered (gauge)
        self._stopped = False
        self._workers = [
            threading.Thread(target=self._worker, args=(i,), daemon=True,
                             name=f"{name}-fetcher-{i}")
            for i in range(self.num_fetchers)]
        self._referee = threading.Thread(target=self._referee_loop,
                                         daemon=True, name=f"{name}-referee")
        for t in self._workers:
            t.start()
        self._referee.start()

    # ------------------------------------------------------------------ API
    def enqueue(self, req: FetchRequest) -> None:
        key = (req.host, req.port)
        with self.lock:
            if self._stopped or req.key in self.done_keys:
                return
            host = self.hosts.get(key)
            if host is None:
                host = self.hosts[key] = _Host(key)
            host.pending.append(req)
            if not req.speculative:
                self._outstanding += 1
                metrics.set_gauge("shuffle.queued_fetches",
                                  self._outstanding)
            self._make_ready(host)
            self.lock.notify()

    def stop(self) -> None:
        with self.lock:
            self._stopped = True
            for sess, _ in self._session_cache.values():
                self._close_session(sess)
            self._session_cache.clear()
            self.lock.notify_all()

    # ------------------------------------------------------------ internals
    def _close_session(self, session: Any) -> None:
        """Caller holds the lock (Condition wraps an RLock, so re-entry from
        checkout eviction is fine).  close() is a socket close — it never
        calls deliver, so the no-two-locks rule holds."""
        self._open_sessions -= 1
        try:
            session.close()
        except Exception:  # noqa: BLE001
            pass

    def _checkout_session(self, host: _Host) -> Any:
        """Reuse the host's cached session, or connect a new one.  The
        connect happens OUTSIDE the lock (it can block for seconds); the
        open-session slot is reserved first so the bound can't be raced.

        TTL is validated HERE, not only in the referee sweep: a session
        that idled past session_ttl may already be half-closed by the
        server, and the referee may simply not have woken yet — reusing
        it would fail the whole batch and penalize a healthy host.  The
        expired session is closed and a fresh connect replaces it (the
        slot count carries over 1:1)."""
        with self.lock:
            cached = self._session_cache.pop(host.key, None)
            if cached is not None:
                sess, last = cached
                if self.session_ttl > 0 and \
                        self._clock() - last >= self.session_ttl:
                    # stale: close (releases its slot) and fall through to
                    # the fresh-connect path, which re-reserves a slot
                    self._close_session(sess)
                else:
                    return sess       # already counted in _open_sessions
            while self._open_sessions >= self.num_fetchers and \
                    self._session_cache:
                oldest = min(self._session_cache,
                             key=lambda k: self._session_cache[k][1])
                sess, _ = self._session_cache.pop(oldest)
                self._close_session(sess)
            self._open_sessions += 1
        try:
            return self.session_factory(*host.key)
        except BaseException:
            with self.lock:
                self._open_sessions -= 1
            raise

    def _checkin_session(self, host: _Host, session: Any,
                         healthy: bool) -> None:
        with self.lock:
            if not healthy or self._stopped or self.session_ttl <= 0 or \
                    host.key in self._session_cache:
                # close-on-error (the connection is suspect), on shutdown,
                # or when a concurrent speculative batch already cached one
                self._close_session(session)
            else:
                self._session_cache[host.key] = (session, self._clock())
                self.lock.notify_all()    # referee recomputes TTL deadline

    def _make_ready(self, host: _Host) -> None:
        """Caller holds the lock."""
        if host.active == 0 and not host.penalized and host.pending and \
                host.key not in self.ready:
            self.ready.append(host.key)

    def _worker(self, worker_id: int) -> None:
        while True:
            with self.lock:
                while not self.ready and not self._stopped:
                    self.lock.wait(0.5)
                if self._stopped:
                    return
                host = self.hosts[self.ready.popleft()]
                batch_reqs: List[FetchRequest] = []
                while host.pending and len(batch_reqs) < self.max_per_fetch:
                    r = host.pending.popleft()
                    if r.key in self.done_keys:
                        continue
                    batch_reqs.append(r)
                if not batch_reqs:
                    self._make_ready(host)
                    continue
                host.active += 1
                self.inflight[worker_id] = _Inflight(host.key, batch_reqs,
                                                     self._clock())
                self.lock.notify_all()   # referee recomputes its deadline
            self._fetch_batch(worker_id, host, batch_reqs)

    def _fetch_batch(self, worker_id: int, host: _Host,
                     reqs: List[FetchRequest]) -> None:
        """ONE session fetches every request (coalescing); reused from the
        per-host cache across batches when the last one ended healthy."""
        if self.local_probe is not None:
            # store short-circuit: serve what this host already holds and
            # connect only for the remainder (zero-copy same-host path)
            remaining: List[FetchRequest] = []
            for req in reqs:
                try:
                    batch = self.local_probe(req.path, req.spill,
                                             req.partition)
                except BaseException:  # noqa: BLE001 — probe is best-effort
                    batch = None
                if batch is None:
                    remaining.append(req)
                    continue
                metrics.observe("shuffle.fetch.short_circuit", 0.0)
                tracing.event("shuffle.fetch.short_circuit",
                              parent=req.trace, src=req.path,
                              spill=req.spill, partition=req.partition)
                self._deliver_once(req, batch, None)
            reqs = remaining
            if not reqs:
                with self.lock:
                    self.inflight.pop(worker_id, None)
                    host.active -= 1
                    self._make_ready(host)
                    self.lock.notify()
                return
        session = None
        completed = 0
        failed_conn: Optional[Exception] = None
        try:
            session = self._checkout_session(host)
            for i, req in enumerate(reqs):
                sp = tracing.span(
                    "shuffle.fetch", cat="shuffle", parent=req.trace,
                    mode="remote", host=f"{req.host}:{req.port}",
                    src=req.path, spill=req.spill, partition=req.partition,
                    attempt=req.attempts, speculative=req.speculative)
                t0 = time.perf_counter()
                try:
                    with sp:
                        batch = session.fetch(req.path, req.spill,
                                              req.partition)
                except (ShuffleDataNotFound, PermissionError) as e:
                    # definitive per-input miss: deliver, connection is fine
                    self._deliver_once(req, None, e)
                    completed = i + 1
                    continue
                except BaseException as e:  # noqa: BLE001 — conn-level fault
                    failed_conn = e
                    completed = i
                    break
                req.rtt_ms = (time.perf_counter() - t0) * 1000.0
                metrics.observe("shuffle.fetch.rtt", req.rtt_ms)
                self._deliver_once(req, batch, None)
                completed = i + 1
        except BaseException as e:  # noqa: BLE001 — session open failed
            failed_conn = e
        finally:
            if session is not None:
                self._checkin_session(host, session, failed_conn is None)
        failed_out: List[Tuple[FetchRequest, Exception]] = []
        with self.lock:
            self.inflight.pop(worker_id, None)
            host.active -= 1
            if failed_conn is not None:
                failed_out = self._host_failed(host, reqs[completed:],
                                               failed_conn)
            else:
                host.failures = 0
            self._make_ready(host)
            self.lock.notify()
        # outside the scheduler lock: delivery takes the caller's lock and
        # the caller's threads take ours via enqueue — never hold both
        for req, err in failed_out:
            self._deliver_once(req, None, err)

    def _deliver_once(self, req: FetchRequest, batch: Any,
                      error: Optional[Exception]) -> None:
        with self.lock:
            if req.key in self.done_keys:
                return      # speculative duplicate lost the race
            self.done_keys.add(req.key)
            self._outstanding = max(0, self._outstanding - 1)
            metrics.set_gauge("shuffle.queued_fetches", self._outstanding)
        try:
            self.deliver(req, batch, error)
        except BaseException:  # noqa: BLE001 — a callback fault must not
            log.exception("fetch delivery failed for %s", req.key)

    def _host_failed(self, host: _Host, rest: List[FetchRequest],
                     error: Exception
                     ) -> List[Tuple[FetchRequest, Exception]]:
        """Caller holds the lock.  Penalize the host with exponential
        backoff; requeue the unfetched requests; return the ones whose
        retry budget is exhausted (caller delivers them lock-free)."""
        host.failures += 1
        penalty = self._penalty.delay(host.failures - 1)
        failed_out: List[Tuple[FetchRequest, Exception]] = []
        for req in rest:
            req.attempts += 1
            if req.attempts >= self.max_attempts:
                failed_out.append((req, ConnectionError(
                    f"fetch {req.key} from {host.key[0]}:{host.key[1]} "
                    f"failed after {req.attempts} attempts: {error!r}")))
            else:
                # speculative dups requeue too: the original may be stalled
                # forever, so dropping the dup could mean NOTHING delivers
                # this key (done_keys still dedups if both complete)
                host.pending.appendleft(req)
        if host.pending:
            host.penalized = True
            heapq.heappush(self.penalties,
                           (self._clock() + penalty, host.key))
            tracing.event("shuffle.penalty_box",
                          parent=rest[0].trace if rest else None,
                          host=f"{host.key[0]}:{host.key[1]}",
                          penalty_s=round(penalty, 4),
                          failures=host.failures,
                          error=f"{type(error).__name__}: {error}")
            log.info("penalty box: %s:%s for %.2fs (%d failures)",
                     host.key[0], host.key[1], penalty, host.failures)
        return failed_out

    def _referee_loop(self) -> None:
        """Releases penalized hosts when their penalty expires and issues
        speculative duplicates for stalled in-flight fetches.  Sleeps until
        the earliest deadline (penalty expiry or stall) rather than polling."""
        with self.lock:
            while not self._stopped:
                now = self._clock()
                # keep-alive TTL sweep: cached sessions idle past
                # session_ttl are closed so quiesced hosts don't pin
                # sockets (and server-side handler threads) forever
                for key in [k for k, (_, last) in
                            self._session_cache.items()
                            if now - last >= self.session_ttl]:
                    sess, _ = self._session_cache.pop(key)
                    self._close_session(sess)
                while self.penalties and self.penalties[0][0] <= now:
                    _, key = heapq.heappop(self.penalties)
                    host = self.hosts.get(key)
                    if host is not None:
                        host.penalized = False
                        self._make_ready(host)
                        self.lock.notify()
                # speculative refetch: an in-flight batch older than the
                # stall timeout gets duplicate requests on a NEW connection
                # (the stuck one may be a dead socket, not a dead host);
                # first completed delivery wins via done_keys
                for infl in list(self.inflight.values()):
                    if now - infl.started < self.stall_timeout:
                        continue
                    host = self.hosts.get(infl.host_key)
                    if host is None:
                        continue
                    added = 0
                    for req in infl.requests:
                        if req.key in self.done_keys or \
                                req.key in self.speculated:
                            continue
                        self.speculated.add(req.key)
                        dup = FetchRequest(req.host, req.port, req.path,
                                           req.spill, req.partition,
                                           cookie=req.cookie,
                                           attempts=req.attempts,
                                           speculative=True,
                                           trace=req.trace)
                        tracing.event("shuffle.speculative_refetch",
                                      parent=req.trace, key=str(req.key),
                                      host=f"{req.host}:{req.port}")
                        host.pending.append(dup)
                        added += 1
                        log.info("speculative refetch of %s from %s:%s",
                                 req.key, req.host, req.port)
                    # the stalled connection still counts in host.active;
                    # allow ONE concurrent speculative connection — only
                    # when this pass actually issued new duplicates
                    if added and not host.penalized and \
                            host.key not in self.ready:
                        self.ready.append(host.key)
                        self.lock.notify()
                deadline = self.penalties[0][0] if self.penalties else None
                for infl in self.inflight.values():
                    if all(r.key in self.speculated or r.key in self.done_keys
                           for r in infl.requests):
                        continue   # fully handled: its stall deadline is
                        # moot — never a reason to wake (avoids a 100Hz spin
                        # while a slow-but-alive batch drains)
                    stall_at = infl.started + self.stall_timeout
                    if deadline is None or stall_at < deadline:
                        deadline = stall_at
                if self._session_cache:
                    ttl_at = min(last for _, last in
                                 self._session_cache.values()) + \
                        self.session_ttl
                    if deadline is None or ttl_at < deadline:
                        deadline = ttl_at
                wait = 5.0 if deadline is None else \
                    max(0.01, deadline - self._clock())
                self.lock.wait(wait)
