"""Cross-host shuffle transport: TCP server + fetcher (the DCN path).

Reference parity: tez-plugins/tez-aux-services ShuffleHandler.java:159 (the
host-resident server every job's consumers fetch from, with job-token HMAC
auth and keep-alive batching) and tez-runtime-library Fetcher.java:79 (retry
with backoff, penalty accounting).  Intra-host fetches short-circuit through
tez_tpu.shuffle.service; this socket path carries inter-host (DCN) fetches
and AM-recovery reads.

Wire format (length-prefixed):
  greeting: 16-byte random per-connection nonce (server -> client)
  request : u32 len | JSON {path, spill, partition_lo, partition_hi, hmac-hex}
            where hmac = HMAC(token, path|spill|lo|hi|nonce) — covers the
            full canonical request and is bound to this connection, so a
            captured request cannot be replayed (SecureShuffleUtils MACs
            the entire request URL; the nonce adds replay resistance)
  response: u32 len | JSON {status, sizes:[...]} | concatenated Run blobs
Each requested partition ships as one checksummed single-partition Run blob
(ops.runformat serialization), so corruption is detected end-to-end.
"""
from __future__ import annotations

import io
import json
import logging
import os
import socket
import socketserver
import struct
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from tez_tpu.common import faults
from tez_tpu.common.security import (JobTokenSecretManager,
                                     hash_from_request, shuffle_request_msg)
from tez_tpu.ops.runformat import KVBatch, Run
from tez_tpu.shuffle.push import PushRejected
from tez_tpu.shuffle.service import (ShuffleDataNotFound, ShuffleService,
                                     local_shuffle_service)
from tez_tpu.utils.backoff import ExponentialBackoff, retry_call

log = logging.getLogger(__name__)


def _run_blob(batch: KVBatch) -> bytes:
    """Serialize one partition as a single-partition Run blob (checksummed)."""
    run = Run(batch, np.array([0, batch.num_records], dtype=np.int64))
    return run.to_bytes()


def _blob_to_batch(blob: bytes) -> KVBatch:
    return Run.from_bytes(blob, where="<shuffle fetch>").batch


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server: "ShuffleServer" = self.server  # type: ignore[assignment]
        try:
            nonce = os.urandom(16)
            self.wfile.write(nonce)
            self.wfile.flush()
            while True:  # keep-alive: serve multiple fetches per connection
                raw_len = self.rfile.read(4)
                if len(raw_len) < 4:
                    return
                (req_len,) = struct.unpack("<I", raw_len)
                req = json.loads(self.rfile.read(req_len))
                self._serve_one(server, req, nonce)
        except (ConnectionError, json.JSONDecodeError, struct.error):
            return

    def _serve_one(self, server: "ShuffleServer", req: dict,
                   nonce: bytes) -> None:
        if req.get("op") == "push":
            self._serve_push(server, req, nonce)
            return
        path = req.get("path", "")
        spill = int(req.get("spill", -1))
        lo = int(req.get("partition_lo", 0))
        hi = int(req.get("partition_hi", lo + 1))
        sig = bytes.fromhex(req.get("hmac", ""))
        faults.fire("shuffle.serve", detail=f"{path}/{spill}")
        if not server.secrets.verify_hash(
                sig, shuffle_request_msg(path, spill, lo, hi, nonce)):
            server.auth_failures += 1   # count BEFORE replying (clients may
            self._reply({"status": "forbidden"}, [])  # observe immediately)
            return
        # epoch fencing (correctness, not auth — deliberately outside the
        # HMAC): a consumer stamped with a pre-restart AM epoch must not be
        # served; its inputs may be re-runs the zombie doesn't know about.
        # Unstamped requests (epoch 0 / legacy clients) are never fenced.
        epoch = int(req.get("epoch", 0) or 0)
        if epoch > 0:
            from tez_tpu.common import epoch as epoch_registry
            if epoch_registry.is_stale(str(req.get("app", "") or ""), epoch):
                faults.fire("fence.stale_epoch",
                            detail=f"shuffle.serve {path}/{spill}")
                from tez_tpu.common import tracing
                tracing.event("fence.stale_epoch", seam="shuffle.serve",
                              reason="stale_consumer", msg_epoch=epoch,
                              src=f"{path}/{spill}")
                self._reply({"status": "fenced"}, [])
                return
        try:
            blobs = [
                _run_blob(server.service.fetch_partition(path, spill, p))
                for p in range(lo, hi)]
        except ShuffleDataNotFound:
            self._reply({"status": "not_found"}, [])
            return
        self._reply({"status": "ok",
                     "sizes": [len(b) for b in blobs]}, blobs)
        server.bytes_served += sum(len(b) for b in blobs)

    def _serve_push(self, server: "ShuffleServer", req: dict,
                    nonce: bytes) -> None:
        """Push verb: a remote mapper lands one spill's partitions in this
        host's buffer store (docs/push_shuffle.md).  Request JSON carries
        ``sizes`` describing the single-partition Run blobs that follow it
        on the wire.  The blobs are drained BEFORE any verdict so the
        keep-alive stream stays framed whatever we reply.  Replies:
        ok / retry (+retry_after_ms, admission said not now) / fenced
        (stale producer epoch) / forbidden / bad_request."""
        path = req.get("path", "")
        spill = int(req.get("spill", -1))
        lo = int(req.get("partition_lo", 0))
        hi = int(req.get("partition_hi", lo + 1))
        sizes = [int(s) for s in req.get("sizes", [])]
        blobs = [self.rfile.read(s) for s in sizes]
        sig = bytes.fromhex(req.get("hmac", ""))
        if not server.secrets.verify_hash(
                sig, shuffle_request_msg(path, spill, lo, hi, nonce)):
            server.auth_failures += 1
            self._reply({"status": "forbidden"}, [])
            return
        if len(sizes) != hi - lo or any(len(b) != s
                                        for b, s in zip(blobs, sizes)):
            self._reply({"status": "bad_request"}, [])
            return
        epoch = int(req.get("epoch", 0) or 0)
        app_id = str(req.get("app", "") or "")
        from tez_tpu.common.epoch import EpochFencedError
        try:
            for i, blob in enumerate(blobs):
                run = Run.from_bytes(blob, where=f"<push {path}/{spill}>")
                server.service.push_publish(
                    path, spill, run, partition=lo + i, epoch=epoch,
                    app_id=app_id)
        except EpochFencedError:
            # push_publish already fired the fence fault point + trace
            self._reply({"status": "fenced"}, [])
            return
        except PushRejected as e:
            # partitions admitted before the rejection stay published —
            # idempotent extras the retry republishes; the pull backstop
            # covers the rest either way
            self._reply({"status": "retry",
                         "retry_after_ms": e.retry_after_ms}, [])
            return
        except (IOError, ValueError):
            self._reply({"status": "bad_request"}, [])
            return
        self._reply({"status": "ok"}, [])
        server.bytes_pushed += sum(sizes)

    def _reply(self, header: dict, blobs: List[bytes]) -> None:
        hdr = json.dumps(header).encode()
        self.wfile.write(struct.pack("<I", len(hdr)) + hdr)
        for b in blobs:
            self.wfile.write(b)
        self.wfile.flush()


class ShuffleServer:
    """Host-resident shuffle server (one per runner host)."""

    def __init__(self, secrets: JobTokenSecretManager,
                 service: Optional[ShuffleService] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self.secrets = secrets
        self.service = service or local_shuffle_service()
        # TLS termination at accept (SSLFactory analog): the HMAC
        # handshake below runs INSIDE the encrypted channel
        from tez_tpu.common.tls import wrap_server_class
        server_cls = wrap_server_class(socketserver.ThreadingTCPServer,
                                       ssl_context)
        self._tcp = server_cls((host, port), _Handler,
                               bind_and_activate=True)
        self._tcp.daemon_threads = True
        # handler back-references
        self._tcp.secrets = secrets          # type: ignore[attr-defined]
        self._tcp.service = self.service     # type: ignore[attr-defined]
        self._tcp.auth_failures = 0          # type: ignore[attr-defined]
        self._tcp.bytes_served = 0           # type: ignore[attr-defined]
        self._tcp.bytes_pushed = 0           # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True, name="shuffle-server")

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    @property
    def auth_failures(self) -> int:
        return self._tcp.auth_failures  # type: ignore[attr-defined]

    @property
    def bytes_served(self) -> int:
        return self._tcp.bytes_served   # type: ignore[attr-defined]

    @property
    def bytes_pushed(self) -> int:
        return self._tcp.bytes_pushed   # type: ignore[attr-defined]

    def start(self) -> "ShuffleServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


class FetchSession:
    """One keep-alive connection serving many fetches — the server's handler
    loops per connection, so N map outputs coalesce onto one TCP connect +
    one nonce handshake (ShuffleHandler keep-alive batching;
    Fetcher.java's multi-output-per-connection fetch).

    Per-request misses (not_found/forbidden) leave the connection usable;
    OSError/struct.error mean the connection is dead — the caller discards
    the session."""

    def __init__(self, secrets: JobTokenSecretManager, host: str, port: int,
                 connect_timeout: float = 5.0, ssl_context=None,
                 read_timeout: float = 30.0, epoch: int = 0,
                 app_id: str = ""):
        self.secrets = secrets
        self.host, self.port = host, port
        # AM-epoch stamp for fetch requests (0 = unstamped): lets the server
        # fence consumers from a superseded AM incarnation
        self.epoch = epoch
        self.app_id = app_id
        faults.fire("shuffle.fetch.connect", detail=f"{host}:{port}")
        self._sk = socket.create_connection((host, port),
                                            timeout=connect_timeout)
        if ssl_context is not None:
            # handshake still under the CONNECT budget (the socket timeout
            # is connect_timeout until after the wrap)
            self._sk = ssl_context.wrap_socket(self._sk)
        # distinct read deadline (reference: tez.runtime.shuffle.read.timeout
        # vs .connect.timeout) — a server that accepts but stops answering
        # must fail the fetch into the retry/penalty path, not hang it
        self._sk.settimeout(read_timeout)
        self._fh = self._sk.makefile("rb")
        self._nonce = self._fh.read(16)
        if len(self._nonce) != 16:
            self.close()
            raise ConnectionError("shuffle server closed before nonce")

    def fetch_range(self, path: str, spill: int, lo: int,
                    hi: int) -> List[KVBatch]:
        faults.fire("shuffle.fetch.read", detail=path)
        req = json.dumps({
            "path": path, "spill": spill,
            "partition_lo": lo, "partition_hi": hi,
            "epoch": self.epoch, "app": self.app_id,
            "hmac": hash_from_request(self.secrets, path, spill, lo, hi,
                                      self._nonce).hex(),
        }).encode()
        self._sk.sendall(struct.pack("<I", len(req)) + req)
        (hdr_len,) = struct.unpack("<I", self._fh.read(4))
        header = json.loads(self._fh.read(hdr_len))
        status = header.get("status")
        if status == "not_found":
            raise ShuffleDataNotFound(f"{path}/{spill}")
        if status != "ok":
            raise PermissionError(f"shuffle fetch {status}: {path}")
        return [_blob_to_batch(self._fh.read(size))
                for size in header["sizes"]]

    def fetch(self, path: str, spill: int, partition: int) -> KVBatch:
        return self.fetch_range(path, spill, partition, partition + 1)[0]

    def close(self) -> None:
        for closer in (self._fh.close, self._sk.close):
            try:
                closer()
            except OSError:
                pass


class ShuffleFetcher:
    """Client side: fetch with retry/backoff (Fetcher.java penalty-box lite).

    Raises ShuffleDataNotFound on a definitive miss (drives the
    InputReadErrorEvent path) and ConnectionError after retries."""

    def __init__(self, secrets: JobTokenSecretManager, retries: int = 3,
                 backoff: float = 0.2, connect_timeout: float = 5.0,
                 ssl_context=None, epoch: int = 0, app_id: str = ""):
        self.secrets = secrets
        # clamp here: retry_call's retries<1 ValueError would otherwise be
        # misread by fetch() as a retryable fetch fault
        self.retries = max(1, retries)
        self.backoff = backoff
        self.connect_timeout = connect_timeout
        self.ssl_context = ssl_context
        self.epoch = epoch
        self.app_id = app_id

    def fetch(self, host: str, port: int, path: str, spill: int,
              partition_lo: int, partition_hi: int = -1) -> List[KVBatch]:
        if partition_hi < 0:
            partition_hi = partition_lo + 1

        def one_try() -> List[KVBatch]:
            session = FetchSession(self.secrets, host, port,
                                   self.connect_timeout,
                                   ssl_context=self.ssl_context,
                                   epoch=self.epoch, app_id=self.app_id)
            try:
                return session.fetch_range(path, spill, partition_lo,
                                           partition_hi)
            finally:
                session.close()

        try:
            # struct.error covers truncated responses (server died
            # mid-reply) — retryable like any connection fault
            return retry_call(
                one_try, self.retries,
                retryable=(OSError, ValueError, struct.error),
                backoff=ExponentialBackoff(self.backoff, jitter=True),
                fatal=(ShuffleDataNotFound, PermissionError))
        except (ShuffleDataNotFound, PermissionError):
            raise   # definitive: retrying cannot help
        except (OSError, ValueError, struct.error) as e:
            raise ConnectionError(
                f"fetch {host}:{port}/{path} failed after "
                f"{self.retries} tries: {e!r}") from e
