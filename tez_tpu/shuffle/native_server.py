"""Native shuffle server bindings + the on-disk partition-blob store.

Reference parity: the NM-resident ShuffleHandler serves every local spill
file for every job from ONE native server with job-token HMAC auth and
zero-copy sendfile (ShuffleHandler.java:159, IndexCache, FadvisedFileRegion)
— index files say where each reducer's slice lives, and the server never
deserializes data.  Here:

- FileShuffleStore writes each registered Run as pre-serialized
  single-partition blobs (`<hex(path)>_<spill>.data`) plus a TZIX index of
  blob offsets (TezSpillRecord analog) — done once at producer close.
- native/shuffle_server.cpp serves byte ranges straight from those files
  via sendfile(2) on the SAME wire protocol as the Python ShuffleServer,
  so the existing FetchSession/ShuffleFetcher clients work unchanged.

Enable per-runner with TEZ_TPU_NATIVE_SHUFFLE_DIR (remote_runner wires the
store as a write-through on the in-process registry: local fetches stay
RAM short-circuited, remote fetches hit the C++ server).
"""
from __future__ import annotations

import ctypes
import logging
import os
import struct
import threading
from typing import Optional

from tez_tpu.common.security import JobTokenSecretManager
from tez_tpu.ops.runformat import Run

log = logging.getLogger(__name__)

_INDEX_MAGIC = b"TZIX"


def _base_name(path_component: str, spill_id: int) -> str:
    return f"{path_component.encode().hex()}_{spill_id}"


class FileShuffleStore:
    """Write-through persistence for the ShuffleService registry."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    def register(self, path_component: str, spill_id: int, run: Run) -> None:
        """Serialize partition-at-a-time; readers get raw byte ranges.
        Resident memory is one partition's blob (a disk-backed FileRun is
        never materialized whole)."""
        base = os.path.join(self.directory,
                            _base_name(path_component, spill_id))
        with self._lock:
            tmp = base + ".tmp"
            offsets = [0]
            with open(tmp, "wb") as fh:
                for p in range(run.num_partitions):
                    single = Run(run.partition(p),
                                 _two_entry_index(run.partition_row_count(p)))
                    blob = single.to_bytes()
                    fh.write(blob)
                    offsets.append(offsets[-1] + len(blob))
            os.replace(tmp, base + ".data")
            with open(base + ".index.tmp", "wb") as fh:
                fh.write(_INDEX_MAGIC)
                fh.write(struct.pack("<I", run.num_partitions))
                fh.write(struct.pack(f"<{len(offsets)}Q", *offsets))
            # data strictly before index: a reader that sees the index can
            # always sendfile the data
            os.replace(base + ".index.tmp", base + ".index")

    def unregister_prefix(self, prefix: str) -> int:
        """Deletion-tracker hook: remove all files whose decoded path starts
        with prefix."""
        removed = 0
        with self._lock:
            for name in os.listdir(self.directory):
                if not name.endswith(".index"):
                    continue
                hexpart = name[:-len(".index")].rsplit("_", 1)[0]
                try:
                    decoded = bytes.fromhex(hexpart).decode()
                except ValueError:
                    continue
                if decoded.startswith(prefix):
                    stem = name[:-len(".index")]
                    for suffix in (".index", ".data"):
                        try:
                            os.unlink(os.path.join(self.directory,
                                                   stem + suffix))
                        except OSError:
                            pass
                    removed += 1
        return removed


def _two_entry_index(n_rows: int):
    import numpy as np
    return np.array([0, n_rows], dtype=np.int64)


class NativeShuffleServer:
    """ctypes wrapper over the C++ server (one per process)."""

    def __init__(self, secrets: JobTokenSecretManager, store_dir: str,
                 host: str = "127.0.0.1", port: int = 0):
        from tez_tpu.ops.native import _load
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native shuffle server unavailable "
                               "(libtezhost.so failed to build/load)")
        self._configure_prototypes()
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        secret = secrets.secret
        self.port = int(self._lib.tez_shuffle_server_start(
            store_dir.encode(), secret, len(secret), host.encode(), port))
        if self.port <= 0:
            raise RuntimeError(f"native shuffle server failed to bind "
                               f"({host}:{port})")
        log.info("native shuffle server serving %s on port %d",
                 store_dir, self.port)

    def _configure_prototypes(self) -> None:
        lib = self._lib
        lib.tez_shuffle_server_start.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32]
        lib.tez_shuffle_server_start.restype = ctypes.c_int32
        lib.tez_shuffle_server_port.restype = ctypes.c_int32
        lib.tez_shuffle_server_bytes_served.restype = ctypes.c_uint64
        lib.tez_shuffle_server_auth_failures.restype = ctypes.c_uint64

    @property
    def bytes_served(self) -> int:
        return int(self._lib.tez_shuffle_server_bytes_served())

    @property
    def auth_failures(self) -> int:
        return int(self._lib.tez_shuffle_server_auth_failures())

    def start(self) -> "NativeShuffleServer":
        return self   # started at construction (bind reports errors early)

    def stop(self) -> None:
        self._lib.tez_shuffle_server_stop()


def native_available() -> bool:
    from tez_tpu.ops.native import _load
    lib = _load()
    return lib is not None and hasattr(lib, "tez_shuffle_server_start")
