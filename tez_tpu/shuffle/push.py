"""Push-based pipelined shuffle: the map-wave eager push transport.

Exoshuffle / Exoshuffle-CloudSort (PAPERS.md) invert Tez's pull shuffle:
mappers *push* partitioned blocks into reducer-side storage while the map
wave is still running, so reduce-side ingest and merge pipeline with the
map wave instead of starting after it.  This module is that transport for
the tez_tpu data plane, connecting the producer's pipelined spills to the
reducer-side ``ShuffleBufferStore``:

``SpillPusher``
    mapper side — a bounded thread pool that ships each finished spill
    asynchronously.  Same-host destinations publish straight through the
    buffer store (zero copy); remote ones ride the shuffle server's push
    verb (``shuffle/server.py``).  Full-jitter retry honors the admission
    controller's RETRY-AFTER hint; a per-destination in-flight byte cap
    blocks ``submit()`` so an over-eager mapper backpressures at the
    source instead of ballooning the queue.

``PushAdmissionController``
    reducer side — per-source byte quotas plus store host-watermark
    backpressure.  A rejected push raises ``PushRejected`` carrying the
    retry-after hint.

Correctness: the pull path is the backstop.  Every spill is registered
with the shuffle service (DME events and all) BEFORE its push is even
queued, so a dead pusher, a rejection storm, or a partial remote push
never loses data — consumers that miss the store simply fetch.  Pushes
are epoch fenced exactly like registers: a re-attempted mapper's stale
pushes are rejected at the landing zone.

Fault points: ``shuffle.push.send`` (each send attempt; fail mode kills
the eager push — the push-storm chaos lever) and ``shuffle.push.admit``
(each admission decision; fail mode turns it into a rejection, delay mode
stretches ``shuffle.push.admit_wait``).
"""
from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tez_tpu.common import faults, metrics
from tez_tpu.common.counters import TaskCounter
from tez_tpu.obs import flight as _flight
from tez_tpu.common.epoch import EpochFencedError
from tez_tpu.common.security import JobTokenSecretManager, hash_from_request
from tez_tpu.ops.runformat import Run
from tez_tpu.utils.backoff import ExponentialBackoff, retry_call

log = logging.getLogger(__name__)


def push_key(path_component: str, partition: int) -> str:
    """Store key for one remotely-pushed partition of a spill.

    Remote pushes land per partition (the wire moves single-partition Run
    blobs), so they key as ``path#p<partition>`` with partition index 0
    inside the stored run.  The '#' never appears in attempt path
    components, and the prefix match in ``unregister_prefix`` still
    catches these keys when the owning DAG is torn down.
    """
    return f"{path_component}#p{partition}"


def replica_key(key_path: str) -> str:
    """Store key for the coded buddy copy of a pushed spill.

    With ``tez.runtime.shuffle.push.replicas=2`` every push also lands
    under ``<key>#r`` — the buddy slot.  In a multi-host deployment the
    buddy STORE is the one owning partition ``coded_buddy(p, n)``
    (parallel/mesh.py, the PR-10 coded-exchange placement); the in-process
    simulation keys both copies into the host-wide store under distinct
    namespaces instead, which exercises the identical failover chain: a
    consumer whose primary entry is lost reconstructs from ``<key>#r``
    without re-running the producer (docs/recovery.md).  '#' never
    appears in attempt path components and the prefix match in
    ``unregister_prefix`` reclaims replica keys with their DAG."""
    return f"{key_path}#r"


class PushRejected(Exception):
    """Admission said no (quota / watermark / no landing zone).  Carries
    the retry-after hint; the pusher sleeps it and retries, then falls
    back to the pull path for good."""

    def __init__(self, retry_after_ms: float, reason: str):
        super().__init__(reason)
        self.retry_after_ms = float(retry_after_ms)
        self.reason = reason


class PushAdmissionController:
    """Reducer-side gatekeeper for eager pushes.

    Two rules, both deliberately conservative because pushed bytes are an
    optimization (the pull path still holds the data):

    * host-watermark backpressure — a push that would lift the store's
      HOST tier above ``admit_watermark * host_capacity`` is rejected.
      The watermark sits BELOW the store's own high watermark, so eager
      pushes never trigger the demotion cascade that registered (pull)
      data is entitled to ride.
    * per-source quota — one source attempt may hold at most
      ``source_quota_bytes`` admitted in this store, so a single hot
      mapper cannot crowd out the rest of the wave.

    ``release_prefix`` returns quota when the owning DAG (or attempt) is
    unregistered.  Thread-safe; one instance per host, attached to the
    ``ShuffleService``.
    """

    def __init__(self, store_provider: Callable[[], Any],
                 source_quota_bytes: int = 256 << 20,
                 admit_watermark: float = 0.85,
                 retry_after_ms: float = 50.0):
        self._store = store_provider
        self.source_quota = int(source_quota_bytes)
        self.admit_watermark = float(admit_watermark)
        self.retry_after_ms = float(retry_after_ms)
        self._lock = threading.Lock()
        self._by_source: Dict[str, int] = {}
        self.admitted = 0
        self.rejected = 0

    def admit(self, source: str, nbytes: int, counters: Any = None) -> None:
        """Admit ``nbytes`` from ``source`` or raise PushRejected."""
        try:
            faults.fire("shuffle.push.admit",
                        detail=f"{source} nbytes={nbytes}")
        except Exception as e:
            # fail mode = the decision becomes a rejection (the
            # backpressure chaos lever), never an unclassified error
            self._count_reject()
            raise PushRejected(self.retry_after_ms,
                               f"fault-injected reject: {e!r}") from e
        store = self._store()
        if store is None:
            self._count_reject()
            raise PushRejected(self.retry_after_ms,
                               "no buffer store on this host (push needs a "
                               "landing zone; spill stays pull-served)")
        from tez_tpu.store.buffer_store import HOST
        cap = int(getattr(store, "host_capacity", 0))
        if cap > 0 and store.tier_bytes(HOST) + nbytes > \
                cap * self.admit_watermark:
            self._count_reject()
            raise PushRejected(
                self.retry_after_ms,
                f"store host tier past admit watermark "
                f"({store.tier_bytes(HOST)} + {nbytes} > "
                f"{cap} * {self.admit_watermark})")
        with self._lock:
            held = self._by_source.get(source, 0)
            # a single spill larger than the whole quota is admitted while
            # the source holds nothing — otherwise it could never push
            if held > 0 and held + nbytes > self.source_quota:
                self.rejected += 1
                _flight.record(_flight.PUSH, "reject.quota", source,
                               a=nbytes)
                raise PushRejected(
                    self.retry_after_ms,
                    f"source quota exhausted for {source} "
                    f"({held} + {nbytes} > {self.source_quota})")
            self._by_source[source] = held + nbytes
            self.admitted += 1
        _flight.record(_flight.PUSH, "admit", source, a=nbytes)

    def _count_reject(self) -> None:
        with self._lock:
            self.rejected += 1
        _flight.record(_flight.PUSH, "reject")

    def release_prefix(self, prefix: str) -> int:
        """Return the quota held by every source under ``prefix`` (called
        from the service's deletion tracker on DAG/vertex cleanup)."""
        with self._lock:
            victims = [s for s in self._by_source if s.startswith(prefix)]
            freed = sum(self._by_source.pop(s) for s in victims)
        return freed

    def held(self, source: str) -> int:
        with self._lock:
            return self._by_source.get(source, 0)


def _partition_blob(batch: Any) -> bytes:
    """One partition as a checksummed single-partition Run blob (the same
    wire shape the fetch path serves)."""
    run = Run(batch, np.array([0, batch.num_records], dtype=np.int64))
    return run.to_bytes()


class PushSession:
    """One connection pushing one spill to a remote host's store.

    Client side of the shuffle server's push verb: after the 16-byte
    nonce greeting, sends ``u32 len | JSON {op:"push", path, spill,
    partition_lo, partition_hi, sizes:[...], epoch, app, hmac}`` followed
    by the concatenated single-partition Run blobs, and reads the usual
    ``u32 len | JSON`` reply.  The HMAC covers the same canonical request
    bytes as a fetch (path|spill|lo|hi|nonce) — a captured push neither
    re-targets another output nor replays on a new connection.
    """

    def __init__(self, secrets: JobTokenSecretManager, host: str, port: int,
                 connect_timeout: float = 5.0, read_timeout: float = 30.0,
                 ssl_context=None, epoch: int = 0, app_id: str = ""):
        self.secrets = secrets
        self.epoch = epoch
        self.app_id = app_id
        self._sk = socket.create_connection((host, port),
                                            timeout=connect_timeout)
        if ssl_context is not None:
            self._sk = ssl_context.wrap_socket(self._sk)
        self._sk.settimeout(read_timeout)
        self._fh = self._sk.makefile("rb")
        self._nonce = self._fh.read(16)
        if len(self._nonce) != 16:
            self.close()
            raise ConnectionError("shuffle server closed before nonce")

    def push_run(self, path: str, spill: int, run: Any) -> None:
        """Push every partition of ``run``; raises PushRejected on a
        RETRY-AFTER reply, EpochFencedError on a fence, PermissionError on
        auth failure."""
        num = int(run.num_partitions)
        blobs = [_partition_blob(run.partition(p)) for p in range(num)]
        req = json.dumps({
            "op": "push", "path": path, "spill": spill,
            "partition_lo": 0, "partition_hi": num,
            "sizes": [len(b) for b in blobs],
            "epoch": self.epoch, "app": self.app_id,
            "hmac": hash_from_request(self.secrets, path, spill, 0, num,
                                      self._nonce).hex(),
        }).encode()
        self._sk.sendall(struct.pack("<I", len(req)) + req)
        for b in blobs:
            self._sk.sendall(b)
        (hdr_len,) = struct.unpack("<I", self._fh.read(4))
        header = json.loads(self._fh.read(hdr_len))
        status = header.get("status")
        if status == "ok":
            return
        if status == "retry":
            raise PushRejected(float(header.get("retry_after_ms", 0.0)),
                               f"remote rejected push: {path}/{spill}")
        if status == "fenced":
            raise EpochFencedError(f"push fenced: {path}/{spill}")
        raise PermissionError(f"shuffle push {status}: {path}")

    def close(self) -> None:
        for closer in (self._fh.close, self._sk.close):
            try:
                closer()
            except OSError:
                pass


class SpillPusher:
    """Mapper-side async pusher: one per OrderedPartitionedKVOutput.

    ``submit()`` is called from the sorter's spill-completion callback; it
    blocks while the destination's in-flight bytes exceed the cap (map-
    side backpressure) then hands the push to the pool.  Push failures are
    terminal for the *push only* — the spill was registered for pull
    before submit, so failure just means the consumer fetches it.
    """

    def __init__(self, service: Any, threads: int = 2, retries: int = 3,
                 inflight_limit_bytes: int = 64 << 20,
                 counters: Any = None, epoch: int = 0, app_id: str = "",
                 tenant: str = "",
                 secrets: Optional[JobTokenSecretManager] = None,
                 backoff_base: float = 0.05, rng: Any = None,
                 replicas: int = 1, window_id: int = 0, stream: str = ""):
        self.service = service
        self.retries = max(1, int(retries))
        self.inflight_limit = int(inflight_limit_bytes)
        self.counters = counters
        #: copies per pushed spill (tez.runtime.shuffle.push.replicas);
        #: >1 lands a coded buddy copy alongside every primary push
        self.replicas = max(1, int(replicas))
        self.epoch = epoch
        self.app_id = app_id
        self.tenant = tenant
        #: generalized fence's second coordinate (0/"" = batch, unfenced)
        self.window_id = int(window_id)
        self.stream = stream
        self.secrets = secrets
        self.backoff_base = backoff_base
        self._rng = rng
        self._cv = threading.Condition()
        self._inflight: Dict[Tuple[str, int], int] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(threads)),
            thread_name_prefix="shuffle-pusher")
        self._closed = False
        self.pushes_sent = 0
        self.pushes_rejected = 0

    # -- producer API --------------------------------------------------------

    def submit(self, path: str, spill_id: int, run: Any,
               host: str = "local", port: int = 0) -> bool:
        """Queue one spill for eager push.  Blocks while the destination
        is over its in-flight byte cap; returns False when the pusher is
        already closed (spill stays pull-only)."""
        nbytes = int(getattr(run, "nbytes", 0))
        dest = (host, int(port))
        with self._cv:
            if self._closed:
                return False
            while self._inflight.get(dest, 0) > 0 and \
                    self._inflight.get(dest, 0) + nbytes > \
                    self.inflight_limit:
                self._cv.wait(0.05)
                if self._closed:
                    return False
            self._inflight[dest] = self._inflight.get(dest, 0) + nbytes
        try:
            self._pool.submit(self._push_one, path, spill_id, run, dest,
                              nbytes)
        except RuntimeError:        # pool shut down under us
            self._release(dest, nbytes)
            return False
        return True

    def close(self) -> None:
        """Drain: every queued push finishes (or exhausts its retries)
        before close returns, so push counters are settled by the time the
        task reports DONE."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._pool.shutdown(wait=True)

    # -- internals -----------------------------------------------------------

    def _release(self, dest: Tuple[str, int], nbytes: int) -> None:
        with self._cv:
            self._inflight[dest] = max(
                0, self._inflight.get(dest, 0) - nbytes)
            self._cv.notify_all()

    def _is_local(self, dest: Tuple[str, int]) -> bool:
        return dest[1] == 0 or dest[0] in ("local", "", "localhost")

    def _push_one(self, path: str, spill_id: int, run: Any,
                  dest: Tuple[str, int], nbytes: int) -> None:
        t0 = time.perf_counter()
        admit_wait_ms = 0.0

        def one_try() -> None:
            nonlocal admit_wait_ms
            faults.fire("shuffle.push.send",
                        detail=f"{path}/{spill_id} -> {dest[0]}:{dest[1]}")
            try:
                if self._is_local(dest):
                    # same-host: straight through the buffer store, zero
                    # copy — the store entry aliases the run the pull
                    # registry already holds
                    self.service.push_publish(
                        path, spill_id, run, epoch=self.epoch,
                        app_id=self.app_id, tenant=self.tenant,
                        counters=self.counters, replicas=self.replicas,
                        window_id=self.window_id, stream=self.stream)
                else:
                    if self.secrets is None:
                        raise PermissionError(
                            "remote push needs a job-token secret")
                    session = PushSession(self.secrets, dest[0], dest[1],
                                          epoch=self.epoch,
                                          app_id=self.app_id)
                    try:
                        session.push_run(path, spill_id, run)
                    finally:
                        session.close()
            except PushRejected as e:
                wait = max(0.0, e.retry_after_ms) / 1000.0
                admit_wait_ms += e.retry_after_ms
                time.sleep(wait)
                raise

        try:
            retry_call(
                one_try, self.retries,
                retryable=(PushRejected, OSError, ValueError, struct.error,
                           RuntimeError),
                backoff=ExponentialBackoff(self.backoff_base, jitter=True,
                                           rng=self._rng),
                fatal=(EpochFencedError, PermissionError))
            rtt_ms = (time.perf_counter() - t0) * 1000.0
            metrics.observe("shuffle.push.rtt", rtt_ms,
                            counters=self.counters)
            _flight.record(_flight.PUSH, "send", path, a=nbytes,
                           b=int(admit_wait_ms * 1000.0))
            if self.counters is not None:
                self.counters.increment(TaskCounter.SHUFFLE_PUSH_BYTES,
                                        nbytes)
            with self._cv:
                self.pushes_sent += 1
        except Exception as e:
            # terminal for the push only: the pull registration preceding
            # submit() is the correctness backstop
            log.debug("push abandoned (pull backstop serves %s/%s): %r",
                      path, spill_id, e)
            if self.counters is not None:
                self.counters.increment(TaskCounter.SHUFFLE_PUSH_REJECTED)
            with self._cv:
                self.pushes_rejected += 1
            _flight.record(_flight.PUSH, "abandon", path, a=nbytes)
        finally:
            metrics.observe("shuffle.push.admit_wait", admit_wait_ms,
                            counters=self.counters)
            self._release(dest, nbytes)
