"""Shuffle object service: producers register runs, consumers fetch slices.

Reference parity: tez-plugins/tez-aux-services ShuffleHandler.java:159 (the
NM-resident shuffle server with per-reduce index lookup) + the local-disk
short-circuit (Fetcher.java:288).  In-process deployments hand buffers over
directly (the "local fetch" path — zero copies of HBM/host-RAM data); the
socket server for cross-host fetch lives in tez_tpu.shuffle.server (DCN
path) and serves the same registry.

Keys: (path_component, spill_id) where path_component identifies a producer
attempt's output (reference: attempt path component in the shuffle URL).
DAG/vertex deletion tracking mirrors the reference's DeletionTracker.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from tez_tpu.common import epoch as epoch_registry
from tez_tpu.common import faults
from tez_tpu.common.epoch import EpochFencedError, WindowFencedError
from tez_tpu.ops.runformat import KVBatch, Run, RUN_HEADER_NBYTES
from tez_tpu.shuffle.push import PushRejected, push_key, replica_key


def _window_fence(seam: str, app_id: str, window_id: int, stream: str,
                  src: str) -> None:
    """The window coordinate of the generalized (epoch, window) fence at a
    shuffle seam: a straggler from a sealed streaming window is rejected
    exactly like a stale-epoch zombie (batch traffic — window 0 / no
    stream — is never fenced)."""
    if not epoch_registry.is_stale_window(app_id, stream, window_id):
        return
    faults.fire("fence.stale_window", detail=f"{seam} {src}")
    from tez_tpu.common import tracing
    tracing.event("fence.stale_window", seam=seam, reason="stale_window",
                  window_id=window_id, stream=stream,
                  current=epoch_registry.current_window(app_id, stream),
                  src=src)
    raise WindowFencedError(
        f"{seam} from stale window {window_id} of stream {stream} "
        f"(current {epoch_registry.current_window(app_id, stream)}): {src}")


def _maybe_corrupt(path_component: str, spill_id: int,
                   batch: KVBatch) -> KVBatch:
    """shuffle.data corrupt seam: round-trip the served partition through
    the checksummed Run wire blob with one byte flipped, so the injected
    damage surfaces as the genuine CRC IOError on the consumer side."""
    wire = Run(batch,
               np.array([0, batch.num_records], dtype=np.int64)).to_bytes()
    bad = faults.corrupt_bytes("shuffle.data", f"{path_component}/{spill_id}",
                               wire, lo=RUN_HEADER_NBYTES)
    if bad is wire:          # no corrupt rule claimed this fetch
        return batch
    return Run.from_bytes(bad, where=f"{path_component}/{spill_id}").batch


class ShuffleDataNotFound(Exception):
    pass


class ShuffleService:
    """Registry of completed (or pipelined-spill) runs on this host."""

    def __init__(self) -> None:
        self._runs: Dict[Tuple[str, int], Run] = {}
        self._lock = threading.Lock()
        self._store: Any = None
        self._buffer: Any = None
        self._push_admission: Any = None
        self._push_listeners: List[Any] = []

    def attach_store(self, store: Any) -> None:
        """Write-through persistence (FileShuffleStore): every registered
        run is also serialized to disk so the native sendfile server can
        serve it without touching Python.  Local fetches keep hitting the
        in-RAM registry."""
        self._store = store

    def has_store(self) -> bool:
        return self._store is not None

    def attach_buffer_store(self, store: Any) -> None:
        """Delegate run storage to the tiered buffer store
        (tez_tpu.store.ShuffleBufferStore): new registrations publish
        into its capacity-governed HBM/host/disk tiers and fetches serve
        from it under leases.  Runs registered before attach stay in the
        bare registry and keep working."""
        self._buffer = store

    def buffer_store(self) -> Any:
        return self._buffer

    def attach_push_admission(self, admission: Any) -> None:
        """Gatekeeper for eager pushes landing on this host
        (tez_tpu.shuffle.push.PushAdmissionController); None detaches —
        push_publish then rejects everything and producers stay on the
        pull path."""
        self._push_admission = admission

    def push_admission(self) -> Any:
        return self._push_admission

    def add_push_listener(self, fn: Any) -> None:
        """``fn(path_component, spill_id)`` fires after every admitted
        push publish — the merge-wake seam: consumers poke their merge
        manager so the async merge lane reacts to pushed arrivals
        mid-map-wave.  Listener errors are swallowed (a broken consumer
        must not fail the producer's push)."""
        self._push_listeners.append(fn)

    def remove_push_listener(self, fn: Any) -> None:
        try:
            self._push_listeners.remove(fn)
        except ValueError:
            pass

    # -- producer side -------------------------------------------------------
    def register(self, path_component: str, spill_id: int, run: Run,
                 epoch: int = 0, app_id: str = "",
                 lineage: str = "", tenant: str = "",
                 counters: Any = None,
                 use_store: bool = True, window_id: int = 0,
                 stream: str = "") -> None:
        """Producers stamped with an AM epoch are fenced: a zombie task from
        a pre-restart incarnation must not (re-)register outputs the live
        AM's re-runs now own.  Unstamped registrations (epoch 0, e.g. direct
        test callers) are never fenced.  Pre-crash data already registered
        stays fetchable — recovery's short-circuited consumers read it.
        In streaming mode the window coordinate is fenced the same way: a
        straggler from a sealed window cannot register into the open one."""
        _window_fence("shuffle.register", app_id, window_id, stream,
                      f"{path_component}/{spill_id}")
        if epoch > 0 and epoch_registry.is_stale(app_id, epoch):
            faults.fire("fence.stale_epoch",
                        detail=f"shuffle.register {path_component}")
            from tez_tpu.common import tracing
            tracing.event("fence.stale_epoch", seam="shuffle.register",
                          reason="stale_producer", msg_epoch=epoch,
                          src=f"{path_component}/{spill_id}")
            raise EpochFencedError(
                f"shuffle register from stale epoch {epoch} "
                f"(current {epoch_registry.current(app_id)}): "
                f"{path_component}/{spill_id}")
        if self._buffer is not None and use_store:
            from tez_tpu.store.buffer_store import StoreQuotaExceeded
            try:
                self._buffer.publish(path_component, spill_id, run,
                                     epoch=epoch, app_id=app_id,
                                     lineage=lineage, tenant=tenant,
                                     counters=counters)
            except StoreQuotaExceeded:
                # per-tenant quota refusal is isolation, not data loss:
                # the run stays in the bare registry (the producer's own
                # memory), pull-served like the pre-store path
                with self._lock:
                    self._runs[(path_component, spill_id)] = run
        else:
            # use_store=False is the push path's pull backstop: the run
            # lands in the bare registry synchronously (events may never
            # race a missing key) and the ASYNC push later aliases the
            # same object into the store — zero copy, no double-count
            with self._lock:
                self._runs[(path_component, spill_id)] = run
        from tez_tpu.common import tracing
        tracing.event("shuffle.register", src=f"{path_component}/{spill_id}",
                      nbytes=getattr(run, "nbytes", 0))
        if self._store is not None:
            self._store.register(path_component, spill_id, run)
            # a concurrent unregister_prefix between the RAM insert and the
            # file write would miss our files (its disk sweep ran first);
            # re-check and self-clean so deleted outputs never linger on
            # disk where the native server would keep serving them
            with self._lock:
                still = (path_component, spill_id) in self._runs
            if not still:
                self._store.unregister_prefix(path_component)

    def push_publish(self, path_component: str, spill_id: int, run: Any,
                     partition: Optional[int] = None, epoch: int = 0,
                     app_id: str = "", tenant: str = "",
                     counters: Any = None, replicas: int = 1,
                     window_id: int = 0, stream: str = "") -> None:
        """Eager-push landing zone (docs/push_shuffle.md).

        Admission-checked publish into the buffer store.  ``partition``
        None = a same-host push of the WHOLE run under the plain
        ``(path, spill)`` key (complete — every partition — so a consumer
        probe can never be served a partial view); an int = one remotely
        pushed partition under ``push_key(path, partition)`` holding a
        single-partition run.  ``replicas`` > 1 additionally lands a coded
        buddy copy under ``replica_key(...)`` — best-effort (quota refusal
        skips the copy, the primary stands) and charged to the primary's
        admission grant.  Raises PushRejected (admission said no —
        caller retries then falls back to pull) or EpochFencedError (a
        re-attempted mapper's stale push, rejected exactly like a stale
        register; a stale-WINDOW push raises the WindowFencedError
        subclass)."""
        _window_fence("shuffle.push", app_id, window_id, stream,
                      f"{path_component}/{spill_id}")
        if epoch > 0 and epoch_registry.is_stale(app_id, epoch):
            faults.fire("fence.stale_epoch",
                        detail=f"shuffle.push {path_component}")
            from tez_tpu.common import tracing
            tracing.event("fence.stale_epoch", seam="shuffle.push",
                          reason="stale_producer", msg_epoch=epoch,
                          src=f"{path_component}/{spill_id}")
            raise EpochFencedError(
                f"shuffle push from stale epoch {epoch} "
                f"(current {epoch_registry.current(app_id)}): "
                f"{path_component}/{spill_id}")
        if self._buffer is None or self._push_admission is None:
            raise PushRejected(
                0.0, "push has no landing zone on this host (no buffer "
                     "store / admission controller attached)")
        nbytes = int(getattr(run, "nbytes", 0))
        self._push_admission.admit(path_component, nbytes,
                                   counters=counters)
        key_path = path_component if partition is None else \
            push_key(path_component, partition)
        from tez_tpu.store.buffer_store import StoreQuotaExceeded
        try:
            self._buffer.publish(key_path, spill_id, run, epoch=epoch,
                                 app_id=app_id, tenant=tenant,
                                 counters=counters)
        except StoreQuotaExceeded as e:
            # surfaces like any admission refusal: the pusher backs off,
            # retries, then abandons to the pull backstop
            raise PushRejected(0.0, str(e)) from e
        buddy = -1
        if replicas > 1:
            # coded buddy copy (docs/recovery.md): placement follows the
            # PR-10 coded-exchange ring — the buddy store for a whole-run
            # push is the one owning coded_buddy(p, n) per partition; the
            # in-process simulation keys both copies into the host store
            # under distinct namespaces, same failover chain.  Best-effort
            # — a quota refusal keeps the primary, just without the
            # redundancy.
            n = int(getattr(run, "num_partitions", 0) or 0)
            if n > 1:
                from tez_tpu.parallel.mesh import coded_buddy
                buddy = coded_buddy(0 if partition is None else partition, n)
            try:
                self._buffer.publish(replica_key(key_path), spill_id, run,
                                     epoch=epoch, app_id=app_id,
                                     tenant=tenant, counters=counters,
                                     replica=True)
            except StoreQuotaExceeded:
                pass
        from tez_tpu.common import tracing
        tracing.event("shuffle.push", src=f"{path_component}/{spill_id}",
                      nbytes=nbytes,
                      partition=-1 if partition is None else partition,
                      replicas=replicas, buddy=buddy)
        for fn in list(self._push_listeners):
            try:
                fn(path_component, spill_id)
            except Exception:       # merge-wake is advisory, never fatal
                pass

    def unregister_prefix(self, prefix: str) -> int:
        """Deletion tracker: drop all outputs whose path starts with prefix
        (per-DAG / per-vertex cleanup).  Disk-backed runs (FileRun) also
        remove their backing file."""
        with self._lock:
            victims = [k for k in self._runs if k[0].startswith(prefix)]
            dead = [self._runs.pop(k) for k in victims]
        for run in dead:
            deleter = getattr(run, "delete", None)
            if deleter is not None:
                deleter()
        n = len(victims)
        if self._buffer is not None:
            # push keys (path#pN) share the path prefix, so pushed
            # partitions die with their DAG here too
            n += self._buffer.unregister_prefix(prefix)
        if self._push_admission is not None:
            self._push_admission.release_prefix(prefix)
        if self._store is not None:
            self._store.unregister_prefix(prefix)
        return n

    # -- consumer side (local short-circuit) ---------------------------------
    def _lookup(self, path_component: str, spill_id: int) -> Optional[Any]:
        """The run under a key: bare registry first, then the buffer
        store (unleased peek — slicing a returned run is safe because
        demotion never invalidates live views, see docs/store.md)."""
        with self._lock:
            run = self._runs.get((path_component, spill_id))
        if run is None and self._buffer is not None:
            run = self._buffer.get(path_component, spill_id)
        return run

    def _store_probe(self, key_path: str, spill_id: int, partition: int,
                     counters: Any) -> Optional[KVBatch]:
        """One buffer-store probe, miss -> None (StoreKeyNotFound and a
        concurrently-deleted backing file both count as a miss — the next
        link in the fetch chain decides what a total miss means)."""
        try:
            return self._buffer.fetch_partition(
                key_path, spill_id, partition, counters=counters)
        except FileNotFoundError:
            return None
        except Exception as e:
            if type(e).__name__ != "StoreKeyNotFound":
                raise
            return None

    def _replica_probe(self, key_path: str, spill_id: int, partition: int,
                       counters: Any) -> Optional[KVBatch]:
        """Failover to the coded buddy copy of a lost primary entry.  A
        hit is accounted as a store.replica.failover — a producer re-run
        avoided (docs/recovery.md)."""
        batch = self._store_probe(replica_key(key_path), spill_id,
                                  partition, counters)
        if batch is not None:
            self._buffer.note_replica_failover(
                f"{key_path}/{spill_id}", counters=counters)
        return batch

    def fetch_partition(self, path_component: str, spill_id: int,
                        partition: int, counters: Any = None,
                        app_id: str = "", window_id: int = 0,
                        stream: str = "") -> KVBatch:
        # consumer-side window fence: a reducer attempt from a sealed
        # streaming window must not keep pulling data the open window's
        # re-run now owns (stamped fetches only; batch is unfenced)
        _window_fence("shuffle.fetch", app_id, window_id, stream,
                      f"{path_component}/{spill_id}")
        # store.replica.lost seam (consumer side): fail mode declares the
        # PRIMARY copies gone — store entries and the producer's local
        # registration both — forcing the coded-replica failover path, the
        # chaos lever proving reconstruction without producer re-execution
        primary_lost = False
        try:
            faults.fire("store.replica.lost",
                        detail=f"{path_component}/{spill_id}")
        except Exception:
            primary_lost = True
        if self._buffer is not None:
            batch = None
            if not primary_lost:
                batch = self._store_probe(path_component, spill_id,
                                          partition, counters)
            if batch is None:
                batch = self._replica_probe(path_component, spill_id,
                                            partition, counters)
            if batch is not None:
                if faults.armed():
                    batch = _maybe_corrupt(path_component, spill_id, batch)
                return batch
        run = None
        if not primary_lost:
            with self._lock:
                run = self._runs.get((path_component, spill_id))
        if run is None:
            # third probe: a remotely PUSHED partition — the producer has
            # no local registration here, but its pusher may have landed
            # this partition under push_key (a single-partition run, so
            # partition index 0 inside the stored run); its coded replica
            # is the last resort
            if self._buffer is not None:
                pk = push_key(path_component, partition)
                batch = None
                if not primary_lost:
                    batch = self._store_probe(pk, spill_id, 0, counters)
                if batch is None:
                    batch = self._replica_probe(pk, spill_id, 0, counters)
                if batch is not None:
                    if faults.armed():
                        batch = _maybe_corrupt(path_component, spill_id,
                                               batch)
                    return batch
            raise ShuffleDataNotFound(f"{path_component}/{spill_id}")
        try:
            batch = run.partition(partition)
        except FileNotFoundError:
            # disk-backed run deleted by a concurrent unregister_prefix
            # (DAG teardown) between the registry lookup and the read —
            # same contract as a missing registration
            raise ShuffleDataNotFound(
                f"{path_component}/{spill_id}") from None
        if faults.armed():
            batch = _maybe_corrupt(path_component, spill_id, batch)
        return batch

    def fetch_partition_range(self, path_component: str, spill_id: int,
                              start: int, stop: int) -> List[KVBatch]:
        run = self._lookup(path_component, spill_id)
        if run is None:
            raise ShuffleDataNotFound(f"{path_component}/{spill_id}")
        try:
            return [run.partition(p) for p in range(start, stop)]
        except FileNotFoundError:
            raise ShuffleDataNotFound(
                f"{path_component}/{spill_id}") from None

    def local_file_source(self, path_component: str, spill_id: int,
                          partition: int) -> Optional[Tuple[str, int]]:
        """Disk-direct short-circuit (LocalDiskFetchedInput analog): when
        the registered run is disk-backed (FileRun), return its (path,
        partition_nbytes) so a same-host consumer can merge straight off
        the producer's partition-indexed file — no materialization, no
        re-spill.  None when the run is RAM-resident or unknown."""
        run = self._lookup(path_component, spill_id)
        if run is None or not hasattr(run, "iter_partition_blocks"):
            return None
        return run.path, run.partition_nbytes(partition)

    def partition_size(self, path_component: str, spill_id: int,
                       partition: int) -> int:
        run = self._lookup(path_component, spill_id)
        if run is None:
            raise ShuffleDataNotFound(f"{path_component}/{spill_id}")
        try:
            return run.partition_nbytes(partition)
        except FileNotFoundError:
            raise ShuffleDataNotFound(
                f"{path_component}/{spill_id}") from None

    def stats(self) -> Tuple[int, int]:
        with self._lock:
            n = len(self._runs)
            nbytes = sum(r.nbytes for r in self._runs.values())
        if self._buffer is not None:
            s = self._buffer.stats()
            n += s["entries"]
            nbytes += sum(s["bytes"].values())
        return n, nbytes


_local = ShuffleService()


def local_shuffle_service() -> ShuffleService:
    """The per-process service (one per host; shared by AM and runners in
    local mode, exactly like the NM-singleton ShuffleHandler)."""
    return _local


def telemetry_collector() -> Dict[str, float]:
    """Live-telemetry hook (obs/timeseries registry): registered-run
    inventory as gauges on every sampler tick — the transport plane's
    resident footprint, next to the store collector's tier bytes."""
    n, nbytes = _local.stats()
    return {"shuffle.registered_runs": float(n),
            "shuffle.registered_bytes": float(nbytes)}
