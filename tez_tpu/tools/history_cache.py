"""Cached read path over history logs — the timeline-cache plugin analog.

Reference role: tez-yarn-timeline-cache-plugin (ATS v1.5 entity-group
cache): the timeline reader groups every entity belonging to one DAG into a
per-DAG group so repeated reads of a finished DAG hit a cache instead of
re-scanning the store.  Here the store is a directory of JSONL history
files (JsonlHistoryLoggingService output); the cache tracks each file's
(mtime, size) fingerprint, re-parses only changed files, keeps per-DAG
DagInfo objects (the "entity group"), and LRU-evicts beyond a cap.

Used by the analyzer CLI (`tez-analyzer --cache-dir`) and embeddable in a
long-lived history-serving process the way the plugin serves the Tez UI.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from tez_tpu.tools.history_parser import DagInfo, parse_jsonl_files


class DagInfoCache:
    """Entity-group cache over a history log directory."""

    def __init__(self, log_dir: str, max_dags: int = 64):
        self.log_dir = log_dir
        self.max_dags = max_dags
        self._lock = threading.Lock()
        self._fingerprints: Dict[str, Tuple[float, int]] = {}
        # dag_id -> (DagInfo, source files); OrderedDict = LRU order
        self._dags: "OrderedDict[str, DagInfo]" = OrderedDict()
        self._dag_files: Dict[str, frozenset] = {}
        # negative cache: dag_id -> store generation at which the bypass
        # parse proved it absent (repeated lookups of a bogus id must not
        # re-read the whole directory every call)
        self._absent: Dict[str, int] = {}
        self._generation = 0
        self.hits = 0
        self.misses = 0

    # -- store scanning -----------------------------------------------------
    def _scan(self) -> List[str]:
        """Manifest scan over the date-partitioned store (flat legacy
        files included)."""
        from tez_tpu.am.history import scan_history_store
        return scan_history_store(self.log_dir)

    def _changed_files(self) -> List[str]:
        """Changed paths with their NEW fingerprints — which are committed
        only after a successful parse (refresh rolls back on error), so a
        partially-flushed JSONL line from a live AM is retried next call."""
        changed = []
        for path in self._scan():
            try:
                st = os.stat(path)
            except OSError:
                continue
            fp = (st.st_mtime, st.st_size)
            if self._fingerprints.get(path) != fp:
                changed.append((path, fp))
        return changed

    def refresh(self) -> int:
        """Re-parse changed files; returns how many files were re-read.
        A DAG whose events span several files is rebuilt from ALL its known
        source files so partial re-parses cannot truncate it."""
        with self._lock:
            changed = self._changed_files()
            if not changed:
                return 0
            self._generation += 1
            self._absent.clear()
            # re-parse the union of changed files and any file sets of DAGs
            # they touch (cheap: JSONL parse is line-local)
            to_read = set(p for p, _ in changed)
            parsed = parse_jsonl_files(sorted(to_read))
            for dag_id, info in parsed.items():
                known = self._dag_files.get(dag_id, frozenset())
                files = frozenset(to_read) | known
                if known - to_read:
                    # events for this DAG live in unchanged files too —
                    # rebuild from the full set for a complete DagInfo
                    info = parse_jsonl_files(sorted(files)).get(dag_id, info)
                self._dag_files[dag_id] = files
                self._dags[dag_id] = info
                self._dags.move_to_end(dag_id)
            while len(self._dags) > self.max_dags:
                old_id, _ = self._dags.popitem(last=False)
                self._dag_files.pop(old_id, None)
            # commit fingerprints only after every parse returned.  Note the
            # parser tolerates torn lines (it skips unparseable lines rather
            # than raising), so the usual retry path for a half-flushed file
            # is the file's size changing when the AM finishes the line; the
            # deferred commit additionally guarantees that an unexpected
            # parse exception (I/O error, bug) leaves the old fingerprints
            # in place so the next refresh() retries the same files.
            for path, fp in changed:
                self._fingerprints[path] = fp
            return len(changed)

    # -- read API -----------------------------------------------------------
    def get(self, dag_id: str) -> Optional[DagInfo]:
        self.refresh()
        with self._lock:
            info = self._dags.get(dag_id)
            if info is not None:
                self.hits += 1
                self._dags.move_to_end(dag_id)
                return info
            self.misses += 1
            if self._absent.get(dag_id) == self._generation:
                return None   # already proven absent at this store state
        # miss for a possibly LRU-evicted DAG: the files are unchanged so
        # refresh() won't re-read them — do a full bypass parse and
        # re-admit the entry if it exists on disk
        parsed = parse_jsonl_files(self._scan())
        info = parsed.get(dag_id)
        if info is None:
            with self._lock:
                if len(self._absent) >= 4 * self.max_dags:
                    self._absent.pop(next(iter(self._absent)))
                self._absent[dag_id] = self._generation
        if info is not None:
            with self._lock:
                self._dags[dag_id] = info
                self._dag_files[dag_id] = frozenset(self._scan())
                self._dags.move_to_end(dag_id)
                while len(self._dags) > self.max_dags:
                    old_id, _ = self._dags.popitem(last=False)
                    self._dag_files.pop(old_id, None)
        return info

    def dag_ids(self) -> List[str]:
        self.refresh()
        with self._lock:
            return list(self._dags)

    def all(self) -> Dict[str, DagInfo]:
        self.refresh()
        with self._lock:
            return dict(self._dags)
