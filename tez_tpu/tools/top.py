"""graft top: a refreshing terminal view of one live AM.

Scrapes ``GET /doctor/live`` (and nothing else — one request per frame)
off the AM web UI and renders the continuous doctor in place: per-plane
blame bars over the sliding window, admission queue depth, per-tenant
running/queued counts, per-stream commit/lag/latency, mesh lane
occupancy, and any active SLO breach or burn alert.  Curses-free on
purpose: plain ANSI (cursor-home + erase-down), so it works in any
terminal, under ``script``, and inside CI logs.

CLI (also ``make top URL=http://127.0.0.1:PORT``):
  python -m tez_tpu.tools.top URL [--window S] [--interval S] [--once]

``--once`` prints a single frame without ANSI control codes and exits —
what the metrics-smoke test and docs examples use.  The AM must run
with ``tez.am.web.enabled=true`` (the soak harness does); the URL is
printed in the AM log line ``AM web UI at ...``.

Rendering is a pure function of the ``/doctor/live`` JSON payload
(:func:`render`), so tests feed it canned payloads without a socket.
See docs/telemetry.md.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

#: ANSI: home the cursor and erase to end of screen — repaint without
#: scrollback spam (unlike a full 2J clear, no flicker on slow TTYs)
_REPAINT = "\x1b[H\x1b[J"

_BAR_W = 24


def _bar(frac: float, width: int = _BAR_W) -> str:
    frac = max(0.0, min(1.0, frac))
    n = int(round(frac * width))
    return "█" * n + "░" * (width - n)


def fetch(base_url: str, window_s: Optional[float] = None,
          timeout: float = 5.0) -> Dict[str, Any]:
    """One ``/doctor/live`` scrape; raises URLError/ValueError on junk."""
    url = base_url.rstrip("/") + "/doctor/live"
    if window_s:
        url += f"?window={window_s:g}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def render(status: Dict[str, Any], width: int = 78) -> str:
    """The frame, as plain text — pure function of the live payload."""
    L: List[str] = []
    win = status.get("window_s", 0)
    samp = status.get("sampler", {})
    L.append(f"== graft top ==  window {win:g}s  "
             f"sampler {'on' if samp.get('enabled') else 'OFF'} "
             f"({samp.get('ticks', 0)} ticks, "
             f"period {samp.get('period_s', 0):g}s)")

    planes = status.get("planes", {})
    busy = planes.get("busy_ms", {}) or {}
    total = sum(busy.values())
    L.append("")
    L.append(f"plane blame (instrumented busy over the window"
             + (f", dominant: {planes['dominant']}"
                if planes.get("dominant") else "") + "):")
    if total > 0:
        for p, ms in sorted(busy.items(), key=lambda kv: -kv[1]):
            if ms <= 0:
                continue
            L.append(f"  {p:<10} {_bar(ms / total)} "
                     f"{100.0 * ms / total:6.2f}%  {ms:9.1f} ms")
    else:
        L.append("  (no instrumented activity in the window)")

    qd = status.get("queue_depth")
    if qd is not None:
        L.append("")
        L.append(f"admission: queue depth {qd}, "
                 f"{status.get('running_dags', 0)} running DAG(s)")
    tenants = status.get("tenants") or {}
    if tenants:
        L.append("tenants:")
        for name, t in sorted(tenants.items()):
            if isinstance(t, dict):
                detail = "  ".join(f"{k}={v}" for k, v in sorted(t.items())
                                   if isinstance(v, (int, float, str)))
            else:
                detail = str(t)
            L.append(f"  {name:<12} {detail}"[:width])

    streams = status.get("streams") or {}
    if streams:
        L.append("")
        L.append("streams:")
        for name, st in sorted(streams.items()):
            parts = [f"{name:<12}"]
            for k in ("state", "committed", "replayed", "lag"):
                if k in st:
                    parts.append(f"{k}={st[k]}")
            wl = st.get("window_latency")
            if wl and wl.get("count"):
                parts.append(f"p95={wl.get('p95_ms', 0):.0f}ms")
                parts.append(f"rate={wl.get('rate_per_s', 0):.2f}/s")
            L.append(("  " + " ".join(str(p) for p in parts))[:width])

    lanes = status.get("lanes") or {}
    if lanes:
        L.append("")
        L.append("mesh lanes (occupancy):")
        for lane, occ in sorted(lanes.items(), key=lambda kv: kv[0]):
            L.append(f"  lane {lane:>3} {_bar(float(occ))} "
                     f"{100.0 * float(occ):6.2f}%")

    slo = status.get("slo") or {}
    breaches = slo.get("breaches") or []
    burns = slo.get("burn") or []
    if breaches or burns:
        L.append("")
        for b in burns:
            where = (f"stream={b['stream']}" if b.get("stream")
                     else f"tenant={b.get('tenant', '?')}")
            L.append(f"  BURN   {where} {b.get('kind', '?')} "
                     f"observed={b.get('observed', '?')} "
                     f"target={b.get('target', '?')}")
        for b in breaches:
            where = (f"stream={b['stream']}" if b.get("stream")
                     else f"tenant={b.get('tenant', '?')}")
            L.append(f"  BREACH {where} {b.get('kind', '?')} "
                     f"observed={b.get('observed', '?')} "
                     f"target={b.get('target', '?')}")
    else:
        L.append("")
        L.append("slo: clean (no active breach or burn alert)")

    acct = status.get("accounting") or {}
    flagged = {k: v for k, v in acct.items()
               if k in ("evicted", "collector_errors", "scrape_errors")
               and v}
    L.append("")
    L.append("rings: " + (", ".join(f"{k}={v}"
                                    for k, v in sorted(flagged.items()))
                          if flagged else "healthy")
             + f"  ({acct.get('series', 0)} series)")
    return "\n".join(L)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Live terminal view of one AM's /doctor/live "
                    "(see docs/telemetry.md)")
    ap.add_argument("url", help="AM web UI base URL, e.g. "
                                "http://127.0.0.1:8080")
    ap.add_argument("--window", type=float, default=0.0,
                    help="aggregation window seconds "
                         "(default: the AM's tez.am.metrics.window-s)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame without ANSI codes and exit")
    args = ap.parse_args(argv)

    while True:
        try:
            status = fetch(args.url, args.window or None)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"graft top: cannot scrape {args.url}: {e}",
                  file=sys.stderr)
            return 1
        frame = render(status)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write(_REPAINT + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
