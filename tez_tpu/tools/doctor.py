"""graft doctor: cross-plane causal triage for one DAG.

Joins the three observability planes this repo grew in PRs 3-15 —
history journals (wall-clock truth for DAG/vertex/attempt lifecycles and
admission verdicts), flight-recorder dumps (typed cross-plane events +
every histogram observation, on the shared monotonic clock of
``common/clock.py``), and optionally an exported span buffer — into one
per-DAG timeline, then answers the question a pager wants answered:
*which plane ate the wall clock?*

Attribution is a **plane-priority timeline sweep**, not a sum of
per-plane busy time: the DAG's submit→finish window is cut at every
interval boundary, and each elementary segment is blamed on the
highest-priority plane active in it (admission > exchange > device >
store > transport > compute; anything uncovered is ``control``).
Because the segments partition the window, per-plane percentages sum to
exactly 100% of the DAG wall clock — overlap-heavy pipelines (the whole
point of the async planes) never double-count.

Report sections:

- **waterfall** — time-ordered merged segments with bars, the wall-clock
  shape of the run;
- **plane blame** — per-plane % + seconds;
- **split** — queue-wait vs compute vs transport, the three-way summary
  the SLO watchdogs alarm on;
- **stragglers** — top-3 attempts by slowdown vs their vertex median
  (an injected ``device.dispatch.delay`` surfaces here by name);
- **slo breaches** — TENANT_SLO_BREACH journal events joined with the
  flight ring's ``slo.breach.*`` records;
- **slo burn alerts** — SLO_BURN_ALERT pre-breach pages joined to the
  breach (if any) that followed them per (tenant, kind, stream), with
  page-to-breach lead time.

The same blame sweep also runs *live*: ``GET /doctor/live`` on the AM
web UI (am/telemetry.py) applies the plane mapping to the in-memory
time-series rings instead of post-hoc artifacts, and ``graft top``
(tools/top.py, ``make top``) renders it as a refreshing terminal view.

CLI (also ``make doctor``):
  python -m tez_tpu.tools.doctor WORKDIR [--dag ID] [--json]
                                 [--perfetto out.json]

WORKDIR is scanned recursively for ``*.jsonl`` journals and
``flight_*.json`` dumps (exactly what ``chaos.py --dump-flight`` leaves
behind).  See docs/doctor.md.
"""
from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# Planes in blame-priority order and the histogram-prefix -> plane
# mapping now live in obs/timeseries.py, shared with the LIVE sweep
# (am/telemetry.py live_status) so the two can never drift; re-exported
# here because this module is the mapping's historical home and other
# tools import it from here.  "recovery" outranks everything: an
# AM-incarnation bump inside the blamed window means the session itself
# died and replayed — no amount of store or compute activity explains
# that wall clock better.
from tez_tpu.obs.timeseries import (PLANES, PREFIX_PLANE,  # noqa: F401
                                    plane_for_name)

#: span cat -> plane, for flight SPAN edges (cat rides in the scope slot)
SPAN_CAT_PLANE = {"fetch": "transport", "shuffle": "transport",
                  "task": "compute", "attempt": "compute",
                  "vertex": "compute", "commit": "store",
                  "admission": "admission"}


# --------------------------------------------------------------------------
# Artifact discovery
# --------------------------------------------------------------------------

def find_artifacts(paths: List[str]) -> Tuple[List[str], List[str]]:
    """(journal files, flight dumps) under the given files/directories."""
    journals: List[str] = []
    dumps: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            journals.extend(sorted(globlib.glob(
                os.path.join(p, "**", "*.jsonl"), recursive=True)))
            dumps.extend(sorted(globlib.glob(
                os.path.join(p, "**", "flight_*.json"), recursive=True)))
        elif os.path.basename(p).startswith("flight_"):
            dumps.append(p)
        else:
            journals.append(p)
    return journals, dumps


def load_flight_dumps(paths: List[str]) -> List[Any]:
    from tez_tpu.obs import flight
    snaps = []
    for p in paths:
        try:
            snaps.append(flight.load_dump(p))
        except (OSError, ValueError, KeyError) as e:
            print(f"doctor: skipping unreadable dump {p}: {e}",
                  file=sys.stderr)
    return snaps


def load_slo_breaches(journal_files: List[str]) -> List[Dict[str, Any]]:
    """TENANT_SLO_BREACH events straight off the journal lines (DagInfo
    aggregation drops session-scoped events we want verbatim)."""
    from tez_tpu.am.recovery import decode_journal_line
    out: List[Dict[str, Any]] = []
    for path in journal_files:
        try:
            with open(path, errors="replace") as fh:
                lines = fh.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = decode_journal_line(line)
            except Exception:  # noqa: BLE001 — torn tail lines etc.
                continue
            if ev.event_type.name == "TENANT_SLO_BREACH":
                out.append(dict(ev.data, time=ev.timestamp))
    return out


def load_slo_burn_alerts(journal_files: List[str]) -> List[Dict[str, Any]]:
    """SLO_BURN_ALERT events off the journal lines — the watchdog's
    pre-breach pages (obs/slo.py evaluate_burn).  Same tenant/kind/stream
    labels as TENANT_SLO_BREACH, so :func:`join_burn_alerts` can match
    each page to the breach (if any) that followed it per stream."""
    from tez_tpu.am.recovery import decode_journal_line
    out: List[Dict[str, Any]] = []
    for path in journal_files:
        try:
            with open(path, errors="replace") as fh:
                lines = fh.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = decode_journal_line(line)
            except Exception:  # noqa: BLE001 — torn tail lines etc.
                continue
            if ev.event_type.name == "SLO_BURN_ALERT":
                out.append(dict(ev.data, time=ev.timestamp))
    return out


def join_burn_alerts(alerts: List[Dict[str, Any]],
                     breaches: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Annotate each burn alert with whether a matching breach followed.

    Alerts and breaches join on the (tenant, kind, stream) label triple.
    An alert that a later breach confirms gains ``breached=True`` and
    ``lead_s`` (page-to-breach lead time — how early the burn evaluator
    fired); an alert with no subsequent matching breach keeps
    ``breached=False`` (the page was early enough that the condition
    cleared, which is the whole point)."""
    out: List[Dict[str, Any]] = []
    for a in alerts:
        key = (a.get("tenant"), a.get("kind"), a.get("stream") or "")
        joined = dict(a, breached=False, lead_s=None)
        for b in breaches:
            if (b.get("tenant"), b.get("kind"),
                    b.get("stream") or "") != key:
                continue
            bt = b.get("time") or 0.0
            at = a.get("time") or 0.0
            if bt >= at:
                joined["breached"] = True
                joined["lead_s"] = round(bt - at, 3)
                break
        out.append(joined)
    return out


def load_am_restarts(journal_files: List[str]) -> List[Dict[str, Any]]:
    """AM incarnation bumps: every ``AM_STARTED`` with ``attempt > 1`` is
    a restart.  The recovery window runs from that record until the first
    DAG-scoped event the new incarnation journals (replay done, real work
    resumed); if nothing follows, to the stream's last record.  Entries:
    ``{"time", "end", "attempt"}``."""
    from tez_tpu.am.recovery import decode_journal_line
    out: List[Dict[str, Any]] = []
    for path in journal_files:
        try:
            with open(path, errors="replace") as fh:
                lines = fh.readlines()
        except OSError:
            continue
        pending: Optional[Dict[str, Any]] = None
        last_t = 0.0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = decode_journal_line(line)
            except Exception:  # noqa: BLE001 — torn tail lines etc.
                continue
            last_t = max(last_t, ev.timestamp or 0.0)
            if ev.event_type.name == "AM_STARTED":
                if int(ev.data.get("attempt", 1) or 1) > 1:
                    pending = {"time": ev.timestamp,
                               "end": ev.timestamp,
                               "attempt": int(ev.data["attempt"])}
                    out.append(pending)
                continue
            if pending is not None and ev.dag_id is not None:
                pending["end"] = max(pending["time"], ev.timestamp)
                pending = None
        if pending is not None:
            pending["end"] = max(pending["time"], last_t)
    return out


# --------------------------------------------------------------------------
# Interval extraction
# --------------------------------------------------------------------------

def intervals_from_history(dag: Any) -> List[Tuple[float, float, str, str]]:
    """(start, end, plane, label) intervals from the DagInfo."""
    out: List[Tuple[float, float, str, str]] = []
    if dag.submit_time and dag.start_time > dag.submit_time:
        out.append((dag.submit_time, dag.start_time, "admission",
                    "admission:queue-wait"))
    for a in dag.all_attempts():
        if a.start_time and a.finish_time > a.start_time:
            out.append((a.start_time, a.finish_time, "compute",
                        f"attempt:{a.attempt_id}"))
    return out


def intervals_from_flight(snaps: List[Any]
                          ) -> List[Tuple[float, float, str, str]]:
    """(start, end, plane, label) intervals from flight snapshots: every
    COUNTER observation becomes a busy interval ending at its record
    time; SPAN edges map through their cat."""
    from tez_tpu.common import clock
    from tez_tpu.obs import flight as fl
    out: List[Tuple[float, float, str, str]] = []
    for snap in snaps:
        anchor = snap.anchor
        for e in snap.events:
            if e.kind == fl.COUNTER:
                plane = plane_for_name(e.name)
                if plane is None or e.a <= 0:
                    continue
                end = clock.mono_to_wall(e.t_ns, anchor)
                out.append((end - e.a / 1e6, end, plane, e.name))
            elif e.kind == fl.SPAN:
                plane = SPAN_CAT_PLANE.get(e.scope)
                if plane is None or e.b <= 0:
                    continue
                start = clock.mono_to_wall(e.a, anchor)
                out.append((start, start + e.b / 1e9, plane, e.name))
    return out


# --------------------------------------------------------------------------
# The sweep
# --------------------------------------------------------------------------

def blame_sweep(t0: float, t1: float,
                intervals: List[Tuple[float, float, str, str]]
                ) -> List[Tuple[float, float, str]]:
    """Partition [t0, t1] into (start, end, plane) segments, each blamed
    on the highest-priority plane active in it; uncovered time is
    ``control``.  Segments partition the window exactly, so per-plane
    sums always add up to the full wall clock."""
    rank = {p: i for i, p in enumerate(PLANES)}
    clipped = []
    for s, e, plane, _label in intervals:
        s, e = max(s, t0), min(e, t1)
        if e > s:
            clipped.append((s, e, plane))
    cuts = sorted({t0, t1, *(s for s, _, _ in clipped),
                   *(e for _, e, _ in clipped)})
    segments: List[Tuple[float, float, str]] = []
    for lo, hi in zip(cuts, cuts[1:]):
        mid = (lo + hi) / 2.0
        active = [p for s, e, p in clipped if s <= mid < e]
        plane = min(active, key=lambda p: rank[p]) if active else "control"
        if segments and segments[-1][2] == plane:
            segments[-1] = (segments[-1][0], hi, plane)
        else:
            segments.append((lo, hi, plane))
    return segments


def vertex_fleet_medians(dags: Dict[str, Any]) -> Dict[str, float]:
    """Median attempt duration per vertex NAME across every parsed DAG.
    Recurring DAGs (the multi-tenant session shape) share vertex names,
    so this is the cross-run baseline a single-task vertex lacks."""
    by_name: Dict[str, List[float]] = {}
    for dag in dags.values():
        for v in dag.vertices.values():
            for t in v.tasks.values():
                for a in t.attempts.values():
                    if a.duration > 0:
                        by_name.setdefault(v.name, []).append(a.duration)
    return {n: sorted(ds)[len(ds) // 2] for n, ds in by_name.items()}


def straggler_attempts(dag: Any, top: int = 3,
                       fleet: Optional[Dict[str, float]] = None
                       ) -> List[Dict[str, Any]]:
    """Top attempts by slowdown vs their vertex's median duration.  A
    vertex with fewer than 3 timed attempts has no in-DAG median worth
    trusting (with 1-2 attempts the slow one IS the median), so the
    fleet-wide per-vertex median stands in when available."""
    rows: List[Dict[str, Any]] = []
    for v in dag.vertices.values():
        durs = sorted(a.duration for t in v.tasks.values()
                      for a in t.attempts.values() if a.duration > 0)
        if not durs:
            continue
        median = durs[len(durs) // 2]
        if len(durs) < 3 and fleet and fleet.get(v.name):
            median = fleet[v.name]
        for t in v.tasks.values():
            for a in t.attempts.values():
                if a.duration <= 0:
                    continue
                rows.append({
                    "attempt_id": a.attempt_id, "vertex": v.name,
                    "duration_s": round(a.duration, 4),
                    "vertex_median_s": round(median, 4),
                    "slowdown": round(a.duration / max(median, 1e-9), 2),
                })
    rows.sort(key=lambda r: (-r["slowdown"], -r["duration_s"]))
    return rows[:top]


# --------------------------------------------------------------------------
# Streaming triage
# --------------------------------------------------------------------------

def diagnose_streams(dags: Dict[str, Any],
                     snaps: Optional[List[Any]] = None
                     ) -> List[Dict[str, Any]]:
    """Per-stream triage rows from the session-scoped ``stream_events``
    the history parser attaches to every DagInfo: window commit /
    replay / abort / lag counts, cut→commit latency p50/p95, and — for
    the slowest committed window — a dominant-plane attribution computed
    by running the blame sweep over that window-DAG's own submit→commit
    wall.  That last bit is the streaming pager question: *this window
    blew its SLO — which plane ate it?*"""
    events: List[Dict[str, Any]] = []
    for d in dags.values():
        events = getattr(d, "stream_events", []) or []
        break                       # session-scoped: same list on every DAG
    if not events:
        return []
    flight_iv = intervals_from_flight(snaps or [])
    by_stream: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        by_stream.setdefault(str(ev.get("stream") or "?"), []).append(ev)
    rows: List[Dict[str, Any]] = []
    for name, evs in sorted(by_stream.items()):
        committed = [e for e in evs if e["event"] == "COMMIT_FINISHED"]
        row: Dict[str, Any] = {
            "stream": name,
            "committed": len(committed),
            "replayed": sum(1 for e in committed if e.get("replayed")),
            "aborted": sum(1 for e in evs
                           if e["event"] == "COMMIT_ABORTED"),
            "lag_episodes": sum(1 for e in evs
                                if e["event"] == "LAGGING"),
            "retired": any(e["event"] == "RETIRED" for e in evs),
        }
        # exact window latency: COMMIT_FINISHED wall time minus the
        # window DAG's own submit time (both on the journal clock)
        timed: List[Tuple[float, Any, Any, float, float]] = []
        for ev in committed:
            d = dags.get(str(ev.get("dag_id") or ""))
            t1 = float(ev.get("time") or 0.0)
            t0 = float(getattr(d, "submit_time", 0.0) or 0.0)
            if d is not None and t1 > t0 > 0:
                timed.append((t1 - t0, ev.get("window_id"), d, t0, t1))
        timed.sort(key=lambda r: r[0])
        if timed:
            n = len(timed)
            row["p50_ms"] = round(timed[int(0.5 * (n - 1))][0] * 1000, 1)
            row["p95_ms"] = round(timed[int(0.95 * (n - 1))][0] * 1000, 1)
            wall, w, d, t0, t1 = timed[-1]
            segments = blame_sweep(
                t0, t1, intervals_from_history(d) + flight_iv)
            plane_s = {p: 0.0 for p in PLANES}
            for s, e, p in segments:
                plane_s[p] += e - s
            dom, sec = max(
                ((p, s) for p, s in plane_s.items() if p != "control"),
                key=lambda ps: ps[1], default=("control", 0.0))
            if sec <= 0:
                dom, sec = "control", plane_s["control"]
            row["slowest"] = {
                "window_id": w, "wall_s": round(wall, 4),
                "dominant_plane": dom,
                "plane_pct": round(100.0 * sec / max(wall, 1e-9), 2),
            }
        rows.append(row)
    return rows


def render_streams(rows: List[Dict[str, Any]]) -> str:
    L: List[str] = ["", "streaming:"]
    for r in rows:
        state = "retired" if r["retired"] else "live"
        lat = (f"  p50/p95 {r['p50_ms']:.0f}/{r['p95_ms']:.0f} ms"
               if "p50_ms" in r else "")
        L.append(f"  {r['stream']} ({state}): {r['committed']} committed, "
                 f"{r['replayed']} replayed, {r['aborted']} aborted, "
                 f"{r['lag_episodes']} lag episode(s){lat}")
        slow = r.get("slowest")
        if slow:
            L.append(f"    slowest window w{slow['window_id']}: "
                     f"{slow['wall_s']:.3f} s — {slow['dominant_plane']} "
                     f"dominates ({slow['plane_pct']}% of the window)")
    return "\n".join(L)


# --------------------------------------------------------------------------
# Report
# --------------------------------------------------------------------------

def diagnose(dag: Any, snaps: List[Any],
             slo_breaches: List[Dict[str, Any]],
             fleet: Optional[Dict[str, float]] = None,
             am_restarts: Optional[List[Dict[str, Any]]] = None,
             burn_alerts: Optional[List[Dict[str, Any]]] = None
             ) -> Dict[str, Any]:
    t0 = dag.submit_time or dag.start_time
    t1 = dag.finish_time
    intervals = intervals_from_history(dag) + intervals_from_flight(snaps)
    for r in (am_restarts or []):
        if r["end"] > r["time"]:
            intervals.append((r["time"], r["end"], "recovery",
                              f"am-restart:attempt={r['attempt']}"))
    if not t1:
        t1 = max((e for _, e, _, _ in intervals), default=t0)
    wall = max(0.0, t1 - t0)
    if wall <= 0:
        return {"dag_id": dag.dag_id, "error": "no wall-clock window "
                "(missing submit/finish times)"}
    segments = blame_sweep(t0, t1, intervals)
    plane_s = {p: 0.0 for p in PLANES}
    for s, e, p in segments:
        plane_s[p] += e - s
    planes = {p: {"seconds": round(sec, 4),
                  "pct": round(100.0 * sec / wall, 2)}
              for p, sec in plane_s.items()}
    # three-way summary: queue-wait vs compute vs transport.  "transport"
    # pools everything that moves or parks bytes between compute steps.
    q = plane_s["admission"]
    comp = plane_s["compute"] + plane_s["device"]
    trans = plane_s["exchange"] + plane_s["store"] + plane_s["transport"]
    three = max(q + comp + trans, 1e-9)
    stragglers = straggler_attempts(dag, fleet=fleet)
    blamed = max(((p, s) for p, s in plane_s.items() if p != "control"),
                 key=lambda ps: ps[1], default=("control", 0.0))
    verdict = (f"{blamed[0]} dominates instrumented time "
               f"({planes[blamed[0]]['pct']}% of wall)")
    if stragglers and stragglers[0]["slowdown"] >= 2.0:
        verdict += (f"; straggler {stragglers[0]['attempt_id']} ran "
                    f"{stragglers[0]['slowdown']}x its vertex median")
    in_window = [r for r in (am_restarts or [])
                 if t0 <= r["time"] <= t1]
    if in_window:
        verdict += (f"; AM restarted inside the window (attempt "
                    f"{in_window[-1]['attempt']}) — recovery replay, "
                    f"not a data-plane stall")
    # query plane (tez_tpu/query/): SUBMITTED entries whose dag_id names
    # THIS dag, plus the REPLANNED decisions for those queries (replans
    # are journaled just before the re-optimized run is submitted)
    q_events = getattr(dag, "query_events", None) or []
    q_submitted = [e for e in q_events
                   if e.get("event") == "SUBMITTED"
                   and e.get("dag_id") == dag.dag_id]
    q_names = {e["query"] for e in q_submitted}
    q_replans = [e for e in q_events
                 if e.get("event") == "REPLANNED"
                 and e.get("query") in q_names]
    if q_replans:
        r = q_replans[-1]
        verdict += (f"; query '{r['query']}' was re-optimized before this "
                    f"run ({r['kind']}: {r['from']} -> {r['to']})")
    if slo_breaches:
        verdict += f"; {len(slo_breaches)} SLO breach(es) on record"
    joined_alerts = join_burn_alerts(burn_alerts or [], slo_breaches)
    if joined_alerts:
        paged = [a for a in joined_alerts if a["breached"]]
        if paged:
            lead = min(a["lead_s"] for a in paged
                       if a["lead_s"] is not None)
            verdict += (f"; burn alert paged {lead:.1f}s before the "
                        f"first matching breach")
        else:
            verdict += (f"; {len(joined_alerts)} burn alert(s) cleared "
                        f"without breaching")
    return {
        "dag_id": dag.dag_id, "name": dag.name, "tenant": dag.tenant,
        "state": dag.state, "wall_s": round(wall, 4),
        "window": [t0, t1],
        "planes": planes,
        "pct_total": round(sum(v["pct"] for v in planes.values()), 2),
        "split": {
            "queue_wait_pct": round(100.0 * q / three, 2),
            "compute_pct": round(100.0 * comp / three, 2),
            "transport_pct": round(100.0 * trans / three, 2),
        },
        "waterfall": [{"offset_s": round(s - t0, 4),
                       "dur_s": round(e - s, 4), "plane": p}
                      for s, e, p in segments],
        "stragglers": stragglers,
        "slo_breaches": slo_breaches,
        "slo_burn_alerts": joined_alerts,
        "am_restarts": in_window,
        "query": {"submitted": q_submitted, "replans": q_replans},
        "verdict": verdict,
        "sources": {
            "flight_dumps": len(snaps),
            "flight_events": sum(len(s.events) for s in snaps),
            "intervals": len(intervals),
        },
    }


def _bar(frac: float, width: int = 28) -> str:
    n = int(round(frac * width))
    return "█" * n + "░" * (width - n)


def render_text(rep: Dict[str, Any]) -> str:
    if "error" in rep:
        return f"doctor: dag {rep['dag_id']}: {rep['error']}"
    L: List[str] = []
    L.append(f"== graft doctor: {rep['dag_id']} "
             f"({rep['name'] or 'unnamed'}, tenant={rep['tenant'] or '-'}, "
             f"{rep['state'] or '?'}) ==")
    L.append(f"wall clock: {rep['wall_s']:.3f} s   "
             f"[{rep['sources']['flight_dumps']} flight dump(s), "
             f"{rep['sources']['flight_events']} events, "
             f"{rep['sources']['intervals']} intervals]")
    L.append("")
    L.append(f"plane blame (priority sweep, sums to {rep['pct_total']}%):")
    for p in PLANES:
        v = rep["planes"][p]
        L.append(f"  {p:<10} {_bar(v['pct'] / 100.0)} "
                 f"{v['pct']:6.2f}%  {v['seconds']:.3f} s")
    s = rep["split"]
    L.append("")
    L.append(f"queue-wait / compute / transport: "
             f"{s['queue_wait_pct']}% / {s['compute_pct']}% / "
             f"{s['transport_pct']}%")
    L.append("")
    L.append("waterfall:")
    for seg in rep["waterfall"]:
        frac = seg["dur_s"] / max(rep["wall_s"], 1e-9)
        L.append(f"  +{seg['offset_s']:8.3f}s  {seg['plane']:<10} "
                 f"{_bar(frac, 20)} {seg['dur_s']:.3f} s")
    if rep["stragglers"]:
        L.append("")
        L.append("top straggler attempts:")
        for r in rep["stragglers"]:
            L.append(f"  {r['attempt_id']} (vertex {r['vertex']}): "
                     f"{r['duration_s']:.3f} s vs median "
                     f"{r['vertex_median_s']:.3f} s  "
                     f"({r['slowdown']}x)")
    if rep.get("am_restarts"):
        L.append("")
        L.append("am restarts (recovery plane):")
        for r in rep["am_restarts"]:
            L.append(f"  attempt {r['attempt']}: "
                     f"+{r['time'] - rep['window'][0]:.3f}s into the "
                     f"window, replay took {r['end'] - r['time']:.3f} s")
    if rep.get("slo_burn_alerts"):
        L.append("")
        L.append("slo burn alerts (pre-breach pages):")
        for a in rep["slo_burn_alerts"]:
            where = (f"stream={a['stream']}" if a.get("stream")
                     else f"tenant={a.get('tenant', '?')}")
            fate = (f"breached {a['lead_s']:.1f}s later"
                    if a["breached"] else "cleared without breaching")
            L.append(f"  {where} {a.get('kind', '?')} observed="
                     f"{a.get('observed', '?')} target="
                     f"{a.get('target', '?')} — {fate}")
    q = rep.get("query") or {}
    if q.get("submitted") or q.get("replans"):
        L.append("")
        L.append("query plane (logical plans behind this dag):")
        for e in q.get("submitted", []):
            strat = ", ".join(f"{fp[:8]}={s}"
                              for fp, s in sorted(
                                  (e.get("strategies") or {}).items()))
            L.append(f"  plan '{e['query']}' fp={e['fingerprint'][:12]} "
                     f"wall={e['wall_s']:.3f}s cache_hits="
                     f"{e['cache_hits']} replans={e['replans']}"
                     + (f" blamed={e['blamed']}" if e.get("blamed")
                        else "")
                     + (f"  [{strat}]" if strat else ""))
        for r in q.get("replans", []):
            L.append(f"  REPLANNED '{r['query']}' {r['operator']} "
                     f"({r['kind']}): {r['from']} -> {r['to']} — "
                     f"{r['detail']}")
    if rep["slo_breaches"]:
        L.append("")
        L.append("slo breaches:")
        for b in rep["slo_breaches"]:
            stream = (f" stream={b['stream']}"
                      if b.get("stream") else "")
            L.append(f"  tenant={b.get('tenant', '?')}{stream} "
                     f"{b.get('kind', '?')} observed="
                     f"{b.get('observed', '?')} target="
                     f"{b.get('target', '?')}")
    L.append("")
    L.append(f"verdict: {rep['verdict']}")
    return "\n".join(L)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _triage_pick(dags: Dict[str, Any]) -> str:
    """Auto-triage default: a failed DAG if any (most recent first), then
    the DAG with the worst intra-vertex straggler skew (one attempt far
    over its siblings' median — the shape injected faults leave), then
    the longest submit→finish wall."""
    failed = [d for d in dags
              if dags[d].state not in ("", "SUCCEEDED", None)]
    if failed:
        return sorted(failed,
                      key=lambda d: dags[d].finish_time or 0.0)[-1]

    fleet = vertex_fleet_medians(dags)

    def skew_then_wall(d: str) -> Tuple[float, float]:
        info = dags[d]
        worst = straggler_attempts(info, top=1, fleet=fleet)
        skew = worst[0]["slowdown"] if worst else 0.0
        t0 = info.submit_time or info.start_time or 0.0
        wall = max(0.0, (info.finish_time or 0.0) - t0)
        # uniform DAGs all sit near 1.0x: treat skew under 2x as noise so
        # the fallback stays "slowest wall", not "noisiest median"
        return (skew if skew >= 2.0 else 0.0, wall)
    return sorted(dags, key=skew_then_wall)[-1]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Cross-plane causal triage: blame waterfall from "
                    "history journals + flight dumps (see docs/doctor.md)")
    ap.add_argument("paths", nargs="+",
                    help="workdirs and/or journal / flight_*.json files")
    ap.add_argument("--dag", default="",
                    help="dag_id to diagnose (default auto-triage: a "
                         "failed DAG if any, else the slowest wall clock)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    ap.add_argument("--perfetto", default="",
                    help="also write a merged Perfetto trace (history "
                         "lanes + flight per-plane tracks) to this path")
    args = ap.parse_args(argv)

    journals, dump_files = find_artifacts(args.paths)
    if not journals:
        print("doctor: no *.jsonl journals found", file=sys.stderr)
        return 1
    from tez_tpu.tools.history_parser import parse_jsonl_files
    dags = parse_jsonl_files(journals)
    if not dags:
        print("doctor: journals contained no DAGs", file=sys.stderr)
        return 1
    dag_id = args.dag or _triage_pick(dags)
    if dag_id not in dags:
        print(f"doctor: dag {dag_id} not in {sorted(dags)}",
              file=sys.stderr)
        return 1
    dag = dags[dag_id]
    snaps = load_flight_dumps(dump_files)
    breaches = load_slo_breaches(journals)
    burn_alerts = load_slo_burn_alerts(journals)
    restarts = load_am_restarts(journals)

    rep = diagnose(dag, snaps, breaches,
                   fleet=vertex_fleet_medians(dags),
                   am_restarts=restarts,
                   burn_alerts=burn_alerts)
    streams = diagnose_streams(dags, snaps)
    if streams:
        rep["streams"] = streams
    if args.perfetto:
        from tez_tpu.tools import trace_export
        events = trace_export.history_to_events(dag)
        for snap in snaps:
            events.extend(trace_export.flight_to_events(snap))
        trace_export.write_trace(
            {"traceEvents": events, "displayTimeUnit": "ms"},
            args.perfetto)
        rep["perfetto"] = args.perfetto
    if args.json:
        print(json.dumps(rep, indent=1))
    else:
        print(render_text(rep))
        if streams:
            print(render_streams(streams))
    return 0 if "error" not in rep else 1


if __name__ == "__main__":
    sys.exit(main())
