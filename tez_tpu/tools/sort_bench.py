"""External-sort scale bench: push-based vs pull-based shuffle, end to end.

Entered via ``make bench-sort`` (``TEZ_BENCH_SORT_ONLY=1 bench.py``).  The
same spill-heavy sort DAG — fixed-width random keys emitted through the
batch write path, io.sort.mb far below the per-task data size so the
producer sorter MUST spill repeatedly — runs twice through the full
framework:

1. PULL (baseline): stock config.  Producers spill to disk, merge their
   spills into one final output at close, and consumers fetch after the
   producer completes — the classic map-side external sort barrier.
2. PUSH: ``tez.runtime.shuffle.push.enabled`` routes every finished spill
   eagerly into the reducer-side buffer store mid-map-wave (pipelined
   emission, no producer final merge, no pspill file), consumers start in
   ingest mode and merge eagerly as pushes land.

Both legs must SUCCEED, both must record ``SPILLED_RECORDS > 0`` (a run
that never spilled is not an external sort — the bench refuses to report
a number for it), the push leg must record ``SHUFFLE_PUSH_BYTES > 0``
(a push bench where push never engaged is a pull bench), and the consumer
outputs — record count + key CRC per reducer, sortedness verified
block-wise — must be bit-identical.  The reported ``vs_baseline`` is
pull wall / push wall with the ``min_vs_baseline`` floor enforced by
``tools/bench_diff.py``.
"""
from __future__ import annotations

import os
import shutil
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from tez_tpu.library.processors import SimpleProcessor

REC_KEY_BYTES = 10
REC_VAL_BYTES = 90      # ~100 B/record: the classic sort-benchmark shape


class SortEmitProcessor(SimpleProcessor):
    """Emits ``mb_per_task`` MB of task-seeded random fixed-width records
    through the vectorized batch write path (per-record Python would be
    the bottleneck, not the shuffle plane being measured)."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        per_task_mb = int(payload.get("mb_per_task", 256))
        chunk_mb = int(payload.get("chunk_mb", 32))
        from tez_tpu.ops.runformat import KVBatch
        writer = outputs["consumer"].get_writer()
        rec = REC_KEY_BYTES + REC_VAL_BYTES
        rng = np.random.default_rng(4242 + self.context.task_index)
        remaining = (per_task_mb << 20) // rec
        chunk = max(1, (chunk_mb << 20) // rec)
        while remaining > 0:
            n = min(chunk, remaining)
            kb = rng.integers(0, 256, n * REC_KEY_BYTES, dtype=np.uint8)
            ko = np.arange(n + 1, dtype=np.int64) * REC_KEY_BYTES
            vb = np.zeros(n * REC_VAL_BYTES, dtype=np.uint8)
            vo = np.arange(n + 1, dtype=np.int64) * REC_VAL_BYTES
            writer.write_batch(KVBatch(kb, ko, vb, vo))
            remaining -= n
            self.context.notify_progress()


def _check_sorted(mat: np.ndarray, prev_last: Optional[np.ndarray]) -> None:
    """Vectorized lexicographic non-decreasing check over a key block (and
    across the block seam)."""
    hi = np.ascontiguousarray(mat[:, :8]).view(">u8").ravel()
    lo = np.ascontiguousarray(mat[:, 8:REC_KEY_BYTES]).view(">u2").ravel()
    ok = (hi[:-1] < hi[1:]) | ((hi[:-1] == hi[1:]) & (lo[:-1] <= lo[1:]))
    if not bool(np.all(ok)):
        raise AssertionError("merged output not sorted within a block")
    if prev_last is not None and \
            bytes(prev_last) > bytes(mat[0]):
        raise AssertionError("merged output not sorted across blocks")


class SortCheckProcessor(SimpleProcessor):
    """Consumes the merged sorted stream block-wise and writes
    ``<records> <key-crc32>`` per reducer, so the push and pull legs can be
    compared bit-exact without materializing gigabytes twice."""

    def run(self, inputs, outputs):
        payload = self.context.user_payload.load() or {}
        reader = inputs["producer"].get_reader()
        crc, records = 0, 0
        prev_last = None
        for batch, _bounds in reader.grouped_blocks():
            kb = np.ascontiguousarray(batch.key_bytes)
            n = batch.num_records
            if n:
                mat = kb.reshape(n, REC_KEY_BYTES)
                _check_sorted(mat, prev_last)
                prev_last = mat[-1].copy()
                crc = zlib.crc32(kb.tobytes(), crc)
                records += n
            self.context.notify_progress()
        out = os.path.join(payload["result_dir"],
                           f"part-{self.context.task_index:05d}")
        with open(out, "w") as fh:
            fh.write(f"{records} {crc & 0xFFFFFFFF:08x}\n")


def _build_sort_dag(name: str, result_dir: str, producers: int,
                    consumers: int, mb_per_task: int, sort_mb: int,
                    merge_mb: int) -> Any:
    from tez_tpu.common.payload import (InputDescriptor, OutputDescriptor,
                                        ProcessorDescriptor)
    from tez_tpu.dag.dag import DAG, Edge, Vertex
    from tez_tpu.dag.edge_property import (DataMovementType, DataSourceType,
                                           EdgeProperty, SchedulingType)
    producer = Vertex.create("producer", ProcessorDescriptor.create(
        SortEmitProcessor, payload={"mb_per_task": mb_per_task}), producers)
    consumer = Vertex.create("consumer", ProcessorDescriptor.create(
        SortCheckProcessor, payload={"result_dir": result_dir}), consumers)
    # io.sort.mb rides the IO payloads, not the client conf: the PRODUCER
    # side stays far below the task's data (spill-heavy — the external
    # sort being measured) while the CONSUMER side gets a real merge
    # budget.  Both legs share the exact same split.
    out_conf = {"tez.runtime.key.class": "bytes",
                "tez.runtime.value.class": "bytes",
                "tez.runtime.io.sort.mb": sort_mb}
    in_conf = {"tez.runtime.key.class": "bytes",
               "tez.runtime.value.class": "bytes",
               "tez.runtime.io.sort.mb": merge_mb}
    prop = EdgeProperty.create(
        DataMovementType.SCATTER_GATHER, DataSourceType.PERSISTED,
        SchedulingType.SEQUENTIAL,
        OutputDescriptor.create(
            "tez_tpu.library.outputs:OrderedPartitionedKVOutput",
            payload=out_conf),
        InputDescriptor.create(
            "tez_tpu.library.inputs:OrderedGroupedKVInput",
            payload=in_conf))
    dag = DAG.create(name).add_vertex(producer).add_vertex(consumer)
    dag.add_edge(Edge.create(producer, consumer, prop))
    return dag


_COUNTER_NAMES = ("SPILLED_RECORDS", "SHUFFLE_BYTES", "SHUFFLE_PUSH_BYTES",
                  "SHUFFLE_PUSH_REJECTED")


def _run_sort(workdir: str, name: str, mb_per_task: int, producers: int,
              consumers: int, sort_mb: int, merge_mb: int,
              extra_conf: Optional[Dict] = None,
              timeout: float = 900.0) -> Tuple[str, str, Dict[str, int],
                                               float]:
    """One client + one sort DAG; returns (state, result, counters, wall).
    ``result`` concatenates every reducer's ``<records> <crc>`` line."""
    from tez_tpu.client.tez_client import TezClient
    staging = os.path.join(workdir, name, "staging")
    result_dir = os.path.join(workdir, name, "out")
    os.makedirs(result_dir, exist_ok=True)
    conf = {
        "tez.staging-dir": staging,
        "tez.am.local.num-containers": producers + consumers,
    }
    conf.update(extra_conf or {})
    t0 = time.time()
    client = TezClient.create(name, conf).start()
    try:
        dag = _build_sort_dag(name, result_dir, producers, consumers,
                              mb_per_task, sort_mb, merge_mb)
        dag_client = client.submit_dag(dag)
        status = dag_client.wait_for_completion(timeout=timeout)
        state = status.state.name
        final = dag_client.get_dag_status(with_counters=True)
    finally:
        client.stop()
    wall = time.time() - t0
    counters: Dict[str, int] = {}
    if final.counters is not None:
        for group in final.counters.to_dict().values():
            for cname in _COUNTER_NAMES:
                if cname in group:
                    counters[cname] = counters.get(cname, 0) + group[cname]
    lines = []
    for fname in sorted(os.listdir(result_dir)):
        with open(os.path.join(result_dir, fname)) as fh:
            lines.append(fh.read().strip())
    return state, "\n".join(lines), counters, wall


def _quiesce(workdir: str, name: str) -> None:
    """Drop the finished leg's files and flush dirty pages so the NEXT
    leg's wall doesn't pay this leg's background writeback (on a small
    box the kernel flushing gigabytes of dead spill pages steals the
    second leg's CPU and disk — the ratio must not depend on leg order).
    Runs outside both timed regions: neither leg is charged."""
    shutil.rmtree(os.path.join(workdir, name), ignore_errors=True)
    os.sync()


def bench_sort(cpu_fallback: bool) -> dict:
    """The push-vs-pull external-sort record for bench.py's JSON stream."""
    import tempfile
    from tez_tpu.store import reset_store
    total_mb = int(os.environ.get("TEZ_BENCH_SORT_MB", "1024"))
    producers = int(os.environ.get("TEZ_BENCH_SORT_TASKS", "4"))
    consumers = int(os.environ.get("TEZ_BENCH_SORT_REDUCERS", "4"))
    sort_mb = int(os.environ.get("TEZ_BENCH_SORT_IOSORT_MB", "48"))
    merge_mb = int(os.environ.get("TEZ_BENCH_SORT_MERGE_MB", "512"))
    mb_per_task = max(1, total_mb // producers)
    push_conf = {
        "tez.runtime.shuffle.push.enabled": True,
        # per-source quota must clear one task's whole output, or the tail
        # spills fall back to pull and the leg measures a hybrid
        "tez.runtime.shuffle.push.source-quota-mb": mb_per_task * 2,
        "tez.runtime.store.enabled": True,
        "tez.runtime.store.device.capacity-mb": 0,
        "tez.runtime.store.host.capacity-mb": total_mb * 3,
        "tez.runtime.store.lineage.reuse": False,
    }
    workdir = tempfile.mkdtemp(prefix="tez-sortbench-")
    try:
        # warmup: tiny run loads the native sorter + merge libraries so the
        # pull leg (which runs first) doesn't eat the one-time costs
        reset_store()
        state, _, _, _ = _run_sort(workdir, "warm", 8, 2, 1,
                                   sort_mb=4, merge_mb=16)
        assert state == "SUCCEEDED", f"warmup run failed ({state})"
        _quiesce(workdir, "warm")

        state, pull_res, pull_c, pull_wall = _run_sort(
            workdir, "pull", mb_per_task, producers, consumers,
            sort_mb, merge_mb)
        assert state == "SUCCEEDED", f"pull leg failed ({state})"
        assert pull_c.get("SPILLED_RECORDS", 0) > 0, \
            "pull leg never spilled — not an external sort; shrink io.sort.mb"
        _quiesce(workdir, "pull")

        reset_store()
        try:
            state, push_res, push_c, push_wall = _run_sort(
                workdir, "push", mb_per_task, producers, consumers,
                sort_mb, merge_mb, extra_conf=push_conf)
        finally:
            reset_store()
        assert state == "SUCCEEDED", f"push leg failed ({state})"
        assert push_c.get("SPILLED_RECORDS", 0) > 0, \
            "push leg never spilled — not an external sort; shrink io.sort.mb"
        assert push_c.get("SHUFFLE_PUSH_BYTES", 0) > 0, \
            "push leg never pushed a byte — the comparison is pull vs pull"
        assert push_res == pull_res and pull_res, (
            f"push/pull outputs diverge:\npull: {pull_res!r}\n"
            f"push: {push_res!r}")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    suffix = " [CPU FALLBACK: TPU relay stalled]" if cpu_fallback else ""
    return {
        "metric": (f"external-sort push vs pull shuffle "
                   f"({total_mb / 1024:.1f} GB, {producers}x{consumers} "
                   f"tasks, io.sort.mb map={sort_mb}/reduce={merge_mb}, "
                   f"SPILLED_RECORDS "
                   f"pull={pull_c.get('SPILLED_RECORDS', 0)} "
                   f"push={push_c.get('SPILLED_RECORDS', 0)}, "
                   f"SHUFFLE_PUSH_BYTES={push_c.get('SHUFFLE_PUSH_BYTES', 0)}"
                   f", rejected={push_c.get('SHUFFLE_PUSH_REJECTED', 0)}, "
                   f"pull {pull_wall:.1f}s, outputs bit-identical){suffix}"),
        "value": round(total_mb / push_wall, 2), "unit": "MB/s",
        "vs_baseline": round(pull_wall / push_wall, 3),
        "min_vs_baseline": 1.2,
    }
