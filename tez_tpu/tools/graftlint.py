"""graftlint CLI: run the static-analysis suite over the tez_tpu tree.

::

    python -m tez_tpu.tools.graftlint            # = make lint
    python -m tez_tpu.tools.graftlint --update-baseline
    python -m tez_tpu.tools.graftlint --checker lockorder --graph

Exit codes: 0 = clean (no findings outside the committed baseline),
1 = new findings, 2 = internal error.  Output is stable and sorted —
``path:line: code [checker] message`` — so run-to-run diffs are
reviewable the way tools/bench_diff.py reports are.

The baseline (``tez_tpu/tools/graftlint_baseline.json``) holds triaged
known-finding identities; the gate fails only on findings *not* listed
there, so adopting a new checker never blocks unrelated PRs.  Refresh it
with ``--update-baseline`` after triage and commit the diff.
"""
from __future__ import annotations

import argparse
import os
import sys
import traceback
from typing import List

from tez_tpu.analysis import all_checkers
from tez_tpu.analysis.core import (Context, load_baseline,
                                   partition_by_baseline, run_checkers,
                                   save_baseline)

_DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "graftlint_baseline.json")


def _default_root() -> str:
    # <root>/tez_tpu/tools/graftlint.py -> <root>
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based static analysis for the tez_tpu tree "
                    "(docs/static_analysis.md)")
    ap.add_argument("--root", default=_default_root(),
                    help="repository root holding tez_tpu/ and docs/")
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="suppression baseline JSON path")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    ap.add_argument("--checker", action="append", default=None,
                    metavar="NAME",
                    help="run only this checker (repeatable)")
    ap.add_argument("--list-checkers", action="store_true",
                    help="print the checker catalog and exit")
    ap.add_argument("--graph", action="store_true",
                    help="also dump the static lock acquisition graph")
    args = ap.parse_args(argv)

    checkers = all_checkers()
    if args.list_checkers:
        for c in checkers:
            print(f"{c.name}: {c.doc}")
        return 0
    if args.checker:
        unknown = set(args.checker) - {c.name for c in checkers}
        if unknown:
            print(f"graftlint: unknown checker(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        checkers = [c for c in checkers if c.name in args.checker]

    try:
        ctx = Context(args.root)
        findings = run_checkers(ctx, checkers)
        if args.graph:
            from tez_tpu.analysis import lockorder
            edges, locks = lockorder.build_graph(ctx)
            print(f"# lock graph: {len(locks)} locks, {len(edges)} edges")
            for (a, b) in sorted(edges):
                where, line = edges[(a, b)]
                print(f"{a} -> {b}  [{where}:{line}]")
        if args.update_baseline:
            save_baseline(args.baseline, findings)
            print(f"graftlint: baseline rewritten with {len(findings)} "
                  f"finding(s) at {args.baseline}")
            return 0
        new, known, stale = partition_by_baseline(
            findings, load_baseline(args.baseline))
        for f in new:
            print(f.render())
        for ident in stale:
            print(f"graftlint: stale baseline entry (fixed? run "
                  f"--update-baseline): {ident}")
        print(f"graftlint: {len(checkers)} checker(s), "
              f"{len(new)} new finding(s), {len(known)} baselined, "
              f"{len(stale)} stale baseline entr(ies)")
        return 1 if new else 0
    except Exception:               # noqa: BLE001 — exit-code contract
        traceback.print_exc()
        print("graftlint: internal error", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
