"""Recovery-journal fsck: validate a journal's record CRCs, event ordering,
commit-ledger pairing, admission-queue pairing (``DAG_QUEUED`` /
``DAG_REQUEUED_ON_RECOVERY`` records resolved by a promoting
``DAG_SUBMITTED``), and the streaming window-commit ledger
(``WINDOW_COMMIT_STARTED`` brackets closed by FINISHED/ABORTED, window
ids strictly increasing per stream, nothing after ``STREAM_RETIRED``),
and the SLO records (``SLO_BURN_ALERT`` / ``TENANT_SLO_BREACH`` must
carry the tenant/kind labels doctor joins on; ``TELEMETRY_SNAPSHOT``
accounting must be non-negative), then print the terminal state recovery
would infer for each DAG, each still-parked submission, and each stream,
plus the per-(tenant, kind, stream) SLO tally.

Point it at one or more journal files, at an app's ``recovery/`` directory
(all attempts are checked in order), or at a staging dir + app id::

    python -m tez_tpu.tools.journal_fsck <journal.jsonl | recovery-dir> ...
    python -m tez_tpu.tools.journal_fsck --staging /path/staging --app app_x

Exit code 0 means the journal is consistent (a torn trailing record — the
AM died mid-append — is tolerated, exactly as recovery tolerates it);
1 means structural damage or ledger violations; 2 means no journal found.
The chaos harness runs this on every divergent trial so a corrupt-journal
root cause is distinguished from a replay bug.
"""
from __future__ import annotations

import argparse
import dataclasses
import os
import sys
from typing import Dict, List, Optional, Tuple

from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.recovery import JournalLineError, decode_journal_line
from tez_tpu.dag.plan import DAGPlan

#: Events whose arrival after a DAG's terminal record is a bug (lifecycle
#: and ledger records; incidental events like NODE_BLACKLISTED may straggle).
_LIFECYCLE = frozenset({
    HistoryEventType.DAG_SUBMITTED, HistoryEventType.DAG_INITIALIZED,
    HistoryEventType.DAG_STARTED, HistoryEventType.DAG_COMMIT_STARTED,
    HistoryEventType.DAG_COMMIT_FINISHED, HistoryEventType.DAG_COMMIT_ABORTED,
    HistoryEventType.DAG_FINISHED,
})

#: Admission-queue records: the ``dag_id`` slot carries the submission id,
#: not a DAG id — they must never materialize a phantom DAG ledger.
_ADMISSION = frozenset({
    HistoryEventType.DAG_QUEUED,
    HistoryEventType.DAG_REQUEUED_ON_RECOVERY,
})

#: Streaming records: keyed by ``data["stream"]``, checked against the
#: per-stream window ledger (a window DAG's own records still flow into
#: its DagLedger like any DAG's — these are the STREAM-level brackets).
_STREAMING = frozenset({
    HistoryEventType.STREAM_OPENED,
    HistoryEventType.STREAM_RETIRED,
    HistoryEventType.WINDOW_COMMIT_STARTED,
    HistoryEventType.WINDOW_COMMIT_FINISHED,
    HistoryEventType.WINDOW_COMMIT_ABORTED,
    HistoryEventType.WINDOW_LAGGING,
})

#: SLO / telemetry records: session-scoped (``dag_id`` is None), keyed by
#: their (tenant, kind, stream) label triple.  The labels are load-bearing:
#: doctor joins a burn alert to the breach that followed it per stream, so
#: a record missing them is a structural error, not cosmetics.
_SLO = frozenset({
    HistoryEventType.TENANT_SLO_BREACH,
    HistoryEventType.SLO_BURN_ALERT,
})


@dataclasses.dataclass
class DagLedger:
    """Per-DAG fsck state."""
    submitted: bool = False
    commit_state: Optional[str] = None      # None/STARTED/FINISHED/ABORTED
    terminal: Optional[str] = None          # DAG_FINISHED state, if journaled
    events: int = 0

    @property
    def inferred_terminal(self) -> str:
        """What recovery would conclude for this DAG."""
        if self.terminal is not None:
            return self.terminal
        if self.commit_state == "FINISHED":
            return "SUCCEEDED (ledger roll-forward)"
        if self.commit_state == "ABORTED":
            return "FAILED (ledger rollback)"
        if self.commit_state == "STARTED":
            return "IN-COMMIT (policy decides: resume or fail)"
        return "IN-FLIGHT (resubmit with task short-circuit)"


@dataclasses.dataclass
class SubLedger:
    """Per-submission admission ledger: a ``DAG_QUEUED`` (and any successor
    ``DAG_REQUEUED_ON_RECOVERY``) record is closed by the ``DAG_SUBMITTED``
    stamped with the same ``sub_id`` — exactly the pairing discipline the
    commit ledger gets."""
    queued: int = 0
    requeued: int = 0
    promoted: bool = False
    dag_name: str = ""
    decode_error: str = ""

    @property
    def inferred(self) -> str:
        """What recovery would conclude for this submission."""
        if self.promoted:
            return "PROMOTED"
        if self.decode_error:
            return f"LOST (plan undecodable: {self.decode_error})"
        return "UNRESOLVED (successor AM must replay)"


@dataclasses.dataclass
class StreamLedger:
    """Per-stream window-commit ledger: every ``WINDOW_COMMIT_STARTED``
    bracket must close (FINISHED or ABORTED), a window is FINISHED at
    most once (exactly-once), committed ids are strictly increasing
    (windows run sequentially), and nothing follows ``STREAM_RETIRED``."""
    opened: bool = False
    retired: bool = False
    open_window: Optional[int] = None       # STARTED with no close yet
    committed: List[int] = dataclasses.field(default_factory=list)
    aborted: List[int] = dataclasses.field(default_factory=list)
    lag_events: int = 0

    @property
    def inferred(self) -> str:
        """What a resuming StreamDriver would conclude."""
        if self.retired:
            return f"RETIRED ({len(self.committed)} window(s) committed)"
        if self.open_window is not None:
            return (f"IN-COMMIT w{self.open_window} (successor rolls the "
                    f"idempotent bracket forward)")
        nxt = (self.committed[-1] + 1) if self.committed else 1
        return f"LIVE (resume from window {nxt})"


@dataclasses.dataclass
class FsckReport:
    files: List[str] = dataclasses.field(default_factory=list)
    records: int = 0
    errors: List[str] = dataclasses.field(default_factory=list)
    warnings: List[str] = dataclasses.field(default_factory=list)
    torn_tail: bool = False
    dags: Dict[str, DagLedger] = dataclasses.field(default_factory=dict)
    subs: Dict[str, SubLedger] = dataclasses.field(default_factory=dict)
    sub_order: List[str] = dataclasses.field(default_factory=list)
    streams: Dict[str, StreamLedger] = dataclasses.field(default_factory=dict)
    #: (tenant, kind, stream) -> {"burn_alerts": n, "breaches": n}
    slo: Dict[Tuple[str, str, str], Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    telemetry_snapshots: int = 0

    @property
    def ok(self) -> bool:
        return not self.errors


def _check_admission(report: FsckReport, ev: HistoryEvent,
                     where: str) -> bool:
    """Admission-queue pairing.  Returns True when the event was a queue
    record (consumed here, never a DAG-ledger record)."""
    t = ev.event_type
    if t in _ADMISSION:
        sub_id = ev.dag_id or ""
        if not sub_id:
            report.errors.append(f"{where}: {t.name} without a sub_id")
            return True
        led = report.subs.get(sub_id)
        if led is None:
            led = report.subs[sub_id] = SubLedger()
            report.sub_order.append(sub_id)
        if t is HistoryEventType.DAG_QUEUED:
            if led.queued:
                report.errors.append(
                    f"{where}: duplicate DAG_QUEUED for {sub_id}")
            if led.requeued:
                report.errors.append(
                    f"{where}: DAG_QUEUED for {sub_id} after a "
                    f"DAG_REQUEUED_ON_RECOVERY (attempt order violated)")
            led.queued += 1
        else:
            if not led.queued:
                report.errors.append(
                    f"{where}: DAG_REQUEUED_ON_RECOVERY for {sub_id} that "
                    f"was never DAG_QUEUED")
            led.requeued += 1
        if led.promoted:
            report.errors.append(
                f"{where}: {t.name} for {sub_id} after its promotion "
                f"(DAG_SUBMITTED already resolved it)")
        led.dag_name = ev.data.get("dag_name", "") or led.dag_name
        raw = ev.data.get("plan")
        if raw:
            try:
                DAGPlan.deserialize(bytes.fromhex(raw))
                led.decode_error = ""
            except Exception as e:  # noqa: BLE001 — flagged, not fatal here
                led.decode_error = repr(e)
        else:
            led.decode_error = "queued record carries no plan"
        return True
    if t is HistoryEventType.DAG_SUBMITTED:
        sub_id = ev.data.get("sub_id")
        if sub_id:
            led = report.subs.get(sub_id)
            if led is None:
                report.errors.append(
                    f"{where}: DAG_SUBMITTED resolves sub_id {sub_id} that "
                    f"was never DAG_QUEUED")
            elif led.promoted:
                report.errors.append(
                    f"{where}: duplicate promotion of {sub_id}")
            else:
                led.promoted = True
    return False


def _check_streaming(report: FsckReport, ev: HistoryEvent,
                     where: str) -> bool:
    """Window-commit ledger pairing.  Returns True when the event was a
    stream-level record (consumed here; a window DAG's OWN lifecycle
    records still flow to its DagLedger)."""
    t = ev.event_type
    if t not in _STREAMING:
        return False
    stream = ev.data.get("stream", "")
    if not stream:
        report.errors.append(f"{where}: {t.name} without a stream id")
        return True
    led = report.streams.setdefault(stream, StreamLedger())
    if led.retired and t is not HistoryEventType.STREAM_OPENED:
        report.errors.append(
            f"{where}: {t.name} for stream {stream} after STREAM_RETIRED")
        return True
    if t is HistoryEventType.STREAM_OPENED:
        if led.opened:
            report.errors.append(
                f"{where}: duplicate STREAM_OPENED for {stream}")
        led.opened = True
        return True
    if not led.opened:
        report.errors.append(
            f"{where}: {t.name} for stream {stream} that was never "
            f"STREAM_OPENED")
    if t is HistoryEventType.STREAM_RETIRED:
        if led.open_window is not None:
            report.errors.append(
                f"{where}: STREAM_RETIRED for {stream} with commit bracket "
                f"w{led.open_window} still open")
        led.retired = True
    elif t is HistoryEventType.WINDOW_LAGGING:
        led.lag_events += 1
    else:
        w = int(ev.data.get("window_id", 0))
        if w <= 0:
            report.errors.append(
                f"{where}: {t.name} for stream {stream} without a window id")
            return True
        if t is HistoryEventType.WINDOW_COMMIT_STARTED:
            if led.open_window == w:
                # the crash-mid-commit replay: the successor re-opens the
                # SAME window's bracket and rolls it forward (idempotent)
                report.warnings.append(
                    f"{where}: commit bracket w{w} of {stream} re-opened "
                    f"(roll-forward after AM crash)")
            elif led.open_window is not None:
                report.errors.append(
                    f"{where}: WINDOW_COMMIT_STARTED w{w} for {stream} with "
                    f"bracket w{led.open_window} still open")
            if w in led.committed:
                report.errors.append(
                    f"{where}: WINDOW_COMMIT_STARTED w{w} for {stream} "
                    f"after that window already committed (exactly-once "
                    f"violated)")
            elif w in led.aborted:
                report.warnings.append(
                    f"{where}: window w{w} of {stream} re-runs after an "
                    f"abort")
            led.open_window = w
        elif t is HistoryEventType.WINDOW_COMMIT_FINISHED:
            if led.open_window != w:
                report.errors.append(
                    f"{where}: WINDOW_COMMIT_FINISHED w{w} for {stream} "
                    f"without its open STARTED (bracket was "
                    f"{'w%d' % led.open_window if led.open_window else 'closed'})")
            if w in led.committed:
                report.errors.append(
                    f"{where}: duplicate WINDOW_COMMIT_FINISHED w{w} for "
                    f"{stream} (exactly-once violated)")
            elif led.committed and w <= led.committed[-1]:
                report.errors.append(
                    f"{where}: {stream} committed w{w} after "
                    f"w{led.committed[-1]} — window ids must be strictly "
                    f"increasing")
            led.committed.append(w)
            led.open_window = None
        else:   # WINDOW_COMMIT_ABORTED
            if led.open_window is not None and led.open_window != w:
                report.errors.append(
                    f"{where}: WINDOW_COMMIT_ABORTED w{w} for {stream} "
                    f"while bracket w{led.open_window} is open")
            led.aborted.append(w)
            led.open_window = None
    return True


def _check_slo(report: FsckReport, ev: HistoryEvent, where: str) -> bool:
    """SLO / telemetry accounting.  Returns True when the event was a
    session-scoped SLO or telemetry record (consumed here)."""
    t = ev.event_type
    if t is HistoryEventType.TELEMETRY_SNAPSHOT:
        report.telemetry_snapshots += 1
        for k in ("evicted", "collector_errors", "scrape_errors"):
            v = ev.data.get(k)
            if v is not None and int(v) < 0:
                report.errors.append(
                    f"{where}: TELEMETRY_SNAPSHOT with negative {k}={v}")
        return True
    if t not in _SLO:
        return False
    tenant = ev.data.get("tenant", "")
    kind = ev.data.get("kind", "")
    if not tenant or not kind:
        report.errors.append(
            f"{where}: {t.name} without tenant/kind labels "
            f"(doctor cannot join it per stream)")
        return True
    key = (tenant, kind, ev.data.get("stream") or "")
    led = report.slo.setdefault(key, {"burn_alerts": 0, "breaches": 0})
    if t is HistoryEventType.SLO_BURN_ALERT:
        led["burn_alerts"] += 1
    else:
        led["breaches"] += 1
    return True


def _check_event(report: FsckReport, ev: HistoryEvent, where: str) -> None:
    report.records += 1
    if _check_admission(report, ev, where):
        return
    if _check_streaming(report, ev, where):
        return
    if _check_slo(report, ev, where):
        return
    dag_id = ev.dag_id
    if dag_id is None:
        return
    led = report.dags.setdefault(dag_id, DagLedger())
    led.events += 1
    t = ev.event_type
    if led.terminal is not None and t in _LIFECYCLE:
        report.errors.append(
            f"{where}: {t.name} for {dag_id} after its terminal "
            f"DAG_FINISHED({led.terminal})")
        return
    if t is HistoryEventType.DAG_SUBMITTED:
        led.submitted = True
    elif not led.submitted and t in _LIFECYCLE:
        report.errors.append(
            f"{where}: {t.name} for {dag_id} before DAG_SUBMITTED")
    if t is HistoryEventType.DAG_COMMIT_STARTED:
        if led.commit_state == "STARTED":
            report.errors.append(
                f"{where}: duplicate DAG_COMMIT_STARTED for {dag_id}")
        elif led.commit_state in ("FINISHED", "ABORTED"):
            report.errors.append(
                f"{where}: DAG_COMMIT_STARTED for {dag_id} after ledger "
                f"already {led.commit_state}")
        led.commit_state = "STARTED"
    elif t is HistoryEventType.DAG_COMMIT_FINISHED:
        if led.commit_state != "STARTED":
            report.errors.append(
                f"{where}: DAG_COMMIT_FINISHED for {dag_id} without an open "
                f"DAG_COMMIT_STARTED (ledger was {led.commit_state})")
        led.commit_state = "FINISHED"
    elif t is HistoryEventType.DAG_COMMIT_ABORTED:
        if led.commit_state != "STARTED":
            report.errors.append(
                f"{where}: DAG_COMMIT_ABORTED for {dag_id} without an open "
                f"DAG_COMMIT_STARTED (ledger was {led.commit_state})")
        led.commit_state = "ABORTED"
    elif t is HistoryEventType.DAG_FINISHED:
        state = ev.data.get("state")
        led.terminal = state
        if state == "SUCCEEDED" and led.commit_state == "STARTED":
            report.errors.append(
                f"{where}: {dag_id} finished SUCCEEDED with commit ledger "
                f"still open (STARTED without FINISHED)")
        if state == "SUCCEEDED" and led.commit_state == "ABORTED":
            report.errors.append(
                f"{where}: {dag_id} finished SUCCEEDED after "
                f"DAG_COMMIT_ABORTED")


def fsck_files(paths: List[str]) -> FsckReport:
    """Validate journals in the given order (attempt order matters: the
    ledger threads across AM incarnations)."""
    report = FsckReport(files=list(paths))
    for fi, path in enumerate(paths):
        # a crash can tear the tail mid-byte, not just mid-line: decode
        # leniently and let the CRC check reject the mangled record
        with open(path, errors="replace") as fh:
            lines = [ln.strip() for ln in fh]
        while lines and not lines[-1]:
            lines.pop()
        for li, line in enumerate(lines):
            if not line:
                continue
            where = f"{os.path.basename(os.path.dirname(path))}/" \
                    f"{os.path.basename(path)}:{li + 1}"
            try:
                ev = decode_journal_line(line)
            except JournalLineError as e:
                # the tail of ANY attempt file is where that incarnation
                # died — a torn final record there is the expected crash
                # signature, not at-rest corruption
                if li == len(lines) - 1:
                    report.torn_tail = True
                    report.warnings.append(
                        f"{where}: torn trailing record (tolerated): {e}")
                else:
                    report.errors.append(f"{where}: corrupt record: {e}")
                continue
            _check_event(report, ev, where)
    # an undecodable plan on a still-parked record is lost work — the
    # successor AM can never replay it; on a promoted record it is merely
    # suspicious (the live plan object made it through)
    for sub_id, led in report.subs.items():
        if not led.decode_error:
            continue
        name = led.dag_name or "<unnamed>"
        if led.promoted:
            report.warnings.append(
                f"queued record {sub_id} ({name}): plan undecodable "
                f"(promoted anyway): {led.decode_error}")
        else:
            report.errors.append(
                f"unresolved queued submission {sub_id} ({name}): plan "
                f"undecodable — replay impossible: {led.decode_error}")
    # a trailing open window bracket is what an AM crash mid-commit
    # leaves; recovery rolls it forward (idempotent renames), so it is a
    # warning — but only on a LIVE stream, never a retired one
    for stream, sled in report.streams.items():
        if sled.open_window is not None and not sled.retired:
            report.warnings.append(
                f"stream {stream}: commit bracket w{sled.open_window} "
                f"still open (AM died mid-commit; successor rolls forward)")
    return report


def discover_journals(target: str) -> List[str]:
    """A journal file itself, or a directory scanned for per-attempt
    ``<n>/journal.jsonl`` children (an app's ``recovery/`` dir), sorted by
    attempt number."""
    if os.path.isfile(target):
        return [target]
    if not os.path.isdir(target):
        return []
    out: List[Tuple[int, str]] = []
    for name in os.listdir(target):
        p = os.path.join(target, name, "journal.jsonl")
        if os.path.isfile(p):
            try:
                out.append((int(name), p))
            except ValueError:
                out.append((1 << 30, p))
    direct = os.path.join(target, "journal.jsonl")
    if os.path.isfile(direct):
        out.append((0, direct))
    return [p for _, p in sorted(out)]


def print_report(report: FsckReport, verbose: bool = False) -> None:
    print(f"checked {len(report.files)} journal file(s), "
          f"{report.records} record(s)")
    for w in report.warnings:
        print(f"warn: {w}")
    for e in report.errors:
        print(f"ERROR: {e}")
    for dag_id, led in sorted(report.dags.items()):
        commit = led.commit_state or "none"
        print(f"dag {dag_id}: {led.events} record(s), commit-ledger={commit}"
              f" -> terminal: {led.inferred_terminal}")
    for sub_id in report.sub_order:
        sub = report.subs[sub_id]
        print(f"sub {sub_id} ({sub.dag_name or '<unnamed>'}): "
              f"queued={sub.queued} requeued={sub.requeued}"
              f" -> {sub.inferred}")
    for stream, sled in sorted(report.streams.items()):
        print(f"stream {stream}: {len(sled.committed)} committed, "
              f"{len(sled.aborted)} aborted, {sled.lag_events} lag "
              f"episode(s) -> {sled.inferred}")
    for (tenant, kind, stream), led in sorted(report.slo.items()):
        where = f" stream={stream}" if stream else ""
        print(f"slo tenant={tenant}{where} {kind}: "
              f"{led['burn_alerts']} burn alert(s), "
              f"{led['breaches']} breach(es)")
    if report.telemetry_snapshots:
        print(f"telemetry: {report.telemetry_snapshots} snapshot(s)")
    print("fsck: " + ("CLEAN" if report.ok else
                      f"{len(report.errors)} error(s)"))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tez_tpu.tools.journal_fsck", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("targets", nargs="*",
                    help="journal.jsonl file(s) or recovery dir(s)")
    ap.add_argument("--staging", default=None,
                    help="staging dir (with --app: checks "
                         "<staging>/<app>/recovery)")
    ap.add_argument("--app", default=None, help="app id under --staging")
    args = ap.parse_args(argv)

    targets = list(args.targets)
    if args.staging and args.app:
        targets.append(os.path.join(args.staging, args.app, "recovery"))
    files: List[str] = []
    for t in targets:
        found = discover_journals(t)
        if not found:
            print(f"no journal found at {t}")
        files.extend(found)
    if not files:
        return 2
    report = fsck_files(files)
    print_report(report)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
