"""Parse history JSONL journals into an analyzable object model.

Reference parity: tez-plugins/tez-history-parser (ATSFileParser /
ProtoHistoryParser / SimpleHistoryParser -> DagInfo/VertexInfo/TaskInfo/
AttemptInfo datamodel) reading the JsonlHistoryLoggingService output (which
doubles as the recovery journal format).
"""
from __future__ import annotations

import dataclasses
import sys
import glob as globlib
import os
from typing import Dict, List, Optional

from tez_tpu.am.history import HistoryEvent, HistoryEventType
from tez_tpu.am.recovery import decode_journal_line


@dataclasses.dataclass
class AttemptInfo:
    attempt_id: str
    task_id: str
    vertex_name: str
    container_id: str = ""
    node_id: str = ""
    start_time: float = 0.0
    finish_time: float = 0.0
    state: str = ""
    diagnostics: str = ""
    counters: Dict = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> float:
        return max(0.0, self.finish_time - self.start_time)

    def counter(self, group: str, name: str, default: int = 0) -> int:
        return self.counters.get(group, {}).get(name, default)


@dataclasses.dataclass
class TaskInfo:
    task_id: str
    vertex_name: str
    start_time: float = 0.0
    finish_time: float = 0.0
    state: str = ""
    attempts: Dict[str, AttemptInfo] = dataclasses.field(default_factory=dict)

    @property
    def successful_attempt(self) -> Optional[AttemptInfo]:
        for a in self.attempts.values():
            if a.state == "SUCCEEDED":
                return a
        return None


@dataclasses.dataclass
class VertexInfo:
    vertex_id: str
    name: str = ""
    num_tasks: int = 0
    init_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    state: str = ""
    counters: Dict = dataclasses.field(default_factory=dict)
    tasks: Dict[str, TaskInfo] = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> float:
        return max(0.0, self.finish_time - self.start_time)


@dataclasses.dataclass
class DagInfo:
    dag_id: str
    name: str = ""
    tenant: str = ""
    submit_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    state: str = ""
    diagnostics: str = ""
    counters: Dict = dataclasses.field(default_factory=dict)
    vertices: Dict[str, VertexInfo] = dataclasses.field(default_factory=dict)
    containers: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    # node health transitions in stream order: {"node_id", "event"
    # (BLACKLISTED|FORCED_ACTIVE), "failures", "time"} — host-scoped like
    # containers, attached to every dag
    node_events: List[Dict] = dataclasses.field(default_factory=list)
    # DAG structure recovered from the journaled plan: list of
    # {"src": name, "dst": name, "movement": DataMovementType name}
    edges: List[Dict] = dataclasses.field(default_factory=list)
    # session admission stream (QUEUED/SHED verdicts) in event order:
    # {"event", "tenant", "dag_name", "reason", "time"} — session-scoped
    # like containers, attached to every dag
    admission_events: List[Dict] = dataclasses.field(default_factory=list)
    # session recovery stream (AM-restart replay + zombie fencing) in
    # event order: REQUEUED entries {"event", "sub_id", "tenant",
    # "dag_name", "attempt", "time"}; FENCED entries {"event", "reason",
    # "detail", "msg_epoch", "am_epoch", "time"} — session-scoped,
    # attached to every dag
    recovery_events: List[Dict] = dataclasses.field(default_factory=list)
    # session streaming stream (window-commit ledger + lag episodes) in
    # event order: {"event": OPENED|RETIRED|COMMIT_STARTED|COMMIT_FINISHED
    # |COMMIT_ABORTED|LAGGING, "stream", "window_id", "dag_id", "time",
    # ...extras} — session-scoped, attached to every dag
    stream_events: List[Dict] = dataclasses.field(default_factory=list)
    # session telemetry stream: SLO_BURN_ALERT pages {"event": "BURN",
    # "tenant", "kind", "stream", "observed", "target", "time"} and the
    # stop-time TELEMETRY_SNAPSHOT accounting {"event": "SNAPSHOT",
    # "series", "evicted", "collector_errors", "scrape_errors", "ticks",
    # "time"} — session-scoped, attached to every dag
    telemetry_events: List[Dict] = dataclasses.field(default_factory=list)
    # session query-plan stream (tez_tpu/query/, docs/query.md):
    # SUBMITTED entries {"event", "query", "fingerprint", "dag_id",
    # "strategies", "cache_hits", "replans", "blamed", "wall_s", "time"}
    # and REPLANNED entries {"event", "query", "node", "operator",
    # "kind", "from", "to", "detail", "time"} — session-scoped, attached
    # to every dag
    query_events: List[Dict] = dataclasses.field(default_factory=list)

    @property
    def duration(self) -> float:
        return max(0.0, self.finish_time - self.start_time)

    def vertex(self, name: str) -> Optional[VertexInfo]:
        for v in self.vertices.values():
            if v.name == name:
                return v
        return None

    def all_attempts(self) -> List[AttemptInfo]:
        return [a for v in self.vertices.values()
                for t in v.tasks.values() for a in t.attempts.values()]


def parse_history_events(events: List[HistoryEvent]) -> Dict[str, DagInfo]:
    """Event stream -> {dag_id: DagInfo}."""
    dags: Dict[str, DagInfo] = {}
    containers: Dict[str, Dict] = {}
    node_events: List[Dict] = []
    admission_events: List[Dict] = []
    recovery_events: List[Dict] = []
    stream_events: List[Dict] = []
    telemetry_events: List[Dict] = []
    query_events: List[Dict] = []
    _streaming = {
        HistoryEventType.STREAM_OPENED: "OPENED",
        HistoryEventType.STREAM_RETIRED: "RETIRED",
        HistoryEventType.WINDOW_COMMIT_STARTED: "COMMIT_STARTED",
        HistoryEventType.WINDOW_COMMIT_FINISHED: "COMMIT_FINISHED",
        HistoryEventType.WINDOW_COMMIT_ABORTED: "COMMIT_ABORTED",
        HistoryEventType.WINDOW_LAGGING: "LAGGING",
    }

    def dag(ev: HistoryEvent) -> Optional[DagInfo]:
        if ev.dag_id is None:
            return None
        return dags.setdefault(ev.dag_id, DagInfo(ev.dag_id))

    for ev in events:
        t = ev.event_type
        if t in (HistoryEventType.DAG_QUEUED,
                 HistoryEventType.DAG_ADMISSION_SHED):
            # session-scoped verdicts; DAG_QUEUED's dag_id is a submission
            # id, not a real DAG — never materialize a phantom DagInfo
            admission_events.append({
                "event": ("QUEUED" if t is HistoryEventType.DAG_QUEUED
                          else "SHED"),
                "tenant": ev.data.get("tenant", ""),
                "dag_name": ev.data.get("dag_name", ""),
                "reason": ev.data.get("reason", ""),
                "time": ev.timestamp})
            continue
        if t in (HistoryEventType.DAG_REQUEUED_ON_RECOVERY,
                 HistoryEventType.ATTEMPT_FENCED):
            # session-scoped recovery stream: a requeue's dag_id is the
            # original submission id and a fence has no DAG at all —
            # neither may materialize a phantom DagInfo
            if t is HistoryEventType.DAG_REQUEUED_ON_RECOVERY:
                recovery_events.append({
                    "event": "REQUEUED",
                    "sub_id": ev.dag_id or "",
                    "tenant": ev.data.get("tenant", ""),
                    "dag_name": ev.data.get("dag_name", ""),
                    "attempt": ev.data.get("attempt", 0),
                    "time": ev.timestamp})
            else:
                recovery_events.append({
                    "event": "FENCED",
                    "reason": ev.data.get("reason", ""),
                    "detail": ev.data.get("detail", ""),
                    "msg_epoch": ev.data.get("msg_epoch", 0),
                    "am_epoch": ev.data.get("am_epoch", 0),
                    "time": ev.timestamp})
            continue
        if t in _streaming:
            # session-scoped streaming ledger: a WINDOW_COMMIT_*'s dag_id
            # names the window DAG (whose own lifecycle events build its
            # DagInfo); the stream-level record stays out of the per-DAG
            # model like admission/recovery records do
            stream_events.append({
                "event": _streaming[t],
                "stream": ev.data.get("stream", ""),
                "window_id": ev.data.get("window_id", 0),
                "dag_id": ev.dag_id or "",
                "replayed": bool(ev.data.get("replayed")),
                "lag": ev.data.get("lag", 0),
                "time": ev.timestamp})
            continue
        if t is HistoryEventType.SLO_BURN_ALERT:
            telemetry_events.append({
                "event": "BURN",
                "tenant": ev.data.get("tenant", ""),
                "kind": ev.data.get("kind", ""),
                "stream": ev.data.get("stream", ""),
                "observed": ev.data.get("observed", 0.0),
                "target": ev.data.get("target", 0.0),
                "time": ev.timestamp})
            continue
        if t is HistoryEventType.QUERY_SUBMITTED:
            # session-scoped planner record; the dag_id names the lowered
            # DAG (whose own lifecycle events build its DagInfo)
            query_events.append({
                "event": "SUBMITTED",
                "query": ev.data.get("query", ""),
                "fingerprint": ev.data.get("fingerprint", ""),
                "dag_id": ev.dag_id or "",
                "strategies": ev.data.get("strategies", {}),
                "cache_hits": ev.data.get("cache_hits", 0),
                "replans": ev.data.get("replans", 0),
                "blamed": ev.data.get("blamed", ""),
                "wall_s": ev.data.get("wall_s", 0.0),
                "time": ev.timestamp})
            continue
        if t is HistoryEventType.QUERY_REPLANNED:
            query_events.append({
                "event": "REPLANNED",
                "query": ev.data.get("query", ""),
                "node": ev.data.get("node", ""),
                "operator": ev.data.get("operator", ""),
                "kind": ev.data.get("kind", ""),
                "from": ev.data.get("from", ""),
                "to": ev.data.get("to", ""),
                "detail": ev.data.get("detail", ""),
                "time": ev.timestamp})
            continue
        if t is HistoryEventType.TELEMETRY_SNAPSHOT:
            telemetry_events.append({
                "event": "SNAPSHOT",
                "series": ev.data.get("series", 0),
                "evicted": ev.data.get("evicted", 0),
                "collector_errors": ev.data.get("collector_errors", 0),
                "scrape_errors": ev.data.get("scrape_errors", 0),
                "ticks": ev.data.get("ticks", 0),
                "time": ev.timestamp})
            continue
        d = dag(ev)
        if t is HistoryEventType.DAG_SUBMITTED and d:
            d.name = ev.data.get("dag_name", "")
            d.tenant = ev.data.get("tenant", "")
            d.submit_time = ev.timestamp
            raw = ev.data.get("plan")
            if raw:
                try:
                    from tez_tpu.dag.plan import DAGPlan
                    plan = DAGPlan.deserialize(bytes.fromhex(raw))
                    d.edges = [
                        {"src": e.input_vertex, "dst": e.output_vertex,
                         "movement":
                         e.edge_property.data_movement_type.name}
                        for e in plan.edges]
                except Exception:  # noqa: BLE001 — plan schema drift is
                    pass           # tolerable; edge-aware analyzers degrade
        elif t is HistoryEventType.DAG_STARTED and d:
            d.start_time = ev.timestamp
        elif t is HistoryEventType.DAG_FINISHED and d:
            d.finish_time = ev.timestamp
            d.state = ev.data.get("state", "")
            d.diagnostics = ev.data.get("diagnostics", "")
            d.counters = ev.data.get("counters", {})
        elif t is HistoryEventType.VERTEX_INITIALIZED and d:
            v = d.vertices.setdefault(ev.vertex_id,
                                      VertexInfo(ev.vertex_id))
            v.name = ev.data.get("vertex_name", "")
            v.num_tasks = ev.data.get("num_tasks", 0)
            v.init_time = ev.timestamp
        elif t is HistoryEventType.VERTEX_STARTED and d:
            v = d.vertices.setdefault(ev.vertex_id, VertexInfo(ev.vertex_id))
            v.start_time = ev.timestamp
        elif t is HistoryEventType.VERTEX_FINISHED and d:
            v = d.vertices.setdefault(ev.vertex_id, VertexInfo(ev.vertex_id))
            v.finish_time = ev.timestamp
            v.state = ev.data.get("state", "")
            v.counters = ev.data.get("counters", {})
            v.name = v.name or ev.data.get("vertex_name", "")
        elif t is HistoryEventType.TASK_STARTED and d:
            v = d.vertices.setdefault(ev.vertex_id, VertexInfo(ev.vertex_id))
            task = v.tasks.setdefault(ev.task_id, TaskInfo(
                ev.task_id, ev.data.get("vertex_name", v.name)))
            task.start_time = ev.timestamp
        elif t is HistoryEventType.TASK_FINISHED and d:
            v = d.vertices.setdefault(ev.vertex_id, VertexInfo(ev.vertex_id))
            task = v.tasks.setdefault(ev.task_id, TaskInfo(
                ev.task_id, ev.data.get("vertex_name", v.name)))
            task.finish_time = ev.timestamp
            task.state = ev.data.get("state", "")
        elif t is HistoryEventType.TASK_ATTEMPT_STARTED and d:
            v = d.vertices.setdefault(ev.vertex_id, VertexInfo(ev.vertex_id))
            task = v.tasks.setdefault(ev.task_id, TaskInfo(
                ev.task_id, ev.data.get("vertex_name", v.name)))
            task.attempts[ev.attempt_id] = AttemptInfo(
                ev.attempt_id, ev.task_id,
                ev.data.get("vertex_name", v.name),
                container_id=ev.container_id or "",
                node_id=ev.data.get("node_id", ""),
                start_time=ev.timestamp)
        elif t is HistoryEventType.TASK_ATTEMPT_FINISHED and d:
            v = d.vertices.setdefault(ev.vertex_id, VertexInfo(ev.vertex_id))
            task = v.tasks.setdefault(ev.task_id, TaskInfo(
                ev.task_id, ev.data.get("vertex_name", v.name)))
            a = task.attempts.setdefault(ev.attempt_id, AttemptInfo(
                ev.attempt_id, ev.task_id,
                ev.data.get("vertex_name", v.name)))
            a.finish_time = ev.timestamp
            a.state = ev.data.get("state", "")
            a.diagnostics = ev.data.get("diagnostics", "")
            a.counters = ev.data.get("counters", {})
        elif t is HistoryEventType.CONTAINER_LAUNCHED:
            containers[ev.container_id] = {"launched": ev.timestamp}
        elif t is HistoryEventType.CONTAINER_STOPPED:
            containers.setdefault(ev.container_id, {})["stopped"] = \
                ev.timestamp
            containers[ev.container_id]["tasks_run"] = \
                ev.data.get("tasks_run", 0)
        elif t in (HistoryEventType.NODE_BLACKLISTED,
                   HistoryEventType.NODE_FORCED_ACTIVE):
            node_events.append({
                "node_id": ev.data.get("node_id", ""),
                "event": ("BLACKLISTED"
                          if t is HistoryEventType.NODE_BLACKLISTED
                          else "FORCED_ACTIVE"),
                "failures": ev.data.get("failures", 0),
                "time": ev.timestamp})
    for d in dags.values():
        d.containers = containers
        d.node_events = node_events
        d.admission_events = admission_events
        d.recovery_events = recovery_events
        d.stream_events = stream_events
        d.telemetry_events = telemetry_events
        d.query_events = query_events
    return dags


def parse_jsonl_files(paths: List[str]) -> Dict[str, DagInfo]:
    events: List[HistoryEvent] = []
    for pattern in paths:
        matches = sorted(globlib.glob(pattern)) if any(
            c in pattern for c in "*?[") else [pattern]
        for path in matches:
            if os.path.isdir(path):
                # a directory is a history STORE: manifest-scan it
                # (date=YYYY-MM-DD partitions + flat legacy files)
                from tez_tpu.am.history import scan_history_store
                matches.extend(scan_history_store(path))
                continue
            if not os.path.exists(path):
                print(f"warning: no such history file: {path}",
                      file=sys.stderr)
                continue
            # lenient decode: a crashed writer can tear the tail
            # mid-byte; the CRC frame rejects the mangled record
            with open(path, errors="replace") as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        try:
                            # canonical journal framing: `crc32-hex SP
                            # json` (recovery journals) OR legacy raw
                            # JSON (history-store partitions) — the
                            # decoder accepts both
                            events.append(decode_journal_line(line))
                        except Exception:  # noqa: BLE001 — torn tail
                            pass
    events.sort(key=lambda e: e.timestamp)
    return parse_history_events(events)
