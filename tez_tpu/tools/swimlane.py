"""Render a runner/attempt swimlane SVG from history.

Reference parity: tez-tools/swimlanes/*.py (container-timeline SVG from ATS).
Usage: python -m tez_tpu.tools.swimlane <history.jsonl...> [-o out.svg]
"""
from __future__ import annotations

import sys
from typing import Dict, List

from tez_tpu.tools.history_parser import DagInfo, parse_jsonl_files

LANE_H = 22
LEFT = 180
PX_PER_S = 120.0

_COLORS = ["#4e79a7", "#f28e2b", "#59a14f", "#e15759", "#76b7b2", "#edc948",
           "#b07aa1", "#ff9da7", "#9c755f", "#bab0ac"]


def render_svg(dag: DagInfo) -> str:
    attempts = [a for a in dag.all_attempts() if a.start_time]
    lanes: Dict[str, List] = {}
    for a in attempts:
        lanes.setdefault(a.container_id or "?", []).append(a)
    t0 = dag.start_time or min((a.start_time for a in attempts), default=0)
    t1 = max([dag.finish_time] + [a.finish_time for a in attempts] + [t0])
    width = LEFT + int((t1 - t0) * PX_PER_S) + 40
    height = (len(lanes) + 2) * LANE_H + 40
    vertex_names = sorted({a.vertex_name for a in attempts})
    color = {v: _COLORS[i % len(_COLORS)]
             for i, v in enumerate(vertex_names)}

    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" font-family="monospace" font-size="11">',
        f'<text x="4" y="14">{dag.name} ({dag.state}) '
        f'{dag.duration:.2f}s</text>']
    y = 30
    for cid in sorted(lanes):
        parts.append(f'<text x="4" y="{y + 14}">{cid[-18:]}</text>')
        for a in sorted(lanes[cid], key=lambda a: a.start_time):
            x = LEFT + (a.start_time - t0) * PX_PER_S
            w = max(2.0, (max(a.finish_time, a.start_time) - a.start_time)
                    * PX_PER_S)
            c = color.get(a.vertex_name, "#999")
            dash = ' stroke="#c00" stroke-width="2"' if a.state != "SUCCEEDED" \
                else ""
            parts.append(
                f'<rect x="{x:.1f}" y="{y + 2}" width="{w:.1f}" '
                f'height="{LANE_H - 6}" fill="{c}"{dash}>'
                f'<title>{a.attempt_id} [{a.state}] '
                f'{a.duration:.2f}s</title></rect>')
        y += LANE_H
    # legend
    x = LEFT
    for v in vertex_names:
        parts.append(f'<rect x="{x}" y="{y + 4}" width="10" height="10" '
                     f'fill="{color[v]}"/>')
        parts.append(f'<text x="{x + 14}" y="{y + 13}">{v}</text>')
        x += 14 + 8 * len(v) + 20
    parts.append("</svg>")
    return "\n".join(parts)


def main() -> int:
    args = [a for a in sys.argv[1:] if a != "-o"]
    out = None
    if "-o" in sys.argv:
        out = sys.argv[sys.argv.index("-o") + 1]
        args.remove(out)
    if not args:
        print("usage: swimlane <history.jsonl...> [-o out.svg]")
        return 2
    dags = parse_jsonl_files(args)
    if not dags:
        print("no DAGs found")
        return 1
    dag = list(dags.values())[-1]
    svg = render_svg(dag)
    if out:
        with open(out, "w") as fh:
            fh.write(svg)
        print(f"wrote {out}")
    else:
        print(svg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
