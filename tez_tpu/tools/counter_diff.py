"""Compare counters — and latency histograms — between two DAG runs.

Reference parity: tez-tools counter-diff.  Usage:
  python -m tez_tpu.tools.counter_diff <history_a.jsonl> <history_b.jsonl>

Plain counters are diffed value-by-value.  ``LatencyHistogram.*`` counter
groups (written by tez_tpu.common.metrics when the tracing/metrics plane is
on) are decoded back into bucket distributions and compared on p50/p95/max,
so a latency regression shows up as "shuffle.fetch.rtt p95 12ms -> 48ms"
rather than an opaque bucket-count delta.

The telemetry section diffs the stop-time ``TELEMETRY_SNAPSHOT`` journal
events: ring-eviction / collector-failure / scrape-error growth is
flagged (an adequately-sized always-on plane has zero of each), series
cardinality and burn-alert counts are reported unflagged.
"""
from __future__ import annotations

import sys
from typing import Any, Dict, List, Tuple

from tez_tpu.common.counters import (MESH_EXCHANGE_EFFICIENCY_COUNTERS,
                                     MESH_EXCHANGE_GROUP,
                                     MESH_EXCHANGE_PRESSURE_COUNTERS)
from tez_tpu.common.metrics import HIST_GROUP_PREFIX, histograms_from_counters
from tez_tpu.tools.history_parser import parse_jsonl_files

# p95 ratio above which a histogram line is flagged as a regression; bucket
# resolution is powers-of-2 ms, so anything under 2x is within quantisation.
REGRESSION_RATIO = 2.0

#: The async device plane's stage histograms (ops/async_stage.py), in
#: pipeline order.  Diffed as cumulative wall ms per stage: stage SUMS say
#: where the plane's time moved (p95 alone can hide a stage whose every
#: span got uniformly slower).
DEVICE_STAGE_HISTS = ("device.encode", "device.h2d", "device.dispatch_wait",
                      "device.d2h")

#: The reduce-side merge plane's histograms: ``device.merge`` is device
#: merge-kernel wall (merge-path dispatches plus the async merge lane's
#: dispatch-wait), ``shuffle.merge`` the consumer-side merge/commit wall.
#: Diffed like the device stages — cumulative wall ms — so a reduce side
#: that quietly fell off the merge-path kernel onto concatenate+re-sort
#: (or host failover) shows up as a sum shift even when p95 stays inside
#: one power-of-2 bucket.
MERGE_STAGE_HISTS = ("device.merge", "shuffle.merge")

#: Failure-containment counters (ops/async_stage.py COUNTER_GROUP): a run
#: that silently started leaning on host failover — or tripping the breaker
#: — is a health regression even when wall clock barely moves, so these get
#: their own section instead of drowning in the flat counter diff.
DEVICE_FAILOVER_GROUP = "DeviceFailover"
DEVICE_FAILOVER_COUNTERS = (
    "device.failover.spans", "device.failover.groups",
    "device.failover.drained", "device.watchdog.fires",
    "device.watchdog.dispatch_fires", "device.watchdog.readback_fires",
    "device.breaker.trips", "device.breaker.short_circuits",
    "device.breaker.recoveries", "device.oom.split_attempts",
    "device.oom.split_success")


#: Tiered buffer-store counters (tez_tpu/store COUNTER_GROUP).  Hits and
#: short-circuits are efficiency (more is better — never flagged);
#: evictions/demotions are pressure: growth means the run started churning
#: its tiers, which costs spill I/O even when wall clock barely moves.
STORE_GROUP = "ShuffleStore"
STORE_EFFICIENCY_COUNTERS = (
    "store.published", "store.hits", "store.misses", "store.short_circuit",
    "store.lineage.hits", "store.lineage.misses", "store.lineage.sealed",
    "store.reuse.tasks", "store.reuse.outputs")
STORE_PRESSURE_COUNTERS = (
    "store.demotions.device_to_host", "store.demotions.host_to_disk",
    "store.evictions.device", "store.evictions.host", "store.evictions.disk")


#: Push-based shuffle (tez_tpu/shuffle/push.py).  Pushed bytes are
#: efficiency (eager pushes landing = the pipeline working — never
#: flagged); rejections are pressure: growth means the admission
#: controller (or a dead transport) started turning pushes away and the
#: run leaned back on the pull path.  The counters live in the TaskCounter
#: enum group; the histograms ride the common LatencyHistogram plumbing.
PUSH_GROUP = "TaskCounter"
PUSH_EFFICIENCY_COUNTERS = ("SHUFFLE_PUSH_BYTES",)
PUSH_PRESSURE_COUNTERS = ("SHUFFLE_PUSH_REJECTED",)
PUSH_HISTS = ("shuffle.push.rtt", "shuffle.push.admit_wait")


#: Mesh ICI exchange (parallel/coordinator.py).  Rows/bytes sent and coded
#: duplicate traffic are workload-shaped efficiency numbers (coded
#: duplicate bytes literally buy straggler masking — never flagged);
#: rounds and splits are pressure: growth means the exchange plane started
#: re-rounding or re-partitioning to absorb skew it previously did not
#: see.  Per-round RTT rides the common LatencyHistogram plumbing.
EXCHANGE_GROUP = MESH_EXCHANGE_GROUP
EXCHANGE_EFFICIENCY_COUNTERS = MESH_EXCHANGE_EFFICIENCY_COUNTERS
EXCHANGE_PRESSURE_COUNTERS = MESH_EXCHANGE_PRESSURE_COUNTERS
EXCHANGE_HISTS = ("mesh.exchange.round",)


#: AM crash-survival (am/recovery.py queue replay, task_comm.py epoch
#: fencing, coded push replicas).  Requeued submissions and zombie-fenced
#: attempts come off the session recovery stream; replica traffic off the
#: ShuffleStore group.  A fault-free run has none of the first three, so
#: any growth is flagged; replica BYTES are workload-shaped (replicas=2
#: pays them on purpose, like coded duplicate exchange — never flagged).
RECOVERY_REPLICA_COUNTERS = ("store.replica.bytes", "store.replica.failover")


#: Streaming mode (am/streaming.py).  Committed windows are workload-
#: shaped (more input = more windows — never flagged); replays, aborts,
#: and lag episodes are pressure: a fault-free keeping-up stream has none,
#: so any growth is flagged.  Per-window latency rides the common
#: LatencyHistogram plumbing plus an exact p50/p95 recomputed from the
#: window-commit ledger timestamps.
STREAM_HISTS = ("stream.window.latency", "stream.window.lag")


#: Observability plane (obs/flight.py, am/admission.py).  Queue wait is
#: admission pressure — growth means submissions parked longer before
#: promotion; flight-dump wall is the recorder's own cost, which must
#: stay negligible (a dump storm in B that A never paid shows up here
#: before it shows up anywhere else).
OBS_HISTS = ("am.admit.queue_wait", "obs.flight.dump")


def tenant_summary(dags: Dict) -> Dict[str, Dict]:
    """Per-tenant admission/latency roll-up over a whole session history:
    {tenant: {submitted, completed, failed, queued, shed, p50_s, p95_s}}.
    Latencies are exact per-DAG submit->finish walls sorted and read at the
    quantile rank — NOT the registry's per-tenant dynamic histograms, which
    deliberately stay out of the lint-checked ``*_HISTS`` tuples."""
    out: Dict[str, Dict] = {}

    def row(tenant: str) -> Dict:
        return out.setdefault(tenant or "<anon>", {
            "submitted": 0, "completed": 0, "failed": 0,
            "queued": 0, "shed": 0, "latencies": []})

    admission = []
    for d in dags.values():
        r = row(d.tenant)
        r["submitted"] += 1
        if d.state == "SUCCEEDED":
            r["completed"] += 1
        elif d.state:
            r["failed"] += 1
        if d.finish_time > d.submit_time > 0:
            r["latencies"].append(d.finish_time - d.submit_time)
        admission = d.admission_events or admission
    for ev in admission:
        row(ev["tenant"])["queued" if ev["event"] == "QUEUED"
                          else "shed"] += 1
    for r in out.values():
        lats = sorted(r.pop("latencies"))
        r["p50_s"] = lats[int(0.50 * (len(lats) - 1))] if lats else 0.0
        r["p95_s"] = lats[int(0.95 * (len(lats) - 1))] if lats else 0.0
    return out


def diff_tenants(dags_a: Dict, dags_b: Dict,
                 ) -> List[Tuple[str, Dict, Dict, bool]]:
    """[(tenant, summary_a|{}, summary_b|{}, regressed)] for every tenant
    in either session; regressed when B shed more, failed more, or its p95
    latency crossed REGRESSION_RATIO x A's (shed growth = admission started
    turning this tenant away; submitted/completed deltas are workload)."""
    ta, tb = tenant_summary(dags_a), tenant_summary(dags_b)
    out = []
    for tenant in sorted(set(ta) | set(tb)):
        a, b = ta.get(tenant, {}), tb.get(tenant, {})
        regressed = bool(a and b and (
            b["shed"] > a["shed"] or b["failed"] > a["failed"] or
            (a["p95_s"] > 0 and b["p95_s"] >= REGRESSION_RATIO * a["p95_s"])))
        out.append((tenant, a, b, regressed))
    return out


def recovery_summary(dags: Dict) -> Dict[str, int]:
    """Session recovery roll-up off the recovery stream:
    ``{"requeued": n, "fenced": n}``."""
    events: List[Dict] = []
    for d in dags.values():
        events = d.recovery_events or events
    return {"requeued": sum(1 for e in events if e["event"] == "REQUEUED"),
            "fenced": sum(1 for e in events if e["event"] == "FENCED")}


def diff_recovery(dags_a: Dict, dags_b: Dict,
                  counters_a: Dict, counters_b: Dict,
                  ) -> List[Tuple[str, int, int, bool]]:
    """[(name, a, b, regressed)] for the crash-survival section: requeued
    submissions, zombie-fenced attempts, and replica failovers — any
    growth is flagged (these are zero on a healthy fault-free run);
    replica bytes are reported but never flagged."""
    ra, rb = recovery_summary(dags_a), recovery_summary(dags_b)
    ga = counters_a.get(STORE_GROUP, {})
    gb = counters_b.get(STORE_GROUP, {})
    out = []
    for name, va, vb in (
            ("dags.requeued_on_recovery", ra["requeued"], rb["requeued"]),
            ("attempts.zombie_fenced", ra["fenced"], rb["fenced"])):
        if va or vb:
            out.append((name, va, vb, vb > va))
    for name in RECOVERY_REPLICA_COUNTERS:
        if name not in ga and name not in gb:
            continue
        va, vb = int(ga.get(name, 0)), int(gb.get(name, 0))
        out.append((name, va, vb,
                    name == "store.replica.failover" and vb > va))
    return out


def stream_summary(dags: Dict) -> Dict[str, Any]:
    """Session streaming roll-up off the window-commit ledger stream:
    ``{"committed", "replayed", "aborted", "lag_episodes", "p50_ms",
    "p95_ms"}``.  Per-window latency is exact — COMMIT_FINISHED timestamp
    minus the window DAG's submit time — so it works on histories whose
    metrics plane was off."""
    events: List[Dict] = []
    for d in dags.values():
        events = getattr(d, "stream_events", None) or events
    committed = [e for e in events if e["event"] == "COMMIT_FINISHED"]
    lat: List[float] = []
    for e in committed:
        d = dags.get(e.get("dag_id", ""))
        if d is not None and d.submit_time and e["time"] > d.submit_time:
            lat.append((e["time"] - d.submit_time) * 1000.0)
    lat.sort()
    return {
        "committed": len(committed),
        "replayed": sum(1 for e in committed if e.get("replayed")),
        "aborted": sum(1 for e in events if e["event"] == "COMMIT_ABORTED"),
        "lag_episodes": sum(1 for e in events if e["event"] == "LAGGING"),
        "p50_ms": lat[len(lat) // 2] if lat else 0.0,
        "p95_ms": lat[int(len(lat) * 0.95)] if lat else 0.0,
    }


def telemetry_summary(dags: Dict) -> Dict[str, int]:
    """Session telemetry roll-up off the journaled stop-time
    ``TELEMETRY_SNAPSHOT`` (last one wins — each AM incarnation journals
    its own) plus the burn-alert count: ``{"series", "evicted",
    "collector_errors", "scrape_errors", "burn_alerts"}``."""
    events: List[Dict] = []
    for d in dags.values():
        events = getattr(d, "telemetry_events", None) or events
    snap: Dict = {}
    for e in events:
        if e["event"] == "SNAPSHOT":
            snap = e
    return {
        "series": int(snap.get("series", 0)),
        "evicted": int(snap.get("evicted", 0)),
        "collector_errors": int(snap.get("collector_errors", 0)),
        "scrape_errors": int(snap.get("scrape_errors", 0)),
        "burn_alerts": sum(1 for e in events if e["event"] == "BURN"),
    }


def query_summary(dags: Dict) -> Dict[str, int]:
    """Session query-plane roll-up off the planner's journal stream
    (tez_tpu/query/session.py): ``{"plans", "cache_hits", "replans"}``.
    ``plans`` counts QUERY_SUBMITTED records, ``cache_hits`` sums their
    sealed-lineage result-cache deltas, ``replans`` counts the typed
    QUERY_REPLANNED decisions."""
    events: List[Dict] = []
    for d in dags.values():
        events = getattr(d, "query_events", None) or events
    submitted = [e for e in events if e["event"] == "SUBMITTED"]
    return {
        "plans": len(submitted),
        "cache_hits": sum(int(e.get("cache_hits", 0)) for e in submitted),
        "replans": sum(1 for e in events if e["event"] == "REPLANNED"),
    }


def diff_query(dags_a: Dict, dags_b: Dict
               ) -> List[Tuple[str, int, int, bool]]:
    """[(name, a, b, regressed)] for the query-plane section: plan count
    is workload-shaped and cache hits are efficiency (more is better) —
    both unflagged; replan growth IS flagged: a replan means the static
    planner mis-sized an exchange badly enough to pay a whole observe-
    and-rerun cycle, so more of them against the same workload means the
    estimator (or the feedback loop's stability) regressed."""
    sa, sb = query_summary(dags_a), query_summary(dags_b)
    if not (sa["plans"] or sb["plans"]):
        return []
    return [
        ("query.plans", sa["plans"], sb["plans"], False),
        ("query.result_cache.hits", sa["cache_hits"], sb["cache_hits"],
         False),
        ("query.replans", sa["replans"], sb["replans"],
         sb["replans"] > sa["replans"]),
    ]


def diff_telemetry(dags_a: Dict, dags_b: Dict
                   ) -> List[Tuple[str, int, int, bool]]:
    """[(name, a, b, regressed)] for the telemetry-plane section: ring
    evictions, collector failures, and scrape errors are flagged on any
    growth (a correctly-sized always-on plane has zero of each); series
    cardinality and burn-alert count are reported unflagged (workload-
    shaped — a chaos leg SHOULD page)."""
    sa, sb = telemetry_summary(dags_a), telemetry_summary(dags_b)
    if not any(sa.values()) and not any(sb.values()):
        return []
    return [
        ("telemetry.series", sa["series"], sb["series"], False),
        ("telemetry.ring.evicted", sa["evicted"], sb["evicted"],
         sb["evicted"] > sa["evicted"]),
        ("telemetry.collector.errors", sa["collector_errors"],
         sb["collector_errors"],
         sb["collector_errors"] > sa["collector_errors"]),
        ("telemetry.scrape.errors", sa["scrape_errors"],
         sb["scrape_errors"],
         sb["scrape_errors"] > sa["scrape_errors"]),
        ("telemetry.slo.burn_alerts", sa["burn_alerts"],
         sb["burn_alerts"], False),
    ]


def diff_stream(dags_a: Dict, dags_b: Dict
                ) -> List[Tuple[str, float, float, bool]]:
    """[(name, a, b, regressed)] for the streaming section: committed
    windows and exact p50/p95 are reported unflagged (workload-shaped);
    replay, abort, and lag-episode growth is flagged — a keeping-up
    fault-free stream has zero of each."""
    sa, sb = stream_summary(dags_a), stream_summary(dags_b)
    if not (sa["committed"] or sb["committed"] or sa["aborted"]
            or sb["aborted"]):
        return []
    out: List[Tuple[str, float, float, bool]] = [
        ("stream.windows.committed", sa["committed"], sb["committed"],
         False),
        ("stream.windows.replayed", sa["replayed"], sb["replayed"],
         sb["replayed"] > sa["replayed"]),
        ("stream.windows.aborted", sa["aborted"], sb["aborted"],
         sb["aborted"] > sa["aborted"]),
        ("stream.lag.episodes", sa["lag_episodes"], sb["lag_episodes"],
         sb["lag_episodes"] > sa["lag_episodes"]),
        ("stream.window.p50_ms", round(sa["p50_ms"], 1),
         round(sb["p50_ms"], 1), False),
        ("stream.window.p95_ms", round(sa["p95_ms"], 1),
         round(sb["p95_ms"], 1), False),
    ]
    return out


def diff_exchange(counters_a: Dict, counters_b: Dict,
                  ) -> List[Tuple[str, int, int, bool]]:
    """[(counter, a, b, regressed)] over the mesh-exchange section;
    regressed only when B needed more rounds or splits than A (row/byte
    and coded-duplicate deltas are workload-shaped, not regressions)."""
    ga = counters_a.get(EXCHANGE_GROUP, {})
    gb = counters_b.get(EXCHANGE_GROUP, {})
    out = []
    for name in EXCHANGE_EFFICIENCY_COUNTERS + EXCHANGE_PRESSURE_COUNTERS:
        if name not in ga and name not in gb:
            continue
        va, vb = int(ga.get(name, 0)), int(gb.get(name, 0))
        out.append((name, va, vb,
                    name in EXCHANGE_PRESSURE_COUNTERS and vb > va))
    return out


def diff_push(counters_a: Dict, counters_b: Dict,
              ) -> List[Tuple[str, int, int, bool]]:
    """[(counter, a, b, regressed)] over the push-shuffle section;
    regressed only when B rejected more pushes than A (pushed-byte deltas
    are workload-shaped, not regressions)."""
    ga = counters_a.get(PUSH_GROUP, {})
    gb = counters_b.get(PUSH_GROUP, {})
    out = []
    for name in PUSH_EFFICIENCY_COUNTERS + PUSH_PRESSURE_COUNTERS:
        if name not in ga and name not in gb:
            continue
        va, vb = int(ga.get(name, 0)), int(gb.get(name, 0))
        out.append((name, va, vb,
                    name in PUSH_PRESSURE_COUNTERS and vb > va))
    return out


def diff_store(counters_a: Dict, counters_b: Dict,
               ) -> List[Tuple[str, int, int, bool]]:
    """[(counter, a, b, regressed)] over the buffer-store section;
    regressed only for PRESSURE counters where B churned more than A
    (eviction/demotion growth = the store started thrashing — hit/miss
    deltas are workload-shaped, not regressions)."""
    ga = counters_a.get(STORE_GROUP, {})
    gb = counters_b.get(STORE_GROUP, {})
    out = []
    for name in STORE_EFFICIENCY_COUNTERS + STORE_PRESSURE_COUNTERS:
        if name not in ga and name not in gb:
            continue
        va, vb = int(ga.get(name, 0)), int(gb.get(name, 0))
        out.append((name, va, vb,
                    name in STORE_PRESSURE_COUNTERS and vb > va))
    return out


def flatten(counters: Dict) -> Dict[str, int]:
    return {f"{g}.{name}": v for g, cs in counters.items()
            if not g.startswith(HIST_GROUP_PREFIX)
            for name, v in cs.items()}


def diff_histograms(counters_a: Dict, counters_b: Dict,
                    ) -> List[Tuple[str, Dict, Dict, bool]]:
    """[(name, summary_a|{}, summary_b|{}, regressed)] for every histogram
    present in either run; regressed means B's p95 is REGRESSION_RATIO x
    A's (only meaningful when both runs recorded the histogram)."""
    ha = histograms_from_counters(counters_a)
    hb = histograms_from_counters(counters_b)
    out = []
    for name in sorted(set(ha) | set(hb)):
        a, b = ha.get(name, {}), hb.get(name, {})
        regressed = bool(
            a and b and a["p95"] > 0 and b["p95"] >= REGRESSION_RATIO * a["p95"])
        out.append((name, a, b, regressed))
    return out


def diff_device_stages(counters_a: Dict, counters_b: Dict,
                       names: Tuple[str, ...] = DEVICE_STAGE_HISTS,
                       ) -> List[Tuple[str, float, float, bool]]:
    """[(stage, sum_ms_a, sum_ms_b, regressed)] for the named stage
    histograms present in either run; regressed when B spent
    REGRESSION_RATIO x A's total wall in that stage."""
    ha = histograms_from_counters(counters_a)
    hb = histograms_from_counters(counters_b)
    out = []
    for name in names:
        if name not in ha and name not in hb:
            continue
        ms_a = ha.get(name, {}).get("sum_us", 0) / 1000.0
        ms_b = hb.get(name, {}).get("sum_us", 0) / 1000.0
        regressed = name in ha and name in hb and ms_a > 0 and \
            ms_b >= REGRESSION_RATIO * ms_a
        out.append((name, ms_a, ms_b, regressed))
    return out


def diff_device_failover(counters_a: Dict, counters_b: Dict,
                         ) -> List[Tuple[str, int, int, bool]]:
    """[(counter, a, b, regressed)] over the device.failover containment
    counters present in either run; regressed when B recorded MORE
    containment events than A (any growth — these should be zero on a
    healthy fault-free run, so a ratio threshold would hide 0 -> n)."""
    ga = counters_a.get(DEVICE_FAILOVER_GROUP, {})
    gb = counters_b.get(DEVICE_FAILOVER_GROUP, {})
    out = []
    for name in DEVICE_FAILOVER_COUNTERS:
        if name not in ga and name not in gb:
            continue
        va, vb = int(ga.get(name, 0)), int(gb.get(name, 0))
        out.append((name, va, vb, vb > va))
    return out


def _fmt_hist(s: Dict) -> str:
    if not s:
        return f"{'-':>26}"
    return (f"n={s['count']:<6d} p50={s['p50']:>8.1f} "
            f"p95={s['p95']:>8.1f} max={s['max_ms']:>8.1f}")


def main() -> int:
    if len(sys.argv) != 3:
        print("usage: counter_diff <history_a> <history_b>")
        return 2
    runs, sessions = [], []
    for path in sys.argv[1:]:
        dags = parse_jsonl_files([path])
        if not dags:
            print(f"no DAG in {path}")
            return 1
        runs.append(list(dags.values())[-1])
        sessions.append(dags)
    a, b = runs
    fa, fb = flatten(a.counters), flatten(b.counters)
    print(f"{'counter':60} {'A':>14} {'B':>14} {'delta':>14}")
    for key in sorted(set(fa) | set(fb)):
        va, vb = fa.get(key, 0), fb.get(key, 0)
        if va != vb:
            print(f"{key:60} {va:14d} {vb:14d} {vb - va:+14d}")
    hists = diff_histograms(a.counters, b.counters)
    regressions = 0
    if hists:
        print(f"\n{'latency histogram (ms)':32} {'A':>44} {'B':>44}")
        for name, sa, sb, regressed in hists:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:32} {_fmt_hist(sa):>44} {_fmt_hist(sb):>44}{flag}")
            regressions += int(regressed)
    stages = diff_device_stages(a.counters, b.counters)
    if stages:
        tot_a = sum(ms for _, ms, _, _ in stages) or 1.0
        tot_b = sum(ms for _, _, ms, _ in stages) or 1.0
        print(f"\n{'device pipeline stage (wall ms)':32} "
              f"{'A':>16} {'B':>16} {'delta':>12}")
        for name, ms_a, ms_b, regressed in stages:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:32} {ms_a:10.1f} {100 * ms_a / tot_a:4.0f}% "
                  f"{ms_b:10.1f} {100 * ms_b / tot_b:4.0f}% "
                  f"{ms_b - ms_a:+12.1f}{flag}")
            regressions += int(regressed)
    merges = diff_device_stages(a.counters, b.counters,
                                names=MERGE_STAGE_HISTS)
    if merges:
        print(f"\n{'reduce-side merge stage (wall ms)':32} "
              f"{'A':>14} {'B':>14} {'delta':>12}")
        for name, ms_a, ms_b, regressed in merges:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:32} {ms_a:14.1f} {ms_b:14.1f} "
                  f"{ms_b - ms_a:+12.1f}{flag}")
            regressions += int(regressed)
    store = diff_store(a.counters, b.counters)
    if store:
        print(f"\n{'buffer store (hits/evictions/demotions)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in store:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
    push = diff_push(a.counters, b.counters)
    if push:
        print(f"\n{'push shuffle (bytes/rejections)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in push:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
        pushes = diff_device_stages(a.counters, b.counters,
                                    names=PUSH_HISTS)
        if pushes:
            print(f"\n{'push transport (wall ms)':32} "
                  f"{'A':>14} {'B':>14} {'delta':>12}")
            for name, ms_a, ms_b, regressed in pushes:
                flag = "  << REGRESSION" if regressed else ""
                print(f"{name:32} {ms_a:14.1f} {ms_b:14.1f} "
                      f"{ms_b - ms_a:+12.1f}{flag}")
                regressions += int(regressed)
    exchange = diff_exchange(a.counters, b.counters)
    if exchange:
        print(f"\n{'mesh exchange (rows/rounds/splits/coded)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in exchange:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
        ex_rtt = diff_device_stages(a.counters, b.counters,
                                    names=EXCHANGE_HISTS)
        if ex_rtt:
            print(f"\n{'exchange round (wall ms)':32} "
                  f"{'A':>14} {'B':>14} {'delta':>12}")
            for name, ms_a, ms_b, regressed in ex_rtt:
                flag = "  << REGRESSION" if regressed else ""
                print(f"{name:32} {ms_a:14.1f} {ms_b:14.1f} "
                      f"{ms_b - ms_a:+12.1f}{flag}")
                regressions += int(regressed)
    obs = diff_device_stages(a.counters, b.counters, names=OBS_HISTS)
    if obs:
        print(f"\n{'observability (wall ms)':32} "
              f"{'A':>14} {'B':>14} {'delta':>12}")
        for name, ms_a, ms_b, regressed in obs:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:32} {ms_a:14.1f} {ms_b:14.1f} "
                  f"{ms_b - ms_a:+12.1f}{flag}")
            regressions += int(regressed)
    tenants = diff_tenants(*sessions)
    if any(t != "<anon>" or s.get("queued") or s.get("shed")
           for t, sa, sb, _ in tenants for s in (sa, sb) if s):
        print(f"\n{'tenant (admission + latency)':24} "
              f"{'A sub/cmp/fail q/shed p50/p95':>40} "
              f"{'B sub/cmp/fail q/shed p50/p95':>40}")

        def _fmt_tenant(s: Dict) -> str:
            if not s:
                return f"{'-':>40}"
            return (f"{s['submitted']:3d}/{s['completed']:3d}/"
                    f"{s['failed']:2d} {s['queued']:2d}/{s['shed']:2d} "
                    f"{s['p50_s']:6.2f}s/{s['p95_s']:6.2f}s")
        for tenant, sa, sb, regressed in tenants:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{tenant:24} {_fmt_tenant(sa):>40} "
                  f"{_fmt_tenant(sb):>40}{flag}")
            regressions += int(regressed)
    stream = diff_stream(sessions[0], sessions[1])
    if stream:
        print(f"\n{'streaming (windows/replays/lag)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in stream:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14g} {vb:14g}{flag}")
            regressions += int(regressed)
        stream_h = diff_device_stages(a.counters, b.counters,
                                      names=STREAM_HISTS)
        if stream_h:
            print(f"\n{'stream window (wall ms)':32} "
                  f"{'A':>14} {'B':>14} {'delta':>12}")
            for name, ms_a, ms_b, regressed in stream_h:
                flag = "  << REGRESSION" if regressed else ""
                print(f"{name:32} {ms_a:14.1f} {ms_b:14.1f} "
                      f"{ms_b - ms_a:+12.1f}{flag}")
                regressions += int(regressed)
    recovery = diff_recovery(sessions[0], sessions[1],
                             a.counters, b.counters)
    if recovery:
        print(f"\n{'recovery (requeues/fences/replica failover)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in recovery:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
    failover = diff_device_failover(a.counters, b.counters)
    if failover:
        print(f"\n{'device.failover (containment)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in failover:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
    telemetry = diff_telemetry(sessions[0], sessions[1])
    if telemetry:
        print(f"\n{'telemetry plane (rings/collectors/scrapes)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in telemetry:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
    query = diff_query(sessions[0], sessions[1])
    if query:
        print(f"\n{'query plane (plans/cache hits/replans)':60} "
              f"{'A':>14} {'B':>14}")
        for name, va, vb, regressed in query:
            flag = "  << REGRESSION" if regressed else ""
            print(f"{name:60} {va:14d} {vb:14d}{flag}")
            regressions += int(regressed)
    print(f"\nA: {a.dag_id} ({a.state}, {a.duration:.2f}s)  "
          f"B: {b.dag_id} ({b.state}, {b.duration:.2f}s)  "
          f"wall delta {b.duration - a.duration:+.2f}s")
    if regressions:
        print(f"{regressions} regression(s) (latency p95 >= "
              f"{REGRESSION_RATIO}x baseline, containment event growth, "
              f"store eviction/demotion churn growth, exchange "
              f"round/split growth, tenant shed/failure growth, "
              f"stream replay/abort/lag growth, "
              f"recovery requeue/fence/failover growth, telemetry "
              f"ring-eviction/collector/scrape-error growth, or query "
              f"replan growth)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
