"""Compare counters between two DAG runs.

Reference parity: tez-tools counter-diff.  Usage:
  python -m tez_tpu.tools.counter_diff <history_a.jsonl> <history_b.jsonl>
"""
from __future__ import annotations

import sys
from typing import Dict

from tez_tpu.tools.history_parser import parse_jsonl_files


def flatten(counters: Dict) -> Dict[str, int]:
    return {f"{g}.{name}": v for g, cs in counters.items()
            for name, v in cs.items()}


def main() -> int:
    if len(sys.argv) != 3:
        print("usage: counter_diff <history_a> <history_b>")
        return 2
    runs = []
    for path in sys.argv[1:]:
        dags = parse_jsonl_files([path])
        if not dags:
            print(f"no DAG in {path}")
            return 1
        runs.append(list(dags.values())[-1])
    a, b = runs
    fa, fb = flatten(a.counters), flatten(b.counters)
    print(f"{'counter':60} {'A':>14} {'B':>14} {'delta':>14}")
    for key in sorted(set(fa) | set(fb)):
        va, vb = fa.get(key, 0), fb.get(key, 0)
        if va != vb:
            print(f"{key:60} {va:14d} {vb:14d} {vb - va:+14d}")
    print(f"\nA: {a.dag_id} ({a.state}, {a.duration:.2f}s)  "
          f"B: {b.dag_id} ({b.state}, {b.duration:.2f}s)  "
          f"wall delta {b.duration - a.duration:+.2f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
