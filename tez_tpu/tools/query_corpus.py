"""Deterministic TPC-H-style corpus + numpy reference oracle.

Four tables (customer/orders/lineitem/part) with zero-padded numeric
columns (so lexicographic order == numeric order, the ordering contract
of the query layer) and seeded foreign keys — uniform by default, Zipf-
skewed when ``skew > 0`` (hot join/group keys, the shape the skew-replan
path exists for).  The same seed always writes byte-identical .tbl
files.

``CORPUS_QUERIES`` is the fixed query suite bench, chaos
(``--query-storm``), the tenant soak, and tests/test_query.py all run:
every relational operator (scan/filter/project/hash_join/
sort_merge_join/auto join/aggregate/window/limit/semi joins) is covered,
and every query carries a numpy oracle producing the exact sorted
(key, value) records the DAG must emit — bit-exact under ANY physical
strategy, which is what makes strategy flips safe to automate.

CLI: ``python -m tez_tpu.tools.query_corpus OUTDIR [--scale S] [--skew Z]
[--seed N]`` writes the tables and prints a manifest line per table.
"""
from __future__ import annotations

import argparse
import dataclasses
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tez_tpu.query.logical import Table

#: rows per table at scale 1.0
_BASE_ROWS = {"customer": 150, "orders": 1500, "lineitem": 6000,
              "part": 200}

SCHEMAS: Dict[str, List[str]] = {
    "customer": ["c_custkey", "c_name", "c_nation"],
    "orders": ["o_orderkey", "o_custkey", "o_total"],
    "lineitem": ["l_orderkey", "l_partkey", "l_qty", "l_price", "l_flag"],
    "part": ["p_partkey", "p_name", "p_brand"],
}


def _fk_indices(rng: np.random.Generator, n: int, domain: int,
                skew: float) -> np.ndarray:
    """``n`` foreign-key indices into [0, domain).  skew=0 -> uniform;
    skew>0 -> Zipf-ish weights 1/(i+1)**skew (index 0 hottest)."""
    if skew <= 0.0:
        return rng.integers(0, domain, size=n)
    weights = 1.0 / np.power(np.arange(1, domain + 1, dtype=np.float64),
                             skew)
    cum = np.cumsum(weights / weights.sum())
    return np.searchsorted(cum, rng.random(n), side="left").clip(
        0, domain - 1)


@dataclasses.dataclass
class Corpus:
    """Generated corpus: table paths + schemas + cached numpy columns."""
    workdir: str
    scale: float
    skew: float
    seed: int
    paths: Dict[str, str]
    _cache: Dict[str, Dict[str, np.ndarray]] = \
        dataclasses.field(default_factory=dict)

    def scan(self, table: str) -> Table:
        return Table.scan(table, [self.paths[table]], SCHEMAS[table])

    def columns(self, table: str) -> Dict[str, np.ndarray]:
        """Parse a .tbl back into {column: np str array} (oracle input —
        the files on disk are the single source of truth)."""
        if table not in self._cache:
            with open(self.paths[table]) as f:
                rows = [line.rstrip("\n").split("|")
                        for line in f if line.strip()]
            cols = SCHEMAS[table]
            arr = np.array(rows, dtype=str) if rows else \
                np.empty((0, len(cols)), dtype=str)
            self._cache[table] = {c: arr[:, i]
                                  for i, c in enumerate(cols)}
        return self._cache[table]


def generate(workdir: str, scale: float = 1.0, skew: float = 0.0,
             seed: int = 0) -> Corpus:
    os.makedirs(workdir, exist_ok=True)
    rng = np.random.default_rng(seed)
    n_cust = max(3, int(_BASE_ROWS["customer"] * scale))
    n_ord = max(6, int(_BASE_ROWS["orders"] * scale))
    n_li = max(12, int(_BASE_ROWS["lineitem"] * scale))
    n_part = max(3, int(_BASE_ROWS["part"] * scale))
    paths = {t: os.path.join(workdir, f"{t}.tbl") for t in SCHEMAS}

    with open(paths["customer"], "w") as f:
        for i in range(n_cust):
            f.write(f"c{i:06d}|name{i:06d}|n{i % 17:02d}\n")

    o_cust = _fk_indices(rng, n_ord, n_cust, skew)
    o_total = rng.integers(0, 100000, size=n_ord)
    with open(paths["orders"], "w") as f:
        for i in range(n_ord):
            f.write(f"o{i:07d}|c{o_cust[i]:06d}|{o_total[i]:08d}\n")

    with open(paths["part"], "w") as f:
        for i in range(n_part):
            f.write(f"p{i:06d}|part{i:06d}|b{i % 25:02d}\n")

    l_ord = _fk_indices(rng, n_li, n_ord, skew)
    l_part = _fk_indices(rng, n_li, n_part, skew)
    l_qty = rng.integers(0, 51, size=n_li)
    l_price = rng.integers(1, 1000000, size=n_li)
    flags = np.array(["A", "N", "R"])
    l_flag = flags[rng.integers(0, 3, size=n_li)]
    with open(paths["lineitem"], "w") as f:
        for i in range(n_li):
            f.write(f"o{l_ord[i]:07d}|p{l_part[i]:06d}|{l_qty[i]:04d}|"
                    f"{l_price[i]:07d}|{l_flag[i]}\n")

    return Corpus(workdir=workdir, scale=scale, skew=skew, seed=seed,
                  paths=paths)


# -- numpy oracle helpers ---------------------------------------------------

def _group_agg(keys: np.ndarray, aggs: List[Tuple[str, np.ndarray]]
               ) -> Dict[str, List[int]]:
    """Group-by over string keys -> {key: [agg values in order]} using
    np.unique inverse indexes + ufunc.at accumulation."""
    if keys.size == 0:
        return {}
    uniq, inv = np.unique(keys, return_inverse=True)
    out: Dict[str, List[int]] = {k: [] for k in uniq}
    for fn, col in aggs:
        if fn == "count":
            vals = np.bincount(inv, minlength=len(uniq))
        elif fn == "sum":
            vals = np.zeros(len(uniq), dtype=np.int64)
            np.add.at(vals, inv, col.astype(np.int64))
        elif fn == "min":
            vals = np.full(len(uniq), np.iinfo(np.int64).max, np.int64)
            np.minimum.at(vals, inv, col.astype(np.int64))
        else:  # max
            vals = np.full(len(uniq), np.iinfo(np.int64).min, np.int64)
            np.maximum.at(vals, inv, col.astype(np.int64))
        for i, k in enumerate(uniq):
            out[str(k)].append(int(vals[i]))
    return out


def _records(rows: Dict[str, List[int]]) -> List[Tuple[str, str]]:
    return sorted((k, "|".join(str(v) for v in vals))
                  for k, vals in rows.items())


def _join_map(keys: np.ndarray, *cols: np.ndarray) -> Dict[str, List[Tuple]]:
    out: Dict[str, List[Tuple]] = {}
    for i in range(keys.size):
        out.setdefault(str(keys[i]), []).append(
            tuple(str(c[i]) for c in cols))
    return out


# -- the corpus query suite -------------------------------------------------

@dataclasses.dataclass
class CorpusQuery:
    name: str
    build: Callable[[Corpus], Table]
    oracle: Callable[[Corpus], List[Tuple[str, str]]]
    sink: Optional[Dict[str, Any]] = None
    #: queries whose physical strategy the planner may choose/replan
    strategy_sensitive: bool = False


def _q_pricing(c: Corpus) -> Table:
    return (c.scan("lineitem")
            .filter("l_qty", "ge", "0025", numeric=True)
            .aggregate(["l_flag"], [("sum_price", "sum", "l_price"),
                                    ("n", "count", "l_orderkey"),
                                    ("max_qty", "max", "l_qty")]))


def _o_pricing(c: Corpus) -> List[Tuple[str, str]]:
    li = c.columns("lineitem")
    sel = li["l_qty"].astype(int) >= 25
    return _records(_group_agg(
        li["l_flag"][sel],
        [("sum", li["l_price"][sel]), ("count", li["l_price"][sel]),
         ("max", li["l_qty"][sel])]))


def _q_nation_revenue(c: Corpus) -> Table:
    return (c.scan("orders")
            .join(c.scan("customer"), "o_custkey", "c_custkey")
            .aggregate(["c_nation"], [("revenue", "sum", "o_total"),
                                      ("n", "count", "o_orderkey")]))


def _o_nation_revenue(c: Corpus) -> List[Tuple[str, str]]:
    o, cu = c.columns("orders"), c.columns("customer")
    cust = _join_map(cu["c_custkey"], cu["c_nation"])
    nations, totals = [], []
    for i in range(o["o_orderkey"].size):
        for (nation,) in cust.get(str(o["o_custkey"][i]), []):
            nations.append(nation)
            totals.append(int(o["o_total"][i]))
    nk = np.array(nations, dtype=str)
    tv = np.array(totals, dtype=np.int64)
    return _records(_group_agg(nk, [("sum", tv), ("count", tv)]))


def _q_supply_chain(c: Corpus) -> Table:
    """Multi-join tree: repartition-pinned big-big join, aggregate,
    then a broadcast-pinned dim join, aggregate again."""
    per_cust = (c.scan("lineitem")
                .sort_merge_join(c.scan("orders"), "l_orderkey",
                                 "o_orderkey")
                .aggregate(["o_custkey"], [("rev", "sum", "l_price")]))
    return (per_cust
            .hash_join(c.scan("customer"), "o_custkey", "c_custkey")
            .aggregate(["c_nation"], [("revenue", "sum", "rev"),
                                      ("n", "count", "o_custkey")]))


def _o_supply_chain(c: Corpus) -> List[Tuple[str, str]]:
    li, o, cu = (c.columns("lineitem"), c.columns("orders"),
                 c.columns("customer"))
    orders = _join_map(o["o_orderkey"], o["o_custkey"])
    per_cust: Dict[str, int] = {}
    for i in range(li["l_orderkey"].size):
        for (custkey,) in orders.get(str(li["l_orderkey"][i]), []):
            per_cust[custkey] = per_cust.get(custkey, 0) + \
                int(li["l_price"][i])
    nation_of = {str(k): str(n) for k, n in
                 zip(cu["c_custkey"], cu["c_nation"])}
    agg: Dict[str, List[int]] = {}
    for custkey in sorted(per_cust):
        nation = nation_of.get(custkey)
        if nation is None:
            continue
        cur = agg.setdefault(nation, [0, 0])
        cur[0] += per_cust[custkey]
        cur[1] += 1
    return _records(agg)


def _q_top_orders(c: Corpus) -> Table:
    return (c.scan("orders")
            .window("o_custkey", "o_total", "row_number", "w_rank")
            .filter("w_rank", "le", "3", numeric=True)
            .project(["o_custkey", "o_orderkey", "w_rank"]))


def _o_top_orders(c: Corpus) -> List[Tuple[str, str]]:
    o = c.columns("orders")
    by_cust: Dict[str, List[Tuple[str, ...]]] = {}
    for i in range(o["o_orderkey"].size):
        row = (str(o["o_orderkey"][i]), str(o["o_custkey"][i]),
               str(o["o_total"][i]))
        by_cust.setdefault(row[1], []).append(row)
    out: List[Tuple[str, str]] = []
    for custkey, rows in by_cust.items():
        rows.sort(key=lambda r: (r[2], r))   # order col, ties by full row
        for rank, row in enumerate(rows[:3], 1):
            out.append((custkey, f"{row[0]}|{rank}"))
    return sorted(out)


def _q_hot_parts(c: Corpus) -> Table:
    return (c.scan("lineitem")
            .filter("l_qty", "ge", "0045", numeric=True)
            .join(c.scan("part"), "l_partkey", "p_partkey",
                  how="semi_distinct"))


def _o_hot_parts(c: Corpus) -> List[Tuple[str, str]]:
    li, p = c.columns("lineitem"), c.columns("part")
    sel = li["l_qty"].astype(int) >= 45
    parts = set(str(k) for k in p["p_partkey"])
    keys = sorted(set(str(k) for k in li["l_partkey"][sel]) & parts)
    return [(k, "") for k in keys]


def _q_flagged_sample(c: Corpus) -> Table:
    return (c.scan("lineitem")
            .filter("l_flag", "eq", "A")
            .project(["l_partkey", "l_price", "l_orderkey"])
            .limit(20, ["l_partkey"]))


def _o_flagged_sample(c: Corpus) -> List[Tuple[str, str]]:
    li = c.columns("lineitem")
    sel = li["l_flag"] == "A"
    rows = sorted(
        (str(pk), str(pr), str(ok)) for pk, pr, ok in
        zip(li["l_partkey"][sel], li["l_price"][sel],
            li["l_orderkey"][sel]))
    return sorted((r[0], f"{r[1]}|{r[2]}") for r in rows[:20])


def _q_local_orders(c: Corpus) -> Table:
    return (c.scan("orders")
            .hash_join(c.scan("customer").filter("c_nation", "eq", "n03"),
                       "o_custkey", "c_custkey", how="semi")
            .project(["o_orderkey", "o_custkey"]))


def _o_local_orders(c: Corpus) -> List[Tuple[str, str]]:
    o, cu = c.columns("orders"), c.columns("customer")
    local = set(str(k) for k, n in zip(cu["c_custkey"], cu["c_nation"])
                if str(n) == "n03")
    return sorted((str(ok), str(ck)) for ok, ck in
                  zip(o["o_orderkey"], o["o_custkey"])
                  if str(ck) in local)


CORPUS_QUERIES: List[CorpusQuery] = [
    CorpusQuery("pricing_summary", _q_pricing, _o_pricing),
    CorpusQuery("nation_revenue", _q_nation_revenue, _o_nation_revenue,
                strategy_sensitive=True),
    CorpusQuery("supply_chain", _q_supply_chain, _o_supply_chain),
    CorpusQuery("top_orders", _q_top_orders, _o_top_orders),
    CorpusQuery("hot_parts", _q_hot_parts, _o_hot_parts),
    CorpusQuery("flagged_sample", _q_flagged_sample, _o_flagged_sample),
    CorpusQuery("local_orders", _q_local_orders, _o_local_orders),
]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="query_corpus",
        description="generate the deterministic TPC-H-style query corpus")
    ap.add_argument("outdir")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--skew", type=float, default=0.0,
                    help="Zipf exponent for foreign keys (0 = uniform)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    corpus = generate(args.outdir, scale=args.scale, skew=args.skew,
                      seed=args.seed)
    for table, path in sorted(corpus.paths.items()):
        print(f"{table}\t{os.path.getsize(path)}B\t{path}")
    print(f"queries\t{len(CORPUS_QUERIES)}\t"
          f"{','.join(q.name for q in CORPUS_QUERIES)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
