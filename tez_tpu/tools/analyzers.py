"""Post-hoc DAG analyzers over DagInfo.

Reference parity: tez-tools/analyzers/job-analyzer/.../plugins/ (19 analyzers
via AnalyzerDriver) — the core set: CriticalPathAnalyzer:53,
ShuffleTimeAnalyzer, SkewAnalyzer, SpillAnalyzerImpl, SlowestVertexAnalyzer,
ContainerReuseAnalyzer, HungTaskAnalyzer, SpeculationAnalyzer.
"""
from __future__ import annotations

import dataclasses
import json
import sys
from typing import Any, Dict, List, Sequence

from tez_tpu.tools.history_parser import DagInfo, parse_jsonl_files


@dataclasses.dataclass
class AnalyzerResult:
    analyzer: str
    headline: str
    rows: List[Dict[str, Any]]

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Analyzer:
    name = "abstract"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        raise NotImplementedError


class CriticalPathAnalyzer(Analyzer):
    """Longest chain of vertex (start..finish) spans ordered by start time —
    which vertices bound the DAG wall-clock."""
    name = "critical_path"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        verts = sorted(dag.vertices.values(), key=lambda v: v.start_time)
        total = dag.duration or 1e-9
        for v in verts:
            rows.append({
                "vertex": v.name, "start_offset": v.start_time - dag.start_time,
                "duration": v.duration,
                "fraction_of_dag": round(v.duration / total, 3),
            })
        slowest = max(verts, key=lambda v: v.duration, default=None)
        headline = (f"DAG {dag.name}: {dag.duration:.2f}s; dominant vertex "
                    f"{slowest.name} ({slowest.duration:.2f}s)"
                    if slowest else "empty DAG")
        return AnalyzerResult(self.name, headline, rows)


class ShuffleTimeAnalyzer(Analyzer):
    """Shuffle/merge phase times + bytes per vertex (reference:
    ShuffleTimeAnalyzer over SHUFFLE_PHASE_TIME/MERGE_PHASE_TIME)."""
    name = "shuffle_time"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            if not tc.get("SHUFFLE_BYTES") and not tc.get("SHUFFLE_PHASE_TIME"):
                continue
            rows.append({
                "vertex": v.name,
                "shuffle_bytes": tc.get("SHUFFLE_BYTES", 0),
                "shuffle_phase_ms": tc.get("SHUFFLE_PHASE_TIME", 0),
                "merge_phase_ms": tc.get("MERGE_PHASE_TIME", 0),
                "shuffled_inputs": tc.get("NUM_SHUFFLED_INPUTS", 0),
                "failed_fetches": tc.get("NUM_FAILED_SHUFFLE_INPUTS", 0),
            })
        total = sum(r["shuffle_bytes"] for r in rows)
        return AnalyzerResult(self.name,
                              f"total shuffled: {total} bytes", rows)


class SkewAnalyzer(Analyzer):
    """Attempt-duration skew per vertex (reference: SkewAnalyzer)."""
    name = "skew"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            durations = [t.successful_attempt.duration
                         for t in v.tasks.values()
                         if t.successful_attempt is not None]
            if not durations:
                continue
            mean = sum(durations) / len(durations)
            rows.append({
                "vertex": v.name, "tasks": len(durations),
                "mean_s": round(mean, 3),
                "max_s": round(max(durations), 3),
                "skew_ratio": round(max(durations) / mean, 2) if mean else 0,
            })
        worst = max(rows, key=lambda r: r["skew_ratio"], default=None)
        return AnalyzerResult(
            self.name,
            f"worst skew {worst['skew_ratio']}x in {worst['vertex']}"
            if worst else "no completed tasks", rows)


class SpillAnalyzer(Analyzer):
    """Spilled records / host-spill bytes per vertex (reference:
    SpillAnalyzerImpl)."""
    name = "spill"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            rows.append({
                "vertex": v.name,
                "spilled_records": tc.get("SPILLED_RECORDS", 0),
                "additional_spill_count": tc.get("ADDITIONAL_SPILL_COUNT", 0),
                "host_spill_bytes": tc.get("HOST_SPILL_BYTES", 0),
                "output_bytes": tc.get("OUTPUT_BYTES", 0),
            })
        total = sum(r["host_spill_bytes"] for r in rows)
        return AnalyzerResult(self.name, f"host spill: {total} bytes", rows)


class SlowestVertexAnalyzer(Analyzer):
    name = "slowest_vertex"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = sorted(
            ({"vertex": v.name, "duration_s": round(v.duration, 3),
              "num_tasks": v.num_tasks}
             for v in dag.vertices.values()),
            key=lambda r: -r["duration_s"])
        return AnalyzerResult(
            self.name,
            f"slowest: {rows[0]['vertex']}" if rows else "none", rows)


class ContainerReuseAnalyzer(Analyzer):
    """Tasks per runner (reference: ContainerReuseAnalyzer)."""
    name = "container_reuse"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = [{"container": cid, **info}
                for cid, info in dag.containers.items()]
        total = sum(r.get("tasks_run", 0) for r in rows)
        return AnalyzerResult(
            self.name,
            f"{len(rows)} runners, {total} tasks ("
            f"{total / len(rows):.1f} tasks/runner)" if rows else "no runners",
            rows)


class SpeculationAnalyzer(Analyzer):
    """Attempts beyond the first per task (reference: SpeculationAnalyzer)."""
    name = "speculation"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            for t in v.tasks.values():
                if len(t.attempts) > 1:
                    rows.append({"task": t.task_id,
                                 "vertex": v.name,
                                 "attempts": len(t.attempts),
                                 "states": sorted(a.state for a in
                                                  t.attempts.values())})
        return AnalyzerResult(self.name,
                              f"{len(rows)} tasks with extra attempts", rows)


class HungTaskAnalyzer(Analyzer):
    """Tasks started but never finished (reference: HungTaskAnalyzer)."""
    name = "hung_tasks"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            for t in v.tasks.values():
                if t.start_time and not t.finish_time:
                    rows.append({"task": t.task_id, "vertex": v.name})
        return AnalyzerResult(self.name, f"{len(rows)} hung tasks", rows)


class TaskConcurrencyAnalyzer(Analyzer):
    """Peak/avg concurrently-running attempts over time (reference:
    TaskConcurrencyAnalyzer)."""
    name = "task_concurrency"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        attempts = [a for a in dag.all_attempts() if a.start_time]
        # open intervals (in-progress/crashed DAGs) close at the latest
        # timestamp seen, never at the 0.0 "unset" sentinel
        horizon = max([dag.finish_time] +
                      [a.finish_time for a in attempts] +
                      [a.start_time for a in attempts], default=0.0)
        points = []
        for a in attempts:
            points.append((a.start_time, 1))
            points.append((a.finish_time or horizon, -1))
        points.sort()
        cur = peak = 0
        area = 0.0
        last_t = points[0][0] if points else 0
        for t, d in points:
            area += cur * (t - last_t)
            cur += d
            peak = max(peak, cur)
            last_t = t
        span = dag.duration or 1e-9
        return AnalyzerResult(
            self.name,
            f"peak {peak} concurrent attempts, avg {area / span:.1f}",
            [{"peak": peak, "avg": round(area / span, 2)}])


class SlowTaskAttemptAnalyzer(Analyzer):
    """Slowest attempts across the DAG (reference: SlowTaskIdentifier)."""
    name = "slow_attempts"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        attempts = sorted(dag.all_attempts(), key=lambda a: -a.duration)[:10]
        rows = [{"attempt": a.attempt_id, "vertex": a.vertex_name,
                 "duration_s": round(a.duration, 3), "state": a.state}
                for a in attempts]
        return AnalyzerResult(
            self.name,
            f"slowest attempt {rows[0]['duration_s']}s in "
            f"{rows[0]['vertex']}" if rows else "none", rows)


class InputOutputRatioAnalyzer(Analyzer):
    """Bytes out / bytes in per vertex — where data amplifies or reduces
    (reference: the IO-ratio family of analyzers)."""
    name = "io_ratio"

    def analyze(self, dag: DagInfo) -> AnalyzerResult:
        rows = []
        for v in dag.vertices.values():
            tc = v.counters.get("TaskCounter", {})
            inp = tc.get("SHUFFLE_BYTES", 0) or \
                tc.get("INPUT_SPLIT_LENGTH_BYTES", 0)
            out = tc.get("OUTPUT_BYTES", 0)
            if inp or out:
                rows.append({"vertex": v.name, "in_bytes": inp,
                             "out_bytes": out,
                             "ratio": round(out / inp, 3) if inp else None})
        return AnalyzerResult(self.name, f"{len(rows)} vertices with IO",
                              rows)


ALL_ANALYZERS: Sequence[Analyzer] = (
    CriticalPathAnalyzer(), ShuffleTimeAnalyzer(), SkewAnalyzer(),
    SpillAnalyzer(), SlowestVertexAnalyzer(), ContainerReuseAnalyzer(),
    SpeculationAnalyzer(), HungTaskAnalyzer(), TaskConcurrencyAnalyzer(),
    SlowTaskAttemptAnalyzer(), InputOutputRatioAnalyzer())


def analyze_dag(dag: DagInfo,
                analyzers: Sequence[Analyzer] = ALL_ANALYZERS
                ) -> List[AnalyzerResult]:
    return [a.analyze(dag) for a in analyzers]


def main() -> int:
    """AnalyzerDriver CLI: python -m tez_tpu.tools.analyzers <jsonl...>"""
    if len(sys.argv) < 2:
        print("usage: analyzers <history.jsonl | dir | glob>...")
        return 2
    dags = parse_jsonl_files(sys.argv[1:])
    if not dags:
        print("no DAGs found")
        return 1
    for dag in dags.values():
        print(f"=== {dag.dag_id} ({dag.name}) state={dag.state} "
              f"duration={dag.duration:.2f}s ===")
        for result in analyze_dag(dag):
            print(f"[{result.analyzer}] {result.headline}")
            for row in result.rows:
                print("   ", json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
